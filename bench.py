"""Headline benchmark — a thin shim over ``distributed_pytorch_tpu.perfbench``.

Prints ONE schema-validated JSON line (``perfbench/record.py``,
``docs/benchmarking.md``):

  {"schema": "dpx.bench.record", "metric": ..., "value": N, ...}

Three measurements, most important first:

1. **Flagship MFU** (the headline ``value``): TransformerLM, ~135M params,
   bf16, flash attention, seq 1024, trained single-chip. ``value`` is the
   MFU fraction = achieved model FLOP/s / chip peak bf16 FLOP/s
   (benchmarks/mfu_transformer.py). The reference cannot run this model at
   all; ``vs_baseline`` is our tokens/s over eager-torch-CPU tokens/s on
   the same model — the only measurable torch baseline in this
   environment (torch has no TPU backend here).
2. **min_ddp metric** (``min_ddp`` field): the reference's implicit
   benchmark (MLP 1->32->4, batch 8, reference min_DDP.py:44-48).
3. **world-8 DP step** (``dp8`` field): the same min_ddp train step on an
   8-device virtual CPU mesh (subprocess), so collective overhead is
   measured at all. steps/s on 8 CPU devices, global batch 64.

The statistical policy is perfbench's, end to end: warmup-discarded
repeated trials, median + IQR, the hard spread gate (``DPX_BENCH_MAX_
SPREAD``) that structurally withholds ``vs_baseline``, and the roofline
plausibility gate. When the TPU backend stays unhealthy after bounded
retries the record still carries the newest verified on-chip number as
an explicit ``last_good`` carry-forward with provenance — a metric is
never null (perfbench/trajectory.py); before falling back to a
carry-forward, a no-TPU container measures the pinned HOST flagship
arm against a calibrated host peak (``mfu_host`` stage,
docs/compute.md) so the headline stays a fresh gated measurement.
``--smoke`` runs the CPU-gated perfbench smoke (CI: the bench-smoke
job); ``--headline`` measures and lands ONLY the flagship headline.

Robustness: the TPU backend behind the axon tunnel comes and goes
(BENCH_r01.json died on it). Backend init runs in a subprocess with
bounded retries + backoff (perfbench/runner.py); on final failure the
script still prints a parseable JSON record with an ``error`` field and
whatever measurements did succeed (rc stays 0 so the record is
recorded).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

try:
    from distributed_pytorch_tpu.perfbench import (record as _record,
                                                   roofline_gate,
                                                   runner as _runner,
                                                   stats as _stats,
                                                   trajectory as _trajectory)
    from distributed_pytorch_tpu.runtime import env as _env
except Exception as e:  # noqa: BLE001 — the record contract survives even this
    # the parseable-record exit is for DIRECT invocation only (incl.
    # --stage children): a library importer (mfu_transformer,
    # step_breakdown, decode_tpu) must see the real ImportError, not
    # have its process killed rc-0 behind a flagship-metric error line
    if __name__ != "__main__":
        raise
    print(json.dumps({"metric": "transformer_lm_mfu_single_chip",
                      "unit": "mfu_fraction",
                      "error": f"perfbench import failed: "
                               f"{type(e).__name__}: {e}"}))
    # rc 0 keeps the record-emission contract for the collector — but
    # --smoke is a CI GATE, and a gate that never ran must not pass
    raise SystemExit(1 if "--smoke" in sys.argv[1:] else 0)

BATCH = 8
HIDDEN = 32
N_CLASSES = 4
DATA_SIZE = 32

HEADLINE_METRIC = _trajectory.FLAGSHIP_METRIC

# compat re-exports: the plumbing's canonical home is perfbench.runner
# (benchmarks/run_all_tpu.py and the mfu sweep import it directly now)
probe_backend = _runner.probe_backend
wait_for_backend = _runner.wait_for_backend
progress = _runner.progress
arm = _runner.arm
run_json_subprocess = _runner.run_json_subprocess

RESULTS_LOG = os.path.join(REPO, "benchmarks", "tpu_results.jsonl")


def append_result(stage: str, result: dict, *, ok: bool = None,
                  wall_s: float = None) -> None:
    """Append one raw benchmark record to the trajectory store, in the
    same {stage, ok, wall_s, result, ts} shape run_all_tpu.run_stage
    writes — through perfbench's thread-safe append path. Every honest
    run must leave a raw-JSON trace (round-3 lesson: the log held only
    retracted rows while the real numbers lived in prose)."""
    if not _record.append_row(RESULTS_LOG, stage, result, ok=ok,
                              wall_s=wall_s):
        print(f"# could not append to {RESULTS_LOG}", file=sys.stderr)


def last_good_record() -> dict:
    """Newest non-retracted, actually-measured flagship record from the
    trajectory store (perfbench/trajectory.py) — the carry-forward
    source that keeps a wedged tunnel from nulling the headline."""
    return _trajectory.last_good_flagship(RESULTS_LOG)


def attach_roofline(rec: dict) -> None:
    """The analytic roofline travels WITH the headline (perfbench/
    roofline_gate.py): floors, the overlap/no-overlap MFU ceilings,
    achieved/ceiling, and the plausibility gate."""
    roofline_gate.attach_flagship(rec)


def _run_stage(stage: str, timeout_s: int) -> dict:
    """Re-invoke this script for one measurement stage in a subprocess
    with a hard timeout — the tunnel can wedge mid-run, and the
    parseable-JSON-on-failure contract must survive that."""
    return run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage", stage],
        timeout_s, label=f"stage {stage}")


# ---------------------------------------------------------------------------
# measurement 2: the reference's implicit benchmark (min_ddp MLP)
# ---------------------------------------------------------------------------


def _batches(n_steps: int, seed: int = 0):
    import numpy as np
    from distributed_pytorch_tpu.data import DummyDataset
    ds = DummyDataset(DATA_SIZE, N_CLASSES, seed=seed)
    xs, ys = [], []
    for t in range(n_steps):
        idx = np.arange(t * BATCH, (t + 1) * BATCH) % DATA_SIZE
        xs.append(ds.data[idx])
        ys.append(ds.labels[idx])
    return np.stack(xs), np.stack(ys)


def bench_min_ddp(n_steps: int = 2000, fused_chunk: int = 100) -> dict:
    import jax
    import jax.numpy as jnp
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import (make_scan_train_steps,
                                                  make_train_step)

    model = models.DummyModel(in_dim=1, hidden_dim=HIDDEN,
                              n_classes=N_CLASSES)
    params = model.init(jax.random.PRNGKey(0))
    opt = optim.adamw(1e-4)
    opt_state = opt.init(params)

    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy(model.apply(p, x), y), {}

    xs, ys = _batches(fused_chunk)
    xs, ys = jnp.asarray(xs), jnp.asarray(ys)

    # All fences below are HOST MATERIALIZATIONS (np.asarray of a scalar):
    # on the tunneled backend jax.block_until_ready can resolve on enqueue
    # (see benchmarks/fence_probe.py), which made every r02 number a
    # dispatch-rate measurement. A fetch cannot complete before the value
    # exists, and chaining steps through params makes the final fetch wait
    # for the whole run.
    from distributed_pytorch_tpu.utils.profiler import (fetch_fence,
                                                        time_steps_amortized)

    # per-step path FIRST (the honest number for the reference's per-step
    # semantics): one jitted call per step, chained; one fetch at the end.
    step = make_train_step(loss_fn, opt, donate=False)
    b0 = (xs[0], ys[0])
    out = step(params, opt_state, b0)
    fetch_fence(out.loss)
    m = min(n_steps, 500)
    s_per_step, out = time_steps_amortized(
        lambda o: step(o.params, o.opt_state, b0), out, m,
        lambda o: o.loss)
    per_step_sps = 1.0 / s_per_step

    # per-step latency with the loss materialized on the host EVERY step
    # (the reference's literal eager semantics, min_DDP.py:110-130) — on a
    # tunneled backend this is round-trip-bound and says more about the
    # tunnel than the chip; reported separately for honesty.
    t0 = time.perf_counter()
    for _ in range(20):
        out = step(out.params, out.opt_state, b0)
        fetch_fence(out.loss)
    eager_sps = 20 / (time.perf_counter() - t0)

    # scan-fused fast path (different semantics: no per-step host visibility)
    run = make_scan_train_steps(loss_fn, opt, n_steps=fused_chunk)
    p2, o2, losses = run(params, opt_state, (xs, ys))
    fetch_fence(losses)
    n_calls = max(n_steps // fused_chunk, 1)
    t0 = time.perf_counter()
    p, o = p2, o2
    for _ in range(n_calls):
        p, o, losses = run(p, o, (xs, ys))
    fetch_fence(losses)
    fused_sps = n_calls * fused_chunk / (time.perf_counter() - t0)

    return {"steps_per_sec": round(per_step_sps, 1),
            "per_step_host_loss_steps_per_sec": round(eager_sps, 1),
            "fused_steps_per_sec": round(fused_sps, 1),
            "timing_method": "chained dispatch, host-fetch fence"}


def _baseline_detail(st: "_stats.TrialStats", key: str) -> dict:
    """Legacy-shaped baseline detail (median under ``key``, runs under
    ``runs_<key>``).  No ``trials`` dict here: the full perfbench blob
    for the same stats lands exactly once, under ``metrics`` — two
    copies in one appended line double store growth and can silently
    diverge."""
    return {key: round(st.median, 1),
            f"runs_{key}": [round(r, 1) for r in st.runs],
            "spread_frac": round(st.spread_frac, 3),
            "range_frac": round(st.range_frac, 3),
            "trusted": st.trusted,
            **({"untrusted_reason": st.untrusted_reason}
               if st.untrusted_reason else {})}


def bench_torch_cpu_mlp(n_steps: int = 500) -> "_stats.TrialStats":
    """Measured baseline: the reference's workload in eager torch on this
    host's CPU (the reference's world<=1 branch runs exactly this,
    reference distributed.py:54-58). Thread-pinned; trials/warmup/gate
    from the perfbench policy — consumers withhold ratios when the
    stats come back untrusted."""
    import torch
    import torch.nn as nn
    from distributed_pytorch_tpu.data import DummyDataset

    _stats.pin_torch_threads(torch)
    torch.manual_seed(0)
    model = nn.Sequential(nn.Linear(1, HIDDEN), nn.Linear(HIDDEN, N_CLASSES))
    opt = torch.optim.AdamW(model.parameters(), 1e-4)
    crit = nn.CrossEntropyLoss()
    ds = DummyDataset(DATA_SIZE, N_CLASSES)
    x = torch.tensor(ds.data[:BATCH])
    y = torch.tensor(ds.labels[:BATCH]).long()
    for _ in range(20):
        opt.zero_grad(); crit(model(x), y).backward(); opt.step()

    def one_run():
        t0 = time.perf_counter()
        for _ in range(n_steps):
            opt.zero_grad()
            loss = crit(model(x), y)
            loss.backward()
            opt.step()
        return n_steps / (time.perf_counter() - t0)

    return _stats.measure(one_run)


def bench_torch_cpu_lm(batch=2, n_steps=2) -> "_stats.TrialStats":
    """tokens/s for the flagship LM config in eager torch CPU — the
    vs_baseline denominator for the MFU headline. The model config comes
    from benchmarks.mfu_transformer.FLAGSHIP (single source of truth);
    only batch is reduced — CPU throughput is ~flat in batch and a full
    flagship batch takes minutes per step here. Thread-pinned;
    trials/warmup/gate from the perfbench policy (round-3 runs varied
    +/-46% under host contention; r05's 70% spread forced the harness
    to withhold vs_baseline — the gate now does that structurally)."""
    import torch
    import torch.nn as nn

    from benchmarks.mfu_transformer import FLAGSHIP
    _stats.pin_torch_threads(torch)
    dim, n_layers, n_heads = (FLAGSHIP["dim"], FLAGSHIP["n_layers"],
                              FLAGSHIP["n_heads"])
    vocab, seq = FLAGSHIP["vocab"], FLAGSHIP["seq"]
    torch.manual_seed(0)
    layer = nn.TransformerEncoderLayer(
        dim, n_heads, 4 * dim, batch_first=True, norm_first=True,
        activation="gelu")
    enc = nn.TransformerEncoder(layer, n_layers)
    emb = nn.Embedding(vocab, dim)
    head = nn.Linear(dim, vocab, bias=False)
    params = (list(enc.parameters()) + list(emb.parameters())
              + list(head.parameters()))
    opt = torch.optim.AdamW(params, 3e-4)
    crit = nn.CrossEntropyLoss()
    mask = nn.Transformer.generate_square_subsequent_mask(seq)
    tokens = torch.randint(0, vocab, (batch, seq + 1))

    def one_step():
        opt.zero_grad()
        h = emb(tokens[:, :-1])
        h = enc(h, mask=mask, is_causal=True)
        loss = crit(head(h).reshape(-1, vocab),
                    tokens[:, 1:].reshape(-1))
        loss.backward()
        opt.step()

    def one_run():
        t0 = time.perf_counter()
        for _ in range(n_steps):
            one_step()
        return n_steps * batch * seq / (time.perf_counter() - t0)

    return _stats.measure(one_run)


# ---------------------------------------------------------------------------
# measurement 3: world-8 DP step on the virtual CPU mesh (subprocess —
# platform selection must happen before backend init)
# ---------------------------------------------------------------------------

def _dp8_code(n_steps: int = 15, min_trial_s: float = 1.0,
              budget_s: float = None) -> str:
    """The dp8 child program. Statistical policy comes from perfbench:
    process affinity pinned (r05 variance source: thread migration),
    warmup discard (r05: 621.6 cold vs ~900 warm steps/s), median + IQR
    + the spread gate.  Two further defenses against THIS container's
    noise structure (2 visible cores, /proc/stat fully masked, available
    CPU swinging 2x over tens of seconds as invisible neighbors come and
    go):

    * each trial's sample is the PEAK ``n_steps``-chunk rate inside a
      >= ``min_trial_s`` window (the min-timing technique, as in
      timeit): external preemption only ever subtracts throughput, so
      the best ~25 ms chunk estimates the uncontended rate and is the
      run-to-run comparable number — the mean rate of the same windows
      measured 18-49%% spread here, the peak-chunk rate 5%%;
    * aggregation is ``stats.measure_until``: a sliding window over
      trials that returns the first gate-passing stationary window
      within ``budget_s``, so a neighbor-load mode switch mid-run ages
      out of the window instead of poisoning the whole estimate.

    The sustained (mean) rate of the final window is reported alongside
    as ``sustained_steps_per_sec`` — on a quiet host the two agree; a
    large gap is a contention fingerprint, not a speedup."""
    if budget_s is None:
        # resolved HERE so the documented env knob actually governs the
        # generated child (the child inherits the parent's environment)
        budget_s = float(_env.get("DPX_BENCH_BUDGET_S"))
    return r"""
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
from distributed_pytorch_tpu.runtime.jax_compat import ensure_cpu_devices
ensure_cpu_devices(8)
import jax.numpy as jnp
import numpy as np
import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.ops.losses import cross_entropy
from distributed_pytorch_tpu.parallel import make_train_step
from distributed_pytorch_tpu.perfbench import record as pbrecord
from distributed_pytorch_tpu.perfbench import stats as pbstats

# one CPU per virtual device, deterministic placement across runs
# (count from DPX_BENCH_AFFINITY — 0 disables pinning)
pbstats.pin_process()

dist.init_process_group(rank=0, world_size=8)
model = models.DummyModel(in_dim=1, hidden_dim=32, n_classes=4)
params = model.init(jax.random.PRNGKey(0))
opt = optim.adamw(1e-4)
opt_state = opt.init(params)

def loss_fn(p, batch):
    x, y = batch
    return cross_entropy(model.apply(p, x), y), {}

step = make_train_step(loss_fn, opt, donate=False)
x = dist.shard_batch(np.arange(64, dtype=np.float32)[:, None])
y = dist.shard_batch(np.zeros(64, dtype=np.int32))
out = step(params, opt_state, (x, y))
jax.block_until_ready(out.loss)
# fence every step: on a small host the 8-way rendezvous aborts if many
# async steps pile up (and the reference's workload materializes loss
# per step anyway, so the fenced number is the semantically right one).
n = %(n_steps)d
min_s = %(min_trial_s)f
state = {"out": out, "sustained": 0.0}

def one_trial():
    o = state["out"]
    best = 0.0
    steps = 0
    t0 = time.perf_counter()
    while True:
        c0 = time.perf_counter()
        for _ in range(n):
            o = step(o.params, o.opt_state, (x, y))
            jax.block_until_ready(o.loss)
        c1 = time.perf_counter()
        best = max(best, n / (c1 - c0))
        steps += n
        if c1 - t0 >= min_s:
            break
    state["out"] = o
    state["sustained"] = steps / (time.perf_counter() - t0)
    return best

st = pbstats.measure_until(one_trial, budget_s=%(budget_s)f)
blob = pbrecord.make_metric(None, "steps_per_sec", stats=st)
print(json.dumps({"steps_per_sec": round(st.median, 1),
                  "sustained_steps_per_sec": round(state["sustained"], 1),
                  "runs_steps_per_sec": [round(r, 1) for r in st.runs],
                  "spread_frac": round(st.spread_frac, 3),
                  "trusted": st.trusted,
                  "timing_method": "peak %(n_steps)d-step-chunk rate "
                                   "per >=%(min_trial_s).0fs window, "
                                   "stationary-window aggregation",
                  "metric_blob": blob,
                  "world": 8, "global_batch": 64}))
""" % {"n_steps": n_steps, "min_trial_s": min_trial_s,
       "budget_s": budget_s}


# 32 MiB f32 gradient bucket: big enough that the ring is bandwidth-
# bound even on loopback (real DDP buckets are tens of MB — ResNet-50's
# full gradient is ~98 MB), which is the regime the quantized wire is
# for; at a few MiB the 8-process mesh is scheduling-latency-bound and
# wire width barely matters.
COMM_BUCKET_ELEMS = 1 << 23
COMM_WORLD = 8
COMM_REPS = 6


def _dp8_comm_worker(rank, world, q, n_elems, reps, runs):
    """Host-ring comm microbench worker: the same flat gradient bucket
    allreduced over the native TCP ring, f32 wire vs quantized (block
    int8) wire. Barrier-fenced so every timed window measures all
    ranks' slowest path; rank 0 reports."""
    import numpy as np

    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu.runtime import context

    dist.init_process_group(rank, world)
    comm = context.get_host_comm()
    try:
        rng = np.random.default_rng(rank)
        x = rng.standard_normal(n_elems).astype(np.float32)

        def timed(op):
            samples = []
            for _ in range(runs):
                comm.barrier()
                t0 = time.perf_counter()
                for _ in range(reps):
                    op(x.copy())
                comm.barrier()
                samples.append(reps / (time.perf_counter() - t0))
            samples.sort()
            return samples[len(samples) // 2], samples

        # one untimed warm rep each (socket buffers, allocator)
        comm.allreduce(x.copy())
        comm.allreduce_q8(x.copy())
        f32_sps, f32_runs = timed(comm.allreduce)
        q_sps, q_runs = timed(comm.allreduce_q8)
        if rank == 0:
            from distributed_pytorch_tpu.comm import wire
            q.put({
                "comm_world": world,
                "comm_bucket_mb": round(n_elems * 4 / (1 << 20), 2),
                # per-rank wire payload of ONE allreduce of the bucket
                "comm_bytes": wire.quant_ring_allreduce_wire_bytes(
                    n_elems, world) // world,
                "comm_f32_bytes": wire.ring_allreduce_wire_bytes(
                    n_elems, world) // world,
                "comm_quant_steps_per_sec": round(q_sps, 2),
                "comm_f32_steps_per_sec": round(f32_sps, 2),
                "comm_runs": {"f32": [round(r, 2) for r in f32_runs],
                              "quant": [round(r, 2) for r in q_runs]},
            })
    finally:
        dist.cleanup()


def bench_dp8_comm() -> dict:
    """8-process native-ring gradient-bucket allreduce: f32 vs quantized
    wire, reported into the dp8 record (comm_bytes /
    comm_quant_steps_per_sec acceptance fields)."""
    import multiprocessing as mp

    from distributed_pytorch_tpu.runtime.multiprocess import (
        launch_multiprocess)

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    launch_multiprocess(_dp8_comm_worker, COMM_WORLD, q,
                        COMM_BUCKET_ELEMS, COMM_REPS, 5)
    return q.get(timeout=60)


# sharded-update flagship arm: big enough that the update compute and
# optimizer state are meaningful (16 MiB f32 bucket ~ a real DDP
# bucket), small enough that the 8-process loopback arm stays
# seconds-scale in the CI smoke
SHARDED_BUCKET_ELEMS = 1 << 22

# hierarchical/adaptive flagship arm: same sizing logic (16 MiB f32
# bucket); the topology is 4 "hosts" x 2 ranks on loopback — the slow
# hop is emulated, so the honest headline is the BYTE accounting (q4 >=
# 6.5x vs f32, slow-hop bytes 1/local_world of flat) plus the measured
# exposed_ms drop; steps/s vs_q8 is reported gated like every ratio
HIER_BUCKET_ELEMS = 1 << 22
HIER_LOCAL_WORLD = 2


def _dp8_sharded_worker(rank, world, q, n_elems, reps, runs):
    """dp8_sharded_adam flagship arm worker: the SAME flat gradient
    bucket driven through (a) the replicated update — quantized ring
    allreduce + full-bucket AdamW on every rank — and (b) the ZeRO-1
    sharded update (optim/sharded/): EF + reduce_scatter_q8 + AdamW on
    the owned 1/world slice + allgather_q8. Each trial's sample is the
    PEAK barrier-fenced ``reps``-step chunk rate over a FIXED number of
    chunks (the dp8 min-timing defense against this container's
    neighbor noise — preemption only ever subtracts throughput; the
    chunk count is fixed, not wall-clock-driven, so every rank runs the
    identical collective schedule and the ring cannot deadlock on a
    diverging loop exit); rank 0 reports the median of trials, measured
    wire bytes (CommStats vs the wire.py accounting), blocking comm ms,
    and per-rank optimizer-state bytes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu import optim
    from distributed_pytorch_tpu.comm import wire
    from distributed_pytorch_tpu.ops.quant import ErrorFeedback
    from distributed_pytorch_tpu.optim.sharded import (build_layout,
                                                       shard_optimizer)
    from distributed_pytorch_tpu.runtime import context

    dist.init_process_group(rank, world)
    comm = context.get_host_comm()
    try:
        rng = np.random.default_rng(rank)
        params = np.zeros(n_elems, np.float32)
        g = (rng.standard_normal(n_elems) * 1e-2).astype(np.float32)
        opt = optim.adamw(1e-3)
        layout = build_layout(params, world)
        sharded = shard_optimizer(opt, layout)
        n = layout.n_padded
        lo, hi = layout.span(layout.ring_segment(rank))

        # BOTH arms run on the padded bucket (n may exceed n_elems when
        # the knob isn't a world*block multiple) — the replicated arm
        # must update the same element count it allreduces
        rep = {"params": jnp.asarray(layout.flatten_np(params)),
               "state": opt.init(jnp.asarray(layout.flatten_np(params)))}
        upd_full = jax.jit(opt.update)
        sh = {"state": sharded.init_slice(params, rank)}
        upd_slice = jax.jit(sharded.update_flat)
        # one EF residual per arm: the production replicated quant path
        # (parallel/data_parallel._make_host_train_step) compensates its
        # bucket too, so both arms pay the same codec-side work and the
        # ratio compares the update strategies, not EF-vs-no-EF
        ef = ErrorFeedback()
        rep_ef = ErrorFeedback()
        gbuf = layout.flatten_np(g)

        def rep_step():
            flat = rep_ef.compensate(gbuf)
            comm.allreduce_q8(flat)
            new_p, rep["state"] = upd_full(jnp.asarray(flat / world),
                                           rep["state"], rep["params"])
            rep["params"] = jax.block_until_ready(new_p)

        def sh_step():
            flat = ef.compensate(gbuf)
            comm.reduce_scatter_q8(flat)
            new_master, sh["state"] = upd_slice(
                jnp.asarray(flat[lo:hi] / world), sh["state"])
            flat[lo:hi] = np.asarray(jax.block_until_ready(new_master))
            comm.allgather_q8(flat)

        CHUNKS = 3

        def timed(fn):
            samples = []
            for _ in range(runs):
                best = 0.0
                for _ in range(CHUNKS):
                    comm.barrier()
                    t0 = time.perf_counter()
                    for _ in range(reps):
                        fn()
                    comm.barrier()
                    best = max(best, reps / (time.perf_counter() - t0))
                samples.append(best)
            samples.sort()
            return samples[len(samples) // 2], samples

        rep_step()
        sh_step()  # warm: compile, sockets, allocator
        comm.stats.reset()
        rep_sps, rep_runs = timed(rep_step)
        rep_stats = comm.stats.summary()
        comm.stats.reset()
        sh_sps, sh_runs = timed(sh_step)
        sh_stats = comm.stats.summary()

        if rank == 0:
            nsteps = runs * CHUNKS * reps
            leg = wire.quant_leg_wire_bytes(n, world) // world
            blocking = lambda s: sum(d["seconds"] for d in s.values())
            # per-rank optimizer bytes: replicated holds 2 full f32
            # moments; sharded holds 2 moments + the exact master on
            # 1/world of the bucket
            rep_opt_bytes = 2 * 4 * n
            sh_opt_bytes = 3 * 4 * layout.seg
            q.put({
                "sharded_world": world,
                "sharded_bucket_mb": round(n * 4 / (1 << 20), 2),
                "sharded_steps_per_sec": round(sh_sps, 2),
                "replicated_steps_per_sec": round(rep_sps, 2),
                "sharded_runs": {
                    "sharded": [round(r, 2) for r in sh_runs],
                    "replicated": [round(r, 2) for r in rep_runs]},
                # per-rank wire payload of ONE step: what CommStats
                # accounted across the run vs the per-step expectation.
                # This pins the runtime's per-op accounting (op counts,
                # n, world, block) against the wire.py formula — NOT a
                # socket-level byte count; that the formula describes
                # the actual framed bytes is pinned separately by the
                # native-vs-numpy-spec bit-parity tests
                "sharded_wire_bytes": (sh_stats["reduce_scatter"]["bytes"]
                                       + sh_stats["allgather"]["bytes"])
                // nsteps,
                "sharded_wire_bytes_expected": 2 * leg,
                "replicated_wire_bytes":
                    rep_stats["allreduce_q8"]["bytes"] // nsteps,
                "replicated_f32_wire_bytes":
                    wire.ring_allreduce_wire_bytes(n, world) // world,
                "sharded_blocking_ms_per_step": round(
                    1000 * blocking(sh_stats) / nsteps, 3),
                "replicated_blocking_ms_per_step": round(
                    1000 * blocking(rep_stats) / nsteps, 3),
                "sharded_opt_state_bytes_per_rank": sh_opt_bytes,
                "replicated_opt_state_bytes_per_rank": rep_opt_bytes,
                "opt_state_shrink": round(rep_opt_bytes / sh_opt_bytes,
                                          2),
            })
    finally:
        dist.cleanup()


def _dp8_hier_worker(rank, world, q, n_elems, reps, runs):
    """dp8_hier_adaptive flagship arm worker. Three measurements on the
    SAME gradient bucket over the 8-process native group:

    (a) paired A/B: flat q8 ring vs the two-level ring with the
        adaptive width chooser (4 hosts x 2 ranks emulated on
        loopback), peak barrier-fenced chunk rates like the sharded
        arm — rank 0 reports both run lists so vs_q8 goes through the
        perfbench spread gate;
    (b) byte accounting: a flat q4 allreduce's CommStats bytes vs the
        wire.py formula vs the f32 ring formula (the >= 6.5x smoke
        assert), and the hier arm's slow-hop bytes vs its formula given
        the widths the chooser actually picked;
    (c) overlap: the real host train step (small MLP) with the bucketed
        overlap OFF then ON — CommStats exposed_ms/overlapped_ms per
        step both ways (the measured hidden fraction)."""
    import jax
    import numpy as np

    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.comm import wire
    from distributed_pytorch_tpu.comm.hier import hier_ring
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.ops.quant import ErrorFeedback
    from distributed_pytorch_tpu.parallel import make_train_step
    from distributed_pytorch_tpu.runtime import context

    dist.init_process_group(rank, world)
    comm = context.get_host_comm()
    try:
        local_world = HIER_LOCAL_WORLD
        ring = hier_ring(comm, local_world)
        nh = world // local_world
        rng = np.random.default_rng(rank)
        g = (rng.standard_normal(n_elems) * 1e-2).astype(np.float32)

        ef_q8, ef_q4, ef_hier = (ErrorFeedback(), ErrorFeedback(),
                                 ErrorFeedback())
        chooser = wire.WidthChooser()

        def q8_step():
            comm.allreduce_q8(ef_q8.compensate(g))

        def q4_step():
            comm.allreduce_q4(ef_q4.compensate(g, bits=4))

        def hier_step():
            bits = chooser.width
            flat = ef_hier.compensate(g, bits=bits)
            ring.allreduce(flat, bits=bits)
            chooser.observe(flat)

        CHUNKS = 3

        def timed(fn):
            samples = []
            for _ in range(runs):
                best = 0.0
                for _ in range(CHUNKS):
                    comm.barrier()
                    t0 = time.perf_counter()
                    for _ in range(reps):
                        fn()
                    comm.barrier()
                    best = max(best, reps / (time.perf_counter() - t0))
                samples.append(best)
            samples.sort()
            return samples[len(samples) // 2], samples

        # warm (sockets, EF residuals, chooser ramps past hysteresis)
        for _ in range(3):
            q8_step(); q4_step(); hier_step()

        comm.stats.reset()
        q8_sps, q8_runs = timed(q8_step)
        q8_stats = comm.stats.summary()
        comm.stats.reset()
        q4_sps, q4_runs = timed(q4_step)
        q4_stats = comm.stats.summary()
        comm.stats.reset()
        w0 = len(chooser.widths)
        hier_sps, hier_runs = timed(hier_step)
        hier_stats = comm.stats.summary()
        hier_widths = chooser.widths[w0:]

        # (c) overlap: the actual host train step, bucketed, on an MLP
        # sized so each bucket's REPLICATED AdamW update is real device
        # work (~2M params -> ~4ms/bucket) — that update, dispatched
        # async, is what the next bucket's ring traffic hides behind
        # (one fused backward delivers all grads atomically, so there
        # is no later-layer backward to overlap; the is_ready-measured
        # accounting in parallel/data_parallel.py would book ZERO
        # overlap for a too-small model, honestly)
        model = models.DummyModel(in_dim=1024, hidden_dim=2048,
                                  n_classes=16)
        params = model.init(jax.random.PRNGKey(0))
        opt = optim.adamw(1e-3)

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(model.apply(p, x), y), {}

        xb = rng.standard_normal((8, 1024)).astype(np.float32)
        yb = (np.arange(8) % 16).astype(np.int32)

        def run_overlap(on):
            step = make_train_step(loss_fn, opt, donate=False,
                                   grad_reduce="quant", overlap=on,
                                   comm_buckets=4)
            st = (step.init_opt_state(params)
                  if hasattr(step, "init_opt_state")
                  else opt.init(params))
            out = step(params, st, (xb, yb))     # warm/compile
            jax.block_until_ready(out.params)
            comm.barrier()
            comm.stats.reset()
            nsteps = 5
            t0 = time.perf_counter()
            for _ in range(nsteps):
                out = step(out.params, out.opt_state, (xb, yb))
            jax.block_until_ready(out.params)
            wall = time.perf_counter() - t0
            snap = comm.stats.snapshot()
            comm.barrier()
            return {"exposed_ms": round(1e3 * snap["exposed_s"]
                                        / nsteps, 3),
                    "overlapped_ms": round(1e3 * snap["overlapped_s"]
                                           / nsteps, 3),
                    # wall time travels with the record so an "overlap"
                    # that relabels without hiding is visible
                    "step_ms": round(1e3 * wall / nsteps, 3)}

        no_ov = run_overlap(False)
        ov = run_overlap(True)

        if rank == 0:
            nsteps = runs * CHUNKS * reps
            blocking = lambda s: sum(d["seconds"] for d in s.values())
            # expected hier slow-hop bytes per leader step given the
            # widths the chooser ACTUALLY used in the timed window —
            # //nh INSIDE the per-leg term, exactly as HierRing
            # accounts each leg (the outer-division form differs by a
            # rounding byte whenever leg_bytes % nh >= nh/2)
            hier_expected = sum(
                2 * (wire.quant_leg_wire_bytes(n_elems, nh, bits=b)
                     // nh)
                for b in hier_widths)
            hier_measured = (hier_stats["hier_reduce"]["bytes"]
                             + hier_stats["hier_gather"]["bytes"])
            hist = {}
            for b in hier_widths:
                hist[str(b)] = hist.get(str(b), 0) + 1
            q.put({
                "hier_world": world,
                "hier_local_world": local_world,
                "hier_bucket_mb": round(n_elems * 4 / (1 << 20), 2),
                "q8_steps_per_sec": round(q8_sps, 2),
                "q4_steps_per_sec": round(q4_sps, 2),
                "hier_steps_per_sec": round(hier_sps, 2),
                "hier_runs": {"q8": [round(r, 2) for r in q8_runs],
                              "q4": [round(r, 2) for r in q4_runs],
                              "hier": [round(r, 2) for r in hier_runs]},
                # per-rank wire payload accounting vs the wire.py
                # formulas (CommStats accounting parity — actual framed
                # bytes are pinned by the native bit-parity tests)
                "f32_wire_bytes": wire.ring_allreduce_wire_bytes(
                    n_elems, world) // world,
                "q8_wire_bytes":
                    q8_stats["allreduce_q8"]["bytes"] // nsteps,
                "q4_wire_bytes":
                    q4_stats["allreduce_q4"]["bytes"] // nsteps,
                "q4_wire_bytes_expected":
                    wire.quant_ring_allreduce_wire_bytes(
                        n_elems, world, bits=4) // world,
                # slow-hop (leader-ring) bytes of the two-level arm:
                # measured on THIS leader vs formula-from-used-widths
                # (the CommStats accounting parity pin), plus the
                # all-leaders total vs the flat ring's all-ranks total
                # — on a flat host ring EVERY byte of EVERY rank rides
                # the slow transport, so the total is the ~local_world
                # reduction headline
                "hier_slow_hop_bytes": hier_measured,
                "hier_slow_hop_bytes_expected": hier_expected,
                # the PER-STEP figure the report renders next to the
                # per-step flat-arm columns (the window total above is
                # the exact-equality accounting pin)
                "hier_slow_hop_bytes_per_step": hier_measured // nsteps,
                "hier_slow_hop_bytes_total": sum(
                    2 * wire.quant_leg_wire_bytes(n_elems, nh, bits=b)
                    for b in hier_widths),
                "flat_slow_hop_bytes_q8":
                    nsteps * wire.quant_ring_allreduce_wire_bytes(
                        n_elems, world),
                # the flat all-ranks ring AT THE SAME WIDTHS the
                # adaptive hier arm actually used: dividing by this
                # isolates the TOPOLOGY cut (~(W-1)/(nh-1)) from the
                # q4 width cut the separate q4 gate already claims
                "flat_slow_hop_bytes_matched_width": sum(
                    wire.quant_ring_allreduce_wire_bytes(
                        n_elems, world, bits=b)
                    for b in hier_widths),
                "hier_width_hist": hist,
                "hier_blocking_ms_per_step": round(
                    1000 * blocking(hier_stats) / nsteps, 3),
                "q8_blocking_ms_per_step": round(
                    1000 * blocking(q8_stats) / nsteps, 3),
                "overlap": {"off": no_ov, "on": ov},
            })
    finally:
        dist.cleanup()


def bench_dp8_hier(n_elems: int = None, reps: int = 2,
                   runs: int = 5, world: int = COMM_WORLD) -> dict:
    """The ``dp8_hier_adaptive`` flagship arm: adaptive-width two-level
    ring vs the flat q8 ring on the same bucket, plus the measured
    overlap exposed_ms drop."""
    import multiprocessing as mp

    from distributed_pytorch_tpu.runtime.multiprocess import (
        launch_multiprocess)

    if n_elems is None:
        n_elems = int(_env.get("DPX_BENCH_HIER_ELEMS")) \
            or HIER_BUCKET_ELEMS
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    launch_multiprocess(_dp8_hier_worker, world, q, n_elems, reps, runs)
    return q.get(timeout=180)


def _dp8_hier_metric_blobs(rec: dict) -> dict:
    """Gated metric blobs + the vs_q8 gated_ratio for the
    dp8_hier_adaptive arm (the flagship claim is a RATIO, so both sides
    run through the spread gate — never a bare division)."""
    blobs = {}
    runs = rec.get("hier_runs") or {}
    stats = {}
    for name, key in (("dp8_hier_adaptive_steps_per_sec", "hier"),
                      ("dp8_hier_q8_steps_per_sec", "q8"),
                      ("dp8_hier_q4_steps_per_sec", "q4")):
        if runs.get(key):
            stats[key] = _stats.summarize(runs[key], warmup=0)
            blobs[name] = _record.make_metric(None, "steps_per_sec",
                                              stats=stats[key])
    if "hier" in stats and "q8" in stats:
        ratio, why = _stats.gated_ratio(stats["hier"], stats["q8"])
        if ratio is not None:
            rec["vs_q8"] = round(ratio, 2)
        else:
            rec["vs_q8_withheld"] = why
    return blobs


def bench_dp8_sharded(n_elems: int = None, reps: int = 2,
                      runs: int = 5, world: int = COMM_WORLD) -> dict:
    """The ``dp8_sharded_adam`` flagship arm: ZeRO-1 sharded AdamW vs
    the replicated update on the 8-process native quantized ring."""
    import multiprocessing as mp

    from distributed_pytorch_tpu.runtime.multiprocess import (
        launch_multiprocess)

    if n_elems is None:
        # smoke sizing knob (registry-typed): 0 means the full-size arm
        n_elems = int(_env.get("DPX_BENCH_SHARDED_ELEMS")) \
            or SHARDED_BUCKET_ELEMS
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    launch_multiprocess(_dp8_sharded_worker, world, q, n_elems, reps,
                        runs)
    return q.get(timeout=120)


def _dp8_sharded_metric_blobs(rec: dict) -> dict:
    """Gated metric blobs + the vs_replicated gated_ratio for the
    dp8_sharded_adam arm (the flagship claim is a RATIO, so both sides
    run through the spread gate — never a bare division)."""
    blobs = {}
    runs = rec.get("sharded_runs") or {}
    stats = {}
    for name, key in (("dp8_sharded_adam_steps_per_sec", "sharded"),
                      ("dp8_sharded_replicated_steps_per_sec",
                       "replicated")):
        if runs.get(key):
            stats[key] = _stats.summarize(runs[key], warmup=0)
            blobs[name] = _record.make_metric(None, "steps_per_sec",
                                              stats=stats[key])
    if "sharded" in stats and "replicated" in stats:
        # TrialStats numerator: gated_ratio gates BOTH sides itself
        ratio, why = _stats.gated_ratio(stats["sharded"],
                                        stats["replicated"])
        if ratio is not None:
            rec["vs_replicated"] = round(ratio, 2)
        else:
            rec["vs_replicated_withheld"] = why
    return blobs


def bench_dp8(n_steps: int = 15) -> dict:
    rec = run_json_subprocess(
        [sys.executable, "-c", _dp8_code(n_steps)], 600,
        label="dp8 bench",
        env={"JAX_PLATFORMS": "cpu", "DPX_CPU_DEVICES": "8"})
    comm = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage", "dp8_comm"],
        600, label="dp8 comm bench", env={"JAX_PLATFORMS": "cpu"})
    if "error" in comm:
        rec["comm_error"] = comm["error"]
    rec.update({k: v for k, v in comm.items() if k.startswith("comm_")})
    return rec


def _dp8_metric_blobs(dp8: dict) -> dict:
    """Gated metric blobs from the dp8 record — the entries benchdiff
    anchors regression verdicts on. The comm medians re-run through
    summarize() (already-warmed samples: warmup=0)."""
    blobs = {}
    if isinstance(dp8.get("metric_blob"), dict):
        # move, don't copy: the record stores each trials blob ONCE,
        # under metrics — the append-only store grows per byte
        blobs["dp8_steps_per_sec"] = dp8.pop("metric_blob")
    for name, key in (("dp8_comm_quant_steps_per_sec", "quant"),
                      ("dp8_comm_f32_steps_per_sec", "f32")):
        runs = (dp8.get("comm_runs") or {}).get(key)
        if runs:
            st = _stats.summarize(runs, warmup=0)
            blobs[name] = _record.make_metric(None, "steps_per_sec",
                                              stats=st)
    return blobs


# ---------------------------------------------------------------------------
# dp8_donate arm: whole-step buffer donation A/B on the pjit front door
# (docs/front_door.md) — the same spec point built donate=ON (the
# default: params + opt state donated, out == in shardings) and
# donate=OFF, paired steps/s through the perfbench policy plus XLA's
# OWN memory accounting (memory_analysis): the donated build must
# alias its state buffers (alias bytes > 0) and its peak bytes must be
# STRICTLY below the copy build's — the HBM the roofline says the
# compute-bound flagship needs back. Compile counters assert one
# program per arm (the front-door discipline, not trusted).
# ---------------------------------------------------------------------------

DONATE_HIDDEN = 2048
DONATE_IN_DIM = 512


def bench_dp8_donate(steps: int = 20) -> dict:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_pytorch_tpu.runtime.jax_compat import (
        ensure_cpu_devices)
    ensure_cpu_devices(8)
    import numpy as np

    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import front_door, make_step

    _stats.pin_process()
    dist.init_process_group(rank=0, world_size=8)
    model = models.DummyModel(in_dim=DONATE_IN_DIM,
                              hidden_dim=DONATE_HIDDEN, n_classes=16)
    params0 = model.init(jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(np.shape(l)))
                   for l in jax.tree_util.tree_leaves(params0))
    opt = optim.adamw(1e-3)

    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy(model.apply(p, x), y), {}

    rng = np.random.default_rng(0)
    x = dist.shard_batch(
        rng.standard_normal((64, DONATE_IN_DIM)).astype(np.float32))
    y = dist.shard_batch((np.arange(64) % 16).astype(np.int32))
    batch = (x, y)

    front_door.cache_clear()
    arms = {}
    for name, donate in (("donated", True), ("copy", False)):
        step = make_step(loss_fn, opt, donate=donate)
        p = model.init(jax.random.PRNGKey(0))
        st = opt.init(p)
        out = step(p, st, batch)          # compile + warm (counted)
        jax.block_until_ready(out.loss)
        # memory_analysis AFTER the counted first call: lower() shares
        # the jit trace cache, so the other order would satisfy the
        # first call from the uncounted analysis trace
        arms[name] = {"step": step,
                      "mem": step.memory_analysis(out.params,
                                                  out.opt_state, batch)}
        state = {"out": out}

        def one_run(step=step, state=state):
            o = state["out"]
            t0 = time.perf_counter()
            for _ in range(steps):
                o = step(o.params, o.opt_state, batch)
            jax.block_until_ready(o.loss)
            state["out"] = o
            return steps / (time.perf_counter() - t0)

        arms[name]["stats"] = _stats.measure(one_run)

    don, cop = arms["donated"], arms["copy"]
    rec = {
        "donate_world": 8,
        "model_params": n_params,
        "global_batch": 64,
        "donated_steps_per_sec": round(don["stats"].median, 2),
        "copy_steps_per_sec": round(cop["stats"].median, 2),
        "donate_runs": {
            "donated": [round(r, 2) for r in don["stats"].runs],
            "copy": [round(r, 2) for r in cop["stats"].runs]},
        # XLA's compiled accounting, not a narrative: peak = args +
        # outputs + temps - aliased; donation aliases params+opt state
        "donated_peak_bytes": don["mem"]["peak_bytes"],
        "copy_peak_bytes": cop["mem"]["peak_bytes"],
        "donated_alias_bytes": don["mem"]["alias"],
        "copy_alias_bytes": cop["mem"]["alias"],
        "peak_saved_bytes": (cop["mem"]["peak_bytes"]
                             - don["mem"]["peak_bytes"]),
        "peak_saved_frac": round(
            1 - don["mem"]["peak_bytes"]
            / max(cop["mem"]["peak_bytes"], 1), 4),
        # the front-door compile discipline, asserted by the smoke
        "donated_compiles": don["step"].compiles,
        "copy_compiles": cop["step"].compiles,
        "timing_method": f"{steps}-step chained windows, fetch-fenced, "
                         "perfbench trials",
    }
    dist.cleanup()
    return rec


def _dp8_donate_metric_blobs(rec: dict) -> dict:
    """Gated metric blobs + the vs_copy gated_ratio for the dp8_donate
    arm (the flagship claim is a RATIO, so both sides run through the
    spread gate — never a bare division)."""
    blobs = {}
    runs = rec.get("donate_runs") or {}
    stats = {}
    for name, key in (("dp8_donate_steps_per_sec", "donated"),
                      ("dp8_donate_copy_steps_per_sec", "copy")):
        if runs.get(key):
            stats[key] = _stats.summarize(runs[key], warmup=0)
            blobs[name] = _record.make_metric(None, "steps_per_sec",
                                              stats=stats[key])
    if "donated" in stats and "copy" in stats:
        ratio, why = _stats.gated_ratio(stats["donated"], stats["copy"])
        if ratio is not None:
            rec["vs_copy"] = round(ratio, 2)
        else:
            rec["vs_copy_withheld"] = why
    return blobs


# ---------------------------------------------------------------------------
# decode-attention arm: the page-blockwise decode kernel vs the dense
# full-pool baseline (docs/compute.md) — the CI smoke gates (i) token
# streams bit-identical to generate() on a LONG pool serving short
# requests and (ii) measured short-resident decode step time <= the
# dense-full-width softmax it replaced
# ---------------------------------------------------------------------------

DECODE_ATTN_POOL = 2048     # pool width (positions) — the "capacity"
DECODE_ATTN_RESIDENT = 12   # resident length — the "occupancy"


def bench_decode_attention(max_len: int = DECODE_ATTN_POOL,
                           n_slots: int = 4,
                           resident: int = DECODE_ATTN_RESIDENT,
                           steps: int = 30) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu import models
    from distributed_pytorch_tpu.models.generate import (decode_step_slots,
                                                         make_generate_fn)
    from distributed_pytorch_tpu.ops.decode_attention import (
        DECODE_BLOCK, resident_blocks)
    from distributed_pytorch_tpu.serve import (EngineConfig,
                                               InferenceEngine,
                                               SamplingParams)
    from distributed_pytorch_tpu.utils.profiler import fetch_fence

    model = models.TransformerLM(vocab=128, dim=64, n_layers=2,
                                 n_heads=4, n_kv_heads=2, pos="rope",
                                 max_seq=max_len)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    # (i) token contract on the long pool: engine streams == generate()
    progress("decode-attn arm: token contract (long pool, short "
             "requests)")
    prompts = [rng.integers(0, 128, (s,)).astype(np.int32)
               for s in (5, resident, 7, 9)]
    sp = SamplingParams(max_new_tokens=6)
    keys = [jax.random.PRNGKey(40 + i) for i in range(len(prompts))]
    eng = InferenceEngine(model, params,
                          EngineConfig(n_slots=n_slots, max_len=max_len))
    with eng:
        outs = [eng.submit(p, sp, rng=k).result(timeout=300)
                for p, k in zip(prompts, keys)]
    decode_compiles = eng.pool.compiles.decode
    tokens_equal = True
    for p, k, out in zip(prompts, keys, outs):
        fn = make_generate_fn(model, sp.max_new_tokens, max_len=max_len)
        ref = np.asarray(jax.jit(fn)(params, jnp.asarray(p[None]), k))[0]
        tokens_equal = tokens_equal and bool(np.array_equal(out, ref))

    # (ii) decode step time at short resident length, blockwise vs the
    # dense full-pool softmax — same jitted step, same donation, only
    # the kernel differs
    def make_step(blockwise):
        def f(p, ks, vs, lengths, tokens):
            lo, ks, vs = decode_step_slots(model, p, ks, vs, lengths,
                                           tokens, blockwise=blockwise)
            return lo, ks, vs
        return jax.jit(f, donate_argnums=(1, 2))

    dh = model.dim // model.n_heads
    lengths = jnp.asarray(
        rng.integers(1, resident, (n_slots,)).astype(np.int32))
    tokens = jnp.asarray(rng.integers(0, 128, (n_slots,)), jnp.int32)

    def one_run(step_fn):
        ks = [jnp.asarray(rng.standard_normal((n_slots, 2, max_len, dh)),
                          jnp.float32) for _ in range(model.n_layers)]
        vs = [jnp.asarray(rng.standard_normal((n_slots, 2, max_len, dh)),
                          jnp.float32) for _ in range(model.n_layers)]
        lo, ks, vs = step_fn(params, ks, vs, lengths, tokens)  # compile
        fetch_fence(lo)
        t0 = time.perf_counter()
        for _ in range(steps):
            lo, ks, vs = step_fn(params, ks, vs, lengths, tokens)
        fetch_fence(lo)
        return steps / (time.perf_counter() - t0)   # steps/s

    rows = {}
    for name, blockwise in (("blockwise", True), ("dense", False)):
        progress(f"decode-attn arm: timing {name} decode "
                 f"(pool {max_len}, resident <= {resident})")
        fn = make_step(blockwise)
        # steps/s through the perfbench policy (trials, warmup discard,
        # spread gate) — the ms medians below are its reciprocal view
        rows[name] = _stats.measure(lambda fn=fn: one_run(fn))
    blk_ms = 1e3 / rows["blockwise"].median
    dense_ms = 1e3 / rows["dense"].median
    visited = int(resident_blocks(lengths, DECODE_BLOCK,
                                  -(-max_len // DECODE_BLOCK)))
    return {"pool_len": max_len,
            "resident_len_max": int(np.asarray(lengths).max()),
            "block_len": DECODE_BLOCK,
            "blocks_total": -(-max_len // DECODE_BLOCK),
            "blocks_visited": visited,
            "tokens_equal_generate": tokens_equal,
            "decode_compiles": decode_compiles,
            "blockwise_step_ms": round(blk_ms, 3),
            "dense_step_ms": round(dense_ms, 3),
            "speedup_x": round(dense_ms / blk_ms, 2) if blk_ms else None,
            "blockwise_trusted": rows["blockwise"].trusted,
            "dense_trusted": rows["dense"].trusted,
            "runs_blockwise_ms": [round(1e3 / r, 3)
                                  for r in rows["blockwise"].runs],
            "runs_dense_ms": [round(1e3 / r, 3)
                              for r in rows["dense"].runs]}


def bench_obs_overhead(n: int = 20000) -> dict:
    """dpxtrace span-API overhead (docs/observability.md): ns/span with
    tracing OFF (must be unmeasurable — one global read + one ``if``),
    ON with the ring only, and ON with the line-JSON sink. The smoke
    gate turns the ON cost into a fraction of the measured dp8 step
    (spans/step x ns/span) and asserts it stays small; the perfbench
    policy (trials, warmup discard, spread gate) governs every number."""
    import tempfile

    from distributed_pytorch_tpu.obs import trace as dpxtrace

    def ns_per_span():
        t0 = time.perf_counter_ns()
        for _ in range(n):
            with dpxtrace.span("bench.op", b=1):
                pass
        return (time.perf_counter_ns() - t0) / n

    rows = {}
    log_path = os.path.join(tempfile.mkdtemp(prefix="dpxtrace_bench_"),
                            "spans.jsonl")
    for name, kw in (
            ("off", dict(enabled=False)),
            # ring only: the flight-recorder-armed production shape
            ("on_ring", dict(enabled=True, ring=256, log_path=None)),
            # full sink: every span to the line-JSON log
            ("on_log", dict(enabled=True, ring=256,
                            log_path=log_path))):
        dpxtrace.reset()
        dpxtrace.configure(**kw)
        rows[name] = _stats.measure(ns_per_span)
    dpxtrace.reset()
    try:
        sz = os.path.getsize(log_path)
    except OSError:
        sz = 0

    # dpxmon counter hot path (obs/metrics.py): metrics-off must be the
    # same one-global-read shape as the disabled span, metrics-on a
    # dict update; the snapshot emission is measured on a REALISTIC
    # registry (instruments + a CommStats-shaped provider) so the
    # cadence cost the smoke amortizes against the dp8 step is honest
    from distributed_pytorch_tpu.obs import metrics as dpxmon

    def ns_per_inc():
        t0 = time.perf_counter_ns()
        for _ in range(n):
            dpxmon.inc("bench.counter")
        return (time.perf_counter_ns() - t0) / n

    mon_rows = {}
    mon_log = os.path.join(os.path.dirname(log_path), "mon.jsonl")
    for name, on in (("off", False), ("on", True)):
        dpxmon.reset()
        dpxmon.configure(enabled=on, rank=0)
        mon_rows[name] = _stats.measure(ns_per_inc)
    # snapshot cost: ~20 gauges/counters, 2 populated histograms, one
    # provider with a comm-shaped payload — the production soak shape
    dpxmon.reset()
    dpxmon.configure(enabled=True, rank=0)
    for i in range(10):
        dpxmon.inc(f"bench.c{i}", i)
        dpxmon.set_gauge(f"bench.g{i}", i * 1.5)
    for i in range(256):
        dpxmon.observe("bench.h0", i * 0.1)
        dpxmon.observe("bench.h1", i * 0.2)
    dpxmon.register_provider("bench", lambda: {
        f"comm.op{i}.bytes": i * 1000 for i in range(8)})

    def ms_per_snapshot(m=50):
        t0 = time.perf_counter_ns()
        for _ in range(m):
            dpxmon.emit_snapshot(path=mon_log, step=0, source="bench")
        return (time.perf_counter_ns() - t0) / m / 1e6

    snap_stats = _stats.measure(ms_per_snapshot)
    dpxmon.reset()
    return {"n_spans_per_trial": n,
            "off_ns_per_span": round(rows["off"].median, 1),
            "on_ring_ns_per_span": round(rows["on_ring"].median, 1),
            "on_log_ns_per_span": round(rows["on_log"].median, 1),
            "off_trusted": rows["off"].trusted,
            "on_log_trusted": rows["on_log"].trusted,
            "log_bytes_per_span": round(
                sz / max(n * len(rows["on_log"].runs
                                 + rows["on_log"].warmup_discarded),
                         1), 1),
            "runs_off_ns": [round(r, 1) for r in rows["off"].runs],
            "runs_on_log_ns": [round(r, 1)
                               for r in rows["on_log"].runs],
            "mon_off_ns_per_inc": round(mon_rows["off"].median, 1),
            "mon_on_ns_per_inc": round(mon_rows["on"].median, 1),
            "mon_snapshot_ms": round(snap_stats.median, 4),
            "mon_snapshot_trusted": snap_stats.trusted,
            "runs_mon_off_ns": [round(r, 1)
                                for r in mon_rows["off"].runs]}


# ---------------------------------------------------------------------------


def _stage_main(stage: str) -> int:
    """Run ONE measurement in this process and print its JSON line
    (invoked by the orchestrator via _run_stage)."""
    if stage == "mfu":
        from benchmarks.mfu_transformer import run as mfu_run
        print(json.dumps(mfu_run()))
    elif stage == "mfu_medium":
        from benchmarks.mfu_transformer import MEDIUM
        from benchmarks.mfu_transformer import run as mfu_run
        print(json.dumps(mfu_run(steps=20, **MEDIUM)))
    elif stage == "mfu_host":
        from benchmarks.mfu_transformer import run_host_flagship
        print(json.dumps(run_host_flagship()))
    elif stage == "min_ddp":
        print(json.dumps(bench_min_ddp()))
    elif stage == "dp8_comm":
        print(json.dumps(bench_dp8_comm()))
    elif stage == "dp8_sharded":
        print(json.dumps(bench_dp8_sharded()))
    elif stage == "dp8_hier":
        print(json.dumps(bench_dp8_hier()))
    elif stage == "dp8_donate":
        print(json.dumps(bench_dp8_donate()))
    elif stage == "decode":
        from benchmarks.decode_tpu import run_gqa_compare
        print(json.dumps(run_gqa_compare()))
    elif stage == "decode_attn":
        print(json.dumps(bench_decode_attention()))
    elif stage == "obs_overhead":
        print(json.dumps(bench_obs_overhead()))
    elif stage == "scale_sweep":
        from benchmarks.scale_sweep import run_scale_sweep
        print(json.dumps(run_scale_sweep()))
    else:
        print(json.dumps({"error": f"unknown stage {stage!r}"}))
        return 2
    return 0


def _adopt_fresh_mfu(rec: dict, mfu_rec: dict, stage: str) -> bool:
    """Fold a fresh mfu-stage result into the headline record (value,
    provenance, trust from the per-run spread gate when trials exist,
    roofline + plausibility BEFORE the raw row lands) and append the
    raw row. Returns True when a measured mfu was adopted."""
    # `is not None`, not `in`: the mfu stage emits "mfu": null when
    # peak FLOPS for the device kind are unknown — that must fall
    # through to the carry-forward path, never become a "measured"
    # null headline (the r03-r05 failure mode)
    ok = mfu_rec.get("mfu") is not None
    if ok:
        runs = mfu_rec.get("mfu_runs") or []
        st = _stats.summarize(runs, warmup=0) if len(runs) > 1 else None
        rec["value"] = mfu_rec["mfu"]
        rec["provenance"] = "measured"
        rec["trusted"] = bool(st.trusted) if st is not None else True
        if rec["trusted"]:
            rec.pop("untrusted_reason", None)
        else:
            rec["untrusted_reason"] = st.untrusted_reason
        rec["device"] = mfu_rec.get("device", rec.get("device"))
        rec["tokens_per_sec"] = mfu_rec["tokens_per_sec"]
        rec["mfu_detail"] = mfu_rec
        rec["metrics"][HEADLINE_METRIC] = _record.make_metric(
            mfu_rec["mfu"], "mfu_fraction", stats=st)
        # plausibility verdict BEFORE the raw row lands: bench_mfu
        # rows are future last_good sources, so a roofline-poisoned
        # value must reach the store as ok=False, not as evidence
        attach_roofline(rec)
    append_result(stage, mfu_rec,
                  ok=ok and rec.get("trusted", False))
    return ok and rec.get("provenance") == "measured"


def _adopt_last_good(rec: dict) -> bool:
    """Fill an unmeasured headline from the newest last_good flagship
    row (explicit provenance, traceable source), or mark the record
    untrusted with the reason when none exists. The ONE carry-forward
    shape — main() and headline() both use it, so the two entry points
    can never drift into writing differently-shaped records into the
    same trajectory store."""
    lg = last_good_record()
    if lg:
        rec["value"] = lg["mfu"]
        rec["provenance"] = "last_good"
        rec["last_good"] = lg
        rec["trusted"] = True
        rec.pop("untrusted_reason", None)
        rec["metrics"][HEADLINE_METRIC] = _record.make_metric(
            lg["mfu"], "mfu_fraction", provenance="last_good",
            last_good=lg)
        return True
    rec["untrusted_reason"] = (
        "unmeasured and no last_good flagship row on file: "
        + rec.get("error", rec.get("tpu_backend", "?")))
    return False


def _host_flagship_fallback(rec: dict) -> bool:
    """No healthy TPU: measure the pinned HOST flagship arm
    (benchmarks/mfu_transformer.FLAGSHIP_CPU — the composed bf16-mp +
    remat + donation recipe against the CALIBRATED host peak) so the
    headline moves off the carry-forward with a fresh, gated, honestly
    labeled measurement (device + peak_source travel in mfu_detail).

    JAX_PLATFORMS=cpu explicitly: the runner strips the axon relay env
    only for cpu children, and THE scenario this fallback exists for is
    a wedged relay — an un-pinned child would block dialing it at
    interpreter startup and burn the whole stage timeout."""
    host_rec = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage",
         "mfu_host"], 2400, label="stage mfu_host",
        env={"JAX_PLATFORMS": "cpu"})
    return _adopt_fresh_mfu(rec, host_rec, "bench_mfu_host")


def main():
    rec = _record.make_record(HEADLINE_METRIC, "mfu_fraction")

    info = wait_for_backend()
    rec["device"] = info.get("kind") or "none"

    if info:
        mfu_rec = _run_stage("mfu", timeout_s=1800)
        adopted = _adopt_fresh_mfu(rec, mfu_rec, "bench_mfu")
        if not adopted:
            rec["error"] = ("mfu stage: "
                            + str(mfu_rec.get("error")
                                  or ("returned null mfu (device kind "
                                      "without a known peak FLOPS?)"
                                      if "mfu" in mfu_rec
                                      else "no result")))
        # bigger matmuls, higher attainable MFU — a reporting arm, never
        # the headline (the flagship config is pinned for comparability)
        rec["mfu_medium"] = _run_stage("mfu_medium", timeout_s=1800)
        append_result("bench_mfu_medium", rec["mfu_medium"])
        rec["min_ddp"] = _run_stage("min_ddp", timeout_s=900)
        append_result("bench_min_ddp", rec["min_ddp"])
        if "steps_per_sec" in rec["min_ddp"]:
            rec["metrics"]["min_ddp_steps_per_sec"] = _record.make_metric(
                rec["min_ddp"]["steps_per_sec"], "steps_per_sec")
        # two full decode benchmarks (MHA + GQA arms) live in this stage
        rec["decode"] = _run_stage("decode", timeout_s=2400)
        append_result("bench_decode", rec["decode"])
    else:
        # no TPU: the pinned host flagship arm is still a REAL gated
        # measurement (calibrated peak, spread-gated trials) — only
        # when IT also fails does the carry-forward path below engage
        rec["tpu_backend"] = "no healthy TPU backend after retries"
        if not _host_flagship_fallback(rec):
            rec["error"] = rec["tpu_backend"] \
                + "; host flagship arm also failed"

    if "value" not in rec:
        # last_good carry-forward — covers BOTH failure modes: backend
        # never appeared, or it appeared and the mfu stage wedged mid-run
        # (the round-3 killer). Nothing was measured NOW, so the record
        # says so in provenance — but it always carries a value a reader
        # can trace to its raw on-chip row, never a null.
        _adopt_last_good(rec)

    rec["dp8"] = bench_dp8()
    rec["metrics"].update(_dp8_metric_blobs(rec["dp8"]))

    # dp8_sharded_adam flagship arm (ZeRO-1 on the quantized ring):
    # steps/s vs the replicated update as a gated ratio, wire bytes and
    # per-rank optimizer-state shrink — subprocess-isolated like every
    # other stage so a wedge yields a parseable error field, not a hang
    rec["dp8_sharded"] = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage",
         "dp8_sharded"], 600, label="dp8 sharded bench",
        env={"JAX_PLATFORMS": "cpu"})
    rec["metrics"].update(_dp8_sharded_metric_blobs(rec["dp8_sharded"]))
    append_result("bench_dp8_sharded", rec["dp8_sharded"],
                  ok="error" not in rec["dp8_sharded"])

    # dp8_donate flagship arm (whole-step buffer donation on the pjit
    # front door): paired donate-on/off steps/s as a gated ratio plus
    # XLA memory_analysis peak bytes per arm — subprocess-isolated like
    # every other stage
    rec["dp8_donate"] = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage",
         "dp8_donate"], 600, label="dp8 donate bench",
        env={"JAX_PLATFORMS": "cpu", "DPX_CPU_DEVICES": "8"})
    rec["metrics"].update(_dp8_donate_metric_blobs(rec["dp8_donate"]))
    append_result("bench_dp8_donate", rec["dp8_donate"],
                  ok="error" not in rec["dp8_donate"])

    # dp8_hier_adaptive flagship arm (adaptive-width two-level ring +
    # measured comm-overlap exposure): paired vs the flat q8 ring as a
    # gated ratio, q4/adaptive wire bytes vs formula, exposed_ms
    # with/without overlap — subprocess-isolated like every other stage
    rec["dp8_hier"] = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage",
         "dp8_hier"], 600, label="dp8 hier bench",
        env={"JAX_PLATFORMS": "cpu"})
    rec["metrics"].update(_dp8_hier_metric_blobs(rec["dp8_hier"]))
    append_result("bench_dp8_hier", rec["dp8_hier"],
                  ok="error" not in rec["dp8_hier"])

    # roofline anchoring + plausibility gate: may flip the record to
    # untrusted (an MFU above the overlapped ceiling cannot be real).
    # Already attached on the fresh-measured path (before the raw
    # bench_mfu row landed); this covers the carry-forward/error paths.
    if "roofline_flagship" not in rec:
        attach_roofline(rec)
    if not rec.get("trusted") and HEADLINE_METRIC in rec["metrics"]:
        blob = rec["metrics"][HEADLINE_METRIC]
        blob["trusted"] = False
        blob["untrusted_reason"] = rec.get("untrusted_reason",
                                           "record untrusted")

    # vs_baseline: printed only when BOTH sides pass the spread gate —
    # withheld with the gate's reason otherwise (never silently blank)
    try:
        lm_stats = bench_torch_cpu_lm()
        rec["torch_cpu_lm_tokens_per_sec"] = round(lm_stats.median, 1)
        rec["torch_cpu_lm_baseline_detail"] = _baseline_detail(
            lm_stats, "tokens_per_sec")
        rec["metrics"]["torch_cpu_lm_tokens_per_sec"] = \
            _record.make_metric(None, "tokens_per_sec", stats=lm_stats)
        if rec.get("provenance") != "measured":
            ratio, why = None, ("flagship side is "
                                f"{rec.get('provenance')}, not a fresh "
                                "measurement")
        elif not rec.get("trusted"):
            ratio, why = None, (f"flagship untrusted: "
                                f"{rec.get('untrusted_reason')}")
        else:
            ratio, why = _stats.gated_ratio(rec.get("tokens_per_sec"),
                                            lm_stats)
        if ratio is not None:
            rec["vs_baseline"] = round(ratio, 2)
        else:
            rec["vs_baseline_withheld"] = why
    except Exception as e:  # noqa: BLE001
        rec["vs_baseline_withheld"] = (
            f"torch lm baseline failed: {type(e).__name__}: {e}")
        rec.setdefault("warnings", []).append(
            rec["vs_baseline_withheld"])

    # only worth minutes of eager-torch stepping if there is a min_ddp
    # record to attach the ratio to (absent whenever the TPU was down)
    if "steps_per_sec" in rec.get("min_ddp", {}):
        try:
            mlp_stats = bench_torch_cpu_mlp()
            rec["min_ddp"]["torch_cpu_baseline"] = _baseline_detail(
                mlp_stats, "steps_per_sec")
            rec["metrics"]["torch_cpu_mlp_steps_per_sec"] = \
                _record.make_metric(None, "steps_per_sec",
                                    stats=mlp_stats)
            ratio, why = _stats.gated_ratio(
                rec["min_ddp"]["steps_per_sec"], mlp_stats)
            if ratio is not None:
                rec["min_ddp"]["vs_torch_cpu"] = round(ratio, 2)
            else:
                rec["min_ddp"]["vs_torch_cpu_withheld"] = why
        except Exception:  # noqa: BLE001
            pass

    # self-check the schema BEFORE printing: an invalid record is a bug,
    # and the record contract says emit it anyway — with the issues
    # attached loudly rather than silently shipped
    issues = _record.validate_record(rec, strict=False)
    if issues:
        rec["schema_issues"] = issues
        print(f"# WARNING: record failed schema self-validation: "
              f"{'; '.join(issues[:3])}", file=sys.stderr)

    # the composite headline record is itself a raw-JSON trace — except
    # under run_all_tpu, whose bench_headline stage wrapper already logs
    # this whole record (avoid double rows for one run). ok=False for
    # carry-forward rows: they must never become a future last_good.
    if _env.get("DPX_BENCH_SELFLOG"):
        append_result("bench_record", rec,
                      ok=rec.get("provenance") == "measured"
                      and rec.get("trusted", False) and not issues)

    print(json.dumps(rec))


def headline() -> int:
    """``--headline``: measure and land ONLY the flagship headline.

    TPU mfu stage when the backend is healthy, else the pinned host
    flagship arm (``mfu_host``) — fresh gated measurement, roofline +
    plausibility attached, schema-validated, appended to the store.
    The dp8*/torch companion arms are NOT re-run: they are environment-
    sensitive (core count, neighbors) and re-measuring them on a
    changed container would manufacture spurious benchdiff verdicts —
    ``vs_baseline`` is withheld with exactly that reason, per the
    gate's never-silently-blank policy."""
    rec = _record.make_record(HEADLINE_METRIC, "mfu_fraction")
    info = wait_for_backend()
    rec["device"] = info.get("kind") or "none"
    if info:
        adopted = _adopt_fresh_mfu(rec, _run_stage("mfu", timeout_s=1800),
                                   "bench_mfu")
    else:
        rec["tpu_backend"] = "no healthy TPU backend after retries"
        adopted = _host_flagship_fallback(rec)
    if not adopted and "value" not in rec:
        if not _adopt_last_good(rec):
            rec["error"] = (rec.get("tpu_backend", "")
                            + "; flagship unmeasured and no last_good "
                              "row on file")
    if "roofline_flagship" not in rec:
        attach_roofline(rec)
    rec["vs_baseline_withheld"] = (
        "headline mode measures the flagship arm only — baselines and "
        "companion arms deliberately not re-run")
    issues = _record.validate_record(rec, strict=False)
    if issues:
        rec["schema_issues"] = issues
        print(f"# WARNING: record failed schema self-validation: "
              f"{'; '.join(issues[:3])}", file=sys.stderr)
    if _env.get("DPX_BENCH_SELFLOG"):
        append_result("bench_record", rec,
                      ok=rec.get("provenance") == "measured"
                      and rec.get("trusted", False) and not issues)
    print(json.dumps(rec))
    return 0 if rec.get("provenance") == "measured" and not issues else 1


# ---------------------------------------------------------------------------
# --smoke: the CPU-gated perfbench smoke (CI bench-smoke job)
# ---------------------------------------------------------------------------


def smoke() -> int:
    """Seconds-scale end-to-end exercise of the statistical policy:

    1. the spread gate structurally withholds a ratio built on synthetic
       noisy trials (the r05 70%-spread-baseline case, deterministic);
    2. the loopback dp8 smoke runs with affinity pinning + warmup
       discard and must come back TRUSTED — spread (IQR/median) under
       the 15% gate (the r05 dp8 fix, asserted);
    3. the resulting record is schema-valid and benchdiff-comparable.

    Exits nonzero on any violation (the CI gate)."""
    def gate(ok: bool, what: str) -> None:
        # explicit check, NOT assert: -O/PYTHONOPTIMIZE compiles
        # asserts out, and a gate whose checks never ran must not pass
        if not ok:
            print(f"# perfbench smoke FAILED: {what}", file=sys.stderr)
            raise SystemExit(1)

    progress("perfbench smoke: synthetic spread-gate check")
    noisy = _stats.summarize([100.0, 60.0, 100.0, 140.0, 101.0, 170.0],
                             warmup=1, max_spread=0.15)
    gate(not noisy.trusted, "70%-spread trials must fail the gate")
    ratio, why = _stats.gated_ratio(100.0, noisy)
    gate(ratio is None and "untrusted" in (why or ""),
         f"gated_ratio must withhold on an untrusted denominator: {why}")
    clean = _stats.summarize([100.0, 99.0, 101.0, 100.0], warmup=1)
    ratio, why = _stats.gated_ratio(200.0, clean)
    gate(ratio == 2.0 and why is None,
         f"gated_ratio must pass a clean 2x ratio: {ratio}, {why}")

    progress("perfbench smoke: dp8_sharded_adam (ZeRO-1 on the q8 ring)")
    sh = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage",
         "dp8_sharded"], 420, label="dp8 sharded smoke",
        env={"JAX_PLATFORMS": "cpu",
             # smoke sizing: 4 MiB bucket keeps the 8-proc arm seconds-
             # scale; byte accounting is size-independent
             "DPX_BENCH_SHARDED_ELEMS": str(1 << 20)})
    gate("error" not in sh, f"dp8 sharded arm failed: {sh.get('error')}")
    # the wire-byte claim is ASSERTED, not narrated: the sharded q8
    # update must move >= 3.5x fewer bytes than the f32 replicated
    # ring, and the runtime's per-op CommStats accounting must agree
    # with the wire.py formula for this bucket (protocol-level framed
    # bytes are pinned by the native bit-parity tests, not here)
    gate(sh["sharded_wire_bytes"] == sh["sharded_wire_bytes_expected"],
         f"CommStats-accounted sharded wire bytes "
         f"{sh['sharded_wire_bytes']} != wire.py formula "
         f"{sh['sharded_wire_bytes_expected']}")
    ratio = sh["replicated_f32_wire_bytes"] / sh["sharded_wire_bytes"]
    gate(ratio >= 3.5, f"sharded q8 wire reduction {ratio:.2f}x < 3.5x "
                       "vs the f32 replicated ring")
    gate(sh["opt_state_shrink"] >= 0.9 * (2 * sh["sharded_world"] / 3),
         f"opt-state shrink {sh['opt_state_shrink']}x below ~2W/3 "
         f"(W={sh['sharded_world']}: 2 moments/W + master vs 2 full)")
    blobs = _dp8_sharded_metric_blobs(sh)
    gate("dp8_sharded_adam_steps_per_sec" in blobs,
         "sharded arm produced no gated metric blob")
    gate(("vs_replicated" in sh) != ("vs_replicated_withheld" in sh),
         "dp8_sharded_adam must carry vs_replicated XOR its "
         "withhold reason")
    print(json.dumps({"smoke": "dp8_sharded_adam",
                      "ok": True,
                      "wire_ratio_vs_f32": round(ratio, 2),
                      "opt_state_shrink": sh["opt_state_shrink"],
                      **{k: sh[k] for k in ("vs_replicated",
                                            "vs_replicated_withheld")
                         if k in sh}}))

    progress("perfbench smoke: dp8_donate (whole-step buffer donation "
             "A/B on the pjit front door)")
    dn = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage",
         "dp8_donate"], 420, label="dp8 donate smoke",
        env={"JAX_PLATFORMS": "cpu", "DPX_CPU_DEVICES": "8"})
    gate("error" not in dn, f"dp8 donate arm failed: {dn.get('error')}")
    # the donation claim is XLA's own accounting, ASSERTED: the donated
    # build must alias its state buffers and its compiled peak bytes
    # must be STRICTLY below the copy build's
    gate(dn["donated_alias_bytes"] > 0,
         "donated build aliased zero bytes — donation silently dropped")
    gate(dn["copy_alias_bytes"] == 0,
         f"copy build aliased {dn['copy_alias_bytes']} bytes — the A/B "
         "arms are not a donation A/B")
    gate(dn["donated_peak_bytes"] < dn["copy_peak_bytes"],
         f"donated peak {dn['donated_peak_bytes']} not below copy peak "
         f"{dn['copy_peak_bytes']}")
    # one compiled program per arm (the front-door counter discipline)
    gate(dn["donated_compiles"] == 1 and dn["copy_compiles"] == 1,
         f"compile counters != 1: donated {dn['donated_compiles']}, "
         f"copy {dn['copy_compiles']}")
    blobs = _dp8_donate_metric_blobs(dn)
    gate("dp8_donate_steps_per_sec" in blobs,
         "donate arm produced no gated metric blob")
    gate(("vs_copy" in dn) != ("vs_copy_withheld" in dn),
         "dp8_donate must carry vs_copy XOR its withhold reason")
    print(json.dumps({"smoke": "dp8_donate", "ok": True,
                      "peak_bytes": {"donated": dn["donated_peak_bytes"],
                                     "copy": dn["copy_peak_bytes"]},
                      "peak_saved_frac": dn["peak_saved_frac"],
                      "alias_bytes": dn["donated_alias_bytes"],
                      **{k: dn[k] for k in ("vs_copy",
                                            "vs_copy_withheld")
                         if k in dn}}))

    progress("perfbench smoke: dp8_hier_adaptive (q4/adaptive two-level "
             "ring + overlap)")
    hr = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage",
         "dp8_hier"], 420, label="dp8 hier smoke",
        env={"JAX_PLATFORMS": "cpu",
             # smoke sizing: 4 MiB bucket keeps the 8-proc arm seconds-
             # scale; byte accounting is size-independent
             "DPX_BENCH_HIER_ELEMS": str(1 << 20)})
    gate("error" not in hr, f"dp8 hier arm failed: {hr.get('error')}")
    # the q4 byte claim is ASSERTED, not narrated: CommStats accounting
    # must equal the wire.py formula, and the q4 wire must move >= 6.5x
    # fewer bytes than the f32 ring on this bucket (protocol-level
    # framed bytes are pinned by the native bit-parity tests, not here)
    gate(hr["q4_wire_bytes"] == hr["q4_wire_bytes_expected"],
         f"CommStats-accounted q4 wire bytes {hr['q4_wire_bytes']} != "
         f"wire.py formula {hr['q4_wire_bytes_expected']}")
    q4_ratio = hr["f32_wire_bytes"] / hr["q4_wire_bytes"]
    gate(q4_ratio >= 6.5, f"q4 wire reduction {q4_ratio:.2f}x < 6.5x "
                          "vs the f32 ring")
    gate(hr["hier_slow_hop_bytes"] == hr["hier_slow_hop_bytes_expected"],
         f"hier slow-hop bytes {hr['hier_slow_hop_bytes']} != formula "
         f"{hr['hier_slow_hop_bytes_expected']} for the widths used")
    # topology cut at MATCHED widths (the pure two-level claim — the
    # q4 width cut is gated separately above, never double-counted)
    slow_x = (hr["flat_slow_hop_bytes_matched_width"]
              / hr["hier_slow_hop_bytes_total"])
    gate(slow_x > 1.5,
         f"two-level ring slow-hop topology reduction {slow_x:.2f}x — "
         "expected ~(W-1)/(nh-1) vs the same-width flat ring")
    # overlap is measured, not claimed: overlapped_ms only accrues when
    # the is_ready probe saw a dispatched bucket update GENUINELY still
    # executing at comm-issue time (a sleep-comm with instant updates
    # would book ~zero), so the gate is the ON mode's own measured
    # hidden fraction — cross-mode absolute exposed_ms comparisons are
    # reported but not gated (the two arms' total comm differs by >2x
    # run to run on this oversubscribed loopback world)
    ov, no_ov = hr["overlap"]["on"], hr["overlap"]["off"]
    gate(no_ov["overlapped_ms"] == 0,
         f"non-overlapped run booked hidden comm: {no_ov}")
    hidden_frac = ov["overlapped_ms"] / max(
        ov["overlapped_ms"] + ov["exposed_ms"], 1e-9)
    gate(ov["overlapped_ms"] > 0 and hidden_frac >= 0.2,
         f"overlap hid only {hidden_frac:.0%} of comm (measured via "
         f"is_ready): on={ov}")
    blobs = _dp8_hier_metric_blobs(hr)
    gate("dp8_hier_adaptive_steps_per_sec" in blobs,
         "hier arm produced no gated metric blob")
    gate(("vs_q8" in hr) != ("vs_q8_withheld" in hr),
         "dp8_hier_adaptive must carry vs_q8 XOR its withhold reason")
    print(json.dumps({"smoke": "dp8_hier_adaptive", "ok": True,
                      "q4_wire_ratio_vs_f32": round(q4_ratio, 2),
                      "slow_hop_reduction_x": round(slow_x, 2),
                      "exposed_ms": {"off": no_ov["exposed_ms"],
                                     "on": ov["exposed_ms"]},
                      "hidden_frac": round(hidden_frac, 3),
                      "step_ms": {"off": no_ov.get("step_ms"),
                                  "on": ov.get("step_ms")},
                      "width_hist": hr.get("hier_width_hist"),
                      **{k: hr[k] for k in ("vs_q8", "vs_q8_withheld")
                         if k in hr}}))

    progress("perfbench smoke: decode-attention arm (page-blockwise vs "
             "dense full pool)")
    da = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage",
         "decode_attn"], 600, label="decode attn smoke",
        env={"JAX_PLATFORMS": "cpu"})
    gate("error" not in da, f"decode-attn arm failed: {da.get('error')}")
    # (i) the kernel swap is invisible at the serving contract: token
    # streams bit-identical to generate() on a 2048-wide pool serving
    # ~12-token requests, with ONE decode compile
    gate(da["tokens_equal_generate"] is True,
         "long-pool engine streams diverged from generate()")
    gate(da["decode_compiles"] == 1,
         f"decode compiles {da['decode_compiles']} != 1")
    # (ii) the claimed win is MEASURED: at short resident length the
    # blockwise step must not be slower than the dense full-pool
    # baseline it replaced (it should be much faster — the scan visits
    # blocks_visited of blocks_total; the conservative gate is <=)
    gate(da["blocks_visited"] < da["blocks_total"],
         f"smoke config visits every block "
         f"({da['blocks_visited']}/{da['blocks_total']}) — the "
         "short-resident claim would be vacuous")
    gate(da["blockwise_step_ms"] <= da["dense_step_ms"],
         f"blockwise decode {da['blockwise_step_ms']}ms slower than "
         f"dense full-pool baseline {da['dense_step_ms']}ms")
    print(json.dumps({"smoke": "decode_attention", "ok": True,
                      "blockwise_step_ms": da["blockwise_step_ms"],
                      "dense_step_ms": da["dense_step_ms"],
                      "speedup_x": da["speedup_x"],
                      "blocks": f"{da['blocks_visited']}/"
                                f"{da['blocks_total']}"}))

    progress("perfbench smoke: loopback dp8 (pinned, warmup-discarded)")
    dp8 = run_json_subprocess(
        [sys.executable, "-c", _dp8_code(n_steps=15)], 420,
        label="dp8 smoke", env={"JAX_PLATFORMS": "cpu",
                                "DPX_CPU_DEVICES": "8"})
    if "error" in dp8:
        print(json.dumps({"smoke": "perfbench", "ok": False,
                          "error": dp8["error"]}))
        return 1

    rec = _record.make_record("dp8_smoke_steps_per_sec", "steps_per_sec",
                              device="cpu-loopback")
    if isinstance(dp8.get("metric_blob"), dict):
        rec["metrics"]["dp8_steps_per_sec"] = dp8["metric_blob"]
    rec["value"] = dp8["steps_per_sec"]
    rec["provenance"] = "measured"
    rec["trusted"] = bool(dp8.get("trusted"))
    if rec["trusted"]:
        rec.pop("untrusted_reason", None)
    else:
        rec["untrusted_reason"] = (dp8.get("metric_blob") or {}).get(
            "untrusted_reason", "dp8 smoke spread gate failed")
    _record.validate_record(rec)  # raises RecordInvalid on a schema bug

    # ONE spread verdict: the child's trust flag already encodes the
    # DPX_BENCH_MAX_SPREAD gate — re-checking a hard-coded 0.15 here
    # could contradict the policy it claims to enforce
    spread = dp8.get("spread_frac", 1.0)
    ok = rec["trusted"]
    print(json.dumps({"smoke": "perfbench", "ok": ok,
                      "dp8_steps_per_sec": dp8["steps_per_sec"],
                      "spread_frac": spread,
                      "runs": dp8.get("runs_steps_per_sec"),
                      "trusted": rec["trusted"]}))
    if not ok:
        gate_frac = float(_env.get("DPX_BENCH_MAX_SPREAD"))
        print(f"# dp8 smoke spread {spread:.0%} tripped the "
              f"{gate_frac:.0%} gate — the loopback dp8 must be quiet "
              "after pinning + warmup discard", file=sys.stderr)
        return 1

    progress("perfbench smoke: dpxtrace overhead (off ~zero, on a "
             "small fraction of the dp8 step)")
    ob = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage",
         "obs_overhead"], 300, label="obs overhead smoke",
        env={"JAX_PLATFORMS": "cpu"})
    gate("error" not in ob, f"obs overhead arm failed: {ob.get('error')}")
    # disabled tracing must be UNMEASURABLE next to any traced op: one
    # module-global read + one `if` — the bound is deliberately loose
    # (2 µs on a contended CI host) against a real cost of ~0.2-0.5 µs
    gate(ob["off_ns_per_span"] <= 2000,
         f"tracing-off span cost {ob['off_ns_per_span']}ns/span — the "
         "disabled path must be near-zero")
    # absolute per-span ceilings first — loose regression backstops
    # (a contended host doubles the measured cost: idle ~4/~12 µs,
    # under full tier-1 load ~8/~25 µs); the fraction gates below are
    # the tight ones and SELF-NORMALIZE (the dp8 denominator slows
    # down with the same contention)
    gate(ob["on_ring_ns_per_span"] <= 15000,
         f"ring-only span cost {ob['on_ring_ns_per_span']}ns/span "
         "exceeds the 15µs ceiling")
    gate(ob["on_log_ns_per_span"] <= 50000,
         f"sink span cost {ob['on_log_ns_per_span']}ns/span exceeds "
         "the 50µs ceiling")
    # then the fraction of the step it instruments — asserted against
    # the MEASURED dp8 step just above, which is a deliberately
    # PATHOLOGICAL denominator (a ~0.7-1.5 ms MLP micro-step; the host
    # flagship step is ~4 s, serve decode ~10 ms — there the same span
    # cost is noise). The non-overlapped host step emits 5 spans
    # (host_step + backward + bucket + comm + update): ring-only (the
    # always-on flight-recorder shape) within 5% of even this
    # micro-step, the full line-JSON sink within 15%.
    step_ns = 1e9 / dp8["steps_per_sec"]
    spans_per_step = 5
    ring_frac = spans_per_step * ob["on_ring_ns_per_span"] / step_ns
    log_frac = spans_per_step * ob["on_log_ns_per_span"] / step_ns
    gate(ring_frac <= 0.05,
         f"ring-only tracing cost {ring_frac:.2%} of the measured dp8 "
         f"micro-step ({ob['on_ring_ns_per_span']}ns/span x "
         f"{spans_per_step}) exceeds the 5% bound")
    gate(log_frac <= 0.15,
         f"tracing-on (line-JSON sink) cost {log_frac:.2%} of the "
         f"measured dp8 micro-step ({ob['on_log_ns_per_span']}ns/span "
         f"x {spans_per_step}) exceeds the 15% bound")
    # dpxmon counter hot path (docs/observability.md): metrics-off is
    # the same one-global-read shape as the disabled span (<= 2 µs),
    # metrics-on a dict update under a loose absolute backstop, and
    # the snapshot emission — measured on a realistic registry —
    # amortizes over the reference 50-step cadence to a small fraction
    # of even the pathological dp8 micro-step denominator
    gate(ob["mon_off_ns_per_inc"] <= 2000,
         f"metrics-off increment {ob['mon_off_ns_per_inc']}ns — the "
         "disabled path must be near-zero")
    gate(ob["mon_on_ns_per_inc"] <= 15000,
         f"metrics-on increment {ob['mon_on_ns_per_inc']}ns exceeds "
         "the 15µs ceiling")
    gate(ob["mon_snapshot_ms"] <= 20.0,
         f"snapshot emission {ob['mon_snapshot_ms']}ms exceeds the "
         "20ms absolute ceiling")
    snap_frac = (ob["mon_snapshot_ms"] * 1e6 / 50) / step_ns
    gate(snap_frac <= 0.05,
         f"snapshot cadence cost {snap_frac:.2%} of the measured dp8 "
         f"micro-step ({ob['mon_snapshot_ms']}ms / 50-step cadence) "
         "exceeds the 5% bound")
    print(json.dumps({"smoke": "obs_overhead", "ok": True,
                      "off_ns_per_span": ob["off_ns_per_span"],
                      "on_ring_ns_per_span": ob["on_ring_ns_per_span"],
                      "on_log_ns_per_span": ob["on_log_ns_per_span"],
                      "ring_frac_of_dp8_step": round(ring_frac, 6),
                      "log_frac_of_dp8_step": round(log_frac, 6),
                      "mon_off_ns_per_inc": ob["mon_off_ns_per_inc"],
                      "mon_on_ns_per_inc": ob["mon_on_ns_per_inc"],
                      "mon_snapshot_ms": ob["mon_snapshot_ms"],
                      "snap_frac_of_dp8_step": round(snap_frac, 6)}))
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        raise SystemExit(_stage_main(sys.argv[2]))
    if "--soak" in sys.argv[1:]:
        # the composed soak arm (benchmarks/soak.py): hier x adaptive x
        # overlap x sharded-elastic-ckpt under chaos at world 4, gated
        # by dpxmon's health verdict (docs/observability.md)
        from benchmarks.soak import run_soak
        raise SystemExit(run_soak(smoke="--smoke" in sys.argv[1:]))
    if "--smoke" in sys.argv[1:]:
        raise SystemExit(smoke())
    if "--headline" in sys.argv[1:]:
        raise SystemExit(headline())
    main()
