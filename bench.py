"""Benchmark: min_ddp steps/sec/chip on DummyModel (BASELINE.json metric).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "steps/s", "vs_baseline": N}

The reference publishes no numbers (BASELINE.md), so the baseline is
*measured* here: the same workload (MLP 1->hidden->classes, batch 8,
CrossEntropy, AdamW lr 1e-4) in eager torch on this host's CPU — the
reference's actual single-process execution model (its world<=1 branch,
reference distributed.py:54-58, runs plain eager torch with no process
group). value = this framework's steps/sec on the accelerator using its
fast path (scan-fused steps: N train steps compiled into one XLA program,
parallel/data_parallel.py make_scan_train_steps; numerics proven equal to
per-step execution in tests/test_models.py).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.data import DummyDataset
from distributed_pytorch_tpu.ops.losses import cross_entropy
from distributed_pytorch_tpu.parallel import (make_scan_train_steps,
                                              make_train_step)

BATCH = 8
HIDDEN = 32
N_CLASSES = 4
DATA_SIZE = 32


def _batches(n_steps: int, seed: int = 0):
    """Cycle the seeded DummyDataset in loader order, batch 8 (the
    reference's implicit benchmark config, BASELINE.md)."""
    ds = DummyDataset(DATA_SIZE, N_CLASSES, seed=seed)
    xs, ys = [], []
    for t in range(n_steps):
        idx = np.arange(t * BATCH, (t + 1) * BATCH) % DATA_SIZE
        xs.append(ds.data[idx])
        ys.append(ds.labels[idx])
    return np.stack(xs), np.stack(ys)


def bench_ours(n_steps: int = 2000, fused_chunk: int = 100):
    model = models.DummyModel(in_dim=1, hidden_dim=HIDDEN, n_classes=N_CLASSES)
    params = model.init(jax.random.PRNGKey(0))
    opt = optim.adamw(1e-4)
    opt_state = opt.init(params)

    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy(model.apply(p, x), y), {}

    xs, ys = _batches(fused_chunk)
    xs, ys = jnp.asarray(xs), jnp.asarray(ys)

    # --- fused path: fused_chunk steps per XLA call
    run = make_scan_train_steps(loss_fn, opt, n_steps=fused_chunk)
    params2, opt2, losses = run(params, opt_state, (xs, ys))  # compile
    jax.block_until_ready(losses)
    n_calls = max(n_steps // fused_chunk, 1)
    t0 = time.perf_counter()
    p, o = params2, opt2
    for _ in range(n_calls):
        p, o, losses = run(p, o, (xs, ys))
    jax.block_until_ready(losses)
    fused_sps = n_calls * fused_chunk / (time.perf_counter() - t0)

    # --- per-step path (one jitted call per step, like the eager loop);
    # fresh params: the fused path donated (and thus deleted) the originals
    params = model.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    step = make_train_step(loss_fn, opt, donate=False)
    b0 = (xs[0], ys[0])
    out = step(params, opt_state, b0)  # compile
    jax.block_until_ready(out.loss)
    m = min(n_steps, 500)
    t0 = time.perf_counter()
    for _ in range(m):
        out = step(out.params, out.opt_state, b0)
    jax.block_until_ready(out.loss)
    per_step_sps = m / (time.perf_counter() - t0)

    return fused_sps, per_step_sps


def bench_torch_cpu(n_steps: int = 500):
    """The measured baseline: the reference's workload in eager torch CPU."""
    import torch
    import torch.nn as nn

    torch.manual_seed(0)
    model = nn.Sequential(nn.Linear(1, HIDDEN), nn.Linear(HIDDEN, N_CLASSES))
    opt = torch.optim.AdamW(model.parameters(), 1e-4)
    crit = nn.CrossEntropyLoss()
    ds = DummyDataset(DATA_SIZE, N_CLASSES)
    x = torch.tensor(ds.data[:BATCH])
    y = torch.tensor(ds.labels[:BATCH]).long()
    # warmup
    for _ in range(20):
        opt.zero_grad(); crit(model(x), y).backward(); opt.step()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        opt.zero_grad()
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
    return n_steps / (time.perf_counter() - t0)


def main():
    fused, per_step, baseline = None, None, None
    fused, per_step = bench_ours()
    try:
        baseline = bench_torch_cpu()
    except Exception:
        baseline = None

    value = fused
    rec = {
        "metric": "min_ddp_dummymodel_steps_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "steps/s",
        "vs_baseline": round(value / baseline, 2) if baseline else None,
        "per_step_path_steps_per_sec": round(per_step, 1),
        "torch_cpu_baseline_steps_per_sec": round(baseline, 1) if baseline else None,
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
