"""Headline benchmark. Prints ONE JSON line:

  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Three measurements, most important first:

1. **Flagship MFU** (the headline ``value``): TransformerLM, ~135M params,
   bf16, flash attention, seq 1024, trained single-chip. ``value`` is the
   MFU fraction = achieved model FLOP/s / chip peak bf16 FLOP/s
   (benchmarks/mfu_transformer.py). The reference cannot run this model at
   all; ``vs_baseline`` is our tokens/s over eager-torch-CPU tokens/s on
   the same model — the only measurable torch baseline in this
   environment (torch has no TPU backend here).
2. **min_ddp metric** (``min_ddp`` field): the reference's implicit
   benchmark (MLP 1->32->4, batch 8, reference min_DDP.py:44-48).
   ``steps_per_sec`` is the PER-STEP path — one jitted call per step,
   matching the reference workload's per-step loss materialization
   semantics. The scan-fused path (N steps per XLA call; legitimate
   TPU fast path but different semantics) is reported separately as
   ``fused_steps_per_sec``, never as the headline.
3. **world-8 DP step** (``dp8`` field): the same min_ddp train step on an
   8-device virtual CPU mesh (subprocess), so collective overhead is
   measured at all. steps/s on 8 CPU devices, global batch 64.

Robustness: the TPU backend behind the axon tunnel comes and goes
(BENCH_r01.json died on it). Backend init runs in a subprocess with
bounded retries + backoff; on final failure the script still prints a
parseable JSON record with an ``error`` field and whatever measurements
did succeed (rc stays 0 so the record is recorded).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

BATCH = 8
HIDDEN = 32
N_CLASSES = 4
DATA_SIZE = 32

# CPU-fallback baselines are measured on a contended host; above this
# run-to-run spread the median is too soft to divide by, and the record
# keeps the raw runs but withholds the vs_* ratio (noise is not signal)
MAX_BASELINE_SPREAD = 0.10


# ---------------------------------------------------------------------------
# backend probing with retries
# ---------------------------------------------------------------------------


def probe_backend(timeout_s: int = 45) -> dict:
    """Probe JAX backend init in a SUBPROCESS (a wedged tunnel hangs the
    whole process — a timeout around an in-process jax.devices() call
    cannot recover it). Only a real TPU counts as healthy: a CPU
    fallback would silently run the flagship bench on the host (with
    interpret-mode pallas — hours, and no meaningful MFU).

    The 45s default is deliberate at every call site: a healthy probe
    answers in ~6s, and a probe hung against a wedged tunnel gets
    SIGKILLed at the timeout — a kill landing just after a heal can
    re-wedge the tunnel (killed clients wedge it), so the hung-probe
    window is kept as narrow as detection reliability allows."""
    code = ("import jax, json; d = jax.devices()[0]; "
            "print(json.dumps({'platform': d.platform, "
            "'kind': d.device_kind}))")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        if out.returncode == 0 and out.stdout.strip():
            info = json.loads(out.stdout.strip().splitlines()[-1])
            if info.get("platform") == "tpu":
                return info
    except (subprocess.TimeoutExpired, json.JSONDecodeError):
        pass
    return {}


def wait_for_backend(max_tries: int = 4, base_sleep_s: float = 30.0) -> dict:
    """Bounded retries with backoff; returns probe info ({} = no TPU)."""
    for i in range(max_tries):
        info = probe_backend()
        if info:
            return info
        if i < max_tries - 1:
            sleep = base_sleep_s * (2 ** i)
            print(f"# backend probe {i + 1}/{max_tries} failed; "
                  f"retrying in {sleep:.0f}s", file=sys.stderr)
            time.sleep(sleep)
    return {}


def progress(msg: str) -> None:
    """One flushed "#"-prefixed stdout line — the progress contract every
    on-chip stage leans on: "#" preserves the parse-last-line-as-JSON
    collector contract, and the flush makes the line survive a collector
    SIGKILL (block-buffered pipes lose unflushed output), so a wedged
    stage's kept stdout tail shows exactly how far it got."""
    print(f"# {msg}", flush=True)


def arm(label: str, thunk):
    """Banner-then-run: announce ``label`` via :func:`progress`, then
    execute the zero-arg ``thunk`` and return its result. The one shared
    shape for multi-arm benchmark stages — the banner prints BEFORE any
    of the arm's work (setup included), so a tunnel wedge anywhere in
    the arm is attributed to the right label in the kept stdout tail."""
    progress(label)
    return thunk()


def run_json_subprocess(argv, timeout_s: int, *, label: str,
                        env: dict = None,
                        keep_stdout_tail: bool = False) -> dict:
    """Run a subprocess with a hard timeout and parse its LAST stdout
    line as JSON. Single implementation of the
    parseable-record-no-matter-what contract — used by this script's
    stage runner and dp8 bench, and by benchmarks/run_all_tpu.py. On any
    failure (nonzero exit, timeout, unparseable output) returns an
    ``error`` record carrying whatever the child did produce — a stage
    that prints its record and then exits nonzero (e.g. a failed
    numerics validation) keeps its measurements, marked with ``error``
    and ``rc``. ``keep_stdout_tail`` preserves the human-readable tail
    (tables) alongside the parsed record."""
    base_env = {**os.environ,
                "PYTHONPATH": REPO + os.pathsep
                + os.environ.get("PYTHONPATH", "")}
    if env:
        base_env.update(env)
    if base_env.get("JAX_PLATFORMS") == "cpu":
        # this environment's sitecustomize dials the TPU relay at EVERY
        # python startup when PALLAS_AXON_POOL_IPS is set; a wedged
        # tunnel then hangs even pure-CPU children before user code
        # runs. CPU stages have no business talking to the relay.
        base_env.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        out = subprocess.run(argv, capture_output=True, text=True,
                             timeout=timeout_s, env=base_env)
    except subprocess.TimeoutExpired as e:
        # TimeoutExpired carries the partial output (text decoded when
        # the child wrote any) — keep it: on a flaky backend the progress
        # lines before the wedge are exactly the diagnostics needed
        rec = {"error": f"{label} timed out after {timeout_s}s"}
        # stdout gets a wider tail than stderr: sweep stages emit one
        # "# ..." progress line per completed arm to stdout precisely so
        # a timeout keeps the partial per-arm record
        for name, cap in (("stdout", 2500), ("stderr", 800)):
            v = getattr(e, name, None)
            if v:
                if isinstance(v, bytes):
                    v = v.decode(errors="replace")
                rec[f"{name}_tail"] = v.strip()[-cap:]
        return rec

    payload = None
    if out.stdout.strip():
        try:
            payload = json.loads(out.stdout.strip().splitlines()[-1])
        except json.JSONDecodeError:
            payload = None
    if isinstance(payload, dict):
        if out.returncode != 0:
            payload.setdefault(
                "error", f"{label} exited rc={out.returncode}")
            payload["rc"] = out.returncode
    elif out.returncode == 0 and payload is not None:
        payload = {"value": payload}
    else:
        payload = {"error": (out.stderr or "no parseable output")
                   .strip()[-500:] or f"{label} produced no output"}
    if keep_stdout_tail:
        payload["stdout_tail"] = out.stdout.strip()[-1500:]
    return payload


RESULTS_LOG = os.path.join(REPO, "benchmarks", "tpu_results.jsonl")


def append_result(stage: str, result: dict, *, ok: bool = None,
                  wall_s: float = None) -> None:
    """Append one raw benchmark record to the on-chip results log, in the
    same {stage, ok, wall_s, result, ts} shape run_all_tpu.run_stage
    writes. Every honest run must leave a raw-JSON trace (round-3
    lesson: the log held only retracted rows while the real numbers
    lived in prose)."""
    rec = {"stage": stage,
           "ok": bool(result.get("error") is None) if ok is None else ok,
           "wall_s": round(wall_s, 1) if wall_s is not None else None,
           "result": result,
           "ts": time.strftime("%Y-%m-%dT%H:%M:%S")}
    try:
        with open(RESULTS_LOG, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError as e:
        print(f"# could not append to {RESULTS_LOG}: {e}", file=sys.stderr)


def last_good_record() -> dict:
    """Most recent non-retracted on-chip FLAGSHIP-config MFU record from
    the results log, so a wedged tunnel never again nulls a round's
    headline: the emitted record points at a raw row a reader can
    verify. Only the pinned flagship config qualifies — a bench_mfu row
    (this script's mfu stage) or a composite bench_headline row whose
    metric is the headline metric; the medium-model arm must never leak
    into the headline's fallback."""
    best = {}
    try:
        with open(RESULTS_LOG) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if row.get("retracted") or not row.get("ok"):
                    continue
                res = row.get("result", {})
                if row.get("stage") == "bench_mfu":
                    mfu = res.get("mfu")
                elif res.get("metric") == "transformer_lm_mfu_single_chip":
                    mfu = res.get("value")
                else:
                    continue
                if mfu is not None:
                    best = {"mfu": mfu, "ts": row.get("ts"),
                            "stage": row.get("stage"),
                            "device": res.get("device"),
                            "tokens_per_sec": res.get("tokens_per_sec"),
                            "source": "benchmarks/tpu_results.jsonl"}
    except OSError:
        pass
    return best


def _run_stage(stage: str, timeout_s: int) -> dict:
    """Re-invoke this script for one measurement stage in a subprocess
    with a hard timeout — the tunnel can wedge mid-run, and the
    parseable-JSON-on-failure contract must survive that."""
    return run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage", stage],
        timeout_s, label=f"stage {stage}")


# ---------------------------------------------------------------------------
# measurement 2: the reference's implicit benchmark (min_ddp MLP)
# ---------------------------------------------------------------------------


def _batches(n_steps: int, seed: int = 0):
    import numpy as np
    from distributed_pytorch_tpu.data import DummyDataset
    ds = DummyDataset(DATA_SIZE, N_CLASSES, seed=seed)
    xs, ys = [], []
    for t in range(n_steps):
        idx = np.arange(t * BATCH, (t + 1) * BATCH) % DATA_SIZE
        xs.append(ds.data[idx])
        ys.append(ds.labels[idx])
    return np.stack(xs), np.stack(ys)


def bench_min_ddp(n_steps: int = 2000, fused_chunk: int = 100) -> dict:
    import jax
    import jax.numpy as jnp
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import (make_scan_train_steps,
                                                  make_train_step)

    model = models.DummyModel(in_dim=1, hidden_dim=HIDDEN,
                              n_classes=N_CLASSES)
    params = model.init(jax.random.PRNGKey(0))
    opt = optim.adamw(1e-4)
    opt_state = opt.init(params)

    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy(model.apply(p, x), y), {}

    xs, ys = _batches(fused_chunk)
    xs, ys = jnp.asarray(xs), jnp.asarray(ys)

    # All fences below are HOST MATERIALIZATIONS (np.asarray of a scalar):
    # on the tunneled backend jax.block_until_ready can resolve on enqueue
    # (see benchmarks/fence_probe.py), which made every r02 number a
    # dispatch-rate measurement. A fetch cannot complete before the value
    # exists, and chaining steps through params makes the final fetch wait
    # for the whole run.
    from distributed_pytorch_tpu.utils.profiler import (fetch_fence,
                                                        time_steps_amortized)

    # per-step path FIRST (the honest number for the reference's per-step
    # semantics): one jitted call per step, chained; one fetch at the end.
    step = make_train_step(loss_fn, opt, donate=False)
    b0 = (xs[0], ys[0])
    out = step(params, opt_state, b0)
    fetch_fence(out.loss)
    m = min(n_steps, 500)
    s_per_step, out = time_steps_amortized(
        lambda o: step(o.params, o.opt_state, b0), out, m,
        lambda o: o.loss)
    per_step_sps = 1.0 / s_per_step

    # per-step latency with the loss materialized on the host EVERY step
    # (the reference's literal eager semantics, min_DDP.py:110-130) — on a
    # tunneled backend this is round-trip-bound and says more about the
    # tunnel than the chip; reported separately for honesty.
    t0 = time.perf_counter()
    for _ in range(20):
        out = step(out.params, out.opt_state, b0)
        fetch_fence(out.loss)
    eager_sps = 20 / (time.perf_counter() - t0)

    # scan-fused fast path (different semantics: no per-step host visibility)
    run = make_scan_train_steps(loss_fn, opt, n_steps=fused_chunk)
    p2, o2, losses = run(params, opt_state, (xs, ys))
    fetch_fence(losses)
    n_calls = max(n_steps // fused_chunk, 1)
    t0 = time.perf_counter()
    p, o = p2, o2
    for _ in range(n_calls):
        p, o, losses = run(p, o, (xs, ys))
    fetch_fence(losses)
    fused_sps = n_calls * fused_chunk / (time.perf_counter() - t0)

    return {"steps_per_sec": round(per_step_sps, 1),
            "per_step_host_loss_steps_per_sec": round(eager_sps, 1),
            "fused_steps_per_sec": round(fused_sps, 1),
            "timing_method": "chained dispatch, host-fetch fence"}


def _median_spread(runs, key: str) -> dict:
    """Median + relative spread over repeated measurements: the record
    shape every CPU-fallback baseline reports (consumers gate vs_*
    ratios on spread_frac <= MAX_BASELINE_SPREAD)."""
    runs = sorted(runs)
    med = runs[len(runs) // 2]
    spread = (runs[-1] - runs[0]) / med if med else 0.0
    return {key: round(med, 1),
            f"runs_{key}": [round(r, 1) for r in runs],
            "spread_frac": round(spread, 3)}


def _pin_torch_threads(torch) -> None:
    """Pin torch to a fixed thread count: the round-3 LM baseline spread
    43.5-63.6 tok/s (+/-46%) across runs from host contention, which made
    vs_baseline soft. A fixed count keeps the denominator comparable
    across rounds even when the host is busy."""
    n = int(os.environ.get("DPX_TORCH_THREADS", "8"))
    try:
        torch.set_num_threads(n)
    except RuntimeError:
        pass  # already started threading: keep whatever it has


def bench_torch_cpu_mlp(n_steps: int = 500, reps: int = 5) -> dict:
    """Measured baseline: the reference's workload in eager torch on this
    host's CPU (the reference's world<=1 branch runs exactly this,
    reference distributed.py:54-58). Thread-pinned, median-of-``reps``
    with the spread reported — the consumer refuses to compute a ratio
    from a noisy denominator (spread > 10%)."""
    import torch
    import torch.nn as nn
    from distributed_pytorch_tpu.data import DummyDataset

    _pin_torch_threads(torch)
    torch.manual_seed(0)
    model = nn.Sequential(nn.Linear(1, HIDDEN), nn.Linear(HIDDEN, N_CLASSES))
    opt = torch.optim.AdamW(model.parameters(), 1e-4)
    crit = nn.CrossEntropyLoss()
    ds = DummyDataset(DATA_SIZE, N_CLASSES)
    x = torch.tensor(ds.data[:BATCH])
    y = torch.tensor(ds.labels[:BATCH]).long()
    for _ in range(20):
        opt.zero_grad(); crit(model(x), y).backward(); opt.step()

    def one_run():
        t0 = time.perf_counter()
        for _ in range(n_steps):
            opt.zero_grad()
            loss = crit(model(x), y)
            loss.backward()
            opt.step()
        return n_steps / (time.perf_counter() - t0)

    # median-of-reps: host CPU contention produced +/-46% spread round 3
    return _median_spread([one_run() for _ in range(reps)],
                          "steps_per_sec")


def bench_torch_cpu_lm(batch=2, n_steps=2, reps=5) -> dict:
    """tokens/s for the flagship LM config in eager torch CPU — the
    vs_baseline denominator for the MFU headline. The model config comes
    from benchmarks.mfu_transformer.FLAGSHIP (single source of truth);
    only batch is reduced — CPU throughput is ~flat in batch and a full
    flagship batch takes minutes per step here. Thread-pinned,
    median-of-``reps`` with the spread reported (round-3 runs varied
    +/-46% under host contention)."""
    import torch
    import torch.nn as nn

    from benchmarks.mfu_transformer import FLAGSHIP
    _pin_torch_threads(torch)
    dim, n_layers, n_heads = (FLAGSHIP["dim"], FLAGSHIP["n_layers"],
                              FLAGSHIP["n_heads"])
    vocab, seq = FLAGSHIP["vocab"], FLAGSHIP["seq"]
    torch.manual_seed(0)
    layer = nn.TransformerEncoderLayer(
        dim, n_heads, 4 * dim, batch_first=True, norm_first=True,
        activation="gelu")
    enc = nn.TransformerEncoder(layer, n_layers)
    emb = nn.Embedding(vocab, dim)
    head = nn.Linear(dim, vocab, bias=False)
    params = (list(enc.parameters()) + list(emb.parameters())
              + list(head.parameters()))
    opt = torch.optim.AdamW(params, 3e-4)
    crit = nn.CrossEntropyLoss()
    mask = nn.Transformer.generate_square_subsequent_mask(seq)
    tokens = torch.randint(0, vocab, (batch, seq + 1))

    def one_step():
        opt.zero_grad()
        h = emb(tokens[:, :-1])
        h = enc(h, mask=mask, is_causal=True)
        loss = crit(head(h).reshape(-1, vocab),
                    tokens[:, 1:].reshape(-1))
        loss.backward()
        opt.step()

    one_step()  # warmup
    runs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            one_step()
        dt = time.perf_counter() - t0
        runs.append(n_steps * batch * seq / dt)
    rec = _median_spread(runs, "tokens_per_sec")
    rec["torch_threads"] = torch.get_num_threads()
    return rec


# ---------------------------------------------------------------------------
# measurement 3: world-8 DP step on the virtual CPU mesh (subprocess —
# platform selection must happen before backend init)
# ---------------------------------------------------------------------------

_DP8_CODE = r"""
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
from distributed_pytorch_tpu.runtime.jax_compat import ensure_cpu_devices
ensure_cpu_devices(8)
import jax.numpy as jnp
import numpy as np
import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.ops.losses import cross_entropy
from distributed_pytorch_tpu.parallel import make_train_step

dist.init_process_group(rank=0, world_size=8)
model = models.DummyModel(in_dim=1, hidden_dim=32, n_classes=4)
params = model.init(jax.random.PRNGKey(0))
opt = optim.adamw(1e-4)
opt_state = opt.init(params)

def loss_fn(p, batch):
    x, y = batch
    return cross_entropy(model.apply(p, x), y), {}

step = make_train_step(loss_fn, opt, donate=False)
x = dist.shard_batch(np.arange(64, dtype=np.float32)[:, None])
y = dist.shard_batch(np.zeros(64, dtype=np.int32))
out = step(params, opt_state, (x, y))
jax.block_until_ready(out.loss)
# fence every step: on a small host the 8-way rendezvous aborts if many
# async steps pile up (and the reference's workload materializes loss
# per step anyway, so the fenced number is the semantically right one).
# median-of-5 reps with spread: identical code swung 37.8-87.9 steps/s
# across rounds 3-4 under host contention — a single rep is noise.
# One UNTIMED warm rep first: the first timed rep otherwise runs ~10x
# slow (cache/dispatch warmup) and poisons the spread with a warmup
# artifact instead of genuine contention signal.
n = 50
for _ in range(n):
    out = step(out.params, out.opt_state, (x, y))
    jax.block_until_ready(out.loss)
runs = []
for _ in range(5):
    t0 = time.perf_counter()
    for _ in range(n):
        out = step(out.params, out.opt_state, (x, y))
        jax.block_until_ready(out.loss)
    runs.append(n / (time.perf_counter() - t0))
runs.sort()
med = runs[len(runs) // 2]
spread = (runs[-1] - runs[0]) / med if med else 0.0
print(json.dumps({"steps_per_sec": round(med, 1),
                  "runs_steps_per_sec": [round(r, 1) for r in runs],
                  "spread_frac": round(spread, 3),
                  "world": 8, "global_batch": 64}))
"""


# 32 MiB f32 gradient bucket: big enough that the ring is bandwidth-
# bound even on loopback (real DDP buckets are tens of MB — ResNet-50's
# full gradient is ~98 MB), which is the regime the quantized wire is
# for; at a few MiB the 8-process mesh is scheduling-latency-bound and
# wire width barely matters. Median-of-5 runs: the mesh shares a small
# contended host, single runs swing 2x.
COMM_BUCKET_ELEMS = 1 << 23
COMM_WORLD = 8
COMM_REPS = 6


def _dp8_comm_worker(rank, world, q, n_elems, reps, runs):
    """Host-ring comm microbench worker: the same flat gradient bucket
    allreduced over the native TCP ring, f32 wire vs quantized (block
    int8) wire. Barrier-fenced so every timed window measures all
    ranks' slowest path; rank 0 reports."""
    import numpy as np

    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu.runtime import context

    dist.init_process_group(rank, world)
    comm = context.get_host_comm()
    try:
        rng = np.random.default_rng(rank)
        x = rng.standard_normal(n_elems).astype(np.float32)

        def timed(op):
            samples = []
            for _ in range(runs):
                comm.barrier()
                t0 = time.perf_counter()
                for _ in range(reps):
                    op(x.copy())
                comm.barrier()
                samples.append(reps / (time.perf_counter() - t0))
            samples.sort()
            return samples[len(samples) // 2], samples

        # one untimed warm rep each (socket buffers, allocator)
        comm.allreduce(x.copy())
        comm.allreduce_q8(x.copy())
        f32_sps, f32_runs = timed(comm.allreduce)
        q_sps, q_runs = timed(comm.allreduce_q8)
        if rank == 0:
            from distributed_pytorch_tpu.comm import wire
            q.put({
                "comm_world": world,
                "comm_bucket_mb": round(n_elems * 4 / (1 << 20), 2),
                # per-rank wire payload of ONE allreduce of the bucket
                "comm_bytes": wire.quant_ring_allreduce_wire_bytes(
                    n_elems, world) // world,
                "comm_f32_bytes": wire.ring_allreduce_wire_bytes(
                    n_elems, world) // world,
                "comm_quant_steps_per_sec": round(q_sps, 2),
                "comm_f32_steps_per_sec": round(f32_sps, 2),
                "comm_runs": {"f32": [round(r, 2) for r in f32_runs],
                              "quant": [round(r, 2) for r in q_runs]},
            })
    finally:
        dist.cleanup()


def bench_dp8_comm() -> dict:
    """8-process native-ring gradient-bucket allreduce: f32 vs quantized
    wire, reported into the dp8 record (comm_bytes /
    comm_quant_steps_per_sec acceptance fields)."""
    import multiprocessing as mp

    from distributed_pytorch_tpu.runtime.multiprocess import (
        launch_multiprocess)

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    launch_multiprocess(_dp8_comm_worker, COMM_WORLD, q,
                        COMM_BUCKET_ELEMS, COMM_REPS, 5)
    return q.get(timeout=60)


def bench_dp8() -> dict:
    rec = run_json_subprocess(
        [sys.executable, "-c", _DP8_CODE], 600, label="dp8 bench",
        env={"JAX_PLATFORMS": "cpu", "DPX_CPU_DEVICES": "8"})
    comm = run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), "--stage", "dp8_comm"],
        600, label="dp8 comm bench", env={"JAX_PLATFORMS": "cpu"})
    if "error" in comm:
        rec["comm_error"] = comm["error"]
    rec.update({k: v for k, v in comm.items() if k.startswith("comm_")})
    return rec


# ---------------------------------------------------------------------------


def _stage_main(stage: str) -> int:
    """Run ONE measurement in this process and print its JSON line
    (invoked by the orchestrator via _run_stage)."""
    if stage == "mfu":
        from benchmarks.mfu_transformer import run as mfu_run
        print(json.dumps(mfu_run()))
    elif stage == "mfu_medium":
        from benchmarks.mfu_transformer import MEDIUM
        from benchmarks.mfu_transformer import run as mfu_run
        print(json.dumps(mfu_run(steps=20, **MEDIUM)))
    elif stage == "min_ddp":
        print(json.dumps(bench_min_ddp()))
    elif stage == "dp8_comm":
        print(json.dumps(bench_dp8_comm()))
    elif stage == "decode":
        from benchmarks.decode_tpu import run_gqa_compare
        print(json.dumps(run_gqa_compare()))
    else:
        print(json.dumps({"error": f"unknown stage {stage!r}"}))
        return 2
    return 0


def attach_roofline(rec: dict) -> None:
    """The analytic roofline travels WITH the headline: floors, the
    overlap/no-overlap MFU ceilings, and (when the flagship measured)
    the efficiency gap — so the record answers "is this number
    physics-bound or attackable?" on its own (benchmarks/roofline.py).
    Best-effort: never blocks the record."""
    try:
        from benchmarks.mfu_transformer import FLAGSHIP
        from benchmarks.roofline import analyze, attach_measured
        rl = attach_measured(
            analyze(FLAGSHIP),
            rec.get("mfu_detail", {}).get("step_ms_median"))
        rec["roofline_flagship"] = {
            k: rl[k] for k in
            ("compute_floor_ms", "hbm_floor_ms", "bound", "mfu_ceiling",
             "mfu_ceiling_no_overlap", "measured_step_ms",
             "efficiency_gap_x") if k in rl}
    except Exception as e:  # noqa: BLE001
        rec.setdefault("warnings", []).append(
            f"roofline attach failed: {type(e).__name__}: {e}")


def main():
    rec = {
        "metric": "transformer_lm_mfu_single_chip",
        "value": None,
        "unit": "mfu_fraction",
        "vs_baseline": None,
    }

    info = wait_for_backend()
    rec["device"] = info.get("kind") or "none"

    if info:
        mfu_rec = _run_stage("mfu", timeout_s=1800)
        append_result("bench_mfu", mfu_rec)
        if "mfu" in mfu_rec:
            rec["value"] = mfu_rec["mfu"]
            rec["tokens_per_sec"] = mfu_rec["tokens_per_sec"]
            rec["mfu_detail"] = mfu_rec
        else:
            rec["error"] = f"mfu stage: {mfu_rec.get('error', 'no result')}"
        # bigger matmuls, higher attainable MFU — a reporting arm, never
        # the headline (the flagship config is pinned for comparability)
        rec["mfu_medium"] = _run_stage("mfu_medium", timeout_s=1800)
        append_result("bench_mfu_medium", rec["mfu_medium"])
        rec["min_ddp"] = _run_stage("min_ddp", timeout_s=900)
        append_result("bench_min_ddp", rec["min_ddp"])
        # two full decode benchmarks (MHA + GQA arms) live in this stage
        rec["decode"] = _run_stage("decode", timeout_s=2400)
        append_result("bench_decode", rec["decode"])
    else:
        rec["error"] = "no healthy TPU backend after retries"

    if rec["value"] is None:
        # traceable fallback — covers BOTH failure modes: backend never
        # appeared, or it appeared and the mfu stage wedged mid-run (the
        # round-3 killer). The headline stays null (nothing was measured
        # NOW), but the record carries the last verified on-chip number
        # + where its raw row lives.
        lg = last_good_record()
        if lg:
            rec["last_good"] = lg

    try:
        lm_base = bench_torch_cpu_lm()
        tps = lm_base["tokens_per_sec"]
        rec["torch_cpu_lm_tokens_per_sec"] = tps
        rec["torch_cpu_lm_baseline_detail"] = lm_base
        if lm_base.get("spread_frac", 1.0) > MAX_BASELINE_SPREAD:
            # a noisy denominator makes the ratio noise presented as
            # signal — keep the raw detail, refuse the headline ratio
            rec.setdefault("warnings", []).append(
                f"torch lm baseline spread "
                f"{lm_base['spread_frac']:.0%} > "
                f"{MAX_BASELINE_SPREAD:.0%}; vs_baseline withheld")
        elif rec.get("tokens_per_sec"):
            rec["vs_baseline"] = round(rec["tokens_per_sec"] / tps, 2)
    except Exception as e:  # noqa: BLE001
        rec["torch_cpu_lm_tokens_per_sec"] = None
        rec.setdefault("warnings", []).append(
            f"torch lm baseline failed: {type(e).__name__}: {e}")

    # only worth minutes of eager-torch stepping if there is a min_ddp
    # record to attach the ratio to (absent whenever the TPU was down)
    if "steps_per_sec" in rec.get("min_ddp", {}):
        try:
            mlp_base = bench_torch_cpu_mlp()
            rec["min_ddp"]["torch_cpu_baseline"] = mlp_base
            if mlp_base.get("spread_frac", 1.0) <= MAX_BASELINE_SPREAD:
                rec["min_ddp"]["vs_torch_cpu"] = round(
                    rec["min_ddp"]["steps_per_sec"]
                    / mlp_base["steps_per_sec"], 2)
            else:
                rec["min_ddp"]["vs_torch_cpu"] = None
        except Exception:  # noqa: BLE001
            pass

    rec["dp8"] = bench_dp8()
    attach_roofline(rec)

    # the composite headline record is itself a raw-JSON trace — except
    # under run_all_tpu, whose bench_headline stage wrapper already logs
    # this whole record (avoid double rows for one run)
    if os.environ.get("DPX_BENCH_SELFLOG", "1") != "0":
        append_result("bench_record", rec,
                      ok=rec.get("value") is not None)

    print(json.dumps(rec))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        raise SystemExit(_stage_main(sys.argv[2]))
    main()
