"""The dpxchaos campaign driver — the full fault matrix through the
full composed stack (ROADMAP item 3; docs/failures.md "Chaos
campaigns").

Where the soak arm (benchmarks/soak.py) injects exactly ONE kill, this
driver runs a DECLARED campaign (``runtime/chaos.py``; ``DPX_CHAOS``
overrides the built-in matrix) clause by clause:

* **train legs** — the composed world-``DPX_CHAOS_WORLD`` train stack
  (hier two-level ring x adaptive wire x bucketed overlap x sharded
  elastic checkpointing) under ``elastic_run``, one campaign clause
  armed per leg (kill/drop/delay on ``hier_reduce`` / ``ckpt_commit`` /
  a step boundary). The ``train_shrink`` leg is the elastic
  shrink-resume proof: the injected kill takes the world down,
  ``reconfigure`` relaunches at HALF the world, and the relaunched rank
  0 verifies the resharded restore BIT-EXACT against the sha256 digest
  the world-4 run recorded at save time.
* **serve legs** — the disagg+paged(+q8 handoff) serve split in-process:
  a severed handoff (typed ``PrefillEngineDied``, victim-only), a
  stalled one (typed ``HandoffTimeout``), a stalled engine iteration
  (typed ``RequestDeadlineExceeded``), and a ``flaky`` handoff absorbed
  by the bounded retry.
* **transport legs** — the retry micro-harness on a bare handoff
  transport: ``flaky`` under the default budget recovers with
  ``comm_retry`` events; under a tightened ``DPX_RETRY_MAX`` it
  exhausts into the typed ``CommRetryExhausted`` carrying the attempt
  count.
* **fleet legs** — the multi-replica serve fleet (``serve/fleet/``)
  in-process: ``drop_conn@op=fleet_submit`` kills the targeted
  request's home replica mid-stream. Green means contained — ONLY the
  victim replica's in-flight stream fails (typed ``ReplicaFailed``,
  replica + request attributed), the co-resident request re-routes to
  the survivor bit-exact, placement re-homes the dead shard, and the
  same-id revive clears the replica's health-failure stream.

The whole run is followed LIVE by the PR 15 HealthMonitor and gated on
dpxmon's verdict; every clause lands a ``chaos_clause`` event and a
report row (fired / typed error observed / attribution correct /
recovered), rolled up by ``tools/dpxchaos.py report`` — whose rc-1 path
is itself proven by a seeded unrecovered clause, exactly like the
seeded SLO-violation log proves dpxmon's.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.soak import _run_cli, _seed_violation_log  # noqa: E402

# train-leg shape: seconds-scale at world 4 (2 "hosts" x 2 ranks), a
# sharded ckpt every CKPT_EVERY steps, the kill landing mid-run with
# completed checkpoints on both sides of it
TRAIN_STEPS = 20
KILL_STEP = 10
CKPT_EVERY = 4
HIER_LOCAL = 2
MON_EVERY = 2

#: The built-in smoke matrix (CI `chaos-smoke`): one flaky
#: retry-success, one kill -> shrink-resume, one typed serve error.
SMOKE_CAMPAIGN = {
    "name": "chaos-smoke",
    "clauses": [
        {"fault": "flaky@op=handoff_send,count=2", "leg": "transport",
         "expect": "retry_recover",
         "note": "transient handoff refusal absorbed by bounded retry"},
        {"fault": f"kill@step={KILL_STEP},rank=3,attempt=0",
         "leg": "train_shrink", "expect": "elastic_resume",
         "note": "kill -> relaunch at world//2 -> bit-exact resharded "
                 "resume"},
        {"fault": "drop_conn@op=handoff_send,call=2", "leg": "serve",
         "expect": "typed_error",
         "note": "severed handoff -> typed PrefillEngineDied, victim "
                 "only"},
        {"fault": "drop_conn@op=fleet_submit,call=2", "leg": "fleet",
         "expect": "typed_error",
         "note": "replica killed mid-stream -> typed ReplicaFailed, "
                 "victim only; survivor serves bit-exact, shard "
                 "re-homes, same-id revive clears health"},
    ],
}

#: The full matrix (the default without --smoke): the smoke clauses
#: plus kills inside the hier ring and the ckpt commit, the stalled
#: handoff / stalled engine iteration timeouts, a flaky handoff through
#: the REAL engine, and the retry-exhaustion proof.
FULL_CAMPAIGN = {
    "name": "chaos-full",
    "clauses": SMOKE_CAMPAIGN["clauses"] + [
        {"fault": "kill@op=hier_reduce,call=3,rank=1,attempt=0",
         "leg": "train", "expect": "elastic_resume",
         "note": "rank 1 dies entering the intra-host reduce phase"},
        {"fault": "kill@op=ckpt_commit,call=2,rank=0,attempt=0",
         "leg": "train", "expect": "elastic_resume",
         "note": "rank 0 dies entering its 2nd ckpt commit"},
        {"fault": "delay@op=handoff_send,call=2,ms=600", "leg": "serve",
         "expect": "typed_error",
         "note": "stalled handoff past DPX_HANDOFF_TIMEOUT_MS -> typed "
                 "HandoffTimeout"},
        {"fault": "delay@op=serve_step,call=3,ms=1200", "leg": "serve",
         "expect": "typed_error",
         "note": "stalled engine iteration -> typed "
                 "RequestDeadlineExceeded(stage=running)"},
        {"fault": "flaky@op=handoff_send,count=2", "leg": "serve",
         "expect": "retry_recover",
         "note": "flaky handoff through the real disagg engine"},
        {"fault": "flaky@op=handoff_send,count=5", "leg": "transport",
         "expect": "typed_error", "env": {"DPX_RETRY_MAX": "1"},
         "note": "transient outlives the budget -> typed "
                 "CommRetryExhausted carrying the attempt count"},
    ],
}


def _progress(msg: str) -> None:
    print(f"# chaos: {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# train legs (subprocess world under elastic_run)
# ---------------------------------------------------------------------------


def _tree_digest(tree) -> str:
    """Deterministic sha256 over a pytree's leaves (dtype+shape+bytes in
    tree-leaf order) — the bit-exactness witness the shrink-resume leg
    compares across world sizes."""
    import hashlib

    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.asarray(leaf)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def _train_worker(rank: int, world: int, workdir: str,
                  steps: int) -> None:
    """One rank of the composed train stack (module-level:
    spawn-picklable) — the soak worker's composition plus the digest
    protocol: rank 0 records a state digest at every step, and a
    resumed rank 0 verifies the restored tree bit-exact against the
    digest recorded at save time (across world sizes — the resharded
    restore must reproduce the SAME full tree)."""
    import jax
    import numpy as np

    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ckpt import CheckpointManager
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import (fsdp_param_specs,
                                                  make_train_step)
    from distributed_pytorch_tpu.runtime import faults
    from distributed_pytorch_tpu.utils.checkpoint import (
        latest_step, restore_checkpoint)
    from jax.sharding import PartitionSpec as P

    dist.init_process_group(rank, world)
    try:
        model = models.DummyModel(in_dim=16, hidden_dim=128, n_classes=8)
        opt = optim.adamw(1e-3)

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(model.apply(p, x), y), {}

        step_fn = make_train_step(loss_fn, opt, grad_reduce="adaptive",
                                  overlap=True, comm_buckets=2)
        params = model.init(jax.random.PRNGKey(0))
        opt_state = step_fn.init_opt_state(params)

        specs = fsdp_param_specs(params, world, min_size=64)
        shape_spec = {np.shape(l): s for l, s in zip(
            jax.tree_util.tree_leaves(params),
            jax.tree_util.tree_leaves(specs))}
        opt_specs = jax.tree_util.tree_map(
            lambda x: shape_spec.get(np.shape(x), P()), opt_state)
        ckdir = os.path.join(workdir, "ckpt")
        start = 0
        if latest_step(ckdir) is not None:
            ck = restore_checkpoint(ckdir, like_params=params,
                                    like_opt_state=opt_state)
            params, opt_state, start = ck.params, ck.opt_state, ck.step
            if rank == 0:
                digfile = os.path.join(workdir, f"digest_{start}.json")
                if os.path.exists(digfile):
                    with open(digfile, "r", encoding="utf-8") as f:
                        want = json.load(f)
                    got = {"world": world,
                           "sha256": _tree_digest((params, opt_state))}
                    if got["sha256"] != want["sha256"]:
                        raise RuntimeError(
                            f"resharded resume NOT bit-exact at step "
                            f"{start}: restored {got['sha256'][:16]} at "
                            f"world {world} != saved "
                            f"{want['sha256'][:16]} at world "
                            f"{want['world']}")
                    marker = os.path.join(workdir,
                                          f"resume_verified_{start}.json")
                    with open(marker, "w", encoding="utf-8") as f:
                        json.dump(got, f)

        rng = np.random.default_rng(7)
        batches = [(rng.random((8, 16), dtype=np.float32),
                    rng.integers(0, 8, size=(8,)).astype(np.int32))
                   for _ in range(min(steps, 64))]
        with CheckpointManager(ckdir, interval=CKPT_EVERY, keep=2,
                               sharded=True, param_specs=specs,
                               opt_specs=opt_specs,
                               axis_sizes={"dp": world}) as mgr:
            for s in range(start, steps):
                faults.on_step(s, rank=rank)
                out = step_fn(params, opt_state,
                              batches[s % len(batches)])
                params, opt_state = out.params, out.opt_state
                mgr.save(s + 1, params, opt_state)
                if rank == 0:
                    # digest BEFORE any failure can land later in the
                    # step loop: what save() was handed is what a
                    # restore must reproduce
                    dig = {"world": world,
                           "sha256": _tree_digest((params, opt_state))}
                    digfile = os.path.join(workdir,
                                           f"digest_{s + 1}.json")
                    with open(digfile, "w", encoding="utf-8") as f:
                        json.dump(dig, f)
    finally:
        dist.cleanup()


def _train_target(workdir: str, steps: int, world: int) -> None:
    """The elastically supervised unit: one full world launch."""
    from distributed_pytorch_tpu.runtime.multiprocess import (
        launch_multiprocess)
    launch_multiprocess(_train_worker, world, workdir, steps)


def _shrink_reconfigure(attempt, exitcode, args):
    """Topology-shrink hook of the train_shrink leg: after the injected
    kill, relaunch on HALF the world and let the sharded ckpt reshard
    the restore onto it."""
    workdir, steps, world = args
    if world > 2:
        return (workdir, steps, max(2, world // 2))
    return None


def _read_new(log: str, pos: int):
    """Records appended to ``log`` since byte offset ``pos``."""
    try:
        with open(log, "r", encoding="utf-8") as f:
            f.seek(pos)
            text = f.read()
            newpos = f.tell()
    except OSError:
        return [], pos
    recs = []
    for ln in text.splitlines():
        try:
            recs.append(json.loads(ln))
        except (json.JSONDecodeError, ValueError):
            continue
    return recs, newpos


def _saw_fault_injected(recs) -> bool:
    """Did the log window record an injection? ``fault_injected`` rides
    the trace stream: standalone it is a ``trace_span`` record named
    ``fault_injected``; inside a collective it nests in the enclosing
    span's ``events``; a kill's last word is the victim's
    ``flight_recorder`` dump (reason ``fault_kill`` — the ``os._exit``
    preempts the span flush)."""
    for r in recs:
        ev = r.get("event")
        if ev == "trace_span":
            if r.get("name") == "fault_injected":
                return True
            if any(e.get("name") == "fault_injected"
                   for e in r.get("events", []) if isinstance(e, dict)):
                return True
        elif ev == "flight_recorder" and r.get("reason") == "fault_kill":
            return True
    return False


def _count_comm_retries(recs) -> int:
    return sum(1 for r in recs if r.get("event") == "comm_retry")


def _run_train_leg(clause, log: str, pos: int, workdir: str,
                   world: int):
    """One composed train leg with ``clause`` armed; returns the report
    row ingredients."""
    from distributed_pytorch_tpu.runtime import chaos, elastic, faults

    legdir = os.path.join(workdir, f"leg_{clause.id}")
    os.makedirs(legdir, exist_ok=True)
    child_env = {
        "DPX_METRICS_LOG": log,
        "DPX_TRACE": "1",
        "DPX_MON": "1",
        "DPX_MON_EVERY": str(MON_EVERY),
        "DPX_HIER_RING": str(HIER_LOCAL),
        "DPX_COMM_TIMEOUT_MS": "60000",
    }
    child_env.update(clause.arm_env())
    shrink = clause.leg == "train_shrink"
    try:
        res = elastic.elastic_run(
            _train_target, (legdir, TRAIN_STEPS, world),
            max_restarts=2, backoff_s=0.2, env=child_env,
            reconfigure=_shrink_reconfigure if shrink else None)
    except Exception as e:  # giveup: the leg is reported, not fatal
        return chaos.clause_report(
            clause, fired=True, typed_error=type(e).__name__,
            attributed=False, recovered=False,
            detail=f"elastic giveup: {e}")

    recs, _ = _read_new(log, pos)
    kill_exits = [c for c in res.exitcodes
                  if c == faults.KILL_EXIT_CODE]
    fired = _saw_fault_injected(recs) or bool(kill_exits)
    # typed attribution: the supervisor's worker_failure event must
    # blame the rank the clause targeted
    want_rank = clause.specs[0].rank
    failures = [r for r in recs if r.get("event") == "worker_failure"]
    typed = "WorkerFailure" if failures else ""
    attributed = any(r.get("rank") == want_rank for r in failures) \
        if want_rank is not None else bool(failures)
    recovered = res.restarts >= 1 and res.exitcodes[-1] == 0
    detail = (f"restarts={res.restarts} "
              f"exitcodes={list(res.exitcodes)}")
    if shrink and recovered:
        markers = [f for f in os.listdir(legdir)
                   if f.startswith("resume_verified_")]
        reconf = [r for r in recs
                  if r.get("event") == "elastic_reconfigured"]
        recovered = bool(markers) and bool(reconf)
        detail += (f" shrink={world}->{max(2, world // 2)} "
                   f"resume_verified={sorted(markers)}")
    return chaos.clause_report(clause, fired=fired, typed_error=typed,
                               attributed=attributed,
                               recovered=recovered, detail=detail)


# ---------------------------------------------------------------------------
# serve + transport legs (in-process)
# ---------------------------------------------------------------------------


def _serve_model():
    import jax

    from distributed_pytorch_tpu import models
    model = models.TransformerLM(vocab=61, dim=32, n_layers=1,
                                 n_heads=4, n_kv_heads=2, pos="rope",
                                 max_seq=128)
    return model, model.init(jax.random.PRNGKey(0))


def _run_serve_leg(clause, log: str, pos: int):
    """One clause through the disagg+paged(+q8) serve split (or the
    monolithic engine for ``serve_step`` clauses) in-process."""
    import jax
    import numpy as np

    from distributed_pytorch_tpu.runtime import chaos, faults
    from distributed_pytorch_tpu.serve import (DisaggConfig, DisaggEngine,
                                               EngineConfig,
                                               HandoffTimeout,
                                               InferenceEngine,
                                               PrefillEngineDied,
                                               RequestDeadlineExceeded,
                                               SamplingParams)

    spec = clause.specs[0]
    model, params = _serve_model()
    rng = np.random.default_rng(11)
    typed, attributed, recovered = "", False, False
    faults.reset()

    if spec.op == "serve_step":
        # the monolithic engine's iteration hook: a stalled iteration
        # breaches a running request's deadline, typed + attributed
        with InferenceEngine(model, params,
                             EngineConfig(n_slots=2,
                                          max_len=128)) as eng:
            # warm every compile first — compile time must not eat the
            # injected deadline (the tests/test_serve.py discipline)
            eng.submit(np.arange(4, dtype=np.int32),
                       SamplingParams(max_new_tokens=2)).result(
                           timeout=120)
            faults.install(clause.fault)
            ha = eng.submit(rng.integers(0, 61, (5,)).astype(np.int32),
                            SamplingParams(max_new_tokens=100,
                                           deadline_ms=700.0))
            hb = eng.submit(rng.integers(0, 61, (8,)).astype(np.int32),
                            SamplingParams(max_new_tokens=8),
                            rng=jax.random.PRNGKey(9))
            try:
                ha.result(timeout=120)
            except RequestDeadlineExceeded as e:
                typed = "RequestDeadlineExceeded"
                attributed = (e.request_id == ha.request_id
                              and e.stage == "running")
            hb.result(timeout=120)   # co-resident stream unaffected
            recovered = True
    else:
        # the disagg split: paged pools + q8 handoff wire composed
        eng = DisaggEngine(model, params,
                           DisaggConfig(n_slots=2, max_len=64,
                                        page_len=8, handoff_width="q8",
                                        handoff_timeout_ms=80
                                        if spec.action == "delay"
                                        else None))
        a = rng.integers(0, 61, (9,)).astype(np.int32)
        b = rng.integers(0, 61, (12,)).astype(np.int32)
        sp = SamplingParams(max_new_tokens=12)
        ka, kb = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
        with eng:
            if spec.action == "flaky":
                faults.install(clause.fault)
                out = eng.submit(a, sp, rng=ka).result(timeout=120)
                recovered = len(out) > 0
            else:
                # armed BEFORE any traffic: the call counter only runs
                # while specs are installed, so a's handoff is call 1
                # and b's is the targeted call 2
                faults.install(clause.fault)
                ha = eng.submit(a, sp, rng=ka)
                while not ha.tokens:   # a decoding before b's handoff
                    time.sleep(0.005)
                hb = eng.submit(b, sp, rng=kb)
                try:
                    hb.result(timeout=120)
                except PrefillEngineDied as e:
                    typed = "PrefillEngineDied"
                    attributed = (e.request_id == hb.request_id
                                  and e.engine == "prefill")
                except HandoffTimeout as e:
                    typed = "HandoffTimeout"
                    attributed = (e.request_id == hb.request_id
                                  and e.deadline_ms == 80.0)
                # the co-resident stream must finish: containment IS
                # the recovery for a victim-only serve fault
                recovered = len(ha.result(timeout=120)) > 0

    fired = bool(faults.fired())
    recs, _ = _read_new(log, pos)
    retries = _count_comm_retries(recs)
    faults.reset()
    return chaos.clause_report(clause, fired=fired, typed_error=typed,
                               attributed=attributed,
                               recovered=recovered, retries=retries,
                               detail=f"fired={faults.fired()!r}"
                               if not fired else "")


def _run_fleet_leg(clause, log: str, pos: int):
    """One clause through an R=2 in-process serve fleet: the armed
    ``drop_conn@op=fleet_submit`` kills the targeted request's home
    replica mid-stream (``_ReplicaAbort`` -> ``kill_replica``). Green
    means the kill is CONTAINED: only the victim replica's in-flight
    stream fails (typed ``ReplicaFailed``, replica + request
    attributed, engine crash chained), the co-resident shared-prefix
    request re-routes to the survivor and completes BIT-EXACT vs a
    standalone ``generate()`` call, placement re-homes the dead
    replica's prefix shard, and a same-id revive serves again and
    recovers the fleet HealthMonitor verdict.

    The fleet writes its events + snapshots to its OWN log (not the
    shared campaign log): the leg runs in the DRIVER process, and its
    process snapshots would collide with the train children's rank-0
    stream (two different processes' ``proc.rss_bytes`` interleaved
    under one rank reads as a fake growth breach). The health proof
    runs HERE instead: the leg's log must show the ok -> degraded
    (``worker-failure``, rank = victim) -> ok trajectory, and
    ``tools/dpxmon.py replay`` over it must exit 0."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu.models.generate import make_generate_fn
    from distributed_pytorch_tpu.obs import health
    from distributed_pytorch_tpu.runtime import chaos, faults
    from distributed_pytorch_tpu.serve import EngineConfig, SamplingParams
    from distributed_pytorch_tpu.serve.fleet import (FleetConfig,
                                                     FleetRouter,
                                                     ReplicaFailed)
    from distributed_pytorch_tpu.utils.logging import MetricsLogger

    model, params = _serve_model()
    rng = np.random.default_rng(13)
    typed, attributed, recovered, rehomed = "", False, False, False
    victim = -1
    faults.reset()

    legdir = tempfile.mkdtemp(prefix="dpx_chaos_fleet_")
    leglog = os.path.join(legdir, "fleet_metrics.jsonl")
    fleet = FleetRouter(model, params,
                        FleetConfig(n_replicas=2,
                                    engine=EngineConfig(n_slots=2,
                                                        max_len=64,
                                                        page_len=8),
                                    metrics=MetricsLogger(leglog),
                                    log_every=4))
    # shared first-page prefix: identical rendezvous key, so both
    # requests home on the SAME replica — a is the in-flight victim,
    # b is the submit whose hook call kills that home
    head = rng.integers(0, 61, (8,)).astype(np.int32)
    a = np.concatenate([head, rng.integers(0, 61, (6,)).astype(np.int32)])
    b = np.concatenate([head, rng.integers(0, 61, (4,)).astype(np.int32)])
    sp_a = SamplingParams(max_new_tokens=48)   # long: in flight at kill
    sp_b = SamplingParams(max_new_tokens=12)
    ka, kb = jax.random.PRNGKey(5), jax.random.PRNGKey(6)
    with fleet:
        # warm every compile BEFORE arming (the serve-leg discipline:
        # the call counter only runs while specs are installed)
        fleet.submit(rng.integers(0, 61, (6,)).astype(np.int32),
                     SamplingParams(max_new_tokens=2)).result(timeout=120)
        victim = fleet.home_of(a)
        faults.install(clause.fault)           # call 1 = a, call 2 = b
        ha = fleet.submit(a, sp_a, rng=ka)
        while not ha.tokens:                   # a streaming on its home
            time.sleep(0.005)
        hb = fleet.submit(b, sp_b, rng=kb)     # the hook kills a's home
        try:
            ha.result(timeout=120)
        except ReplicaFailed as e:
            typed = "ReplicaFailed"
            attributed = (e.replica == victim
                          and e.request_id == ha.request_id)
        # containment IS the recovery: b re-routed to the survivor and
        # its stream is bit-exact vs a standalone generate()
        out_b = hb.result(timeout=120)
        fn = make_generate_fn(model, sp_b.max_new_tokens,
                              temperature=sp_b.temperature,
                              top_k=sp_b.top_k, top_p=sp_b.top_p,
                              max_len=64)
        want = np.asarray(jax.jit(fn)(params, jnp.asarray(b[None]),
                                      kb))[0]
        rehomed = fleet.home_of(a) != victim
        recovered = (bool(np.array_equal(out_b, want)) and rehomed
                     and hb.replica != victim)
        # relaunch under the SAME id: the following snapshots name the
        # replica live again, clearing its health-failure stream
        fleet.revive_replica(victim)
        hc = fleet.submit(a, SamplingParams(max_new_tokens=4))
        recovered = recovered and len(hc.result(timeout=120)) > 0
        fleet.emit_snapshot()
        fleet.emit_snapshot()
    fired = bool(faults.fired())
    faults.reset()
    # the fleet health proof, over the leg's own log: the kill must
    # degrade the victim's stream (rule + replica attributed) and the
    # revive + snapshots must recover it; replay re-derives the same
    # verdict with strict snapshot validation (rc 0)
    mon = health.HealthMonitor(
        health.parse_rules("fleet.max_queue_depth<=9999"))
    legrecs, _ = _read_new(leglog, 0)
    for r in legrecs:
        mon.feed(r)
    degraded = any(t["to"] == "degraded"
                   and t["rule"] == health.FAILURE_RULE
                   and t["rank"] == victim for t in mon.transitions)
    rc, _out = _run_cli("tools.dpxmon", ["replay", leglog])
    recovered = (recovered and degraded and mon.state == "ok"
                 and rc == 0)
    if recovered:
        shutil.rmtree(legdir, ignore_errors=True)
    recs, _ = _read_new(log, pos)
    return chaos.clause_report(
        clause, fired=fired, typed_error=typed, attributed=attributed,
        recovered=recovered, retries=_count_comm_retries(recs),
        detail=f"victim=replica {victim} rehomed={rehomed} "
               f"health_degraded={degraded} health_end={mon.state} "
               f"dpxmon_rc={rc} log={leglog}")


def _run_transport_leg(clause, log: str, pos: int):
    """The retry micro-harness: one bare LocalTransport send with the
    clause armed — recovery proves the bounded retry, exhaustion proves
    the typed error carries the attempt count."""
    from distributed_pytorch_tpu.runtime import chaos, faults
    from distributed_pytorch_tpu.runtime import env as _env
    from distributed_pytorch_tpu.runtime.native import CommRetryExhausted
    from distributed_pytorch_tpu.serve.disagg import LocalTransport

    typed, attributed, recovered = "", False, False
    saved = _env.snapshot(list(clause.env))
    for k, v in clause.env.items():
        _env.set(k, str(v))
    faults.reset()
    faults.install(clause.fault)
    try:
        t = LocalTransport()
        try:
            t.send(b"frame", 16)
            recovered = t.frames_sent == 1
        except CommRetryExhausted as e:
            typed = "CommRetryExhausted"
            budget = int(_env.get(chaos.RETRY_MAX_ENV))
            attributed = (e.op == "handoff_send"
                          and e.attempts == budget + 1)
        fired = bool(faults.fired())
    finally:
        faults.reset()
        _env.restore(saved)
    recs, _ = _read_new(log, pos)
    return chaos.clause_report(clause, fired=fired, typed_error=typed,
                               attributed=attributed,
                               recovered=recovered,
                               retries=_count_comm_retries(recs))


# ---------------------------------------------------------------------------
# the campaign
# ---------------------------------------------------------------------------


def run_campaign(smoke: bool = False) -> int:
    """Run the armed campaign end to end; returns the exit code (0 =
    every clause green AND every meta-gate held). Prints one JSON
    summary line."""
    from distributed_pytorch_tpu.obs import health
    from distributed_pytorch_tpu.runtime import chaos
    from distributed_pytorch_tpu.runtime import env as _env
    from distributed_pytorch_tpu.utils.logging import append_event

    world = int(_env.get("DPX_CHAOS_WORLD"))
    workdir = tempfile.mkdtemp(prefix="dpx_chaos_")
    log = os.path.join(workdir, "chaos_metrics.jsonl")
    campaign = chaos.load_campaign(
        default=SMOKE_CAMPAIGN if smoke else FULL_CAMPAIGN)
    _progress(f"campaign {campaign.name!r}: {len(campaign.clauses)} "
              f"clause(s), train world {world}, log {log}")

    # supervisor + in-process legs write events/traces into the one
    # campaign log (restored on exit); the live monitor follows it
    saved = _env.snapshot(["DPX_METRICS_LOG", "DPX_TRACE", "DPX_MON"])
    _env.set("DPX_METRICS_LOG", log)
    _env.set("DPX_TRACE", "1")
    _env.set("DPX_MON", "1")
    live_rules = health.parse_rules(
        "drift(train.steps_per_sec)@k=3,floor=0.5;"
        "growth(proc.rss_bytes)@window=8,grow=0.25")
    monitor = health.HealthMonitor(live_rules, emit_path=log,
                                   critical_after=5)
    follower = health.LogFollower(log, monitor)
    stop = threading.Event()

    def _follow():
        while not stop.is_set():
            follower.poll()
            stop.wait(0.5)

    t = threading.Thread(target=_follow, name="dpx-chaos-health",
                         daemon=True)
    t.start()

    rows = []
    t0 = time.perf_counter()
    try:
        pos = 0
        for clause in campaign.clauses:
            _progress(f"clause {clause.id}: [{clause.leg}] "
                      f"{clause.fault} (expect {clause.expect})")
            t_leg = time.perf_counter()
            if clause.leg in ("train", "train_shrink"):
                row = _run_train_leg(clause, log, pos, workdir, world)
            elif clause.leg == "serve":
                row = _run_serve_leg(clause, log, pos)
            elif clause.leg == "fleet":
                row = _run_fleet_leg(clause, log, pos)
            else:
                row = _run_transport_leg(clause, log, pos)
            row["wall_s"] = round(time.perf_counter() - t_leg, 1)
            rows.append(row)
            green = chaos.clause_green(row)
            append_event("chaos_clause", id=clause.id,
                         fault=clause.fault, leg=clause.leg,
                         expect=clause.expect, fired=row["fired"],
                         typed_error=row["typed_error"],
                         attributed=row["attributed"],
                         recovered=row["recovered"],
                         retries=row["retries"], green=green)
            typed = row["typed_error"] or None
            _progress(f"clause {clause.id}: "
                      f"{'GREEN' if green else 'NOT GREEN'} "
                      f"({row['wall_s']}s; typed={typed} "
                      f"retries={row['retries']})")
            _, pos = _read_new(log, pos)
    finally:
        _env.restore(saved)
        stop.set()
        t.join(timeout=10)
    follower.poll()
    wall_s = time.perf_counter() - t0

    failures = []

    def gate(ok: bool, what: str) -> None:
        # explicit checks, NOT assert (-O/PYTHONOPTIMIZE safe)
        if not ok:
            failures.append(what)
            _progress(f"GATE FAILED: {what}")

    verdict = chaos.campaign_verdict(rows)
    gate(verdict["ok"],
         f"clause(s) not green: {verdict['failing']}")

    # the LIVE monitor must have seen the train-leg failure degrade
    # health (the deterministic worker-failure rule)
    trs = monitor.transitions
    gate(any(x["to"] == "degraded" for x in trs),
         "no ok->degraded transition observed live")

    # dpxmon verdict over the whole campaign log: strict validation +
    # re-derived health, exit 0
    rc, _out = _run_cli("tools.dpxmon", ["replay", log])
    gate(rc == 0, f"dpxmon replay over the campaign log exited {rc}")
    rc2, out2 = _run_cli("tools.dpxtrace", ["check", log])
    gate(rc2 == 0,
         f"dpxtrace check over the campaign log exited {rc2}: "
         f"{out2.strip()[:300]}")

    # the gates can FAIL: seeded SLO violation -> dpxmon rc 1
    seeded = os.path.join(workdir, "seeded_violation.jsonl")
    _seed_violation_log(seeded)
    rc3, _out3 = _run_cli("tools.dpxmon", ["replay", seeded])
    gate(rc3 == 1, f"seeded SLO-violation log exited {rc3}, wanted 1")

    # the per-clause report, rolled up by the stdlib CLI (rc 0) ...
    report = {"name": campaign.name, "world": world,
              "smoke": smoke, "clauses": rows, "verdict": verdict}
    report_path = os.path.join(workdir, "campaign_report.json")
    with open(report_path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    rc4, out4 = _run_cli("tools.dpxchaos", ["report", report_path])
    gate(rc4 == (0 if verdict["ok"] else 1),
         f"dpxchaos report exited {rc4} for ok={verdict['ok']}: "
         f"{out4.strip()[:300]}")

    # ... and a seeded UNRECOVERED clause must make it exit 1
    seeded_rows = [dict(r) for r in rows] + [{
        "id": "seeded", "fault": "kill@step=1,rank=0",
        "leg": "train", "expect": "elastic_resume", "fired": True,
        "typed_error": "WorkerFailure", "attributed": True,
        "recovered": False, "retries": 0,
        "detail": "seeded unrecovered clause (gate-can-fail proof)"}]
    seeded_report = os.path.join(workdir, "seeded_report.json")
    with open(seeded_report, "w", encoding="utf-8") as f:
        json.dump({"name": "seeded", "clauses": seeded_rows}, f)
    rc5, _out5 = _run_cli("tools.dpxchaos", ["report", seeded_report])
    gate(rc5 == 1,
         f"seeded unrecovered-clause report exited {rc5}, wanted 1")

    summary = {
        "chaos_campaign": campaign.name,
        "ok": not failures,
        "world": world,
        "wall_s": round(wall_s, 1),
        "clauses": [{k: r[k] for k in
                     ("id", "leg", "fault", "expect", "fired",
                      "typed_error", "attributed", "recovered",
                      "retries", "wall_s")} for r in rows],
        "verdict": verdict,
        "dpxmon_replay_rc": rc,
        "dpxtrace_check_rc": rc2,
        "seeded_violation_rc": rc3,
        "dpxchaos_report_rc": rc4,
        "seeded_report_rc": rc5,
        "report": report_path,
        "log": log,
        **({"failures": failures} if failures else {}),
    }
    print(json.dumps(summary))
    if not failures and smoke:
        shutil.rmtree(workdir, ignore_errors=True)
    elif failures:
        _progress(f"artifacts kept for inspection: {workdir}")
    return 1 if failures else 0


def main(argv=None) -> int:
    smoke = "--smoke" in (sys.argv[1:] if argv is None else argv)
    return run_campaign(smoke=smoke)


if __name__ == "__main__":
    raise SystemExit(main())
