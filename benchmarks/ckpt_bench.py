"""Checkpoint benchmark: sharded (every-host-writes-its-shards) vs
full-replica (rank-0-writes-everything) save/restore under the host
front door.

Three arms over the same replicated state on a dp=8 native TCP process
group (the per-rank-process front door — the execution model where
"bytes per host" is a real quantity):

- **full-sync**    — the legacy single-writer format-1 path: rank 0
  serializes the entire state every save, everyone else waits at the
  barrier.
- **sharded-sync** — format 2 (ckpt/): each rank writes only the shards
  it owns per the FSDP specs (1/world of the bytes per host), commit on
  rank 0.
- **sharded-async** — same bytes, but serialization/IO on the background
  thread with the commit barrier deferred: the number that matters is
  ``save_call_ms`` (how long training is actually blocked), which drops
  to the D2H-snapshot cost.

Per arm: wall seconds/step (barrier-fenced), blocking ``save()``
latency through the perfbench statistical policy (the first save is
discarded as warmup — directory creation + allocator cold start — and
the rest aggregate to median + IQR with the hard spread gate;
docs/benchmarking.md), restore seconds (full reassembly on every
rank), and measured-from-manifest bytes-per-host. The printed line is
a schema-valid ``dpx.bench.record`` whose per-arm ``save_call_ms``
metrics benchdiff can anchor regression verdicts on (direction:
lower-is-better). ``--smoke`` shrinks to a seconds-scale dp=4 run and
ASSERTS restored state equals the source bit-for-bit in both formats
plus the 1/world write-bytes property — the CI gate (tier1.yml) that
keeps the sharded path from rotting.

Usage: python benchmarks/ckpt_bench.py [--smoke] [--world N]
           [--mib M] [--steps K]
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

ARMS = ("full-sync", "sharded-sync", "sharded-async")


def _make_state(n_elems: int):
    """A few big leaves + a small one, every big dim divisible by the
    worlds we bench (8, 4, 2) — replicated DDP-style state."""
    rng = np.random.default_rng(0)
    big = n_elems // 2
    return {
        "emb": rng.standard_normal((big // 64, 64)).astype(np.float32),
        "w": rng.standard_normal((n_elems - big) // 32 * 32)
        .astype(np.float32).reshape(-1, 32),
        "scale": np.float32(1.0),
    }


def _bytes_per_host(step_dir: str, world: int):
    """Actual shard bytes each writer landed, from the manifest."""
    man = json.load(open(os.path.join(step_dir, "manifest.json")))
    per = [0] * world
    if man.get("format") != 2:
        total = sum(
            os.path.getsize(os.path.join(step_dir, n))
            for n in os.listdir(step_dir))
        per[0] = total
        return per
    for tree in man["trees"].values():
        for leaf in tree["leaves"]:
            for sh in leaf["shards"]:
                per[sh["writer"]] += sh["nbytes"]
    return per


def _ckpt_worker(rank, world, q, n_elems, steps, base):
    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu.ckpt import CheckpointManager
    from distributed_pytorch_tpu.parallel import fsdp_param_specs
    from distributed_pytorch_tpu.runtime import context
    from distributed_pytorch_tpu.utils.checkpoint import (
        latest_step, restore_checkpoint)

    dist.init_process_group(rank, world)
    comm = context.get_host_comm()
    params = _make_state(n_elems)
    specs = fsdp_param_specs(params, world, min_size=1024)
    results = {}
    try:
        for arm in ARMS:
            workdir = os.path.join(base, arm.replace("-", "_"))
            if arm == "full-sync":
                mgr = CheckpointManager(workdir, interval=1, keep=2)
            else:
                mgr = CheckpointManager(
                    workdir, interval=1, keep=2,
                    async_save=arm.endswith("async"), sharded=True,
                    param_specs=specs, axis_sizes={"dp": world})
            comm.barrier()
            t0 = time.perf_counter()
            call_ms = []
            for s in range(1, steps + 1):
                c0 = time.perf_counter()
                mgr.save(s, params)
                call_ms.append((time.perf_counter() - c0) * 1e3)
            mgr.wait()
            comm.barrier()
            wall = time.perf_counter() - t0

            comm.barrier()
            r0 = time.perf_counter()
            ck = restore_checkpoint(workdir, like_params=params)
            comm.barrier()
            restore_s = time.perf_counter() - r0

            for k in params:  # every arm must round-trip bit-exactly
                np.testing.assert_array_equal(
                    np.asarray(ck.params[k]), params[k],
                    err_msg=f"{arm}: leaf {k} corrupted in round trip")
            if rank == 0:
                from distributed_pytorch_tpu.perfbench import (
                    record as pbrecord, stats as pbstats)
                step_dir = os.path.join(workdir,
                                        f"step_{latest_step(workdir)}")
                # per-save latencies ARE the repeated trials: first save
                # discarded as warmup (directory creation, allocator),
                # median + IQR + spread gate on the rest
                st = pbstats.summarize(call_ms, warmup=1)
                results[arm] = {
                    "wall_s_per_step": round(wall / steps, 4),
                    "save_call_ms_p50": round(st.median, 2),
                    "save_call_ms_blob": pbrecord.make_metric(
                        None, "ms", stats=st, direction="lower"),
                    "restore_s": round(restore_s, 4),
                    "bytes_per_host": _bytes_per_host(step_dir, world),
                }
        if rank == 0:
            total = sum(v.nbytes for v in params.values())
            sharded = results["sharded-sync"]["bytes_per_host"]
            assert all(b <= 2 * total // world + 4096 for b in sharded), \
                f"sharded mode wrote {sharded}, expected ~{total}/{world}" \
                " per host"
            q.put({"world": world, "state_mib": round(total / 2**20, 2),
                   "steps": steps, "arms": results})
    finally:
        dist.cleanup()


def main(argv):
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale dp=4 CPU run with correctness "
                         "asserts (the CI gate)")
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--mib", type=float, default=64.0,
                    help="state size in MiB of f32")
    # 1 warmup + >=3 kept saves per arm: the minimum the perfbench
    # spread estimate is meaningful on (stats.MIN_TRUSTED_TRIALS)
    ap.add_argument("--steps", type=int, default=6)
    args = ap.parse_args(argv)
    world = 4 if args.smoke else args.world
    mib = 2.0 if args.smoke else args.mib
    steps = 4 if args.smoke else args.steps
    n_elems = int(mib * 2**20 / 4)

    from distributed_pytorch_tpu.runtime.multiprocess import (
        launch_multiprocess)

    base = tempfile.mkdtemp(prefix="ckpt_bench_")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    try:
        launch_multiprocess(_ckpt_worker, world, q, n_elems, steps, base)
        raw = q.get(timeout=60)
    finally:
        shutil.rmtree(base, ignore_errors=True)

    # schema record: per-arm blocking-save latency as gated
    # lower-is-better metrics, headline = the async path (the number
    # that measures how long training is actually blocked)
    from distributed_pytorch_tpu.perfbench import record as pbrecord
    rec = pbrecord.make_record("ckpt_sharded_async_save_call_ms", "ms",
                               device="cpu-loopback")
    rec.update({"bench": "ckpt", "smoke": bool(args.smoke)})
    rec.update(raw)
    for arm, res in rec["arms"].items():
        blob = res.pop("save_call_ms_blob", None)
        if blob:
            key = f"ckpt_{arm.replace('-', '_')}_save_call_ms"
            rec["metrics"][key] = blob
    head = rec["metrics"].get("ckpt_sharded_async_save_call_ms", {})
    if head.get("value") is not None:
        rec["value"] = head["value"]
        rec["provenance"] = "measured"
        rec["trusted"] = bool(head.get("trusted"))
        if rec["trusted"]:
            rec.pop("untrusted_reason", None)
        else:
            rec["untrusted_reason"] = head.get("untrusted_reason",
                                               "spread gate failed")
    else:
        rec["error"] = "sharded-async arm produced no save latency"
    issues = pbrecord.validate_record(rec, strict=False)
    if issues:
        rec["schema_issues"] = issues
        print(f"# WARNING: ckpt record failed schema self-validation: "
              f"{'; '.join(issues[:3])}", file=sys.stderr)
    # one line: the parse-last-stdout-line-as-JSON collector contract
    print(json.dumps(rec))
    if args.smoke:
        arms = rec["arms"]
        full0 = arms["full-sync"]["bytes_per_host"][0]
        shard = arms["sharded-sync"]["bytes_per_host"]
        print(f"# smoke OK: full-replica rank0 wrote {full0} B; "
              f"sharded per-host {shard}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
