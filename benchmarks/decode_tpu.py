"""Single-chip autoregressive decode benchmark: tokens/sec with the
compiled KV-cache path (models/generate.py).

The reference has no inference path at all; this measures ours where it
matters — per-token decode latency/throughput on the flagship-class model.
Decode is bandwidth-bound (each step streams the params + KV cache once),
so the companion number to MFU here is achieved HBM bandwidth:

    bytes/step ~= param_bytes + kv_cache_bytes(current length)
    achieved GB/s = bytes/step * tokens/step / step_time

Usage: python benchmarks/decode_tpu.py [--small] [--gqa]
(``--gqa`` adds a grouped-query arm — group 4 at full scale — and the
decode speedup the shrunken cache buys.) Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

# Public spec-sheet HBM bandwidth per chip (bytes/s).
HBM_BW = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}


def run(dim=768, n_layers=12, n_heads=12, vocab=32000,
        prompt_len=128, max_new=256, batch=8, n_kv_heads=None,
        int8_weights=False, pin_weight_stream=False, window=None,
        dtype=jnp.bfloat16) -> dict:
    from benchmarks.mfu_transformer import count_params
    from distributed_pytorch_tpu import models
    from distributed_pytorch_tpu.models import make_generate_fn
    from distributed_pytorch_tpu.models.generate import prefill
    from distributed_pytorch_tpu.ops.flash_attention import \
        make_flash_attn_fn
    from distributed_pytorch_tpu.ops.quant import (quantize_tree,
                                                   quantized_bytes)
    from distributed_pytorch_tpu.utils.profiler import (fetch_fence,
                                                        time_steps_amortized)

    max_seq = prompt_len + max_new
    # a sliding window switches generate to the rolling O(window) cache
    # (models/generate.py): each decode step streams min(window, total)
    # cache slots instead of max_seq — the bandwidth lever this arm
    # measures
    attn_fn = make_flash_attn_fn(window=window) if window else None
    model = models.TransformerLM(vocab=vocab, dim=dim, n_layers=n_layers,
                                 n_heads=n_heads, n_kv_heads=n_kv_heads,
                                 max_seq=max_seq, dtype=dtype,
                                 attn_fn=attn_fn)
    params = model.init(jax.random.PRNGKey(0))
    n_params = count_params(params)
    if int8_weights:
        params = quantize_tree(params)
    param_bytes = quantized_bytes(params)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, vocab, dtype=jnp.int32)

    gen = jax.jit(make_generate_fn(
        model, max_new, pin_weight_stream=pin_weight_stream))
    rng = jax.random.PRNGKey(2)

    # Amortized timing with host-fetch fencing (block_until_ready can
    # resolve early on the tunneled backend — benchmarks/fence_probe.py):
    # successive gen calls are chained through an rng folded with the
    # previous output, so one final fetch waits for all of them and the
    # per-call tunnel round trip amortizes over n calls.
    toks = gen(params, prompt, rng)
    fetch_fence(toks[:, -1])                  # compile + drain

    def gen_step(state):
        r, _ = state
        t = gen(params, prompt, r)
        return (jax.random.fold_in(r, t[:, -1].sum()), t)

    n_gen = 5
    t_total, _ = time_steps_amortized(gen_step, (rng, toks), n_gen,
                                      lambda s: s[1][:, -1])

    # prefill timed separately so the decode metrics are decode-only:
    # gen() = one prefill (which also yields the FIRST new token's logits)
    # + (max_new - 1) scanned decode steps. Chained by perturbing the
    # prompt with a zero derived from the previous output.
    cache_len = min(window, max_seq) if window else max_seq
    pf = jax.jit(lambda p, toks: prefill(model, p, toks, max_seq,
                                         window=(cache_len if window
                                                 else None)))
    out0 = pf(params, prompt)
    fetch_fence(jax.tree_util.tree_leaves(out0)[0].ravel()[0])

    def pf_step(state):
        pr, prev = state
        dep = jax.tree_util.tree_leaves(prev)[0].ravel()[0]
        pr = pr + (dep * 0).astype(pr.dtype)
        return (pr, pf(params, pr))

    t_prefill, _ = time_steps_amortized(
        pf_step, (prompt, out0), 5,
        lambda s: jax.tree_util.tree_leaves(s[1])[0].ravel()[0])
    decode_steps = max_new - 1
    t_decode = max(t_total - t_prefill, 1e-9)

    tok_s_e2e = batch * max_new / t_total
    tok_s_decode = batch * decode_steps / t_decode
    bpe = jnp.dtype(dtype).itemsize
    # each decode step streams the params (int8 bytes when quantized —
    # an ASSUMPTION the est_achieved_hbm numbers inherit: if XLA hoists
    # the dequant out of the decode scan, actual traffic is the bf16
    # bytes; the int8-vs-bf16 tok/s comparison in run_gqa_compare is the
    # empirical check) plus the FULL preallocated cache (decode attends
    # over max_len under a position mask — static shapes); GQA shrinks
    # the cache rows to n_kv_heads * head_dim
    kv_dim = (n_kv_heads or n_heads) * (dim // n_heads)
    kv_bytes = n_layers * 2 * batch * kv_dim * cache_len * bpe
    bytes_per_step = param_bytes + kv_bytes
    achieved_bw = bytes_per_step * decode_steps / t_decode

    dev = jax.devices()[0]
    peak_bw = HBM_BW.get(dev.device_kind)
    return {
        "device": dev.device_kind,
        "config": {"dim": dim, "n_layers": n_layers, "n_heads": n_heads,
                   "n_kv_heads": n_kv_heads or n_heads,
                   "vocab": vocab, "prompt_len": prompt_len,
                   "max_new": max_new, "batch": batch,
                   "int8_weights": bool(int8_weights),
                   "pin_weight_stream": bool(pin_weight_stream),
                   "window": window, "cache_len": cache_len,
                   "dtype": str(jnp.dtype(dtype).name)},
        "n_params": n_params,
        "param_bytes": int(param_bytes),
        "wall_s_median": round(t_total, 4),
        "prefill_ms": round(t_prefill * 1e3, 3),
        "e2e_tokens_per_sec": round(tok_s_e2e, 1),
        "decode_tokens_per_sec": round(tok_s_decode, 1),
        "decode_per_token_latency_ms": round(1e3 * t_decode / decode_steps,
                                             3),
        "est_achieved_hbm_gbps": round(achieved_bw / 1e9, 1),
        "peak_hbm_gbps": round(peak_bw / 1e9, 1) if peak_bw else None,
        "est_hbm_utilization": round(achieved_bw / peak_bw, 3)
        if peak_bw else None,
    }


def run_gqa_compare(small: bool = False) -> dict:
    """MHA vs grouped-query decode vs int8 weights, at equal model class.
    Decode is bandwidth-bound (params + KV cache stream once per token),
    so the speedups quantify what the group-factor-smaller cache (GQA)
    and the halved weight bytes (int8) buy — untrained weights, identical
    compute graph shape. One schema for the small and full arms."""
    kw = dict(dim=128, n_layers=2, n_heads=4, vocab=512, prompt_len=16,
              max_new=32, batch=2) if small else {}
    n_kv = 1 if small else 3                         # group 4

    import bench

    def arm(msg, fn, *a, **k):
        # bench.arm contract: a tunnel wedge mid-arm leaves WHICH arm
        # hung in the collector's kept stdout tail
        return bench.arm(f"decode arm: {msg}", lambda: fn(*a, **k))

    mha = arm("mha", run, **kw)
    gqa = arm("gqa", run, n_kv_heads=n_kv, **kw)
    gqa_int8 = arm("gqa_int8", run, n_kv_heads=n_kv, int8_weights=True,
                   **kw)
    # pinned arm: weight stream tied into the scan so int8 dequant can't
    # be hoisted (generate.py:pin_weight_stream). int8 vs int8_pinned is
    # the empirical answer to "did XLA hoist the dequant": if pinned is
    # faster, the plain arm was streaming bf16.
    gqa_int8_pin = arm("gqa_int8_pinned", run, n_kv_heads=n_kv,
                       int8_weights=True, pin_weight_stream=True, **kw)
    # rolling-cache arm: sliding window = 1/3 of the total length, so
    # the cache the decode step streams shrinks 3x (models/generate.py
    # rolling buffer) — stacks with GQA's group-factor shrink
    win = 16 if small else 128
    gqa_window = arm("gqa_window", run, n_kv_heads=n_kv, window=win,
                     **kw)
    bench.progress("decode arms done")
    base = mha["decode_tokens_per_sec"]
    return {"mha": mha, "gqa": gqa, "gqa_int8": gqa_int8,
            "gqa_int8_pinned": gqa_int8_pin,
            "gqa_window": gqa_window,
            "gqa_decode_speedup": round(
                gqa["decode_tokens_per_sec"] / base, 2),
            "gqa_int8_decode_speedup": round(
                gqa_int8["decode_tokens_per_sec"] / base, 2),
            "gqa_int8_pinned_decode_speedup": round(
                gqa_int8_pin["decode_tokens_per_sec"] / base, 2),
            "gqa_window_decode_speedup": round(
                gqa_window["decode_tokens_per_sec"] / base, 2)}


def main(argv):
    small = "--small" in argv
    if "--gqa" in argv:
        rec = run_gqa_compare(small=small)
    elif small:
        rec = run(dim=128, n_layers=2, n_heads=4, vocab=512,
                  prompt_len=16, max_new=32, batch=2)
    else:
        rec = run()
    # one compact line: collectors parse the last stdout line as JSON
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
