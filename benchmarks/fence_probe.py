"""Characterize the backend's synchronization semantics (tunnel probe).

The axon-tunneled TPU backend produced mutually inconsistent timings
(BASELINE.md round 3): a per-step MLP at 52us/step, a flat ~72ms floor on
small attention kernels, and a transformer "step" of 6.8ms that would
imply 4.4x the chip's peak FLOP/s. This probe decides what a host-side
fence actually waits for, by timing matmul chains of KNOWN FLOPs three
ways:

- ``dispatch``: no fence at all (pure enqueue cost)
- ``block``:    ``jax.block_until_ready`` per call
- ``fetch``:    ``np.asarray`` of the (scalar) result per call — this
                materializes bytes on the host and CANNOT resolve before
                the value exists

and an ``amortized`` mode: K chained calls, one fetch at the end, /K.
If ``block`` per-call times sit below the analytic minimum (flops/peak)
while ``fetch`` does not, block_until_ready resolves early on this
backend and every benchmark must fence by fetching (or amortize).

Usage: python benchmarks/fence_probe.py [--sizes 2048,4096,8192]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

CHAIN = 8  # matmuls per jitted call


def make_fn(n):
    def f(x):
        y = x
        for _ in range(CHAIN):
            y = jnp.matmul(y, x, preferred_element_type=jnp.float32) \
                   .astype(jnp.bfloat16) / n
        return jnp.sum(y.astype(jnp.float32))
    return jax.jit(f)


def resolve_peak_flops() -> float:
    """Peak bf16 FLOP/s for the attached chip, from the same table the
    MFU bench maintains — the analytic_min (and hence the probe's whole
    verdict) is wrong if computed against another generation's peak."""
    from benchmarks.mfu_transformer import PEAK_BF16
    kind = jax.devices()[0].device_kind
    return PEAK_BF16.get(kind, 197e12)


def probe_size(n, peak_flops=197e12, reps=5):
    f = make_fn(n)
    x = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
    flops = CHAIN * 2 * n ** 3
    analytic_min_s = flops / peak_flops

    r = f(x)
    np.asarray(r)  # warm compile + execute, fully drained

    def timed(fence):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = f(x)
            if fence == "block":
                jax.block_until_ready(out)
            elif fence == "fetch":
                np.asarray(out)
            ts.append(time.perf_counter() - t0)
        if fence == "dispatch":
            np.asarray(out)  # drain the queue outside the timed region
        return sorted(ts)[len(ts) // 2]

    t_dispatch = timed("dispatch")
    t_block = timed("block")
    t_fetch = timed("fetch")

    # amortized: K dispatches chained by data dependence, one fetch
    k = 10
    np.asarray(f(x))
    t0 = time.perf_counter()
    out = None
    for _ in range(k):
        out = f(x)
    np.asarray(out)
    t_amort = (time.perf_counter() - t0) / k

    return {"n": n, "tflops_per_call": round(flops / 1e12, 3),
            "analytic_min_ms": round(analytic_min_s * 1e3, 3),
            "dispatch_ms": round(t_dispatch * 1e3, 3),
            "block_ms": round(t_block * 1e3, 3),
            "fetch_ms": round(t_fetch * 1e3, 3),
            "amortized_ms": round(t_amort * 1e3, 3),
            "block_below_physical_min": bool(t_block < analytic_min_s),
            "fetch_below_physical_min": bool(t_fetch < analytic_min_s)}


def main(argv):
    sizes = [2048, 4096, 8192]
    if "--sizes" in argv:
        sizes = [int(s) for s in
                 argv[argv.index("--sizes") + 1].split(",")]
    dev = jax.devices()[0]
    peak = resolve_peak_flops()
    rows = [probe_size(n, peak_flops=peak) for n in sizes]
    for r in rows:
        print(f"# n={r['n']}: min {r['analytic_min_ms']}ms  "
              f"dispatch {r['dispatch_ms']}ms  block {r['block_ms']}ms  "
              f"fetch {r['fetch_ms']}ms  amortized {r['amortized_ms']}ms",
              file=sys.stderr)
    verdict = ("block_until_ready resolves EARLY — fence by fetch/amortize"
               if any(r["block_below_physical_min"] for r in rows)
               else "block_until_ready waits for completion")
    print(json.dumps({"device": dev.device_kind, "verdict": verdict,
                      "rows": rows}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
