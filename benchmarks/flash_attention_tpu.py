"""On-chip flash attention validation + speedup table vs dense attention.

Runs the pallas kernel COMPILED (interpret=False) on the real TPU — the
unit tests (tests/test_flash_attention.py) run the same numerics in
interpret mode on the CPU mesh; this script is the hardware half of that
contract: it proves the Mosaic lowering is correct and measures what the
kernel buys over the dense einsum path at increasing sequence length.

Usage:  python benchmarks/flash_attention_tpu.py
Output: a markdown table (appended by hand to BASELINE.md) plus one JSON
        line with the headline speedup for tooling.
"""

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributed_pytorch_tpu.nn.attention import dense_attention
from distributed_pytorch_tpu.ops import flash_attention
from distributed_pytorch_tpu.utils.profiler import fetch_fence


def _qkv(key, b, h, s_q, s_k, d, dtype):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, s_q, d), dtype)
    k = jax.random.normal(kk, (b, h, s_k, d), dtype)
    v = jax.random.normal(kv, (b, h, s_k, d), dtype)
    return q, k, v


def validate_numerics():
    """Compiled-kernel numerics vs the dense path, on the chip.

    Tolerances are wider than the interpret-mode unit tests because BOTH
    paths run TPU matmuls (bf16 passes for f32 inputs by default); this
    checks the Mosaic lowering, not float32 reference numerics (the unit
    tests already pin those down in interpret mode).
    """
    ok = True
    for causal, s_q, s_k in [(False, 256, 256), (True, 256, 256),
                             (True, 250, 250), (True, 128, 256)]:
        q, k, v = _qkv(jax.random.PRNGKey(0), 2, 4, s_q, s_k, 64, jnp.float32)
        want = dense_attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal=causal, interpret=False)
        err = float(jnp.max(jnp.abs(got - want)))
        line_ok = err < 2e-2
        ok &= line_ok
        print(f"fwd   causal={causal} s_q={s_q} s_k={s_k} "
              f"max_err={err:.2e} {'OK' if line_ok else 'FAIL'}")

        def lf(q, k, v, _c=causal):
            return jnp.sum(flash_attention(q, k, v, causal=_c,
                                           interpret=False) ** 2)

        def ld(q, k, v, _c=causal):
            return jnp.sum(dense_attention(q, k, v, causal=_c) ** 2)

        g = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        w = jax.grad(ld, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g, w):
            err = float(jnp.max(jnp.abs(a - b)))
            line_ok = err < 1e-1
            ok &= line_ok
            print(f"  d{name} causal={causal} s_q={s_q} s_k={s_k} "
                  f"max_err={err:.2e} {'OK' if line_ok else 'FAIL'}")

    # s_q > s_k causal: fully-masked rows must be NaN exactly where the
    # dense path's are (regression for the _finish masked-row bug).
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 2, 256, 128, 64, jnp.float32)
    want = np.asarray(dense_attention(q, k, v, causal=True))
    got = np.asarray(flash_attention(q, k, v, causal=True, interpret=False))
    nan_match = bool((np.isnan(got) == np.isnan(want)).all())
    has_nan = bool(np.isnan(want).any())
    ok &= nan_match and has_nan
    print(f"causal s_q>s_k NaN rows: match={nan_match} present={has_nan} "
          f"{'OK' if nan_match and has_nan else 'FAIL'}")
    return ok


R_INNER = 100   # kernel invocations fused into one XLA call
N_CALLS = 2     # chained dispatches of that call


def _time_kernel(scalar_fn, q, k, v):
    """Per-invocation seconds of ``scalar_fn(q, k, v) -> scalar``, honest
    on the high-latency tunneled backend: R_INNER serial invocations run
    inside ONE jitted ``lax.scan`` (the carry perturbs q, so the
    loop-invariant body cannot be hoisted — and since the carry is
    ~1e-27, ``q + c`` rounds back to exactly q for any element above one
    ulp of that, so the perturbation is numerically free while remaining
    opaque to the compiler), N_CALLS dispatches are chained through that
    carry, and a single host fetch of the final scalar transitively waits
    for all of it. Per-call dispatch latency —
    which dwarfs these kernels' compute — amortizes over N_CALLS*R_INNER
    invocations instead of gating each one (see fence_probe.py)."""
    def repeated(q, k, v, c0):
        def body(c, _):
            out = scalar_fn(q + c.astype(q.dtype), k, v)
            return out.astype(jnp.float32) * 1e-30, None
        c, _ = lax.scan(body, c0, None, length=R_INNER)
        return c
    f = jax.jit(repeated)

    c = jnp.zeros((), jnp.float32)
    fetch_fence(f(q, k, v, c))           # compile + warm, fully drained
    t0 = time.perf_counter()
    for _ in range(N_CALLS):
        c = f(q, k, v, c)
    fetch_fence(c)
    return (time.perf_counter() - t0) / (N_CALLS * R_INNER)


def speedup_table(dtype=jnp.bfloat16, b=4, h=8, d=64):
    """fwd and fwd+bwd wall time, flash vs dense, causal, seq 512..4096."""
    rows = []
    for s in (512, 1024, 2048, 4096):
        q, k, v = _qkv(jax.random.PRNGKey(2), b, h, s, s, d, dtype)

        def fwd_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=False)
                           .astype(jnp.float32))

        def fwd_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True)
                           .astype(jnp.float32))

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=False)
                           .astype(jnp.float32) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True)
                           .astype(jnp.float32) ** 2)

        def grad_scalar(loss):
            g = jax.grad(loss, argnums=(0, 1, 2))

            def f(q, k, v):
                dq, dk, dv = g(q, k, v)
                return (jnp.sum(dq.astype(jnp.float32))
                        + jnp.sum(dk.astype(jnp.float32))
                        + jnp.sum(dv.astype(jnp.float32)))
            return f

        tf = _time_kernel(fwd_flash, q, k, v)
        td = _time_kernel(fwd_dense, q, k, v)
        tfg = _time_kernel(grad_scalar(loss_flash), q, k, v)
        tdg = _time_kernel(grad_scalar(loss_dense), q, k, v)
        # causal attention FLOPs: ~half the full 4*B*H*S^2*D (fwd, qk+pv)
        fwd_flops = 4 * b * h * s * s * d / 2
        rows.append({
            "seq": s,
            "flash_fwd_ms": tf * 1e3, "dense_fwd_ms": td * 1e3,
            "fwd_speedup": td / tf,
            "flash_fwdbwd_ms": tfg * 1e3, "dense_fwdbwd_ms": tdg * 1e3,
            "fwdbwd_speedup": tdg / tfg,
            "flash_fwd_tflops": fwd_flops / tf / 1e12,
        })
        print(f"S={s:5d}  fwd: flash {tf*1e3:7.2f}ms dense {td*1e3:7.2f}ms "
              f"({td/tf:4.2f}x)   fwd+bwd: flash {tfg*1e3:7.2f}ms "
              f"dense {tdg*1e3:7.2f}ms ({tdg/tfg:4.2f}x)")
    return rows


def main():
    # line-buffer stdout: the collector SIGKILLs a wedged stage at its
    # timeout, and a block-buffered pipe would lose every progress line
    # printed before the hang (the round-5 zero-output-timeout mode)
    sys.stdout.reconfigure(line_buffering=True)
    print("flash_attention_tpu: querying backend (first RPC)...")
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})")
    if dev.platform != "tpu":
        print(json.dumps({"error": "no TPU available", "device": str(dev)}))
        return 1
    ok = validate_numerics()
    rows = speedup_table()
    print("\n| seq | flash fwd (ms) | dense fwd (ms) | fwd speedup | "
          "flash f+b (ms) | dense f+b (ms) | f+b speedup |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['seq']} | {r['flash_fwd_ms']:.2f} | "
              f"{r['dense_fwd_ms']:.2f} | {r['fwd_speedup']:.2f}x | "
              f"{r['flash_fwdbwd_ms']:.2f} | {r['dense_fwdbwd_ms']:.2f} | "
              f"{r['fwdbwd_speedup']:.2f}x |")
    print(json.dumps({
        "metric": "flash_attention_fwdbwd_speedup_vs_dense_seq4096",
        "value": round(rows[-1]["fwdbwd_speedup"], 2),
        "unit": "x",
        "numerics_ok": ok,
        "device": dev.device_kind,
        "rows": [{k: (round(v, 3) if isinstance(v, float) else v)
                  for k, v in r.items()} for r in rows],
    }))
    return 0 if ok else 2


if __name__ == "__main__":
    raise SystemExit(main())
