"""Block-size sweep for the blockwise attention kernels — train AND
decode shapes from one driver.

The flash kernel's cost at moderate sequence lengths is dominated by
grid-step count (per-step fixed overhead + per-tile mask/stat VPU
work), not MXU time, so (block_q, block_k) is the first-order tuning
knob. This sweeps tilings per sequence length, timed with the amortized
scan-repeat method (see flash_attention_tpu._time_kernel) and prints
the best per seq — those become the kernel's dispatch-table defaults.

``--decode`` sweeps the DECODE page-scan instead
(ops/decode_attention.py): block length vs resident length over a long
slot pool, so the same table that picks the training tiles also picks
the serving page/block size (the decode kernel is shared by
serve/cache.py, serve/pages/ and both engines — docs/compute.md).

Usage: python benchmarks/flash_block_sweep.py [--fwdbwd | --decode]
"""

import itertools
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks.flash_attention_tpu import _qkv, _time_kernel
from distributed_pytorch_tpu.ops import flash_attention
from distributed_pytorch_tpu.ops.decode_attention import (
    blockwise_decode_attention, dense_decode_attention)


def sweep_decode(pool_len: int = 4096, n_slots: int = 8, h: int = 8,
                 h_kv: int = 4, d: int = 64) -> dict:
    """Decode page-scan point: ms/step per (block_len, resident_len)
    over a (n_slots, Hkv, pool_len, Dh) pool, plus the dense full-pool
    baseline per resident length. The right block length balances
    per-block loop overhead against wasted tail width — exactly the
    grid-step-vs-tile tradeoff of the training sweep, at decode shapes.
    """
    dtype = jnp.bfloat16
    scale = 1.0 / math.sqrt(d)
    key = jax.random.PRNGKey(3)
    q, k, v = _qkv(key, n_slots, h, 1, pool_len, d, dtype)
    k = k[:, :h_kv]
    v = v[:, :h_kv]
    table = {}
    for resident in (64, 512, pool_len):
        lengths = jnp.full((n_slots,), resident - 1, jnp.int32)
        rows = []
        for blk in (64, 128, 256, 512):

            def fn(q, k, v, _b=blk):
                return jnp.sum(blockwise_decode_attention(
                    q, k, v, lengths, scale=scale,
                    block_len=_b).astype(jnp.float32))

            try:
                t = _time_kernel(fn, q, k, v)
            except Exception as e:  # noqa: BLE001
                print(f"# decode res={resident} blk={blk}: "
                      f"{type(e).__name__}", file=sys.stderr, flush=True)
                continue
            rows.append({"block_len": blk, "ms": round(t * 1e3, 3)})
            print(f"# decode res={resident} blk={blk}: {t*1e3:.3f}ms",
                  file=sys.stderr, flush=True)

        def dense_fn(q, k, v):
            mask = jnp.arange(pool_len)[None, :] <= lengths[:, None]
            return jnp.sum(dense_decode_attention(
                q, k, v, mask, scale=scale).astype(jnp.float32))

        try:
            t = _time_kernel(dense_fn, q, k, v)
            dense_ms = round(t * 1e3, 3)
        except Exception as e:  # noqa: BLE001
            dense_ms = f"{type(e).__name__}"
        rows.sort(key=lambda r: r["ms"])
        table[resident] = {"dense_full_pool_ms": dense_ms, "arms": rows}
        print(f"# decode res={resident} best: "
              f"{json.dumps(rows[0]) if rows else 'ALL FAILED'} "
              f"(dense {dense_ms}ms)", flush=True)
    return {"mode": "decode", "pool_len": pool_len, "n_slots": n_slots,
            "best": {r: t["arms"][0] for r, t in table.items()
                     if t["arms"]},
            "all": table}


def main(argv):
    if "--decode" in argv:
        print(json.dumps(sweep_decode()))
        return 0
    grad_mode = "--fwdbwd" in argv
    b, h, d = 4, 8, 64
    dtype = jnp.bfloat16
    blocks = [128, 256, 512, 1024]
    table = {}
    for s in (512, 1024, 2048, 4096):
        q, k, v = _qkv(jax.random.PRNGKey(2), b, h, s, s, d, dtype)
        results = []
        for bq, bk in itertools.product(blocks, blocks):
            if bq > s or bk > s:
                continue

            def fwd(q, k, v, _bq=bq, _bk=bk):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, block_q=_bq, block_k=_bk,
                    interpret=False).astype(jnp.float32))

            if grad_mode:
                g = jax.grad(fwd, argnums=(0, 1, 2))
                fn = lambda q, k, v, _g=g: sum(
                    jnp.sum(x.astype(jnp.float32)) for x in _g(q, k, v))
            else:
                fn = fwd
            try:
                t = _time_kernel(fn, q, k, v)
            except Exception as e:  # noqa: BLE001 — VMEM overflow arms
                print(f"# s={s} bq={bq} bk={bk}: "
                      f"{type(e).__name__}", file=sys.stderr, flush=True)
                continue
            results.append({"bq": bq, "bk": bk, "ms": round(t * 1e3, 3)})
            print(f"# s={s} bq={bq} bk={bk}: {t*1e3:.3f}ms",
                  file=sys.stderr, flush=True)
        results.sort(key=lambda r: r["ms"])
        table[s] = results
        # stdout on purpose: the collector's timeout handler keeps the
        # stdout tail, so completed seq rows survive a mid-sweep SIGKILL
        print(f"# s={s} best: "
              f"{json.dumps(results[0]) if results else 'ALL FAILED'}",
              flush=True)
    print(json.dumps({"mode": "fwdbwd" if grad_mode else "fwd",
                      "best": {s: r[0] for s, r in table.items() if r},
                      "all": table}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
