"""On-chip block-size sweep for the flash attention kernel.

The kernel's cost at moderate sequence lengths is dominated by grid-step
count (per-step fixed overhead + per-tile mask/stat VPU work), not MXU
time, so (block_q, block_k) is the first-order tuning knob. This sweeps
tilings per sequence length, timed with the amortized scan-repeat method
(see flash_attention_tpu._time_kernel) and prints the best per seq —
those become the kernel's dispatch-table defaults.

Usage: python benchmarks/flash_block_sweep.py [--fwdbwd]
"""

import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks.flash_attention_tpu import _qkv, _time_kernel
from distributed_pytorch_tpu.ops import flash_attention


def main(argv):
    grad_mode = "--fwdbwd" in argv
    b, h, d = 4, 8, 64
    dtype = jnp.bfloat16
    blocks = [128, 256, 512, 1024]
    table = {}
    for s in (512, 1024, 2048, 4096):
        q, k, v = _qkv(jax.random.PRNGKey(2), b, h, s, s, d, dtype)
        results = []
        for bq, bk in itertools.product(blocks, blocks):
            if bq > s or bk > s:
                continue

            def fwd(q, k, v, _bq=bq, _bk=bk):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, block_q=_bq, block_k=_bk,
                    interpret=False).astype(jnp.float32))

            if grad_mode:
                g = jax.grad(fwd, argnums=(0, 1, 2))
                fn = lambda q, k, v, _g=g: sum(
                    jnp.sum(x.astype(jnp.float32)) for x in _g(q, k, v))
            else:
                fn = fwd
            try:
                t = _time_kernel(fn, q, k, v)
            except Exception as e:  # noqa: BLE001 — VMEM overflow arms
                print(f"# s={s} bq={bq} bk={bk}: "
                      f"{type(e).__name__}", file=sys.stderr, flush=True)
                continue
            results.append({"bq": bq, "bk": bk, "ms": round(t * 1e3, 3)})
            print(f"# s={s} bq={bq} bk={bk}: {t*1e3:.3f}ms",
                  file=sys.stderr, flush=True)
        results.sort(key=lambda r: r["ms"])
        table[s] = results
        # stdout on purpose: the collector's timeout handler keeps the
        # stdout tail, so completed seq rows survive a mid-sweep SIGKILL
        print(f"# s={s} best: "
              f"{json.dumps(results[0]) if results else 'ALL FAILED'}",
              flush=True)
    print(json.dumps({"mode": "fwdbwd" if grad_mode else "fwd",
                      "best": {s: r[0] for s, r in table.items() if r},
                      "all": table}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
