"""Flagship single-chip benchmark: TransformerLM tokens/sec and MFU.

The reference repo's implicit benchmark is a 1->32->4 MLP whose steps/s
measures dispatch overhead, not accelerator compute (see BASELINE.md). The
number the "matching-or-beating on perf" bar is judged on is this one: a
GPT-2-small-class causal LM (>=100M params, seq 1024, bfloat16, flash
attention) trained single-chip, reported as tokens/s and **MFU** =
achieved model FLOP/s / chip peak bf16 FLOP/s.

Model FLOPs use the standard analytic count (matmul FLOPs only, causal
attention at half the S^2 term, backward = 2x forward); XLA's own cost
model (utils/profiler.compiled_stats) is reported alongside as a
cross-check. Peak FLOP/s per chip generation is tabled below from public
spec sheets.

Usage: python benchmarks/mfu_transformer.py             (flagship, ~135M)
       python benchmarks/mfu_transformer.py --small     (CI-sized smoke)
       python benchmarks/mfu_transformer.py --sweep     (batch/remat/fused-CE arms)
       python benchmarks/mfu_transformer.py --model medium   (~355M arm)
       python benchmarks/mfu_transformer.py --model long     (seq 4096 arm)
       python benchmarks/mfu_transformer.py --host-flagship  (pinned host
           arm vs the CALIBRATED host peak — bench.py's no-TPU fallback;
           docs/compute.md)
       flags: --batch N --steps N --remat --fused-ce --no-fused-ce
              --no-remat --master-f32 --remat-policy none|full|dots_saveable
              --mp off|bf16
       (--sweep isolates each arm in a subprocess with a per-arm
       timeout and probes the backend between arms, unless
       JAX_PLATFORMS=cpu)
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# Public peak dense-matmul throughput per chip, bf16, FLOP/s.
# (v5 lite == v5e. The axon tunnel reports device_kind "TPU v5 lite".)
PEAK_BF16 = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,           # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,      # Trillium / v6e
    "TPU v6e": 918e12,
}
# Note: only the generations we can actually run on matter for the judged
# number; "TPU v5 lite" (v5e, 197 TFLOP/s bf16) is the chip in this
# environment. Others are best-effort from cloud.google.com spec pages.


# The flagship single-chip benchmark config (GPT-2-small class). bench.py
# measures its torch-CPU baseline from THESE constants — change them here
# and every consumer (run() defaults, the vs_baseline denominator) follows.
# The arm flags (fused_ce/remat/master_f32) are part of the flagship
# identity too: run() defaults to them, so promoting a sweep winner to
# flagship is a one-dict edit picked up by every consumer (bench.py
# --stage mfu and mfu_medium, the CLI default path, the roofline join).
# Sweep arms are immune on purpose: they pin every arm flag explicitly
# so the recorded arm labels always describe what ran.
FLAGSHIP = {"dim": 768, "n_layers": 12, "n_heads": 12, "vocab": 32000,
            "seq": 1024, "batch": 8,
            "fused_ce": False, "remat": False, "master_f32": False}
ARM_FLAGS = ("fused_ce", "remat", "master_f32")
# GPT-2-medium class (~355M params): bigger matmuls -> higher attainable
# MFU; an additional reporting arm (--model medium), never the headline.
MEDIUM = {"dim": 1024, "n_layers": 24, "n_heads": 16, "vocab": 32000,
          "seq": 1024, "batch": 8}
# Mid tier (~60M params, --model mid): between the CI-sized smoke and the
# flagship. Exists for the flaky-tunnel bracket: if flagship-scale
# compiles wedge the tunnel, this still lands a meaningful MXU number
# and brackets the wedge threshold (smoke 0.5M -> mid 60M -> 135M).
MID = {"dim": 512, "n_layers": 8, "n_heads": 8, "vocab": 32000,
       "seq": 1024, "batch": 8}
# Long-context arm (--model long): flagship model at seq 4096 — the
# regime the flash kernel was tuned for (8.5x vs dense at this seq,
# BASELINE.md). Same 8192 tokens/step as the flagship; remat + fused-CE
# default on (the (B,S,vocab) logits alone would be 1 GiB f32).
LONGCTX = {"dim": 768, "n_layers": 12, "n_heads": 12, "vocab": 32000,
           "seq": 4096, "batch": 2}
# The pinned HOST flagship (--model host / bench.py's no-TPU fallback):
# a config a 1-core container measures in minutes, with the COMPOSED
# compute-path recipe as its identity — f32 master + bf16 mixed
# precision (DPX_MP_POLICY semantics), dots_saveable remat, donation,
# flash attn_fn (which honestly dispatches dense below the crossover at
# this seq). MFU for this arm is achieved FLOP/s over the MEASURED host
# matmul peak (calibrate_host), so the headline is a real fraction of
# what this machine can do — never a spec-sheet fiction. Pinned like
# FLAGSHIP: comparability across rounds is the point.
FLAGSHIP_CPU = {"dim": 256, "n_layers": 4, "n_heads": 4, "vocab": 4096,
                "seq": 256, "batch": 8,
                "fused_ce": False, "remat": "dots_saveable",
                "master_f32": False, "mp": "bf16"}


def calibrate_host(n: int = 1024, reps: int = 5,
                   copy_mb: int = 64) -> dict:
    """Measured compute/memory peaks of THIS host, for MFU and roofline
    normalization on devices without a spec-sheet entry (CPU
    containers). Peak FLOP/s = best-of-``reps`` timed ``n``x``n`` f32
    XLA matmul (the same compiler the workload runs under); memory
    bytes/s = best-of timed large numpy copy (2x buffer bytes per
    pass). Both are *achievable* peaks — an MFU of 1.0 against them
    means "as fast as this host's own best matmul", the honest analog
    of the chip spec sheets in ``PEAK_BF16``."""
    import time as _time

    a = jnp.asarray(np.random.default_rng(0).standard_normal((n, n)),
                    jnp.float32)
    f = jax.jit(lambda a: a @ a)
    np.asarray(f(a))  # compile + first run
    best = float("inf")
    for _ in range(reps):
        t0 = _time.perf_counter()
        np.asarray(f(a))
        best = min(best, _time.perf_counter() - t0)
    peak_flops = 2 * n ** 3 / best

    src = np.ones(copy_mb * (1 << 20) // 8, np.float64)
    dst = np.empty_like(src)
    best_bw = float("inf")
    for _ in range(reps):
        t0 = _time.perf_counter()
        np.copyto(dst, src)
        best_bw = min(best_bw, _time.perf_counter() - t0)
    mem_bytes_per_s = 2 * src.nbytes / best_bw
    return {"method": f"xla f32 {n}^3 matmul + numpy memcpy, "
                      f"best of {reps}",
            "matmul_n": n,
            "peak_flops": peak_flops,
            "mem_bytes_per_s": mem_bytes_per_s}


def model_flops_per_token(dim: int, n_layers: int, vocab: int, seq: int,
                          mlp_ratio: int = 4, causal: bool = True) -> float:
    """Analytic matmul FLOPs per token, forward pass.

    Per layer: qkv (6d^2) + out-proj (2d^2) + mlp (2*2*r*d^2) per token,
    plus attention score/value matmuls 4*S*d per token (halved when
    causal). Final vocab projection 2*d*V. Embedding lookups are gathers,
    not matmuls — excluded, as is standard for MFU accounting.
    """
    per_layer = (8 + 4 * mlp_ratio) * dim * dim
    attn = 4 * seq * dim * (0.5 if causal else 1.0)
    return n_layers * (per_layer + attn) + 2 * dim * vocab


def count_params(params) -> int:
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(params))


def run(dim: int = FLAGSHIP["dim"], n_layers: int = FLAGSHIP["n_layers"],
        n_heads: int = FLAGSHIP["n_heads"], vocab: int = FLAGSHIP["vocab"],
        seq: int = FLAGSHIP["seq"], batch: int = FLAGSHIP["batch"],
        steps: int = 30, dtype=jnp.bfloat16,
        remat=FLAGSHIP["remat"],
        use_flash: bool = True, fused_ce: bool = FLAGSHIP["fused_ce"],
        master_f32: bool = FLAGSHIP["master_f32"],
        mp: str = "off", runs: int = 1,
        interpret: Optional[bool] = None) -> dict:
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ops import make_flash_attn_fn
    from distributed_pytorch_tpu.ops.flash_attention import FLASH_MIN_SEQ
    from distributed_pytorch_tpu.ops.losses import (
        cross_entropy, fused_linear_cross_entropy)
    from distributed_pytorch_tpu.parallel import make_train_step
    from distributed_pytorch_tpu.utils.profiler import (
        StepTimer, compiled_stats, fetch_fence, time_steps_amortized)

    def phase(msg):
        # "#"-prefixed stdout so (a) the last-line-JSON contract holds and
        # (b) a tunnel wedge mid-run leaves the reached phase in the
        # collector's kept stdout tail — the round-3/round-5 flagship
        # hangs died with zero output, undiagnosable
        print(f"# mfu phase: {msg}", flush=True)

    # two lines on purpose: jax.devices() is the first backend RPC and
    # can hang on a wedged tunnel — the config must already be on stdout
    phase(f"start dim={dim} L={n_layers} batch={batch} seq={seq}")
    phase(f"backend device={jax.devices()[0].device_kind}")
    attn_fn = make_flash_attn_fn(interpret=interpret) \
        if use_flash else None
    model = models.TransformerLM(vocab=vocab, dim=dim, n_layers=n_layers,
                                 n_heads=n_heads, max_seq=seq,
                                 attn_fn=attn_fn, remat=remat, dtype=dtype)
    params = model.init(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    phase("params initialized on device")
    n_params = count_params(params)
    opt = optim.adamw(3e-4)
    if master_f32:
        # authoritative f32 copy updated by the inner optimizer; working
        # bf16 params are its cast (the matmuls stay bf16). Perf cost =
        # the extra f32 param stream per step; numerics gain = no stalled
        # late-training updates (optim/schedules.py:with_master_f32)
        opt = optim.with_master_f32(opt)
    opt_state = opt.init(params)

    if fused_ce:
        # stream the vocab projection chunkwise — the (B, S, vocab) logits
        # (1 GiB f32 at the flagship config) never materialize, freeing
        # HBM for batch (ops/losses.py:fused_linear_cross_entropy)
        def loss_fn(p, tokens):
            hid = model.apply(p, tokens[:, :-1], return_hidden=True)
            return fused_linear_cross_entropy(
                hid, model.head_weight(p), tokens[:, 1:]), {}
    else:
        def loss_fn(p, tokens):
            logits = model.apply(p, tokens[:, :-1]).astype(jnp.float32)
            return cross_entropy(logits, tokens[:, 1:]), {}

    # mp="bf16": f32 master + bf16 compute cast inside the step (the
    # DPX_MP_POLICY recipe, docs/compute.md) — composes with donation,
    # remat policies and the flash core; distinct from master_f32,
    # which keeps bf16 params and hides the f32 master in opt state
    step = make_train_step(loss_fn, opt, donate=True, mixed_precision=mp)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1),
                                0, vocab, dtype=jnp.int32)

    # Headline timing: an amortized data-dependent chain with ONE host
    # materialization at the end. On the tunneled backend here,
    # block_until_ready can resolve on enqueue (benchmarks/fence_probe.py),
    # which once produced a physically impossible 4.4 "MFU"; fetching the
    # final loss transitively waits for all n steps and cannot lie.
    out = step(params, opt_state, tokens)          # compile
    fetch_fence(out.loss)
    phase("train step compiled + first step fetched")
    for _ in range(2):                             # cache warming
        out = step(out.params, out.opt_state, tokens)
    fetch_fence(out.loss)
    phase(f"warm; timing {steps} chained steps x {runs} run(s)")
    step_runs = []
    for _ in range(max(runs, 1)):
        step_s, out = time_steps_amortized(
            lambda o: step(o.params, o.opt_state, tokens), out, steps,
            lambda o: o.loss)
        step_runs.append(step_s)
    # median of warm chains (runs=1 keeps the historical single-chain
    # behavior); the per-run list travels with the record so perfbench
    # can apply its spread gate to the trials
    step_s = float(np.median(step_runs))

    tok_per_step = batch * seq
    tokens_per_sec = tok_per_step / step_s
    fwd_fpt = model_flops_per_token(dim, n_layers, vocab, seq)
    train_flops_per_step = 3 * fwd_fpt * tok_per_step   # bwd = 2x fwd
    achieved = train_flops_per_step / step_s

    dev = jax.devices()[0]
    peak = PEAK_BF16.get(dev.device_kind)
    peak_source, calibration = "spec_sheet", None
    if peak is None and dev.platform == "cpu":
        # no spec-sheet entry: normalize against the MEASURED host peak
        # so the headline is a real fraction of this machine's best
        # matmul rather than a null (docs/compute.md)
        phase("calibrating host peak (no spec entry for this device)")
        calibration = calibrate_host()
        peak = calibration["peak_flops"]
        peak_source = "calibrated_host"
    mfu = achieved / peak if peak else None
    # the measurement exists NOW — put it in the stdout tail before the
    # diagnostics below, so a wedge in them cannot lose the headline
    phase(f"MEASURED step_ms={step_s * 1e3:.3f} "
          f"tokens_per_sec={tokens_per_sec:.1f} "
          f"mfu={mfu if mfu is None else round(mfu, 4)}")

    # XLA's own FLOP count for one step (cross-check; includes remat /
    # non-matmul work, so it can exceed the analytic model count). After
    # the headline timing on purpose: it is a second full compile, and on
    # the tunneled backend any extra RPC is a chance to wedge.
    try:
        xla_flops = compiled_stats(
            lambda p, o, t: step(p, o, t), params, opt_state, tokens
        ).get("flops", 0.0)
    except Exception:
        xla_flops = 0.0
    phase("cost-model cross-check done")

    # diagnostic: per-step latency with a host-fetch fence each step —
    # includes one tunnel round trip per step, so it upper-bounds the
    # true step latency (the gap vs the amortized number is the RTT)
    lat = StepTimer(warmup=1, fetch=True)
    for _ in range(5 + lat.warmup):
        with lat.step() as h:
            out = step(out.params, out.opt_state, tokens)
            h["fence"] = out.loss
    lat_summ = lat.summary()
    return {
        "device": dev.device_kind,
        "platform": dev.platform,
        "config": {"dim": dim, "n_layers": n_layers, "n_heads": n_heads,
                   "vocab": vocab, "seq": seq, "batch": batch,
                   "dtype": str(jnp.dtype(dtype).name),
                   # the attn_fn dispatches dense below the measured
                   # crossover — report what actually ran
                   "attention": ("flash" if seq >= FLASH_MIN_SEQ
                                 else "dense(flash-crossover)")
                   if use_flash else "dense",
                   "remat": model.remat_policy, "fused_ce": fused_ce,
                   "mp": mp, "master_f32": master_f32,
                   "optimizer": "adamw+master_f32" if master_f32
                   else "adamw"},
        "n_params": n_params,
        "steps_timed": steps,
        "timing_method": "amortized_chain_fetch_fence",
        "step_ms_median": round(step_s * 1e3, 3),
        "per_step_fetch_fenced_ms_median": round(
            lat_summ["median_s"] * 1e3, 3),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "model_tflops_per_step": round(train_flops_per_step / 1e12, 3),
        "achieved_tflops_per_sec": round(achieved / 1e12, 2),
        "xla_cost_model_tflops_per_step": round(xla_flops / 1e12, 3)
        if xla_flops else None,
        "peak_bf16_tflops": peak / 1e12 if peak else None,
        "peak_source": peak_source,
        **({"calibration": calibration} if calibration else {}),
        **({"step_ms_runs": [round(s * 1e3, 3) for s in step_runs],
            "mfu_runs": [round(train_flops_per_step / s / peak, 4)
                         for s in step_runs]}
           if runs > 1 and peak else {}),
        "mfu": round(mfu, 4) if mfu is not None else None,
        # hardware-FLOPs companion (counts recompute): XLA's cost model
        # measures the HLO actually executed, remat included, so remat
        # arms aren't artificially dinged by the model-FLOPs-only MFU
        "mfu_hw": round(xla_flops / step_s / peak, 4)
        if (xla_flops and peak) else None,
    }


def run_host_flagship(steps: int = 8, runs: int = 5) -> dict:
    """The pinned host flagship arm (``FLAGSHIP_CPU``): the composed
    compute-path recipe — f32 master + bf16 mixed precision +
    dots_saveable remat + donated step buffers + the flash attn_fn
    (dense below the crossover at this seq) — measured as ``runs``
    warm amortized chains so perfbench can gate the spread, against
    the calibrated host peak. bench.py's no-TPU fallback: a fresh
    gated measurement instead of an eternal carry-forward."""
    cfg = {k: FLAGSHIP_CPU[k] for k in ("dim", "n_layers", "n_heads",
                                        "vocab", "seq", "batch")}
    return run(steps=steps, runs=runs, dtype=jnp.float32,
               mp=FLAGSHIP_CPU["mp"], remat=FLAGSHIP_CPU["remat"],
               fused_ce=FLAGSHIP_CPU["fused_ce"],
               master_f32=FLAGSHIP_CPU["master_f32"], **cfg)


def _flag_val(argv, flag, default, cast=int):
    if flag in argv:
        i = argv.index(flag)
        if i + 1 < len(argv):
            return cast(argv[i + 1])
    return default


def _arm_argv(arm) -> list:
    """Round-trip a sweep arm dict into CLI flags (subprocess mode).

    Every arm flag is passed EXPLICITLY (--fused-ce or --no-fused-ce,
    never absent): an absent flag would fall back to the FLAGSHIP
    default in the child, so after a flagship promotion the arm label
    would no longer describe what ran."""
    unknown = set(arm) - ({"batch"} | set(ARM_FLAGS))
    if unknown:
        raise ValueError(f"sweep arm has no CLI mapping for {unknown}")
    argv = []
    if "batch" in arm:
        argv += ["--batch", str(arm["batch"])]
    for key, flag in (("fused_ce", "--fused-ce"), ("remat", "--remat"),
                      ("master_f32", "--master-f32")):
        argv.append(flag if arm.get(key)
                    else flag.replace("--", "--no-", 1))
    return argv


def sweep(arms=None, steps: int = 20,
          isolate: Optional[bool] = None) -> dict:
    """Try several (batch, remat, fused_ce) arms and report the best MFU.

    An arm that OOMs (or otherwise dies) is recorded with its error and
    skipped — finding the HBM cliff is part of the sweep's job.

    ``isolate`` (default: auto — on unless JAX_PLATFORMS=cpu) runs each
    arm as its own subprocess with a hard per-arm timeout and probes the
    backend between arms: on the tunneled TPU here a wedge mid-arm would
    otherwise hang the WHOLE sweep until the collector's outer timeout,
    losing every later arm — per-arm isolation caps the damage at one
    arm and keeps collecting if the tunnel recovers (probe gate aborts
    early when it doesn't, leaving the per-arm records)."""
    if isolate is None:
        from distributed_pytorch_tpu.runtime import env as _envreg
        isolate = (_envreg.get("JAX_PLATFORMS") or "") != "cpu"
    if arms is None:
        arms = [dict(batch=8), dict(batch=8, fused_ce=True),
                dict(batch=8, fused_ce=True, master_f32=True),
                dict(batch=16, fused_ce=True),
                # no-remat large-batch arms: fused-CE never materializes
                # the (B,S,vocab) logits, so batch 32 may fit in 16 GiB
                # HBM without remat — remat arms pay ~0.1 MFU of
                # uncounted recompute, so a fitting no-remat arm should
                # dominate (round-3 sweep only ever ran 32/64 with remat)
                dict(batch=32, fused_ce=True),
                dict(batch=16, fused_ce=True, master_f32=True),
                dict(batch=16, fused_ce=True, remat=True),
                dict(batch=32, fused_ce=True, remat=True),
                dict(batch=64, fused_ce=True, remat=True)]
    results, best = [], None
    for arm in arms:
        label = json.dumps(arm, sort_keys=True)
        rec, err, extra = None, None, {}
        if isolate:
            import bench  # repo root is on sys.path (module preamble)
            if not bench.probe_backend():
                results.append({"arm": arm, "error":
                                "backend wedged; sweep aborted early"})
                print(f"# arm {label}: {json.dumps(results[-1])}",
                      flush=True)
                break
            try:
                argv = _arm_argv(arm)
            except ValueError as e:
                results.append({"arm": arm, "error": str(e)})
                print(f"# arm {label}: {json.dumps(results[-1])}",
                      flush=True)
                continue
            payload = bench.run_json_subprocess(
                [sys.executable, os.path.abspath(__file__),
                 "--steps", str(steps)] + argv,
                900, label=f"sweep arm {label}", keep_stdout_tail=True)
            if payload.get("mfu") is not None \
                    or payload.get("tokens_per_sec") is not None:
                # a record was printed: keep the measurements. Strip the
                # error/rc a nonzero exit AFTER printing would add — a
                # top-level "error" key would mark the whole sweep stage
                # failed in the collector and burn a ~3h retry on data
                # already collected — but surface it on the arm row.
                rec = dict(payload)
                arm_err = rec.pop("error", None)
                arm_rc = rec.pop("rc", None)
                if arm_err is not None:
                    extra = {"arm_error": str(arm_err)[:300],
                             "arm_rc": arm_rc}
            else:
                err = str(payload.get("error", "no record"))[:300]
                # keep the child's per-phase progress lines — they show
                # WHERE a wedged arm hung (the whole point of phase())
                for k in ("stdout_tail", "stderr_tail"):
                    if payload.get(k):
                        extra[k] = str(payload[k])[-500:]
        else:
            try:
                # arm flags pinned explicitly (False unless the arm sets
                # them) — mirrors _arm_argv's explicit on/off flags, so
                # both isolation modes measure the same grid even after
                # a flagship promotion changes run()'s defaults
                rec = run(steps=steps,
                          **{**{k: False for k in ARM_FLAGS}, **arm})
            except Exception as e:  # noqa: BLE001 — OOM arms expected
                err = f"{type(e).__name__}: {str(e)[:300]}"
        if rec is not None:
            results.append({"arm": arm, "mfu": rec["mfu"],
                            "tokens_per_sec": rec["tokens_per_sec"],
                            "step_ms_median": rec["step_ms_median"],
                            **extra})
            if best is None or (rec["mfu"] or 0) > (best["mfu"] or 0):
                best = rec
        else:
            results.append({"arm": arm, "error": err, **extra})
        # stdout on purpose: the collector's timeout handler keeps the
        # stdout tail, so completed arms survive a mid-sweep SIGKILL
        # ("#" lines don't disturb the parse-last-line-as-JSON contract)
        print(f"# arm {label}: {json.dumps(results[-1])}", flush=True)
    out = dict(best or {"error": "every sweep arm failed"})
    out["sweep"] = results
    return out


def _tristate(argv, flag):
    """--flag -> True, --no-flag -> False, absent -> None (= defer to
    run()'s defaults, i.e. the FLAGSHIP arm-flag identity)."""
    if flag in argv:
        return True
    if flag.replace("--", "--no-", 1) in argv:
        return False
    return None


def main(argv):
    tri = {"remat": _tristate(argv, "--remat"),
           "fused_ce": _tristate(argv, "--fused-ce"),
           "master_f32": _tristate(argv, "--master-f32")}
    explicit = {k: v for k, v in tri.items() if v is not None}
    # named compute-path knobs (docs/compute.md): --remat-policy
    # overrides the boolean --remat tristate with a named policy;
    # --mp off|bf16 selects the mixed-precision mode
    if (pol := _flag_val(argv, "--remat-policy", None, str)) is not None:
        explicit["remat"] = pol
    if (mp := _flag_val(argv, "--mp", None, str)) is not None:
        explicit["mp"] = mp
    batch = _flag_val(argv, "--batch", None)
    steps = _flag_val(argv, "--steps", None)  # sweep arms pass their own
    if "--host-flagship" in argv:
        print(json.dumps(run_host_flagship(
            **({"steps": steps} if steps else {}))))
        return 0
    if "--sweep" in argv:
        if explicit or batch:
            print("# --sweep runs its own fixed arm grid; --batch/--remat/"
                  "--fused-ce/--master-f32 are ignored (--steps is "
                  "honored)", file=sys.stderr)
        rec = sweep(**({"steps": steps} if steps else {}))
    elif "--small" in argv:
        # CI-sized smoke: arm flags explicit-off unless flagged — the
        # flagship recipe is irrelevant at this scale
        rec = run(dim=128, n_layers=2, n_heads=4, vocab=512, seq=256,
                  batch=batch or 4, steps=5,
                  **{k: tri[k] or False for k in tri})
    elif (model := _flag_val(argv, "--model", "flagship", str)) != "flagship":
        if model == "medium":
            cfg = dict(MEDIUM)
            arm = dict(explicit)  # unflagged -> flagship recipe
        elif model == "mid":
            cfg = dict(MID)
            arm = dict(explicit)
        elif model == "long":
            cfg = dict(LONGCTX)
            # remat + fused-CE on unless explicitly overridden: at seq
            # 4096 the logits and per-layer activations dominate HBM
            arm = dict(remat=tri["remat"] is not False,
                       fused_ce=tri["fused_ce"] is not False,
                       master_f32=tri["master_f32"] or False)
        else:
            print(json.dumps({"error": f"unknown --model {model!r} "
                              "(choices: mid, medium, long)"}))
            return 2
        if batch:
            cfg["batch"] = batch
        rec = run(steps=steps or 20, **arm, **cfg)
    else:
        # the flagship path: unflagged arm flags defer to run()'s
        # defaults — the FLAGSHIP dict — so a promotion changes this
        # path and bench.py --stage mfu identically
        rec = run(**explicit,
                  **({"batch": batch} if batch else {}),
                  **({"steps": steps} if steps else {}))
    # one compact line: collectors parse the last stdout line as JSON
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
