"""Render the measured-results section from the raw records log.

BASELINE.md's rule (round 4 on) is that prose tables are regenerated
from `benchmarks/tpu_results.jsonl` — this is the regenerator. It reads
every non-retracted `ok` row, keeps the NEWEST record per stage, and
prints a markdown summary ready to paste into BASELINE.md (plus one JSON
line for tooling). Retracted rows are listed by stage + reason so the
retraction trail stays visible.

Usage: python benchmarks/report.py [--log FILE] [--write-baseline]
       [--trace-log FILE]

--trace-log renders the dpxtrace observability section from a span log
(per-op per-rank duration summary + the k*IQR straggler verdict —
docs/observability.md), appended after the measured-results section.

--write-baseline splices the rendered section into BASELINE.md between
the BEGIN/END MEASURED AUTO markers (the watcher runs this after every
pass that lands a stage, so fresh evidence reaches BASELINE.md on disk
even when no one is at the keyboard).

Reading the store goes through perfbench (``record.iter_rows``), so
malformed lines are surfaced as comments instead of silently skipped,
and the newest schema record renders a gated-metrics table — value,
spread (IQR/median), trial count, trusted — with withheld
``vs_baseline`` rows carrying their reason instead of going blank
(docs/benchmarking.md).
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
DEFAULT_LOG = os.path.join(REPO, "benchmarks", "tpu_results.jsonl")


_PB_RECORD = None

#: Private root the file-based loader fabricates modules under. ONE
#: root for everything report.py loads (perfbench AND obs), so shared
#: dependencies (obs.detect -> ..perfbench.stats) resolve to a single
#: module instance instead of loading twice under separate roots.
_PRIVATE_ROOT = "_report_dpx"


def _load_private(modules):
    """Load package modules file-based under :data:`_PRIVATE_ROOT`,
    WITHOUT importing the real package: run_all_tpu's watcher shells
    out to report.py on a 60s budget precisely because report is
    jax-free and cannot hang on a wedged tunnel — the heavy package
    ``__init__`` (api → jax) must never be pulled here, and the genuine
    package must be neither imported nor shadowed.

    ``modules`` is an ordered sequence of ``(pkg, sub)`` pairs (the
    dependency order matters: errors → stats → record); already-loaded
    names are reused. Returns the loaded modules, in order."""
    import importlib.util
    import types

    pkg_dir = os.path.join(REPO, "distributed_pytorch_tpu")
    if _PRIVATE_ROOT not in sys.modules:
        root = types.ModuleType(_PRIVATE_ROOT)
        root.__path__ = [pkg_dir]
        sys.modules[_PRIVATE_ROOT] = root
    out = []
    for pkg, sub in modules:
        parent = f"{_PRIVATE_ROOT}.{pkg}"
        if parent not in sys.modules:
            mod = types.ModuleType(parent)
            mod.__path__ = [os.path.join(pkg_dir, pkg)]
            sys.modules[parent] = mod
        name = f"{parent}.{sub}"
        if name not in sys.modules:
            spec = importlib.util.spec_from_file_location(
                name, os.path.join(pkg_dir, pkg, sub + ".py"))
            mod = importlib.util.module_from_spec(spec)
            sys.modules[name] = mod
            spec.loader.exec_module(mod)
        out.append(sys.modules[name])
    return out


def _perfbench_record():
    """The perfbench record module: the real one when already imported
    (in-process test use), else file-based under the private root."""
    global _PB_RECORD
    if _PB_RECORD is not None:
        return _PB_RECORD
    real = sys.modules.get("distributed_pytorch_tpu.perfbench.record")
    if real is not None:
        _PB_RECORD = real
        return _PB_RECORD
    *_, _PB_RECORD = _load_private(
        [("perfbench", "errors"), ("perfbench", "stats"),
         ("perfbench", "record")])
    return _PB_RECORD


def load_rows_checked(path):
    """(rows, malformed) via perfbench's one store reader — malformed
    is [(1-based line, reason), ...], surfaced by main() as comments."""
    return _perfbench_record().iter_rows(path)


def load_rows(path):
    return load_rows_checked(path)[0]


def latest_per_stage(rows):
    """Newest non-retracted ok row per stage (file order = time order)."""
    out = {}
    for r in rows:
        if r.get("ok") and not r.get("retracted"):
            out[r.get("stage", "?")] = r
    return out


def _truncate_words(s: str, cap: int = 200) -> str:
    """Cap a free-text reason at a WORD boundary with an ellipsis —
    the retraction reasons run ~120 chars and the old hard [:100] cut
    them mid-word in the regenerated BASELINE.md (ADVICE round 5)."""
    s = str(s)
    if len(s) <= cap:
        return s
    cut = s[:cap].rsplit(None, 1)[0] if " " in s[:cap] else s[:cap]
    return cut + "…"


def _fmt(v, nd=3):
    if isinstance(v, float):
        s = f"{v:.{nd}f}"
        return s.rstrip("0").rstrip(".") if "." in s else s
    return str(v)


def newest_schema_record(rows):
    """Newest non-retracted row carrying a perfbench schema record —
    including not-ok rows: a carry-forward headline is logged ok=False
    (it must never become a future last_good) but its provenance and
    withheld vs_baseline are exactly what the report must show."""
    schema = _perfbench_record().SCHEMA
    best = None
    for r in rows:
        if r.get("retracted"):
            continue
        res = r.get("result", {})
        if isinstance(res, dict) and res.get("schema") == schema:
            best = r
    return best


def render_gated(row):
    """The gated-metrics section of one schema record: headline
    provenance/trust, vs_baseline or its withhold reason (never a
    silent blank), and the per-metric spread/IQR/trusted table."""
    res = row["result"]
    lines = ["", f"### Gated metrics (stage {row.get('stage', '?')}, "
             f"{row.get('ts') or res.get('ts', '?')}; perfbench "
             "spread-gate policy — docs/benchmarking.md)", ""]
    if "value" in res:
        head = (f"Headline `{res.get('metric')}` = "
                f"**{_fmt(float(res['value']), 4)}** {res.get('unit')}, "
                f"provenance **{res.get('provenance')}**")
        lg = res.get("last_good")
        if res.get("provenance") == "last_good" and isinstance(lg, dict):
            head += (f" (carried forward from stage {lg.get('stage')}, "
                     f"{lg.get('ts', '?')})")
        lines.append(head + ".")
    if not res.get("trusted"):
        lines.append(f"**UNTRUSTED**: "
                     f"{_truncate_words(res.get('untrusted_reason', '?'))}")
    if "vs_baseline" in res:
        lines.append(f"vs_baseline: **{_fmt(float(res['vs_baseline']))}x**"
                     " (both sides passed the spread gate).")
    elif "vs_baseline_withheld" in res:
        lines.append(f"vs_baseline **withheld**: "
                     f"{_truncate_words(res['vs_baseline_withheld'])}")
    metrics = res.get("metrics") or {}
    if metrics:
        lines += ["", "| metric | value | unit | spread (IQR/med) | "
                  "trials | trusted |", "|---|---|---|---|---|---|"]
        for name in sorted(metrics):
            b = metrics[name]
            if not isinstance(b, dict):
                continue
            spread = (f"{b['spread_frac']:.1%}"
                      if isinstance(b.get("spread_frac"), (int, float))
                      else "n/a")
            n = (b.get("trials") or {}).get("n_trials", 1)
            if b.get("trusted"):
                trust = ("yes" if b.get("provenance") == "measured"
                         else f"yes ({b.get('provenance')})")
            else:
                trust = ("no: " + _truncate_words(
                    b.get("untrusted_reason", "?"), 80))
            val = (_fmt(float(b["value"]), 4)
                   if isinstance(b.get("value"), (int, float)) else "n/a")
            lines.append(f"| {name} | {val} | {b.get('unit', '?')} | "
                         f"{spread} | {n} | {trust} |")
    return lines


def render(rows) -> str:
    live = latest_per_stage(rows)
    lines = ["## Measured (regenerated from benchmarks/tpu_results.jsonl)",
             ""]
    if not live:
        lines.append("*(no non-retracted successful records on file)*")

    def res(stage):
        return live.get(stage, {}).get("result", {})

    if "bench_mfu" in live:
        src_stage = "bench_mfu"
        mfu = res("bench_mfu")
    else:
        src_stage = ("bench_headline" if "bench_headline" in live
                     else "bench_record")
        mfu = res(src_stage).get("mfu_detail", {})
    med = res("bench_mfu_medium")
    lng = res("mfu_long")
    mid = res("mfu_mid")
    # the metric table starts whenever ANY MFU row exists — a round where
    # the flagship stage wedged but medium/long landed still renders
    if any(r.get("mfu") is not None for r in (mfu, med, lng, mid)):
        lines += ["| Metric | Value | Source row |", "|---|---|---|"]
        if mfu.get("mfu") is not None:
            c = mfu.get("config", {})
            src = (f"stage {src_stage}, "
                   f"{live.get(src_stage, {}).get('ts', '?')}")
            lines += [
                f"| **Flagship MFU** | **{_fmt(mfu['mfu'], 4)}** "
                f"({_fmt(mfu.get('achieved_tflops_per_sec', 0), 1)} of "
                f"{_fmt(mfu.get('peak_bf16_tflops', 0), 0)} peak TF/s) | "
                f"{src} |",
                f"| Flagship tokens/s | "
                f"{_fmt(mfu.get('tokens_per_sec', 0))} "
                f"(step {_fmt(mfu.get('step_ms_median', 0))} ms, "
                f"batch {c.get('batch')}, seq {c.get('seq')}) | same |",
            ]
        if med.get("mfu") is not None:
            lines.append(f"| medium (~355M) MFU | {_fmt(med['mfu'], 4)} | "
                         f"stage bench_mfu_medium |")
        if mid.get("mfu") is not None:
            lines.append(f"| mid (~60M bracket tier) MFU | "
                         f"{_fmt(mid['mfu'], 4)} | stage mfu_mid |")
        if lng.get("mfu") is not None:
            lines.append(
                f"| long-context (seq 4096) MFU | {_fmt(lng['mfu'], 4)}"
                f" (hw {_fmt(lng.get('mfu_hw') or 0, 4)}) | "
                f"stage mfu_long |")
        lines.append("")

    sr = newest_schema_record(rows)
    if sr:
        lines += render_gated(sr)
        lines.append("")

    sv = res("serve_shared")
    sh = (sv.get("arms") or {}).get("engine_paged_shared") or {}
    un = (sv.get("arms") or {}).get("engine_unshared_open") or {}
    if sh:
        pages = sh.get("pages", {})
        lines += ["", "Shared-prefix serving (paged KV, stage "
                  "serve_shared; gated medians — docs/serving.md):", "",
                  "| arm | TTFT p50 (ms) | TTFT p99 (ms) | tokens/s |",
                  "|---|---|---|---|",
                  f"| paged+shared | {_fmt(sh.get('ttft_ms_p50', 0))} | "
                  f"{_fmt(sh.get('ttft_ms_p99', 0))} | "
                  f"{_fmt(sh.get('tokens_per_sec', 0))} |"]
        if un:
            lines.append(
                f"| unshared | {_fmt(un.get('ttft_ms_p50', 0))} | "
                f"{_fmt(un.get('ttft_ms_p99', 0))} | "
                f"{_fmt(un.get('tokens_per_sec', 0))} |")
        lines.append("")
        hr = pages.get("prefix_hit_rate")
        lines.append(
            f"Prefix hit rate {_fmt(hr, 3) if hr is not None else 'n/a'}"
            f" ({pages.get('prefix_hit_pages', 0)} pages), "
            f"prefill tokens saved "
            f"{_fmt(sh.get('prefill_tokens_saved', 0), 0)}, pool "
            f"occupancy {_fmt(pages.get('pool_occupancy', 0), 3)} "
            f"({pages.get('evictions', 0)} evictions).")
        if "vs_unshared_ttft_p50_x" in sv:
            lines.append(f"vs_unshared TTFT p50: "
                         f"**{_fmt(float(sv['vs_unshared_ttft_p50_x']))}x**"
                         " (both sides passed the spread gate).")
        elif "vs_unshared_ttft_p50_withheld" in sv:
            lines.append(f"vs_unshared TTFT p50 **withheld**: "
                         f"{_truncate_words(sv['vs_unshared_ttft_p50_withheld'])}")
        lines.append("")

    hr = res("bench_dp8_hier")
    if hr.get("hier_steps_per_sec") is not None:
        lines += ["", f"Adaptive/hierarchical comm (stage bench_dp8_hier"
                  f", {hr.get('hier_bucket_mb', '?')} MiB bucket, world "
                  f"{hr.get('hier_world', '?')} as "
                  f"{hr.get('hier_world', 0) // max(hr.get('hier_local_world', 1), 1)}"
                  f"x{hr.get('hier_local_world', '?')} hosts — "
                  "docs/comms.md):", "",
                  "| arm | steps/s | wire bytes/rank/step |",
                  "|---|---|---|",
                  f"| flat q8 | {_fmt(hr.get('q8_steps_per_sec', 0))} | "
                  f"{hr.get('q8_wire_bytes', '?')} |",
                  f"| flat q4 | {_fmt(hr.get('q4_steps_per_sec', 0))} | "
                  f"{hr.get('q4_wire_bytes', '?')} |",
                  f"| hier adaptive | "
                  f"{_fmt(hr.get('hier_steps_per_sec', 0))} | "
                  f"slow-hop {hr.get('hier_slow_hop_bytes_per_step', '?')} |"]
        if hr.get("f32_wire_bytes") and hr.get("q4_wire_bytes"):
            lines.append(
                f"q4 wire {_fmt(hr['f32_wire_bytes'] / hr['q4_wire_bytes'])}"
                f"x smaller than f32 (CommStats accounting == wire.py "
                f"formula); adaptive widths {hr.get('hier_width_hist')}.")
        if hr.get("hier_slow_hop_bytes_total"):
            parts = []
            if hr.get("flat_slow_hop_bytes_matched_width"):
                parts.append(
                    f"{_fmt(hr['flat_slow_hop_bytes_matched_width'] / hr['hier_slow_hop_bytes_total'])}"
                    "x below the same-width flat ring (topology)")
            if hr.get("flat_slow_hop_bytes_q8"):
                parts.append(
                    f"{_fmt(hr['flat_slow_hop_bytes_q8'] / hr['hier_slow_hop_bytes_total'])}"
                    "x below the flat q8 ring (topology x width)")
            if parts:
                lines.append("Two-level ring slow-hop total "
                             + "; ".join(parts) + ".")
        ov = hr.get("overlap") or {}
        if ov.get("on") and ov.get("off"):
            line = (f"Comm overlap (bucketed host step): exposed "
                    f"{_fmt(ov['off'].get('exposed_ms', 0))} -> "
                    f"{_fmt(ov['on'].get('exposed_ms', 0))} ms/step "
                    f"({_fmt(ov['on'].get('overlapped_ms', 0))} ms "
                    "measured hidden behind async bucket updates")
            if ov["on"].get("step_ms") is not None:
                line += (f"; wall {_fmt(ov['off'].get('step_ms', 0))}"
                         f" -> {_fmt(ov['on'].get('step_ms', 0))} "
                         "ms/step")
            lines.append(line + ").")
        if "vs_q8" in hr:
            lines.append(f"vs_q8: **{_fmt(float(hr['vs_q8']))}x** (both "
                         "sides passed the spread gate).")
        elif "vs_q8_withheld" in hr:
            lines.append(f"vs_q8 **withheld**: "
                         f"{_truncate_words(hr['vs_q8_withheld'])}")
        lines.append("")

    smoke = res("mfu_smoke")
    if smoke.get("step_ms_median") is not None:
        lines.append(
            f"Chip-liveness smoke (CI-sized model, not a perf claim): "
            f"device {smoke.get('device')}, step "
            f"{_fmt(smoke['step_ms_median'], 2)} ms, "
            f"{live.get('mfu_smoke', {}).get('ts', '?')}.")
        lines.append("")

    dec = res("bench_decode")
    header_done = False
    for arm in ("mha", "gqa", "gqa_int8", "gqa_int8_pinned",
                "gqa_window"):
        d = dec.get(arm, {})
        if d.get("decode_tokens_per_sec"):
            if not header_done:
                lines += ["| Decode arm | tok/s | ms/token | est HBM util |",
                          "|---|---|---|---|"]
                header_done = True
            lines.append(
                f"| {arm} | {_fmt(d['decode_tokens_per_sec'], 1)} | "
                f"{_fmt(d.get('decode_per_token_latency_ms', 0))} | "
                f"{_fmt(d.get('est_hbm_utilization', 0))} |")
    if dec.get("gqa_decode_speedup"):
        line = (f"\nGQA decode speedup {dec['gqa_decode_speedup']}x; "
                f"int8 {dec.get('gqa_int8_decode_speedup')}x")
        if dec.get("gqa_int8_pinned_decode_speedup") is not None:
            line += (f"; int8 pinned (anti-hoist) "
                     f"{dec['gqa_int8_pinned_decode_speedup']}x")
        if dec.get("gqa_window_decode_speedup") is not None:
            line += (f"; sliding-window rolling cache "
                     f"{dec['gqa_window_decode_speedup']}x")
        lines.append(line + ".")

    fa = res("flash_attention")
    if fa.get("rows"):
        lines += ["", "| seq | flash fwd (ms) | dense fwd (ms) | fwd x | "
                  "flash f+b (ms) | dense f+b (ms) | f+b x |",
                  "|---|---|---|---|---|---|---|"]
        for r in fa["rows"]:
            lines.append(
                f"| {r['seq']} | {_fmt(r['flash_fwd_ms'], 2)} | "
                f"{_fmt(r['dense_fwd_ms'], 2)} | "
                f"{_fmt(r['fwd_speedup'], 2)}x | "
                f"{_fmt(r['flash_fwdbwd_ms'], 2)} | "
                f"{_fmt(r['dense_fwdbwd_ms'], 2)} | "
                f"{_fmt(r['fwdbwd_speedup'], 2)}x |")

    sw = res("mfu_sweep")
    if sw.get("sweep"):
        lines += ["", "| MFU-sweep arm | MFU | tokens/s | step ms |",
                  "|---|---|---|---|"]
        # keep arms whose run succeeded even when mfu is None (unknown
        # device kind): tokens/s and step time are still signal
        arms = sorted((a for a in sw["sweep"] if not a.get("error")),
                      key=lambda a: (a.get("mfu") is None,
                                     -(a.get("mfu") or 0),
                                     -(a.get("tokens_per_sec") or 0)))
        for a in arms:
            mfu_cell = (_fmt(a["mfu"], 4) if a.get("mfu") is not None
                        else "n/a")
            # † marks arms that printed a record but then exited nonzero
            # (arm_error/arm_rc): suspect measurements must be visibly
            # distinct from clean rows (ADVICE round 5)
            mark = " †" if a.get("arm_error") else ""
            lines.append(
                f"| `{json.dumps(a['arm'], sort_keys=True)}`{mark} | "
                f"{mfu_cell} | {_fmt(a.get('tokens_per_sec', 0))} | "
                f"{_fmt(a.get('step_ms_median', 0), 2)} |")
        suspect = [a for a in arms if a.get("arm_error")]
        if suspect:
            lines.append("")
            for a in suspect:
                lines.append(
                    f"† `{json.dumps(a['arm'], sort_keys=True)}` exited "
                    f"nonzero after printing its record "
                    f"(rc {a.get('arm_rc')}): "
                    f"{str(a['arm_error'])[:90]}")
        failed = [a for a in sw["sweep"] if a.get("error")]
        if failed:
            lines.append("")
            for a in failed:
                lines.append(f"- arm `{json.dumps(a['arm'], sort_keys=True)}`"
                             f" failed: {a['error'][:90]}")

    bw = res("flash_bwd_sweep")
    if bw.get("best"):
        lines += ["", f"Flash {bw.get('mode', 'fwdbwd')} best block sizes "
                  "(block-size sweep):",
                  "", "| seq | block_q | block_k | ms |", "|---|---|---|---|"]
        for s in sorted(bw["best"], key=int):
            r = bw["best"][s]
            lines.append(f"| {s} | {r['bq']} | {r['bk']} | "
                         f"{_fmt(r['ms'], 3)} |")

    for stage in ("step_breakdown", "step_breakdown_b32"):
        sb = res(stage)
        if sb.get("attribution_ms"):
            a = sb["attribution_ms"]
            lines += ["", f"Step attribution ({stage}, batch "
                      f"{sb.get('config', {}).get('batch')}): "
                      + ", ".join(f"{k} {_fmt(v, 2)}"
                                  for k, v in a.items())
                      + f"; full step {_fmt(sb['step_ms']['full'], 2)} ms."]

    retracted = [r for r in rows if r.get("retracted")]
    if retracted:
        lines += ["", "Retracted rows (kept for the audit trail):"]
        for r in retracted:
            lines.append(f"- {r.get('stage')} ({r.get('ts', '?')}): "
                         f"{_truncate_words(r.get('reason', 'retracted'))}")
    return "\n".join(lines)


_OBS = None


def _obs_modules():
    """obs.export/detect — the real modules when already imported
    (in-process test use), else file-based under the SAME private root
    as :func:`_perfbench_record` (obs.detect's relative import of
    ``..perfbench.stats`` then resolves to the one already-loaded
    private stats instance)."""
    global _OBS
    if _OBS is not None:
        return _OBS
    real = sys.modules.get("distributed_pytorch_tpu.obs.export")
    if real is not None:
        _OBS = (real,
                sys.modules["distributed_pytorch_tpu.obs.detect"])
        return _OBS
    _, export_mod, detect_mod = _load_private(
        [("perfbench", "stats"), ("obs", "export"), ("obs", "detect")])
    _OBS = (export_mod, detect_mod)
    return _OBS


def render_trace(path: str) -> str:
    """The observability section: per-op per-rank span summary + the
    straggler verdict from one span log (``dpxtrace summarize`` /
    ``stragglers`` as markdown)."""
    export, detect = _obs_modules()
    try:
        records, malformed = export.read_log(path)
    except OSError as e:
        return f"## Trace\n\n(cannot read {path}: {e})\n"
    spans = export.collect_spans(records)
    lines = ["## Trace (dpxtrace)", "",
             f"Source: `{os.path.basename(path)}` — {len(spans)} "
             f"span(s), {len(malformed)} malformed line(s)", ""]
    rows = detect.summarize_ops(spans)
    if not rows:
        lines += ["(no spans recorded — set `DPX_TRACE=1`)", ""]
        return "\n".join(lines)
    lines += ["| op | rank | count | median ms | IQR ms | total ms |",
              "|---|---|---|---|---|---|"]
    for r in rows:
        lines.append(f"| `{r['op']}` | {r['rank']} | {r['count']} | "
                     f"{r['median_ms']} | {r['iqr_ms']} | "
                     f"{r['total_ms']} |")
    lines.append("")
    found = detect.stragglers(spans)
    if not found:
        lines += ["Stragglers: none flagged "
                  f"(k·IQR gate, k={detect.IQR_K})", ""]
    else:
        lines += ["**Stragglers flagged** (per-rank median outside "
                  f"k·IQR, k={detect.IQR_K}):", ""]
        for f in found:
            lines.append(
                f"- `{f['op']}` rank {f['rank']}: {f['median_ms']} ms "
                f"vs world median {f['world_median_ms']} ms "
                f"({f['excess_x']}x, threshold {f['threshold_ms']} ms)")
        lines.append("")
    return "\n".join(lines)


BASELINE_PATH = os.path.join(REPO, "BASELINE.md")
MARK_BEGIN = ("<!-- BEGIN MEASURED AUTO (regenerated by "
              "benchmarks/report.py --write-baseline; do not edit by "
              "hand) -->")
MARK_END = "<!-- END MEASURED AUTO -->"


def write_baseline(md: str, path: str = None) -> bool:
    """Replace the marker-delimited span in BASELINE.md with ``md``.
    Returns False (no write) when the markers are absent/corrupted —
    never clobbers prose outside the span."""
    path = path or BASELINE_PATH
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except (OSError, ValueError):  # ValueError covers UnicodeDecodeError
        return False
    b = text.find(MARK_BEGIN)
    e = text.find(MARK_END)
    if b == -1 or e == -1 or e < b:
        return False
    new = (text[:b + len(MARK_BEGIN)] + "\n" + md.rstrip() + "\n"
           + text[e:])
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(new)
    os.replace(tmp, path)
    return True


def main(argv):
    path = DEFAULT_LOG
    if "--log" in argv:
        i = argv.index("--log")
        if i + 1 >= len(argv):
            print("usage: report.py [--log FILE] [--write-baseline]",
                  file=sys.stderr)
            return 2
        path = argv[i + 1]
    rows, malformed = load_rows_checked(path)
    for line_no, reason in malformed:
        print(f"# report: skipping malformed store line {line_no}: "
              f"{reason}", file=sys.stderr)
    md = render(rows)
    print(md)
    if "--trace-log" in argv:
        i = argv.index("--trace-log")
        if i + 1 >= len(argv):
            print("usage: report.py [--trace-log FILE]",
                  file=sys.stderr)
            return 2
        print(render_trace(argv[i + 1]))
    rc = 0
    if "--write-baseline" in argv:
        ok = write_baseline(md)
        status = "updated" if ok else "NOT updated (markers missing)"
        print(f"# BASELINE.md {status}", file=sys.stderr)
        rc = 0 if ok else 1
    # the JSON summary line prints on EVERY path — tooling parses the
    # last stdout line even when the baseline write failed
    live = latest_per_stage(rows)
    print(json.dumps({"stages_on_file": sorted(live),
                      "n_rows": len(rows),
                      "n_malformed": len(malformed),
                      "n_retracted": sum(bool(r.get("retracted"))
                                         for r in rows)}))
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
