"""Render the measured-results section from the raw records log.

BASELINE.md's rule (round 4 on) is that prose tables are regenerated
from `benchmarks/tpu_results.jsonl` — this is the regenerator. It reads
every non-retracted `ok` row, keeps the NEWEST record per stage, and
prints a markdown summary ready to paste into BASELINE.md (plus one JSON
line for tooling). Retracted rows are listed by stage + reason so the
retraction trail stays visible.

Usage: python benchmarks/report.py [--log FILE] [--write-baseline]

--write-baseline splices the rendered section into BASELINE.md between
the BEGIN/END MEASURED AUTO markers (the watcher runs this after every
pass that lands a stage, so fresh evidence reaches BASELINE.md on disk
even when no one is at the keyboard).
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_LOG = os.path.join(REPO, "benchmarks", "tpu_results.jsonl")


def load_rows(path):
    rows = []
    try:
        with open(path) as f:
            for line in f:
                if line.strip():
                    try:
                        rows.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass
    except OSError:
        pass
    return rows


def latest_per_stage(rows):
    """Newest non-retracted ok row per stage (file order = time order)."""
    out = {}
    for r in rows:
        if r.get("ok") and not r.get("retracted"):
            out[r.get("stage", "?")] = r
    return out


def _truncate_words(s: str, cap: int = 200) -> str:
    """Cap a free-text reason at a WORD boundary with an ellipsis —
    the retraction reasons run ~120 chars and the old hard [:100] cut
    them mid-word in the regenerated BASELINE.md (ADVICE round 5)."""
    s = str(s)
    if len(s) <= cap:
        return s
    cut = s[:cap].rsplit(None, 1)[0] if " " in s[:cap] else s[:cap]
    return cut + "…"


def _fmt(v, nd=3):
    if isinstance(v, float):
        s = f"{v:.{nd}f}"
        return s.rstrip("0").rstrip(".") if "." in s else s
    return str(v)


def render(rows) -> str:
    live = latest_per_stage(rows)
    lines = ["## Measured (regenerated from benchmarks/tpu_results.jsonl)",
             ""]
    if not live:
        lines.append("*(no non-retracted successful records on file)*")

    def res(stage):
        return live.get(stage, {}).get("result", {})

    if "bench_mfu" in live:
        src_stage = "bench_mfu"
        mfu = res("bench_mfu")
    else:
        src_stage = ("bench_headline" if "bench_headline" in live
                     else "bench_record")
        mfu = res(src_stage).get("mfu_detail", {})
    med = res("bench_mfu_medium")
    lng = res("mfu_long")
    mid = res("mfu_mid")
    # the metric table starts whenever ANY MFU row exists — a round where
    # the flagship stage wedged but medium/long landed still renders
    if any(r.get("mfu") is not None for r in (mfu, med, lng, mid)):
        lines += ["| Metric | Value | Source row |", "|---|---|---|"]
        if mfu.get("mfu") is not None:
            c = mfu.get("config", {})
            src = (f"stage {src_stage}, "
                   f"{live.get(src_stage, {}).get('ts', '?')}")
            lines += [
                f"| **Flagship MFU** | **{_fmt(mfu['mfu'], 4)}** "
                f"({_fmt(mfu.get('achieved_tflops_per_sec', 0), 1)} of "
                f"{_fmt(mfu.get('peak_bf16_tflops', 0), 0)} peak TF/s) | "
                f"{src} |",
                f"| Flagship tokens/s | "
                f"{_fmt(mfu.get('tokens_per_sec', 0))} "
                f"(step {_fmt(mfu.get('step_ms_median', 0))} ms, "
                f"batch {c.get('batch')}, seq {c.get('seq')}) | same |",
            ]
        if med.get("mfu") is not None:
            lines.append(f"| medium (~355M) MFU | {_fmt(med['mfu'], 4)} | "
                         f"stage bench_mfu_medium |")
        if mid.get("mfu") is not None:
            lines.append(f"| mid (~60M bracket tier) MFU | "
                         f"{_fmt(mid['mfu'], 4)} | stage mfu_mid |")
        if lng.get("mfu") is not None:
            lines.append(
                f"| long-context (seq 4096) MFU | {_fmt(lng['mfu'], 4)}"
                f" (hw {_fmt(lng.get('mfu_hw') or 0, 4)}) | "
                f"stage mfu_long |")
        lines.append("")

    smoke = res("mfu_smoke")
    if smoke.get("step_ms_median") is not None:
        lines.append(
            f"Chip-liveness smoke (CI-sized model, not a perf claim): "
            f"device {smoke.get('device')}, step "
            f"{_fmt(smoke['step_ms_median'], 2)} ms, "
            f"{live.get('mfu_smoke', {}).get('ts', '?')}.")
        lines.append("")

    dec = res("bench_decode")
    header_done = False
    for arm in ("mha", "gqa", "gqa_int8", "gqa_int8_pinned",
                "gqa_window"):
        d = dec.get(arm, {})
        if d.get("decode_tokens_per_sec"):
            if not header_done:
                lines += ["| Decode arm | tok/s | ms/token | est HBM util |",
                          "|---|---|---|---|"]
                header_done = True
            lines.append(
                f"| {arm} | {_fmt(d['decode_tokens_per_sec'], 1)} | "
                f"{_fmt(d.get('decode_per_token_latency_ms', 0))} | "
                f"{_fmt(d.get('est_hbm_utilization', 0))} |")
    if dec.get("gqa_decode_speedup"):
        line = (f"\nGQA decode speedup {dec['gqa_decode_speedup']}x; "
                f"int8 {dec.get('gqa_int8_decode_speedup')}x")
        if dec.get("gqa_int8_pinned_decode_speedup") is not None:
            line += (f"; int8 pinned (anti-hoist) "
                     f"{dec['gqa_int8_pinned_decode_speedup']}x")
        if dec.get("gqa_window_decode_speedup") is not None:
            line += (f"; sliding-window rolling cache "
                     f"{dec['gqa_window_decode_speedup']}x")
        lines.append(line + ".")

    fa = res("flash_attention")
    if fa.get("rows"):
        lines += ["", "| seq | flash fwd (ms) | dense fwd (ms) | fwd x | "
                  "flash f+b (ms) | dense f+b (ms) | f+b x |",
                  "|---|---|---|---|---|---|---|"]
        for r in fa["rows"]:
            lines.append(
                f"| {r['seq']} | {_fmt(r['flash_fwd_ms'], 2)} | "
                f"{_fmt(r['dense_fwd_ms'], 2)} | "
                f"{_fmt(r['fwd_speedup'], 2)}x | "
                f"{_fmt(r['flash_fwdbwd_ms'], 2)} | "
                f"{_fmt(r['dense_fwdbwd_ms'], 2)} | "
                f"{_fmt(r['fwdbwd_speedup'], 2)}x |")

    sw = res("mfu_sweep")
    if sw.get("sweep"):
        lines += ["", "| MFU-sweep arm | MFU | tokens/s | step ms |",
                  "|---|---|---|---|"]
        # keep arms whose run succeeded even when mfu is None (unknown
        # device kind): tokens/s and step time are still signal
        arms = sorted((a for a in sw["sweep"] if not a.get("error")),
                      key=lambda a: (a.get("mfu") is None,
                                     -(a.get("mfu") or 0),
                                     -(a.get("tokens_per_sec") or 0)))
        for a in arms:
            mfu_cell = (_fmt(a["mfu"], 4) if a.get("mfu") is not None
                        else "n/a")
            # † marks arms that printed a record but then exited nonzero
            # (arm_error/arm_rc): suspect measurements must be visibly
            # distinct from clean rows (ADVICE round 5)
            mark = " †" if a.get("arm_error") else ""
            lines.append(
                f"| `{json.dumps(a['arm'], sort_keys=True)}`{mark} | "
                f"{mfu_cell} | {_fmt(a.get('tokens_per_sec', 0))} | "
                f"{_fmt(a.get('step_ms_median', 0), 2)} |")
        suspect = [a for a in arms if a.get("arm_error")]
        if suspect:
            lines.append("")
            for a in suspect:
                lines.append(
                    f"† `{json.dumps(a['arm'], sort_keys=True)}` exited "
                    f"nonzero after printing its record "
                    f"(rc {a.get('arm_rc')}): "
                    f"{str(a['arm_error'])[:90]}")
        failed = [a for a in sw["sweep"] if a.get("error")]
        if failed:
            lines.append("")
            for a in failed:
                lines.append(f"- arm `{json.dumps(a['arm'], sort_keys=True)}`"
                             f" failed: {a['error'][:90]}")

    bw = res("flash_bwd_sweep")
    if bw.get("best"):
        lines += ["", f"Flash {bw.get('mode', 'fwdbwd')} best block sizes "
                  "(block-size sweep):",
                  "", "| seq | block_q | block_k | ms |", "|---|---|---|---|"]
        for s in sorted(bw["best"], key=int):
            r = bw["best"][s]
            lines.append(f"| {s} | {r['bq']} | {r['bk']} | "
                         f"{_fmt(r['ms'], 3)} |")

    for stage in ("step_breakdown", "step_breakdown_b32"):
        sb = res(stage)
        if sb.get("attribution_ms"):
            a = sb["attribution_ms"]
            lines += ["", f"Step attribution ({stage}, batch "
                      f"{sb.get('config', {}).get('batch')}): "
                      + ", ".join(f"{k} {_fmt(v, 2)}"
                                  for k, v in a.items())
                      + f"; full step {_fmt(sb['step_ms']['full'], 2)} ms."]

    retracted = [r for r in rows if r.get("retracted")]
    if retracted:
        lines += ["", "Retracted rows (kept for the audit trail):"]
        for r in retracted:
            lines.append(f"- {r.get('stage')} ({r.get('ts', '?')}): "
                         f"{_truncate_words(r.get('reason', 'retracted'))}")
    return "\n".join(lines)


BASELINE_PATH = os.path.join(REPO, "BASELINE.md")
MARK_BEGIN = ("<!-- BEGIN MEASURED AUTO (regenerated by "
              "benchmarks/report.py --write-baseline; do not edit by "
              "hand) -->")
MARK_END = "<!-- END MEASURED AUTO -->"


def write_baseline(md: str, path: str = None) -> bool:
    """Replace the marker-delimited span in BASELINE.md with ``md``.
    Returns False (no write) when the markers are absent/corrupted —
    never clobbers prose outside the span."""
    path = path or BASELINE_PATH
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except (OSError, ValueError):  # ValueError covers UnicodeDecodeError
        return False
    b = text.find(MARK_BEGIN)
    e = text.find(MARK_END)
    if b == -1 or e == -1 or e < b:
        return False
    new = (text[:b + len(MARK_BEGIN)] + "\n" + md.rstrip() + "\n"
           + text[e:])
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(new)
    os.replace(tmp, path)
    return True


def main(argv):
    path = DEFAULT_LOG
    if "--log" in argv:
        i = argv.index("--log")
        if i + 1 >= len(argv):
            print("usage: report.py [--log FILE] [--write-baseline]",
                  file=sys.stderr)
            return 2
        path = argv[i + 1]
    rows = load_rows(path)
    md = render(rows)
    print(md)
    rc = 0
    if "--write-baseline" in argv:
        ok = write_baseline(md)
        status = "updated" if ok else "NOT updated (markers missing)"
        print(f"# BASELINE.md {status}", file=sys.stderr)
        rc = 0 if ok else 1
    # the JSON summary line prints on EVERY path — tooling parses the
    # last stdout line even when the baseline write failed
    live = latest_per_stage(rows)
    print(json.dumps({"stages_on_file": sorted(live),
                      "n_rows": len(rows),
                      "n_retracted": sum(bool(r.get("retracted"))
                                         for r in rows)}))
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
