"""Analytic roofline for the MFU benchmark configs (v5e single chip).

The round-4 verdict's ask: either a measured flagship MFU >= 0.42 or a
committed roofline analysis locating the remaining gap. This is the
analysis, executable: for each benchmark config it derives

- the **compute floor**: analytic model FLOPs / peak bf16 FLOP/s (the
  step time at MFU 1.0 — same FLOP accounting as mfu_transformer.py, so
  the two agree by construction);
- the **HBM floor**: an itemized per-step traffic model (params, grads,
  optimizer moments, activations, logits) / peak HBM bandwidth;
- the implied **MFU ceiling** = compute_floor / max(compute, hbm) — what
  a perfectly overlapped execution could reach; and
- against the newest measured row in tpu_results.jsonl (when present),
  the **efficiency gap**: measured_step / max(floor) — the factor that
  is kernel/overlap inefficiency rather than physics.

The verdict-facing conclusion this model supports: at flagship scale
(135M params, batch 8, seq 1024) the step is COMPUTE-dominated on paper
(HBM floor ~1/3 of the compute floor), so a sub-0.9 MFU is NOT
"memory-bound and irreducible" — the gap lives in kernel efficiency and
is attackable (fused-CE removes the largest single HBM item, the f32
logits; the no-remat large-batch arms amortize per-step overheads).

Usage: python benchmarks/roofline.py            (table + one JSON line)
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.mfu_transformer import (  # noqa: E402
    FLAGSHIP, LONGCTX, MEDIUM, MID, PEAK_BF16, model_flops_per_token)

# Public per-chip HBM specs (same sourcing rule as PEAK_BF16: only the
# generation we can run on is judged; others best-effort). Key set
# MIRRORS PEAK_BF16 exactly — analyze() indexes both with one
# device_kind, so a key present in one but not the other turned into a
# bare KeyError for v2/v3/v5 (ADVICE round 5).
HBM_GBPS = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,           # v5p, mirroring PEAK_BF16's aliasing
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,      # Trillium / v6e
    "TPU v6e": 1640e9,
}
assert set(HBM_GBPS) == set(PEAK_BF16), \
    "HBM_GBPS and PEAK_BF16 must stay key-identical (analyze() indexes both)"
# Activation tensors written in forward and re-read in backward, per
# layer, in units of (batch*seq*dim) elements. Transformer block with
# flash attention (no S^2 materialization): ln1 out, qkv out (3x), attn
# out, proj out, ln2 out, mlp hidden (4x), mlp out ~= 12 B*S*d tensors.
# bf16. Remat reduces the stored set to the block boundary (~2) at the
# price of recomputing the forward (uncounted by model-FLOPs MFU).
_ACT_UNITS_PER_LAYER = 12.0
_ACT_UNITS_PER_LAYER_REMAT = 2.0


def remat_enabled(remat) -> bool:
    """Normalize a remat flag OR named policy to the binary question
    the activation-traffic model asks: are per-layer activations
    rematerialized? The ONE rule — ``hbm_bytes_per_step`` and
    ``analyze`` both use it, so a config carrying the new policy
    strings (``none|full|dots_saveable``, models/transformer.py) can
    never read as remat-enabled through one entry point and disabled
    through the other. ``dots_saveable`` stores strictly less than
    "none"; the two-unit block-boundary estimate is the conservative
    lower bound for both remat policies."""
    return remat not in (False, None, "none")


def count_params(cfg) -> int:
    d, L, V = cfg["dim"], cfg["n_layers"], cfg["vocab"]
    per_layer = 12 * d * d  # qkv 3d^2 + proj d^2 + mlp 8d^2 (r=4)
    return V * d + L * per_layer + V * d  # emb + blocks + untied head


def hbm_bytes_per_step(cfg, *, fused_ce: Optional[bool] = None,
                       remat: Optional[bool] = None,
                       master_f32: Optional[bool] = None) -> dict:
    """Itemized HBM traffic for one train step, bytes.

    A deliberate lower-bound model: each item counted once at its
    minimum unavoidable traffic (e.g. params read once for forward and
    once for backward, moments read+written once). Real executions
    re-stream tiles; that inefficiency is what the measured gap shows.

    Arm flags left as None default from the config dict itself (the
    FLAGSHIP identity carries them), same contract as :func:`analyze`.
    """
    fused_ce = cfg.get("fused_ce", False) if fused_ce is None else fused_ce
    remat = remat_enabled(cfg.get("remat", False) if remat is None
                          else remat)
    master_f32 = (cfg.get("master_f32", False) if master_f32 is None
                  else master_f32)
    P = count_params(cfg)
    B, S, d, L, V = (cfg["batch"], cfg["seq"], cfg["dim"],
                     cfg["n_layers"], cfg["vocab"])
    tok = B * S
    p_bytes = 4 if master_f32 else 2
    items = {
        # bf16 working params read by fwd and again by bwd
        "params_fwd+bwd_read": 2 * P * 2,
        # bf16 grads written by bwd, read by the update
        "grads_write+read": 2 * P * 2,
        # adamw f32 moments m,v: read + write each
        "adamw_moments_rw": 4 * P * 4,
        # updated params written (+ f32 master copy rw when enabled)
        "params_update_write": P * p_bytes
        + (2 * P * 4 if master_f32 else 0),
        # stored activations: fwd write + bwd read, bf16
        "activations_fwd_write+bwd_read":
            int(2 * (_ACT_UNITS_PER_LAYER_REMAT if remat
                     else _ACT_UNITS_PER_LAYER) * L * tok * d * 2),
        # f32 logits (B,S,V): write + CE read + bwd read — absent
        # entirely under fused-CE (losses.fused_linear_cross_entropy
        # streams the vocab projection chunkwise)
        "logits_f32": 0 if fused_ce else 3 * tok * V * 4,
    }
    items["total"] = sum(items.values())
    return items


def analyze(cfg, *, device_kind: str = "TPU v5 lite",
            fused_ce: Optional[bool] = None, remat=None,
            master_f32: Optional[bool] = None,
            peak_flops: Optional[float] = None,
            mem_bytes_per_s: Optional[float] = None) -> dict:
    # arm flags default from the config dict itself (FLAGSHIP carries
    # its arm flags as part of the flagship identity) so a flagship
    # promotion propagates here without touching call sites
    fused_ce = cfg.get("fused_ce", False) if fused_ce is None else fused_ce
    # named remat policies normalize through the shared rule
    remat = remat_enabled(cfg.get("remat", False) if remat is None
                          else remat)
    master_f32 = (cfg.get("master_f32", False) if master_f32 is None
                  else master_f32)
    if peak_flops is not None and mem_bytes_per_s is not None:
        # CALIBRATED specs (benchmarks/mfu_transformer.calibrate_host):
        # hosts without a spec-sheet row anchor their ceilings to their
        # own measured matmul/memcpy peaks — same math, honest inputs
        peak, bw = peak_flops, mem_bytes_per_s
    else:
        if device_kind not in PEAK_BF16 or device_kind not in HBM_GBPS:
            raise ValueError(
                f"unsupported device_kind {device_kind!r}: roofline "
                f"specs exist for {sorted(PEAK_BF16)} (or pass measured "
                f"peak_flops + mem_bytes_per_s overrides)")
        peak = PEAK_BF16[device_kind]
        bw = HBM_GBPS[device_kind]
    tok = cfg["batch"] * cfg["seq"]
    flops = 3 * model_flops_per_token(
        cfg["dim"], cfg["n_layers"], cfg["vocab"], cfg["seq"]) * tok
    traffic = hbm_bytes_per_step(cfg, fused_ce=fused_ce, remat=remat,
                                 master_f32=master_f32)
    t_compute = flops / peak
    t_hbm = traffic["total"] / bw
    floor = max(t_compute, t_hbm)
    return {
        "n_params": count_params(cfg),
        "model_tflops_per_step": round(flops / 1e12, 3),
        "hbm_gb_per_step": round(traffic["total"] / 1e9, 3),
        "hbm_items_gb": {k: round(v / 1e9, 3)
                         for k, v in traffic.items() if k != "total"},
        "compute_floor_ms": round(t_compute * 1e3, 2),
        "hbm_floor_ms": round(t_hbm * 1e3, 2),
        "bound": "compute" if t_compute >= t_hbm else "hbm",
        # perfect compute/memory overlap (the optimistic extreme) ...
        "mfu_ceiling": round(t_compute / floor, 4),
        # ... and zero overlap (the pessimistic extreme): real
        # executions land between the two
        "mfu_ceiling_no_overlap": round(t_compute / (t_compute + t_hbm),
                                        4),
    }


#: Wire bytes per gradient element of the comm-ceiling arms: f32, the
#: block-q8 wire (one f32 scale per 1024-block), and the nibble-packed
#: q4 wire (comm/wire.py's widths — ~3.98x / ~7.9x less than f32).
WIRE_BYTES_PER_ELEM = {32: 4.0, 8: 1.0 + 4 / 1024, 4: 0.5 + 4 / 1024}


def dp_comm_bytes_per_step(cfg, world: int, wire_bits: int = 32) -> int:
    """Bytes ONE chip puts on the interconnect for a data-parallel
    gradient ring allreduce of the model's params: ``2*(W-1)/W * P``
    elements at the wire width (the bandwidth-optimal ring's per-rank
    traffic; the quantized widths carry their per-block scale tax)."""
    if world <= 1:
        return 0
    per_elem = WIRE_BYTES_PER_ELEM[wire_bits]
    return int(2 * (world - 1) / world * count_params(cfg) * per_elem)


def comm_ceilings(analysis: dict, cfg, *, dp_world: int,
                  net_gbps: float, wire_bits: int = 8) -> dict:
    """Fold a data-parallel gradient-allreduce comm floor into an
    :func:`analyze` result — the distributed-step extension of the
    overlap story. Adds ``comm_floor_ms`` plus the two MFU ceilings
    that bracket real distributed executions:

    * ``mfu_ceiling_comm_overlap`` — comm fully hidden behind compute
      (what the double-buffered chunk pipeline + bucketed backward
      overlap drive toward; ``t_compute / max(t_compute, t_hbm,
      t_comm)``);
    * ``mfu_ceiling_comm_exposed`` — comm strictly serialized after the
      backward (the no-overlap floor, ``t_compute / (t_compute + t_hbm
      + t_comm)``).

    The gap between the two IS the overlap win the dp8 bench's
    ``exposed_ms`` measures; the plausibility gate keeps using the
    OVERLAPPED ceiling (nothing real exceeds the optimistic extreme).
    """
    t_c = analysis["compute_floor_ms"] / 1e3
    t_h = analysis["hbm_floor_ms"] / 1e3
    t_comm = dp_comm_bytes_per_step(cfg, dp_world, wire_bits) \
        / (net_gbps * 1e9)
    analysis["comm_floor_ms"] = round(t_comm * 1e3, 3)
    analysis["comm_wire_bits"] = wire_bits
    analysis["comm_dp_world"] = dp_world
    analysis["mfu_ceiling_comm_overlap"] = round(
        t_c / max(t_c, t_h, t_comm), 4)
    analysis["mfu_ceiling_comm_exposed"] = round(
        t_c / (t_c + t_h + t_comm), 4)
    return analysis


def attach_measured(analysis: dict, meas_ms) -> dict:
    """Join a measured step time onto an analyze() result: records
    measured_step_ms and the efficiency gap vs the binding floor. The
    ONE definition of the join rule — bench.attach_roofline and main()
    both use it, so the headline record and the roofline report can
    never disagree about the same measurement."""
    if meas_ms:
        analysis["measured_step_ms"] = meas_ms
        analysis["efficiency_gap_x"] = round(
            meas_ms / max(analysis["compute_floor_ms"],
                          analysis["hbm_floor_ms"]), 2)
    return analysis


def measured_step_ms(rows, stage: str):
    """The NEWEST ok non-retracted row's step_ms_median for a stage —
    None when that row lacks one (no silent fallback to a stale older
    measurement; keeps this join consistent with report.latest_per_stage
    so the two BASELINE-facing outputs agree on what is current)."""
    newest = None
    for r in rows:
        if r.get("stage") == stage and r.get("ok") \
                and not r.get("retracted"):
            newest = r
    if newest is None:
        return None
    return newest.get("result", {}).get("step_ms_median")


def main(argv):
    from benchmarks.report import load_rows
    log = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "tpu_results.jsonl")
    rows = load_rows(log)

    configs = [
        ("flagship", FLAGSHIP, {}, "bench_mfu"),
        ("flagship+fused_ce", FLAGSHIP, {"fused_ce": True}, None),
        ("mid", MID, {}, "mfu_mid"),
        ("medium", MEDIUM, {}, "bench_mfu_medium"),
        ("long(seq4096,remat+fce)", LONGCTX,
         {"remat": True, "fused_ce": True}, "mfu_long"),
    ]
    out = {"device": "TPU v5 lite",
           "peak_bf16_tflops": PEAK_BF16["TPU v5 lite"] / 1e12,
           "hbm_gbps": HBM_GBPS["TPU v5 lite"] / 1e9,
           "configs": {}}
    print("# config | params | TF/step | HBM GB/step | compute floor | "
          "HBM floor | bound | MFU ceiling (overlap/none) | measured | "
          "gap")
    for name, cfg, arm, stage in configs:
        a = analyze(cfg, **arm)
        meas = measured_step_ms(rows, stage) if stage else None
        attach_measured(a, meas)
        gap = a.get("efficiency_gap_x")
        out["configs"][name] = a
        print(f"# {name}: {a['n_params']/1e6:.0f}M | "
              f"{a['model_tflops_per_step']} | {a['hbm_gb_per_step']} | "
              f"{a['compute_floor_ms']} ms | {a['hbm_floor_ms']} ms | "
              f"{a['bound']} | {a['mfu_ceiling']}/"
              f"{a['mfu_ceiling_no_overlap']} | "
              f"{meas if meas is not None else '-'} ms | "
              f"{gap if gap is not None else '-'}", flush=True)
    # the distributed extension: what a dp8 flagship could reach over a
    # 100 Gb/s-class DCN hop per wire width, with and without comm
    # overlap — the analytic bracket behind the dp8_hier bench arm's
    # measured exposed_ms
    print("# dp8 comm ceilings (flagship, 12.5 GB/s interconnect): "
          "wire | comm floor | MFU ceiling overlapped/exposed")
    dp = {}
    for bits in (32, 8, 4):
        a = comm_ceilings(dict(analyze(FLAGSHIP)), FLAGSHIP, dp_world=8,
                          net_gbps=12.5, wire_bits=bits)
        dp[f"q{bits}" if bits != 32 else "f32"] = {
            k: a[k] for k in ("comm_floor_ms",
                              "mfu_ceiling_comm_overlap",
                              "mfu_ceiling_comm_exposed")}
        print(f"#   {'f32' if bits == 32 else f'q{bits}'} | "
              f"{a['comm_floor_ms']} ms | "
              f"{a['mfu_ceiling_comm_overlap']}/"
              f"{a['mfu_ceiling_comm_exposed']}", flush=True)
    out["dp8_comm_ceilings"] = dp
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
