"""Run every on-chip measurement in one go (the TPU-recovery runbook).

The tunneled TPU backend in this environment comes and goes; when it is
healthy, this script collects everything BASELINE.md lists as pending:

1. flash-attention compiled validation + speedup table
   (benchmarks/flash_attention_tpu.py, adaptive block defaults)
2. the remat arm of the flagship MFU measurement
   (benchmarks/mfu_transformer.py --remat; the default-config and
   --model medium arms come from bench.py below)
3. the headline bench record (bench.py — embeds flagship MFU, the
   medium-model MFU arm, min_ddp, and the decode MHA/GQA/int8 arms)

A TPU-health probe gates everything: without a healthy chip no stage
launches (a CPU fallback would grind the flagship through interpret-mode
pallas for hours). Each stage runs as a subprocess with a hard timeout (a
mid-run tunnel wedge must not take the collector down) and everything is
appended as JSON lines to --out (default benchmarks/tpu_results.jsonl)
for transfer into BASELINE.md.

Usage: python benchmarks/run_all_tpu.py [--quick] [--out FILE]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402  (the shared subprocess/JSON plumbing)


def run_stage(name: str, argv, timeout_s: int) -> dict:
    t0 = time.time()
    payload = bench.run_json_subprocess(argv, timeout_s, label=name,
                                        keep_stdout_tail=True)
    rec = {"stage": name, "ok": "error" not in payload,
           "wall_s": round(time.time() - t0, 1), "result": payload}
    return rec


def main(argv):
    quick = "--quick" in argv
    out_path = os.path.join(REPO, "benchmarks", "tpu_results.jsonl")
    if "--out" in argv:
        i = argv.index("--out")
        if i + 1 >= len(argv):
            print("usage: run_all_tpu.py [--quick] [--out FILE]",
                  file=sys.stderr)
            return 2
        out_path = argv[i + 1]
    py = sys.executable

    info = bench.wait_for_backend(max_tries=2, base_sleep_s=15.0)
    if not info:
        rec = {"stage": "tpu_health_gate", "ok": False,
               "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
               "result": {"error": "no healthy TPU backend; not running "
                          "any on-chip stage"}}
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec))
        return 1
    print(f"# TPU healthy: {info.get('kind')}", flush=True)

    # bench.py embeds the default-config MFU, min_ddp and decode stages —
    # don't re-measure them standalone (every duplicated minute on the
    # flaky tunnel is another chance to wedge mid-collection). The outer
    # timeout must exceed bench.py's own internal worst case (probe
    # retries + per-stage subprocess timeouts + CPU baselines), or a late
    # wedge would SIGKILL it and lose its partial record.
    def path(rel):
        return os.path.join(REPO, rel)

    stages = [("flash_attention",
               [py, path("benchmarks/flash_attention_tpu.py")], 2400),
              ("bench_headline", [py, path("bench.py")], 7200)]
    if not quick:
        # MFU sweep arm: remat trades activation HBM for FLOPs
        stages.insert(1, ("mfu_remat",
                          [py, path("benchmarks/mfu_transformer.py"),
                           "--remat"], 1800))

    results = []
    with open(out_path, "a") as f:
        for name, cmd, timeout_s in stages:
            print(f"=== {name} ===", flush=True)
            rec = run_stage(name, cmd, timeout_s)
            rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
            results.append(rec)
            f.write(json.dumps(rec) + "\n")
            f.flush()
            print(json.dumps({k: rec[k] for k in ("stage", "ok", "wall_s")
                              if k in rec}), flush=True)

    n_ok = sum(r["ok"] for r in results)
    print(f"\n{n_ok}/{len(results)} stages ok -> {out_path}")
    return 0 if n_ok == len(results) else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
