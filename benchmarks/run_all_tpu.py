"""Run every on-chip measurement in one go (the TPU-recovery runbook).

The tunneled TPU backend in this environment comes and goes; when it is
healthy, this script collects everything BASELINE.md lists as pending,
in PRIORITY order (a re-wedge mid-collection keeps what landed):

1. the flagship MFU alone (bench.py --stage mfu) — the round's headline
2. flash-attention compiled validation + fwd/fwd+bwd speedup table
   (benchmarks/flash_attention_tpu.py, adaptive block defaults)
3. the MFU-candidate sweep (the config grid the next flagship comes
   from), the long-context (seq 4096) MFU arm, the step-time ablation
   breakdowns (batch 8 and 32), the backward block-size sweep, and the
   remat arm
4. the headline bench record (bench.py — embeds flagship MFU, the
   medium-model MFU arm, min_ddp, and the decode MHA/GQA/int8 arms)

A TPU-health probe gates everything: without a healthy chip no stage
launches (a CPU fallback would grind the flagship through interpret-mode
pallas for hours). Each stage runs as a subprocess with a hard timeout (a
mid-run tunnel wedge must not take the collector down) and everything is
appended as JSON lines to --out (default benchmarks/tpu_results.jsonl)
for transfer into BASELINE.md.

With --watch the script becomes the recovery automation itself: it
probes the backend every --interval seconds (subprocess-isolated — an
in-process `jax.devices()` against a wedged tunnel hangs forever) and
the moment a probe succeeds it runs the priority queue. A stage that
fails (or a mid-collection re-wedge) does not end the run: the loop
returns to the watch and retries every not-yet-succeeded stage on the
next heal, until all stages landed, a stage failed MAX_ATTEMPTS times,
or --max-hours ran out — so the process may live for the whole budget.
This is the committed, reproducible form of the watcher that previous
rounds ran as an ad-hoc session process.

Usage: python benchmarks/run_all_tpu.py [--quick] [--out FILE]
           [--watch] [--interval SECONDS] [--max-hours H]
           [--done-flag FILE] [--write-baseline]

BASELINE.md's measured section is regenerated only when collecting into
the DEFAULT results log (benchmarks/tpu_results.jsonl) — a trial run
with a scratch --out must not silently replace the repo's evidence with
its rows (ADVICE round 5). Pass --write-baseline to force regeneration
from a non-default log.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the shared probe/retry/JSON-subprocess plumbing lives in the perfbench
# subsystem now (bench.py is a thin shim over the same module)
from distributed_pytorch_tpu.perfbench import runner  # noqa: E402

# In watch mode a failed stage is retried on later heals; after this many
# failures with a healthy backend it is skipped for the rest of the run (a
# poison stage that wedges the tunnel must not starve the rest of the
# queue, and a genuinely broken stage would otherwise retry forever).
# Failures observed with the backend ALREADY wedged do not count: a stage
# whose attempts were all eaten by someone else's wedge is a victim, not a
# poison stage, and must keep its retry budget (ADVICE round 5 — the
# flagship was permanently skipped because the tunnel wedged during its
# window three times).
MAX_ATTEMPTS = 3

# ... but a stage that WEDGES THE TUNNEL ITSELF also looks like a victim
# (the post-failure probe sees the wedge it caused), so uncapped exemption
# would let it starve the queue forever. After this many wedge-coincident
# failures the stage is skipped like a poison stage — deliberately more
# lenient than MAX_ATTEMPTS so genuine victims keep extra retries.
MAX_WEDGE_VICTIMS = 6


def regenerate_baseline(py: str, out_path: str) -> None:
    """Regenerate BASELINE.md's measured section from the rows on file —
    fresh evidence must reach the prose even if no one is at the
    keyboard when the tunnel heals (report.py is pure stdlib: no jax
    import, cannot hang on the tunnel). Best-effort: a failure here
    must not take down the collection loop."""
    import subprocess
    try:
        r = subprocess.run(
            [py, os.path.join(REPO, "benchmarks", "report.py"),
             "--log", out_path, "--write-baseline"],
            capture_output=True, text=True, timeout=60)
        if r.returncode != 0:
            # e.g. a hand-edit mangled the markers — say so loudly, or
            # BASELINE.md silently stops updating for the rest of the run
            print(f"# baseline regen rc={r.returncode}: "
                  f"{(r.stderr or '').strip()[-300:]}", flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"# baseline regen failed: {e}", flush=True)


def run_stage(name: str, argv, timeout_s: int, env: dict = None) -> dict:
    t0 = time.time()
    payload = runner.run_json_subprocess(argv, timeout_s, label=name,
                                         env=env, keep_stdout_tail=True)
    rec = {"stage": name, "ok": "error" not in payload,
           "wall_s": round(time.time() - t0, 1), "result": payload}
    return rec


def _flag_value(argv, flag, default):
    if flag in argv:
        i = argv.index(flag)
        if i + 1 >= len(argv):
            print(f"usage: run_all_tpu.py [...] {flag} VALUE",
                  file=sys.stderr)
            raise SystemExit(2)
        return argv[i + 1]
    return default


def watch_for_backend(interval_s: float, max_hours: float,
                      out_path: str) -> bool:
    """Probe the tunnel until it heals or the time budget runs out.

    Each probe is a subprocess with a hard timeout (runner.probe_backend)
    — the tunnel in this environment wedges for hours at a time and an
    in-process probe would hang with it. Returns True on a healthy
    probe; on expiry appends a watch_expired row so the round's record
    shows the watcher ran and for how long. The budget is approximate:
    a probe in flight at the deadline may overrun it by up to the probe
    timeout (45s — see probe_backend; immaterial against multi-hour
    budgets).
    """
    deadline = time.time() + max_hours * 3600.0
    n = 0
    while True:
        n += 1
        t0 = time.time()
        # default 45s timeout: see probe_backend's docstring (narrow
        # hung-probe window; a kill after a heal can re-wedge the tunnel)
        ok = runner.probe_backend()
        stamp = time.strftime("%H:%M:%S")
        print(f"[watch {stamp}] probe {n}: "
              f"{'HEALTHY' if ok else 'down'} ({time.time() - t0:.0f}s)",
              flush=True)
        if ok:
            return True
        if time.time() >= deadline:
            # Wording is segment-scoped on purpose: after a heal-then-
            # flap cycle _run re-enters this loop with the remaining
            # budget, so "never healed" would be false for the round.
            rec = {"stage": "watch_expired", "ok": False,
                   "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                   "result": {"error": f"watch segment expired after {n} "
                              f"probes / {max_hours:g}h budget; no "
                              "healthy backend at expiry"}}
            with open(out_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
            print(json.dumps(rec), flush=True)
            return False
        time.sleep(min(interval_s, max(0.0, deadline - time.time())))


def main(argv):
    done_flag = _flag_value(argv, "--done-flag", None)
    try:
        rc = _run(argv)
    except SystemExit as e:
        # usage errors (bad flags) are not crashes — record the rc
        if done_flag:
            with open(done_flag, "w") as f:
                f.write(f"rc={e.code} at "
                        f"{time.strftime('%Y-%m-%dT%H:%M:%S')}\n")
        raise
    except BaseException:
        if done_flag:
            with open(done_flag, "w") as f:
                f.write(f"crashed at {time.strftime('%Y-%m-%dT%H:%M:%S')}\n")
        raise
    if done_flag:
        with open(done_flag, "w") as f:
            f.write(f"rc={rc} at {time.strftime('%Y-%m-%dT%H:%M:%S')}\n")
    return rc


def _run(argv):
    quick = "--quick" in argv
    default_out = os.path.join(REPO, "benchmarks", "tpu_results.jsonl")
    out_path = _flag_value(argv, "--out", default_out)
    # BASELINE.md only regenerates from the repo's canonical log (or on
    # explicit request): a scratch --out run must never rewrite the
    # committed measured section from its own rows
    write_baseline = ("--write-baseline" in argv
                      or os.path.abspath(out_path)
                      == os.path.abspath(default_out))
    py = sys.executable

    watching = "--watch" in argv
    if watching:
        interval_s = float(_flag_value(argv, "--interval", "240"))
        deadline = time.time() + 3600.0 * float(
            _flag_value(argv, "--max-hours", "12"))

    # bench.py embeds the default-config MFU, min_ddp and decode stages.
    # min_ddp/decode are NOT re-measured standalone (every duplicated
    # minute on the flaky tunnel is another chance to wedge
    # mid-collection); the flagship MFU is the ONE deliberate exception —
    # it runs first as its own stage so the round's headline is on file
    # within minutes of a heal, duplication accepted. The outer timeout
    # must exceed bench.py's own internal worst case (probe retries +
    # per-stage subprocess timeouts + CPU baselines), or a late wedge
    # would SIGKILL it and lose its partial record.
    def path(rel):
        return os.path.join(REPO, rel)

    # PRIORITY ORDER: the round's headline must land first — a tunnel
    # that heals for twenty minutes and wedges again should still leave
    # a flagship-MFU row on file (round 3 lost its headline to exactly
    # this). Stage name "bench_mfu" is what bench.last_good_record and
    # benchmarks/report.py treat as the flagship record. mfu_smoke goes
    # even before it: a <60s CI-sized run that proves the chip did real
    # compute within a minute of a heal (the round-5 flagship attempt
    # wedged the tunnel 30 minutes in and left NOTHING on file).
    stages = [("mfu_smoke",
               [py, path("benchmarks/mfu_transformer.py"), "--small"],
               420, None),
              ("bench_mfu",
               [py, path("bench.py"), "--stage", "mfu"], 1800, None),
              # the ~60M bracket tier: if flagship-scale wedges the
              # tunnel, this still lands a meaningful MXU number
              ("mfu_mid",
               [py, path("benchmarks/mfu_transformer.py"),
                "--model", "mid"], 900, None),
              ("flash_attention",
               [py, path("benchmarks/flash_attention_tpu.py")], 2400,
               None),
              # DPX_BENCH_SELFLOG=0: this wrapper logs the composite
              # record; bench.py must not append a duplicate. Timeout
              # must cover bench.py's own worst case: four child stages
              # (1800+1800+900+2400s) + probe retries + the tripled
              # (median-of-3) CPU baselines — a mid-run wedge burns all
              # of it, and a SIGKILL here would lose the partial record.
              ("bench_headline", [py, path("bench.py")], 10800,
               {"DPX_BENCH_SELFLOG": "0"})]
    if not quick:
        extra = [
            # the MFU-candidate grid (batch8+fused-CE+master-f32, the
            # no-remat batch 16/32 arms, remat arms, HBM cliff at 64) —
            # the data that picks the next flagship config (round-4
            # verdict: push >= 0.45). 10800s: nine flagship-scale arms
            # (9x compile) — sized to the file's timeout standard (outer
            # > child worst case); both sweeps also progress-print per
            # arm to stdout so even a SIGKILL keeps the completed arms
            # in the stdout tail
            ("mfu_sweep", [py, path("benchmarks/mfu_transformer.py"),
                           "--sweep"], 10800, None),
            # long-context arm: flagship model at seq 4096 — the regime
            # the flash kernel's 8.5x win lives in (remat+fused-CE on)
            ("mfu_long", [py, path("benchmarks/mfu_transformer.py"),
                          "--model", "long"], 2400, None),
            # bottleneck map: ablation attribution of the flagship step
            # at batch 8 and 32 ("why doesn't batch 16-64 beat 8")
            ("step_breakdown",
             [py, path("benchmarks/step_breakdown.py")], 2400, None),
            ("step_breakdown_b32",
             [py, path("benchmarks/step_breakdown.py"),
              "--batch", "32"], 2400, None),
            # backward block-size tuning: the bwd 512 cap is an analytic
            # VMEM estimate (ops/flash_attention.py) never confirmed on
            # chip post-adaptive-tiling
            ("flash_bwd_sweep",
             [py, path("benchmarks/flash_block_sweep.py"), "--fwdbwd"],
             7200, None),
            # MFU sweep arm: remat trades activation HBM for FLOPs
            ("mfu_remat", [py, path("benchmarks/mfu_transformer.py"),
                           "--remat"], 1800, None),
        ]
        # after smoke/flagship/mid/flash, before the composite headline —
        # the multi-hour sweeps must not starve the priority stages
        stages[4:4] = extra

    # Collection loop. One-shot mode: a single pass, aborting on a
    # mid-collection wedge. Watch mode: a stage that fails does NOT end
    # the run — the loop returns to the watch and retries every
    # not-yet-succeeded stage on the next heal, until all stages landed,
    # a stage failed MAX_ATTEMPTS times with the backend healthy (a real
    # bug / a poison stage that wedges the tunnel — skip it, the rest of
    # the queue still deserves its shot), or the time budget ran out.
    # Round-5 lesson: the first heal lasted 30 min, the flagship wedged
    # it, and the old abort-on-wedge path threw away the whole round.
    done, attempts, wedges = set(), {}, {}

    def skipped(name):
        return (attempts.get(name, 0) >= MAX_ATTEMPTS
                or wedges.get(name, 0) >= MAX_WEDGE_VICTIMS)

    while True:
        if watching:
            hours_left = max(0.0, (deadline - time.time()) / 3600.0)
            if not watch_for_backend(interval_s, hours_left, out_path):
                return 1
        info = runner.wait_for_backend(max_tries=2, base_sleep_s=15.0)
        if not info:
            rec = {"stage": "tpu_health_gate", "ok": False,
                   "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                   "result": {"error": "no healthy TPU backend; not "
                              "running any on-chip stage"}}
            with open(out_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
            print(json.dumps(rec))
            if not (watching and time.time() + interval_s < deadline):
                # one-shot mode, or the watch budget is spent: give up.
                # In watch mode with budget left, a post-heal flap
                # (healthy probe, then re-wedge before the gate's
                # re-probe) loops back into the watch instead of
                # abandoning the run.
                return 1
            time.sleep(interval_s)
            continue
        print(f"# TPU healthy: {info.get('kind')}", flush=True)

        ran_this_pass = False
        n_done_before = len(done)
        with open(out_path, "a") as f:
            for name, cmd, timeout_s, env in stages:
                if name in done or skipped(name):
                    continue
                if ran_this_pass and not runner.probe_backend():
                    # the tunnel wedged mid-collection: stop this pass
                    # instead of burning each remaining stage's full
                    # timeout against a dead backend (collected stages
                    # stay on file; watch mode re-enters the watch)
                    rec = {"stage": f"health_gate_before_{name}",
                           "ok": False,
                           "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                           "result": {"error": "tunnel wedged "
                                      "mid-collection; "
                                      + ("pausing queue until next heal"
                                         if watching else
                                         "aborting remaining stages "
                                         "(one-shot mode)")}}
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
                    print(json.dumps(rec), flush=True)
                    break
                print(f"=== {name} ===", flush=True)
                ran_this_pass = True
                rec = run_stage(name, cmd, timeout_s, env=env)
                rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
                if rec["ok"]:
                    done.add(name)
                else:
                    # before charging the failure against the stage's
                    # retry budget, ask whether the backend is even
                    # alive: a stage that failed because the tunnel
                    # wedged UNDER it is a wedge victim — recording the
                    # attempt would let one bad evening permanently
                    # skip a flagship stage (ADVICE round 5)
                    if runner.probe_backend():
                        attempts[name] = attempts.get(name, 0) + 1
                        rec["attempt"] = attempts[name]
                    else:
                        rec["wedge_victim"] = True
                        wedges[name] = wedges.get(name, 0) + 1
                        rec["wedge_count"] = wedges[name]
                f.write(json.dumps(rec) + "\n")
                f.flush()
                print(json.dumps({k: rec[k]
                                  for k in ("stage", "ok", "wall_s",
                                            "attempt", "wedge_victim")
                                  if k in rec}),
                      flush=True)
                if rec.get("wedge_victim"):
                    # the backend is down: stop this pass now instead of
                    # feeding the remaining stages to the same wedge
                    # (watch mode re-enters the watch; one-shot aborts)
                    gate = {"stage": f"health_gate_after_{name}",
                            "ok": False,
                            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                            "result": {"error": "backend unhealthy after "
                                       "stage failure; failure not "
                                       "counted against MAX_ATTEMPTS"}}
                    f.write(json.dumps(gate) + "\n")
                    f.flush()
                    print(json.dumps(gate), flush=True)
                    break

        pending = [n for n, _, _, _ in stages
                   if n not in done and not skipped(n)]
        print(f"\n{len(done)}/{len(stages)} stages ok, "
              f"{len(pending)} pending -> {out_path}", flush=True)
        if len(done) > n_done_before:  # only passes that landed a stage
            if write_baseline:
                regenerate_baseline(py, out_path)
            else:
                print("# BASELINE.md regen skipped: non-default --out "
                      "(pass --write-baseline to force)", flush=True)
        if not pending:
            return 0 if len(done) == len(stages) else 1
        if not (watching and time.time() + interval_s < deadline):
            return 1
        # wedged (or flaky-failed) with watch budget left: re-watch,
        # then retry the pending stages on the next heal


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
