"""Run every on-chip measurement in one go (the TPU-recovery runbook).

The tunneled TPU backend in this environment comes and goes; when it is
healthy, this script collects everything BASELINE.md lists as pending:

1. flash-attention compiled validation + speedup table
   (benchmarks/flash_attention_tpu.py)
2. flagship MFU, with a small config sweep (batch x remat) to report the
   best achievable number (benchmarks/mfu_transformer.py)
3. KV-cache decode throughput (benchmarks/decode_tpu.py)
4. the headline bench record (bench.py)

Each stage runs as a subprocess with a hard timeout (a mid-run tunnel
wedge must not take the collector down) and everything is appended as
JSON lines to --out (default benchmarks/tpu_results.jsonl) for transfer
into BASELINE.md.

Usage: python benchmarks/run_all_tpu.py [--quick] [--out FILE]
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_stage(name: str, argv, timeout_s: int) -> dict:
    t0 = time.time()
    try:
        out = subprocess.run(argv, capture_output=True, text=True,
                             timeout=timeout_s, cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"stage": name, "ok": False,
                "error": f"timeout after {timeout_s}s"}
    rec = {"stage": name, "ok": out.returncode == 0,
           "wall_s": round(time.time() - t0, 1)}
    # take the last JSON-parseable line as the stage's record
    payload = None
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            payload = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    if payload is None:
        # some stages pretty-print one JSON object over many lines
        try:
            start = out.stdout.index("{")
            payload = json.loads(out.stdout[start:])
        except (ValueError, json.JSONDecodeError):
            pass
    if payload is not None:
        rec["result"] = payload
    elif not rec["ok"]:
        rec["error"] = (out.stderr or "no output").strip()[-800:]
    rec["stdout_tail"] = out.stdout.strip()[-1500:]
    return rec


def main(argv):
    quick = "--quick" in argv
    out_path = os.path.join(REPO, "benchmarks", "tpu_results.jsonl")
    if "--out" in argv:
        i = argv.index("--out")
        if i + 1 >= len(argv):
            print("usage: run_all_tpu.py [--quick] [--out FILE]",
                  file=sys.stderr)
            return 2
        out_path = argv[i + 1]
    py = sys.executable

    # bench.py already embeds the default-config MFU, min_ddp and decode
    # stages — don't re-measure them standalone (every duplicated minute
    # on the flaky tunnel is another chance to wedge mid-collection). The
    # outer timeout must exceed bench.py's own internal worst case
    # (probe retries + per-stage subprocess timeouts + CPU baselines),
    # or a late wedge would SIGKILL it and lose its partial record.
    stages = [("flash_attention",
               [py, "benchmarks/flash_attention_tpu.py"], 2400),
              ("bench_headline", [py, "bench.py"], 7200)]
    if not quick:
        # MFU sweep arm: remat trades activation HBM for FLOPs
        stages.insert(1, ("mfu_remat",
                          [py, "benchmarks/mfu_transformer.py", "--remat"],
                          1800))

    results = []
    with open(out_path, "a") as f:
        for name, cmd, timeout_s in stages:
            print(f"=== {name} ===", flush=True)
            rec = run_stage(name, cmd, timeout_s)
            rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
            results.append(rec)
            f.write(json.dumps(rec) + "\n")
            f.flush()
            print(json.dumps({k: rec[k] for k in ("stage", "ok", "wall_s")
                              if k in rec}), flush=True)

    n_ok = sum(r["ok"] for r in results)
    print(f"\n{n_ok}/{len(results)} stages ok -> {out_path}")
    return 0 if n_ok == len(results) else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
