"""Composed-stack scale sweep: the full train composition (hier
two-level ring x adaptive wire x bucketed overlap) at world sizes
w=2..max-sustainable on THIS host, one gated perfbench record per
world (``python bench.py --stage scale_sweep``; ROADMAP item 3's
"scale story" satellite).

Per world the sweep reports steps/s, the exposed-vs-overlapped comm
split (ms/step, straight from CommStats — the same numbers dpxmon
surfaces live) and bytes moved per step. The point is the SHAPE across
worlds, not any one absolute number: exposed_ms must not explode as
the world grows (overlap keeps hiding the wire), and bytes/step must
track the expected ring volume. ``DPX_SCALE_WORLDS=2,4,8`` overrides
the world list; worlds the host cannot sustain (beyond
``max(4, cpu_count)`` — world 4 is the repo's floor everywhere else:
soak, chaos, the dp8 family time-share smaller hosts) are skipped and
reported as skipped, never silently dropped.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SWEEP_STEPS = 12
WARMUP_STEPS = 3


def _sweep_worker(rank: int, world: int, q, steps: int) -> None:
    """One rank of the composed stack (module-level: spawn-picklable).
    Rank 0 puts the per-world row; timing is barrier-fenced so every
    rank measures the same window."""
    import jax
    import numpy as np

    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import make_train_step
    from distributed_pytorch_tpu.runtime import context

    dist.init_process_group(rank, world)
    try:
        model = models.DummyModel(in_dim=16, hidden_dim=128, n_classes=8)
        opt = optim.adamw(1e-3)

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(model.apply(p, x), y), {}

        step_fn = make_train_step(loss_fn, opt, grad_reduce="adaptive",
                                  overlap=True, comm_buckets=2)
        params = model.init(jax.random.PRNGKey(0))
        opt_state = step_fn.init_opt_state(params)
        rng = np.random.default_rng(7)
        batch = (rng.random((8, 16), dtype=np.float32),
                 rng.integers(0, 8, size=(8,)).astype(np.int32))

        comm = context.get_host_comm()
        for _ in range(WARMUP_STEPS):
            out = step_fn(params, opt_state, batch)
            params, opt_state = out.params, out.opt_state

        before = comm.stats.snapshot()
        comm.barrier()
        t0 = time.perf_counter()
        for _ in range(steps):
            out = step_fn(params, opt_state, batch)
            params, opt_state = out.params, out.opt_state
        comm.barrier()
        wall = time.perf_counter() - t0
        after = comm.stats.snapshot()

        if rank == 0:
            # per-step deltas over the fenced window (the barriers
            # themselves land in the totals; their cost is part of the
            # composed stack's step)
            d = {k: after[k] - before[k] for k in after}
            q.put({
                "world": world,
                "steps": steps,
                "steps_per_sec": round(steps / wall, 2),
                "exposed_ms": round(d["exposed_s"] * 1e3 / steps, 3),
                "overlapped_ms": round(d["overlapped_s"] * 1e3 / steps,
                                       3),
                "bytes_per_step": int(d["bytes"] / steps),
                "comm_calls_per_step": round(d["calls"] / steps, 1),
            })
    finally:
        dist.cleanup()


def _worlds() -> list:
    from distributed_pytorch_tpu.runtime import env as _env
    raw = _env.get("DPX_SCALE_WORLDS")
    if raw:
        return [int(w) for w in str(raw).split(",") if w.strip()]
    return [2, 4]


def run_scale_sweep() -> dict:
    """The sweep entry (``bench.py --stage scale_sweep``): one row per
    sustainable world, gated (steps/s and bytes/step must be positive
    at every world) and appended to the perfbench trajectory."""
    from distributed_pytorch_tpu.runtime import env as _env
    from distributed_pytorch_tpu.runtime.multiprocess import (
        launch_multiprocess)

    max_world = max(4, os.cpu_count() or 2)
    rows, skipped = [], []
    t0 = time.perf_counter()
    saved = _env.snapshot(["DPX_HIER_RING"])
    try:
        for world in _worlds():
            if world > max_world:
                skipped.append(world)
                print(f"# scale_sweep: skipping world {world} "
                      f"(> max sustainable {max_world})",
                      file=sys.stderr, flush=True)
                continue
            # hier ring only divides even worlds >= 4; below that the
            # flat ring IS the composed stack
            if world >= 4 and world % 2 == 0:
                _env.set("DPX_HIER_RING", "2")
            else:
                _env.unset("DPX_HIER_RING")
            ctx = mp.get_context("spawn")
            q = ctx.Queue()
            launch_multiprocess(_sweep_worker, world, q, SWEEP_STEPS)
            rows.append(q.get(timeout=60))
    finally:
        _env.restore(saved)
    wall_s = time.perf_counter() - t0

    ok = bool(rows) and all(
        r["steps_per_sec"] > 0 and r["bytes_per_step"] > 0
        for r in rows)
    result = {"scale_sweep": rows, "skipped_worlds": skipped,
              "ok": ok, "wall_s": round(wall_s, 1)}
    try:
        from bench import append_result
        append_result("scale_sweep", result, ok=ok, wall_s=wall_s)
    except Exception as e:  # noqa: BLE001 — the sweep result still prints
        print(f"# scale_sweep: trajectory append failed: {e}",
              file=sys.stderr)
    return result


if __name__ == "__main__":
    out = run_scale_sweep()
    print(json.dumps(out))
    raise SystemExit(0 if out["ok"] else 1)
