"""Serving benchmark: continuous batching vs static batching.

Two load shapes over the same request population:

- **closed loop**: every request submitted at t=0 (the floodgates
  case) — measures peak engine throughput and the TTFT spread induced
  by queueing behind the slot pool;
- **open loop**: Poisson arrivals at ``--rate`` req/s (seeded, so a
  run is reproducible) — the serving-paper methodology (the TTFT/TPOT
  numbers that matter under load are open-loop ones; arxiv 2605.25645
  makes the same point for TPU serving).

The baseline arm is **static batching**: the same requests grouped
FCFS into fixed batches of ``n_slots``, each batch served by ONE
compiled ``generate()`` call (everyone in the batch waits for the
whole batch's decode — the pre-Orca serving shape). Uniform prompt
length/max-new in that arm, since ``generate`` has no per-row
lengths; the engine arms use the mixed population.

Per-arm output: tokens/s, p50/p99 TTFT and TPOT (serve.metrics
definitions). Throughput numbers go through the perfbench statistical
policy (docs/benchmarking.md): each closed-loop arm runs
warmup-discarded repeated trials, tokens/s is the median with IQR and
the hard spread gate attached, and the engine-vs-static throughput
ratio is structurally withheld (with the gate's reason) when either
side comes back untrusted. The printed line is a schema-valid
``dpx.bench.record`` (perfbench/record.py).

The **shared-prefix arm** (serve/pages/, docs/serving.md) runs the same
seeded Poisson open loop over K "system prompts" round-robined across
N requests, paged+prefix-shared vs the unshared engine: TTFT p50/p99 as
gated medians, ``prefill_tokens_saved``, pool occupancy and hit rate,
and a ``vs_unshared_ttft_p50_x`` ratio withheld-or-printed per the
spread-gate policy; non-smoke runs append the record to
``benchmarks/tpu_results.jsonl`` (stage ``serve_shared``).

The **disaggregated arm** (serve/disagg/, docs/serving.md) runs the
same seeded Poisson open loop through the split engine (PrefillEngine +
DecodeEngine over the KV-page handoff) vs the monolithic paged engine
on the SAME population/arrivals: TTFT and TPOT p50/p99 as gated
medians, per-request handoff bytes, and a ``vs_monolithic_tpot_p99_x``
ratio printed-or-withheld per the spread gate; a second record (stage
``serve_disagg``) lands in ``benchmarks/tpu_results.jsonl`` on
non-smoke runs. A one-shot q8 run pins the handoff byte claim:
CommStats-booked bytes equal the ``wire.handoff_page_wire_bytes``
formula, at >= 3.5x under the f32 frame.

The **quantized resident pool arm** (``kv_dtype``, serve/pages/,
docs/serving.md "Quantized resident pool") reruns the shared-prefix
population with q8 block-quantized resident pages vs the exact f32
pool: the headline is the deterministic bytes-per-resident-token
capacity ratio (~3.9x at q8, ~7.5x at q4 — reported as pure storage
math), with TTFT p50/p99 gated medians, occupancy/hit-rate/evictions,
and the token-divergence fraction vs the exact pool; non-smoke runs
append stage ``serve_kvq``.

The **speculative decoding arm** (serve/spec/, docs/serving.md
"Speculative decoding") runs the mixed greedy population closed-loop
through the paged engine with a draft model proposing ``--draft-len``
tokens per iteration vs the SAME engine non-spec: acceptance rate and
tokens/iteration are the speculation headline, TPOT p50/p99 ride as
gated medians, and ``vs_nonspec_tpot_p50_x`` is printed-or-withheld
per the spread gate. Smoke self-drafts (draft == target) so the gate
set — accepted streams bit-exact vs ``generate()``, acceptance > 0,
verify compiles == {draft_len+1: 1}, ``tools/dpxmon.py replay`` rc 0
over the spec engine's metrics log — is deterministic; non-smoke runs
use a thin 1-layer draft and append stage ``serve_spec``.

The **fleet arm** (serve/fleet/, docs/serving.md "Multi-replica
fleet") runs the shared-prefix population through the prefix-affine
FleetRouter at R=1, 2, 4 replicas on the SAME seeded Poisson arrivals:
tokens/s and TTFT p50/p99 as gated medians per R, and
``vs_single_replica_r{2,4}_x`` throughput ratios printed-or-withheld
per the spread gate (on one CPU host the replicas share cores, so a
withheld-or-flat ratio is the honest outcome — the record is the
methodology rail for a real multi-host run). Non-smoke runs append
stage ``serve_fleet``. The separate ``--fleet-smoke`` mode is the CI
gate (tier1.yml ``fleet-smoke``): an R=2 fleet serves the
shared-prefix mix BIT-IDENTICAL to standalone ``generate()`` (and to
the R=1 fleet — routing never changes tokens) with affinity hit rate
> 0; one replica killed mid-run fails ONLY its in-flight requests as
typed replica-attributed ``ReplicaFailed`` while a co-resident stream
finishes bit-exact; and ``tools/dpxmon.py replay`` exits 0 over the
fleet's emitted metrics log.

``--smoke`` shrinks everything to a seconds-scale CPU run AND asserts
engine streams equal standalone ``generate()`` (all three engines —
continuous, paged+shared, disaggregated), that the shared arm's hit
rate is > 0 with ``prefill_tokens_saved`` exactly the analytic count
for the synthetic population, that the paged AND disagg engines kept
ONE decode program (zero on the prefill side of the split), and the
q8 handoff byte gates above — the CI job that keeps the engine loops
from rotting (tier1.yml).

Usage: python benchmarks/serve_bench.py [--smoke | --fleet-smoke]
           [--requests N] [--rate R] [--max-new N] [--seed S]
           [--slots N] [--trials N] [--warmup N] [--prefixes K]
           [--prefix-len N] [--draft-len K]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_model(smoke: bool):
    import jax
    from distributed_pytorch_tpu import models
    if smoke:
        model = models.TransformerLM(vocab=61, dim=32, n_layers=2,
                                     n_heads=4, n_kv_heads=2, pos="rope",
                                     max_seq=256)
    else:
        model = models.TransformerLM(vocab=512, dim=256, n_layers=4,
                                     n_heads=8, n_kv_heads=4, pos="rope",
                                     max_seq=1024)
    return model, model.init(jax.random.PRNGKey(0))


def make_requests(n, vocab, max_new, seed, uniform=False):
    """(prompt, SamplingParams, key) population; ``uniform`` pins one
    shape for the static-batching arm."""
    import jax
    from distributed_pytorch_tpu.serve import SamplingParams
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        s = 16 if uniform else int(rng.integers(4, 24))
        mn = max_new if uniform else int(rng.integers(max(2, max_new // 2),
                                                      max_new + 1))
        prompt = rng.integers(0, vocab, (s,)).astype(np.int32)
        out.append((prompt, SamplingParams(max_new_tokens=mn),
                    jax.random.PRNGKey(1000 + i)))
    return out


def make_shared_requests(n, vocab, max_new, seed, k_prefixes, prefix_len,
                         tail_max):
    """The shared-prefix serving population: ``k_prefixes`` "system
    prompts" of ``prefix_len`` tokens round-robined over ``n`` requests,
    each with a private random tail — the consumer-traffic shape the
    paged prefix cache exists for (the first occurrence of each prefix
    is cold, every later one shares its full pages)."""
    import jax
    from distributed_pytorch_tpu.serve import SamplingParams
    rng = np.random.default_rng(seed + 7)
    prefixes = [rng.integers(0, vocab, (prefix_len,)).astype(np.int32)
                for _ in range(k_prefixes)]
    out = []
    for i in range(n):
        t = int(rng.integers(1, tail_max + 1))
        prompt = np.concatenate(
            [prefixes[i % k_prefixes],
             rng.integers(0, vocab, (t,))]).astype(np.int32)
        out.append((prompt, SamplingParams(max_new_tokens=max_new),
                    jax.random.PRNGKey(2000 + i)))
    return out


def run_engine(model, params, reqs, n_slots, max_len, rate=None, seed=0,
               paged=False, page_len=None, prefix_share=True,
               kv_dtype=None, draft_model=None, draft_params=None,
               draft_len=None, metrics=None, log_every=16):
    """Submit ``reqs`` (closed loop, or Poisson open loop at ``rate``)
    and aggregate per-request SLO records. A non-None ``draft_model``
    turns on speculative decoding (serve/spec/) and attaches the
    engine's speculation accounting as ``rep["spec"]``."""
    from distributed_pytorch_tpu.serve import (EngineConfig,
                                               InferenceEngine, aggregate)
    eng = InferenceEngine(model, params,
                          EngineConfig(n_slots=n_slots, max_len=max_len,
                                       paged=paged, page_len=page_len,
                                       prefix_share=prefix_share,
                                       kv_dtype=kv_dtype,
                                       spec_decode=draft_model is not None,
                                       draft_model=draft_model,
                                       draft_params=draft_params,
                                       draft_len=draft_len,
                                       metrics=metrics,
                                       log_every=log_every))
    rng = np.random.default_rng(seed)
    handles = []
    t0 = time.monotonic()
    with eng:
        for prompt, sp, key in reqs:
            if rate is not None:
                time.sleep(rng.exponential(1.0 / rate))
            handles.append(eng.submit(prompt, sp, rng=key))
        outs = [h.result(timeout=600) for h in handles]
    wall = time.monotonic() - t0
    rep = aggregate([h.metrics for h in handles], wall_s=wall)
    st = eng.stats()
    rep["stats"] = {k: v for k, v in st.items()
                    if k in ("iterations", "decode_compiles",
                             "prefill_compiles", "sample_compiles")}
    if paged:
        rep["pages"] = st["pages"]
    if draft_model is not None:
        rep["spec"] = st["spec"]
    return rep, outs


def run_disagg(model, params, reqs, n_slots, max_len, rate=None, seed=0,
               page_len=None, width="f32"):
    """Submit ``reqs`` through the disaggregated split (closed loop, or
    Poisson open loop at ``rate``) and aggregate per-request records —
    which now carry the TTFT decomposition and handoff bytes."""
    from distributed_pytorch_tpu.serve import (DisaggConfig, DisaggEngine,
                                               aggregate)
    eng = DisaggEngine(model, params,
                       DisaggConfig(n_slots=n_slots, max_len=max_len,
                                    page_len=page_len,
                                    handoff_width=width))
    rng = np.random.default_rng(seed)
    handles = []
    t0 = time.monotonic()
    with eng:
        for prompt, sp, key in reqs:
            if rate is not None:
                time.sleep(rng.exponential(1.0 / rate))
            handles.append(eng.submit(prompt, sp, rng=key))
        outs = [h.result(timeout=600) for h in handles]
    wall = time.monotonic() - t0
    rep = aggregate([h.metrics for h in handles], wall_s=wall)
    st = eng.stats()
    rep["stats"] = {
        "decode_compiles": st["decode"]["decode_compiles"],
        "prefill_side_decode_compiles": st["prefill"]["decode_compiles"],
        "prefill_compiles": st["prefill"]["prefill_compiles"],
    }
    rep["handoff"] = st["handoff"]
    return rep, outs


def run_fleet(model, params, reqs, n_replicas, n_slots, max_len,
              rate=None, seed=0, page_len=None, metrics=None):
    """Submit ``reqs`` through an R-replica prefix-affine fleet
    (closed loop, or Poisson open loop at ``rate``) and aggregate the
    per-request SLO records, with the fleet routing counters
    attached."""
    from distributed_pytorch_tpu.serve import EngineConfig, aggregate
    from distributed_pytorch_tpu.serve.fleet import (FleetConfig,
                                                     FleetRouter)
    fleet = FleetRouter(
        model, params,
        FleetConfig(n_replicas=n_replicas,
                    engine=EngineConfig(n_slots=n_slots, max_len=max_len,
                                        paged=page_len is not None,
                                        page_len=page_len),
                    metrics=metrics))
    rng = np.random.default_rng(seed)
    handles = []
    t0 = time.monotonic()
    with fleet:
        for prompt, sp, key in reqs:
            if rate is not None:
                time.sleep(rng.exponential(1.0 / rate))
            handles.append(fleet.submit(prompt, sp, rng=key))
        outs = [h.result(timeout=600) for h in handles]
    wall = time.monotonic() - t0
    rep = aggregate([h.metrics for h in handles], wall_s=wall)
    fst = fleet.stats()
    rep["fleet"] = {"replicas": n_replicas, "routes": fst["routes"],
                    "spills": fst["spills"],
                    "route_affinity_hit_rate":
                        fst["route_affinity_hit_rate"]}
    return rep, outs


def run_static(model, params, reqs, n_slots, max_len):
    """Static batching: FCFS groups of ``n_slots`` through one compiled
    generate() each; every request's TTFT is its group's full wall time
    (tokens only exist when the whole batch finishes)."""
    import jax
    import jax.numpy as jnp
    from distributed_pytorch_tpu.models.generate import make_generate_fn
    from distributed_pytorch_tpu.serve import aggregate
    sp = reqs[0][1]
    fn = jax.jit(make_generate_fn(model, sp.max_new_tokens,
                                  max_len=max_len))
    # compile lands inside the wall, same as the engine arm (both pay
    # their first-call compiles in the measured region)
    records, t0 = [], time.monotonic()
    for g0 in range(0, len(reqs), n_slots):
        group = reqs[g0:g0 + n_slots]
        prompts = jnp.asarray(np.stack([p for p, _, _ in group]))
        gt0 = time.monotonic()
        toks = fn(params, prompts, group[0][2])
        jax.block_until_ready(toks)
        gt1 = time.monotonic()
        for i in range(len(group)):
            n = sp.max_new_tokens
            records.append({
                "request_id": g0 + i, "outcome": "ok",
                "prompt_len": int(prompts.shape[1]), "n_tokens": n,
                # all tokens arrive at batch completion: TTFT is the
                # group wall from t=0 (closed loop), TPOT the amortized
                # per-token group time
                "ttft_ms": (gt1 - t0) * 1e3,
                "tpot_ms": (gt1 - gt0) * 1e3 / n,
                "queue_ms": (gt0 - t0) * 1e3,
            })
    return aggregate(records, wall_s=time.monotonic() - t0)


def measured_stats(run_once, keys, *, warmup, trials,
                   absent_as_zero=("prefill_tokens_saved",)):
    """``measured_arm`` generalized to several scalar keys — the
    shared-prefix latency arms gate TTFT p50/p99 medians (and the
    deterministic prefill-savings count), not tokens/s.

    A key missing from a trial rep is a HARD error (KeyError), never a
    silent 0 — for a lower-is-better latency a fabricated 0 would be a
    perfect trusted median, exactly the null-laundering the perfbench
    schema forbids.  The one exception is ``absent_as_zero``:
    ``aggregate()`` legitimately omits ``prefill_tokens_saved`` when
    nothing was saved, and 0 is its honest (direction=higher,
    pessimistic) value."""
    from distributed_pytorch_tpu.perfbench import stats as pbstats
    reps = [run_once() for _ in range(warmup + trials)]
    sts = {}
    for k in keys:
        vals = []
        for i, r in enumerate(reps):
            v = r.get(k)
            if v is None:
                if k in absent_as_zero:
                    v = 0
                else:
                    raise KeyError(
                        f"metric {k!r} absent from trial {i}'s aggregate "
                        f"— refusing to launder a missing measurement "
                        f"into a 0")
            vals.append(v)
        sts[k] = pbstats.summarize(vals, warmup=warmup)
    rep = dict(reps[-1])
    for k, st in sts.items():
        rep[k] = round(st.median, 2)
        rep[k + "_trials"] = st.to_dict(nd=2)
    return rep, sts


def measured_arm(run_once, *, warmup, trials):
    """Repeated-trial wrapper for one throughput arm: runs ``run_once``
    (returning an aggregate rep with ``tokens_per_sec``) ``warmup +
    trials`` times under the perfbench policy.  The first trial pays the
    arm's jit compiles — exactly the cold-start artifact the warmup
    discard exists for.  Returns ``(last rep + trials detail, stats)``."""
    rep, sts = measured_stats(run_once, ("tokens_per_sec",),
                              warmup=warmup, trials=trials,
                              absent_as_zero=())
    return rep, sts["tokens_per_sec"]


def fleet_smoke(argv):
    """The CI fleet gate (tier1.yml ``fleet-smoke``): an R=2
    prefix-affine fleet serves the shared-prefix mix BIT-IDENTICAL to
    both standalone ``generate()`` and an R=1 fleet (routing never
    changes tokens) with affinity hit rate > 0; one replica killed
    mid-run fails ONLY its in-flight request as typed
    replica-attributed ``ReplicaFailed`` while a co-resident stream on
    the survivor finishes bit-exact and a same-id revive serves again;
    and ``tools/dpxmon.py replay`` exits 0 over the fleet's emitted
    metrics log (strict snapshot validation + the replica-failure
    health stream recovering)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from benchmarks.soak import _run_cli
    from distributed_pytorch_tpu.models.generate import make_generate_fn
    from distributed_pytorch_tpu.serve import EngineConfig, SamplingParams
    from distributed_pytorch_tpu.serve.fleet import (FleetConfig,
                                                     FleetRouter,
                                                     ReplicaFailed)
    from distributed_pytorch_tpu.utils.logging import MetricsLogger

    model, params = build_model(True)
    max_len, page_len = 64, 8
    n_req, k_prefixes, prefix_len, max_new = 10, 2, 8, 8
    reqs = make_shared_requests(n_req, model.vocab, max_new, 0,
                                k_prefixes, prefix_len, tail_max=7)
    problems = []
    workdir = tempfile.mkdtemp(prefix="dpx_fleet_smoke_")
    log = os.path.join(workdir, "fleet_metrics.jsonl")

    # R=2 vs R=1 vs standalone: the determinism gate
    rep2, outs2 = run_fleet(model, params, reqs, 2, 2, max_len,
                            rate=50.0, seed=3, page_len=page_len,
                            metrics=MetricsLogger(log))
    _, outs1 = run_fleet(model, params, reqs, 1, 2, max_len,
                         rate=50.0, seed=3, page_len=page_len)
    for i, (a, b) in enumerate(zip(outs1, outs2)):
        if not np.array_equal(a, b):
            problems.append(f"request {i}: R=2 stream != R=1 stream")
    for i in (0, n_req // 2, n_req - 1):
        prompt, sp, key = reqs[i]
        ref = np.asarray(jax.jit(make_generate_fn(
            model, sp.max_new_tokens, max_len=max_len))(
            params, jnp.asarray(prompt[None]), key))[0]
        if not np.array_equal(outs2[i], ref):
            problems.append(f"request {i} diverged from standalone "
                            f"generate()")
    hit = rep2["fleet"]["route_affinity_hit_rate"] or 0.0
    if not hit > 0:
        problems.append(f"affinity hit rate {hit} not > 0")

    # kill one replica mid-run: victim-only typed failure, co-resident
    # bit-exact, same-id revive serves again
    fleet = FleetRouter(
        model, params,
        FleetConfig(n_replicas=2,
                    engine=EngineConfig(n_slots=2, max_len=max_len,
                                        paged=True, page_len=page_len),
                    metrics=MetricsLogger(log), log_every=4))
    rng = np.random.default_rng(5)
    with fleet:
        fleet.submit(reqs[0][0][:6],
                     SamplingParams(max_new_tokens=2)).result(timeout=120)
        pa = reqs[0][0]
        victim = fleet.home_of(pa)
        # everything the kill window doesn't need happens BEFORE the
        # victim stream starts: the off-victim prompt scan and the key
        # constructions would otherwise eat the in-flight runway
        q = None
        for _ in range(64):        # a prompt homed OFF the victim
            cand = rng.integers(0, model.vocab, (10,)).astype(np.int32)
            if fleet.home_of(cand) != victim:
                q = cand
                break
        ka, kb = jax.random.PRNGKey(7), jax.random.PRNGKey(8)
        kc = jax.random.PRNGKey(9)
        spb, spc = (SamplingParams(max_new_tokens=6),
                    SamplingParams(max_new_tokens=40))
        if q is None:
            problems.append("no off-victim prompt found in 64 draws")
        else:
            # the co-resident stream starts FIRST (on the survivor),
            # then the victim stream; the kill lands the moment the
            # victim stream has a token in flight — nothing else sits
            # in that window (this model decodes a token every few ms,
            # so any work between first-token and kill loses the race)
            hc = fleet.submit(q, spc, rng=kc)
            ha = fleet.submit(pa, SamplingParams(max_new_tokens=45),
                              rng=ka)
            while not ha.tokens:   # in flight on its home replica
                time.sleep(0.005)
            fleet.kill_replica(victim, reason="fleet_smoke_kill")
            try:
                ha.result(timeout=120)
                problems.append("in-flight request on the killed "
                                "replica did not fail")
            except ReplicaFailed as e:
                if e.replica != victim or e.request_id != ha.request_id:
                    problems.append(
                        f"ReplicaFailed misattributed: replica="
                        f"{e.replica} request={e.request_id} (wanted "
                        f"{victim}/{ha.request_id})")
            except Exception as e:  # noqa: BLE001 — the gate reports it
                problems.append(f"in-flight failure not typed "
                                f"ReplicaFailed: {type(e).__name__}")
            out_c = hc.result(timeout=120)
            ref_c = np.asarray(jax.jit(make_generate_fn(
                model, spc.max_new_tokens, max_len=max_len))(
                params, jnp.asarray(q[None]), kc))[0]
            if not np.array_equal(out_c, ref_c):
                problems.append("co-resident stream diverged after "
                                "the kill")
            # the dead replica's shard re-homes: a post-kill submit of
            # the SAME prompt must land on the survivor, bit-exact
            if fleet.home_of(pa) == victim:
                problems.append("prefix shard did not re-home off the "
                                "killed replica")
            hb = fleet.submit(pa, spb, rng=kb)
            if hb.replica == victim:
                problems.append("post-kill submit routed to the dead "
                                "replica")
            out_b = hb.result(timeout=120)
            ref_b = np.asarray(jax.jit(make_generate_fn(
                model, spb.max_new_tokens, max_len=max_len))(
                params, jnp.asarray(pa[None]), kb))[0]
            if not np.array_equal(out_b, ref_b):
                problems.append("re-homed stream diverged from "
                                "standalone generate()")
            fleet.revive_replica(victim)
            out_d = fleet.submit(
                pa, SamplingParams(max_new_tokens=4)).result(timeout=120)
            if not len(out_d) > 0:
                problems.append("revived replica served nothing")
        fleet.emit_snapshot()
        fleet.emit_snapshot()

    # replay the fleet's own log: strict validation + the
    # replica-failure stream must degrade AND recover (rc 0); the rule
    # spec is the fleet SLO (queue ceiling) — process-growth rules
    # don't apply to a log whose snapshots straddle jit compiles
    rc, out = _run_cli("tools.dpxmon",
                       ["replay", log, "--rules",
                        "fleet.max_queue_depth<=64"])
    if rc != 0:
        problems.append(f"dpxmon replay over the fleet log exited "
                        f"{rc}: {out.strip()[-200:]}")

    if problems:
        print(json.dumps({"bench": "serve_fleet",
                          "error": "; ".join(problems)}))
        return 1
    shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps({"bench": "serve_fleet", "fleet_smoke_gates": {
        "engine_matches_generate": True,
        "matches_single_replica": True,
        "route_affinity_hit_rate": round(hit, 4),
        "spills": rep2["fleet"]["spills"],
        "kill_typed_attributed": True,
        "coresident_bit_exact": True,
        "dpxmon_replay_rc": rc}}))
    return 0


def main(argv):
    if "--fleet-smoke" in argv:
        return fleet_smoke(argv)
    smoke = "--smoke" in argv

    def flag(name, default):
        if name in argv:
            return type(default)(argv[argv.index(name) + 1])
        return default

    n_slots = flag("--slots", 4)
    n_req = flag("--requests", 12 if smoke else 64)
    max_new = flag("--max-new", 8 if smoke else 64)
    rate = flag("--rate", 0.0) or (50.0 if smoke else 8.0)
    seed = flag("--seed", 0)
    max_len = 64 if smoke else 512
    from distributed_pytorch_tpu.perfbench import record as pbrecord
    from distributed_pytorch_tpu.perfbench import stats as pbstats
    from distributed_pytorch_tpu.runtime import env as dpxenv
    warmup = flag("--warmup", 1 if smoke else
                  int(dpxenv.get("DPX_BENCH_WARMUP")))
    trials = flag("--trials", 3 if smoke else
                  int(dpxenv.get("DPX_BENCH_TRIALS")))

    model, params = build_model(smoke)
    rec = pbrecord.make_record("serve_engine_closed_tokens_per_sec",
                               "tokens_per_sec", device="cpu-loopback")
    rec.update({"bench": "serve", "smoke": smoke,
                "config": {"n_slots": n_slots, "n_requests": n_req,
                           "max_new": max_new, "rate_rps": rate,
                           "max_len": max_len, "vocab": model.vocab,
                           "dim": model.dim, "n_layers": model.n_layers,
                           "warmup": warmup, "trials": trials},
                "arms": {}})

    # closed loop (mixed population) — the headline arm. outs (for the
    # smoke correctness gate) come from the FIRST run: identical
    # submissions, and divergence would invalidate every trial equally.
    mixed = make_requests(n_req, model.vocab, max_new, seed)
    first = {}

    def closed_once():
        rep, outs = run_engine(model, params, mixed, n_slots, max_len)
        first.setdefault("outs", outs)
        return rep

    closed, closed_st = measured_arm(closed_once, warmup=warmup,
                                     trials=trials)
    outs = first["outs"]
    rec["arms"]["engine_closed"] = closed
    rec["value"] = round(closed_st.median, 2)
    rec["provenance"] = "measured"
    rec["trusted"] = closed_st.trusted
    if closed_st.trusted:
        rec.pop("untrusted_reason", None)
    else:
        rec["untrusted_reason"] = closed_st.untrusted_reason
    rec["metrics"]["serve_engine_closed_tokens_per_sec"] = \
        pbrecord.make_metric(None, "tokens_per_sec", stats=closed_st)

    if smoke:
        # correctness gate: engine streams == standalone generate()
        import jax
        import jax.numpy as jnp
        from distributed_pytorch_tpu.models.generate import make_generate_fn
        for i in (0, n_req // 2, n_req - 1):
            prompt, sp, key = mixed[i]
            ref = np.asarray(jax.jit(make_generate_fn(
                model, sp.max_new_tokens, max_len=max_len))(
                params, jnp.asarray(prompt[None]), key))[0]
            if not np.array_equal(outs[i], ref):
                print(json.dumps({"bench": "serve", "error":
                                  f"request {i} diverged from "
                                  f"standalone generate()"}))
                return 1
        rec["engine_matches_generate"] = True

    # open loop (Poisson arrivals, mixed population)
    open_rep, _ = run_engine(model, params, mixed, n_slots, max_len,
                             rate=rate, seed=seed + 1)
    rec["arms"]["engine_open_poisson"] = open_rep

    # static-batching baseline (uniform shapes; generate has no per-row
    # lengths) — same trial policy on BOTH sides of the ratio
    uni = make_requests(n_req, model.vocab, max_new, seed, uniform=True)
    static, static_st = measured_arm(
        lambda: run_static(model, params, uni, n_slots, max_len),
        warmup=warmup, trials=trials)
    rec["arms"]["static_batch"] = static
    rec["metrics"]["serve_static_batch_tokens_per_sec"] = \
        pbrecord.make_metric(None, "tokens_per_sec", stats=static_st)
    eng_uni, eng_uni_st = measured_arm(
        lambda: run_engine(model, params, uni, n_slots, max_len)[0],
        warmup=warmup, trials=trials)
    rec["arms"]["engine_closed_uniform"] = eng_uni
    rec["metrics"]["serve_engine_uniform_tokens_per_sec"] = \
        pbrecord.make_metric(None, "tokens_per_sec", stats=eng_uni_st)

    # continuous-vs-static throughput: printed only when both sides pass
    # the spread gate, withheld with the gate's reason otherwise
    ratio, why = pbstats.gated_ratio(eng_uni_st, static_st)
    if ratio is not None:
        rec["engine_vs_static_tokens_x"] = round(ratio, 2)
    else:
        rec["engine_vs_static_tokens_x_withheld"] = why
    st, en = static, eng_uni
    if st.get("ttft_ms_p50") and en.get("ttft_ms_p50"):
        # last-trial latency detail (a distribution, not a gated median)
        rec["engine_vs_static_ttft_p50_x"] = round(
            st["ttft_ms_p50"] / en["ttft_ms_p50"], 2)

    # ---- shared-prefix paged arm (serve/pages/, ROADMAP item 4) ----
    # K "system prompts" round-robined over N requests, seeded Poisson
    # open loop: the paged+prefix-shared engine vs the unshared engine
    # on the SAME population/arrivals. TTFT p50/p99 go through the
    # spread-gate policy; vs_unshared is withheld with the gate's
    # reason when either side comes back untrusted.
    k_prefixes = flag("--prefixes", 3 if smoke else 8)
    prefix_len = flag("--prefix-len", 16 if smoke else 128)
    page_len = 8 if smoke else 16
    tail_max = 7 if smoke else 32
    shared_reqs = make_shared_requests(n_req, model.vocab, max_new, seed,
                                       k_prefixes, prefix_len, tail_max)
    rec["config"].update({"k_prefixes": k_prefixes,
                          "prefix_len": prefix_len,
                          "page_len": page_len, "tail_max": tail_max})
    first_shared = {}

    def shared_once():
        rep, outs = run_engine(model, params, shared_reqs, n_slots,
                               max_len, rate=rate, seed=seed + 2,
                               paged=True, page_len=page_len)
        first_shared.setdefault("outs", outs)
        first_shared.setdefault("rep", rep)
        return rep

    shared_rep, shared_st = measured_stats(
        shared_once,
        ("ttft_ms_p50", "ttft_ms_p99", "prefill_tokens_saved"),
        warmup=warmup, trials=trials)
    rec["arms"]["engine_paged_shared"] = shared_rep
    unshared_rep, unshared_st = measured_stats(
        lambda: run_engine(model, params, shared_reqs, n_slots, max_len,
                           rate=rate, seed=seed + 2)[0],
        ("ttft_ms_p50", "ttft_ms_p99"), warmup=warmup, trials=trials)
    rec["arms"]["engine_unshared_open"] = unshared_rep
    for name, stx in (
            ("serve_shared_ttft_ms_p50", shared_st["ttft_ms_p50"]),
            ("serve_shared_ttft_ms_p99", shared_st["ttft_ms_p99"]),
            ("serve_unshared_ttft_ms_p50", unshared_st["ttft_ms_p50"]),
            ("serve_unshared_ttft_ms_p99", unshared_st["ttft_ms_p99"]),
            ("serve_prefill_tokens_saved",
             shared_st["prefill_tokens_saved"])):
        rec["metrics"][name] = pbrecord.make_metric(
            None, "ms" if "ttft" in name else "tokens", stats=stx,
            direction="lower" if "ttft" in name else "higher")
    pages = first_shared["rep"]["pages"]
    rec["metrics"]["serve_paged_pool_occupancy"] = pbrecord.make_metric(
        round(pages["pool_occupancy"], 4), "frac")
    rec["metrics"]["serve_paged_prefix_hit_rate"] = pbrecord.make_metric(
        round(pages["prefix_hit_rate"] or 0.0, 4), "frac")
    # TTFT is lower-better, so the speedup ratio is unshared/shared
    vs, why = pbstats.gated_ratio(unshared_st["ttft_ms_p50"],
                                  shared_st["ttft_ms_p50"])
    if vs is not None:
        rec["vs_unshared_ttft_p50_x"] = round(vs, 2)
    else:
        rec["vs_unshared_ttft_p50_withheld"] = why

    if smoke:
        # the shared-prefix CI gates (tier1.yml): sharing must actually
        # happen, save EXACTLY the analytic token count for this
        # synthetic population ((n-k) repeats x prefix_len — smoke
        # tails are < one page so nothing else can be indexed), keep
        # the one-decode-program discipline, and stay bit-exact
        problems = []
        hit_rate = pages["prefix_hit_rate"] or 0.0
        if not hit_rate > 0:
            problems.append(f"prefix hit rate {hit_rate} not > 0")
        analytic = (n_req - k_prefixes) * prefix_len
        got_saved = first_shared["rep"].get("prefill_tokens_saved", 0)
        if got_saved != analytic:
            problems.append(f"prefill_tokens_saved {got_saved} != "
                            f"analytic {analytic}")
        if first_shared["rep"]["stats"]["decode_compiles"] != 1:
            problems.append(
                f"paged decode_compiles "
                f"{first_shared['rep']['stats']['decode_compiles']} != 1")
        import jax
        import jax.numpy as jnp
        from distributed_pytorch_tpu.models.generate import make_generate_fn
        for i in (0, k_prefixes, n_req - 1):   # cold + shared samples
            prompt, sp, key = shared_reqs[i]
            ref = np.asarray(jax.jit(make_generate_fn(
                model, sp.max_new_tokens, max_len=max_len))(
                params, jnp.asarray(prompt[None]), key))[0]
            if not np.array_equal(first_shared["outs"][i], ref):
                problems.append(f"shared request {i} diverged from "
                                f"standalone generate()")
        if problems:
            print(json.dumps({"bench": "serve", "error":
                              "; ".join(problems)}))
            return 1
        rec["shared_prefix_gates"] = {
            "prefix_hit_rate": round(hit_rate, 4),
            "prefill_tokens_saved": got_saved, "analytic": analytic,
            "engine_matches_generate": True}

    # ---- disaggregated prefill/decode arm (serve/disagg/) ----
    # the SAME mixed population and Poisson arrivals through the split
    # engine vs the monolithic paged engine; TTFT/TPOT p50/p99 as gated
    # medians, vs_monolithic withheld-or-printed per the spread gate,
    # and the q8 handoff byte claim pinned against the wire formula.
    from distributed_pytorch_tpu.serve.disagg import kv_wire_bytes
    rec_d = pbrecord.make_record("serve_disagg_tpot_ms_p99", "ms",
                                 device="cpu-loopback")
    rec_d.update({"bench": "serve_disagg", "smoke": smoke,
                  "config": dict(rec["config"], page_len=page_len,
                                 handoff_width="f32"),
                  "arms": {}})
    lat_keys = ("ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50",
                "tpot_ms_p99")
    first_disagg = {}

    def disagg_once():
        rep, outs = run_disagg(model, params, mixed, n_slots, max_len,
                               rate=rate, seed=seed + 3,
                               page_len=page_len)
        first_disagg.setdefault("outs", outs)
        first_disagg.setdefault("rep", rep)
        return rep

    disagg_rep, disagg_st = measured_stats(
        disagg_once, lat_keys, warmup=warmup, trials=trials,
        absent_as_zero=())
    rec_d["arms"]["engine_disagg_open"] = disagg_rep
    mono_rep, mono_st = measured_stats(
        lambda: run_engine(model, params, mixed, n_slots, max_len,
                           rate=rate, seed=seed + 3, paged=True,
                           page_len=page_len)[0],
        lat_keys, warmup=warmup, trials=trials, absent_as_zero=())
    rec_d["arms"]["engine_monolithic_open"] = mono_rep
    for k in lat_keys:
        rec_d["metrics"][f"serve_disagg_{k}"] = pbrecord.make_metric(
            None, "ms", stats=disagg_st[k], direction="lower")
        rec_d["metrics"][f"serve_monolithic_{k}"] = pbrecord.make_metric(
            None, "ms", stats=mono_st[k], direction="lower")
    rec_d["value"] = round(disagg_st["tpot_ms_p99"].median, 2)
    rec_d["provenance"] = "measured"
    rec_d["trusted"] = disagg_st["tpot_ms_p99"].trusted
    if rec_d["trusted"]:
        rec_d.pop("untrusted_reason", None)
    else:
        rec_d["untrusted_reason"] = \
            disagg_st["tpot_ms_p99"].untrusted_reason
    # TPOT is lower-better: >1 means the split decodes at a faster
    # cadence than the prefill-interleaved monolithic loop
    vs, why = pbstats.gated_ratio(mono_st["tpot_ms_p99"],
                                  disagg_st["tpot_ms_p99"])
    if vs is not None:
        rec_d["vs_monolithic_tpot_p99_x"] = round(vs, 2)
    else:
        rec_d["vs_monolithic_tpot_p99_withheld"] = why
    # handoff byte claim: one q8 closed-loop pass over the population;
    # booked bytes must EQUAL the wire formula on both widths and the
    # q8 frame must be >= 3.5x under f32
    q8_rep, _ = run_disagg(model, params, mixed, n_slots, max_len,
                           page_len=page_len, width="q8")
    pe = (getattr(model, "n_kv_heads", model.n_heads) * page_len
          * (model.dim // model.n_heads))
    f32_formula = sum(
        kv_wire_bytes(model.n_layers, -(-len(p) // page_len), pe, None)
        for p, _, _ in mixed)
    q8_formula = sum(
        kv_wire_bytes(model.n_layers, -(-len(p) // page_len), pe, 8)
        for p, _, _ in mixed)
    f32_bytes = first_disagg["rep"]["handoff"]["bytes_sent"]
    q8_bytes = q8_rep["handoff"]["bytes_sent"]
    rec_d["handoff"] = {
        "f32_bytes": f32_bytes, "q8_bytes": q8_bytes,
        "f32_formula": f32_formula, "q8_formula": q8_formula,
        "q8_vs_f32_bytes_x": round(f32_bytes / q8_bytes, 2),
        "page_elems": pe,
        "handoff_ms_p50": first_disagg["rep"].get("handoff_ms_p50"),
    }
    rec_d["metrics"]["serve_disagg_q8_vs_f32_bytes_x"] = \
        pbrecord.make_metric(round(f32_bytes / q8_bytes, 2), "x")

    if smoke:
        # the disagg CI gates (tier1.yml): exact-handoff streams must
        # equal standalone generate(), the q8 handoff must book >= 3.5x
        # fewer bytes than f32 with CommStats EXACTLY the wire formula,
        # and the split must keep ONE decode program (zero on the
        # prefill side)
        import jax
        import jax.numpy as jnp
        from distributed_pytorch_tpu.models.generate import make_generate_fn
        problems = []
        for i in (0, n_req // 2, n_req - 1):
            prompt, sp, key = mixed[i]
            ref = np.asarray(jax.jit(make_generate_fn(
                model, sp.max_new_tokens, max_len=max_len))(
                params, jnp.asarray(prompt[None]), key))[0]
            if not np.array_equal(first_disagg["outs"][i], ref):
                problems.append(f"disagg request {i} diverged from "
                                f"standalone generate()")
        if f32_bytes != f32_formula:
            problems.append(f"f32 handoff bytes {f32_bytes} != wire "
                            f"formula {f32_formula}")
        if q8_bytes != q8_formula:
            problems.append(f"q8 handoff bytes {q8_bytes} != wire "
                            f"formula {q8_formula}")
        if not f32_bytes / q8_bytes >= 3.5:
            problems.append(f"q8 handoff byte cut "
                            f"{f32_bytes / q8_bytes:.2f}x < 3.5x")
        st_d = first_disagg["rep"]["stats"]
        if st_d["decode_compiles"] != 1:
            problems.append(f"disagg decode_compiles "
                            f"{st_d['decode_compiles']} != 1")
        if st_d["prefill_side_decode_compiles"] != 0:
            problems.append(
                f"prefill-side decode_compiles "
                f"{st_d['prefill_side_decode_compiles']} != 0")
        if problems:
            print(json.dumps({"bench": "serve_disagg",
                              "error": "; ".join(problems)}))
            return 1
        rec_d["disagg_gates"] = {
            "engine_matches_generate": True,
            "q8_vs_f32_bytes_x": round(f32_bytes / q8_bytes, 2),
            "commstats_equals_formula": True,
            "decode_compiles": 1}

    # ---- quantized resident pool arm (serve/pages/ kv_dtype) ----
    # the shared-prefix population through the paged engine at q8
    # resident storage vs the exact f32 pool: capacity per byte is the
    # headline (a deterministic storage-layout ratio), TTFT p50/p99
    # ride as gated medians, and the smoke asserts the quality bound —
    # cold first tokens EXACT (in-register prefill, zero quant error at
    # admission), bounded token divergence on the mixed cold/shared
    # population, and the one-decode-program discipline intact.
    from distributed_pytorch_tpu.serve.pages import PagedSlotPool
    rec_q = pbrecord.make_record("serve_kvq_capacity_x", "x",
                                 device="cpu-loopback")
    rec_q.update({"bench": "serve_kvq", "smoke": smoke,
                  "config": dict(rec["config"], page_len=page_len,
                                 kv_dtype="q8"),
                  "arms": {}})
    first_kvq = {}

    def kvq_once():
        # closed loop on purpose: identical admission order on every
        # trial makes the q8-vs-f32 token comparison deterministic
        rep, outs = run_engine(model, params, shared_reqs, n_slots,
                               max_len, paged=True, page_len=page_len,
                               kv_dtype="q8")
        first_kvq.setdefault("outs", outs)
        first_kvq.setdefault("rep", rep)
        return rep

    kvq_rep, kvq_st = measured_stats(
        kvq_once, ("ttft_ms_p50", "ttft_ms_p99"), warmup=warmup,
        trials=trials, absent_as_zero=())
    rec_q["arms"]["engine_paged_q8"] = kvq_rep
    f32_rep, f32_outs = run_engine(model, params, shared_reqs, n_slots,
                                   max_len, paged=True,
                                   page_len=page_len)
    rec_q["arms"]["engine_paged_f32"] = f32_rep
    for k in ("ttft_ms_p50", "ttft_ms_p99"):
        rec_q["metrics"][f"serve_kvq_{k}"] = pbrecord.make_metric(
            None, "ms", stats=kvq_st[k], direction="lower")
    pq = first_kvq["rep"]["pages"]
    pf = f32_rep["pages"]
    # q4 rides along as pure storage math — same constructor, no run
    q4_bpt = PagedSlotPool(
        model, n_slots, max_len, page_len=page_len,
        n_pages=n_slots * (-(-max_len // page_len)),
        kv_dtype="q4").bytes_per_resident_token()
    capacity_x = (pf["bytes_per_resident_token"]
                  / pq["bytes_per_resident_token"])
    div = float(np.mean([a != b
                         for x, y in zip(f32_outs, first_kvq["outs"])
                         for a, b in zip(x, y)]))
    rec_q["metrics"]["serve_kvq_bytes_per_token_f32"] = \
        pbrecord.make_metric(round(pf["bytes_per_resident_token"], 2),
                             "bytes", direction="lower")
    rec_q["metrics"]["serve_kvq_bytes_per_token_q8"] = \
        pbrecord.make_metric(round(pq["bytes_per_resident_token"], 2),
                             "bytes", direction="lower")
    rec_q["metrics"]["serve_kvq_bytes_per_token_q4"] = \
        pbrecord.make_metric(round(q4_bpt, 2), "bytes",
                             direction="lower")
    rec_q["metrics"]["serve_kvq_pool_occupancy"] = pbrecord.make_metric(
        round(pq["pool_occupancy"], 4), "frac")
    rec_q["metrics"]["serve_kvq_prefix_hit_rate"] = pbrecord.make_metric(
        round(pq["prefix_hit_rate"] or 0.0, 4), "frac")
    rec_q["metrics"]["serve_kvq_page_evictions"] = pbrecord.make_metric(
        pq["evictions"], "count")
    rec_q["metrics"]["serve_kvq_token_divergence"] = \
        pbrecord.make_metric(round(div, 4), "frac", direction="lower")
    # the headline is a deterministic storage-layout ratio, not a
    # timing sample — no spread gate applies
    rec_q["value"] = round(capacity_x, 2)
    rec_q["provenance"] = "measured"
    rec_q["trusted"] = True
    rec_q.pop("untrusted_reason", None)
    rec_q["kv_pool_bytes"] = {"f32": pf["kv_pool_bytes"],
                              "q8": pq["kv_pool_bytes"]}

    if smoke:
        # the quantized-pool CI gates (tier1.yml): ~4x resident pages
        # per byte at q8, cold first tokens bit-exact (their prefill
        # attends in-register f32 — quantization cannot touch token 0
        # of a cold prompt), bounded divergence on the mixed
        # cold/shared stream, ONE decode program
        problems = []
        if not capacity_x >= 3.5:
            problems.append(f"q8 capacity {capacity_x:.2f}x < 3.5x "
                            f"resident pages per byte")
        if first_kvq["rep"]["stats"]["decode_compiles"] != 1:
            problems.append(
                f"q8 decode_compiles "
                f"{first_kvq['rep']['stats']['decode_compiles']} != 1")
        for i in range(k_prefixes):   # the cold (first-occurrence) reqs
            if f32_outs[i][0] != first_kvq["outs"][i][0]:
                problems.append(f"cold request {i} first token "
                                f"{first_kvq['outs'][i][0]} != exact "
                                f"{f32_outs[i][0]}")
        if not div <= 0.25:
            problems.append(f"q8 token divergence {div:.3f} > 0.25 on "
                            f"the shared-prefix population")
        if problems:
            print(json.dumps({"bench": "serve_kvq",
                              "error": "; ".join(problems)}))
            return 1
        rec_q["kvq_gates"] = {
            "capacity_x": round(capacity_x, 2),
            "cold_first_tokens_exact": True,
            "token_divergence": round(div, 4),
            "decode_compiles": 1}

    # ---- speculative decoding arm (serve/spec/) ----
    # the mixed greedy population through the paged engine with a
    # draft proposing k tokens per iteration vs the SAME engine
    # non-spec on the SAME closed-loop population: acceptance rate and
    # tokens/iteration are the speculation headline, TPOT p50/p99 ride
    # as gated medians, and the TPOT speedup is printed-or-withheld
    # per the spread gate. Smoke self-drafts (draft == target) so the
    # wiring/accounting gates are deterministic (acceptance 1.0 by
    # construction); real runs use a thin 1-layer draft so acceptance
    # is a measurement, not a tautology.
    draft_len = flag("--draft-len", 3)
    if smoke:
        draft_model, draft_params = model, params
    else:
        import jax
        from distributed_pytorch_tpu import models
        draft_model = models.TransformerLM(
            vocab=model.vocab, dim=max(16, model.dim // 4), n_layers=1,
            n_heads=2, n_kv_heads=1, pos="rope", max_seq=model.max_seq)
        draft_params = draft_model.init(jax.random.PRNGKey(11))
    rec_s = pbrecord.make_record("serve_spec_tpot_ms_p50", "ms",
                                 device="cpu-loopback")
    rec_s.update({"bench": "serve_spec", "smoke": smoke,
                  "config": dict(rec["config"], page_len=page_len,
                                 draft_len=draft_len,
                                 draft="self" if smoke else "thin-1l"),
                  "arms": {}})
    spec_keys = ("tpot_ms_p50", "tpot_ms_p99")
    first_spec = {}

    def spec_once():
        rep, souts = run_engine(model, params, mixed, n_slots, max_len,
                                paged=True, page_len=page_len,
                                draft_model=draft_model,
                                draft_params=draft_params,
                                draft_len=draft_len)
        first_spec.setdefault("outs", souts)
        first_spec.setdefault("rep", rep)
        return rep

    spec_rep, spec_sts = measured_stats(spec_once, spec_keys,
                                        warmup=warmup, trials=trials,
                                        absent_as_zero=())
    rec_s["arms"]["engine_spec_closed"] = spec_rep
    nonspec_rep, nonspec_sts = measured_stats(
        lambda: run_engine(model, params, mixed, n_slots, max_len,
                           paged=True, page_len=page_len)[0],
        spec_keys, warmup=warmup, trials=trials, absent_as_zero=())
    rec_s["arms"]["engine_nonspec_closed"] = nonspec_rep
    for k in spec_keys:
        rec_s["metrics"][f"serve_spec_{k}"] = pbrecord.make_metric(
            None, "ms", stats=spec_sts[k], direction="lower")
        rec_s["metrics"][f"serve_nonspec_{k}"] = pbrecord.make_metric(
            None, "ms", stats=nonspec_sts[k], direction="lower")
    sp_st = first_spec["rep"]["spec"]
    rec_s["acceptance_rate"] = round(sp_st["acceptance_rate"] or 0.0, 4)
    rec_s["tokens_per_iteration"] = round(
        sp_st["tokens_per_iteration"] or 0.0, 4)
    rec_s["metrics"]["serve_spec_acceptance_rate"] = \
        pbrecord.make_metric(rec_s["acceptance_rate"], "frac")
    rec_s["metrics"]["serve_spec_tokens_per_iteration"] = \
        pbrecord.make_metric(rec_s["tokens_per_iteration"], "tokens")
    rec_s["value"] = round(spec_sts["tpot_ms_p50"].median, 2)
    rec_s["provenance"] = "measured"
    rec_s["trusted"] = spec_sts["tpot_ms_p50"].trusted
    if rec_s["trusted"]:
        rec_s.pop("untrusted_reason", None)
    else:
        rec_s["untrusted_reason"] = \
            spec_sts["tpot_ms_p50"].untrusted_reason
    # TPOT is lower-better: > 1 means speculation beats plain decode
    # on wall-clock cadence, not just on tokens/iteration
    vs, why = pbstats.gated_ratio(nonspec_sts["tpot_ms_p50"],
                                  spec_sts["tpot_ms_p50"])
    if vs is not None:
        rec_s["vs_nonspec_tpot_p50_x"] = round(vs, 2)
    else:
        rec_s["vs_nonspec_tpot_p50_withheld"] = why

    if smoke:
        # the spec CI gates (tier1.yml): speculation must be invisible
        # (accepted greedy streams == standalone generate() bit-exact),
        # must actually accept on this self-draft workload, must keep
        # the one-verify-program-per-bucket discipline, and the spec
        # engine's own metrics log (snapshots carrying the serve.spec_*
        # gauges) must replay clean through tools/dpxmon.py
        import shutil
        import tempfile

        import jax
        import jax.numpy as jnp

        from benchmarks.soak import _run_cli
        from distributed_pytorch_tpu.models.generate import make_generate_fn
        from distributed_pytorch_tpu.utils.logging import MetricsLogger
        problems = []
        for i in (0, n_req // 2, n_req - 1):
            prompt, sp_i, key = mixed[i]
            ref = np.asarray(jax.jit(make_generate_fn(
                model, sp_i.max_new_tokens, max_len=max_len))(
                params, jnp.asarray(prompt[None]), key))[0]
            if not np.array_equal(first_spec["outs"][i], ref):
                problems.append(f"spec request {i} diverged from "
                                f"standalone generate()")
        if not (sp_st["acceptance_rate"] or 0.0) > 0:
            problems.append(f"acceptance rate "
                            f"{sp_st['acceptance_rate']} not > 0 "
                            f"under the self-draft")
        if sp_st["verify_compiles"] != {draft_len + 1: 1}:
            problems.append(f"verify compiles "
                            f"{sp_st['verify_compiles']} != "
                            f"{{{draft_len + 1}: 1}}")
        # record-schema gate: the full-size record must land on real
        # hardware with the speculation fields present and the speedup
        # ratio either printed or withheld-with-reason — never absent
        for field in ("acceptance_rate", "tokens_per_iteration"):
            if field not in rec_s:
                problems.append(f"spec record missing {field}")
        if (("vs_nonspec_tpot_p50_x" in rec_s)
                == ("vs_nonspec_tpot_p50_withheld" in rec_s)):
            problems.append(
                "spec record must carry exactly one of "
                "vs_nonspec_tpot_p50_x / vs_nonspec_tpot_p50_withheld")
        workdir = tempfile.mkdtemp(prefix="dpx_spec_smoke_")
        log = os.path.join(workdir, "spec_metrics.jsonl")
        run_engine(model, params, mixed, n_slots, max_len, paged=True,
                   page_len=page_len, draft_model=draft_model,
                   draft_params=draft_params, draft_len=draft_len,
                   metrics=MetricsLogger(log), log_every=2)
        rc, out_cli = _run_cli("tools.dpxmon", ["replay", log])
        if rc != 0:
            problems.append(f"dpxmon replay over the spec log exited "
                            f"{rc}: {out_cli.strip()[-200:]}")
        shutil.rmtree(workdir, ignore_errors=True)
        if problems:
            print(json.dumps({"bench": "serve_spec",
                              "error": "; ".join(problems)}))
            return 1
        rec_s["spec_gates"] = {
            "engine_matches_generate": True,
            "acceptance_rate": rec_s["acceptance_rate"],
            "tokens_per_iteration": rec_s["tokens_per_iteration"],
            "verify_compiles": {str(k): v for k, v
                                in sp_st["verify_compiles"].items()},
            "dpxmon_replay_rc": rc}

    # ---- multi-replica fleet arm (serve/fleet/) ----
    # the shared-prefix population through the prefix-affine fleet at
    # R=1, 2, 4 replicas on the SAME seeded Poisson arrivals: tokens/s
    # and TTFT p50/p99 as gated medians per R, the scaling ratios
    # printed-or-withheld per the spread gate. On one CPU host the
    # replicas contend for the same cores, so a flat/withheld ratio is
    # the honest outcome; the record is the methodology rail for a
    # real multi-host run. Smoke runs skip this arm — the dedicated
    # --fleet-smoke CI step owns the fleet correctness gates.
    rec_f = None
    if not smoke:
        fleet_rs = (1, 2, 4)
        rec_f = pbrecord.make_record("serve_fleet_tokens_per_sec",
                                     "tokens_per_sec",
                                     device="cpu-loopback")
        rec_f.update({"bench": "serve_fleet", "smoke": smoke,
                      "config": dict(rec["config"], page_len=page_len,
                                     fleet_replicas=list(fleet_rs)),
                      "arms": {}})
        fleet_sts = {}
        fkeys = ("tokens_per_sec", "ttft_ms_p50", "ttft_ms_p99")
        for r in fleet_rs:
            rep_r, sts_r = measured_stats(
                lambda r=r: run_fleet(model, params, shared_reqs, r,
                                      n_slots, max_len, rate=rate,
                                      seed=seed + 4,
                                      page_len=page_len)[0],
                fkeys, warmup=warmup, trials=trials, absent_as_zero=())
            rec_f["arms"][f"fleet_r{r}_open"] = rep_r
            fleet_sts[r] = sts_r
            for k in fkeys:
                rec_f["metrics"][f"serve_fleet_r{r}_{k}"] = \
                    pbrecord.make_metric(
                        None,
                        "tokens_per_sec" if k == "tokens_per_sec"
                        else "ms", stats=sts_r[k],
                        direction="higher" if k == "tokens_per_sec"
                        else "lower")
        top = fleet_sts[fleet_rs[-1]]["tokens_per_sec"]
        rec_f["value"] = round(top.median, 2)
        rec_f["provenance"] = "measured"
        rec_f["trusted"] = top.trusted
        if top.trusted:
            rec_f.pop("untrusted_reason", None)
        else:
            rec_f["untrusted_reason"] = top.untrusted_reason
        for r in fleet_rs[1:]:
            vs, why = pbstats.gated_ratio(
                fleet_sts[r]["tokens_per_sec"],
                fleet_sts[1]["tokens_per_sec"])
            if vs is not None:
                rec_f[f"vs_single_replica_r{r}_x"] = round(vs, 2)
            else:
                rec_f[f"vs_single_replica_r{r}_x_withheld"] = why

    issues = pbrecord.validate_record(rec, strict=False)
    if issues:
        rec["schema_issues"] = issues
        print(f"# WARNING: serve record failed schema self-validation: "
              f"{'; '.join(issues[:3])}", file=sys.stderr)
    print(json.dumps(rec))
    issues = pbrecord.validate_record(rec_d, strict=False)
    if issues:
        rec_d["schema_issues"] = issues
        print(f"# WARNING: disagg record failed schema self-validation: "
              f"{'; '.join(issues[:3])}", file=sys.stderr)
    print(json.dumps(rec_d))
    issues = pbrecord.validate_record(rec_q, strict=False)
    if issues:
        rec_q["schema_issues"] = issues
        print(f"# WARNING: kvq record failed schema self-validation: "
              f"{'; '.join(issues[:3])}", file=sys.stderr)
    print(json.dumps(rec_q))
    issues = pbrecord.validate_record(rec_s, strict=False)
    if issues:
        rec_s["schema_issues"] = issues
        print(f"# WARNING: spec record failed schema self-validation: "
              f"{'; '.join(issues[:3])}", file=sys.stderr)
    print(json.dumps(rec_s))
    if rec_f is not None:
        issues = pbrecord.validate_record(rec_f, strict=False)
        if issues:
            rec_f["schema_issues"] = issues
            print(f"# WARNING: fleet record failed schema "
                  f"self-validation: {'; '.join(issues[:3])}",
                  file=sys.stderr)
        print(json.dumps(rec_f))
    if not smoke and dpxenv.get("DPX_BENCH_SELFLOG"):
        # real (non-CI) runs land in the trajectory store so the
        # shared-prefix TTFT numbers join the BENCH record trail
        store = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tpu_results.jsonl")
        pbrecord.append_row(store, "serve_shared", rec)
        pbrecord.append_row(store, "serve_disagg", rec_d)
        pbrecord.append_row(store, "serve_kvq", rec_q)
        pbrecord.append_row(store, "serve_spec", rec_s)
        if rec_f is not None:
            pbrecord.append_row(store, "serve_fleet", rec_f)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
