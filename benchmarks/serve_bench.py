"""Serving benchmark: continuous batching vs static batching.

Two load shapes over the same request population:

- **closed loop**: every request submitted at t=0 (the floodgates
  case) — measures peak engine throughput and the TTFT spread induced
  by queueing behind the slot pool;
- **open loop**: Poisson arrivals at ``--rate`` req/s (seeded, so a
  run is reproducible) — the serving-paper methodology (the TTFT/TPOT
  numbers that matter under load are open-loop ones; arxiv 2605.25645
  makes the same point for TPU serving).

The baseline arm is **static batching**: the same requests grouped
FCFS into fixed batches of ``n_slots``, each batch served by ONE
compiled ``generate()`` call (everyone in the batch waits for the
whole batch's decode — the pre-Orca serving shape). Uniform prompt
length/max-new in that arm, since ``generate`` has no per-row
lengths; the engine arms use the mixed population.

Per-arm output: tokens/s, p50/p99 TTFT and TPOT (serve.metrics
definitions). Throughput numbers go through the perfbench statistical
policy (docs/benchmarking.md): each closed-loop arm runs
warmup-discarded repeated trials, tokens/s is the median with IQR and
the hard spread gate attached, and the engine-vs-static throughput
ratio is structurally withheld (with the gate's reason) when either
side comes back untrusted. The printed line is a schema-valid
``dpx.bench.record`` (perfbench/record.py). ``--smoke`` shrinks
everything to a seconds-scale CPU run AND asserts engine streams equal
standalone ``generate()`` — the CI job that keeps the engine loop from
rotting (tier1.yml).

Usage: python benchmarks/serve_bench.py [--smoke] [--slots N]
           [--requests N] [--rate R] [--max-new N] [--seed S]
           [--trials N] [--warmup N]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_model(smoke: bool):
    import jax
    from distributed_pytorch_tpu import models
    if smoke:
        model = models.TransformerLM(vocab=61, dim=32, n_layers=2,
                                     n_heads=4, n_kv_heads=2, pos="rope",
                                     max_seq=256)
    else:
        model = models.TransformerLM(vocab=512, dim=256, n_layers=4,
                                     n_heads=8, n_kv_heads=4, pos="rope",
                                     max_seq=1024)
    return model, model.init(jax.random.PRNGKey(0))


def make_requests(n, vocab, max_new, seed, uniform=False):
    """(prompt, SamplingParams, key) population; ``uniform`` pins one
    shape for the static-batching arm."""
    import jax
    from distributed_pytorch_tpu.serve import SamplingParams
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        s = 16 if uniform else int(rng.integers(4, 24))
        mn = max_new if uniform else int(rng.integers(max(2, max_new // 2),
                                                      max_new + 1))
        prompt = rng.integers(0, vocab, (s,)).astype(np.int32)
        out.append((prompt, SamplingParams(max_new_tokens=mn),
                    jax.random.PRNGKey(1000 + i)))
    return out


def run_engine(model, params, reqs, n_slots, max_len, rate=None, seed=0):
    """Submit ``reqs`` (closed loop, or Poisson open loop at ``rate``)
    and aggregate per-request SLO records."""
    from distributed_pytorch_tpu.serve import (EngineConfig,
                                               InferenceEngine, aggregate)
    eng = InferenceEngine(model, params,
                          EngineConfig(n_slots=n_slots, max_len=max_len))
    rng = np.random.default_rng(seed)
    handles = []
    t0 = time.monotonic()
    with eng:
        for prompt, sp, key in reqs:
            if rate is not None:
                time.sleep(rng.exponential(1.0 / rate))
            handles.append(eng.submit(prompt, sp, rng=key))
        outs = [h.result(timeout=600) for h in handles]
    wall = time.monotonic() - t0
    rep = aggregate([h.metrics for h in handles], wall_s=wall)
    rep["stats"] = {k: v for k, v in eng.stats().items()
                    if k in ("iterations", "decode_compiles",
                             "prefill_compiles", "sample_compiles")}
    return rep, outs


def run_static(model, params, reqs, n_slots, max_len):
    """Static batching: FCFS groups of ``n_slots`` through one compiled
    generate() each; every request's TTFT is its group's full wall time
    (tokens only exist when the whole batch finishes)."""
    import jax
    import jax.numpy as jnp
    from distributed_pytorch_tpu.models.generate import make_generate_fn
    from distributed_pytorch_tpu.serve import aggregate
    sp = reqs[0][1]
    fn = jax.jit(make_generate_fn(model, sp.max_new_tokens,
                                  max_len=max_len))
    # compile lands inside the wall, same as the engine arm (both pay
    # their first-call compiles in the measured region)
    records, t0 = [], time.monotonic()
    for g0 in range(0, len(reqs), n_slots):
        group = reqs[g0:g0 + n_slots]
        prompts = jnp.asarray(np.stack([p for p, _, _ in group]))
        gt0 = time.monotonic()
        toks = fn(params, prompts, group[0][2])
        jax.block_until_ready(toks)
        gt1 = time.monotonic()
        for i in range(len(group)):
            n = sp.max_new_tokens
            records.append({
                "request_id": g0 + i, "outcome": "ok",
                "prompt_len": int(prompts.shape[1]), "n_tokens": n,
                # all tokens arrive at batch completion: TTFT is the
                # group wall from t=0 (closed loop), TPOT the amortized
                # per-token group time
                "ttft_ms": (gt1 - t0) * 1e3,
                "tpot_ms": (gt1 - gt0) * 1e3 / n,
                "queue_ms": (gt0 - t0) * 1e3,
            })
    return aggregate(records, wall_s=time.monotonic() - t0)


def measured_arm(run_once, *, warmup, trials):
    """Repeated-trial wrapper for one throughput arm: runs ``run_once``
    (returning an aggregate rep with ``tokens_per_sec``) ``warmup +
    trials`` times under the perfbench policy.  The first trial pays the
    arm's jit compiles — exactly the cold-start artifact the warmup
    discard exists for.  Returns ``(last rep + trials detail, stats)``."""
    from distributed_pytorch_tpu.perfbench import stats as pbstats
    reps = [run_once() for _ in range(warmup + trials)]
    st = pbstats.summarize([r["tokens_per_sec"] for r in reps],
                           warmup=warmup)
    rep = dict(reps[-1])
    rep["tokens_per_sec"] = round(st.median, 2)
    rep["tokens_per_sec_trials"] = st.to_dict(nd=2)
    return rep, st


def main(argv):
    smoke = "--smoke" in argv

    def flag(name, default):
        if name in argv:
            return type(default)(argv[argv.index(name) + 1])
        return default

    n_slots = flag("--slots", 4)
    n_req = flag("--requests", 12 if smoke else 64)
    max_new = flag("--max-new", 8 if smoke else 64)
    rate = flag("--rate", 0.0) or (50.0 if smoke else 8.0)
    seed = flag("--seed", 0)
    max_len = 64 if smoke else 512
    from distributed_pytorch_tpu.perfbench import record as pbrecord
    from distributed_pytorch_tpu.perfbench import stats as pbstats
    from distributed_pytorch_tpu.runtime import env as dpxenv
    warmup = flag("--warmup", 1 if smoke else
                  int(dpxenv.get("DPX_BENCH_WARMUP")))
    trials = flag("--trials", 3 if smoke else
                  int(dpxenv.get("DPX_BENCH_TRIALS")))

    model, params = build_model(smoke)
    rec = pbrecord.make_record("serve_engine_closed_tokens_per_sec",
                               "tokens_per_sec", device="cpu-loopback")
    rec.update({"bench": "serve", "smoke": smoke,
                "config": {"n_slots": n_slots, "n_requests": n_req,
                           "max_new": max_new, "rate_rps": rate,
                           "max_len": max_len, "vocab": model.vocab,
                           "dim": model.dim, "n_layers": model.n_layers,
                           "warmup": warmup, "trials": trials},
                "arms": {}})

    # closed loop (mixed population) — the headline arm. outs (for the
    # smoke correctness gate) come from the FIRST run: identical
    # submissions, and divergence would invalidate every trial equally.
    mixed = make_requests(n_req, model.vocab, max_new, seed)
    first = {}

    def closed_once():
        rep, outs = run_engine(model, params, mixed, n_slots, max_len)
        first.setdefault("outs", outs)
        return rep

    closed, closed_st = measured_arm(closed_once, warmup=warmup,
                                     trials=trials)
    outs = first["outs"]
    rec["arms"]["engine_closed"] = closed
    rec["value"] = round(closed_st.median, 2)
    rec["provenance"] = "measured"
    rec["trusted"] = closed_st.trusted
    if closed_st.trusted:
        rec.pop("untrusted_reason", None)
    else:
        rec["untrusted_reason"] = closed_st.untrusted_reason
    rec["metrics"]["serve_engine_closed_tokens_per_sec"] = \
        pbrecord.make_metric(None, "tokens_per_sec", stats=closed_st)

    if smoke:
        # correctness gate: engine streams == standalone generate()
        import jax
        import jax.numpy as jnp
        from distributed_pytorch_tpu.models.generate import make_generate_fn
        for i in (0, n_req // 2, n_req - 1):
            prompt, sp, key = mixed[i]
            ref = np.asarray(jax.jit(make_generate_fn(
                model, sp.max_new_tokens, max_len=max_len))(
                params, jnp.asarray(prompt[None]), key))[0]
            if not np.array_equal(outs[i], ref):
                print(json.dumps({"bench": "serve", "error":
                                  f"request {i} diverged from "
                                  f"standalone generate()"}))
                return 1
        rec["engine_matches_generate"] = True

    # open loop (Poisson arrivals, mixed population)
    open_rep, _ = run_engine(model, params, mixed, n_slots, max_len,
                             rate=rate, seed=seed + 1)
    rec["arms"]["engine_open_poisson"] = open_rep

    # static-batching baseline (uniform shapes; generate has no per-row
    # lengths) — same trial policy on BOTH sides of the ratio
    uni = make_requests(n_req, model.vocab, max_new, seed, uniform=True)
    static, static_st = measured_arm(
        lambda: run_static(model, params, uni, n_slots, max_len),
        warmup=warmup, trials=trials)
    rec["arms"]["static_batch"] = static
    rec["metrics"]["serve_static_batch_tokens_per_sec"] = \
        pbrecord.make_metric(None, "tokens_per_sec", stats=static_st)
    eng_uni, eng_uni_st = measured_arm(
        lambda: run_engine(model, params, uni, n_slots, max_len)[0],
        warmup=warmup, trials=trials)
    rec["arms"]["engine_closed_uniform"] = eng_uni
    rec["metrics"]["serve_engine_uniform_tokens_per_sec"] = \
        pbrecord.make_metric(None, "tokens_per_sec", stats=eng_uni_st)

    # continuous-vs-static throughput: printed only when both sides pass
    # the spread gate, withheld with the gate's reason otherwise
    ratio, why = pbstats.gated_ratio(eng_uni_st, static_st)
    if ratio is not None:
        rec["engine_vs_static_tokens_x"] = round(ratio, 2)
    else:
        rec["engine_vs_static_tokens_x_withheld"] = why
    st, en = static, eng_uni
    if st.get("ttft_ms_p50") and en.get("ttft_ms_p50"):
        # last-trial latency detail (a distribution, not a gated median)
        rec["engine_vs_static_ttft_p50_x"] = round(
            st["ttft_ms_p50"] / en["ttft_ms_p50"], 2)

    issues = pbrecord.validate_record(rec, strict=False)
    if issues:
        rec["schema_issues"] = issues
        print(f"# WARNING: serve record failed schema self-validation: "
              f"{'; '.join(issues[:3])}", file=sys.stderr)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
