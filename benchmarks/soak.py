"""The composed soak harness — ``bench.py --soak`` (ROADMAP item 5's
on-ramp; docs/observability.md "Live monitoring & soak").

Every scaling feature existed only in isolation until now; this arm is
the first that runs them COMPOSED as one long-lived job and gates the
result on live telemetry instead of a post-hoc trace merge:

* **the composed step** — host front door at ``DPX_SOAK_WORLD`` (one OS
  process per rank), ``grad_reduce="adaptive"`` (per-bucket q4/q8
  WidthChooser) over the TWO-LEVEL hierarchical ring
  (``DPX_HIER_RING=2``) with bucketed comm/update OVERLAP
  (``overlap=True``) — hier × adaptive × overlap in one step. The
  ZeRO-1 sharded weight UPDATE is wire-incompatible with the adaptive
  chooser by the documented front-door contract (its gather-leg error
  feedback owns the fixed q8 grid — docs/front_door.md), so the
  composition's "sharded" leg is the SHARDED ELASTIC CHECKPOINT: every
  rank writes only the shards it owns (``CheckpointManager
  (sharded=True)``, format 2) and the elastic relaunch restores from
  it mid-campaign.
* **chaos + elastic** — ``DPX_FAULT`` kills a rank mid-run on attempt
  0; ``elastic_run`` reaps the world and relaunches; the relaunch
  resumes from the sharded checkpoint and finishes.
* **live telemetry + gating** — every rank's instrumented step emits
  rank-attributed ``metrics_snapshot`` events on the ``DPX_MON_EVERY``
  cadence (comm bytes/exposed-vs-overlapped via the CommStats
  provider, step cadence, RSS, ckpt phase durations, flight-recorder
  drops); a live :class:`~distributed_pytorch_tpu.obs.health
  .HealthMonitor` follows the log from the supervisor and lands
  ``health_transition`` events as they happen (the kill shows as
  ok → degraded, the resumed snapshots as degraded → ok). The arm's
  verdict IS dpxmon's: ``tools/dpxmon.py replay`` must validate every
  snapshot strictly and exit 0, ``tools/dpxtrace.py check`` must hold
  the event vocabulary, the degraded → recovered transitions must be
  present with rank+rule attribution — and a seeded SLO-violation log
  must make dpxmon exit 1 (the gate can fail, so its green means
  something).

``--smoke`` pins a seconds-scale configuration (the CI soak-smoke
step); the full arm takes ``DPX_SOAK_STEPS`` / ``DPX_SOAK_SECONDS``
for hours-long runs with the same machinery.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# smoke shape: world 4 (2 "hosts" x 2 ranks), kill rank 1 mid-run on
# attempt 0, resume from the sharded ckpt and finish
SMOKE_STEPS = 24
SMOKE_KILL_STEP = 12
CKPT_EVERY = 4
HIER_LOCAL = 2
MON_EVERY = 2

#: The seeded-violation rule dpxmon's default set must catch: pool
#: occupancy pinned above the 0.98 saturation ceiling long enough to
#: escalate ok -> degraded -> critical.
_SEEDED_METRIC = "serve.pool_occupancy"


def _progress(msg: str) -> None:
    print(f"# soak: {msg}", file=sys.stderr, flush=True)


def _soak_worker(rank: int, world: int, workdir: str, steps: int,
                 seconds: float) -> None:
    """One rank of the composed arm (module-level: spawn-picklable)."""
    import jax
    import numpy as np

    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ckpt import CheckpointManager
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import (fsdp_param_specs,
                                                  make_train_step)
    from distributed_pytorch_tpu.runtime import faults
    from distributed_pytorch_tpu.utils.checkpoint import (
        latest_step, restore_checkpoint)
    from jax.sharding import PartitionSpec as P

    dist.init_process_group(rank, world)
    try:
        model = models.DummyModel(in_dim=16, hidden_dim=128, n_classes=8)
        opt = optim.adamw(1e-3)

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(model.apply(p, x), y), {}

        # hier x adaptive x overlap in ONE step (DPX_HIER_RING set by
        # the harness env); per-bucket opt states from init_opt_state
        step_fn = make_train_step(loss_fn, opt, grad_reduce="adaptive",
                                  overlap=True, comm_buckets=2)
        params = model.init(jax.random.PRNGKey(0))
        opt_state = step_fn.init_opt_state(params)

        # sharded elastic checkpointing: every rank writes only the
        # shards it owns. Moment specs mirror the param specs (same
        # shapes); scalar counters replicate (P()).
        specs = fsdp_param_specs(params, world, min_size=64)
        shape_spec = {np.shape(l): s for l, s in zip(
            jax.tree_util.tree_leaves(params),
            jax.tree_util.tree_leaves(specs))}
        opt_specs = jax.tree_util.tree_map(
            lambda x: shape_spec.get(np.shape(x), P()), opt_state)
        ckdir = os.path.join(workdir, "ckpt")
        start = 0
        ck = None
        if latest_step(ckdir) is not None:
            ck = restore_checkpoint(ckdir, like_params=params,
                                    like_opt_state=opt_state)
            params, opt_state, start = ck.params, ck.opt_state, ck.step

        rng = np.random.default_rng(7)
        batches = [(rng.random((8, 16), dtype=np.float32),
                    rng.integers(0, 8, size=(8,)).astype(np.int32))
                   for _ in range(min(steps, 64))]
        t_end = (time.monotonic() + seconds) if seconds else None
        with CheckpointManager(ckdir, interval=CKPT_EVERY, keep=2,
                               sharded=True, param_specs=specs,
                               opt_specs=opt_specs,
                               axis_sizes={"dp": world}) as mgr:
            for s in range(start, steps):
                faults.on_step(s, rank=rank)
                out = step_fn(params, opt_state,
                              batches[s % len(batches)])
                params, opt_state = out.params, out.opt_state
                mgr.save(s + 1, params, opt_state)
                if t_end is not None and time.monotonic() >= t_end:
                    break
    finally:
        dist.cleanup()


def _soak_target(workdir: str, steps: int, seconds: float) -> None:
    """The elastically supervised unit (module-level: spawn-picklable):
    one full world launch of the composed arm."""
    from distributed_pytorch_tpu.runtime import env as _env
    from distributed_pytorch_tpu.runtime.multiprocess import (
        launch_multiprocess)
    launch_multiprocess(_soak_worker, int(_env.get("DPX_SOAK_WORLD")),
                        workdir, steps, seconds)


def _seed_violation_log(path: str) -> None:
    """A synthetic SLO-violation stream: valid, rank-attributed
    snapshots whose pool occupancy sits pinned above the default
    saturation ceiling — the dpxmon replay over it MUST exit 1, or the
    soak gate is a rubber stamp."""
    from distributed_pytorch_tpu.obs import trace as _trace
    with open(path, "w", encoding="utf-8") as f:
        for i in range(6):
            f.write(json.dumps({
                "event": "metrics_snapshot",
                "time": _trace.wall_now() + i,
                "rank": 0, "step": i, "source": "seeded",
                "metrics": {_SEEDED_METRIC: 0.999,
                            "train.steps": i}}) + "\n")


def _run_cli(module: str, args, timeout_s: int = 120):
    proc = subprocess.run(
        [sys.executable, "-m", module, *args],
        capture_output=True, text=True, timeout=timeout_s,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return proc.returncode, proc.stdout + proc.stderr


def run_soak(smoke: bool = False) -> int:
    """Run the composed arm end to end; returns the exit code (0 =
    every gate held). Prints one JSON summary line."""
    from distributed_pytorch_tpu.obs import health
    from distributed_pytorch_tpu.runtime import elastic
    from distributed_pytorch_tpu.runtime import env as _env

    seconds = float(_env.get("DPX_SOAK_SECONDS"))
    steps = int(_env.get("DPX_SOAK_STEPS"))
    if not steps:
        # purely time-bounded runs must not be silently capped at the
        # smoke's step count — the wall budget is the only bound then
        steps = 10 ** 9 if seconds else SMOKE_STEPS
    world = int(_env.get("DPX_SOAK_WORLD"))
    workdir = tempfile.mkdtemp(prefix="dpx_soak_")
    log = os.path.join(workdir, "soak_metrics.jsonl")
    _progress(f"composed soak: world {world} (hier {HIER_LOCAL}x"
              f"{world // HIER_LOCAL}), adaptive wire, overlap on, "
              f"sharded ckpt every {CKPT_EVERY}; kill rank 1 at step "
              f"{SMOKE_KILL_STEP} attempt 0; log {log}")

    # live health following from the supervisor: transitions land as
    # rank-attributed health_transition events WHILE the job runs.
    # The live rule set keeps drift/growth evaluation real but damps
    # this container's neighbor noise (floor=0.5: only a sustained
    # 2x+ throughput collapse fires) — the DETERMINISTIC degraded
    # signal the gates rely on is the built-in worker-failure rule
    live_rules = health.parse_rules(
        "drift(train.steps_per_sec)@k=3,floor=0.5;"
        "growth(proc.rss_bytes)@window=8,grow=0.25")
    monitor = health.HealthMonitor(live_rules, emit_path=log,
                                   critical_after=5)
    follower = health.LogFollower(log, monitor)
    stop = threading.Event()

    def _follow():
        while not stop.is_set():
            follower.poll()
            stop.wait(0.5)

    t = threading.Thread(target=_follow, name="dpx-soak-health",
                         daemon=True)
    t.start()

    child_env = {
        "DPX_METRICS_LOG": log,
        "DPX_TRACE": "1",
        "DPX_MON": "1",
        "DPX_MON_EVERY": str(MON_EVERY),
        "DPX_HIER_RING": str(HIER_LOCAL),
        "DPX_FAULT": f"kill@step={SMOKE_KILL_STEP},rank=1,attempt=0",
        "DPX_COMM_TIMEOUT_MS": "60000",
    }
    # the supervisor writes elastic/worker events into the same stream
    saved = _env.snapshot(["DPX_METRICS_LOG"])
    _env.set("DPX_METRICS_LOG", log)
    t0 = time.perf_counter()
    try:
        res = elastic.elastic_run(_soak_target, (workdir, steps, seconds),
                                  max_restarts=2, backoff_s=0.2,
                                  env=child_env)
    finally:
        _env.restore(saved)
        stop.set()
        t.join(timeout=10)
    follower.poll()   # drain the tail written after the last poll
    wall_s = time.perf_counter() - t0
    _progress(f"elastic run done in {wall_s:.1f}s: restarts="
              f"{res.restarts} exitcodes={list(res.exitcodes)}")

    failures = []

    def gate(ok: bool, what: str) -> None:
        # explicit checks, NOT assert (-O/PYTHONOPTIMIZE safe)
        if not ok:
            failures.append(what)
            _progress(f"GATE FAILED: {what}")

    gate(res.restarts >= 1, "the injected kill never caused a relaunch")
    gate(res.exitcodes[-1] == 0, "the relaunched attempt did not finish")

    # the LIVE monitor must have seen the failure degrade health and
    # the resumed snapshots recover it — with rank+rule attribution
    trs = monitor.transitions
    degraded = [x for x in trs if x["to"] == "degraded"]
    recovered = [x for x in trs
                 if x["from"] == "degraded" and x["to"] == "ok"]
    gate(bool(degraded), "no ok->degraded transition observed live")
    gate(bool(recovered), "no degraded->ok (recovered) transition")
    # the killed rank's failure must have breached the worker-failure
    # stream (the monitor may already have been degraded by another
    # rule when the event arrived — the stream audit, not the
    # transition list, is the order-independent check) AND that stream
    # must have recovered once the relaunched rank reported again
    fail_streams = [s for s in monitor.stream_states()
                    if s["rule"] == health.FAILURE_RULE
                    and s["rank"] == 1]
    gate(bool(fail_streams) and fail_streams[0]["total_breaches"] >= 1,
         "the killed rank never breached the worker-failure rule")
    gate(bool(fail_streams) and fail_streams[0]["state"] == "ok",
         "the killed rank's failure stream never recovered after the "
         "relaunch")

    # dpxmon replay: strict snapshot validation + re-derived health
    # trajectory over the whole log, exit 0 (the composed stack's
    # health verdict)
    rc, out = _run_cli("tools.dpxmon", ["replay", log])
    gate(rc == 0, f"dpxmon replay over the soak log exited {rc}")
    gate("degraded" in out, "dpxmon replay reports no degraded leg")

    # the event vocabulary stays strict over soak logs (dpxtrace check)
    rc2, out2 = _run_cli("tools.dpxtrace", ["check", log])
    gate(rc2 == 0,
         f"dpxtrace check over the soak log exited {rc2}: "
         f"{out2.strip()[:300]}")

    # the gate can FAIL: a seeded SLO-violation log must exit 1
    seeded = os.path.join(workdir, "seeded_violation.jsonl")
    _seed_violation_log(seeded)
    rc3, out3 = _run_cli("tools.dpxmon", ["replay", seeded])
    gate(rc3 == 1, f"seeded SLO-violation log exited {rc3}, wanted 1")
    gate("CRITICAL" in out3.upper(),
         "seeded replay did not report a critical verdict")

    snapshots = monitor.snapshots_seen
    summary = {
        "soak": "composed",
        "ok": not failures,
        "world": world,
        "steps": steps if steps < 10 ** 9 else None,
        "seconds": seconds or None,
        "wall_s": round(wall_s, 1),
        "restarts": res.restarts,
        "exitcodes": list(res.exitcodes),
        "snapshots_evaluated": snapshots,
        "transitions": [{k: x[k] for k in ("from", "to", "rule", "rank")}
                        for x in trs],
        "dpxmon_replay_rc": rc,
        "dpxtrace_check_rc": rc2,
        "seeded_violation_rc": rc3,
        "log": log,
        **({"failures": failures} if failures else {}),
    }
    print(json.dumps(summary))
    if not failures and smoke:
        shutil.rmtree(workdir, ignore_errors=True)
    elif failures:
        _progress(f"artifacts kept for inspection: {workdir}")
    return 1 if failures else 0


def main(argv=None) -> int:
    smoke = "--smoke" in (sys.argv[1:] if argv is None else argv)
    return run_soak(smoke=smoke)


if __name__ == "__main__":
    raise SystemExit(main())
