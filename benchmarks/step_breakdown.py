"""Where does the flagship step's time go? (the MFU bottleneck map)

Ablation-based attribution: time the full train step and a ladder of
variants with the honest amortized fetch-fenced method, then read the
components off the differences:

- ``full``        forward + backward + AdamW update  (the flagship step)
- ``no_opt``      forward + backward only            -> optimizer cost
- ``fwd``         forward (loss) only                -> backward cost
- ``attn_stub``   full, attention replaced by identity(v)
                                                     -> attention cost
- ``no_head``     full, vocab projection + CE replaced by a mean over
                  hidden                             -> head+CE cost
- ``dense_attn``  full, dense-einsum attention core  (flash vs dense at
                                                       the flagship seq)

Differences of amortized step times are far more robust on the tunneled
backend than trace parsing (XProf's xplane protos need TF tooling this
image doesn't ship), and each variant is a REAL compiled step — XLA
fusion effects stay in.

Also answers the round-3 question "why doesn't batch 16-64 beat batch
8": run with --batch 8 and --batch 32 and compare which component fails
to scale sublinearly.

Usage: python benchmarks/step_breakdown.py [--batch N] [--seq N] [--steps N]
       python benchmarks/step_breakdown.py --compute   (remat x mp ladder:
           step time + compiled activation-memory per policy, docs/compute.md)
       python benchmarks/step_breakdown.py --comm      (grad-reduce arms)
Prints one JSON line; appends nothing (bench.py/run_all_tpu own the log).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks.mfu_transformer import (FLAGSHIP, PEAK_BF16,
                                        model_flops_per_token)


def _flag(argv, name, default, cast=int):
    if name in argv:
        i = argv.index(name)
        if i + 1 < len(argv):
            return cast(argv[i + 1])
    return default


def _time_step(step, params, opt_state, tokens, steps):
    """Amortized chained timing, one host fetch at the end (the only
    fencing the tunneled backend cannot lie to — fence_probe.py)."""
    from distributed_pytorch_tpu.utils.profiler import (fetch_fence,
                                                        time_steps_amortized)
    out = step(params, opt_state, tokens)
    fetch_fence(out.loss)
    out = step(out.params, out.opt_state, tokens)
    fetch_fence(out.loss)
    step_s, _ = time_steps_amortized(
        lambda o: step(o.params, o.opt_state, tokens), out, steps,
        lambda o: o.loss)
    return step_s


def _time_fwd(loss_fn, params, tokens, steps):
    """Forward-only chain: the loss feeds back through a zero-sum trick
    so each call depends on the previous (no dead-code elimination)."""
    from distributed_pytorch_tpu.utils.profiler import (fetch_fence,
                                                        time_steps_amortized)

    @jax.jit
    def fwd(carry, params, toks):
        loss, _ = loss_fn(params, toks)
        return carry + loss

    c = fwd(jnp.float32(0.0), params, tokens)
    fetch_fence(c)
    c = fwd(c, params, tokens)
    fetch_fence(c)
    step_s, _ = time_steps_amortized(
        lambda c: fwd(c, params, tokens), c, steps, lambda c: c)
    return step_s


def run(dim=FLAGSHIP["dim"], n_layers=FLAGSHIP["n_layers"],
        n_heads=FLAGSHIP["n_heads"], vocab=FLAGSHIP["vocab"],
        seq=FLAGSHIP["seq"], batch=FLAGSHIP["batch"], steps=20,
        dtype=jnp.bfloat16) -> dict:
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ops import make_flash_attn_fn
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import make_train_step

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1),
                                0, vocab, dtype=jnp.int32)
    opt = optim.adamw(3e-4)

    def build(attn_fn):
        model = models.TransformerLM(vocab=vocab, dim=dim,
                                     n_layers=n_layers, n_heads=n_heads,
                                     max_seq=seq, attn_fn=attn_fn,
                                     dtype=dtype)
        params = model.init(jax.random.PRNGKey(0))
        return model, params

    def ce_loss(model):
        def loss_fn(p, toks):
            logits = model.apply(p, toks[:, :-1]).astype(jnp.float32)
            return cross_entropy(logits, toks[:, 1:]), {}
        return loss_fn

    def headless_loss(model):
        def loss_fn(p, toks):
            hid = model.apply(p, toks[:, :-1], return_hidden=True)
            return jnp.mean(hid.astype(jnp.float32) ** 2), {}
        return loss_fn

    flash = make_flash_attn_fn()

    def attn_identity(q, k, v, *, causal=False, scale=None):
        # keep a q/k dependence so neither projection is dead code, at
        # negligible FLOPs vs the real attention matmuls
        return v + 0.0 * (q + k.repeat(q.shape[-3] // k.shape[-3], -3))

    import bench

    def arm(name, thunk):
        # bench.arm: banner BEFORE any of the arm's work (setup deferred
        # into the thunk), so a wedge during build/opt.init is
        # attributed to the right arm in the collector's stdout tail
        rows[name] = bench.arm(f"breakdown arm: {name}", thunk)

    rows = {}
    bench.progress("breakdown: building flagship model (first device "
                   "allocation)")
    model, params = build(flash)
    st = opt.init(params)

    arm("full", lambda: _time_step(
        make_train_step(ce_loss(model), opt, donate=False),
        params, st, tokens, steps))

    @jax.jit
    def fwd_bwd(params, opt_state, toks):
        (loss, _), grads = jax.value_and_grad(ce_loss(model),
                                              has_aux=True)(params, toks)
        # fold grads into the carried loss so the whole backward is live.
        # The scale is derived from runtime DATA (not a literal 0.0), so
        # no simplifier/fast-math pass can prove the term away and
        # dead-code-eliminate the backward; numerically it is ~1e-30 *
        # mean|g| — far below f32 resolution next to the loss.
        eps = (toks[0, 0].astype(jnp.float32) + 1.0) * 1e-30
        gsum = sum(jnp.mean(jnp.abs(g).astype(jnp.float32))
                   for g in jax.tree_util.tree_leaves(grads))
        from distributed_pytorch_tpu.parallel.spmd import SpmdStepOutput
        return SpmdStepOutput(params, opt_state, loss + eps * gsum, {})

    arm("no_opt", lambda: _time_step(fwd_bwd, params, st, tokens, steps))
    arm("fwd", lambda: _time_fwd(ce_loss(model), params, tokens, steps))

    def attn_stub_arm():
        m2, p2 = build(attn_identity)
        return _time_step(make_train_step(ce_loss(m2), opt,
                                          donate=False),
                          p2, opt.init(p2), tokens, steps)
    arm("attn_stub", attn_stub_arm)

    arm("no_head", lambda: _time_step(
        make_train_step(headless_loss(model), opt, donate=False),
        params, st, tokens, steps))

    def dense_arm():
        m3, p3 = build(None)  # dense einsum core
        return _time_step(make_train_step(ce_loss(m3), opt,
                                          donate=False),
                          p3, opt.init(p3), tokens, steps)
    arm("dense_attn", dense_arm)
    bench.progress("breakdown arms done")

    full = rows["full"]
    ms = {k: round(v * 1e3, 3) for k, v in rows.items()}
    attribution = {
        "attention_ms": round((full - rows["attn_stub"]) * 1e3, 3),
        "head_ce_ms": round((full - rows["no_head"]) * 1e3, 3),
        "optimizer_ms": round((full - rows["no_opt"]) * 1e3, 3),
        "backward_ms": round((rows["no_opt"] - rows["fwd"]) * 1e3, 3),
        "flash_vs_dense_ms": round((rows["dense_attn"] - full) * 1e3, 3),
    }
    dev = jax.devices()[0]
    peak = PEAK_BF16.get(dev.device_kind)
    tok = batch * seq
    fl = 3 * model_flops_per_token(dim, n_layers, vocab, seq) * tok
    return {"device": dev.device_kind,
            "config": {"dim": dim, "n_layers": n_layers, "vocab": vocab,
                       "seq": seq, "batch": batch,
                       "dtype": str(jnp.dtype(dtype).name)},
            "steps_timed": steps,
            "step_ms": ms,
            "attribution_ms": attribution,
            "mfu_full": round(fl / rows["full"] / peak, 4) if peak else None}


def run_compute(dim=FLAGSHIP["dim"], n_layers=FLAGSHIP["n_layers"],
                n_heads=FLAGSHIP["n_heads"], vocab=FLAGSHIP["vocab"],
                seq=FLAGSHIP["seq"], batch=FLAGSHIP["batch"], steps=20,
                dtype=jnp.float32) -> dict:
    """The compute-path ladder (docs/compute.md): remat policies x
    mixed precision, each a REAL compiled train step measured with the
    amortized fetch-fenced method plus XLA's compiled memory analysis —
    the activation-memory/step-time tradeoff as data, not prose.

    Arms: remat none/full/dots_saveable at mp=off, plus the composed
    recipe (dots_saveable + bf16 mixed precision). Per arm: step_ms,
    temp (activation high-water) bytes, argument bytes. The model is
    f32-NATIVE on purpose — the mp arm measures the master-weights
    recipe (f32 master, bf16 compute cast) against the f32 baseline;
    the bf16-native flagship is mfu_transformer's own measurement.
    Run with ``--compute``."""
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.models.transformer import REMAT_POLICIES
    from distributed_pytorch_tpu.ops import make_flash_attn_fn
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import make_train_step
    from distributed_pytorch_tpu.utils.profiler import compiled_memory

    import bench

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1),
                                0, vocab, dtype=jnp.int32)
    opt = optim.adamw(3e-4)
    arms = [(pol, "off") for pol in REMAT_POLICIES] \
        + [("dots_saveable", "bf16")]
    rows = {}
    for pol, mp in arms:
        label = f"remat={pol},mp={mp}"

        def arm_thunk(pol=pol, mp=mp):
            model = models.TransformerLM(
                vocab=vocab, dim=dim, n_layers=n_layers, n_heads=n_heads,
                max_seq=seq, attn_fn=make_flash_attn_fn(), remat=pol,
                dtype=dtype)
            params = model.init(jax.random.PRNGKey(0))

            def loss_fn(p, toks):
                logits = model.apply(p, toks[:, :-1]).astype(jnp.float32)
                return cross_entropy(logits, toks[:, 1:]), {}

            step = make_train_step(loss_fn, opt, donate=False,
                                   mixed_precision=mp)
            st = opt.init(params)
            t = _time_step(step, params, st, tokens, steps)
            mem = compiled_memory(
                lambda p, o, b: step(p, o, b), params, st, tokens)
            return {"step_ms": round(t * 1e3, 3),
                    "temp_bytes": mem.get("temp_size_bytes"),
                    "argument_bytes": mem.get("argument_size_bytes")}

        rows[label] = bench.arm(f"compute arm: {label}", arm_thunk)
    base = rows.get("remat=none,mp=off", {})
    dev = jax.devices()[0]
    return {"device": dev.device_kind,
            "config": {"dim": dim, "n_layers": n_layers, "vocab": vocab,
                       "seq": seq, "batch": batch,
                       "dtype": str(jnp.dtype(dtype).name)},
            "steps_timed": steps,
            "arms": rows,
            # the tradeoff, joined: bytes saved vs ms paid per policy
            "vs_none": {k: {"step_ms_delta": round(
                                v["step_ms"] - base.get("step_ms", 0), 3),
                            "temp_bytes_saved":
                                (base.get("temp_bytes") - v["temp_bytes"])
                                if (base.get("temp_bytes") is not None
                                    and v.get("temp_bytes") is not None)
                                else None}
                        for k, v in rows.items()
                        if k != "remat=none,mp=off" and "step_ms" in v}}


def run_comm(world=8, hidden=1024, in_dim=256, batch_per_rank=8,
             steps=30) -> dict:
    """Gradient-reduce comm breakdown on the virtual CPU mesh: the same
    DP step with ``grad_reduce="mean"`` (exact f32 pmean) vs ``"quant"``
    (block-int8 bucket), plus per-step wire-byte accounting from
    ``comm/primitives``. The quantized-vs-f32 comm cost of the tentpole
    quantized collective layer, measured as REAL compiled steps (XLA
    fusion effects stay in). Per-step comm seconds = step-time delta vs
    a world-1 compute-only step on the same per-rank batch.

    Run with ``--comm`` (forces JAX_PLATFORMS=cpu + an 8-device virtual
    mesh, so it works on any host); invoke in a fresh process — the
    platform switch must precede backend init.
    """
    import numpy as np

    from distributed_pytorch_tpu.runtime.jax_compat import ensure_cpu_devices

    jax.config.update("jax_platforms", "cpu")
    ensure_cpu_devices(world)
    from distributed_pytorch_tpu.runtime import env as _envreg
    if _envreg.raw("DPX_CPU_DEVICES") is None:
        _envreg.set("DPX_CPU_DEVICES", world)

    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.comm import primitives as prim
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import make_train_step

    model = models.DummyModel(in_dim=in_dim, hidden_dim=hidden,
                              n_classes=16)
    opt = optim.adamw(1e-4)

    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy(model.apply(p, x), y), {}

    hists = {}

    def arm(world_size, grad_reduce):
        dist.cleanup()
        dist.init_process_group(rank=0, world_size=world_size)
        params = model.init(jax.random.PRNGKey(0))
        gb = batch_per_rank * world_size
        x = dist.shard_batch(np.random.default_rng(0).standard_normal(
            (gb, in_dim)).astype(np.float32))
        y = dist.shard_batch((np.arange(gb) % 16).astype(np.int32))
        step = make_train_step(loss_fn, opt, donate=False,
                               grad_reduce=grad_reduce)
        t = _time_step(step, params, opt.init(params), (x, y), steps)
        chooser = getattr(step, "width_chooser", None)
        if chooser is not None:
            # the adaptive-width histogram: which wire the chooser
            # actually picked, step by step (hysteresis included)
            hists[grad_reduce] = {str(k): v for k, v
                                  in chooser.histogram().items()}
        return t

    n_grad = sum(x.size for x in jax.tree_util.tree_leaves(
        model.init(jax.random.PRNGKey(0))))
    base_s = arm(1, "mean")          # compute-only floor (no dp axis)
    mean_s = arm(world, "mean")
    quant_s = arm(world, "quant")
    q4_s = arm(world, "q4")
    adaptive_s = arm(world, "adaptive")
    dist.cleanup()
    f32_bytes = prim.ring_allreduce_wire_bytes(n_grad, world)
    return {
        "world": world,
        "grad_elems": n_grad,
        "step_ms": {"world1": round(base_s * 1e3, 3),
                    "mean": round(mean_s * 1e3, 3),
                    "quant": round(quant_s * 1e3, 3),
                    "q4": round(q4_s * 1e3, 3),
                    "adaptive": round(adaptive_s * 1e3, 3)},
        "comm_ms": {"mean": round((mean_s - base_s) * 1e3, 3),
                    "quant": round((quant_s - base_s) * 1e3, 3),
                    "q4": round((q4_s - base_s) * 1e3, 3),
                    # the adaptive arm pays a per-step scalar fetch for
                    # the chooser statistic — part of its honest cost
                    "adaptive": round((adaptive_s - base_s) * 1e3, 3)},
        "adaptive_width_hist": hists.get("adaptive"),
        "wire_bytes_per_step": {
            "mean_f32": f32_bytes,
            "quant": prim.quantized_pmean_wire_bytes(n_grad, world)},
    }


def main(argv):
    if "--comm" in argv:
        print(json.dumps(run_comm(steps=_flag(argv, "--steps", 30))))
        return 0
    if "--compute" in argv:
        print(json.dumps(run_compute(
            batch=_flag(argv, "--batch", FLAGSHIP["batch"]),
            seq=_flag(argv, "--seq", FLAGSHIP["seq"]),
            steps=_flag(argv, "--steps", 20))))
        return 0
    rec = run(batch=_flag(argv, "--batch", FLAGSHIP["batch"]),
              seq=_flag(argv, "--seq", FLAGSHIP["seq"]),
              steps=_flag(argv, "--steps", 20))
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
