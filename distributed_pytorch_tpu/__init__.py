"""distributed_pytorch_tpu — a TPU-native distributed training framework.

Brand-new implementation of the capability surface of
joh-fischer/distributed-pytorch (see SURVEY.md): the 18-function helper API
(launch, process-group lifecycle, topology queries, collectives, DDP wrap,
sharded sampling, primary-only printing) plus the workload it serves —
redesigned for TPUs. The compute path is JAX/XLA: one compiled program per
training step with gradient all-reduce over ICI, SPMD over a
``jax.sharding.Mesh``, and shard_map/ppermute-based tensor/sequence
parallelism for scale-out. The host runtime (rendezvous store, CPU
collectives for the per-rank-process front door) is native C++ under
``native/``.

``import distributed_pytorch_tpu as dist`` mirrors the reference's
``import distributed as dist`` (reference ``min_DDP.py:7``).
"""

from .api import *  # noqa: F401,F403 — the 18-function surface + extensions
from .api import __all__ as _api_all

from . import ckpt, comm, data, models, nn, ops, optim, parallel, runtime, serve, utils  # noqa: F401

__all__ = list(_api_all) + [
    "ckpt", "comm", "data", "models", "nn", "ops", "optim", "parallel",
    "runtime", "serve", "utils",
]

__version__ = "0.1.0"
