"""Static + runtime correctness tooling for the comm core (PR 5).

Three legs (docs/analysis.md):

* :mod:`.lint` — **dpxlint**, an AST-based checker enforcing the
  repo-wide invariants PRs 2-4 accumulated (collectives stay on the
  control thread, env reads go through the typed registry, blocking
  calls carry deadlines, typed errors carry attribution, threads are
  named). CLI: ``python -m tools.dpxlint``.
* :mod:`.schedule` — the collective-schedule verifier: static extraction
  of per-front-door collective sequences, plus the cheap always-on
  runtime recorder whose per-rank rolling digests turn a mismatched
  collective from a bare ``CommTimeout`` into "rank 2 issued all_gather
  where ranks 0,1,3 issued all_reduce at seq 417".
* Sanitizer wiring lives in ``native/Makefile`` (``make asan`` /
  ``make tsan``) + the ``DPX_NATIVE_LIB`` override in
  :mod:`..runtime.native`, not in Python.
"""

from .schedule import (DivergenceReport, RankSchedule,  # noqa: F401
                       diagnose, diagnose_log, extract_schedules)
