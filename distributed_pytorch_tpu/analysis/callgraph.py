"""Package-wide call graph with per-function collective effect signatures.

The interprocedural half of dpxverify (analysis/spmd.py): dpxlint's
DPX001 walks ONE module's defs to ask "is a collective reachable from
this thread target?"; the SPMD rules need the same question answered
across the whole package ("does this helper, three modules away, issue
a barrier?"). This module builds that graph once per run:

* every ``def`` in every package module, keyed by bare name — same
  merged-resolution approximation as DPX001 (collisions merge; merged
  resolution only ever ADDS coverage), with same-module definitions
  preferred over package-wide ones;
* ``effect(rel, name)`` — the ordered sequence of collective op names a
  function can issue (directly or through same-package callees), the
  *collective effect signature*. Memoized, cycle-safe (a recursive
  cycle contributes its already-accumulated prefix and stops).

Collective vocabulary is dpxlint's ``COLLECTIVE_NAMES`` (which is the
schedule verifier's — one vocabulary across all three legs).

Everything here is stdlib-only AST work: the jax-free CI lint job runs
it in milliseconds.
"""

from __future__ import annotations

import ast
import collections
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .lint import COLLECTIVE_NAMES, _call_name


def iter_scope(root: ast.AST) -> Iterator[ast.AST]:
    """Walk ``root`` without descending into nested function/class
    defs — the statements that execute when THIS body runs. (A nested
    ``def`` only contributes effects where it is *called*.)"""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        if node is not root and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class CallGraph:
    """Collective effect signatures over a set of parsed modules.

    ``modules`` maps repo-relative path -> parsed ``ast.Module``; only
    package modules belong here (the rules are package-scoped).
    """

    def __init__(self, modules: Dict[str, ast.Module]):
        # (rel, bare name) -> defs in that module; name -> defs anywhere
        self.local_defs: Dict[Tuple[str, str], List[ast.AST]] = \
            collections.defaultdict(list)
        self.global_defs: Dict[str, List[ast.AST]] = \
            collections.defaultdict(list)
        self._def_module: Dict[int, str] = {}
        for rel, tree in modules.items():
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self.local_defs[(rel, node.name)].append(node)
                    self.global_defs[node.name].append(node)
                    self._def_module[id(node)] = rel
        self._effect_cache: Dict[int, Tuple[str, ...]] = {}
        # id(stmt) -> sites: the SPMD rules query overlapping blocks of
        # the same statements (per rule, per enclosing scope); id keys
        # are stable because the graph owns every module tree
        self._sites_cache: Dict[int, List[Tuple[str, ast.Call]]] = {}

    # -- resolution --------------------------------------------------------

    def resolve(self, rel: str, name: str) -> List[ast.AST]:
        """Definitions a bare call name may bind to: same-module defs
        win (they shadow); otherwise every same-named def in the
        package (the DPX001 merge)."""
        local = self.local_defs.get((rel, name))
        if local:
            return local
        return self.global_defs.get(name, [])

    # -- effect signatures -------------------------------------------------

    def effect(self, rel: str, name: str) -> Tuple[str, ...]:
        """Ordered collective ops callable ``name`` (resolved from
        module ``rel``) can issue, deduped order-preservingly across
        multiple same-named defs."""
        out: List[str] = []
        seen: Set[str] = set()
        for node in self.resolve(rel, name):
            for op in self._node_effect(node, set()):
                if op not in seen:
                    seen.add(op)
                    out.append(op)
        return tuple(out)

    def _node_effect(self, fn_node: ast.AST, visiting: Set[int]
                     ) -> Tuple[str, ...]:
        key = id(fn_node)
        cached = self._effect_cache.get(key)
        if cached is not None:
            return cached
        if key in visiting:
            return ()   # cycle: the caller already owns this frame
        visiting.add(key)
        rel = self._def_module.get(key, "")
        ops: List[str] = []
        for sub in iter_scope(fn_node):
            if not isinstance(sub, ast.Call):
                continue
            callee = _call_name(sub)
            if callee is None:
                continue
            if callee in COLLECTIVE_NAMES:
                ops.append(callee)
            else:
                for target in self.resolve(rel, callee):
                    if target is not fn_node:
                        ops.extend(self._node_effect(target, visiting))
        visiting.discard(key)
        sig = tuple(ops)
        self._effect_cache[key] = sig
        return sig

    # -- per-statement collective sites ------------------------------------

    def collective_sites(self, root: ast.AST, rel: str
                         ) -> List[Tuple[str, ast.Call]]:
        """Every collective a statement subtree can issue, attributed
        to the call node IN THIS SUBTREE: a direct collective call
        yields itself; a call to a package function with a non-empty
        effect signature yields one entry per op of that signature,
        all attributed to the call site (the flaggable line)."""
        cached = self._sites_cache.get(id(root))
        if cached is not None:
            return cached
        out: List[Tuple[str, ast.Call]] = []
        for node in iter_scope(root):
            if not isinstance(node, ast.Call):
                continue
            callee = _call_name(node)
            if callee is None:
                continue
            if callee in COLLECTIVE_NAMES:
                out.append((callee, node))
            elif self.resolve(rel, callee):
                for op in self.effect(rel, callee):
                    out.append((op, node))
        out.sort(key=lambda e: (e[1].lineno, e[1].col_offset))
        self._sites_cache[id(root)] = out
        return out

    def always_raises(self, rel: str, name: str) -> bool:
        """True when every resolved def of ``name`` definitely raises
        (its body cannot fall through): the ``_reraise``-style helper
        an except handler may delegate to."""
        defs = self.resolve(rel, name)
        return bool(defs) and all(
            isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
            and _terminates_by_raise(d.body) for d in defs)


def _terminates_by_raise(body: List[ast.stmt]) -> bool:
    if not body:
        return False
    last = body[-1]
    if isinstance(last, ast.Raise):
        return True
    if isinstance(last, ast.If) and last.orelse:
        return (_terminates_by_raise(last.body)
                and _terminates_by_raise(last.orelse))
    return False
