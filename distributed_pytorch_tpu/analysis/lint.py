"""dpxlint — AST lint pass enforcing this repo's distributed-runtime
invariants.

PRs 2-4 accumulated repo-wide rules that were only enforced at runtime
(or by review): collectives stay on the control thread, env reads go
through the typed registry, blocking calls carry deadlines, typed errors
carry attribution, threads are named. Each is now a machine-checked rule
(catalog in docs/analysis.md):

* **DPX001** — a collective / ``_barrier`` call is statically reachable
  from a function handed to ``threading.Thread(target=...)``. The
  ckpt/serve control-thread invariant: an IO/engine thread that issues a
  collective deadlocks the world (the PR-4 bug class that
  ``CheckpointManager._barrier`` now guards at runtime — this rule
  catches it before it runs).
* **DPX002** — raw ``os.environ`` / ``os.getenv`` access outside the
  typed registry (``runtime/env.py``). ``tests/`` are exempt (tests
  legitimately stage raw environments).
* **DPX003** — a blocking call (``.join()``, ``.wait()``, ``.get()``,
  ``.accept()``, ``.recv()``, ``.communicate()``, ``subprocess.run``)
  without a timeout/deadline argument, inside the package. The
  PR-2 invariant: nothing in the runtime may block unboundedly.
  Scoped to ``distributed_pytorch_tpu/`` (the native deadline layer
  ``runtime/native.py`` is the enforcement point itself and is exempt).
* **DPX004** — ``raise`` of a typed comm/ckpt/serve error with zero
  attribution kwargs. The typed hierarchies exist so supervisors act on
  structure (which rank, which op, which step); an unattributed raise
  is a plain RuntimeError wearing a type.
* **DPX005** — ``threading.Thread(...)`` without ``name=``. Every
  thread must carry a named owner: the ckpt phase trace, the watchdog,
  and crash dumps all attribute by thread name.
* **DPX006** — ``jax.jit`` of a step/decode builder (innermost
  enclosing function name contains ``step`` or ``decode``) inside the
  package without ``donate_argnums``. The front-door invariant
  (docs/front_door.md): train-step and decode hot loops donate their
  state buffers — a copying build silently doubles peak memory every
  step. Inline-waivable like the others (eval steps and grad-only
  jits legitimately don't own their inputs).
* **DPX007** — ``time.time()`` used for DURATION measurement (the
  ``t1 - t0`` pattern) inside the package. Wall clock steps under NTP,
  so a wall-clock difference is not a duration — ``time.perf_counter``
  / ``perf_counter_ns`` (or ``time.monotonic`` for deadlines) is.
  Flags a subtraction whose operand is a direct ``time.time()`` call,
  a local name assigned from one, or an attribute assigned from one
  anywhere in the file. Legitimate WALL-CLOCK sites (cross-process
  staleness against a timestamp another process wrote) are
  inline-waived with a reason; ``obs/trace.py``'s single anchor read
  is not a subtraction and does not trigger.
* **DPX008** — ``append_event`` called with a literal event name
  outside the registered ``KNOWN_EVENTS`` vocabulary
  (``obs/export.py``). The strict validators (``dpxtrace check`` /
  ``dpxmon check``) flag unknown names in the LOG; this rule catches
  the typo at the write site, before a soak run ships a week of
  invisible events. ``tests/`` are exempt (they stage unknown names to
  test the validators). Register the name in ``KNOWN_EVENTS`` or waive
  a deliberately-foreign stream with a reason.

Suppression: append ``# dpxlint: disable=DPXnnn <reason>`` to the
offending line (or the line above); ``# dpxlint: disable-file=DPXnnn
<reason>`` within the first 10 lines exempts the whole file. A
committed baseline (``analysis/dpxlint_baseline.json``) holds the
accepted pre-existing findings — CI fails only on NEW ones. Baselines
match on (rule, path, normalized line text), not line numbers, so
unrelated edits don't churn them.
"""

from __future__ import annotations

import ast
import collections
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..obs.export import KNOWN_EVENTS
from .schedule import FRONT_DOOR_SURFACE, NATIVE_OPS

RULES = ("DPX001", "DPX002", "DPX003", "DPX004", "DPX005", "DPX006",
         "DPX007", "DPX008")

#: DPX006: a jit call inside a function whose name matches this is a
#: step/decode-builder site and must carry ``donate_argnums``.
_STEP_BUILDER_RE = re.compile(r"step|decode", re.IGNORECASE)

#: Call names counted as collectives for DPX001 (the static half shares
#: its vocabulary with the schedule verifier).
COLLECTIVE_NAMES: Set[str] = (set(FRONT_DOOR_SURFACE) | set(NATIVE_OPS)
                              | {"all_gather", "wait_for_everyone",
                                 "_barrier"})

#: DPX003: attribute calls that block forever when called with no
#: timeout-ish argument.
BLOCKING_ATTRS = ("join", "wait", "get", "accept", "recv", "recvfrom",
                  "communicate")
_TIMEOUT_KWARGS = ("timeout", "deadline", "deadline_ms", "timeout_ms",
                   "block")

#: DPX004: typed error class → attribution kwargs, at least one required.
TYPED_ERRORS: Dict[str, Tuple[str, ...]] = {
    "CommError": ("op", "rank", "peer"),
    "CommPeerDied": ("op", "rank", "peer"),
    "CommTimeout": ("op", "rank", "peer", "deadline_ms"),
    "CommCorrupt": ("op", "rank", "peer"),
    "CommRetryExhausted": ("op", "rank", "peer", "attempts"),
    "CollectiveMismatch": ("op", "rank", "peer", "seq"),
    "CkptError": ("step", "rank", "shard"),
    "CkptCorrupt": ("step", "rank", "shard"),
    "CkptIncomplete": ("step", "rank", "shard"),
    "CkptShapeMismatch": ("step", "rank", "shard"),
    "ServeError": ("request_id", "iteration"),
    "AdmissionRejected": ("request_id", "iteration", "reason"),
    "RequestDeadlineExceeded": ("request_id", "iteration", "deadline_ms",
                                "stage"),
    "EngineStopped": ("request_id", "iteration"),
    "PagePoolExhausted": ("request_id", "iteration", "needed",
                          "free_pages"),
    "HandoffError": ("request_id", "iteration", "engine"),
    "PrefillEngineDied": ("request_id", "iteration", "engine"),
    "HandoffTimeout": ("request_id", "iteration", "engine",
                       "deadline_ms"),
    "HandoffCorrupt": ("request_id", "iteration", "engine", "page"),
    "ReplicaFailed": ("request_id", "iteration", "replica"),
    "SpecDecodeError": ("request_id", "iteration", "stage"),
    "WorkerFailure": ("rank", "exitcode", "op", "kind"),
}

_EXCLUDED_DIRS = {".git", ".github", ".pytest_cache", "__pycache__",
                  ".claude", ".venv", "node_modules"}
_EXCLUDED_FILES = {"__graft_entry__.py"}  # harness shim, not repo code
_ENV_REGISTRY_FILE = os.path.join("distributed_pytorch_tpu", "runtime",
                                  "env.py")
_DEADLINE_LAYER_FILES = {
    os.path.join("distributed_pytorch_tpu", "runtime", "native.py"),
}
_PACKAGE_DIR = "distributed_pytorch_tpu"

# the rule list is the comma-separated DPXnnn prefix; everything after
# it is the (required-by-convention) free-text reason
_DISABLE_RE = re.compile(
    r"#\s*dpxlint:\s*disable=((?:DPX\d+)(?:\s*,\s*DPX\d+)*)")
_DISABLE_FILE_RE = re.compile(
    r"#\s*dpxlint:\s*disable-file=((?:DPX\d+)(?:\s*,\s*DPX\d+)*)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    message: str
    line_text: str     # stripped source of the offending line

    def fingerprint(self) -> Tuple[str, str, str]:
        # line numbers churn with unrelated edits; (rule, file, text)
        # survives them
        return (self.rule, self.path, self.line_text)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


# ---------------------------------------------------------------------------
# per-file checker
# ---------------------------------------------------------------------------

def _rules_in(match: Optional[re.Match]) -> Set[str]:
    if not match:
        return set()
    return {tok.strip() for tok in match.group(1).split(",") if tok.strip()}


class _FileChecker:
    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self.file_disabled: Set[str] = set()
        # disable-file markers may sit below a long module docstring, so
        # the whole file is scanned (the marker is explicit + greppable)
        for line in self.lines:
            self.file_disabled |= _rules_in(_DISABLE_FILE_RE.search(line))

    # -- helpers -----------------------------------------------------------

    def _suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disabled:
            return True
        for n in (line, line - 1):
            if 1 <= n <= len(self.lines):
                if rule in _rules_in(_DISABLE_RE.search(self.lines[n - 1])):
                    return True
        return False

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if self._suppressed(rule, line):
            return
        text = (self.lines[line - 1].strip()
                if 1 <= line <= len(self.lines) else "")
        self.findings.append(Finding(rule=rule, path=self.rel, line=line,
                                     message=message, line_text=text))

    def _in_package(self) -> bool:
        return self.rel.startswith(_PACKAGE_DIR + "/")

    # -- run ---------------------------------------------------------------

    def run(self) -> List[Finding]:
        try:
            tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            self.findings.append(Finding(
                rule="DPX000", path=self.rel, line=e.lineno or 1,
                message=f"syntax error: {e.msg}", line_text=""))
            return self.findings
        self._check_thread_collectives(tree)   # DPX001
        self._check_env_access(tree)           # DPX002
        self._check_blocking_calls(tree)       # DPX003
        self._check_typed_raises(tree)         # DPX004
        self._check_thread_names(tree)         # DPX005
        self._check_jit_donation(tree)         # DPX006
        self._check_wall_clock_durations(tree)  # DPX007
        self._check_event_vocabulary(tree)     # DPX008
        return self.findings

    # -- DPX001 ------------------------------------------------------------

    def _check_thread_collectives(self, tree: ast.Module) -> None:
        # every function/method defined anywhere in the module, by bare
        # name (collisions merged — a lint over one module can't do
        # better, and merged resolution only ever ADDS coverage)
        defs: Dict[str, List[ast.AST]] = collections.defaultdict(list)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name].append(node)

        entries: List[Tuple[str, ast.AST]] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _call_name(node) == "Thread"):
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                tgt = kw.value
                name = None
                if isinstance(tgt, ast.Name):
                    name = tgt.id
                elif (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    name = tgt.attr
                if name and name in defs:
                    entries.append((name, node))

        for entry_name, thread_call in entries:
            seen: Set[str] = set()
            queue = [entry_name]
            while queue:
                fn = queue.pop()
                if fn in seen:
                    continue
                seen.add(fn)
                for fn_node in defs.get(fn, ()):
                    for sub in ast.walk(fn_node):
                        if not isinstance(sub, ast.Call):
                            continue
                        callee = _call_name(sub)
                        if callee in COLLECTIVE_NAMES:
                            self._emit(
                                "DPX001", sub,
                                f"collective {callee!r} reachable from "
                                f"thread target {entry_name!r} (line "
                                f"{thread_call.lineno}) — collectives "
                                "must stay on the control thread")
                        elif callee and callee in defs and callee != fn:
                            # nested defs of the callee are walked too —
                            # only recurse into same-module definitions
                            queue.append(callee)

    # -- DPX002 ------------------------------------------------------------

    def _check_env_access(self, tree: ast.Module) -> None:
        if self.rel == _ENV_REGISTRY_FILE.replace(os.sep, "/"):
            return
        if self.rel.startswith("tests/"):
            return  # tests stage raw environments deliberately
        # aliases matter: `import os as _os` and `from os import environ
        # [as e]` are the same raw access with a different spelling —
        # the registry's closedness holds only if every spelling is seen
        os_aliases: Set[str] = set()
        environ_aliases: Set[str] = set()
        getenv_aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "os":
                        os_aliases.add(alias.asname or "os")
            elif isinstance(node, ast.ImportFrom) and node.module == "os":
                for alias in node.names:
                    if alias.name == "environ":
                        environ_aliases.add(alias.asname or "environ")
                    elif alias.name == "getenv":
                        getenv_aliases.add(alias.asname or "getenv")
        for node in ast.walk(tree):
            if (isinstance(node, ast.Attribute) and node.attr == "environ"
                    and isinstance(node.value, ast.Name)
                    and node.value.id in os_aliases):
                self._emit(
                    "DPX002", node,
                    "raw os.environ access — declare the variable in "
                    "runtime/env.py and use env.get/raw/set")
            elif (isinstance(node, ast.Name)
                    and node.id in environ_aliases):
                self._emit(
                    "DPX002", node,
                    "raw environ access (from os import environ) — use "
                    "the runtime/env.py registry")
            elif (isinstance(node, ast.Call)
                    and (_call_name(node) == "getenv"
                         or (isinstance(node.func, ast.Name)
                             and node.func.id in getenv_aliases))):
                self._emit(
                    "DPX002", node,
                    "raw os.getenv — use the runtime/env.py registry")

    # -- DPX003 ------------------------------------------------------------

    def _check_blocking_calls(self, tree: ast.Module) -> None:
        if not self._in_package():
            return  # the deadline invariant governs the runtime package
        if self.rel in {p.replace(os.sep, "/")
                        for p in _DEADLINE_LAYER_FILES}:
            return  # the deadline layer itself
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in BLOCKING_ATTRS
                    and not (isinstance(fn.value, ast.Name)
                             and fn.value.id == "self")
                    and not node.args
                    and not any(kw.arg in _TIMEOUT_KWARGS
                                for kw in node.keywords)):
                # zero-arg .get()/.wait()/.join()/... is the
                # block-forever form (dict.get(k) etc. carry args;
                # self.X() is an app-level method, not a primitive)
                self._emit(
                    "DPX003", node,
                    f".{fn.attr}() with no timeout — blocking calls in "
                    "the runtime must carry a deadline "
                    "(docs/failures.md)")
            elif (isinstance(fn, ast.Attribute) and fn.attr == "run"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "subprocess"
                    and not any(kw.arg == "timeout"
                                for kw in node.keywords)):
                self._emit(
                    "DPX003", node,
                    "subprocess.run without timeout= — a wedged child "
                    "must become an error, not a hang")

    # -- DPX004 ------------------------------------------------------------

    def _check_typed_raises(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Raise)
                    and isinstance(node.exc, ast.Call)):
                continue
            name = _call_name(node.exc)
            required = TYPED_ERRORS.get(name or "")
            if not required:
                continue
            kwargs = {kw.arg for kw in node.exc.keywords if kw.arg}
            if not kwargs & set(required):
                self._emit(
                    "DPX004", node,
                    f"raise {name} without attribution — pass at least "
                    f"one of {required} so supervisors can attribute "
                    "the failure")

    # -- DPX005 ------------------------------------------------------------

    def _check_thread_names(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and _call_name(node) == "Thread"
                    and not any(kw.arg == "name" for kw in node.keywords)):
                self._emit(
                    "DPX005", node,
                    "threading.Thread without name= — every thread "
                    "carries a named owner (phase traces, watchdog, "
                    "crash dumps attribute by thread name)")


    # -- DPX006 ------------------------------------------------------------

    def _check_jit_donation(self, tree: ast.Module) -> None:
        """``jit(...)`` without ``donate_argnums`` inside a step/decode
        builder — in any spelling: a direct call, a ``@jax.jit``
        decorator on a step/decode-named def, or ``partial(jax.jit,
        ...)``. Attribution is to the INNERMOST enclosing function def:
        helper closures named outside the step/decode vocabulary
        (samplers, admit buckets) are not builder sites."""
        if not self._in_package():
            return

        def is_jit_ref(node: ast.AST) -> bool:
            return ((isinstance(node, ast.Name) and node.id == "jit")
                    or (isinstance(node, ast.Attribute)
                        and node.attr == "jit"))

        def msg(owner: str, spelling: str) -> str:
            return (f"{spelling} in step/decode builder {owner!r} "
                    "without donate_argnums — the front door donates "
                    "step buffers (docs/front_door.md); pass "
                    "donate_argnums or waive with a reason")

        def check_decorators(fn: ast.AST) -> None:
            for dec in fn.decorator_list:
                if is_jit_ref(dec):
                    # bare @jax.jit can never donate
                    self._emit("DPX006", dec, msg(fn.name, "@jit"))
                elif (isinstance(dec, ast.Call)
                        and _call_name(dec) == "jit"
                        and not any(kw.arg == "donate_argnums"
                                    for kw in dec.keywords)):
                    self._emit("DPX006", dec, msg(fn.name, "@jit(...)"))

        # decorator expressions are judged ONCE, by check_decorators
        # (against the decorated def's own name) — never re-judged by
        # the generic call walk against the enclosing owner
        decorator_nodes = {
            id(d)
            for fn in ast.walk(tree)
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            for dec in fn.decorator_list
            for d in ast.walk(dec)}

        def walk(node: ast.AST, owner: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    if _STEP_BUILDER_RE.search(child.name):
                        check_decorators(child)
                    walk(child, child.name)
                    continue
                if id(child) in decorator_nodes:
                    continue
                in_builder = (owner is not None
                              and _STEP_BUILDER_RE.search(owner))
                if (isinstance(child, ast.Call) and in_builder
                        and _call_name(child) == "jit"
                        and not any(kw.arg == "donate_argnums"
                                    for kw in child.keywords)):
                    self._emit("DPX006", child, msg(owner, "jax.jit"))
                elif (isinstance(child, ast.Call) and in_builder
                        and _call_name(child) == "partial"
                        and child.args and is_jit_ref(child.args[0])
                        and not any(kw.arg == "donate_argnums"
                                    for kw in child.keywords)):
                    self._emit("DPX006", child,
                               msg(owner, "partial(jax.jit, ...)"))
                walk(child, owner)

        walk(tree, None)


    # -- DPX007 ------------------------------------------------------------

    def _check_wall_clock_durations(self, tree: ast.Module) -> None:
        """``time.time()`` in a subtraction — duration math on the wall
        clock. Wall time steps (NTP) and a stepped clock turns a
        "duration" negative or wildly wrong; ``perf_counter`` exists
        for exactly this. Tracked taint: direct ``time.time()`` calls
        (any alias spelling), local names assigned from one (per
        function scope), and attributes assigned from one (module-wide
        — ``self.start_time = time.time()`` subtracted in another
        method is the classic offender)."""
        if not self._in_package():
            return

        # alias spellings: `import time as t` → t.time(); `from time
        # import time [as now]` → now()
        time_mod_aliases: Set[str] = set()
        time_fn_aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time":
                        time_mod_aliases.add(alias.asname or "time")
            elif (isinstance(node, ast.ImportFrom)
                    and node.module == "time"):
                for alias in node.names:
                    if alias.name == "time":
                        time_fn_aliases.add(alias.asname or "time")

        def is_wall_call(node: ast.AST) -> bool:
            if not isinstance(node, ast.Call):
                return False
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr == "time"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in time_mod_aliases):
                return True
            return (isinstance(fn, ast.Name)
                    and fn.id in time_fn_aliases)

        # module-wide attribute taint: self.X = time.time() anywhere
        tainted_attrs: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and is_wall_call(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        tainted_attrs.add(tgt.attr)

        def scope_walk(root: ast.AST, skip_defs: bool):
            """ast.walk, optionally not descending into nested function
            defs — the MODULE scope must not inherit a sibling
            function's local taint (a `start = time.time()` in one def
            must never flag another def's perf_counter `end - start`).
            Function scopes keep nested defs (closure taint only ADDS
            coverage; duplicates dedupe via `flagged`)."""
            stack = [root]
            while stack:
                node = stack.pop()
                if node is not root and skip_defs and isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                yield node
                stack.extend(ast.iter_child_nodes(node))

        def scope_names(fn_node: ast.AST, skip_defs: bool) -> Set[str]:
            names: Set[str] = set()
            for node in scope_walk(fn_node, skip_defs):
                if (isinstance(node, ast.Assign)
                        and is_wall_call(node.value)):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            names.add(tgt.id)
                elif (isinstance(node, (ast.AnnAssign, ast.NamedExpr))
                        and node.value is not None
                        and is_wall_call(node.value)
                        and isinstance(node.target, ast.Name)):
                    names.add(node.target.id)
            return names

        flagged: Set[int] = set()   # node ids — scopes overlap (a def
        # is walked by its own scope AND enclosing ones); emit once

        def check_scope(fn_node: ast.AST, skip_defs: bool = False) -> None:
            tainted = scope_names(fn_node, skip_defs)

            def is_wall(node: ast.AST) -> bool:
                if is_wall_call(node):
                    return True
                if isinstance(node, ast.Name) and node.id in tainted:
                    return True
                return (isinstance(node, ast.Attribute)
                        and node.attr in tainted_attrs)

            for node in scope_walk(fn_node, skip_defs):
                if (isinstance(node, ast.BinOp)
                        and isinstance(node.op, ast.Sub)
                        and id(node) not in flagged
                        and (is_wall(node.left) or is_wall(node.right))):
                    flagged.add(id(node))
                    self._emit(
                        "DPX007", node,
                        "time.time() used for duration measurement "
                        "(t1 - t0) — wall clock steps under NTP; use "
                        "time.perf_counter/perf_counter_ns (or the "
                        "obs.trace wall anchor for monotone wall "
                        "stamps), or waive a legitimate cross-process "
                        "wall-clock comparison with a reason")

        # one scope per function def + the module top level; the module
        # pass skips function bodies entirely so one function's local
        # wall-clock name can never taint a sibling's duration math
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_scope(node)
        check_scope(tree, skip_defs=True)


    # -- DPX008 ------------------------------------------------------------

    def _check_event_vocabulary(self, tree: ast.Module) -> None:
        """``append_event("name", ...)`` with a literal name outside
        the ``KNOWN_EVENTS`` vocabulary (obs/export.py). Variable names
        are out of scope (``MetricsLogger.event`` forwards its caller's
        name — the caller's own literal is the checked site)."""
        if self.rel.startswith("tests/"):
            return  # tests stage unknown names to test the validators
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _call_name(node) == "append_event"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if name not in KNOWN_EVENTS:
                self._emit(
                    "DPX008", node,
                    f"append_event({name!r}) is outside the registered "
                    f"KNOWN_EVENTS vocabulary (obs/export.py) — the "
                    f"strict log validators would flag every line it "
                    f"writes; register the name or waive with a reason")


def _call_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


# ---------------------------------------------------------------------------
# output formats (shared by tools/dpxlint.py and tools/dpxverify.py)
# ---------------------------------------------------------------------------

FORMATS = ("text", "json", "github")


def _gh_escape(s: str) -> str:
    # the workflow-command property/message escaping GitHub documents
    return (s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A"))


def format_findings(findings: Sequence["Finding"], fmt: str = "text") -> str:
    """Render findings as ``text`` (the classic path:line lines),
    ``json`` (machine-readable list of finding dicts), or ``github``
    (``::error`` workflow annotations that surface inline on PRs)."""
    if fmt == "json":
        return json.dumps(
            [{"rule": f.rule, "path": f.path, "line": f.line,
              "message": f.message, "line_text": f.line_text}
             for f in findings], indent=1, sort_keys=True)
    if fmt == "github":
        return "\n".join(
            f"::error file={f.path},line={f.line},"
            f"title={f.rule}::{_gh_escape(f.message)}" for f in findings)
    return "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# repo walk + baseline
# ---------------------------------------------------------------------------

def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in _EXCLUDED_DIRS)
        for fname in sorted(filenames):
            if fname.endswith(".py") and fname not in _EXCLUDED_FILES:
                yield os.path.join(dirpath, fname)


def lint_paths(paths: Optional[Sequence[str]] = None,
               root: Optional[str] = None) -> List[Finding]:
    root = root or repo_root()
    files: List[str] = []
    if not paths:
        files = list(iter_py_files(root))
    else:
        for p in paths:
            p = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isdir(p):
                files.extend(iter_py_files(p))
            else:
                files.append(p)
    out: List[Finding] = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        rel = os.path.relpath(path, root)
        out.extend(_FileChecker(path, rel, source).run())
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


DEFAULT_BASELINE = os.path.join("distributed_pytorch_tpu", "analysis",
                                "dpxlint_baseline.json")


def load_baseline(path: str) -> collections.Counter:
    with open(path, encoding="utf-8") as f:
        entries = json.load(f)
    return collections.Counter(
        (e["rule"], e["path"], e["line_text"]) for e in entries)


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [{"rule": f.rule, "path": f.path, "line_text": f.line_text,
                "message": f.message} for f in findings]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=1, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: Sequence[Finding], baseline: collections.Counter
                   ) -> List[Finding]:
    """Findings not covered by the baseline (multiset subtraction: N
    accepted copies of a fingerprint absorb at most N occurrences)."""
    budget = collections.Counter(baseline)
    fresh: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget[fp] > 0:
            budget[fp] -= 1
        else:
            fresh.append(f)
    return fresh
