"""Collective-schedule verifier: who issued what, in which order.

The deadliest distributed bug class this repo can have is a *mismatched
collective schedule*: one rank's control flow takes a branch the others
don't, it issues a different collective (or none), and the world
deadlocks until ``DPX_COMM_TIMEOUT_MS`` turns it into a bare
``CommTimeout`` that names no call site. The MPI world solved this with
schedule verification (MUST, MPI-Checker — PAPERS.md); this module is
the dpx equivalent, in two halves:

**Runtime half (always on, ~a string format + one hash fold per op).**
Every :class:`~..runtime.native.HostComm` collective calls
:meth:`RankSchedule.record` with the op's signature ``(op, dtype, size,
extra)``. The recorder keeps a monotone sequence number, folds each
signature into a rolling 64-bit FNV-1a digest, and retains the last
``DPX_SCHEDULE_WINDOW`` records. When an op fails, the comm layer calls
:meth:`RankSchedule.flush`, which appends one ``comm_schedule``
line-JSON event (rank, seq, digest, recent window) to the existing
``DPX_METRICS_LOG`` stream — the same multi-writer-safe channel the
failure events already ride. :func:`diagnose` then joins all ranks'
events and names the first sequence number where the ranks disagree,
the minority rank(s), and both ops. The supervisor
(:func:`..runtime.multiprocess.launch_multiprocess`) runs it
automatically on worker failure and logs a ``schedule_divergence``
event, so the report lands *alongside* the typed ``CommTimeout`` with
zero operator action.

**Static half.** :func:`extract_schedules` parses the comm front doors'
source (AST, no import) and returns, per public collective function,
the sequence of native ops its body can issue. Uses: the front-door
parity check (both front doors must expose the same collective surface;
every issued op must be in the native vocabulary) is a tier-1 test, and
the extraction is the ground truth dpxlint's DPX001 rule shares for
"what is a collective call".
"""

from __future__ import annotations

import ast
import collections
import json
import os
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Tuple


def _envreg():
    # lazy: this module must import with NOTHING but stdlib available —
    # the dpxlint CLI loads it in a bare CI job where jax (pulled in by
    # the package __init__ chain) is absent
    from ..runtime import env
    return env


#: Native collective vocabulary — the ops `HostComm` can issue (what the
#: runtime recorder sees). `_pre_op` names, not Python method names.
#: `allreduce_q4` is the 4-bit adaptive wire (the width is part of the
#: recorded op name, so ranks disagreeing on a bucket's width diverge
#: HERE instead of deadlocking on mismatched frame sizes);
#: `hier_reduce`/`hier_gather` are the two-level ring's phases,
#: recorded on the PARENT comm's schedule by comm/hier.py.
NATIVE_OPS = ("allreduce", "allreduce_q8", "allreduce_q4",
              "hier_reduce", "hier_gather",
              "reduce", "gather", "broadcast", "barrier")

#: HostComm methods composed FROM native ops: calling one issues the
#: listed primitive sequence (what the runtime recorder will see).
COMPOSITE_OPS = {"all_gather": ["gather", "broadcast"]}

#: Public collective surface every comm front door must expose (the
#: reference's §2.1 names + the all_gather extension).
FRONT_DOOR_SURFACE = ("all_reduce", "reduce", "gather", "all_gather",
                      "broadcast", "sync_params", "barrier")

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def _fold(digest: int, text: str) -> int:
    for b in text.encode():
        digest = ((digest ^ b) * _FNV_PRIME) & _MASK64
    return digest


# ---------------------------------------------------------------------------
# Runtime recorder
# ---------------------------------------------------------------------------

class RankSchedule:
    """Per-rank issued-collective recorder (one per ``HostComm``).

    Cheap enough to be always on: one f-string and one 64-bit hash fold
    per collective — noise next to a TCP round trip. ``window`` bounds
    memory; 0 (via ``DPX_SCHEDULE_WINDOW=0``) disables retention but
    keeps the digest."""

    def __init__(self, rank: int, world: int,
                 window: Optional[int] = None):
        if window is None:
            window = max(int(_envreg().get("DPX_SCHEDULE_WINDOW")), 0)
        self.rank = rank
        self.world = world
        self.seq = 0
        self.digest = _FNV_OFFSET
        self.window: Deque[Tuple[int, str]] = collections.deque(
            maxlen=window or None) if window else collections.deque(
            maxlen=1)
        self._enabled = window > 0
        self._flushed_seq = -1

    def record(self, op: str, *, dtype: str = "", size: int = 0,
               extra: str = "") -> None:
        self.seq += 1
        sig = f"{op}|{dtype}|{size}|{extra}"
        self.digest = _fold(_fold(self.digest, sig), str(self.seq))
        if self._enabled:
            self.window.append((self.seq, sig))

    def digest_hex(self) -> str:
        return f"{self.digest:016x}"

    def flush(self, op: str = "", event: str = "comm_schedule") -> None:
        """Append this rank's schedule tail to the line-JSON event log.

        Called from the comm layer's failure path BEFORE the typed error
        raises; must never mask that error, so every failure here is
        swallowed. Idempotent per sequence point (a teardown that fails
        several ops in a row flushes once)."""
        if self.seq == self._flushed_seq:
            return
        self._flushed_seq = self.seq
        try:
            from ..utils.logging import append_event
            # the launch tag discriminates runs: DPX_METRICS_LOG is a
            # long-lived append-only stream, and seq restarts at 1 per
            # comm — without the tag, a rank's flush from a PREVIOUS
            # launch could be joined against this launch's schedules
            append_event(event, rank=self.rank, world=self.world,
                         seq=self.seq, digest=self.digest_hex(),
                         failed_op=op,
                         tag=_envreg().get("DPX_WORKER_TAG"),
                         window=[[s, sig] for s, sig in self.window])
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Cross-rank divergence diagnosis
# ---------------------------------------------------------------------------

@dataclass
class DivergenceReport:
    """First cross-rank disagreement in the recorded schedules."""

    seq: int                       # first diverging sequence number
    minority_ranks: List[int]      # rank(s) issuing the odd op out
    minority_op: str               # their full signature at `seq`
    majority_ranks: List[int]
    majority_op: str
    digests: Dict[int, str] = field(default_factory=dict)

    def __str__(self) -> str:
        few = ",".join(str(r) for r in self.minority_ranks)
        many = ",".join(str(r) for r in self.majority_ranks)
        return (f"schedule divergence at seq {self.seq}: rank {few} "
                f"issued {self.minority_op} where rank(s) {many} issued "
                f"{self.majority_op}")


def _schedule_events(events: Sequence[dict],
                     tag: Optional[str]) -> List[dict]:
    """``comm_schedule`` events of one launch. ``tag=None`` selects the
    NEWEST launch in the stream (the last event's tag, by append order)
    — the log is long-lived and a stale rank's flush from a previous
    launch must never be joined against the current one. Malformed
    events (the log is a shared multi-writer file) are skipped, never
    raised on."""
    sched = [e for e in events if isinstance(e, dict)
             and e.get("event") == "comm_schedule"]
    if tag is None and sched:
        tag = sched[-1].get("tag")
    return [e for e in sched if e.get("tag") == tag]


def _entries_by_rank(events: Sequence[dict]) -> Dict[int, Dict[int, str]]:
    by_rank: Dict[int, Dict[int, str]] = {}
    for ev in events:
        try:
            rank = int(ev.get("rank", -1))
            seqs = by_rank.setdefault(rank, {})
            for seq, sig in ev.get("window", []):
                seqs[int(seq)] = str(sig)
        except (TypeError, ValueError):
            continue  # foreign/damaged event in the shared stream
    return by_rank


def diagnose(events: Sequence[dict],
             tag: Optional[str] = None) -> Optional[DivergenceReport]:
    """Join ranks' ``comm_schedule`` events; name the first divergence.

    ``tag`` restricts the join to one launch's events (the supervisor
    passes its own tag; None = the newest launch in the stream).
    Returns None when fewer than two ranks reported or every overlapping
    sequence point agrees (then the failure was a death/stall, not a
    mismatched schedule — the ``WorkerFailure`` attribution already
    covers those)."""
    sched = _schedule_events(events, tag)
    by_rank = _entries_by_rank(sched)
    if len(by_rank) < 2:
        return None
    digests: Dict[int, str] = {}
    for e in sched:
        try:
            digests[int(e.get("rank", -1))] = str(e.get("digest", ""))
        except (TypeError, ValueError):
            continue
    all_seqs = sorted({s for seqs in by_rank.values() for s in seqs})
    for seq in all_seqs:
        present = {r: seqs[seq] for r, seqs in by_rank.items()
                   if seq in seqs}
        if len(present) < 2:
            continue
        groups: Dict[str, List[int]] = {}
        for r, sig in present.items():
            groups.setdefault(sig, []).append(r)
        if len(groups) == 1:
            continue
        ordered = sorted(groups.items(), key=lambda kv: len(kv[1]))
        minority_sig, minority = ordered[0]
        majority_sig, majority = ordered[-1]
        return DivergenceReport(
            seq=seq, minority_ranks=sorted(minority),
            minority_op=minority_sig, majority_ranks=sorted(majority),
            majority_op=majority_sig, digests=digests)
    return None


def diagnose_log(path: Optional[str] = None,
                 tag: Optional[str] = None) -> Optional[DivergenceReport]:
    """:func:`diagnose` over a line-JSON metrics log file (defaults to
    ``$DPX_METRICS_LOG``). Unreadable/absent log → None."""
    path = path or _envreg().get("DPX_METRICS_LOG")
    if not path or not os.path.exists(path):
        return None
    events = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn line from a killed writer
    except OSError:
        return None
    return diagnose(events, tag=tag)


def report_divergence(path: Optional[str] = None,
                      tag: Optional[str] = None) -> Optional[str]:
    """Supervisor hook: diagnose the metrics log and, when a divergence
    is found, append a ``schedule_divergence`` event naming rank/op/seq
    (and return the human-readable report). None when no divergence.
    ``tag`` scopes the join to the calling launch's own events."""
    rep = diagnose_log(path, tag=tag)
    if rep is None:
        return None
    try:
        from ..utils.logging import append_event
        append_event("schedule_divergence", path=path, seq=rep.seq,
                     minority_ranks=rep.minority_ranks,
                     minority_op=rep.minority_op,
                     majority_ranks=rep.majority_ranks,
                     majority_op=rep.majority_op)
    except Exception:
        pass
    return str(rep)


# ---------------------------------------------------------------------------
# Static extraction
# ---------------------------------------------------------------------------

def _comm_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "comm")


def extract_schedules(path: Optional[str] = None
                      ) -> Dict[str, List[str]]:
    """Per public function of a comm front-door module, the sequence of
    native collective ops its body can issue, in source order.

    Pure AST (the module is never imported): an "issue site" is a call
    whose attribute name is one of :data:`NATIVE_OPS` on a ``comm``-like
    receiver (``comm.allreduce(...)``, ``self.gather(...)``), or a call
    to another extracted function of the same module (one level of
    intra-module inlining — ``all_gather`` reports the ops of the
    ``gather`` + ``broadcast`` it delegates to). Source order is the
    *potential* schedule; branches contribute in order of appearance.
    """
    path = path or os.path.join(_comm_dir(), "host_backend.py")
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)

    raw: Dict[str, List[Tuple[str, Optional[str]]]] = {}
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        sites: List[Tuple[str, Optional[str]]] = []
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Attribute) and fn.attr in NATIVE_OPS:
                sites.append((fn.attr, None))
            elif (isinstance(fn, ast.Attribute)
                    and fn.attr in COMPOSITE_OPS):
                for op in COMPOSITE_OPS[fn.attr]:
                    sites.append((op, None))
            elif isinstance(fn, ast.Name):
                sites.append(("", fn.id))  # possible intra-module call
        raw[node.name] = sites

    out: Dict[str, List[str]] = {}
    for name, sites in raw.items():
        ops: List[str] = []
        for op, callee in sites:
            if op:
                ops.append(op)
            elif callee in raw and callee != name:
                ops.extend(o for o, c in raw[callee] if o)
        out[name] = ops
    return out


def check_front_door_parity() -> List[str]:
    """Static front-door consistency: every FRONT_DOOR_SURFACE name must
    exist in BOTH comm front doors (collectives.py and host_backend.py),
    and every native op host_backend can issue must be in NATIVE_OPS.
    Returns a list of violation strings (empty = consistent)."""
    problems: List[str] = []
    host = extract_schedules(os.path.join(_comm_dir(), "host_backend.py"))
    spmd = extract_schedules(os.path.join(_comm_dir(), "collectives.py"))
    for fn in FRONT_DOOR_SURFACE:
        if fn not in host:
            problems.append(f"host_backend.py missing front-door {fn}()")
        if fn not in spmd:
            problems.append(f"collectives.py missing front-door {fn}()")
    for fn, ops in host.items():
        for op in ops:
            if op not in NATIVE_OPS:
                problems.append(
                    f"host_backend.{fn} issues unknown native op {op!r}")
    return problems
