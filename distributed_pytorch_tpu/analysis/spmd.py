"""dpxverify — SPMD collective-order rules (DPX009-011).

dpxlint (analysis/lint.py) checks local AST shapes; these rules reason
about *cross-rank control flow*: every rank must issue the same
collective sequence, or the job hangs for a full ``DPX_COMM_TIMEOUT_MS``
with no attribution. Built on the package call graph
(analysis/callgraph.py) so a collective three helpers deep still counts.

* **DPX009** — a collective reachable on only one side of a
  rank-divergent branch (``if rank == 0``, ``is_primary()``,
  ``self.is_leader`` ...). Compares the collective effect multiset of
  the two arms; a guard clause (``if rank != 0: return``) is compared
  against the remainder of its enclosing block (the implicit else
  path). Flagged at the one-sided collective's call site.
* **DPX010** — an early-exit path that skips the second of a paired
  collective sequence: a rank-dependent conditional ``return`` lexically
  between a function's first and last collective site, or an ``except``
  handler that swallows (or returns past) an exception raised around a
  collective — the failing rank silently drops out of the sequence
  while its peers block. Handlers that definitely re-raise (a bare
  ``raise``, an always-raising helper like ``HierRing._reraise``, or
  ``os._exit``) are exempt.
* **DPX011** — a lock held across a collective (``with self._lock:``
  around a barrier, or ``.acquire()`` ... collective ... ``.release()``)
  — the distributed lock-order deadlock: rank A holds the lock inside
  the collective while rank B needs it to reach the same collective.

Suppression and baselines are dpxlint's, unchanged: append
``# dpxlint: disable=DPXnnn <reason>`` to the offending line (or the
line above); the committed baseline is
``analysis/dpxverify_baseline.json``. Like dpxlint, a syntax error in
any scanned file is DPX000. Rules are scoped to the package
(``distributed_pytorch_tpu/``) — tests legitimately stage divergence.

Approximations (deliberate, FP-biased-against): bare-name call
resolution merges same-named defs package-wide (same as DPX001);
multiset comparison counts both arms of nested *data*-dependent
branches; rank-dependence is syntactic (an identifier from
``RANK_IDENTIFIERS`` appearing in the branch test).
"""

from __future__ import annotations

import ast
import collections
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from . import lint as _lint
from .callgraph import CallGraph, iter_scope
from .lint import Finding, _call_name

RULES = ("DPX009", "DPX010", "DPX011")

#: Terminal identifiers whose appearance in an ``if`` test marks the
#: branch rank-divergent: different ranks can take different arms.
RANK_IDENTIFIERS = {
    "rank", "local_rank", "global_rank", "node_rank", "host_rank",
    "get_rank", "process_index", "is_primary", "is_main", "is_master",
    "is_main_process", "is_leader", "is_coordinator",
}

#: Terminal identifiers of a context/acquire target treated as a lock.
_LOCK_HINTS = ("lock", "mutex")

DEFAULT_BASELINE = os.path.join("distributed_pytorch_tpu", "analysis",
                                "dpxverify_baseline.json")

#: The fault-injection layer exists to CREATE collective divergence
#: (its ``diverge`` action issues a one-sided barrier on the matched
#: rank — that is the tested behavior, not a bug), and every fault
#: hook (``on_comm_op``/``on_serve_iteration``/``_mark``) reaches it.
#: Excluded from both the call graph and the per-file rules, mirroring
#: dpxlint's deadline-layer exemption for runtime/native.py.
EXEMPT_FILES = {
    "distributed_pytorch_tpu/runtime/faults.py",
}


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def is_rank_dependent(test: ast.AST) -> bool:
    """True when the branch test mentions a rank-ish identifier — the
    syntactic marker that different ranks may take different arms."""
    for node in ast.walk(test):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is not None and name.lower() in RANK_IDENTIFIERS:
            return True
    return False


def _terminates(body: Sequence[ast.stmt]) -> bool:
    """The block cannot fall through to the statement after it."""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
        return True
    if isinstance(last, ast.If) and last.orelse:
        return _terminates(last.body) and _terminates(last.orelse)
    return False


def _child_blocks(stmt: ast.stmt) -> Iterator[List[ast.stmt]]:
    """Statement blocks nested inside ``stmt`` (never into defs)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    for field in ("body", "orelse", "finalbody"):
        block = getattr(stmt, field, None)
        if block:
            yield block
    for handler in getattr(stmt, "handlers", ()) or ():
        if handler.body:
            yield handler.body


class _SpmdChecker(_lint._FileChecker):
    """Per-file SPMD rule pass; inherits dpxlint's suppression +
    emission machinery so ``# dpxlint: disable=DPX009`` works."""

    def __init__(self, path: str, rel: str, source: str,
                 tree: ast.Module, graph: CallGraph):
        super().__init__(path, rel, source)
        self.tree = tree
        self.graph = graph
        self._scope_list: "List[ast.AST] | None" = None

    # -- shared helpers ----------------------------------------------------

    def _sites(self, stmts: Sequence[ast.AST]
               ) -> List[Tuple[str, ast.Call]]:
        out: List[Tuple[str, ast.Call]] = []
        for stmt in stmts:
            out.extend(self.graph.collective_sites(stmt, self.rel))
        return out

    def _scopes(self) -> Iterator[ast.AST]:
        # walked once, replayed per rule (all three iterate it)
        if self._scope_list is None:
            self._scope_list = [self.tree] + [
                node for node in ast.walk(self.tree)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))]
        return iter(self._scope_list)

    # -- run ---------------------------------------------------------------

    def run(self) -> List[Finding]:
        if not self._in_package():
            return self.findings   # DPX000 handled by verify_paths
        self._check_divergent_branches()    # DPX009
        self._check_early_exits()           # DPX010
        self._check_locked_collectives()    # DPX011
        return self.findings

    # -- DPX009 ------------------------------------------------------------

    def _check_divergent_branches(self) -> None:
        for scope in self._scopes():
            body = scope.body if hasattr(scope, "body") else []
            self._walk_block_for_ifs(list(body))

    def _walk_block_for_ifs(self, block: List[ast.stmt]) -> None:
        for i, stmt in enumerate(block):
            if isinstance(stmt, ast.If) and is_rank_dependent(stmt.test):
                self._compare_arms(stmt, block[i + 1:])
            for child in _child_blocks(stmt):
                self._walk_block_for_ifs(child)

    def _compare_arms(self, node: ast.If, rest: List[ast.stmt]) -> None:
        body_sites = self._sites(node.body)
        if node.orelse:
            else_sites = self._sites(node.orelse)
        elif _terminates(node.body):
            # guard clause: the taken arm exits here, the implicit else
            # continues through the rest of the enclosing block — THOSE
            # are the collectives the guarded ranks skip
            else_sites = self._sites(rest)
        else:
            else_sites = []   # both paths rejoin; body ops are one-sided
        body_ops = collections.Counter(op for op, _ in body_sites)
        else_ops = collections.Counter(op for op, _ in else_sites)
        if body_ops == else_ops:
            return
        for op in sorted((body_ops - else_ops) | (else_ops - body_ops)):
            heavier = (body_sites if body_ops[op] > else_ops[op]
                       else else_sites)
            site = next(n for o, n in heavier if o == op)
            self._emit(
                "DPX009", site,
                f"collective {op!r} reachable on only one side of the "
                f"rank-divergent branch at line {node.lineno} — every "
                "rank must issue the same collective sequence, or peers "
                "hang until DPX_COMM_TIMEOUT_MS")

    # -- DPX010 ------------------------------------------------------------

    def _check_early_exits(self) -> None:
        for scope in self._scopes():
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            sites = self._sites(scope.body)
            if len(sites) < 2:
                continue
            first = sites[0][1].lineno
            last = sites[-1][1].lineno
            if first >= last:
                continue
            site_ids = {id(n) for _, n in sites}
            self._flag_rank_dep_returns(scope.body, first, last,
                                        site_ids, in_rank_dep=False)
            self._flag_swallowing_handlers(scope.body, sites)

    def _flag_rank_dep_returns(self, block: List[ast.stmt], first: int,
                               last: int, site_ids: set,
                               in_rank_dep: bool) -> None:
        for stmt in block:
            if isinstance(stmt, ast.Return):
                if (in_rank_dep and first < stmt.lineno < last
                        and not any(id(sub) in site_ids
                                    for sub in ast.walk(stmt))):
                    self._emit(
                        "DPX010", stmt,
                        "rank-dependent early return between paired "
                        "collectives (first at line "
                        f"{first}, last at line {last}) — the returning "
                        "rank drops out of the sequence while peers "
                        "block in the later collective")
                continue
            if isinstance(stmt, ast.If):
                rank_dep = in_rank_dep or is_rank_dependent(stmt.test)
                self._flag_rank_dep_returns(stmt.body, first, last,
                                            site_ids, rank_dep)
                self._flag_rank_dep_returns(stmt.orelse, first, last,
                                            site_ids, rank_dep)
                continue
            for child in _child_blocks(stmt):
                self._flag_rank_dep_returns(child, first, last,
                                            site_ids, in_rank_dep)

    def _handler_reraises(self, handler: ast.ExceptHandler) -> bool:
        if _terminates_by_raise_or_exit(handler.body, self.graph,
                                        self.rel):
            return True
        # a bare `raise` anywhere in the handler body counts: the
        # common `log(); raise` and conditional-reraise shapes
        for node in iter_scope_block(handler.body):
            if isinstance(node, ast.Raise):
                return True
        return False

    def _flag_swallowing_handlers(
            self, block: List[ast.stmt],
            sites: List[Tuple[str, ast.Call]]) -> None:
        for stmt in block:
            if isinstance(stmt, ast.Try):
                try_sites = self._sites(stmt.body)
                after = any(n.lineno > stmt.lineno
                            and not (stmt.body[0].lineno <= n.lineno
                                     <= _block_end(stmt))
                            for _, n in sites)
                for handler in stmt.handlers:
                    if self._handler_reraises(handler):
                        continue
                    if try_sites:
                        ops = sorted({op for op, _ in try_sites})
                        self._emit(
                            "DPX010", handler,
                            f"except path swallows a failure around "
                            f"collective(s) {', '.join(ops)} issued in "
                            "the try body — the failing rank skips the "
                            "op its peers complete; re-raise (or "
                            "abort the comm) instead")
                    elif after and _terminates(handler.body):
                        self._emit(
                            "DPX010", handler,
                            "except path returns past later "
                            "collective(s) in this function — the "
                            "exiting rank drops out of the sequence "
                            "while peers block")
            for child in _child_blocks(stmt):
                self._flag_swallowing_handlers(child, sites)

    # -- DPX011 ------------------------------------------------------------

    def _check_locked_collectives(self) -> None:
        for scope in self._scopes():
            body = scope.body if hasattr(scope, "body") else []
            self._walk_block_for_locks(list(body))

    def _looks_like_lock(self, expr: ast.AST) -> bool:
        name = _terminal_name(expr)
        if name is None:
            return False
        low = name.lower()
        return any(h in low for h in _LOCK_HINTS)

    def _walk_block_for_locks(self, block: List[ast.stmt]) -> None:
        acquired_at: Dict[str, int] = {}
        for stmt in block:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                lockish = [item.context_expr for item in stmt.items
                           if self._looks_like_lock(item.context_expr)]
                if lockish:
                    seen_ops = set()
                    for op, site in self._sites(stmt.body):
                        if op in seen_ops:
                            continue
                        seen_ops.add(op)
                        self._emit(
                            "DPX011", site,
                            f"collective {op!r} issued while holding "
                            f"{_src_of(lockish[0])!r} (with-block at "
                            f"line {stmt.lineno}) — a rank blocked in "
                            "the collective holds the lock a peer "
                            "needs to reach it (distributed lock-order "
                            "deadlock)")
            # explicit acquire()/release() bracketing in the same block
            call = _expr_call(stmt)
            if call is not None and isinstance(call.func, ast.Attribute):
                base = _src_of(call.func.value)
                if (call.func.attr == "acquire"
                        and self._looks_like_lock(call.func.value)):
                    acquired_at[base] = stmt.lineno
                elif call.func.attr == "release":
                    acquired_at.pop(base, None)
            if acquired_at:
                held = next(iter(acquired_at))
                seen_ops = set()
                for op, site in self._sites([stmt]):
                    if op in seen_ops:
                        continue
                    seen_ops.add(op)
                    self._emit(
                        "DPX011", site,
                        f"collective {op!r} issued between "
                        f"{held}.acquire() (line {acquired_at[held]}) "
                        "and its release() — a rank blocked in the "
                        "collective holds the lock a peer needs to "
                        "reach it")
            for child in _child_blocks(stmt):
                self._walk_block_for_locks(child)


def _expr_call(stmt: ast.stmt) -> Optional[ast.Call]:
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        return stmt.value
    return None


def _src_of(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return _terminal_name(node) or "<expr>"


def _block_end(stmt: ast.stmt) -> int:
    return getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno


def iter_scope_block(block: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    for stmt in block:
        yield from iter_scope(stmt)


def _terminates_by_raise_or_exit(body: Sequence[ast.stmt],
                                 graph: CallGraph, rel: str) -> bool:
    """The block definitely ends by raising (or hard-exiting): its last
    statement is a ``raise``, an if/else whose arms both do, a call to
    an always-raising package helper (``_reraise`` style), or
    ``os._exit``/``sys.exit``."""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, ast.Raise):
        return True
    if isinstance(last, ast.If) and last.orelse:
        return (_terminates_by_raise_or_exit(last.body, graph, rel)
                and _terminates_by_raise_or_exit(last.orelse, graph, rel))
    call = _expr_call(last)
    if call is not None:
        name = _call_name(call)
        if name in ("_exit", "exit", "abort"):
            return True
        if name and graph.always_raises(rel, name):
            return True
    return False


# ---------------------------------------------------------------------------
# repo walk — mirrors lint.lint_paths, plus the one-shot call graph
# ---------------------------------------------------------------------------

def verify_paths(paths: Optional[Sequence[str]] = None,
                 root: Optional[str] = None) -> List[Finding]:
    root = root or _lint.repo_root()
    files: List[str] = []
    if not paths:
        files = list(_lint.iter_py_files(root))
    else:
        for p in paths:
            p = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isdir(p):
                files.extend(_lint.iter_py_files(p))
            else:
                files.append(p)

    out: List[Finding] = []
    parsed: List[Tuple[str, str, str, ast.Module]] = []
    modules: Dict[str, ast.Module] = {}
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            out.append(Finding(
                rule="DPX000", path=rel, line=e.lineno or 1,
                message=f"syntax error: {e.msg}", line_text=""))
            continue
        parsed.append((path, rel, source, tree))
        if (rel.startswith(_lint._PACKAGE_DIR + "/")
                and rel not in EXEMPT_FILES):
            modules[rel] = tree

    graph = CallGraph(modules)
    for path, rel, source, tree in parsed:
        if rel in EXEMPT_FILES:
            continue
        out.extend(_SpmdChecker(path, rel, source, tree, graph).run())
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out
