"""The public helper API — full parity with reference ``distributed.py``.

All 18 functions of the reference library (SURVEY.md §2.1, reference
``distributed.py:32-187``), same names, same call shapes, same graceful
degradation (every function is safe before init / without distribution),
reimplemented TPU-natively. ``import distributed_pytorch_tpu as dist`` is a
drop-in for the reference's ``import distributed as dist`` (``min_DDP.py:7``)
for JAX workloads.

Reference-function → implementation map:

==== ======================================  =========================================
#    reference (distributed.py)              here
==== ======================================  =========================================
1    find_free_port          (:32-37)        runtime.launcher.find_free_port
2    launch                  (:40-58)        runtime.launcher.launch
3    init_process_group      (:62-66)        runtime.context.init_process_group
4    is_dist_avail_and_initialized (:69-74)  is_dist_avail_and_initialized (below)
5    cleanup                 (:77-79)        cleanup (below)
6    get_rank                (:82-85)        runtime.context.get_rank
7    get_device              (:88-91)        runtime.context.get_device
8    is_primary              (:94-95)        utils.logging.is_primary
9    get_world_size          (:98-101)       runtime.context.get_world_size
10   data_sampler            (:105-108)      data.sampler.data_sampler
11   prepare_ddp_model       (:112-115)      parallel.data_parallel.prepare_ddp_model
12   all_reduce              (:119-133)      comm.collectives.all_reduce
13   reduce                  (:136-144)      comm.collectives.reduce
14   gather                  (:147-160)      comm.collectives.gather
15   sync_params             (:163-170)      comm.collectives.sync_params
16   barrier                 (:173-177)      comm.collectives.barrier
17   wait_for_everyone       (:181-182)      comm.collectives.wait_for_everyone
18   print_primary           (:185-187)      utils.logging.print_primary
==== ======================================  =========================================
"""

from __future__ import annotations

from .comm.collectives import (all_gather, all_reduce, barrier, broadcast,
                               gather, reduce, sync_params, wait_for_everyone)
from .data.sampler import data_sampler
from .parallel.data_parallel import prepare_ddp_model
from .runtime import context as _context
from .runtime.context import (batch_sharding, device_count, get_backend,
                              get_device, get_mesh, get_rank, get_world_size,
                              init_process_group, replicate,
                              replicated_sharding, shard_batch)
from .runtime.launcher import find_free_port, launch
from .utils.logging import is_primary, print_primary


def is_dist_avail_and_initialized() -> bool:
    """Guard used by every helper (reference ``distributed.py:69-74``).

    Distribution is always *available* here (the XLA runtime is the
    backend), so this reduces to the initialized bit."""
    return _context.is_initialized()


def cleanup() -> None:
    """Destroy the process group iff initialized (reference
    ``distributed.py:77-79``)."""
    if is_dist_avail_and_initialized():
        _context.destroy_process_group()


__all__ = [
    "find_free_port", "launch", "init_process_group",
    "is_dist_avail_and_initialized", "cleanup", "get_rank", "get_device",
    "is_primary", "get_world_size", "data_sampler", "prepare_ddp_model",
    "all_reduce", "reduce", "gather", "sync_params", "barrier",
    "wait_for_everyone", "print_primary",
    # TPU-native extensions
    "all_gather", "broadcast", "device_count", "get_backend", "get_mesh",
    "batch_sharding", "replicated_sharding", "shard_batch", "replicate",
]
