"""Sharded distributed checkpointing with topology-resharding restore.

Every host writes only the shards it owns (driven by the FSDP/ZeRO/TP
PartitionSpec trees in :mod:`..parallel`), as per-shard npz members with
per-shard CRC32C (the PR 2 checksum vocabulary) plus one global manifest
— committed atomically via the two-rename dance, so a crash at any byte
leaves a complete restorable step. Restore reshards: a checkpoint
written at mesh ``dp=N`` restores onto ``dp=M`` for any M (including 1),
each host reading exactly the shard slices it needs. The manager adds a
true async snapshot path that runs **no collectives off the main
thread** (see :mod:`.manager`).

:mod:`..utils.checkpoint` remains the single-replica fallback and
re-exports this API; ``CheckpointManager(sharded=True)`` is the one-flag
switch.
"""

from . import errors, integrity, layout, manifest, manager, reader, writer  # noqa: F401
from .errors import (CkptCorrupt, CkptError, CkptIncomplete,  # noqa: F401
                     CkptShapeMismatch)
from .integrity import crc32c  # noqa: F401
from .manager import CheckpointManager, clear_trace, trace_log  # noqa: F401
from .reader import ReadStats, Target, restore_sharded  # noqa: F401

__all__ = [
    "CheckpointManager", "CkptCorrupt", "CkptError", "CkptIncomplete",
    "CkptShapeMismatch", "ReadStats", "Target", "clear_trace", "crc32c",
    "restore_sharded", "trace_log",
]
