"""Typed checkpoint failure vocabulary — the ckpt mirror of the comm
hierarchy (:class:`~..runtime.native.CommError` and friends, ISSUE 2).

Every failure a save/restore can observe maps to one of three concrete
classes, each carrying enough structure to *attribute* the failure —
which step, which rank observed it, which shard (file + npz member) is to
blame — so supervisors, retries, and tests act on types and fields
instead of grepping message strings:

* :class:`CkptCorrupt`      — bytes exist but fail their CRC32C (the PR 2
  checksum vocabulary): bit-rot, torn write, transport damage.
* :class:`CkptIncomplete`   — bytes are missing: no/truncated manifest, a
  shard file or npz member absent, a writer-rank fragment never landed.
* :class:`CkptShapeMismatch` — bytes are fine but do not fit the request:
  template leaf-count/shape disagreement, a reshard target outside the
  saved global shape.

``FileNotFoundError`` stays reserved for "nothing is checkpointed here at
all" (the resume-or-fresh-start branch of every training script); the
typed hierarchy covers checkpoints that *exist but cannot be trusted*.
"""

from __future__ import annotations


class CkptError(RuntimeError):
    """A checkpoint save/restore failed.

    Attributes mirror the comm hierarchy's attribution fields: ``step``
    (which checkpoint), ``rank`` (which process observed the failure) and
    ``shard`` (the ``file:member`` of the offending shard, when one is
    identifiable).
    """

    def __init__(self, msg: str, *, step: int = -1, rank: int = -1,
                 shard: str = ""):
        super().__init__(msg)
        self.step = step
        self.rank = rank
        self.shard = shard


class CkptCorrupt(CkptError):
    """A shard's bytes failed their CRC32C integrity check — the data on
    disk is not what was written and must never reach training state."""


class CkptIncomplete(CkptError):
    """A required piece of the checkpoint is missing or truncated —
    manifest, shard file, npz member, or a writer rank's fragment."""


class CkptShapeMismatch(CkptError):
    """The checkpoint is internally consistent but does not fit the
    request: template structure/shape disagreement, or a reshard target
    incompatible with the saved global shapes."""
