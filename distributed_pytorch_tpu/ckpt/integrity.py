"""Per-shard CRC32C — the PR 2 checksum vocabulary applied to files.

The wire protocol stamps every quantized chunk frame with a CRC32C
trailer (native/dpxhost.cpp: hw sse4.2 + bit-identical sw slice-by-4);
checkpoint shards reuse the *same* function through the same library, so
a checksum computed by any component of this framework verifies against
any other. The pure-python table fallback below exists only for
environments where the native library cannot build (no compiler) — it
computes the identical Castagnoli value, just slowly, and is exercised
directly by tests to pin the equivalence.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

_CRC_POLY = 0x82F63B78  # CRC32C, reflected — mirrors dpxhost.cpp kCrcPoly

_table: Optional[np.ndarray] = None
_native_ok: Optional[bool] = None


def _crc_table() -> np.ndarray:
    global _table
    if _table is None:
        t = np.empty(256, np.uint32)
        for i in range(256):
            c = i
            for _ in range(8):
                c = (_CRC_POLY ^ (c >> 1)) if (c & 1) else (c >> 1)
            t[i] = c
        _table = t
    return _table


def crc32c_sw(buf) -> int:
    """Table-driven CRC32C in pure python — bit-identical to the native
    value; only for no-compiler environments and equivalence tests."""
    data = np.frombuffer(memoryview(buf), dtype=np.uint8) \
        if not isinstance(buf, np.ndarray) \
        else np.ascontiguousarray(buf).view(np.uint8).ravel()
    t = _crc_table()
    c = 0xFFFFFFFF
    for b in data.tobytes():
        c = int(t[(c ^ b) & 0xFF]) ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def crc32c(buf) -> int:
    """CRC32C of a bytes-like / C-contiguous array, native when possible."""
    global _native_ok
    if _native_ok is not False:
        try:
            from ..runtime.native import crc32c as _native
            v = _native(buf)
            _native_ok = True
            return v
        except Exception:
            _native_ok = False
    return crc32c_sw(buf)


def array_crc32c(a: np.ndarray) -> int:
    """CRC32C over an array's C-order raw bytes (the shard checksum)."""
    return crc32c(np.ascontiguousarray(a))
