"""Shard layout: PartitionSpec trees → per-leaf shard grids and slices.

The bridge between the sharding layouts in :mod:`..parallel` (FSDP/ZeRO/
TP specs — trees of ``jax.sharding.PartitionSpec``) and files on disk.
A :class:`LeafLayout` records, for one pytree leaf, how its global array
decomposes into hyperrectangular shards: the per-dimension shard grid
(derived from the spec's axis names and the mesh axis sizes), each
shard's ``[start, stop)`` offsets, and which *writer* (host process)
owns it. Restore onto a different topology is then pure geometry:
:func:`intersect` maps any requested slice of the global array onto the
saved shards that overlap it, so a checkpoint written at mesh ``dp=N``
restores onto ``dp=M`` (any M, including 1) with each reader touching
exactly the bytes it needs.

Everything here is deterministic from ``(shapes, specs, axis_sizes,
writer_world)`` — both sides of a save/restore recompute the same layout
without communicating, which is what lets the async writer run with no
collectives off the main thread (ckpt/manager.py).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .errors import CkptShapeMismatch


def _spec_entry_axes(entry) -> Tuple[str, ...]:
    """Axis names a PartitionSpec entry shards one dimension over:
    None → (), 'dp' → ('dp',), ('dp','tp') → both."""
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def dim_partitions(spec, shape: Sequence[int],
                   axis_sizes: Dict[str, int]) -> Tuple[int, ...]:
    """Number of shards along each dimension of ``shape`` under ``spec``.

    ``spec`` is a PartitionSpec (or None = replicated). Unknown axis
    names (not in ``axis_sizes``) count as size 1 — a tp-sharded leaf
    checkpointed on a dp-only topology stays whole along that dim.
    Dimensions the spec does not mention are unsharded.
    """
    entries = list(spec) if spec is not None else []
    grid = []
    for d, n in enumerate(shape):
        parts = 1
        if d < len(entries):
            for ax in _spec_entry_axes(entries[d]):
                parts *= int(axis_sizes.get(ax, 1))
        if parts > 1 and n % parts != 0:
            # typed: a reshard target (or save spec) that doesn't fit the
            # shapes is the CkptShapeMismatch contract, not a bare
            # ValueError — supervisors catch CkptError to fall back
            # dpxlint: disable=DPX004 planning-time error on the calling rank; no shard exists yet
            raise CkptShapeMismatch(
                f"dim {d} of shape {tuple(shape)} not divisible by "
                f"{parts} (spec {spec!r}, axes {axis_sizes})")
        grid.append(max(parts, 1))
    return tuple(grid)


@dataclasses.dataclass
class Shard:
    """One hyperrectangular piece of a leaf."""
    index: Tuple[int, ...]              # grid coordinates, one per dim
    offsets: Tuple[Tuple[int, int], ...]  # [start, stop) per dim
    writer: int                          # owning writer rank at save time

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(b - a for a, b in self.offsets)

    def slices(self) -> Tuple[slice, ...]:
        return tuple(slice(a, b) for a, b in self.offsets)


@dataclasses.dataclass
class LeafLayout:
    """How one leaf decomposes into shards."""
    key: str                  # escaped '/'-joined key path (checkpoint.py)
    shape: Tuple[int, ...]
    dtype: str                # numpy dtype name (incl. extension dtypes)
    spec: Tuple[Any, ...]     # per-dim axis name(s) or None, JSON-ready
    grid: Tuple[int, ...]
    shards: List[Shard]

    @property
    def nshards(self) -> int:
        return len(self.shards)


def _json_spec(spec, ndim: int) -> Tuple[Any, ...]:
    entries = list(spec) if spec is not None else []
    out = []
    for d in range(ndim):
        axes = _spec_entry_axes(entries[d]) if d < len(entries) else ()
        out.append(None if not axes else
                   (axes[0] if len(axes) == 1 else list(axes)))
    return tuple(out)


def leaf_layout(key: str, shape: Sequence[int], dtype: str, spec,
                axis_sizes: Dict[str, int], writer_world: int
                ) -> LeafLayout:
    """Enumerate the shard grid of one leaf.

    Writer ownership: shards are dealt round-robin over the grid's
    row-major linear index modulo ``writer_world``. For the canonical
    FSDP case (one dim sharded ``dp=W`` under W writer processes) this
    puts shard i on rank i — each host writes exactly the state it
    already owns; a replicated leaf (grid of 1s) lands on writer 0 (the
    primary), and the single-controller front door (writer_world=1) owns
    everything.
    """
    shape = tuple(int(n) for n in shape)
    grid = dim_partitions(spec, shape, axis_sizes)
    sizes = tuple(n // g for n, g in zip(shape, grid))
    shards = []
    for lin, idx in enumerate(itertools.product(*(range(g) for g in grid))):
        offs = tuple((i * s, (i + 1) * s) for i, s in zip(idx, sizes))
        shards.append(Shard(index=idx, offsets=offs,
                            writer=lin % max(writer_world, 1)))
    return LeafLayout(key=key, shape=shape, dtype=dtype,
                      spec=_json_spec(spec, len(shape)), grid=grid,
                      shards=shards)


def intersect(shard: Shard, request: Sequence[slice]
              ) -> Optional[Tuple[Tuple[slice, ...], Tuple[slice, ...]]]:
    """Overlap of ``shard`` with a requested global hyperrect slice.

    Returns ``(src, dst)`` — ``src`` indexes *within the shard's array*,
    ``dst`` within the request's array — or None when disjoint. Requests
    must be plain ``slice(start, stop)`` with no step.
    """
    src, dst = [], []
    for (a, b), r in zip(shard.offsets, request):
        lo = max(a, r.start if r.start is not None else 0)
        hi = min(b, r.stop if r.stop is not None else b)
        if lo >= hi:
            return None
        src.append(slice(lo - a, hi - a))
        dst.append(slice(lo - (r.start or 0), hi - (r.start or 0)))
    return tuple(src), tuple(dst)


def full_request(shape: Sequence[int]) -> Tuple[slice, ...]:
    return tuple(slice(0, n) for n in shape)


def local_slices(shape: Sequence[int], spec, axis_sizes: Dict[str, int],
                 coords: Dict[str, int]) -> Tuple[slice, ...]:
    """The global slice a host at mesh coordinates ``coords`` owns.

    ``coords`` maps axis name → this host's index along that axis (axes
    absent from ``coords`` or ``axis_sizes`` contribute index 0 /
    replication). This is the restore-side dual of the writer grid: a
    rank at ``dp=r`` on a ``dp=M`` topology asks for exactly its slice
    of each leaf, whatever topology wrote the checkpoint.
    """
    grid = dim_partitions(spec, shape, axis_sizes)
    entries = list(spec) if spec is not None else []
    out = []
    for d, (n, g) in enumerate(zip(shape, grid)):
        size = n // g
        idx = 0
        if d < len(entries):
            # row-major over the dim's (possibly multiple) axes
            for ax in _spec_entry_axes(entries[d]):
                ax_size = int(axis_sizes.get(ax, 1))
                coord = int(coords.get(ax, 0))
                if ax not in axis_sizes:
                    coord = 0  # axis absent from this topology: replicated
                elif not 0 <= coord < ax_size:
                    # a stale rank from the pre-shrink topology must be a
                    # typed error, never a silent modulo wrap onto some
                    # other host's shard
                    # dpxlint: disable=DPX004 planning-time error on the calling rank; no shard exists yet
                    raise CkptShapeMismatch(
                        f"coordinate {coord} out of range for mesh axis "
                        f"{ax!r} of size {ax_size}")
                idx = idx * ax_size + coord
        out.append(slice(idx * size, (idx + 1) * size))
    return tuple(out)


# ---------------------------------------------------------------------------
# Tree-level layout
# ---------------------------------------------------------------------------

def _flatten_with_specs(tree, specs):
    """Aligned (keys, arrays, spec_leaves, seq_prefixes) for a pytree and
    its spec tree (replicated P() everywhere when ``specs`` is None)."""
    import jax

    from ..utils import checkpoint as _ck

    keys, arrs, seq_prefixes = _ck._flatten(tree)
    if specs is None:
        spec_leaves = [None] * len(arrs)
    else:
        from jax.sharding import PartitionSpec
        spec_leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda s: s is None
            or isinstance(s, PartitionSpec))
        if len(spec_leaves) != len(arrs):
            raise ValueError(
                f"spec tree has {len(spec_leaves)} leaves but state tree "
                f"has {len(arrs)}")
    return keys, arrs, spec_leaves, seq_prefixes


def tree_layout(tree, specs, axis_sizes: Dict[str, int],
                writer_world: int):
    """Per-leaf layouts for a whole pytree.

    Returns ``(layouts, arrays, seq_prefixes)`` with ``layouts[i]``
    describing ``arrays[i]`` (host numpy). ``specs=None`` → every leaf
    replicated (single-shard), the degenerate full-replica layout.
    """
    keys, arrs, spec_leaves, seq_prefixes = _flatten_with_specs(tree, specs)
    layouts = []
    for key, a, s in zip(keys, arrs, spec_leaves):
        a = np.asarray(a)
        layouts.append(leaf_layout(key, a.shape, a.dtype.name, s,
                                   axis_sizes, writer_world))
    return layouts, [np.asarray(a) for a in arrs], seq_prefixes
