"""Checkpoint manager: interval + retention + a true async snapshot path.

The staging that makes async correct under BOTH front doors (the old
manager silently degraded to sync whenever a host process group was
live, because its save ran barriers on the background thread):

1. **snapshot (main thread, synchronous)** — device state is
   materialized to host (D2H) and defensively copied, so the caller may
   donate/overwrite its arrays on the very next step.
2. **serialize + IO (background thread)** — shard slicing, npz writes,
   CRC32C stamping, fragment land. *File IO only; provably no
   collectives*: :meth:`CheckpointManager._barrier` asserts it runs on
   the manager's control thread and raises :class:`~.errors.CkptError`
   otherwise, and the trace log (:func:`trace_log`) records which thread
   executed each phase so tests pin the contract.
3. **commit (main thread, deferred)** — the barriers and the two-rename
   dance run at the *next* ``save()``/``wait()`` call on the control
   thread: barrier (all ranks' fragments durable) → committing rank
   merges fragments + renames → barrier (commit visible). Until then the
   step is pending: crash-killing the process loses only the pending
   step, never a committed one.

``sharded=False`` keeps the single-replica format-1 layout
(:mod:`..utils.checkpoint`, primary-only write) but gains the same
staged async path. ``sharded=True`` writes format 2: every host writes
only the shards it owns, per the FSDP/ZeRO/TP PartitionSpec trees from
:mod:`..parallel`.

Collective discipline: ``save()``, ``wait()`` and ``restore_latest()``
are collective calls — every rank of a host process group must make them
in the same order (the same discipline the legacy barrier-in-save
already imposed). A rank whose IO failed raises at its next collective
call; peers observe a typed ``CommTimeout`` within one deadline tick
(PR 2 failure semantics) instead of hanging.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from ..obs import trace as _dpxtrace
from . import writer as _writer
from .errors import CkptError
from .reader import ReadStats, Target  # noqa: F401  (re-exported surface)

#: Phase trace for tests: (phase, thread_name) tuples, process-local.
#: Bounded — a multi-week training run must not fund a test facility
#: with an unbounded list (256 covers ~40 saves of history).
_trace: Deque[Tuple[str, str]] = collections.deque(maxlen=256)
_trace_lock = threading.Lock()


def trace_log() -> List[Tuple[str, str]]:
    """Recent phases executed: ('d2h'|'io'|'barrier'|'commit', thread)."""
    return list(_trace)


def clear_trace() -> None:
    with _trace_lock:
        _trace.clear()


def _mark(phase: str) -> None:
    with _trace_lock:
        _trace.append((phase, threading.current_thread().name))
    # the same phase on the dpxtrace timeline (obs/trace.py): instant
    # markers for phase ENTRY; the enclosing save/io/commit spans carry
    # the durations (no-ops unless DPX_TRACE)
    _dpxtrace.event(f"ckpt.{phase}")


def _snapshot(tree):
    """Host-materialize + defensively copy a pytree (device arrays D2H,
    host numpy copied — the caller may overwrite either next step)."""
    import jax
    return jax.tree_util.tree_map(
        lambda x: np.array(x) if isinstance(x, np.ndarray)
        else np.asarray(x), tree)


class _Pending:
    """One staged save awaiting commit."""

    def __init__(self, step: int, tmp: str, plan, extra):
        self.step = step
        self.tmp = tmp
        self.plan = plan          # sharded: writer plan (arrays stripped)
        self.extra = extra
        self.io_stats: Dict[str, Any] = {}


class CheckpointManager:
    """Save every ``interval`` steps, keep the newest ``keep``; optional
    background serialization with main-thread-deferred commit (see module
    docstring); ``sharded=True`` for the every-host-writes-its-shards
    format driven by ``param_specs``.

    ``wait()`` (or context-manager exit) joins in-flight IO *and commits
    the pending step* — call it before reading the checkpoint back or
    exiting the process.
    """

    def __init__(self, ckpt_dir: str, interval: int = 1,
                 keep: Optional[int] = 3, async_save: bool = False,
                 sharded: bool = False, param_specs: Any = None,
                 opt_specs: Any = None,
                 axis_sizes: Optional[Dict[str, int]] = None):
        if keep is not None and keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        if sharded and param_specs is None:
            raise ValueError("sharded=True needs param_specs (the "
                             "PartitionSpec tree from parallel/)")
        self.ckpt_dir = ckpt_dir
        self.interval = max(int(interval), 1)
        self.keep = keep
        self.async_save = async_save
        self.sharded = sharded
        self.param_specs = param_specs
        self.opt_specs = opt_specs
        self.axis_sizes = axis_sizes
        self._ctl_thread = threading.current_thread()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._pending: Optional[_Pending] = None
        self._save_seq = 0

    # -- topology ----------------------------------------------------------

    def _topo(self) -> Tuple[int, int]:
        """(rank, writer_world). Host front door: every rank is a writer.
        Single controller: one process owns all shards."""
        from ..runtime import context
        if context.get_host_comm() is not None:
            return context.get_rank(), context.get_world_size()
        return context.get_rank(), 1

    def _resolved_axes(self) -> Dict[str, int]:
        if self.axis_sizes is not None:
            return dict(self.axis_sizes)
        from ..runtime import context
        if context.get_host_comm() is not None:
            return {"dp": context.get_world_size()}
        return {k: int(v) for k, v in dict(context.get_mesh().shape).items()
                if int(v) > 1} or {"dp": 1}

    # -- collective discipline --------------------------------------------

    def _barrier(self) -> None:
        if threading.current_thread() is not self._ctl_thread:
            raise CkptError(
                "checkpoint collective (barrier) attempted off the "
                "manager's control thread — async IO threads must never "
                "run collectives", rank=self._topo()[0])
        _mark("barrier")
        from ..comm.collectives import barrier
        barrier()

    # -- policy ------------------------------------------------------------

    def should_save(self, step: int) -> bool:
        return step % self.interval == 0

    # -- save --------------------------------------------------------------

    def save(self, step: int, params, opt_state=None,
             extra: Optional[Dict[str, Any]] = None, force: bool = False
             ) -> bool:
        """Stage a save if the policy says so; returns True iff staged.

        Sync mode commits before returning; async mode returns after the
        D2H snapshot with serialization running in the background and the
        commit deferred to the next ``save()``/``wait()``.
        """
        if not force and not self.should_save(step):
            return False
        self._finish_pending()
        json.dumps(extra or {})  # reject unserializable extras up front
        rank, world = self._topo()
        t0 = time.perf_counter()
        # the control-thread half of the save on the trace timeline:
        # D2H snapshot + staging (sync mode runs IO inside it too); the
        # async IO span opens on the ckpt-io thread in _run_io, the
        # commit span in _finish_pending — together the ckpt phases,
        # per rank, on the one cross-rank timeline (obs/trace.py)
        with _dpxtrace.span("ckpt.save", step=step, rank=rank,
                            sharded=self.sharded,
                            async_save=self.async_save):
            _mark("d2h")
            from ..runtime import context
            live_replica = (self.sharded
                            and context.get_host_comm() is not None)
            if (self.sharded and not live_replica) or \
                    (not self.sharded and rank == 0):
                # single-controller D2H (or primary-only full-replica
                # copy); under the host front door the sharded path
                # skips the full defensive copy — snapshot_owned cuts
                # private copies of exactly the 1/world of the state
                # this rank writes
                params = _snapshot(params)
                if opt_state is not None:
                    opt_state = _snapshot(opt_state)
            tmp = self._prepare_tmp(step, rank)
            if self.sharded:
                plan = self._plan(params, opt_state, world)
                _writer.snapshot_owned(plan, rank,
                                       force_copy=live_replica)
                job = lambda: self._io_sharded(tmp, rank, plan)
            else:
                plan = None
                job = (lambda: self._io_full(tmp, step, params,
                                             opt_state, extra)) \
                    if rank == 0 else None
            pend = _Pending(step, tmp, plan, extra)
            pend.io_stats["snapshot_s"] = time.perf_counter() - t0
            self._pending = pend
            if job is not None:
                if self.async_save:
                    self._thread = threading.Thread(
                        target=self._run_io, args=(job, pend),
                        name="ckpt-io", daemon=True)
                    self._thread.start()
                else:
                    self._run_io(job, pend)
            if not self.async_save:
                self._finish_pending()
        return True

    def _plan(self, params, opt_state, world):
        specs: Dict[str, Any] = {"params": self.param_specs}
        trees: Dict[str, Any] = {"params": params}
        if opt_state is not None:
            o_specs = self.opt_specs
            if o_specs is None:
                from ..parallel.fsdp import opt_state_specs
                o_specs = opt_state_specs(opt_state, self.param_specs,
                                          params=params)
            specs["opt_state"] = o_specs
            trees["opt_state"] = opt_state
        return _writer.plan_trees(trees, specs, self._resolved_axes(),
                                  world)

    def _prepare_tmp(self, step: int, rank: int) -> str:
        from ..utils import checkpoint as _ck
        self._save_seq += 1
        tmp = _ck._step_dir(self.ckpt_dir, step) + f".tmp.{self._save_seq}"
        if rank == 0:
            os.makedirs(self.ckpt_dir, exist_ok=True)
            _ck._sweep_stale(self.ckpt_dir, keep_old_for=step)
            os.makedirs(tmp, exist_ok=True)
        # every writer must see the tmp dir before its IO thread starts
        self._barrier()
        return tmp

    def _run_io(self, job, pend: _Pending) -> None:
        # a fresh span (ckpt-io thread in async mode): its tid on the
        # timeline IS the proof serialization left the control thread
        with _dpxtrace.span("ckpt.io", step=pend.step):
            _mark("io")
            try:
                pend.io_stats.update(job() or {})
            except BaseException as e:  # surfaced on the control thread
                self._error = e

    def _io_sharded(self, tmp: str, rank: int, plan) -> Dict[str, Any]:
        stats = _writer.write_shards(tmp, rank, plan)
        for meta in plan.values():
            meta["pieces"] = None  # commit needs layouts only; free now
        return stats

    def _io_full(self, tmp: str, step: int, params, opt_state, extra
                 ) -> Dict[str, Any]:
        from ..utils import checkpoint as _ck
        t0 = time.perf_counter()
        nbytes = _ck._write_full(tmp, step, params, opt_state, extra)
        return {"bytes": nbytes, "shards": 1,
                "duration_s": time.perf_counter() - t0}

    # -- commit ------------------------------------------------------------

    def _join_io(self) -> None:
        if self._thread is not None:
            # dpxlint: disable=DPX003 IO join IS the durability sync point; a deadline would turn committed-means-durable into a race
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            self._pending = None  # a failed write must never commit
            raise err

    def _finish_pending(self) -> None:
        self._join_io()
        if self._pending is None:
            return
        pend, self._pending = self._pending, None
        rank, world = self._topo()
        with _dpxtrace.span("ckpt.commit", step=pend.step, rank=rank):
            self._barrier()  # every writer's fragment is durable
            # dpxmon (obs/metrics.py): the ckpt phase durations land in
            # every writer's own rank-attributed snapshot stream —
            # blocking-save creep over a soak is a health signal
            # (growth/ceiling rules), not just a post-hoc event field
            from ..obs import metrics as _dpxmon
            _dpxmon.inc("ckpt.saves")
            _dpxmon.observe("ckpt.snapshot_ms",
                            pend.io_stats.get("snapshot_s", 0.0) * 1e3)
            if "duration_s" in pend.io_stats:
                _dpxmon.observe("ckpt.io_ms",
                                pend.io_stats["duration_s"] * 1e3)
            if rank == 0:
                _mark("commit")
                from ..utils import checkpoint as _ck
                from ..utils.logging import append_event
                if self.sharded:
                    _writer.commit(self.ckpt_dir, pend.step, pend.tmp,
                                   pend.plan, pend.extra,
                                   self._resolved_axes(), world,
                                   keep=self.keep, rank=rank)
                else:
                    _ck._commit_full(self.ckpt_dir, pend.step, pend.tmp,
                                     keep=self.keep, rank=rank)
                append_event(
                    "ckpt_save", step=pend.step, rank=rank, world=world,
                    sharded=self.sharded, async_save=self.async_save,
                    bytes=pend.io_stats.get("bytes"),
                    shards=pend.io_stats.get("shards"),
                    io_s=round(pend.io_stats.get("duration_s", 0.0), 6),
                    snapshot_s=round(
                        pend.io_stats.get("snapshot_s", 0.0), 6))
            self._barrier()  # commit visible on every rank

    def wait(self) -> None:
        """Join in-flight IO and commit the pending step (collective)."""
        self._finish_pending()

    # -- restore -----------------------------------------------------------

    def restore_latest(self, like_params=None, like_opt_state=None,
                       target: Optional[Target] = None):
        """Latest checkpoint, or None when the directory is empty."""
        from ..utils import checkpoint as _ck
        self.wait()
        if _ck.latest_step(self.ckpt_dir) is None:
            return None
        return _ck.restore_checkpoint(self.ckpt_dir,
                                      like_params=like_params,
                                      like_opt_state=like_opt_state,
                                      target=target)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        return False
