"""Checkpoint manifest — format 2 (sharded) schema, merge, and load.

One ``manifest.json`` per committed step is the *only* source of truth a
restore trusts: mesh axis sizes at save time, per-leaf sharding layout,
and the shard→file map with a CRC32C and byte count per shard member.
Its existence defines checkpoint completeness (the two-rename commit in
:mod:`..utils.checkpoint` makes it appear atomically), so a crash at any
byte of any shard leaves either the previous step or a complete new one.

During an (async) save every writer rank emits a *fragment* —
``manifest_r<rank>.json`` listing just the members it wrote with their
checksums — purely via file IO, no collectives. The committing rank
merges fragments against the deterministic layout at commit time
(main thread); a missing fragment or member surfaces as
:class:`~.errors.CkptIncomplete` naming the writer rank.

Schema (format 2)::

    {"format": 2, "step": N, "extra": {...},
     "mesh": {"axes": {"dp": 4}, "writer_world": W},
     "trees": {
       "params": {
         "seq_prefixes": [...],          # list/tuple internal nodes
         "leaves": [
           {"key": "blocks/0/w", "shape": [128, 512],
            "dtype": "float32", "raw": false,   # true: stored as u8 bytes
            "spec": [null, "dp"], "grid": [1, 4],
            "shards": [
              {"index": [0, 0], "offsets": [[0,128],[0,128]],
               "file": "shard_r0.npz", "member": "t0_l3_s0",
               "crc32c": 123456, "nbytes": 65536, "writer": 0},
              ...]}, ...]}, ...}}
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from .errors import CkptError, CkptIncomplete
from .layout import LeafLayout, Shard

MANIFEST = "manifest.json"
FORMAT = 2


def fragment_name(rank: int) -> str:
    return f"manifest_r{rank}.json"


def shard_file(rank: int) -> str:
    return f"shard_r{rank}.npz"


def member_name(tree_idx: int, leaf_idx: int, shard_lin: int) -> str:
    return f"t{tree_idx}_l{leaf_idx}_s{shard_lin}"


def write_fragment(tmp_dir: str, rank: int,
                   members: Dict[str, Dict[str, int]]) -> None:
    """Atomically write this rank's fragment: member → {crc32c, nbytes}.

    Written LAST by the shard writer (after its .npz landed) so fragment
    presence is the rank-local durability marker the committer checks.
    """
    path = os.path.join(tmp_dir, fragment_name(rank))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"rank": rank, "members": members}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _leaf_entry(tree_idx: int, leaf_idx: int, layout: LeafLayout,
                raw: bool, frags: Dict[int, Dict[str, Dict[str, int]]],
                step: int) -> Dict[str, Any]:
    shards = []
    for lin, sh in enumerate(layout.shards):
        member = member_name(tree_idx, leaf_idx, lin)
        frag = frags.get(sh.writer)
        if frag is None:
            raise CkptIncomplete(
                f"step {step}: writer rank {sh.writer} left no manifest "
                f"fragment (shard {member} unaccounted)", step=step,
                shard=f"{shard_file(sh.writer)}:{member}")
        meta = frag.get(member)
        if meta is None:
            raise CkptIncomplete(
                f"step {step}: shard {member} missing from rank "
                f"{sh.writer}'s fragment", step=step,
                shard=f"{shard_file(sh.writer)}:{member}")
        shards.append({"index": list(sh.index),
                       "offsets": [list(o) for o in sh.offsets],
                       "file": shard_file(sh.writer), "member": member,
                       "crc32c": int(meta["crc32c"]),
                       "nbytes": int(meta["nbytes"]),
                       "writer": sh.writer})
    return {"key": layout.key, "shape": list(layout.shape),
            "dtype": layout.dtype, "raw": raw,
            "spec": [list(s) if isinstance(s, (list, tuple)) else s
                     for s in layout.spec],
            "grid": list(layout.grid), "shards": shards}


def merge(tmp_dir: str, step: int, extra: Optional[Dict[str, Any]],
          axis_sizes: Dict[str, int], writer_world: int,
          tree_meta: Dict[str, Any]) -> Dict[str, Any]:
    """Build the global manifest from per-rank fragments in ``tmp_dir``.

    ``tree_meta``: tree name → ``{"layouts": [LeafLayout], "raw": [bool],
    "seq_prefixes": [...]}`` (the deterministic layout, recomputed by the
    committer). Raises :class:`CkptIncomplete` when any expected fragment
    or member is absent — an async writer that died mid-save can never be
    committed.
    """
    frags: Dict[int, Dict[str, Dict[str, int]]] = {}
    for rank in range(max(writer_world, 1)):
        path = os.path.join(tmp_dir, fragment_name(rank))
        if os.path.exists(path):
            with open(path) as f:
                frags[rank] = json.load(f)["members"]
    trees = {}
    for t_idx, (name, meta) in enumerate(sorted(tree_meta.items())):
        leaves = [
            _leaf_entry(t_idx, l_idx, lay, raw, frags, step)
            for l_idx, (lay, raw) in enumerate(
                zip(meta["layouts"], meta["raw"]))]
        trees[name] = {"seq_prefixes": list(meta["seq_prefixes"]),
                       "leaves": leaves}
    return {"format": FORMAT, "step": step, "extra": extra or {},
            "mesh": {"axes": {k: int(v) for k, v in axis_sizes.items()},
                     "writer_world": int(writer_world)},
            "trees": trees}


def load(step_dir: str, step: int = -1, rank: int = -1) -> Dict[str, Any]:
    """Read + structurally validate a manifest, typed errors on failure.

    A present-but-unparseable manifest is :class:`CkptIncomplete` (a torn
    write — the commit never finished); a parseable manifest of an
    unknown format is :class:`CkptError`.
    """
    path = os.path.join(step_dir, MANIFEST)
    if not os.path.exists(path):
        raise CkptIncomplete(
            f"no manifest under {step_dir!r} (incomplete checkpoint)",
            step=step, rank=rank)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CkptIncomplete(
            f"step {step}: manifest at {path!r} is truncated/unparseable "
            f"({e})", step=step, rank=rank) from e
    fmt = manifest.get("format")
    if fmt not in (1, FORMAT):
        raise CkptError(f"step {step}: unknown manifest format {fmt!r}",
                        step=step, rank=rank)
    return manifest


def leaf_shards(entry: Dict[str, Any]) -> List[Shard]:
    """Rehydrate a manifest leaf's shard list into layout objects."""
    return [Shard(index=tuple(s["index"]),
                  offsets=tuple(tuple(o) for o in s["offsets"]),
                  writer=int(s["writer"]))
            for s in entry["shards"]]
