"""Resharding checkpoint reader: restore onto any topology.

A format-2 checkpoint records global shapes plus the shard decomposition
it was written with; restoring is pure geometry (ckpt/layout.py), so the
target topology is a free parameter:

* **Full assembly** (default): every leaf is reassembled to its global
  shape from whatever shards cover it — the M=1 debugging path and the
  single-controller resume path (the controller re-places full arrays
  onto its mesh, whatever size that mesh now is).
* **Slice restore** (``target=``): the caller states its own coordinates
  on a *new* mesh (e.g. ``dp=r`` of ``M``) and gets, per leaf, only its
  local shard — each host reads exactly the saved members that overlap
  its slice, nothing else. A checkpoint written at ``dp=N`` restores at
  ``dp=M`` for any M; no shard-count equality is ever assumed.

Every member read is CRC32C-verified against the manifest before its
bytes can reach training state; failures raise the typed
:mod:`.errors` hierarchy with step + shard attribution. Reads are
collective-free — callers that need cross-rank ordering (the utils
front door) add their own barriers.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional, Set

import numpy as np

from . import manifest as _mf
from .errors import CkptCorrupt, CkptIncomplete, CkptShapeMismatch
from .integrity import array_crc32c
from .layout import full_request, intersect, local_slices

import os


@dataclasses.dataclass
class ReadStats:
    """What a restore actually touched — the slice-exactness evidence."""
    files: Set[str] = dataclasses.field(default_factory=set)
    members: int = 0
    bytes: int = 0


@dataclasses.dataclass
class Target:
    """A reader's coordinates on its (new) topology.

    ``specs``: PartitionSpec tree per restored tree name (may be the
    save-time specs recomputed for the new axis sizes). ``axis_sizes``:
    the new mesh axes, e.g. ``{"dp": 2}``. ``coords``: this host's index
    per axis, e.g. ``{"dp": 1}``.
    """
    specs: Dict[str, Any]
    axis_sizes: Dict[str, int]
    coords: Dict[str, int]


class _ShardFiles:
    """Lazily opened npz handles, one per shard file."""

    def __init__(self, step_dir: str, step: int, rank: int):
        self.step_dir = step_dir
        self.step = step
        self.rank = rank
        self._open: Dict[str, Any] = {}

    def member(self, fname: str, member: str, crc: int,
               stats: Optional[ReadStats]) -> np.ndarray:
        z = self._open.get(fname)
        if z is None:
            path = os.path.join(self.step_dir, fname)
            if not os.path.exists(path):
                raise CkptIncomplete(
                    f"step {self.step}: shard file {fname!r} missing",
                    step=self.step, rank=self.rank, shard=fname)
            try:
                z = np.load(path)
            except Exception as e:
                raise CkptCorrupt(
                    f"step {self.step}: shard file {fname!r} unreadable "
                    f"({e})", step=self.step, rank=self.rank,
                    shard=fname) from e
            self._open[fname] = z
        try:
            arr = z[member]
        except KeyError as e:
            raise CkptIncomplete(
                f"step {self.step}: member {member!r} missing from "
                f"{fname!r}", step=self.step, rank=self.rank,
                shard=f"{fname}:{member}") from e
        except Exception as e:
            # zipfile's own CRC / a torn npy header: damaged container
            raise CkptCorrupt(
                f"step {self.step}: member {member!r} of {fname!r} "
                f"unreadable ({e})", step=self.step, rank=self.rank,
                shard=f"{fname}:{member}") from e
        if array_crc32c(arr) != crc:
            raise CkptCorrupt(
                f"step {self.step}: shard {fname}:{member} failed CRC32C",
                step=self.step, rank=self.rank,
                shard=f"{fname}:{member}")
        if stats is not None:
            stats.files.add(fname)
            stats.members += 1
            stats.bytes += int(arr.nbytes)
        return arr

    def close(self) -> None:
        for z in self._open.values():
            try:
                z.close()
            except Exception:
                pass
        self._open.clear()


def _leaf_spec_from_tree(specs, n_leaves: int):
    import jax
    from jax.sharding import PartitionSpec
    if specs is None:
        return [None] * n_leaves
    leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: s is None or isinstance(s, PartitionSpec))
    if len(leaves) != n_leaves:
        # dpxlint: disable=DPX004 template/spec disagreement predates any shard read; nothing to attribute
        raise CkptShapeMismatch(
            f"target spec tree has {len(leaves)} leaves, checkpoint tree "
            f"has {n_leaves}")
    return leaves


def read_tree(step_dir: str, manifest: Dict[str, Any], name: str,
              template=None, target: Optional[Target] = None,
              stats: Optional[ReadStats] = None, rank: int = -1):
    """Restore one tree (``params``/``opt_state``/...) from a format-2
    checkpoint. Returns None when the tree was never saved."""
    from ..utils import checkpoint as _ck

    entry = manifest["trees"].get(name)
    if entry is None:
        return None
    step = int(manifest.get("step", -1))
    leaves_meta = entry["leaves"]
    spec_leaves = (_leaf_spec_from_tree(target.specs.get(name),
                                        len(leaves_meta))
                   if target is not None else [None] * len(leaves_meta))
    files = _ShardFiles(step_dir, step, rank)
    out_leaves = []
    try:
        for lmeta, tspec in zip(leaves_meta, spec_leaves):
            shape = tuple(lmeta["shape"])
            dtype = np.dtype(lmeta["dtype"])
            if target is None:
                request = full_request(shape)
            else:
                request = local_slices(shape, tspec, target.axis_sizes,
                                       target.coords)
            req_shape = tuple(s.stop - s.start for s in request)
            dst = np.empty(req_shape, dtype)
            covered = 0
            for sh, smeta in zip(_mf.leaf_shards(lmeta), lmeta["shards"]):
                ov = intersect(sh, request)
                if ov is None:
                    continue
                src_sl, dst_sl = ov
                arr = files.member(smeta["file"], smeta["member"],
                                   int(smeta["crc32c"]), stats)
                if lmeta.get("raw"):
                    arr = np.frombuffer(arr.tobytes(), dtype) \
                        .reshape(sh.shape)
                elif arr.shape != sh.shape:
                    raise CkptShapeMismatch(
                        f"step {step}: shard {smeta['member']} has shape "
                        f"{arr.shape}, manifest says {sh.shape}",
                        step=step, rank=rank,
                        shard=f"{smeta['file']}:{smeta['member']}")
                dst[dst_sl] = arr[src_sl]
                covered += int(np.prod([s.stop - s.start
                                        for s in dst_sl], dtype=np.int64))
            if covered != int(np.prod(req_shape, dtype=np.int64)):
                raise CkptIncomplete(
                    f"step {step}: leaf {lmeta['key']!r} request "
                    f"{request} only {covered} of "
                    f"{int(np.prod(req_shape))} elements covered by "
                    f"saved shards", step=step, rank=rank)
            out_leaves.append(dst)
    finally:
        files.close()
    if template is not None:
        import jax
        treedef = jax.tree_util.tree_structure(template)
        if treedef.num_leaves != len(out_leaves):
            raise CkptShapeMismatch(
                f"step {step}: checkpoint tree {name!r} has "
                f"{len(out_leaves)} leaves but template has "
                f"{treedef.num_leaves}", step=step, rank=rank)
        return jax.tree_util.tree_unflatten(treedef, out_leaves)
    return _ck._nest([m["key"] for m in leaves_meta], out_leaves,
                     entry.get("seq_prefixes") or [])


def restore_dir(step_dir: str, manifest: Dict[str, Any], *,
                like_params=None, like_opt_state=None,
                target: Optional[Target] = None,
                stats: Optional[ReadStats] = None, rank: int = -1):
    """Restore a resolved, loaded format-2 step dir (collective-free);
    emits the ``ckpt_restore`` event. The shared engine behind
    :func:`restore_sharded` and the format dispatch in
    :func:`..utils.checkpoint.restore_checkpoint`."""
    from ..utils import checkpoint as _ck
    from ..utils.logging import append_event

    t0 = time.perf_counter()
    step = int(manifest.get("step", -1))
    own_stats = stats if stats is not None else ReadStats()
    params = read_tree(step_dir, manifest, "params", template=like_params,
                       target=target, stats=own_stats, rank=rank)
    opt_state = read_tree(step_dir, manifest, "opt_state",
                          template=like_opt_state, target=target,
                          stats=own_stats, rank=rank)
    append_event("ckpt_restore", step=step, rank=rank, sharded=True,
                 bytes=own_stats.bytes, shards=own_stats.members,
                 duration_s=round(time.perf_counter() - t0, 6),
                 resharded=target is not None,
                 saved_axes=manifest["mesh"]["axes"],
                 target_axes=(target.axis_sizes if target else None))
    return _ck.Checkpoint(step=step, params=params, opt_state=opt_state,
                          extra=manifest.get("extra") or {})


def restore_sharded(ckpt_dir: str, step: Optional[int] = None, *,
                    like_params=None, like_opt_state=None,
                    target: Optional[Target] = None,
                    stats: Optional[ReadStats] = None,
                    rank: int = -1):
    """Read a format-2 checkpoint back into host pytrees (collective-free).

    Returns a :class:`~..utils.checkpoint.Checkpoint`. ``target`` opts
    into slice restore (see module docstring); ``stats`` collects read
    accounting. Raises ``FileNotFoundError`` when nothing is
    checkpointed, the typed :mod:`.errors` hierarchy when a checkpoint
    exists but cannot be trusted, and :class:`~.errors.CkptError` for a
    format-1 directory (restore those through
    ``utils.checkpoint.restore_checkpoint``, which dispatches).
    """
    from ..utils import checkpoint as _ck
    from .errors import CkptError

    if step is None:
        step = _ck.latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir!r}")
    d = _ck._resolve_step_dir(ckpt_dir, step)
    if d is None:
        raise FileNotFoundError(
            f"no complete checkpoint for step {step} under {ckpt_dir!r}")
    manifest = _mf.load(d, step=step, rank=rank)
    if manifest.get("format") != _mf.FORMAT:
        raise CkptError(
            f"step {step} is a format-{manifest.get('format')} "
            "(single-replica) checkpoint; restore it via "
            "utils.checkpoint.restore_checkpoint", step=step, rank=rank)
    return restore_dir(d, manifest, like_params=like_params,
                       like_opt_state=like_opt_state, target=target,
                       stats=stats, rank=rank)
