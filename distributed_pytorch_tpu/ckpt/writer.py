"""Sharded checkpoint writer: each host writes only the shards it owns.

Split into two halves so the manager can stage them on different threads
(ckpt/manager.py):

* :func:`write_shards` — pure file IO, safe on a background thread: the
  calling rank slices its owned shards out of the (already host-resident)
  arrays, writes them as one ``shard_r<rank>.npz``, stamps each member
  with a CRC32C (native, PR 2 vocabulary), and lands its manifest
  fragment last as the durability marker. **No collectives.**
* :func:`commit` — main-thread only, on the committing rank (0), after a
  barrier has established every rank's fragment is durable: merges
  fragments into the global manifest and runs the two-rename dance from
  :mod:`..utils.checkpoint`, so a crash at any byte leaves the previous
  step complete and discoverable.

Fault-injection hooks (``DPX_FAULT``, runtime/faults.py): the save path
fires op ``ckpt`` at shard-write entry, ``ckpt_commit`` at commit entry,
and ``ckpt_commit_window`` between the two commit renames — the exact
crash window the atomicity tests target.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from . import manifest as _mf
from .integrity import array_crc32c
from .layout import tree_layout


def plan_trees(trees: Dict[str, Any], specs: Dict[str, Any],
               axis_sizes: Dict[str, int], writer_world: int
               ) -> Dict[str, Dict[str, Any]]:
    """Deterministic save plan: tree name → layouts + host arrays.

    ``specs[name]`` may be None (replicated/full layout for that tree).
    Every rank computes the identical plan locally — the committer
    recomputes it to merge fragments without any cross-rank data motion.
    """
    meta: Dict[str, Dict[str, Any]] = {}
    for name, tree in trees.items():
        if tree is None:
            continue
        layouts, arrays, seq = tree_layout(tree, specs.get(name),
                                           axis_sizes, writer_world)
        raw = [np.dtype(lay.dtype).kind == "V" for lay in layouts]
        meta[name] = {"layouts": layouts, "arrays": arrays, "raw": raw,
                      "seq_prefixes": seq}
    return meta


def snapshot_owned(plan: Dict[str, Dict[str, Any]], rank: int,
                   force_copy: bool) -> None:
    """Cut this rank's owned shard pieces out of the plan's arrays
    (main thread — this IS the synchronous part of an async save) and
    drop the full-array references.

    Each host materializes only the 1/world of the state it writes —
    NOT a defensive copy of the whole replica. ``force_copy=True`` when
    the plan references live training arrays (the host front door's
    numpy replicas, which the caller may overwrite next step);
    ``force_copy=False`` when the arrays are already private host
    copies (the single-controller D2H snapshot), where a full-range
    slice stays a zero-copy view.
    """
    for name, meta in sorted(plan.items()):
        pieces: Dict[int, list] = {}
        for l_idx, (lay, arr, raw) in enumerate(
                zip(meta["layouts"], meta["arrays"], meta["raw"])):
            for lin, sh in enumerate(lay.shards):
                if sh.writer != rank:
                    continue
                # reshape pins the shard shape: ascontiguousarray
                # promotes 0-d arrays to (1,), which would disagree
                # with the manifest on read-back
                if force_copy:
                    piece = np.array(arr[sh.slices()]).reshape(sh.shape)
                else:
                    piece = np.ascontiguousarray(arr[sh.slices()]) \
                        .reshape(sh.shape)
                if raw:
                    # extension dtypes (bfloat16/fp8) don't survive npy;
                    # store raw bytes, dtype+shape live in the manifest
                    piece = np.frombuffer(piece.tobytes(), np.uint8)
                pieces.setdefault(l_idx, []).append((lin, piece))
        meta["pieces"] = pieces
        meta["arrays"] = None  # owned slices only from here on


def write_shards(tmp_dir: str, rank: int,
                 plan: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Write this rank's owned shard pieces + fragment into ``tmp_dir``.

    Requires :func:`snapshot_owned` to have cut the pieces. Returns
    ``{"bytes": ..., "shards": ..., "duration_s": ...}``. Safe on a
    background thread: CRC + file IO only.
    """
    from ..runtime import faults

    faults.on_comm_op("ckpt", rank=rank)
    t0 = time.perf_counter()
    members: Dict[str, np.ndarray] = {}
    frag: Dict[str, Dict[str, int]] = {}
    total = 0
    for t_idx, (name, meta) in enumerate(sorted(plan.items())):
        for l_idx, shards in meta["pieces"].items():
            for lin, piece in shards:
                m = _mf.member_name(t_idx, l_idx, lin)
                members[m] = piece
                frag[m] = {"crc32c": array_crc32c(piece),
                           "nbytes": int(piece.nbytes)}
                total += piece.nbytes
    path = os.path.join(tmp_dir, _mf.shard_file(rank))
    if members:
        np.savez(path, **members)
    else:
        np.savez(path)  # owns nothing this step; fragment still lands
    _mf.write_fragment(tmp_dir, rank, frag)  # last: durability marker
    return {"bytes": total, "shards": len(members),
            "duration_s": time.perf_counter() - t0}


def commit(ckpt_dir: str, step: int, tmp_dir: str,
           plan: Dict[str, Dict[str, Any]],
           extra: Optional[Dict[str, Any]],
           axis_sizes: Dict[str, int], writer_world: int,
           keep: Optional[int] = None, rank: int = 0
           ) -> Tuple[str, Dict[str, Any]]:
    """Merge fragments → manifest → two-rename commit (the shared
    ``_commit_full`` dance + fault hooks). Main thread, one rank, after
    all fragments are durable (barrier in the manager)."""
    from ..utils import checkpoint as _ck

    tree_meta = {
        name: {"layouts": meta["layouts"], "raw": meta["raw"],
               "seq_prefixes": meta["seq_prefixes"]}
        for name, meta in plan.items()}
    man = _mf.merge(tmp_dir, step, extra, axis_sizes, writer_world,
                    tree_meta)
    mpath = os.path.join(tmp_dir, _mf.MANIFEST)
    with open(mpath, "w") as f:
        json.dump(man, f)
        f.flush()
        os.fsync(f.fileno())
    final = _ck._commit_full(ckpt_dir, step, tmp_dir, keep=keep,
                             rank=rank)
    return final, man
