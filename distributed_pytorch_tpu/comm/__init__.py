"""Communication: eager collective helpers + in-step primitives."""
from . import collectives, primitives
from .collectives import (all_gather, all_reduce, barrier, broadcast, gather,
                          reduce, sync_params, wait_for_everyone)
