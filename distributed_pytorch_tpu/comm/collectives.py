"""Eager collective helpers — the sync API of reference ``distributed.py:119-187``.

Semantics model. The reference's collectives act on *per-rank tensors*: each
of N processes holds its own ``tensor`` and the collective relates them. Under
single-controller SPMD there are no per-rank processes — the controller holds
*one* array for all ranks. The mapping used throughout this framework:

    per-rank tensor of shape S  ⇔  "stacked" array of shape (world, *S),
                                   sharded over the ``dp`` mesh axis on axis 0

(:func:`distributed_pytorch_tpu.parallel.data_parallel` steps return exactly
this layout for per-rank metrics.) Each helper's world>1 path is a tiny jnp
program on the stacked array; because the array is dp-sharded, XLA lowers the
reduction to real cross-device collectives over ICI — that is the entire
NCCL-replacement story (SURVEY.md §2.3 row 1).

The controller *is* the primary rank, so rooted collectives return the
primary's view directly:

* ``all_reduce``  — stacked → stacked; every rank row holds the reduced
  value (reference ``distributed.py:119-133``; same ``sum``/``avg``/ValueError
  contract).
* ``reduce``      — stacked → single tensor of shape S: the reduced value as
  rank 0 sees it (reference ``distributed.py:136-144``; non-root contents
  are backend-defined there, so collapsing to the root view loses nothing).
* ``gather``      — stacked → list of per-rank tensors as rank 0 sees them
  (reference ``distributed.py:147-160``; the reference's
  zeros-on-non-primary contract is a wart of its allocation strategy — the
  primary-side values, the only defined ones, are what callers may use).
* ``sync_params`` / ``broadcast`` — rank-0 row wins
  (reference ``distributed.py:163-170``).
* ``barrier`` / ``wait_for_everyone`` — drain outstanding device work
  (reference ``distributed.py:173-182``).

Every helper short-circuits to the identity at world==1 with the reference's
exact shapes (``gather`` → ``[x]`` etc.; reference ``distributed.py:122-123,
139-140,150-151,175-176``).

The true multi-process path (one OS process per rank, native TCP collectives
— the gloo/c10d equivalent) implements this same signature set in
:mod:`distributed_pytorch_tpu.comm.host_backend`.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from ..runtime import context
from . import host_backend
from .sanitizer import CollectiveMismatch  # noqa: F401  (re-export:
# under DPX_COMM_SANITIZE=1 every front-door collective may raise it
# on cross-rank divergence — comm/sanitizer.py, docs/analysis.md)

_VALID_OPS = ("sum", "avg", "max", "min")


def _check_stacked(x: jnp.ndarray, fn: str) -> jnp.ndarray:
    world = context.get_world_size()
    if x.ndim == 0 or x.shape[0] != world:
        raise ValueError(
            f"{fn} expects a stacked (world, ...) array with one row per "
            f"rank; got shape {x.shape} with world={world}"
        )
    return x


def _reduce_stacked(x: jnp.ndarray, op: str) -> jnp.ndarray:
    if op == "sum":
        return jnp.sum(x, axis=0)
    if op == "avg":
        # Reference computes SUM then divides by world (distributed.py:127-129).
        return jnp.sum(x, axis=0) / context.get_world_size()
    if op == "max":
        return jnp.max(x, axis=0)
    if op == "min":
        return jnp.min(x, axis=0)
    raise ValueError(f'"{op}" is an invalid reduce operation!')


def all_reduce(tensor, op: str = "sum", wire: str = "exact"):
    """All-reduce over the rank axis (reference ``distributed.py:119-133``).

    world==1: identity. world>1: ``tensor`` is stacked ``(world, *S)``; the
    result is stacked with every row equal to the reduction. Invalid ``op``
    raises ``ValueError`` like the reference (``distributed.py:131``); as
    there, validation happens only on the distributed path.

    ``wire="quant"``/``"q4"``/``"adaptive"`` opts the HOST front door's
    sum/avg into the block-quantized ring (:mod:`.wire`; ~4x/~7.9x less
    TCP traffic, lossy; adaptive width with hysteresis; two-level under
    ``DPX_HIER_RING``). The single-controller path has no wire to
    compress — XLA moves exact bytes over ICI — so it ignores the hint
    and stays exact (the flag is accepted for cross-front-door
    call-site parity).
    """
    comm = context.get_host_comm()
    if comm is not None:
        return host_backend.all_reduce(comm, tensor, op, wire=wire)
    host_backend._check_wire(wire)
    if context.get_world_size() == 1:
        return tensor
    x = _check_stacked(jnp.asarray(tensor), "all_reduce")
    reduced = _reduce_stacked(x, op)
    return jnp.broadcast_to(reduced[None], x.shape)


def reduce(tensor, op: str = "sum"):
    """Rooted reduce to the primary (reference ``distributed.py:136-144``).

    world==1: identity. world>1: input stacked ``(world, *S)``, output the
    reduced tensor of shape S — the value rank 0 holds in the reference
    (non-root contents are backend-defined there, §2.1 #13)."""
    comm = context.get_host_comm()
    if comm is not None:
        return host_backend.reduce(comm, tensor, op)
    if context.get_world_size() == 1:
        return tensor
    return _reduce_stacked(_check_stacked(jnp.asarray(tensor), "reduce"), op)


def gather(data) -> List:
    """Rooted gather to the primary (reference ``distributed.py:147-160``).

    world==1: ``[data]``. world>1: input stacked ``(world, *S)``, output the
    primary's gather list ``[rank0, rank1, ...]`` (each shape S). As in the
    reference, equal per-rank shapes are required — guaranteed here by the
    stacked layout."""
    comm = context.get_host_comm()
    if comm is not None:
        return host_backend.gather(comm, data)
    world = context.get_world_size()
    if world == 1:
        return [data]
    x = jnp.asarray(data)
    if x.shape[0] != world:
        raise ValueError(
            f"gather expects a stacked (world, ...) array; got shape {x.shape} "
            f"with world={world}"
        )
    return [x[r] for r in range(world)]


def all_gather(data):
    """All-gather: every rank sees the stacked values.

    No direct reference analog (its ``gather`` is rooted); provided because
    it is the natural TPU primitive the rooted emulations ride on
    (SURVEY.md §5 'distributed communication backend')."""
    comm = context.get_host_comm()
    if comm is not None:
        return host_backend.all_gather(comm, data)
    world = context.get_world_size()
    if world == 1:
        return jnp.asarray(data)[None]
    return _check_stacked(jnp.asarray(data), "all_gather")


def broadcast(tensor, src: int = 0):
    """Broadcast the ``src`` rank's value to all ranks.

    world>1: input stacked ``(world, *S)``; output stacked with every row
    equal to row ``src``. Underlies :func:`sync_params` (reference
    ``distributed.py:163-170``)."""
    comm = context.get_host_comm()
    if comm is not None:
        return host_backend.broadcast(comm, tensor, src)
    world = context.get_world_size()
    if world == 1:
        return tensor
    if not (0 <= src < world):
        raise ValueError(f"broadcast src={src} out of range for world={world}")
    x = _check_stacked(jnp.asarray(tensor), "broadcast")
    return jnp.broadcast_to(x[src][None], x.shape)


def sync_params(params: Sequence, wire: str = "exact"):
    """Synchronize a sequence of tensors from rank 0 (reference
    ``distributed.py:163-170``).

    Under SPMD, replicated parameters are *by construction* identical on all
    devices, so this re-asserts replicated placement (a no-op when already
    replicated) rather than moving bytes. It exists for the reference's
    stated use case — non-DDP/EMA params after load — where the input may be
    host or per-device data.

    ``wire="quant"``: on the host front door rank 0's floats broadcast in
    the block-int8 format (every rank, rank 0 included, adopts the
    dequantized value — still bit-identical everywhere). Ignored on the
    single-controller path, which moves no bytes to begin with."""
    comm = context.get_host_comm()
    if comm is not None:
        return host_backend.sync_params(comm, params, wire=wire)
    host_backend._check_wire(wire)
    if not context.is_initialized():
        return list(params)
    return [jax.device_put(p, context.replicated_sharding()) for p in params]


def barrier():
    """Wait until all outstanding device work is done (reference
    ``distributed.py:173-177``).

    A single controller needs no cross-process rendezvous; the observable
    contract — nothing after the barrier begins until everything before it
    finished everywhere — is delivered by draining the async dispatch queue.
    In host mode (per-rank processes) it is a true cross-process rendezvous
    on the native group.
    """
    comm = context.get_host_comm()
    if comm is not None:
        return host_backend.barrier(comm)
    if context.get_world_size() == 1:
        return
    # Enqueue a trivial op on EVERY mesh device and block: per-device FIFO
    # ordering then guarantees all previously dispatched work on all devices
    # has completed.
    token = jax.device_put(jnp.zeros(()), context.replicated_sharding())
    token.block_until_ready()


def wait_for_everyone():
    """Readability alias for :func:`barrier` (reference ``distributed.py:181-182``)."""
    barrier()
