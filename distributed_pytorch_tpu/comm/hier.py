"""Hierarchical two-level gradient ring: fast hop intra-host, quantized
slow hop between one designated leader per host.

A flat quantized ring over W ranks puts EVERY byte of every hop on the
same transport; when ranks live L-per-host, 1/L of those hops cross the
slow inter-host link, but the slow links still carry the full
``2*(W-1)/W * quant_bytes`` stream each — total slow-hop traffic
``~2*(W-1)*quant_bytes(n)``. The two-level schedule here (the classic
hierarchical allreduce, cf. the CUDA-aware-MPI characterization in
arXiv 1810.11112) instead:

1. **hier_reduce** — each host reduces EXACT f32 to its leader over the
   fast hop (the rooted native hub, modeling the intra-host
   ``psum_scatter`` an SPMD-per-host deployment runs over ICI), then
   the ``nh = W/L`` leaders run the quantized ring's reduce-scatter leg
   among themselves;
2. **hier_gather** — the leaders run the byte-forwarding all-gather
   leg (bit-identical result on every leader), then each leader
   broadcasts exact f32 back over the fast hop.

Total slow-hop traffic: ``2*(nh-1)*quant_bytes(n)`` — each gradient
byte crosses the slow hop exactly once per leg, ``(W-1)/(nh-1) ~ L``
times less than the flat ring. Results are BIT-IDENTICAL on every rank
(leader-ring bit-identity + exact local broadcast), so replicas cannot
drift — the same contract as the flat quantized ring.

The numpy executable spec is :func:`..comm.wire.simulate_hier_ring`
(bit-exact against this class: the rooted hub accumulates in the same
local-rank order, and the leader ring is the native ``dpx_*_qn`` family
the flat-ring parity tests already pin).

Observability/failure surface: both phases fire the ``DPX_FAULT``
grammar (``kill@op=hier_reduce`` dies entering phase 1), record
``hier_reduce``/``hier_gather`` on the PARENT comm's schedule digest
(so a rank disagreeing about width or shape diverges attributably), and
account the SLOW-HOP bytes on the parent's CommStats under those op
names (the fast-hop traffic is accounted on the local sub-comm's own
stats under ``reduce``/``broadcast`` — the two transports are different
budgets and must not be summed). A failure in either phase aborts every
link (sub-groups and parent) so the whole world fails typed within one
deadline tick, re-raised as the same :class:`~..runtime.native.CommError`
subtype attributed to the hier op.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..runtime.native import CommError, CommTimeout, HostComm
from . import wire as _wire

#: Port offset of the hierarchy's sub-groups relative to the parent
#: group's base port: local group h occupies
#: ``base + world + 1 + h*local_world .. + local_world`` and the leader
#: group ``base + 2*world + 1 .. + nh`` — disjoint from the parent's
#: ``base .. base + world - 1`` listeners by construction.
_LOCAL_PORT_OFFSET = 1
_LEADER_PORT_OFFSET = 1


class HierRing:
    """Two-level ring over an existing :class:`HostComm` group.

    ``local_world`` consecutive ranks form one "host"; rank
    ``host*local_world`` is its designated leader. Build it once per
    group (or use :func:`hier_ring`, which caches on the comm) — the
    constructor rendezvouses the sub-groups, which is a collective
    moment all ranks must reach."""

    def __init__(self, comm: HostComm, local_world: Optional[int] = None,
                 *, rendezvous_timeout_ms: int = 30000):
        if local_world is None:
            from ..runtime import env as _env
            local_world = int(_env.get("DPX_HIER_RING"))
        if local_world < 1 or comm.world % local_world:
            raise ValueError(
                f"DPX_HIER_RING/local_world {local_world} must be >= 1 "
                f"and divide world {comm.world}")
        from ..runtime import faults as _faults
        self._faults = _faults
        self.comm = comm
        self.local_world = local_world
        self.nh = comm.world // local_world
        self.host = comm.rank // local_world
        self.local_rank = comm.rank % local_world
        self.is_leader = self.local_rank == 0

        base = comm.base_port
        self.local = None
        self.leaders = None
        if local_world > 1:
            local_base = (base + comm.world + _LOCAL_PORT_OFFSET
                          + self.host * local_world)
            self.local = HostComm(
                comm.master_addr, local_base, self.local_rank,
                local_world, timeout_ms=rendezvous_timeout_ms,
                op_timeout_ms=comm.op_timeout_ms)
        if self.is_leader and self.nh > 1:
            leader_base = base + 2 * comm.world + _LEADER_PORT_OFFSET
            self.leaders = HostComm(
                comm.master_addr, leader_base, self.host, self.nh,
                timeout_ms=rendezvous_timeout_ms,
                op_timeout_ms=comm.op_timeout_ms)

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        for sub in (self.local, self.leaders):
            if sub is not None:
                sub.close()
        self.local = self.leaders = None

    def abort(self):
        """Tear down every link NOW — sub-groups AND the parent group —
        so peers blocked in ANY phase observe peer-closed within one
        deadline tick (also the ``drop_conn`` fault action's target)."""
        for sub in (self.local, self.leaders):
            if sub is not None:
                sub.abort()
        self.comm.abort()

    def barrier(self):
        """Parent-group barrier (the ``diverge`` fault action's hook)."""
        self.comm.barrier()

    # -- the collective ----------------------------------------------------

    @property
    def rank(self) -> int:
        return self.comm.rank

    def slow_hop_bytes(self, n: int, bits: int = 8,
                       block: int = None) -> int:
        """Per-LEADER slow-hop wire bytes of ONE leg for an n-element
        buffer (0 on non-leaders — they never touch the slow hop)."""
        block = block or _wire.QUANT_BLOCK
        if not self.is_leader or self.nh <= 1:
            return 0
        return _wire.quant_leg_wire_bytes(n, self.nh, block, bits) \
            // self.nh

    def _pre_op(self, op: str, n: int, bits: int) -> None:
        # fault hook first (an injected kill must land at ITS issue
        # point), then the parent schedule digest — mirroring
        # HostComm._pre_op so hier steps verify cross-rank like flat ones
        self._faults.on_comm_op(op, rank=self.comm.rank, comm=self)
        self.comm.schedule.record(
            op, dtype="float32", size=int(n),
            extra=f"q{bits},L={self.local_world}")
        # sanitize the LOGICAL hier op on the parent group (every
        # global rank enters it) — the sub-group legs each carry their
        # own comm's sanitizer
        if self.comm._sanitizer is not None:
            self.comm._sanitizer.check(op, dtype="float32", size=int(n))

    def _global_peer(self, e: CommError, scope: str) -> int:
        """Translate a sub-group CommError's blamed peer into a GLOBAL
        rank — the supervisor's died-without-reporting attribution
        joins blames across ranks, so a local-group index would point
        at the wrong process."""
        p = getattr(e, "peer", -1)
        if p is None or p < 0:
            return -1
        if scope == "local":
            return self.host * self.local_world + p
        return p * self.local_world  # leader h sits at global h*L

    def _reraise(self, op: str, e: CommError, scope: str):
        # abort EVERYTHING first: a healthy host's members would
        # otherwise sit out their full deadline inside the local
        # broadcast while only the leaders know the slow hop died
        self.comm.schedule.flush(op=op)
        self.abort()
        kw = dict(op=op, rank=self.comm.rank,
                  peer=self._global_peer(e, scope))
        msg = f"hierarchical ring failed in {op}: {e}"
        if isinstance(e, CommTimeout):
            raise CommTimeout(msg, deadline_ms=e.deadline_ms,
                              **kw) from e
        raise type(e)(msg, **kw) from e

    def allreduce(self, arr: np.ndarray, bits: int = 8,
                  block: int = None, hidden: bool = False) -> np.ndarray:
        """In-place two-level allreduce (sum) of a flat f32 buffer.

        Exact intra-host, quantized (``bits`` wide) between leaders;
        result bit-identical on every rank. ``hidden`` routes the wall
        time into CommStats' overlapped bucket (the overlapping train
        step's non-final gradient buckets)."""
        _wire.quant_levels(bits)
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        n = arr.size
        leg = self.slow_hop_bytes(n, bits, block)
        kwargs = {} if block is None else {"block": block}

        self._pre_op("hier_reduce", n, bits)
        with self.comm.stats.timed("hier_reduce", leg, hidden=hidden):
            if self.local is not None:
                # rooted exact f32 sum to the leader (fast hop, in
                # place on the leader); non-leader buffers stay
                # untouched until the phase-2 broadcast
                try:
                    out = self.local.reduce(arr)
                except CommError as e:
                    self._reraise("hier_reduce", e, "local")
                if self.is_leader and out is not arr:
                    arr[...] = out
            if self.leaders is not None:
                try:
                    self.leaders.reduce_scatter_quant(arr, bits,
                                                      **kwargs)
                except CommError as e:
                    self._reraise("hier_reduce", e, "leaders")

        self._pre_op("hier_gather", n, bits)
        with self.comm.stats.timed("hier_gather", leg, hidden=hidden):
            if self.leaders is not None:
                try:
                    self.leaders.allgather_quant(arr, bits, **kwargs)
                except CommError as e:
                    self._reraise("hier_gather", e, "leaders")
            if self.local is not None:
                try:
                    self.local.broadcast(arr, src=0)
                except CommError as e:
                    self._reraise("hier_gather", e, "local")
        return arr


def hier_ring(comm: HostComm,
              local_world: Optional[int] = None) -> HierRing:
    """The comm's cached :class:`HierRing` (built on first use; torn
    down with the comm). All ranks must first call this at the same
    point — construction rendezvouses the sub-groups. A second call
    requesting a DIFFERENT topology raises: silently reusing the old
    ring would run the wrong byte/schedule accounting (and rebuilding
    would be a hidden collective rendezvous mid-step)."""
    if local_world is None:
        from ..runtime import env as _env
        local_world = int(_env.get("DPX_HIER_RING"))
    ring = getattr(comm, "_hier_ring", None)
    if ring is None:
        ring = HierRing(comm, local_world)
        comm._hier_ring = ring
    elif ring.local_world != local_world:
        raise ValueError(
            f"hier_ring already built with local_world="
            f"{ring.local_world}; cannot switch to {local_world} on a "
            "live group (close the comm or build HierRing explicitly)")
    return ring
