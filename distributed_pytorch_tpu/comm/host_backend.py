"""Host-mode collectives: reference-exact per-rank semantics over the
native TCP process group.

When the current process is a spawned per-rank worker
(runtime/multiprocess.py), each rank holds its OWN tensor — the reference's
execution model — and these implementations reproduce reference
``distributed.py:119-177`` semantics bit-for-bit, including the
warts: ``reduce`` leaves non-root buffers untouched, ``gather`` returns a
list of ZEROS on non-primary ranks (reference ``distributed.py:153-160``).
The transport is native ring-allreduce / hub rooted ops
(native/dpxhost.cpp), the gloo replacement.

All functions take/return numpy arrays (host-resident data; accelerator
arrays are converted in, which is exactly what torch's gloo path does with
CPU staging).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def _to_np(tensor) -> np.ndarray:
    return np.asarray(tensor)


def all_reduce(comm, tensor, op: str = "sum"):
    """Reference distributed.py:119-133: sum or sum/world, in every rank.
    (max/min supported too, matching the SPMD front door's extension.)"""
    x = _to_np(tensor)
    if op not in ("sum", "avg", "max", "min"):
        raise ValueError(f'"{op}" is an invalid reduce operation!')
    orig_dtype = x.dtype
    if op in ("max", "min"):
        stacked = comm.all_gather(np.ascontiguousarray(x))
        return (stacked.max(axis=0) if op == "max"
                else stacked.min(axis=0))
    work = x.astype(np.float64) if x.dtype.kind in "iub" else x.copy()
    comm.allreduce(work)
    if op == "avg":
        work = work / comm.world
    return work.astype(orig_dtype) if x.dtype.kind in "iub" else work


def reduce(comm, tensor, op: str = "sum"):
    """Reference distributed.py:136-144: rooted sum to rank 0; non-root
    buffers returned unchanged (their contents backend-defined there).
    Dtype is preserved (integer inputs reduce exactly via float64)."""
    if op != "sum":
        raise ValueError(f'"{op}" is an invalid reduce operation!')
    x = _to_np(tensor)
    orig_dtype = x.dtype
    if orig_dtype == np.float32:
        return comm.reduce(x.copy())
    # other dtypes: exact sum in f64 via the ring, root casts back,
    # non-root returns its buffer unchanged (the reference contract)
    work = x.astype(np.float64)
    comm.allreduce(work)
    if comm.rank == 0:
        return work.astype(orig_dtype)
    return x.copy()


def all_gather(comm, tensor) -> np.ndarray:
    """Every rank gets the stacked (world, *S) values."""
    return comm.all_gather(np.ascontiguousarray(_to_np(tensor)))


def gather(comm, tensor) -> List[np.ndarray]:
    """Reference distributed.py:147-160: the primary gets the real values;
    every other rank gets the zeros it allocated."""
    x = _to_np(tensor)
    out = comm.gather(x)
    if out is not None:
        return out
    return [np.zeros_like(x) for _ in range(comm.world)]


def broadcast(comm, tensor, src: int = 0):
    x = _to_np(tensor).copy()
    return comm.broadcast(x, src=src)


def sync_params(comm, params: Sequence) -> list:
    """Reference distributed.py:163-170: broadcast each tensor from 0."""
    return [comm.broadcast(_to_np(p).copy(), src=0) for p in params]


def barrier(comm):
    comm.barrier()
