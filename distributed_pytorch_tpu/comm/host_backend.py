"""Host-mode collectives: reference-exact per-rank semantics over the
native TCP process group.

When the current process is a spawned per-rank worker
(runtime/multiprocess.py), each rank holds its OWN tensor — the reference's
execution model — and these implementations reproduce reference
``distributed.py:119-177`` semantics bit-for-bit, including the
warts: ``reduce`` leaves non-root buffers untouched, ``gather`` returns a
list of ZEROS on non-primary ranks (reference ``distributed.py:153-160``).
The transport is native ring-allreduce / hub rooted ops
(native/dpxhost.cpp), the gloo replacement.

All functions take/return numpy arrays (host-resident data; accelerator
arrays are converted in, which is exactly what torch's gloo path does with
CPU staging).

Wire formats: every collective defaults to the exact full-width wire.
``all_reduce``/``sync_params`` additionally accept ``wire="quant"`` —
the block-scaled quantized format of :mod:`.wire` (~4x less TCP traffic
at the default 8-bit width, lossy, bit-identical across ranks) — plus
``wire="q4"`` (nibble-packed, ~7.9x) and ``wire="adaptive"`` (width per
bucket from observed dynamic range, hysteresis across steps; the
``quant`` default width itself comes from ``DPX_WIRE_WIDTH``). With
``DPX_HIER_RING=L`` the quantized reduce runs the two-level ring
(:mod:`.hier`): exact intra-host to one leader per host, quantized ring
between leaders only. The REFERENCE-EXACT contracts are never
quantized: ``reduce`` (non-root buffers untouched) and ``gather``
(zeros-on-non-primary) always move exact full-width bytes, as does any
integer payload (f64 ring keeps integer sums exact).

Failure semantics: every collective here observes the native per-op
deadline (``DPX_COMM_TIMEOUT_MS``) and raises the typed
:class:`~..runtime.native.CommError` hierarchy re-exported below —
``CommPeerDied`` (a rank died mid-collective), ``CommTimeout`` (wedged
peer/link), ``CommCorrupt`` (quant frame failed CRC32). A failed op
tears this rank's links down, so peers fail within one deadline tick
instead of deadlocking (see docs/failures.md).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..runtime import env as _env
from ..runtime.native import (CommCorrupt, CommError,  # noqa: F401
                              CommPeerDied, CommTimeout)
from . import wire as _wire
from .sanitizer import CollectiveMismatch  # noqa: F401  (re-export:
# the DPX_COMM_SANITIZE divergence error surfaces through this module
# like every other typed comm failure)

#: Wire formats a lossy-tolerant collective accepts. ``quant`` is the
#: historical opt-in (width from the typed ``DPX_WIRE_WIDTH`` knob,
#: default 8-bit); ``q4`` forces the nibble-packed 4-bit wire;
#: ``adaptive`` picks the width per bucket from observed dynamic range
#: (:class:`..comm.wire.WidthChooser`, hysteresis across steps).
WIRE_FORMATS = ("exact", "quant", "q4", "adaptive")


def _check_wire(wire: str) -> str:
    if wire not in WIRE_FORMATS:
        raise ValueError(f"wire must be one of {WIRE_FORMATS}, got {wire!r}")
    return wire


_warned_widths = set()


def resolve_wire_width(wire: str):
    """Map a wire format onto a width: ``None`` (exact), 8, 4, or the
    string ``"adaptive"``. ``wire="quant"`` defers to the typed
    ``DPX_WIRE_WIDTH`` registry knob so a deployment can move every
    ``quant`` call site to q4/adaptive without touching code. An
    unrecognized knob value degrades to the q8 default — the registry's
    malformed-falls-back contract; env garbage must not crash a job at
    its first collective — but LOUDLY, once per value."""
    _check_wire(wire)
    if wire == "exact":
        return None
    if wire == "q4":
        return 4
    if wire == "adaptive":
        return "adaptive"
    w = str(_env.get("DPX_WIRE_WIDTH") or "8").strip().lower()
    if w == "adaptive":
        return "adaptive"
    if w in ("4", "8"):
        return int(w)
    if w not in _warned_widths:
        _warned_widths.add(w)
        import sys
        print(f"# DPX_WIRE_WIDTH={w!r} not one of 8|4|adaptive — "
              f"falling back to the q8 wire", file=sys.stderr)
    return 8


def _chooser_for(comm, size: int) -> "_wire.WidthChooser":
    """The comm's cached adaptive width chooser for buckets of ``size``
    elements — keyed per bucket size so call sites reducing DIFFERENT
    tensors through one comm don't interleave a single hysteresis state
    machine (a q4-friendly gradient bucket alternating with a
    q4-hostile metric tensor would otherwise pin each other's width).
    Size is the bucket identity the eager front door can observe; the
    train step keeps its own chooser per step function. Every chooser
    is fed the bit-identical reduced bucket after its reduce, so all
    ranks' machines agree (comm/wire.py)."""
    chs = getattr(comm, "_width_choosers", None)
    if chs is None:
        chs = comm._width_choosers = {}
    ch = chs.get(size)
    if ch is None:
        ch = chs[size] = _wire.WidthChooser()
    return ch


def _quant_allreduce(comm, work: np.ndarray, wire: str) -> np.ndarray:
    """Ship a flat f32 sum bucket over the quantized ring: width from
    the wire format (adaptive = per-bucket-size chooser), two-level
    when ``DPX_HIER_RING`` names a local world that divides this one."""
    width = resolve_wire_width(wire)
    chooser = _chooser_for(comm, work.size) \
        if width == "adaptive" else None
    bits = chooser.width if chooser is not None else width
    local_world = int(_env.get("DPX_HIER_RING"))
    if local_world > 1 and comm.world % local_world == 0:
        from .hier import hier_ring
        hier_ring(comm, local_world).allreduce(work, bits=bits)
    elif bits == 4:
        comm.allreduce_q4(work)
    else:
        comm.allreduce_q8(work)
    if chooser is not None:
        # observe the REDUCED bucket (bit-identical on every rank) so
        # every rank's chooser steps the same state machine
        chooser.observe(work)
    return work


def _to_np(tensor) -> np.ndarray:
    return np.asarray(tensor)


def all_reduce(comm, tensor, op: str = "sum", wire: str = "exact"):
    """Reference distributed.py:119-133: sum or sum/world, in every rank.
    (max/min supported too, matching the SPMD front door's extension.)

    ``wire="quant"``/``"q4"``/``"adaptive"`` ships sum/avg over the
    chunk-pipelined quantized ring (:meth:`..runtime.native.HostComm.
    allreduce_quant`; two-level under ``DPX_HIER_RING``) — opt-in and
    only where lossy is safe: float data under sum/avg. max/min and
    integer payloads always use the exact ring (a quantized max would
    corrupt the winner's exact value; integers must sum exactly)."""
    x = _to_np(tensor)
    if op not in ("sum", "avg", "max", "min"):
        raise ValueError(f'"{op}" is an invalid reduce operation!')
    _check_wire(wire)
    orig_dtype = x.dtype
    if op in ("max", "min"):
        # elementwise ring reduce — same 2*(W-1)/W bytes as sum (the old
        # emulation all-gathered the full tensor from every rank)
        if x.dtype == np.float32:
            return comm.allreduce(x.copy(), op=op)
        work = comm.allreduce(x.astype(np.float64), op=op)
        return work.astype(orig_dtype) if x.dtype != np.float64 else work
    if (wire != "exact" and x.dtype.kind not in "iub"
            and comm.world > 1):
        work = _quant_allreduce(comm, x.astype(np.float32, copy=True),
                                wire)
        if op == "avg":
            work = work / comm.world
        return work.astype(orig_dtype) if orig_dtype != np.float32 else work
    work = x.astype(np.float64) if x.dtype.kind in "iub" else x.copy()
    comm.allreduce(work)
    if op == "avg":
        work = work / comm.world
    return work.astype(orig_dtype) if x.dtype.kind in "iub" else work


def reduce(comm, tensor, op: str = "sum"):
    """Reference distributed.py:136-144: rooted sum to rank 0; non-root
    buffers returned unchanged (their contents backend-defined there).
    Dtype is preserved (integer inputs reduce exactly via float64)."""
    if op != "sum":
        raise ValueError(f'"{op}" is an invalid reduce operation!')
    x = _to_np(tensor)
    orig_dtype = x.dtype
    if orig_dtype == np.float32:
        return comm.reduce(x.copy())
    # other dtypes: exact sum in f64 via the ring, root casts back,
    # non-root returns its buffer unchanged (the reference contract)
    work = x.astype(np.float64)
    comm.allreduce(work)
    if comm.rank == 0:
        return work.astype(orig_dtype)
    return x.copy()


def all_gather(comm, tensor) -> np.ndarray:
    """Every rank gets the stacked (world, *S) values."""
    return comm.all_gather(np.ascontiguousarray(_to_np(tensor)))


def gather(comm, tensor) -> List[np.ndarray]:
    """Reference distributed.py:147-160: the primary gets the real values;
    every other rank gets the zeros it allocated."""
    x = _to_np(tensor)
    out = comm.gather(x)
    if out is not None:
        return out
    return [np.zeros_like(x) for _ in range(comm.world)]


def broadcast(comm, tensor, src: int = 0):
    x = _to_np(tensor).copy()
    return comm.broadcast(x, src=src)


def _broadcast_quant(comm, x: np.ndarray, bits: int) -> np.ndarray:
    """Broadcast one f32 tensor from rank 0 in the quantized frame form
    (``[scales][payload]``, nibble-packed at q4). EVERY rank — rank 0
    included — adopts the dequantized value, so results stay
    bit-identical across ranks."""
    n = x.size
    nb = _wire.num_blocks(n)
    frame = np.empty(_wire.quant_wire_bytes(n, bits=bits), np.uint8)
    if comm.rank == 0:
        q, scales = _wire.quantize_blocks(
            x.astype(np.float32).ravel(), bits=bits)
        frame[:4 * nb] = scales.view(np.uint8)
        frame[4 * nb:] = (_wire.pack_nibbles(q) if bits == 4
                          else q.view(np.uint8))
    comm.broadcast(frame, src=0)
    scales = frame[:4 * nb].view(np.float32)
    q = (_wire.unpack_nibbles(frame[4 * nb:], n) if bits == 4
         else frame[4 * nb:].view(np.int8))
    return _wire.dequantize_blocks(q, scales).reshape(x.shape) \
        .astype(x.dtype)


def sync_params(comm, params: Sequence, wire: str = "exact") -> list:
    """Reference distributed.py:163-170: broadcast each tensor from 0.

    ``wire="quant"``/``"q4"``: rank 0 block-quantizes each FLOAT32
    tensor (:mod:`.wire` format) and broadcasts the payload+scales frame
    instead of full-width bytes (~4x / ~7.9x less traffic for big param
    syncs). ``wire="adaptive"``: rank 0 picks the width per tensor from
    its dynamic range and ships the one-byte verdict ahead of the frame
    (receivers must know the frame size before the bytes arrive). EVERY
    rank — rank 0 included — adopts the dequantized value, so params
    stay bit-identical across ranks (the only guarantee sync_params
    makes; the absolute values move by at most one quantization step).
    All other dtypes (integers, f16, f64) always broadcast exact."""
    width = resolve_wire_width(wire)
    xs = [_to_np(p) for p in params]
    # quantize f32 only: f64 would silently lose precision through the
    # f32 cast beyond the one-step bound, and f16 is already half-width
    # — both broadcast exact, as do integers
    quantizable = [i for i, x in enumerate(xs)
                   if width is not None and x.dtype == np.float32
                   and comm.world > 1]
    widths = {}
    if quantizable and width == "adaptive":
        # ONE verdict broadcast for the whole tree: rank 0 sees every
        # tensor up front, so per-tensor verdict round trips would pay
        # N extra rooted broadcasts for nothing (each is a full round
        # trip on a high-latency link — the big-param-sync use case)
        verdicts = np.zeros(len(quantizable), np.uint8)
        if comm.rank == 0:
            for j, i in enumerate(quantizable):
                frac = _wire.block_outlier_frac(xs[i])
                verdicts[j] = (4 if frac <= _wire.Q4_MAX_OUTLIER_FRAC
                               else 8)
        comm.broadcast(verdicts, src=0)
        widths = {i: int(verdicts[j])
                  for j, i in enumerate(quantizable)}
    elif quantizable:
        widths = {i: width for i in quantizable}
    out = []
    for i, x in enumerate(xs):
        if i in widths:
            out.append(_broadcast_quant(comm, x, widths[i]))
        else:
            out.append(comm.broadcast(x.copy(), src=0))
    return out


def barrier(comm):
    comm.barrier()
