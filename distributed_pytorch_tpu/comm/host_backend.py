"""Host-mode collectives: reference-exact per-rank semantics over the
native TCP process group.

When the current process is a spawned per-rank worker
(runtime/multiprocess.py), each rank holds its OWN tensor — the reference's
execution model — and these implementations reproduce reference
``distributed.py:119-177`` semantics bit-for-bit, including the
warts: ``reduce`` leaves non-root buffers untouched, ``gather`` returns a
list of ZEROS on non-primary ranks (reference ``distributed.py:153-160``).
The transport is native ring-allreduce / hub rooted ops
(native/dpxhost.cpp), the gloo replacement.

All functions take/return numpy arrays (host-resident data; accelerator
arrays are converted in, which is exactly what torch's gloo path does with
CPU staging).

Wire formats: every collective defaults to the exact full-width wire.
``all_reduce``/``sync_params`` additionally accept ``wire="quant"`` — the
block-scaled int8 format of :mod:`.wire` (~4x less TCP traffic, lossy,
bit-identical across ranks). The REFERENCE-EXACT contracts are never
quantized: ``reduce`` (non-root buffers untouched) and ``gather``
(zeros-on-non-primary) always move exact full-width bytes, as does any
integer payload (f64 ring keeps integer sums exact).

Failure semantics: every collective here observes the native per-op
deadline (``DPX_COMM_TIMEOUT_MS``) and raises the typed
:class:`~..runtime.native.CommError` hierarchy re-exported below —
``CommPeerDied`` (a rank died mid-collective), ``CommTimeout`` (wedged
peer/link), ``CommCorrupt`` (quant frame failed CRC32). A failed op
tears this rank's links down, so peers fail within one deadline tick
instead of deadlocking (see docs/failures.md).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..runtime.native import (CommCorrupt, CommError,  # noqa: F401
                              CommPeerDied, CommTimeout)
from . import wire as _wire

#: Wire formats a lossy-tolerant collective accepts.
WIRE_FORMATS = ("exact", "quant")


def _check_wire(wire: str) -> str:
    if wire not in WIRE_FORMATS:
        raise ValueError(f"wire must be one of {WIRE_FORMATS}, got {wire!r}")
    return wire


def _to_np(tensor) -> np.ndarray:
    return np.asarray(tensor)


def all_reduce(comm, tensor, op: str = "sum", wire: str = "exact"):
    """Reference distributed.py:119-133: sum or sum/world, in every rank.
    (max/min supported too, matching the SPMD front door's extension.)

    ``wire="quant"`` ships sum/avg over the chunk-pipelined int8 ring
    (:meth:`..runtime.native.HostComm.allreduce_q8`) — opt-in and only
    where lossy is safe: float data under sum/avg. max/min and integer
    payloads always use the exact ring (an int8 max would corrupt the
    winner's exact value; integers must sum exactly)."""
    x = _to_np(tensor)
    if op not in ("sum", "avg", "max", "min"):
        raise ValueError(f'"{op}" is an invalid reduce operation!')
    _check_wire(wire)
    orig_dtype = x.dtype
    if op in ("max", "min"):
        # elementwise ring reduce — same 2*(W-1)/W bytes as sum (the old
        # emulation all-gathered the full tensor from every rank)
        if x.dtype == np.float32:
            return comm.allreduce(x.copy(), op=op)
        work = comm.allreduce(x.astype(np.float64), op=op)
        return work.astype(orig_dtype) if x.dtype != np.float64 else work
    if (wire == "quant" and x.dtype.kind not in "iub"
            and comm.world > 1):
        work = comm.allreduce_q8(x.astype(np.float32, copy=True))
        if op == "avg":
            work = work / comm.world
        return work.astype(orig_dtype) if orig_dtype != np.float32 else work
    work = x.astype(np.float64) if x.dtype.kind in "iub" else x.copy()
    comm.allreduce(work)
    if op == "avg":
        work = work / comm.world
    return work.astype(orig_dtype) if x.dtype.kind in "iub" else work


def reduce(comm, tensor, op: str = "sum"):
    """Reference distributed.py:136-144: rooted sum to rank 0; non-root
    buffers returned unchanged (their contents backend-defined there).
    Dtype is preserved (integer inputs reduce exactly via float64)."""
    if op != "sum":
        raise ValueError(f'"{op}" is an invalid reduce operation!')
    x = _to_np(tensor)
    orig_dtype = x.dtype
    if orig_dtype == np.float32:
        return comm.reduce(x.copy())
    # other dtypes: exact sum in f64 via the ring, root casts back,
    # non-root returns its buffer unchanged (the reference contract)
    work = x.astype(np.float64)
    comm.allreduce(work)
    if comm.rank == 0:
        return work.astype(orig_dtype)
    return x.copy()


def all_gather(comm, tensor) -> np.ndarray:
    """Every rank gets the stacked (world, *S) values."""
    return comm.all_gather(np.ascontiguousarray(_to_np(tensor)))


def gather(comm, tensor) -> List[np.ndarray]:
    """Reference distributed.py:147-160: the primary gets the real values;
    every other rank gets the zeros it allocated."""
    x = _to_np(tensor)
    out = comm.gather(x)
    if out is not None:
        return out
    return [np.zeros_like(x) for _ in range(comm.world)]


def broadcast(comm, tensor, src: int = 0):
    x = _to_np(tensor).copy()
    return comm.broadcast(x, src=src)


def sync_params(comm, params: Sequence, wire: str = "exact") -> list:
    """Reference distributed.py:163-170: broadcast each tensor from 0.

    ``wire="quant"``: rank 0 block-quantizes each FLOAT32 tensor
    (:mod:`.wire` format) and broadcasts the int8+scales frame instead of
    full-width bytes (~4x less traffic for big param syncs). EVERY rank —
    rank 0 included — adopts the dequantized value, so params stay
    bit-identical across ranks (the only guarantee sync_params makes;
    the absolute values move by at most one quantization step). All
    other dtypes (integers, f16, f64) always broadcast exact."""
    _check_wire(wire)
    out = []
    for p in params:
        x = _to_np(p)
        # quantize f32 only: f64 would silently lose precision through
        # the f32 cast beyond the one-step bound, and f16 is already
        # half-width — both broadcast exact, as do integers
        if wire == "quant" and x.dtype == np.float32 and comm.world > 1:
            n = x.size
            nb = _wire.num_blocks(n)
            frame = np.empty(_wire.quant_wire_bytes(n), np.uint8)
            if comm.rank == 0:
                q, scales = _wire.quantize_blocks(
                    x.astype(np.float32).ravel())
                frame[:4 * nb] = scales.view(np.uint8)
                frame[4 * nb:] = q.view(np.uint8)
            comm.broadcast(frame, src=0)
            scales = frame[:4 * nb].view(np.float32)
            q = frame[4 * nb:].view(np.int8)
            out.append(_wire.dequantize_blocks(q, scales)
                       .reshape(x.shape).astype(x.dtype))
        else:
            out.append(comm.broadcast(x.copy(), src=0))
    return out


def barrier(comm):
    comm.barrier()
