"""In-step collective primitives for use under ``shard_map``/``jit``.

These are the compiled-program counterparts of the eager helpers in
:mod:`distributed_pytorch_tpu.comm.collectives`: inside a sharded region each
device holds its own block and the primitive names the mesh axis to
communicate over. They lower directly to XLA HLO collectives (all-reduce,
all-gather, collective-permute, all-to-all, reduce-scatter) riding ICI — the
NCCL replacement called for by SURVEY.md §2.3 row 1 — and ARE the transport
layer of the parallel engines: :mod:`..parallel.data_parallel` averages
grads through :func:`pmean`, :mod:`..parallel.sequence` rotates k/v blocks
through :func:`ring_shift`, :mod:`..parallel.pipeline` moves activations
between stages through :func:`line_shift`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def psum(x, axis_name: str):
    """All-reduce sum over a mesh axis (HLO ``all-reduce``)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    """All-reduce mean over a mesh axis — DDP's gradient averaging
    (reference ``distributed.py:112-115``, C++ reducer semantics)."""
    return lax.pmean(x, axis_name)

def pmax(x, axis_name: str):
    return lax.pmax(x, axis_name)


def pmin(x, axis_name: str):
    return lax.pmin(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = False):
    """All-gather over a mesh axis (HLO ``all-gather``)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, scatter_axis: int = 0):
    """Reduce-scatter over a mesh axis (HLO ``reduce-scatter``) — the
    bandwidth-optimal half of an all-reduce; used by ZeRO-style sharded
    optimizers."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True)


def ppermute(x, axis_name: str, perm):
    """Point-to-point ring/permute (HLO ``collective-permute``) — the
    transport under ring attention (:mod:`..parallel.sequence`)."""
    return lax.ppermute(x, axis_name, perm=perm)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate each device's block ``shift`` hops around the mesh-axis ring
    — the k/v transport under ring attention (:mod:`..parallel.sequence`)."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def line_shift(x, axis_name: str, shift: int = 1):
    """Shift blocks ``shift`` hops along a mesh axis WITHOUT wraparound;
    devices with no sender receive zeros (``collective-permute``
    semantics). The stage-to-stage transport under pipeline parallelism
    (:mod:`..parallel.pipeline`): activations move +1, gradients -1, and
    the zero fill feeds the warmup/drain bubbles."""
    n = lax.psum(1, axis_name)
    if shift >= 0:
        perm = [(i, i + shift) for i in range(n - shift)]
    else:
        perm = [(i, i + shift) for i in range(-shift, n)]
    return lax.ppermute(x, axis_name, perm=perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int, tiled: bool = True):
    """All-to-all (HLO ``all-to-all``) — the transport for Ulysses-style
    sequence parallelism and MoE token dispatch."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def axis_index(axis_name: str):
    """This device's position along a mesh axis (the in-step 'rank')."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    """Size of a mesh axis (the in-step 'world size')."""
    return lax.psum(1, axis_name)
