"""In-step collective primitives for use under ``shard_map``/``jit``.

These are the compiled-program counterparts of the eager helpers in
:mod:`distributed_pytorch_tpu.comm.collectives`: inside a sharded region each
device holds its own block and the primitive names the mesh axis to
communicate over. They lower directly to XLA HLO collectives (all-reduce,
all-gather, collective-permute, all-to-all, reduce-scatter) riding ICI — the
NCCL replacement called for by SURVEY.md §2.3 row 1 — and ARE the transport
layer of the parallel engines: :mod:`..parallel.data_parallel` averages
grads through :func:`pmean`, :mod:`..parallel.sequence` rotates k/v blocks
through :func:`ring_shift`, :mod:`..parallel.pipeline` moves activations
between stages through :func:`line_shift`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def psum(x, axis_name: str):
    """All-reduce sum over a mesh axis (HLO ``all-reduce``)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    """All-reduce mean over a mesh axis — DDP's gradient averaging
    (reference ``distributed.py:112-115``, C++ reducer semantics)."""
    return lax.pmean(x, axis_name)

def pmax(x, axis_name: str):
    return lax.pmax(x, axis_name)


def pmin(x, axis_name: str):
    return lax.pmin(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = False):
    """All-gather over a mesh axis (HLO ``all-gather``)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, scatter_axis: int = 0):
    """Reduce-scatter over a mesh axis (HLO ``reduce-scatter``) — the
    bandwidth-optimal half of an all-reduce; used by ZeRO-style sharded
    optimizers."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True)


def ppermute(x, axis_name: str, perm):
    """Point-to-point ring/permute (HLO ``collective-permute``) — the
    transport under ring attention (:mod:`..parallel.sequence`)."""
    return lax.ppermute(x, axis_name, perm=perm)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate each device's block ``shift`` hops around the mesh-axis ring
    — the k/v transport under ring attention (:mod:`..parallel.sequence`)."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def line_shift(x, axis_name: str, shift: int = 1):
    """Shift blocks ``shift`` hops along a mesh axis WITHOUT wraparound;
    devices with no sender receive zeros (``collective-permute``
    semantics). The stage-to-stage transport under pipeline parallelism
    (:mod:`..parallel.pipeline`): activations move +1, gradients -1, and
    the zero fill feeds the warmup/drain bubbles."""
    n = lax.psum(1, axis_name)
    if shift >= 0:
        perm = [(i, i + shift) for i in range(n - shift)]
    else:
        perm = [(i, i + shift) for i in range(-shift, n)]
    return lax.ppermute(x, axis_name, perm=perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int, tiled: bool = True):
    """All-to-all (HLO ``all-to-all``) — the transport for Ulysses-style
    sequence parallelism and MoE token dispatch."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


# quantization-block width shared with the host front door's wire format
# (comm/wire.py is the single source of truth) — exported so bucketing
# callers (parallel/data_parallel._reduce_grads) can pad each leaf to a
# block multiple and keep scale blocks from spanning leaves
from .wire import (QUANT_BLOCK, quant_leg_wire_bytes,  # noqa: E402,F401
                   quant_ring_allreduce_wire_bytes, quant_wire_bytes,
                   ring_allreduce_wire_bytes)


def quantized_pmean_wire_bytes(n: int, world: int,
                               block: int = QUANT_BLOCK) -> int:
    """Total wire bytes (all devices, both legs) of one
    :func:`quantized_pmean` over an n-element bucket: in each leg
    (all-to-all, then all-gather) every device ships world-1 quantized
    chunks of the zero-padded bucket's 1/world rows."""
    if world <= 1:
        return 0
    padded = n + ((-n) % (world * block))
    chunk = quant_wire_bytes(padded // world, block)
    return 2 * world * (world - 1) * chunk


def quantized_pmean(x, axis_name: str, *, block: int = QUANT_BLOCK,
                    bits: int = 8):
    """Bandwidth-compressed (int8) mean over a mesh axis — LOSSY.

    ``bits`` selects the wire grid (8 or 4 — comm/wire.py's widths):
    the q4 grid quantizes to 15 levels per block, the compiled twin of
    the host ring's nibble-packed wire, chosen per bucket by the
    adaptive policy in ``parallel.make_train_step``.

    The EQuARX recipe (arxiv 2506.17615) mapped onto XLA collectives:
    each device symmetrically int8-quantizes its 1/n chunk-row of the
    flattened tensor (one f32 scale per ``block`` elements, so a big
    bucket of concatenated gradients keeps LOCAL dynamic range — tiny
    layernorm grads are not scaled by an embedding's max), exchanges
    quantized chunks with ``all-to-all``, dequantizes and reduces ITS
    chunk in f32, requantizes the partial, and ``all-gather``s the
    result — both wire legs move int8 bytes (+4 bytes per block for the
    scale), ~4x less traffic than an f32 all-reduce (2x vs bf16). Error
    is bounded by one quantization step per leg:
    |err| <= blockmax|x|/254 + blockmax|mean|/254 per element.

    Use for DATA-PARALLEL GRADIENTS on bandwidth-bound interconnects
    (DCN hops, very large meshes) where SGD noise dwarfs the
    quantization error — ``make_train_step(grad_reduce="int8")``
    buckets the whole gradient tree through one call. Keep exact
    :func:`pmean` for losses/metrics and small meshes.
    """
    n = int(lax.psum(1, axis_name))
    if n == 1:
        return x
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).ravel()
    size = flat.shape[0]
    pad = (-size) % (n * block)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    nb = flat.shape[0] // (n * block)

    # the shared block codec (ops/quant.py == comm/wire.py rule: clip to
    # [-levels,levels] — round(amax/scale) can land past the top level
    # and wrap — plus the integer-exact snap for small integer payloads)
    from ..ops.quant import dequantize_grad_blocks, quantize_grad_blocks

    q, scale = quantize_grad_blocks(flat.reshape(n, nb, block), bits)
    # row i of the result = device i's row <my_index>: every device
    # ends up holding all n quantized versions of ITS chunk
    rq = all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    rs = all_to_all(scale, axis_name, split_axis=0, concat_axis=0)
    partial = jnp.sum(dequantize_grad_blocks(rq, rs), axis=0) / n  # (nb,blk)
    q2, scale2 = quantize_grad_blocks(partial, bits)
    gq = all_gather(q2[None], axis_name, axis=0, tiled=True)
    gs = all_gather(scale2[None], axis_name, axis=0, tiled=True)
    out = dequantize_grad_blocks(gq, gs).ravel()
    if pad:
        out = out[:size]
    return out.reshape(shape).astype(dtype)


def quantized_reduce_scatter(x, axis_name: str, *, block: int = QUANT_BLOCK):
    """Bandwidth-compressed (int8) reduce-scatter SUM over a mesh axis —
    LOSSY; the scatter half of :func:`quantized_pmean`.

    ``x``: a FLAT f32 vector whose length is a multiple of
    ``world * block`` (the :mod:`..optim.sharded` layout guarantees
    this). Each device symmetrically int8-quantizes its world
    chunk-rows (one f32 scale per ``block`` elements), exchanges them
    with ``all-to-all``, and dequantize-accumulates ITS chunk in f32.
    Returns this device's ``(len(x)/world,)`` chunk of the SUM (callers
    divide by world for a mean). One quantization step of error per
    contribution; int8 + scales on the wire instead of f32."""
    n = int(lax.psum(1, axis_name))
    if n == 1:
        return x
    from ..ops.quant import dequantize_grad_blocks, quantize_grad_blocks

    size = x.shape[0]
    if size % (n * block):
        raise ValueError(
            f"quantized_reduce_scatter needs len(x) divisible by "
            f"world*block = {n * block}, got {size}")
    nb = size // (n * block)
    q, scale = quantize_grad_blocks(x.astype(jnp.float32)
                                    .reshape(n, nb, block))
    rq = all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    rs = all_to_all(scale, axis_name, split_axis=0, concat_axis=0)
    return jnp.sum(dequantize_grad_blocks(rq, rs), axis=0).ravel()


def quantized_all_gather(x, axis_name: str, *, block: int = QUANT_BLOCK):
    """Bandwidth-compressed (int8) all-gather over a mesh axis — LOSSY
    but BIT-IDENTICAL on every device: each device quantizes its flat
    chunk once, the int8 codes + scales are all-gathered, and every
    device (the owner included) decodes the same bytes — so replicated
    values rebuilt from sharded updates cannot drift across devices.
    ``x``: a flat f32 chunk whose length is a multiple of ``block``.
    Returns the ``(world * len(x),)`` concatenation in axis order.
    A 1-device axis is a NO-OP (exact, no grid snap) — the same
    contract as ``dpx_allgather_q8`` and the numpy leg spec."""
    n = int(lax.psum(1, axis_name))
    if x.shape[0] % block:
        raise ValueError(
            f"quantized_all_gather needs len(x) divisible by block = "
            f"{block}, got {x.shape[0]}")
    if n == 1:
        return x.astype(jnp.float32)
    from ..ops.quant import dequantize_grad_blocks, quantize_grad_blocks

    q, scale = quantize_grad_blocks(x.astype(jnp.float32)
                                    .reshape(-1, block))
    gq = all_gather(q[None], axis_name, axis=0, tiled=True)
    gs = all_gather(scale[None], axis_name, axis=0, tiled=True)
    return dequantize_grad_blocks(gq, gs).ravel()


def axis_index(axis_name: str):
    """This device's position along a mesh axis (the in-step 'rank')."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    """Size of a mesh axis (the in-step 'world size')."""
    return lax.psum(1, axis_name)
