"""Runtime collective sanitizer — dpxverify's dynamic half.

Armed by ``DPX_COMM_SANITIZE=1``: before every host-group collective
runs its native payload, all ranks exchange a fixed-size fingerprint of
what they are ABOUT to issue and compare. A rank that diverged — took a
rank-dependent branch, swallowed an exception past a barrier, had a
fault injected — raises a typed, rank-attributed
:class:`CollectiveMismatch` within ONE fingerprint exchange, instead of
leaving its peers to hang for a full ``DPX_COMM_TIMEOUT_MS`` deadline
with no attribution. The flight recorder (obs/trace.py) and the
rolling schedule digest (analysis/schedule.py) both dump on the way
out, exactly like every other typed comm failure.

Wire format (``_RECORD`` struct, little-endian, 88 bytes — fixed size
so MISMATCHED ranks still complete the exchange):

====== ===== =====================================================
offset bytes field
====== ===== =====================================================
0      2     magic ``0xD9F1``
2      1     version (1)
3      1     pad
4      8     seq — per-comm monotone exchange counter (u64)
12     8     payload nbytes (u64)
20     4     CRC32 of the full ``file:line`` call site (u32)
24     12    op name (NUL-padded ASCII)
36     8     dtype name (NUL-padded ASCII, may be empty)
44     44    call site tail, ``file.py:line`` (NUL-padded)
====== ===== =====================================================

The exchange itself is a rooted gather of the 88-byte record to rank 0
followed by a broadcast of the full ``world x 88`` matrix — raw
``dpx_gather``/``dpx_broadcast`` native calls that bypass
``HostComm._pre_op`` (no recursion, no schedule/fault side effects).
Every rank then compares locally and raises its OWN attributed error,
so supervisors see the mismatch from both sides.

Divergence is keyed on (seq, op, dtype, nbytes); the call-site fields
ride along for attribution only (two ranks may legitimately reach the
same collective from different lines).

Unarmed (the default), the entire feature is one ``is None`` attribute
test per collective in ``HostComm._pre_op`` — no fingerprinting, no
extra traffic, no measurable overhead.
"""

from __future__ import annotations

import ctypes
import os
import struct
import sys
import zlib

from ..runtime.native import CommError

_MAGIC = 0xD9F1
_VERSION = 1
_FMT = "<HBxQQI12s8s44s"
RECORD_SIZE = struct.calcsize(_FMT)   # 88

_PKG_SKIP_DIRS = tuple(
    os.sep + os.path.join("distributed_pytorch_tpu", d) + os.sep
    for d in ("comm", "runtime"))


class CollectiveMismatch(CommError):
    """Two ranks issued DIFFERENT collectives at the same sequence
    point — the cross-rank divergence that would otherwise surface as
    an unattributed ``CommTimeout`` hang. Carries both sides: this
    rank's op/call site and the diverging peer's."""

    def __init__(self, msg: str, *, seq: int = -1, peer_op: str = "",
                 call_site: str = "", peer_call_site: str = "", **kw):
        super().__init__(msg, **kw)
        self.seq = seq
        self.peer_op = peer_op
        self.call_site = call_site
        self.peer_call_site = peer_call_site


def _call_site() -> str:
    """First stack frame OUTSIDE the comm/runtime plumbing — the line
    that asked for the collective (falls back to the innermost frame
    for bare-comm callers like the tests)."""
    frame = sys._getframe(1)
    best = frame
    while frame is not None:
        fname = frame.f_code.co_filename
        if not any(d in fname for d in _PKG_SKIP_DIRS):
            best = frame
            break
        best = frame
        frame = frame.f_back
    return f"{os.path.basename(best.f_code.co_filename)}:{best.f_lineno}"


class CollectiveSanitizer:
    """Per-:class:`HostComm` fingerprint exchanger (one per comm —
    hierarchical sub-groups arm their own against their own world)."""

    def __init__(self, comm):
        self._comm = comm
        self._seq = 0

    # -- wire --------------------------------------------------------------

    def _pack(self, op: str, dtype: str, size: int, site: str) -> bytes:
        return struct.pack(
            _FMT, _MAGIC, _VERSION, self._seq, int(size),
            zlib.crc32(site.encode()) & 0xFFFFFFFF,
            op.encode()[:12], dtype.encode()[:8],
            site.encode()[-44:])

    @staticmethod
    def _unpack(raw: bytes) -> dict:
        magic, ver, seq, nbytes, crc, op, dtype, site = struct.unpack(
            _FMT, raw)
        return {"magic": magic, "version": ver, "seq": seq,
                "nbytes": nbytes, "site_crc": crc,
                "op": op.rstrip(b"\0").decode(errors="replace"),
                "dtype": dtype.rstrip(b"\0").decode(errors="replace"),
                "site": site.rstrip(b"\0").decode(errors="replace")}

    # -- the exchange ------------------------------------------------------

    def check(self, op: str, dtype: str = "", size: int = 0) -> None:
        """Fingerprint-exchange-and-compare for the collective this comm
        is about to issue. Raises :class:`CollectiveMismatch` when any
        peer's fingerprint diverges; returns silently when all match."""
        comm = self._comm
        if comm.world <= 1:
            return
        self._seq += 1
        site = _call_site()
        rec = self._pack(op, dtype, size, site)
        lib, h, world = comm._lib, comm._h, comm.world
        matrix = ctypes.create_string_buffer(RECORD_SIZE * world)
        if comm.rank == 0:
            rc = lib.dpx_gather(h, rec, RECORD_SIZE, matrix)
        else:
            rc = lib.dpx_gather(h, rec, RECORD_SIZE, None)
        if rc == 0:
            rc = lib.dpx_broadcast(h, matrix, RECORD_SIZE * world, 0)
        if rc != 0:
            # transport-level failure of the exchange itself: the
            # ordinary typed path (flush + flight recorder + raise)
            comm._check(rc, f"sanitize:{op}")
        mine = self._unpack(rec)
        for peer in range(world):
            if peer == comm.rank:
                continue
            raw = matrix.raw[peer * RECORD_SIZE:(peer + 1) * RECORD_SIZE]
            theirs = self._unpack(raw)
            if theirs["magic"] != _MAGIC:
                self._raise(op, mine, peer, None, site)
            if (theirs["seq"] != mine["seq"]
                    or theirs["op"] != mine["op"]
                    or theirs["dtype"] != mine["dtype"]
                    or theirs["nbytes"] != mine["nbytes"]):
                self._raise(op, mine, peer, theirs, site)

    def _raise(self, op: str, mine: dict, peer: int,
               theirs: "dict | None", site: str) -> None:
        comm = self._comm
        comm.schedule.flush(op=f"sanitize:{op}")
        if theirs is None:
            msg = (f"collective sanitizer: rank {peer} sent a garbled "
                   f"fingerprint while rank {comm.rank} issued "
                   f"{op!r} seq {mine['seq']} at {site}")
            exc = CollectiveMismatch(msg, op=op, rank=comm.rank,
                                     peer=peer, seq=mine["seq"],
                                     call_site=site)
        else:
            msg = (f"collective divergence at seq {mine['seq']}: "
                   f"rank {comm.rank} issued {mine['op']!r} "
                   f"(dtype={mine['dtype'] or '-'}, "
                   f"nbytes={mine['nbytes']}) at {site} "
                   f"but rank {peer} issued {theirs['op']!r} "
                   f"(dtype={theirs['dtype'] or '-'}, "
                   f"nbytes={theirs['nbytes']}, seq {theirs['seq']}) "
                   f"at {theirs['site']} — every rank must issue the "
                   "same collective sequence")
            exc = CollectiveMismatch(
                msg, op=op, rank=comm.rank, peer=peer, seq=mine["seq"],
                peer_op=theirs["op"], call_site=site,
                peer_call_site=theirs["site"])
        # flight recorder rides out with the typed error, same as every
        # native failure path (HostComm._check)
        comm._dpxtrace.on_typed_failure(exc)
        raise exc
