"""Quantized collective wire format — the single source of truth.

Both comm front doors speak this format when a caller opts into
``wire="quant"`` / ``grad_reduce="quant"``:

* the native TCP ring (``native/dpxhost.cpp:dpx_allreduce_q8`` and the
  width-parameterized ``dpx_*_qn`` family) encodes and decodes it in
  C++ on the host-process front door, and
* the SPMD front door's :func:`..comm.primitives.quantized_pmean` uses
  the same block rule in jnp (via :mod:`..ops.quant`).

**Block codec** (EQuARX-style, arxiv 2506.17615): the flat f32 payload is
cut into blocks of :data:`QUANT_BLOCK` elements (last block ragged). Per
block, with ``levels`` = 127 for the 8-bit wire and 7 for the 4-bit
wire: ``amax = max|v|``; ``scale = 1`` if ``amax == 0``; ``scale = 1``
if every value is an integer with ``amax <= levels`` (small-magnitude
integer payloads — step counters, one-hot count buckets — transfer
EXACTLY); else ``scale = amax/levels``. ``q = clip(rint(v *
(levels/amax)), -levels, levels)`` (quantization multiplies by the f32
inverse — the vectorizable form all three implementations share). One
f32 scale per block keeps LOCAL dynamic range: a tiny layernorm grad
never shares a scale with an embedding grad.

**Width selection**: the 8-bit wire is the default. The 4-bit wire packs
two sign-extended nibbles per byte (:func:`pack_nibbles`) — ~7.9x less
traffic than f32 — at ~18x the per-hop rounding error of q8, so it is
chosen PER BUCKET from observed dynamic range: :class:`WidthChooser`
computes the fraction of blocks whose ``amax/rms`` exceeds
:data:`DYNRANGE_THRESH` on the (bit-identical-across-ranks) REDUCED
bucket of the previous step, and flips the width only after
:data:`WIDTH_HYSTERESIS` consecutive identical verdicts — so the
compiled-program count stays bounded and all ranks always agree
(deciding from per-rank raw gradients would diverge).

**Chunk framing**: a contiguous run of blocks is framed as
``[f32 scales x nblocks][payload]`` where the payload is one int8 per
element (q8) or one packed nibble pair per two elements (q4) —
scatter-gather friendly (two plain memcpys each side, no per-chunk
header; both peers derive every length from ``(n, block, chunk_blocks,
bits, step)``). :data:`QUANT_BLOCK` is even, so every chunk boundary
falls on an even element offset and per-chunk nibble packing equals the
packing of the whole span.

**Ring schedule** (:func:`simulate_quant_ring` is the executable spec;
the C++ implements it chunk-pipelined): reduce-scatter leg — each hop
quantizes the f32 partial of the outgoing segment, the receiver
dequantize-accumulates in f32; all-gather leg — the segment owner
quantizes its reduced segment ONCE, replaces its own copy with the
dequantized value, and the quantized bytes are forwarded UNCHANGED
around the ring, so every rank decodes identical bytes and the result
is bit-identical on all ranks.

Everything here is numpy-only (no jax import): the torch front door and
spawned rank workers use it without touching an XLA backend, and the
numpy sim is bit-exact against the C++ (same IEEE f32 ops in the same
order), which the native parity test leans on.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

#: Quantization-block width (elements per f32 scale). Exported through
#: :mod:`.primitives` for bucketing callers.
QUANT_BLOCK = 1024

#: Blocks per wire chunk on the native ring (256 KiB of int8 payload at
#: the default block): small enough that peers' compute phases overlap
#: in-flight socket transfer, large enough that the extra lockstep
#: rounds don't dominate on small oversubscribed hosts (measured: on a
#: 2-core/8-rank loopback mesh, 64 KiB chunks cost ~25% of the ring's
#: throughput in pure scheduling; 256 KiB recovers it while still
#: splitting every >256 KiB segment for overlap).
QUANT_CHUNK_BLOCKS = 256

SCALE_BYTES = 4  # one f32 scale per block

#: Wire widths the quantized collectives speak (bits per element).
WIRE_WIDTHS = (8, 4)


def quant_levels(bits: int) -> int:
    """Symmetric integer levels of a wire width: |q| <= levels."""
    if bits == 8:
        return 127
    if bits == 4:
        return 7
    raise ValueError(f"wire width must be one of {WIRE_WIDTHS}, got {bits}")


def payload_bytes(elems: int, bits: int = 8) -> int:
    """Wire payload bytes of ``elems`` quantized values (excluding
    scales): one byte per element at q8, two packed nibbles per byte at
    q4 (odd tails pad a zero nibble)."""
    quant_levels(bits)
    return elems if bits == 8 else (elems + 1) // 2


# ---------------------------------------------------------------------------
# block codec (numpy reference; C++ and jnp mirror it)
# ---------------------------------------------------------------------------


def _block_codec(x: np.ndarray, block: int = QUANT_BLOCK,
                 bits: int = 8) -> Tuple[np.ndarray, np.ndarray]:
    """Per-block (dequant scales, quant inverses) for a flat f32 array.

    Quantization MULTIPLIES by the f32 inverse ``levels/amax`` rather
    than dividing by ``amax/levels`` — the native codec does the same (a
    vectorized multiply), and grids must agree bit for bit. Fully
    vectorized: this runs per training step on the error-feedback path,
    so a per-block Python loop would sit on the hot path the quantized
    ring exists to speed up (zero-padding the ragged tail changes
    neither amax nor the all-integer test)."""
    levels = np.float32(quant_levels(bits))
    x = np.ascontiguousarray(x, dtype=np.float32).ravel()
    nb = num_blocks(x.size, block)
    pad = nb * block - x.size
    v = (np.pad(x, (0, pad)) if pad else x).reshape(nb, block)
    amax = np.abs(v).max(axis=1)
    # integer-exact snap: small-magnitude integer payloads round-trip
    # exactly (scale 1, |q| <= levels)
    unit = (amax == 0.0) | ((amax <= levels)
                            & (v == np.rint(v)).all(axis=1))
    safe = np.where(unit, np.float32(1.0), amax)  # no 0-div warnings
    one = np.float32(1.0)
    scales = np.where(unit, one, safe / levels)
    invs = np.where(unit, one, levels / safe)
    return scales.astype(np.float32), invs.astype(np.float32)


def block_scales(x: np.ndarray, block: int = QUANT_BLOCK,
                 bits: int = 8) -> np.ndarray:
    """Per-block dequantization scales for a flat f32 array."""
    return _block_codec(x, block, bits)[0]


def quantize_blocks(x: np.ndarray, block: int = QUANT_BLOCK,
                    bits: int = 8) -> Tuple[np.ndarray, np.ndarray]:
    """Flat f32 -> (int8 q of same length, f32 scales per block).

    ``q`` is UNPACKED (one int8 per element, |q| <= levels) regardless
    of ``bits`` — the in-memory form the simulations accumulate on;
    :func:`pack_nibbles` produces the q4 wire bytes."""
    x = np.ascontiguousarray(x, dtype=np.float32).ravel()
    levels = quant_levels(bits)
    scales, invs = _block_codec(x, block, bits)
    per_elem = np.repeat(invs, block)[:x.size]
    q = np.clip(np.rint(x * per_elem), -levels, levels).astype(np.int8)
    return q, scales


def pack_nibbles(q: np.ndarray) -> np.ndarray:
    """int8 values in [-8, 7] -> packed uint8 wire bytes (two
    two's-complement nibbles per byte, low nibble first; an odd tail
    leaves the final high nibble zero). The q4 wire payload form —
    ``native/dpxhost.cpp`` packs identically."""
    q = np.ascontiguousarray(q, dtype=np.int8)
    n = q.size
    u = (q.astype(np.uint8) & 0x0F)
    if n % 2:
        u = np.append(u, np.uint8(0))
    return (u[0::2] | (u[1::2] << 4)).astype(np.uint8)


def unpack_nibbles(packed: np.ndarray, n: int) -> np.ndarray:
    """Inverse of :func:`pack_nibbles`: ``n`` sign-extended int8 values."""
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    lo = packed & 0x0F
    hi = packed >> 4
    u = np.empty(packed.size * 2, np.uint8)
    u[0::2] = lo
    u[1::2] = hi
    return ((u[:n] ^ 8).astype(np.int8) - np.int8(8))


def dequantize_blocks(q: np.ndarray, scales: np.ndarray,
                      block: int = QUANT_BLOCK) -> np.ndarray:
    """(int8 q, f32 scales) -> f32 values (``q * scale`` per block)."""
    per_elem = np.repeat(scales.astype(np.float32), block)[:q.size]
    return q.astype(np.float32) * per_elem


# ---------------------------------------------------------------------------
# segment grid: how the ring splits n elements across world ranks
# ---------------------------------------------------------------------------


def num_blocks(n: int, block: int = QUANT_BLOCK) -> int:
    return (n + block - 1) // block


def segment_blocks(n: int, world: int,
                   block: int = QUANT_BLOCK) -> List[Tuple[int, int]]:
    """Block-aligned ring segments: ``[(start_block, n_blocks)] * world``.

    Blocks are distributed as evenly as possible (first ``rem`` segments
    get one extra); a segment never splits a block, so no quantization
    scale ever spans two ranks' segments.
    """
    nb = num_blocks(n, block)
    base, rem = divmod(nb, world)
    out, start = [], 0
    for s in range(world):
        cnt = base + (1 if s < rem else 0)
        out.append((start, cnt))
        start += cnt
    return out


def block_span_elems(start_block: int, nblocks: int, n: int,
                     block: int = QUANT_BLOCK) -> Tuple[int, int]:
    """(element offset, element count) covered by a run of blocks."""
    lo = start_block * block
    hi = min((start_block + nblocks) * block, n)
    return lo, max(hi - lo, 0)


def span_wire_bytes(start_block: int, nblocks: int, n: int,
                    block: int = QUANT_BLOCK, bits: int = 8) -> int:
    """Wire bytes of a framed run of blocks: scales + quantized payload."""
    _, elems = block_span_elems(start_block, nblocks, n, block)
    return SCALE_BYTES * nblocks + payload_bytes(elems, bits)


# ---------------------------------------------------------------------------
# byte accounting (what the bench and tests assert on)
# ---------------------------------------------------------------------------


def quant_wire_bytes(n: int, block: int = QUANT_BLOCK,
                     bits: int = 8) -> int:
    """Bytes for ONE quantized copy of an n-element payload."""
    return payload_bytes(n, bits) + SCALE_BYTES * num_blocks(n, block)


def ring_allreduce_wire_bytes(n: int, world: int, elem_size: int = 4) -> int:
    """Total wire bytes (all ranks, both legs) of the full-width ring
    all-reduce (``native/dpxhost.cpp:ring_allreduce``): 2*(world-1) hops
    per segment, segments of ceil(n/world) elements (last ragged)."""
    if world <= 1:
        return 0
    chunk = (n + world - 1) // world
    total_seg_elems = 0
    for s in range(world):
        lo = chunk * s
        total_seg_elems += max(min(lo + chunk, n) - lo, 0)
    return 2 * (world - 1) * total_seg_elems * elem_size


def quant_ring_allreduce_wire_bytes(n: int, world: int,
                                    block: int = QUANT_BLOCK,
                                    bits: int = 8) -> int:
    """Total wire bytes (all ranks, both legs) of the quantized ring
    (``dpx_allreduce_q8`` / ``dpx_allreduce_qn``): each segment travels
    world-1 hops per leg in framed payload+scales form."""
    if world <= 1:
        return 0
    total = 0
    for start, cnt in segment_blocks(n, world, block):
        total += 2 * (world - 1) * span_wire_bytes(start, cnt, n, block,
                                                  bits)
    return total


def quant_leg_wire_bytes(n: int, world: int, block: int = QUANT_BLOCK,
                         bits: int = 8) -> int:
    """Total wire bytes (all ranks) of ONE leg of the quantized ring —
    ``dpx_reduce_scatter_q8`` or ``dpx_allgather_q8`` each move exactly
    half of :func:`quant_ring_allreduce_wire_bytes` (every segment
    travels world-1 hops once per leg)."""
    if world <= 1:
        return 0
    total = 0
    for start, cnt in segment_blocks(n, world, block):
        total += (world - 1) * span_wire_bytes(start, cnt, n, block, bits)
    return total


def handoff_page_wire_bytes(page_elems: int, n_tensors: int,
                            block: int = QUANT_BLOCK,
                            bits: Optional[int] = 8) -> int:
    """Wire bytes of a paged KV handoff's quantizable section
    (``serve/disagg/``): ``n_tensors`` page tensors of ``page_elems``
    f32 values each, every page framed INDEPENDENTLY (its scales are
    local — "per-page scales" — so a hot page never shares dynamic
    range with a cold one). ``bits=None`` is the exact f32 wire (4
    bytes/element, no scales). This is the number the handoff books
    into CommStats, and the CI gate asserts the booked bytes equal it
    exactly (tier1.yml serve smoke)."""
    if bits is None:
        return n_tensors * page_elems * 4
    return n_tensors * quant_wire_bytes(page_elems, block, bits)


def ring_owned_span(n: int, world: int, rank: int,
                    block: int = QUANT_BLOCK) -> Tuple[int, int]:
    """(element offset, element count) of the segment rank ``rank`` OWNS
    after the ring reduce-scatter leg — segment ``(rank+1) % world`` of
    the block-aligned grid (the same ownership convention as
    ``native/dpxhost.cpp``'s ring schedule)."""
    seg = (rank + 1) % world
    start, cnt = segment_blocks(n, world, block)[seg]
    return block_span_elems(start, cnt, n, block)


# ---------------------------------------------------------------------------
# executable spec: the quantized ring, simulated in numpy
# ---------------------------------------------------------------------------


def _seg_spans(n: int, w: int, block: int) -> List[slice]:
    """Per-segment element slices, computed ONCE per simulation (the
    hop loops index it O(world^2) times)."""
    out = []
    for start, cnt in segment_blocks(n, w, block):
        lo, elems = block_span_elems(start, cnt, n, block)
        out.append(slice(lo, lo + elems))
    return out


def simulate_quant_reduce_scatter(per_rank: Sequence[np.ndarray],
                                  block: int = QUANT_BLOCK,
                                  bits: int = 8
                                  ) -> Tuple[List[np.ndarray], int]:
    """The reduce-scatter LEG of the quantized ring, simulated.

    ``per_rank``: one equal-shape array per rank. Returns ``(buffers,
    wire_bytes)`` where ``buffers[r]`` is rank r's FLAT working buffer
    after the leg: the span :func:`ring_owned_span` ``(n, w, r)`` holds
    the full (lossily accumulated) SUM of that segment; every other span
    holds a partial accumulation (undefined to callers — exactly the
    ``dpx_reduce_scatter_q8`` contract, bit for bit)."""
    w = len(per_rank)
    data = [np.ascontiguousarray(x, dtype=np.float32).ravel().copy()
            for x in per_rank]
    n = data[0].size
    if w == 1:
        return data, 0
    spans = _seg_spans(n, w, block)
    bytes_moved = 0
    # quantize the outgoing f32 partial each hop, receiver dequantize-
    # accumulates (all sends of a step happen "at once": quantize from
    # the pre-step snapshot, like the real ring)
    for step in range(w - 1):
        sends = {}
        for r in range(w):
            send_seg = (r - step) % w
            q, s = quantize_blocks(data[r][spans[send_seg]], block, bits)
            sends[r] = (q, s)
            bytes_moved += payload_bytes(q.size, bits) \
                + SCALE_BYTES * s.size
        for r in range(w):
            recv_seg = (r - step - 1) % w
            q, s = sends[(r - 1) % w]
            data[r][spans[recv_seg]] += dequantize_blocks(q, s, block)
    return data, bytes_moved


def simulate_quant_allgather(per_rank: Sequence[np.ndarray],
                             block: int = QUANT_BLOCK,
                             bits: int = 8
                             ) -> Tuple[List[np.ndarray], int]:
    """The byte-forwarding all-gather LEG of the quantized ring,
    simulated. Rank r contributes the span :func:`ring_owned_span`
    ``(n, w, r)`` of its flat buffer; afterwards every rank's buffer is
    BIT-IDENTICAL (each span is the dequantized grid of its owner's
    bytes, owner included). Mirrors ``dpx_allgather_q8`` bit for bit."""
    w = len(per_rank)
    data = [np.ascontiguousarray(x, dtype=np.float32).ravel().copy()
            for x in per_rank]
    n = data[0].size
    if w == 1:
        return data, 0
    spans = _seg_spans(n, w, block)
    bytes_moved = 0
    wires = {}
    for r in range(w):
        own = (r + 1) % w
        q, s = quantize_blocks(data[r][spans[own]], block, bits)
        wires[own] = (q, s)
        data[r][spans[own]] = dequantize_blocks(q, s, block)
    for step in range(w - 1):
        for r in range(w):
            recv_seg = (r - step) % w
            q, s = wires[recv_seg]
            data[r][spans[recv_seg]] = dequantize_blocks(q, s, block)
            bytes_moved += payload_bytes(q.size, bits) \
                + SCALE_BYTES * s.size
    return data, bytes_moved


def simulate_quant_ring(per_rank: Sequence[np.ndarray],
                        block: int = QUANT_BLOCK,
                        bits: int = 8
                        ) -> Tuple[List[np.ndarray], int]:
    """Run the quantized ring schedule on in-memory "ranks".

    ``per_rank``: one equal-shape array per rank. Returns ``(results,
    wire_bytes)`` where ``results[r]`` is rank r's reduced SUM (callers
    divide by world for a mean) and ``wire_bytes`` is the total bytes
    that would cross the wire. The arithmetic (op kind and order) is
    bit-identical to ``dpx_allreduce_q8`` (``dpx_allreduce_qn`` at
    ``bits=4``), so this doubles as the parity oracle for the native
    path — and all results are bit-identical across ranks by
    construction of the byte-forwarding all-gather leg. Composed from
    the two standalone leg simulations, exactly like the native op is
    (``dpx_allreduce_q8`` == reduce-scatter + all-gather)."""
    shape = per_rank[0].shape
    if len(per_rank) == 1:
        return [np.ascontiguousarray(per_rank[0], dtype=np.float32)
                .reshape(shape).copy()], 0
    data, rs_bytes = simulate_quant_reduce_scatter(per_rank, block, bits)
    data, ag_bytes = simulate_quant_allgather(data, block, bits)
    return [d.reshape(shape) for d in data], rs_bytes + ag_bytes


def simulate_hier_ring(per_rank: Sequence[np.ndarray],
                       local_world: int,
                       block: int = QUANT_BLOCK,
                       bits: int = 8
                       ) -> Tuple[List[np.ndarray], int]:
    """The two-level hierarchical ring, simulated — the executable spec
    of :class:`..comm.hier.HierRing`.

    Ranks are grouped into hosts of ``local_world`` consecutive ranks.
    Per host the FAST hop runs exact f32: the leader (first rank of the
    host) accumulates its members' buffers in local-rank order — the
    same op order as the native rooted ``dpx_reduce_f32`` hub, so the
    sim stays bit-identical to the real thing. The SLOW hop is the
    quantized ring (:func:`simulate_quant_ring`) over the per-host
    partial sums, one designated leader per host; the result broadcasts
    back exactly. Returns ``(results, slow_hop_bytes)``: results are
    bit-identical on EVERY rank (leader ring bit-identity + exact
    broadcast), and ``slow_hop_bytes`` counts only the inter-host
    (leader-ring) traffic — each gradient byte crosses the slow hop
    exactly once per leg, ``1/local_world`` of a flat all-ranks ring's
    slow-hop bytes."""
    w = len(per_rank)
    if local_world < 1 or w % local_world:
        raise ValueError(
            f"local_world {local_world} must divide world {w}")
    shape = per_rank[0].shape
    nh = w // local_world
    leaders = []
    for h in range(nh):
        acc = np.ascontiguousarray(per_rank[h * local_world],
                                   dtype=np.float32).ravel().copy()
        for lr in range(1, local_world):
            acc += np.ascontiguousarray(
                per_rank[h * local_world + lr],
                dtype=np.float32).ravel()
        leaders.append(acc)
    reduced, slow_bytes = simulate_quant_ring(leaders, block, bits)
    return ([reduced[r // local_world].reshape(shape).copy()
             for r in range(w)], slow_bytes)


# ---------------------------------------------------------------------------
# adaptive width selection (EQuARX-style dynamic block-wise width)
# ---------------------------------------------------------------------------

#: Per-block ``amax/rms`` above this marks the block q4-hostile: one
#: outlier would claim the whole nibble range and flush its block-mates
#: to zero. A Gaussian block of 1024 sits near sqrt(2*ln 1024) ~ 3.7.
DYNRANGE_THRESH = 6.0

#: Fraction of q4-hostile blocks above which the bucket stays on q8.
Q4_MAX_OUTLIER_FRAC = 0.05

#: Consecutive identical width verdicts required before the wire width
#: flips — bounds the compiled-program churn on the SPMD front door and
#: keeps a borderline bucket from flapping 8<->4 every step.
WIDTH_HYSTERESIS = 2


def block_outlier_frac(x: np.ndarray, block: int = QUANT_BLOCK,
                       thresh: float = DYNRANGE_THRESH) -> float:
    """Fraction of (nonzero) blocks whose ``amax/rms`` exceeds
    ``thresh`` — the chooser's dynamic-range statistic, computed on a
    flat f32 bucket. All-zero blocks are neither counted nor hostile.
    The ragged tail block's rms divides by its REAL element count — the
    zero padding this function adds must not read as dynamic range."""
    x = np.ascontiguousarray(x, dtype=np.float32).ravel()
    if x.size == 0:
        return 0.0
    nb = num_blocks(x.size, block)
    pad = nb * block - x.size
    v = (np.pad(x, (0, pad)) if pad else x).reshape(nb, block)
    amax = np.abs(v).max(axis=1)
    counts = np.full(nb, block, np.float64)
    counts[-1] = x.size - (nb - 1) * block
    rms = np.sqrt(np.square(v, dtype=np.float64).sum(axis=1) / counts)
    valid = rms > 0.0
    if not valid.any():
        return 0.0
    hostile = valid & (amax > thresh * rms)
    return float(hostile.sum()) / float(valid.sum())


class WidthChooser:
    """Deterministic per-bucket wire-width policy with hysteresis.

    Feed it the REDUCED bucket after each quantized collective
    (:meth:`observe`) — that bucket is bit-identical on every rank by
    the all-gather leg's byte-forwarding construction, so every rank's
    chooser walks the identical state machine and the next step's width
    agrees world-wide with zero extra communication. (Deciding from the
    per-rank RAW gradient would diverge; the schedule recorder would
    then flag the mismatched op signatures.) The SPMD front door feeds
    the precomputed statistic instead (:meth:`observe_frac`) so the
    compiled step only ships one scalar to the host.

    Starts at q8 (safe); drops to q4 only after ``hysteresis``
    consecutive low-dynamic-range verdicts, and climbs back the same
    way. ``widths`` records the width used per observed step — the
    bench's adaptive-width histogram."""

    def __init__(self, *, thresh: float = DYNRANGE_THRESH,
                 max_frac: float = Q4_MAX_OUTLIER_FRAC,
                 hysteresis: int = WIDTH_HYSTERESIS,
                 block: int = QUANT_BLOCK, initial: int = 8):
        quant_levels(initial)
        self.thresh = float(thresh)
        self.max_frac = float(max_frac)
        self.hysteresis = max(int(hysteresis), 1)
        self.block = block
        self._width = initial
        self._pending_width = initial
        self._pending_count = 0
        self.widths: List[int] = []

    @property
    def width(self) -> int:
        """The wire width to use for the NEXT quantized collective."""
        return self._width

    def observe_frac(self, frac: float) -> int:
        """Fold one bucket's outlier fraction into the state machine;
        returns the width for the next step."""
        self.widths.append(self._width)
        verdict = 4 if float(frac) <= self.max_frac else 8
        if verdict == self._width:
            self._pending_count = 0
            self._pending_width = self._width
        else:
            if verdict == self._pending_width:
                self._pending_count += 1
            else:
                self._pending_width = verdict
                self._pending_count = 1
            if self._pending_count >= self.hysteresis:
                self._width = verdict
                self._pending_count = 0
        return self._width

    def observe(self, reduced: np.ndarray) -> int:
        """Observe a reduced bucket (bit-identical across ranks) and
        return the width for the next step."""
        return self.observe_frac(
            block_outlier_frac(reduced, self.block, self.thresh))

    def histogram(self) -> dict:
        """{width: steps used} over every observed step."""
        out: dict = {}
        for b in self.widths:
            out[b] = out.get(b, 0) + 1
        return out
