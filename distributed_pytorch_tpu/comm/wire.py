"""Quantized collective wire format — the single source of truth.

Both comm front doors speak this format when a caller opts into
``wire="quant"`` / ``grad_reduce="quant"``:

* the native TCP ring (``native/dpxhost.cpp:dpx_allreduce_q8``) encodes
  and decodes it in C++ on the host-process front door, and
* the SPMD front door's :func:`..comm.primitives.quantized_pmean` uses
  the same block rule in jnp (via :mod:`..ops.quant`).

**Block codec** (EQuARX-style, arxiv 2506.17615): the flat f32 payload is
cut into blocks of :data:`QUANT_BLOCK` elements (last block ragged). Per
block: ``amax = max|v|``; ``scale = 1`` if ``amax == 0``; ``scale = 1``
if every value is an integer with ``amax <= 127`` (small-magnitude
integer payloads — step counters, one-hot count buckets — transfer
EXACTLY); else ``scale = amax/127``. ``q = clip(rint(v * (127/amax)),
-127, 127)`` as int8 (quantization multiplies by the f32 inverse — the
vectorizable form all three implementations share). One f32 scale per
block keeps LOCAL dynamic range: a tiny layernorm grad never shares a
scale with an embedding grad.

**Chunk framing**: a contiguous run of blocks is framed as
``[f32 scales x nblocks][int8 q x nelems]`` — scatter-gather friendly
(two plain memcpys each side, no per-chunk header; both peers derive
every length from ``(n, block, chunk_blocks, step)``).

**Ring schedule** (:func:`simulate_quant_ring` is the executable spec;
the C++ implements it chunk-pipelined): reduce-scatter leg — each hop
quantizes the f32 partial of the outgoing segment, the receiver
dequantize-accumulates in f32; all-gather leg — the segment owner
quantizes its reduced segment ONCE, replaces its own copy with the
dequantized value, and the quantized bytes are forwarded UNCHANGED
around the ring, so every rank decodes identical bytes and the result
is bit-identical on all ranks.

Everything here is numpy-only (no jax import): the torch front door and
spawned rank workers use it without touching an XLA backend, and the
numpy sim is bit-exact against the C++ (same IEEE f32 ops in the same
order), which the native parity test leans on.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

#: Quantization-block width (elements per f32 scale). Exported through
#: :mod:`.primitives` for bucketing callers.
QUANT_BLOCK = 1024

#: Blocks per wire chunk on the native ring (256 KiB of int8 payload at
#: the default block): small enough that peers' compute phases overlap
#: in-flight socket transfer, large enough that the extra lockstep
#: rounds don't dominate on small oversubscribed hosts (measured: on a
#: 2-core/8-rank loopback mesh, 64 KiB chunks cost ~25% of the ring's
#: throughput in pure scheduling; 256 KiB recovers it while still
#: splitting every >256 KiB segment for overlap).
QUANT_CHUNK_BLOCKS = 256

SCALE_BYTES = 4  # one f32 scale per block


# ---------------------------------------------------------------------------
# block codec (numpy reference; C++ and jnp mirror it)
# ---------------------------------------------------------------------------


def _block_codec(x: np.ndarray,
                 block: int = QUANT_BLOCK) -> Tuple[np.ndarray, np.ndarray]:
    """Per-block (dequant scales, quant inverses) for a flat f32 array.

    Quantization MULTIPLIES by the f32 inverse ``127/amax`` rather than
    dividing by ``amax/127`` — the native codec does the same (a
    vectorized multiply), and grids must agree bit for bit. Fully
    vectorized: this runs per training step on the error-feedback path,
    so a per-block Python loop would sit on the hot path the quantized
    ring exists to speed up (zero-padding the ragged tail changes
    neither amax nor the all-integer test)."""
    x = np.ascontiguousarray(x, dtype=np.float32).ravel()
    nb = num_blocks(x.size, block)
    pad = nb * block - x.size
    v = (np.pad(x, (0, pad)) if pad else x).reshape(nb, block)
    amax = np.abs(v).max(axis=1)
    # integer-exact snap: small-magnitude integer payloads round-trip
    # exactly (scale 1, |q| <= 127)
    unit = (amax == 0.0) | ((amax <= 127.0)
                            & (v == np.rint(v)).all(axis=1))
    safe = np.where(unit, np.float32(1.0), amax)  # no 0-div warnings
    one = np.float32(1.0)
    scales = np.where(unit, one, safe / np.float32(127.0))
    invs = np.where(unit, one, np.float32(127.0) / safe)
    return scales.astype(np.float32), invs.astype(np.float32)


def block_scales(x: np.ndarray, block: int = QUANT_BLOCK) -> np.ndarray:
    """Per-block dequantization scales for a flat f32 array."""
    return _block_codec(x, block)[0]


def quantize_blocks(x: np.ndarray,
                    block: int = QUANT_BLOCK) -> Tuple[np.ndarray, np.ndarray]:
    """Flat f32 -> (int8 q of same length, f32 scales per block)."""
    x = np.ascontiguousarray(x, dtype=np.float32).ravel()
    scales, invs = _block_codec(x, block)
    per_elem = np.repeat(invs, block)[:x.size]
    q = np.clip(np.rint(x * per_elem), -127, 127).astype(np.int8)
    return q, scales


def dequantize_blocks(q: np.ndarray, scales: np.ndarray,
                      block: int = QUANT_BLOCK) -> np.ndarray:
    """(int8 q, f32 scales) -> f32 values (``q * scale`` per block)."""
    per_elem = np.repeat(scales.astype(np.float32), block)[:q.size]
    return q.astype(np.float32) * per_elem


# ---------------------------------------------------------------------------
# segment grid: how the ring splits n elements across world ranks
# ---------------------------------------------------------------------------


def num_blocks(n: int, block: int = QUANT_BLOCK) -> int:
    return (n + block - 1) // block


def segment_blocks(n: int, world: int,
                   block: int = QUANT_BLOCK) -> List[Tuple[int, int]]:
    """Block-aligned ring segments: ``[(start_block, n_blocks)] * world``.

    Blocks are distributed as evenly as possible (first ``rem`` segments
    get one extra); a segment never splits a block, so no quantization
    scale ever spans two ranks' segments.
    """
    nb = num_blocks(n, block)
    base, rem = divmod(nb, world)
    out, start = [], 0
    for s in range(world):
        cnt = base + (1 if s < rem else 0)
        out.append((start, cnt))
        start += cnt
    return out


def block_span_elems(start_block: int, nblocks: int, n: int,
                     block: int = QUANT_BLOCK) -> Tuple[int, int]:
    """(element offset, element count) covered by a run of blocks."""
    lo = start_block * block
    hi = min((start_block + nblocks) * block, n)
    return lo, max(hi - lo, 0)


def span_wire_bytes(start_block: int, nblocks: int, n: int,
                    block: int = QUANT_BLOCK) -> int:
    """Wire bytes of a framed run of blocks: scales + int8 payload."""
    _, elems = block_span_elems(start_block, nblocks, n, block)
    return SCALE_BYTES * nblocks + elems


# ---------------------------------------------------------------------------
# byte accounting (what the bench and tests assert on)
# ---------------------------------------------------------------------------


def quant_wire_bytes(n: int, block: int = QUANT_BLOCK) -> int:
    """Bytes for ONE quantized copy of an n-element payload."""
    return n + SCALE_BYTES * num_blocks(n, block)


def ring_allreduce_wire_bytes(n: int, world: int, elem_size: int = 4) -> int:
    """Total wire bytes (all ranks, both legs) of the full-width ring
    all-reduce (``native/dpxhost.cpp:ring_allreduce``): 2*(world-1) hops
    per segment, segments of ceil(n/world) elements (last ragged)."""
    if world <= 1:
        return 0
    chunk = (n + world - 1) // world
    total_seg_elems = 0
    for s in range(world):
        lo = chunk * s
        total_seg_elems += max(min(lo + chunk, n) - lo, 0)
    return 2 * (world - 1) * total_seg_elems * elem_size


def quant_ring_allreduce_wire_bytes(n: int, world: int,
                                    block: int = QUANT_BLOCK) -> int:
    """Total wire bytes (all ranks, both legs) of the quantized ring
    (``dpx_allreduce_q8``): each segment travels world-1 hops per leg in
    framed int8+scales form."""
    if world <= 1:
        return 0
    total = 0
    for start, cnt in segment_blocks(n, world, block):
        total += 2 * (world - 1) * span_wire_bytes(start, cnt, n, block)
    return total


def quant_leg_wire_bytes(n: int, world: int, block: int = QUANT_BLOCK) -> int:
    """Total wire bytes (all ranks) of ONE leg of the quantized ring —
    ``dpx_reduce_scatter_q8`` or ``dpx_allgather_q8`` each move exactly
    half of :func:`quant_ring_allreduce_wire_bytes` (every segment
    travels world-1 hops once per leg)."""
    if world <= 1:
        return 0
    total = 0
    for start, cnt in segment_blocks(n, world, block):
        total += (world - 1) * span_wire_bytes(start, cnt, n, block)
    return total


def ring_owned_span(n: int, world: int, rank: int,
                    block: int = QUANT_BLOCK) -> Tuple[int, int]:
    """(element offset, element count) of the segment rank ``rank`` OWNS
    after the ring reduce-scatter leg — segment ``(rank+1) % world`` of
    the block-aligned grid (the same ownership convention as
    ``native/dpxhost.cpp``'s ring schedule)."""
    seg = (rank + 1) % world
    start, cnt = segment_blocks(n, world, block)[seg]
    return block_span_elems(start, cnt, n, block)


# ---------------------------------------------------------------------------
# executable spec: the quantized ring, simulated in numpy
# ---------------------------------------------------------------------------


def _seg_spans(n: int, w: int, block: int) -> List[slice]:
    """Per-segment element slices, computed ONCE per simulation (the
    hop loops index it O(world^2) times)."""
    out = []
    for start, cnt in segment_blocks(n, w, block):
        lo, elems = block_span_elems(start, cnt, n, block)
        out.append(slice(lo, lo + elems))
    return out


def simulate_quant_reduce_scatter(per_rank: Sequence[np.ndarray],
                                  block: int = QUANT_BLOCK
                                  ) -> Tuple[List[np.ndarray], int]:
    """The reduce-scatter LEG of the quantized ring, simulated.

    ``per_rank``: one equal-shape array per rank. Returns ``(buffers,
    wire_bytes)`` where ``buffers[r]`` is rank r's FLAT working buffer
    after the leg: the span :func:`ring_owned_span` ``(n, w, r)`` holds
    the full (lossily accumulated) SUM of that segment; every other span
    holds a partial accumulation (undefined to callers — exactly the
    ``dpx_reduce_scatter_q8`` contract, bit for bit)."""
    w = len(per_rank)
    data = [np.ascontiguousarray(x, dtype=np.float32).ravel().copy()
            for x in per_rank]
    n = data[0].size
    if w == 1:
        return data, 0
    spans = _seg_spans(n, w, block)
    bytes_moved = 0
    # quantize the outgoing f32 partial each hop, receiver dequantize-
    # accumulates (all sends of a step happen "at once": quantize from
    # the pre-step snapshot, like the real ring)
    for step in range(w - 1):
        sends = {}
        for r in range(w):
            send_seg = (r - step) % w
            q, s = quantize_blocks(data[r][spans[send_seg]], block)
            sends[r] = (q, s)
            bytes_moved += q.size + SCALE_BYTES * s.size
        for r in range(w):
            recv_seg = (r - step - 1) % w
            q, s = sends[(r - 1) % w]
            data[r][spans[recv_seg]] += dequantize_blocks(q, s, block)
    return data, bytes_moved


def simulate_quant_allgather(per_rank: Sequence[np.ndarray],
                             block: int = QUANT_BLOCK
                             ) -> Tuple[List[np.ndarray], int]:
    """The byte-forwarding all-gather LEG of the quantized ring,
    simulated. Rank r contributes the span :func:`ring_owned_span`
    ``(n, w, r)`` of its flat buffer; afterwards every rank's buffer is
    BIT-IDENTICAL (each span is the dequantized grid of its owner's
    bytes, owner included). Mirrors ``dpx_allgather_q8`` bit for bit."""
    w = len(per_rank)
    data = [np.ascontiguousarray(x, dtype=np.float32).ravel().copy()
            for x in per_rank]
    n = data[0].size
    if w == 1:
        return data, 0
    spans = _seg_spans(n, w, block)
    bytes_moved = 0
    wires = {}
    for r in range(w):
        own = (r + 1) % w
        q, s = quantize_blocks(data[r][spans[own]], block)
        wires[own] = (q, s)
        data[r][spans[own]] = dequantize_blocks(q, s, block)
    for step in range(w - 1):
        for r in range(w):
            recv_seg = (r - step) % w
            q, s = wires[recv_seg]
            data[r][spans[recv_seg]] = dequantize_blocks(q, s, block)
            bytes_moved += q.size + SCALE_BYTES * s.size
    return data, bytes_moved


def simulate_quant_ring(per_rank: Sequence[np.ndarray],
                        block: int = QUANT_BLOCK
                        ) -> Tuple[List[np.ndarray], int]:
    """Run the quantized ring schedule on in-memory "ranks".

    ``per_rank``: one equal-shape array per rank. Returns ``(results,
    wire_bytes)`` where ``results[r]`` is rank r's reduced SUM (callers
    divide by world for a mean) and ``wire_bytes`` is the total bytes
    that would cross the wire. The arithmetic (op kind and order) is
    bit-identical to ``dpx_allreduce_q8``, so this doubles as the parity
    oracle for the native path — and all results are bit-identical
    across ranks by construction of the byte-forwarding all-gather leg.
    Composed from the two standalone leg simulations, exactly like the
    native op is (``dpx_allreduce_q8`` == reduce-scatter + all-gather)."""
    shape = per_rank[0].shape
    if len(per_rank) == 1:
        return [np.ascontiguousarray(per_rank[0], dtype=np.float32)
                .reshape(shape).copy()], 0
    data, rs_bytes = simulate_quant_reduce_scatter(per_rank, block)
    data, ag_bytes = simulate_quant_allgather(data, block)
    return [d.reshape(shape) for d in data], rs_bytes + ag_bytes
