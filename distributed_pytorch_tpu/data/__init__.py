"""Data: sharded sampling, mesh-aware loading, device prefetching,
ladder datasets."""
from . import datasets, loader, prefetch, sampler
from .datasets import DummyDataset, SyntheticImages, SyntheticLM
from .loader import DataLoader
from .prefetch import PrefetchLoader, device_prefetch
from .sampler import ShardedSampler, data_sampler
