"""Data: sharded sampling, mesh-aware loading, ladder datasets."""
from . import datasets, loader, sampler
from .datasets import DummyDataset, SyntheticImages, SyntheticLM
from .loader import DataLoader
from .sampler import ShardedSampler, data_sampler
