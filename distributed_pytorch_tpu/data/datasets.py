"""Datasets for the evaluation ladder (BASELINE.md).

``DummyDataset`` mirrors the reference's seeded toy dataset
(``min_DDP.py:27-38``): feature = the sample's own index as a float scalar,
label = seeded random class — identical in every process without any
broadcast, which is what makes cross-rank loss-parity checks meaningful.
The synthetic classification/LM datasets back the ResNet/Transformer rungs
without external downloads.
"""

from __future__ import annotations

import numpy as np


class DummyDataset:
    """Index-as-feature toy dataset (reference ``min_DDP.py:27-38``).

    Labels are drawn once from a seeded generator (the reference seeds
    ``torch.Generator().manual_seed(0)``; here a numpy Generator seeded the
    same way) so every process constructs the identical dataset."""

    def __init__(self, length: int, n_classes: int, seed: int = 0):
        self.len = int(length)
        rng = np.random.default_rng(seed)
        self.data = np.arange(self.len, dtype=np.float32)[:, None]
        self.labels = rng.integers(0, n_classes, size=(self.len,)).astype(np.int32)

    def __getitem__(self, idx):
        return self.data[idx], self.labels[idx]

    def __len__(self):
        return self.len


class SyntheticImages:
    """Seeded fake image-classification set (CIFAR-shaped by default) for
    the ResNet rung of the ladder — NHWC, float32 in [0, 1)."""

    def __init__(self, length: int, shape=(32, 32, 3), n_classes: int = 10,
                 seed: int = 0):
        rng = np.random.default_rng(seed)
        self.images = rng.random((length, *shape), dtype=np.float32)
        self.labels = rng.integers(0, n_classes, size=(length,)).astype(np.int32)

    def __getitem__(self, idx):
        return self.images[idx], self.labels[idx]

    def __len__(self):
        return len(self.labels)


class SyntheticLM:
    """Seeded fake next-token-prediction set for the Transformer-LM rung:
    each sample is (tokens[:-1], tokens[1:])."""

    def __init__(self, length: int, seq_len: int, vocab: int, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.tokens = rng.integers(0, vocab, size=(length, seq_len + 1)).astype(np.int32)

    def __getitem__(self, idx):
        t = self.tokens[idx]
        return t[:-1], t[1:]

    def __len__(self):
        return len(self.tokens)
