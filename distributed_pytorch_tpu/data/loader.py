"""Batch loading onto the mesh — the ``DataLoader`` seam of the workload.

The reference iterates ``DataLoader(dataset, batch_size, sampler)`` per rank
and moves each batch to its GPU (``min_DDP.py:65-66,96``). Under
single-controller SPMD one loader produces the *global* batch each step,
laid out so axis 0 splits into per-rank shards in rank order, and one
``device_put`` shards it over the ``dp`` mesh axis — N H2D copies become one
sharded transfer.

Key layout invariant: for world W and per-rank batch B, step t's global
batch rows ``[r*B:(r+1)*B]`` are exactly what the reference's rank r would
have loaded at step t from its strided ``DistributedSampler`` shard. The
data-parallel engine and the stacked collectives rely on this.
"""

from __future__ import annotations

import math
from typing import Callable, Iterator, Optional

import numpy as np

from .sampler import ShardedSampler


class DataLoader:
    """Minimal map-style loader: dataset + optional sharded sampler → batches.

    ``dataset`` must support ``len()`` and integer ``__getitem__`` returning
    a tuple/list of numpy-convertible leaves (the reference's Dataset
    contract, ``min_DDP.py:27-38``). With a sampler, each yielded batch is
    the *global* batch: per-rank sub-batches concatenated in rank order
    (see module docstring). Without one, plain (optionally shuffled)
    batching — matching the reference quirk that non-distributed runs
    shuffle while distributed ones don't (``min_DDP.py:64-66``).
    """

    def __init__(self, dataset, batch_size: int,
                 sampler: Optional[ShardedSampler] = None,
                 shuffle: bool = False, seed: int = 0,
                 drop_last: bool = False,
                 collate: Optional[Callable] = None):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.sampler = sampler
        self.shuffle = shuffle and sampler is None
        self.seed = seed
        self.drop_last = drop_last
        self.collate = collate or _default_collate
        self._epoch = 0
        self._cache_key = None
        self._cache_rows = None

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def _index_matrix(self) -> list:
        """Per-step global-batch index rows for this epoch. ``batch_size``
        is the *per-rank* batch (the reference's ``--batch-size``,
        ``min_DDP.py:14``), so with a sampler each row has W*B indices.
        Cached per (loader epoch, sampler epoch)."""
        key = (self._epoch,
               self.sampler.epoch if self.sampler is not None else None)
        if self._cache_key == key:
            return self._cache_rows
        rows = self._build_rows()
        self._cache_key, self._cache_rows = key, rows
        return rows

    def _build_rows(self) -> list:
        if self.sampler is not None:
            s = self.sampler
            glob = s.global_indices()
            # shard r, in rank-strided order, reshaped to (steps, B) then
            # concatenated along batch axis in rank order
            per_rank = [glob[r :: s.world_size] for r in range(s.world_size)]
            n_local = len(per_rank[0])
            b = self.batch_size
            steps = n_local // b if self.drop_last else math.ceil(n_local / b)
            rows = []
            for t in range(steps):
                chunk = [pr[t * b : (t + 1) * b] for pr in per_rank]
                rows.append(np.concatenate(chunk))
            return rows
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            idx = rng.permutation(n)
        else:
            idx = np.arange(n)
        b = self.batch_size
        steps = n // b if self.drop_last else math.ceil(n / b)
        return [idx[t * b : (t + 1) * b] for t in range(steps)]

    def __iter__(self) -> Iterator:
        return self.iter_from(0)

    def iter_from(self, start_batch: int) -> Iterator:
        """This epoch's batches starting at batch index ``start_batch``:
        earlier rows are skipped at the INDEX level — no dataset reads, no
        collation — which is what makes checkpoint-resume fast-forward
        (examples/train_transformer_lm.py) O(1) per skipped batch."""
        for row in self._index_matrix()[start_batch:]:
            yield self.collate([self.dataset[int(i)] for i in row])

    def __len__(self) -> int:
        if self.sampler is not None:
            n_local = len(self.sampler)
        else:
            n_local = len(self.dataset)
        if self.drop_last:
            return n_local // self.batch_size
        return math.ceil(n_local / self.batch_size)


def _default_collate(items):
    """Stack tuple-of-leaves samples into a tuple of batched numpy arrays."""
    first = items[0]
    if isinstance(first, (tuple, list)):
        return tuple(np.stack([np.asarray(it[k]) for it in items])
                     for k in range(len(first)))
    return np.stack([np.asarray(it) for it in items])
