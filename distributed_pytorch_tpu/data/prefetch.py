"""Background-thread device prefetching for the input pipeline.

The reference's DataLoader blocks the training loop on both batch
assembly and the H2D copy every step (``min_DDP.py:95-96``). On TPU the
H2D transfer is the expensive half (on remote-tunneled chips it can cost
more than the step itself — measured while building the ladder
examples), and it is fully overlappable: a worker thread assembles the
next batches and starts their device transfers while the current step
runs, keeping the accelerator fed.

``device_prefetch`` wraps any batch iterator (e.g. ``data.DataLoader``)
and yields batches that are already on device (or in flight —
``device_put`` is async; by the time the step consumes them the transfer
has overlapped with the previous step's compute).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

import jax


class _Stop:
    pass


_STOP = _Stop()


def device_prefetch(iterable: Iterable, size: int = 2,
                    place: Optional[Callable] = None) -> Iterator:
    """Iterate ``iterable`` with ``size`` batches prefetched onto device.

    ``place`` maps a host batch to device (default:
    ``runtime.context.shard_batch`` — dp-sharded axis 0, replicated at
    world 1). Exceptions from the source iterator or placement propagate
    to the consumer at the matching position. The worker is a daemon
    thread; when the consumer abandons the iterator, every queue
    interaction the worker makes is abandonment-aware (timeout + flag
    polls), so the thread exits as soon as the source yields control —
    only a source blocked forever inside ``next()`` can pin it, which no
    queue design can interrupt.
    """
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    if place is None:
        from ..runtime.context import shard_batch
        place = shard_batch

    q: "queue.Queue" = queue.Queue(maxsize=size)
    abandoned = threading.Event()

    def put_or_abandon(item) -> bool:
        """Bounded put that gives up when the consumer is gone."""
        while not abandoned.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in iterable:
                if abandoned.is_set():
                    return
                if not put_or_abandon(place(batch)):
                    return
            put_or_abandon(_STOP)
        except BaseException as e:  # noqa: BLE001 — repropagated below
            put_or_abandon(e)

    t = threading.Thread(target=worker, daemon=True,
                         name="dpx-prefetch")
    t.start()

    try:
        while True:
            # dpxlint: disable=DPX003 producer is in-process and always lands _STOP or the exception before exiting
            item = q.get()
            if item is _STOP:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        abandoned.set()


class PrefetchLoader:
    """A DataLoader wrapper yielding device-resident batches each epoch.

    Keeps the loader's epoch/len surface (``set_epoch``, ``len``) so it
    drops into the ladder examples in place of the bare loader::

        loader = PrefetchLoader(DataLoader(ds, batch_size, sampler=s))
        for epoch ...:
            loader.set_epoch(epoch)
            for batch in loader:   # already on device
                ...
    """

    def __init__(self, loader, size: int = 2,
                 place: Optional[Callable] = None):
        self.loader = loader
        self.size = size
        self.place = place

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def __iter__(self):
        return device_prefetch(self.loader, self.size, self.place)

    def __len__(self):
        return len(self.loader)
