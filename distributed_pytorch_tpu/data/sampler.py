"""Sharded sampling — the ``DistributedSampler`` equivalent.

Reproduces the contract the reference relies on (``distributed.py:105-108``
wrapping ``torch.utils.data.distributed.DistributedSampler``, exercised with
``set_epoch`` at ``min_DDP.py:82-83``):

* rank-strided index sharding: rank r gets indices ``r, r+W, r+2W, ...`` of
  the (optionally shuffled) index list;
* padding: the index list is extended by wrapping from its own start so every
  rank gets exactly ``ceil(N / W)`` indices — equal shard sizes, which the
  stacked-collective layout (comm/collectives.py) also requires;
* ``set_epoch(e)``: reseeds the shuffle with ``seed + e`` so every rank
  draws the *same* permutation each epoch but different ones across epochs;
* ``shuffle=False`` → plain ``arange`` order.

Shuffling uses a deterministic seeded permutation (numpy Generator), the
analog of the torch sampler's ``g.manual_seed(self.seed + self.epoch)``.
"""

from __future__ import annotations

import math
from typing import Iterator, Optional, Sequence

import numpy as np


class ShardedSampler:
    """Per-rank view of a dataset's indices, equal-sized via wrap padding."""

    def __init__(self, dataset_size: int, rank: int, world_size: int,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = False):
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world {world_size}")
        self.dataset_size = int(dataset_size)
        self.rank = rank
        self.world_size = world_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.drop_last = drop_last
        if drop_last and self.dataset_size % world_size != 0:
            self.num_samples = self.dataset_size // world_size
        else:
            self.num_samples = math.ceil(self.dataset_size / world_size)
        self.total_size = self.num_samples * world_size

    def set_epoch(self, epoch: int) -> None:
        """Reseed the per-epoch shuffle (contract of ``min_DDP.py:82-83``)."""
        self.epoch = int(epoch)

    def global_indices(self) -> np.ndarray:
        """The padded, epoch-shuffled index list shared by all ranks."""
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            idx = rng.permutation(self.dataset_size)
        else:
            idx = np.arange(self.dataset_size)
        if not self.drop_last and self.total_size > len(idx):
            # wrap-pad from the start, like the torch sampler
            pad = self.total_size - len(idx)
            reps = math.ceil(pad / max(len(idx), 1))
            idx = np.concatenate([idx] + [idx] * reps)[: self.total_size]
        else:
            idx = idx[: self.total_size]
        return idx

    def local_indices(self) -> np.ndarray:
        """This rank's strided shard: positions rank, rank+W, ... ."""
        return self.global_indices()[self.rank :: self.world_size]

    def __iter__(self) -> Iterator[int]:
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples


def data_sampler(dataset, distributed: bool, shuffle: bool,
                 rank: Optional[int] = None, world_size: Optional[int] = None,
                 seed: int = 0) -> Optional[ShardedSampler]:
    """Return a sampler iff distributed, else ``None`` (reference
    ``distributed.py:105-108``).

    Like the torch sampler, rank/world default from the live process group.
    Under single-controller SPMD the controller owns every rank's shard, so
    the loader (``data/loader.py``) consumes all W strided shards in rank
    order and the sampler here carries rank 0's view for API parity.
    """
    if not distributed:
        return None
    from ..runtime import context

    r = context.get_rank() if rank is None else rank
    w = context.get_world_size() if world_size is None else world_size
    return ShardedSampler(len(dataset), rank=r, world_size=w,
                          shuffle=shuffle, seed=seed)
