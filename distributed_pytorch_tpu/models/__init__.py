"""Model zoo: the reference workload's MLP plus the evaluation-ladder
models (ResNet-18, Transformer LM, MoE Transformer LM) and the compiled
KV-cache generation path."""
from . import generate, mlp, moe_lm, resnet, transformer
from .generate import (KVCache, decode_step, decode_step_slots, init_cache,
                       make_generate_fn, prefill, prefill_partial)
from .generate import generate as generate_tokens
from .mlp import DummyModel
from .moe_lm import MoETransformerLM
from .resnet import ResNet18
from .transformer import TransformerLM
