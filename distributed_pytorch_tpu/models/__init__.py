"""Model zoo: the reference workload's MLP plus the evaluation-ladder
models (ResNet-18, Transformer LM, MoE Transformer LM)."""
from . import mlp, moe_lm, resnet, transformer
from .mlp import DummyModel
from .moe_lm import MoETransformerLM
from .resnet import ResNet18
from .transformer import TransformerLM
