"""Model zoo: the reference workload's MLP plus the evaluation-ladder
models (ResNet-18, Transformer LM)."""
from . import mlp, resnet, transformer
from .mlp import DummyModel
from .resnet import ResNet18
from .transformer import TransformerLM
