"""Model zoo: the reference workload's MLP plus the evaluation-ladder
models (ResNet, Transformer LM)."""
from . import mlp
from .mlp import DummyModel
