"""Autoregressive text generation with a KV cache — the inference path.

The reference has no inference story at all (its workload is a training
loop over an MLP, reference ``min_DDP.py``); a complete LM framework needs
one, and on TPU it must be a *compiled* loop: the whole
prefill-then-decode pipeline here is two XLA programs (one prefill, one
``lax.scan`` over decode steps), with the KV cache as a fixed-shape
carry — no per-token host round trips, no dynamic shapes.

Design notes (TPU-first):
- The cache is preallocated at ``max_len`` per layer ((B, Hkv, max, Dh)
  for K and V — Hkv = ``model.n_kv_heads``, so GQA shrinks the cache by
  the group factor); each step writes one slot with
  ``dynamic_update_slice`` and attends over the full buffer under a
  position mask. Static shapes keep XLA happy; the masked tail costs
  FLOPs but no recompilation.
- Decode attention is a (B, Hkv, g, 1, max) x (B, Hkv, max, Dh) grouped
  matmul pair — bandwidth-bound as always for single-token decoding (GQA
  cuts exactly that cache bandwidth); the cache layout keeps the
  contraction on the MXU's fast axis.
- Sampling (greedy / temperature / top-k / nucleus top-p) happens
  on-device inside the scan; the host sees only the final (B, steps)
  token block.

Works on the same ``TransformerLM`` params used for training (reads the
block submodules directly; no weight conversion).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..nn.attention import dense_attention
from ..ops.decode_attention import (blockwise_decode_attention,
                                    dense_decode_attention,
                                    paged_decode_attention)
from .transformer import TransformerLM

Params = Dict[str, Any]


class KVCache(NamedTuple):
    k: Any        # list-like pytree of (B, Hkv, max_len, Dh) per layer
    v: Any        # (Hkv = model.n_kv_heads: GQA shrinks the cache)
    length: jnp.ndarray   # () int32 — number of valid positions


# qkv projection / output projection / MLP all go through the block's own
# methods (nn/attention.py), so the fused-qkv layout and MLP math have one
# source of truth shared with training.


def init_cache(model: TransformerLM, batch: int, max_len: int,
               dtype=None) -> KVCache:
    dtype = dtype or model.dtype
    dh = model.dim // model.n_heads
    h_kv = getattr(model, "n_kv_heads", model.n_heads)
    shape = (batch, h_kv, max_len, dh)
    zeros = lambda: [jnp.zeros(shape, dtype) for _ in range(model.n_layers)]
    return KVCache(k=zeros(), v=zeros(), length=jnp.zeros((), jnp.int32))


def prefill(model: TransformerLM, params: Params, tokens,
            max_len: int,
            window: Optional[int] = None) -> Tuple[jnp.ndarray, KVCache]:
    """Run the prompt through the model once, filling the cache.

    tokens: (B, S) int32. Returns (last-position logits (B, vocab),
    cache with ``length = S``). With ``window`` the cache is a ROLLING
    buffer of ``window`` slots — position p lives at slot ``p % W`` —
    holding the last W prompt positions; attention inside the prefill
    already runs the model's own (windowed) attn_fn, so only the cache
    layout changes."""
    b, s = tokens.shape
    if s > max_len:
        raise ValueError(f"prompt length {s} exceeds max_len {max_len}")
    w = window
    cache = init_cache(model, b, w if w is not None else max_len)
    x = model.tok.apply(params["tok"], tokens)
    positions = jnp.arange(s)
    if getattr(model, "pos", None) is not None:
        x = x + model.pos.apply(params["pos"], positions)
    ks, vs = [], []
    for i, blk in enumerate(model.blocks):
        p = params["blocks"][i]
        hq, hk, hv = blk.attn.project_qkv(p["attn"],
                                          blk.ln1.apply(p["ln1"], x))
        # rope rotates BEFORE caching: the cache holds post-rotation keys
        hq, hk = blk.attn.maybe_rope(hq, hk, positions)
        o = blk.attn.attn_fn(hq, hk, hv, causal=True)
        x = x + blk.attn.project_out(p["attn"], o)
        x = x + blk.mlp(p, x)
        hk = hk.astype(cache.k[i].dtype)
        hv = hv.astype(cache.v[i].dtype)
        if w is not None:
            # keep the LAST min(s, w) positions, laid out so position p
            # sits at slot p % w (roll of the contiguous tail)
            keep = min(s, w)
            hk, hv = hk[:, :, -keep:], hv[:, :, -keep:]
            shift = (s - keep) % w
            ks.append(jnp.roll(_pad_to(hk, w), shift, axis=2))
            vs.append(jnp.roll(_pad_to(hv, w), shift, axis=2))
        else:
            ks.append(jax.lax.dynamic_update_slice(
                cache.k[i], hk, (0, 0, 0, 0)))
            vs.append(jax.lax.dynamic_update_slice(
                cache.v[i], hv, (0, 0, 0, 0)))
    x = model.ln_f.apply(params["ln_f"], x[:, -1:])
    logits = model.project_vocab(params, x)[:, 0]
    return logits, KVCache(k=ks, v=vs,
                           length=jnp.asarray(s, jnp.int32))


def _pad_to(x, w: int):
    """Zero-pad the cache axis (2) up to ``w`` slots (prompt < window)."""
    pad = w - x.shape[2]
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


def decode_step(model: TransformerLM, params: Params, cache: KVCache,
                token,
                window: Optional[int] = None,
                blockwise: bool = True) -> Tuple[jnp.ndarray,
                                                 KVCache]:
    """One cached decode step. token: (B,) int32 at position
    ``cache.length``. Returns (logits (B, vocab), advanced cache).

    Attention over the cache runs page-blockwise by default
    (:func:`..ops.decode_attention.blockwise_decode_attention`): the
    online-softmax block merge visits only the blocks that hold
    resident positions, so the per-token cost scales with
    ``cache.length``, not the preallocated ``max_len``.
    ``blockwise=False`` keeps the dense full-width softmax — the
    reference implementation the blockwise kernel is tested against,
    and the baseline the decode-attention bench arm times.

    With ``window`` the cache is the rolling W-slot buffer from
    :func:`prefill`: the new position writes slot ``idx % W``
    (overwriting the token that just fell out of the window) and the
    mask reconstructs each slot's global position from the slot index —
    slot j holds ``idx - ((idx - j) mod W)``, valid iff >= 0. Exact
    sliding-window semantics in O(window) memory, independent of how
    long generation runs. (The rolling buffer's width IS the window —
    every slot is potentially resident, so it keeps the dense path.)"""
    idx = cache.length
    x = model.tok.apply(params["tok"], token[:, None])         # (B,1,D)
    if getattr(model, "pos", None) is not None:
        x = x + model.pos.apply(params["pos"], idx[None])
    scale = 1.0 / math.sqrt(model.dim // model.n_heads)
    max_len = cache.k[0].shape[2]
    if window is not None:
        slots = jnp.arange(max_len)
        slot_pos = idx - ((idx - slots) % window)
        pos_mask = slot_pos >= 0                               # (W,)
        write_at = idx % window
    else:
        pos_mask = (jnp.arange(max_len) <= idx)                # (max,)
        write_at = idx

    new_k, new_v = [], []
    for i, blk in enumerate(model.blocks):
        p = params["blocks"][i]
        hq, hk, hv = blk.attn.project_qkv(p["attn"],
                                          blk.ln1.apply(p["ln1"], x))
        hq, hk = blk.attn.maybe_rope(hq, hk, idx[None])
        k = jax.lax.dynamic_update_slice(
            cache.k[i], hk.astype(cache.k[i].dtype), (0, 0, write_at, 0))
        v = jax.lax.dynamic_update_slice(
            cache.v[i], hv.astype(cache.v[i].dtype), (0, 0, write_at, 0))
        new_k.append(k)
        new_v.append(v)
        if blockwise and window is None:
            # scalar position broadcast to a length-1 batch axis: the
            # (1, L) validity mask broadcasts over the B rows
            o = blockwise_decode_attention(hq, k, v, idx[None],
                                           scale=scale)
        else:
            o = dense_decode_attention(hq, k, v, pos_mask[None, :],
                                       scale=scale)
        x = x + blk.attn.project_out(p["attn"], o)
        x = x + blk.mlp(p, x)

    x = model.ln_f.apply(params["ln_f"], x)
    logits = model.project_vocab(params, x)[:, 0]
    return logits, KVCache(k=new_k, v=new_v, length=idx + 1)


def prefill_partial(model: TransformerLM, params: Params, tokens,
                    true_len,
                    window: Optional[int] = None
                    ) -> Tuple[jnp.ndarray, list, list]:
    """Prefill over a RIGHT-PADDED prompt — the slot-writable half of
    :func:`prefill` for the serving engine (``serve/``).

    tokens: (B, S) int32 where only the first ``true_len`` positions are
    real (``true_len`` may be traced — one compile per padded length
    bucket, not per prompt length). Causality makes the pad tail inert:
    real query positions never attend a later pad key, so the logits at
    position ``true_len - 1`` are bit-identical to an exact-length
    :func:`prefill` (the pad keys only ever contribute exact zeros to
    masked-softmax sums).

    Returns ``(logits (B, vocab) at the last real position, ks, vs)``
    where ks/vs are per-layer (B, Hkv, S, Dh) — or, with ``window``, the
    (B, Hkv, W, Dh) ROLLING layout of :func:`prefill` (position p at
    slot ``p % W``, unreached slots zeroed) built by gather so
    ``true_len`` can stay traced. The caller owns writing these rows
    into a cache pool (``serve/cache.py``)."""
    b, s = tokens.shape
    true_len = jnp.asarray(true_len, jnp.int32)
    x = model.tok.apply(params["tok"], tokens)
    positions = jnp.arange(s)
    if getattr(model, "pos", None) is not None:
        x = x + model.pos.apply(params["pos"], positions)
    ks, vs = [], []
    for i, blk in enumerate(model.blocks):
        p = params["blocks"][i]
        hq, hk, hv = blk.attn.project_qkv(p["attn"],
                                          blk.ln1.apply(p["ln1"], x))
        hq, hk = blk.attn.maybe_rope(hq, hk, positions)
        o = blk.attn.attn_fn(hq, hk, hv, causal=True)
        x = x + blk.attn.project_out(p["attn"], o)
        x = x + blk.mlp(p, x)
        hk = hk.astype(model.dtype)
        hv = hv.astype(model.dtype)
        if window is not None:
            # rolling layout with a TRACED true_len: slot j holds the
            # largest real position ≡ j (mod W) — a gather, so no
            # dynamic shapes (prefill's roll trick needs static lengths)
            j = jnp.arange(window)
            p_j = true_len - 1 - ((true_len - 1 - j) % window)
            valid = (p_j >= 0)[None, None, :, None]
            take = jnp.take(hk, jnp.clip(p_j, 0, s - 1), axis=2)
            ks.append(jnp.where(valid, take, 0))
            take = jnp.take(hv, jnp.clip(p_j, 0, s - 1), axis=2)
            vs.append(jnp.where(valid, take, 0))
        else:
            ks.append(hk)
            vs.append(hv)
    x_last = jax.lax.dynamic_slice_in_dim(x, true_len - 1, 1, axis=1)
    x_last = model.ln_f.apply(params["ln_f"], x_last)
    return model.project_vocab(params, x_last)[:, 0], ks, vs


def decode_step_slots(model: TransformerLM, params: Params, ks, vs,
                      lengths, tokens,
                      window: Optional[int] = None,
                      blockwise: bool = True
                      ) -> Tuple[jnp.ndarray, list, list]:
    """One decode step over a SLOT POOL: per-row cache lengths.

    The continuous-batching generalization of :func:`decode_step` — the
    pool rows are independent requests at different depths, so the
    scalar ``cache.length`` becomes ``lengths`` (B,) int32 and every
    row writes/masks at its own position (the write is a where-mask
    select, value-identical to ``dynamic_update_slice``). ks/vs:
    per-layer (B, Hkv, max_len, Dh); tokens (B,) int32.

    Attention is page-blockwise by default (see :func:`decode_step`):
    the cost per step scales with ``max(lengths)``, not the pool's
    ``max_len`` — a pool sized for long requests no longer taxes every
    short resident request for its full width. ``blockwise=False``
    keeps the dense full-width softmax (reference + bench baseline;
    the sliding-window rolling layout always uses it).

    Per-row math is exactly :func:`decode_step`'s; XLA's fusion choices
    are batch-shape-dependent, so across DIFFERENT batch shapes logits
    agree to ~1 ulp rather than bitwise — sampled token streams are
    what the serving engine guarantees identical (tests/test_serve.py).

    Returns ``(logits (B, vocab), new_ks, new_vs)``; advancing
    ``lengths`` (and masking dead slots) is the caller's business."""
    idx = lengths
    x = model.tok.apply(params["tok"], tokens[:, None])       # (B,1,D)
    if getattr(model, "pos", None) is not None:
        x = x + model.pos.apply(params["pos"], idx[:, None])
    scale = 1.0 / math.sqrt(model.dim // model.n_heads)
    max_len = ks[0].shape[2]
    if window is not None:
        slots = jnp.arange(max_len)[None, :]
        slot_pos = idx[:, None] - ((idx[:, None] - slots) % window)
        pos_mask = slot_pos >= 0                           # (B, W)
        write_at = idx % window
    else:
        pos_mask = jnp.arange(max_len)[None, :] <= idx[:, None]
        write_at = idx
    write_mask = (jnp.arange(max_len)[None, :]
                  == write_at[:, None])[:, None, :, None]  # (B,1,L,1)

    new_k, new_v = [], []
    for i, blk in enumerate(model.blocks):
        p = params["blocks"][i]
        hq, hk, hv = blk.attn.project_qkv(p["attn"],
                                          blk.ln1.apply(p["ln1"], x))
        hq, hk = blk.attn.maybe_rope(hq, hk, idx[:, None, None])
        k = jnp.where(write_mask, hk.astype(ks[i].dtype), ks[i])
        v = jnp.where(write_mask, hv.astype(vs[i].dtype), vs[i])
        new_k.append(k)
        new_v.append(v)
        if blockwise and window is None:
            o = blockwise_decode_attention(hq, k, v, idx, scale=scale)
        else:
            o = dense_decode_attention(hq, k, v, pos_mask, scale=scale)
        x = x + blk.attn.project_out(p["attn"], o)
        x = x + blk.mlp(p, x)

    x = model.ln_f.apply(params["ln_f"], x)
    return model.project_vocab(params, x)[:, 0], new_k, new_v


def _gather_pages(pool, tables):
    """Gather a slot batch's pages into contiguous rows.

    pool: (n_pages, Hkv, page_len, Dh); tables: (B, P) int32 page ids
    (unallocated entries may hold any valid id — the caller's position
    mask hides them). Returns (B, Hkv, P*page_len, Dh)."""
    g = pool[tables]                       # (B, P, Hkv, page_len, Dh)
    b, p, h, l, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(b, h, p * l, d)


def decode_step_slots_paged(model: TransformerLM, params: Params,
                            k_pages, v_pages, tables, lengths, tokens,
                            active, *, page_len: int,
                            blockwise: bool = True, kv_bits=None,
                            k_scales=None, v_scales=None,
                            k_tail=None, v_tail=None
                            ) -> Tuple[jnp.ndarray, list, list]:
    """One decode step over a PAGED slot pool (``serve/pages/``).

    The paged counterpart of :func:`decode_step_slots`: instead of each
    slot owning a contiguous (max_len) cache row, K/V live in a shared
    block pool — per layer ``(n_pages, Hkv, page_len, Dh)`` — and each
    slot addresses its pages through ``tables`` (B, P) int32. Slots can
    therefore SHARE full pages (a refcounted common prefix is resident
    once); sharing is safe because shared pages are immutable — decode
    only ever writes each slot's private tail page.

    Per-row math is exactly :func:`decode_step_slots`'s: the row's
    logical cache is the page gather (positions ``j`` at page
    ``tables[b, j // page_len]`` offset ``j % page_len``), the new K/V
    is written at ``lengths[b]`` (a pool scatter into the slot's tail
    page; ``active=False`` rows scatter out of bounds and are dropped,
    so a freed slot's stale table cannot be corrupted), and the position
    mask exposes ``<= lengths[b]``. ``tables``/``lengths``/``tokens``/
    ``active`` are all traced — ONE compiled program serves every
    request mix and every page-table state.

    Attention runs page-blockwise by default
    (:func:`..ops.decode_attention.paged_decode_attention`): the page
    gather moved INSIDE the online-softmax block loop, whose traced
    trip count is the resident page count — per-token cost scales with
    ``max(lengths)``, not ``tables.shape[1] * page_len``, and dead
    pages past every slot's length are never even gathered.
    ``blockwise=False`` keeps the dense full-table gather + softmax
    (the reference the contract tests pin the kernel against).

    Returns ``(logits (B, vocab), new_k_pages, new_v_pages)``; host-side
    page allocation (growing a table at page boundaries) and length
    bookkeeping belong to the caller.

    **Quantized resident pool** (``kv_bits`` = 8 | 4; docs/serving.md):
    ``k_pages``/``v_pages`` hold block-quantized int pages and
    ``k_scales``/``v_scales``/``k_tail``/``v_tail`` are per-layer lists
    of their scales and per-slot f32 tail buffers. The step's K/V is
    written to the slot's TAIL buffer (exact f32); when the write lands
    on the page's last position the whole tail page is quantized ONCE —
    from exact values, on the wire block grid — and scattered into the
    int pool with its scales (everything inside this one program, so
    the compile discipline is unchanged). Attention dequantizes inside
    the page-gather loop and overlays the exact tail page. Returns the
    extended tuple ``(logits, new_k_pages, new_v_pages, new_k_scales,
    new_v_scales, new_k_tail, new_v_tail)``. Requires ``blockwise=True``
    (the dense fallback would gather the whole int pool undequantized).
    """
    if kv_bits is not None and not blockwise:
        raise ValueError("quantized paged KV (kv_bits) requires the "
                         "blockwise decode path")
    idx = lengths
    n_pages = k_pages[0].shape[0]
    width = tables.shape[1] * page_len
    x = model.tok.apply(params["tok"], tokens[:, None])       # (B,1,D)
    if getattr(model, "pos", None) is not None:
        x = x + model.pos.apply(params["pos"], idx[:, None])
    scale = 1.0 / math.sqrt(model.dim // model.n_heads)
    pos_mask = jnp.arange(width)[None, :] <= idx[:, None]
    write_mask = (jnp.arange(width)[None, :]
                  == idx[:, None])[:, None, :, None]          # (B,1,W,1)
    # pool write target: the slot's page holding position idx. Inactive
    # rows are routed out of bounds (index n_pages) and dropped.
    wp = jnp.take_along_axis(tables, (idx // page_len)[:, None],
                             axis=1)[:, 0]
    wo = idx % page_len
    dest = jnp.where(active, wp, n_pages)
    if kv_bits is not None:
        from ..ops.quant import pack_page_nibbles, quantize_page_blocks
        bsz = tokens.shape[0]
        n_tail = k_tail[0].shape[0]
        # tail-buffer write target (one exact f32 page per slot);
        # inactive rows are dropped exactly like the pool scatter
        dest_t = jnp.where(active, jnp.arange(bsz), n_tail)
        # page completion: this write fills position page_len - 1 — the
        # ONE moment a page's values are quantized (from exact f32)
        completed = jnp.logical_and(active, wo == page_len - 1)
        dest_q = jnp.where(completed, wp, n_pages)

    new_kp, new_vp = [], []
    new_ks, new_vs, new_kt, new_vt = [], [], [], []
    for i, blk in enumerate(model.blocks):
        p = params["blocks"][i]
        hq, hk, hv = blk.attn.project_qkv(p["attn"],
                                          blk.ln1.apply(p["ln1"], x))
        hq, hk = blk.attn.maybe_rope(hq, hk, idx[:, None, None])
        if kv_bits is None:
            kp = k_pages[i].at[dest, :, wo].set(
                hk[:, :, 0, :].astype(k_pages[i].dtype), mode="drop")
            vp = v_pages[i].at[dest, :, wo].set(
                hv[:, :, 0, :].astype(v_pages[i].dtype), mode="drop")
        else:
            kt = k_tail[i].at[dest_t, :, wo].set(
                hk[:, :, 0, :].astype(jnp.float32), mode="drop")
            vt = v_tail[i].at[dest_t, :, wo].set(
                hv[:, :, 0, :].astype(jnp.float32), mode="drop")
            qk, sk = quantize_page_blocks(kt, kv_bits)  # (B,Hkv,L,Dh)
            qv, sv = quantize_page_blocks(vt, kv_bits)
            if kv_bits == 4:
                qk, qv = pack_page_nibbles(qk), pack_page_nibbles(qv)
            kp = k_pages[i].at[dest_q].set(qk, mode="drop")
            vp = v_pages[i].at[dest_q].set(qv, mode="drop")
            ks_i = k_scales[i].at[dest_q].set(sk, mode="drop")
            vs_i = v_scales[i].at[dest_q].set(sv, mode="drop")
            new_ks.append(ks_i)
            new_vs.append(vs_i)
            new_kt.append(kt)
            new_vt.append(vt)
        new_kp.append(kp)
        new_vp.append(vp)
        if kv_bits is not None:
            o = paged_decode_attention(hq, kp, vp, tables, idx,
                                       hk, hv, scale=scale,
                                       page_len=page_len,
                                       k_scales=ks_i, v_scales=vs_i,
                                       k_tail=kt, v_tail=vt)
        elif blockwise:
            # the page gather lives inside the block loop; hk/hv are
            # re-selected at the write position per block — identity
            # for active rows (already scattered), and gives inactive
            # rows decode_step_slots' exact value semantics (their
            # discarded logits still see "their" key)
            o = paged_decode_attention(hq, kp, vp, tables, idx,
                                       hk, hv, scale=scale,
                                       page_len=page_len)
        else:
            # logical rows: gather the updated pool, then re-select the
            # new key at the write position
            k = jnp.where(write_mask, hk.astype(kp.dtype),
                          _gather_pages(kp, tables))
            v = jnp.where(write_mask, hv.astype(vp.dtype),
                          _gather_pages(vp, tables))
            o = dense_decode_attention(hq, k, v, pos_mask, scale=scale)
        x = x + blk.attn.project_out(p["attn"], o)
        x = x + blk.mlp(p, x)

    x = model.ln_f.apply(params["ln_f"], x)
    logits = model.project_vocab(params, x)[:, 0]
    if kv_bits is None:
        return logits, new_kp, new_vp
    return logits, new_kp, new_vp, new_ks, new_vs, new_kt, new_vt


def prefill_partial_paged(model: TransformerLM, params: Params,
                          k_pages, v_pages, table_row, tokens, offset,
                          true_len, *, page_len: int, kv_bits=None,
                          k_scales=None, v_scales=None,
                          k_tail=None, v_tail=None, slot=None
                          ) -> Tuple[jnp.ndarray, list, list]:
    """Prefill the TAIL of a prompt into pool pages, attending over a
    page-resident shared prefix (``serve/pages/``).

    ``tokens`` (1, S) is the right-padded tail — the prompt MINUS its
    ``offset`` prefix tokens whose K/V are already resident in the pages
    ``table_row`` (P,) names (``offset`` is page-aligned: only FULL
    pages are ever shared, so the tail always starts at a page
    boundary). ``offset`` and ``true_len`` (the real tail length, >= 1)
    are both TRACED — one compile per padded tail bucket serves cold
    (``offset == 0``), partially shared, and fully shared admissions
    alike.

    Tail queries run at global positions ``offset + i`` (rope/learned
    positions included) and attend over [shared prefix pages | tail]:
    prefix keys are gathered from the pool and masked to positions
    ``< offset``; the tail is causal, so its pad columns are inert
    exactly as in :func:`prefill_partial`. Tail K/V are scattered into
    the slot's own pages (pad positions route out of bounds and drop);
    the shared prefix pages are never written.

    Returns ``(logits (1, vocab) at the last real position,
    new_k_pages, new_v_pages)``.

    **Quantized resident pool** (``kv_bits`` = 8 | 4; docs/serving.md):
    tail K/V that COMPLETE a page (a full ``page_len`` chunk of the
    tail within ``true_len``) are quantized once — from exact f32, on
    the wire block grid — and scattered into the int pool with their
    scales; the partial last page goes EXACT into the per-slot f32
    tail buffer ``k_tail[.][slot]``/``v_tail[.][slot]`` (stale region
    past ``true_len`` zeroed), where decode continues writing it. The
    shared prefix is dequantized for the tail's attention; the tail
    itself attends in-register exact f32, so a cold prompt's logits and
    written values see no quantization at admission. Returns the
    extended tuple ``(logits, new_k_pages, new_v_pages, new_k_scales,
    new_v_scales, new_k_tail, new_v_tail)``."""
    b, s = tokens.shape
    n_pages = k_pages[0].shape[0]
    width = table_row.shape[0] * page_len
    offset = jnp.asarray(offset, jnp.int32)
    true_len = jnp.asarray(true_len, jnp.int32)
    positions = offset + jnp.arange(s)
    x = model.tok.apply(params["tok"], tokens)
    if getattr(model, "pos", None) is not None:
        x = x + model.pos.apply(params["pos"], positions)
    scale = 1.0 / math.sqrt(model.dim // model.n_heads)
    # attention mask over [prefix pages | tail]: prefix columns valid
    # below offset, tail columns causal (pad tail is causally inert)
    prefix_mask = jnp.broadcast_to((jnp.arange(width) < offset)[None, :],
                                   (s, width))
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))
    mask = jnp.concatenate([prefix_mask, causal], axis=1)   # (S, W+S)
    # tail scatter destinations: position offset+i lives in the slot's
    # page (offset+i)//page_len at offset (offset+i)%page_len; pad
    # positions (i >= true_len) route out of bounds and are dropped
    dest_page = table_row[jnp.clip(positions // page_len, 0,
                                   table_row.shape[0] - 1)]
    dest_off = positions % page_len
    dest = jnp.where(jnp.arange(s) < true_len, dest_page, n_pages)
    if kv_bits is not None:
        from ..ops.quant import (dequantize_page_blocks,
                                 page_block_map, pack_page_nibbles,
                                 quantize_page_blocks,
                                 unpack_page_nibbles)
        h_kv = getattr(model, "n_kv_heads", model.n_heads)
        dh = model.dim // model.n_heads
        bmap = page_block_map(h_kv, page_len, dh)
        slot = jnp.asarray(slot, jnp.int32)
        # the tail starts at a page boundary (offset is page-aligned),
        # so tail chunk c IS the slot's page offset//page_len + c; the
        # chunk is complete — quantizable — iff it lies within true_len
        n_chunks = s // page_len
        r = jnp.arange(page_len)
        # partial-page span (tail coordinates): the positions past the
        # last complete page, exact f32 into the slot's tail buffer
        floor = (offset + true_len) // page_len * page_len - offset
        t_src = jnp.clip(floor + r, 0, s - 1)
        t_valid = ((floor + r) < true_len)[None, :, None]

    new_kp, new_vp = [], []
    new_ks, new_vs, new_kt, new_vt = [], [], [], []
    for i, blk in enumerate(model.blocks):
        p = params["blocks"][i]
        hq, hk, hv = blk.attn.project_qkv(p["attn"],
                                          blk.ln1.apply(p["ln1"], x))
        hq, hk = blk.attn.maybe_rope(hq, hk, positions)
        if kv_bits is None:
            kp = k_pages[i].at[dest, :, dest_off].set(
                jnp.moveaxis(hk[0], 1, 0).astype(k_pages[i].dtype),
                mode="drop")
            vp = v_pages[i].at[dest, :, dest_off].set(
                jnp.moveaxis(hv[0], 1, 0).astype(v_pages[i].dtype),
                mode="drop")
        else:
            kp, vp = k_pages[i], v_pages[i]
            ks_i, vs_i = k_scales[i], v_scales[i]
            for c in range(n_chunks):
                lo = c * page_len
                ck = hk[0, :, lo:lo + page_len, :].astype(jnp.float32)
                cv = hv[0, :, lo:lo + page_len, :].astype(jnp.float32)
                qk, sk = quantize_page_blocks(ck, kv_bits)
                qv, sv = quantize_page_blocks(cv, kv_bits)
                if kv_bits == 4:
                    qk, qv = (pack_page_nibbles(qk),
                              pack_page_nibbles(qv))
                # incomplete chunks route out of bounds and drop; the
                # page index gather clamps harmlessly for them
                comp = (lo + page_len) <= true_len
                dpi = jnp.where(
                    comp,
                    table_row[jnp.clip(offset // page_len + c, 0,
                                       table_row.shape[0] - 1)],
                    n_pages)
                kp = kp.at[dpi].set(qk, mode="drop")
                vp = vp.at[dpi].set(qv, mode="drop")
                ks_i = ks_i.at[dpi].set(sk, mode="drop")
                vs_i = vs_i.at[dpi].set(sv, mode="drop")
            tk = jnp.where(t_valid,
                           jnp.take(hk[0], t_src, axis=1), 0.0) \
                .astype(jnp.float32)
            tv = jnp.where(t_valid,
                           jnp.take(hv[0], t_src, axis=1), 0.0) \
                .astype(jnp.float32)
            kt = k_tail[i].at[slot].set(tk)
            vt = v_tail[i].at[slot].set(tv)
            new_ks.append(ks_i)
            new_vs.append(vs_i)
            new_kt.append(kt)
            new_vt.append(vt)
        new_kp.append(kp)
        new_vp.append(vp)
        # prefix keys from the (updated) pool; tail keys inline — the
        # tail pages were just written, but using the in-register tail
        # avoids a second gather and keeps the math identical to
        # prefill_partial's [real | pad] layout
        if kv_bits is not None:
            # dequantize the gathered prefix pages (the mask exposes
            # only positions < offset — complete, quantized, shared);
            # the tail attends in-register EXACT, so cold admissions
            # (offset == 0) see zero quantization error
            gk, gv = kp[table_row], vp[table_row]
            if kv_bits == 4:
                gk, gv = unpack_page_nibbles(gk), unpack_page_nibbles(gv)
            gk = dequantize_page_blocks(gk, ks_i[table_row], bmap)
            gv = dequantize_page_blocks(gv, vs_i[table_row], bmap)
            pref_k = gk.transpose(1, 0, 2, 3) \
                .reshape(1, -1, width, gk.shape[-1]).astype(hk.dtype)
            pref_v = gv.transpose(1, 0, 2, 3) \
                .reshape(1, -1, width, gv.shape[-1]).astype(hv.dtype)
        else:
            pref_k = kp[table_row].transpose(1, 0, 2, 3) \
                .reshape(1, -1, width, kp.shape[-1]).astype(hk.dtype)
            pref_v = vp[table_row].transpose(1, 0, 2, 3) \
                .reshape(1, -1, width, vp.shape[-1]).astype(hv.dtype)
        k_all = jnp.concatenate([pref_k, hk], axis=2)   # (1,Hkv,W+S,Dh)
        v_all = jnp.concatenate([pref_v, hv], axis=2)
        bq, hh, _, dd = hq.shape
        hkv = k_all.shape[1]
        hq_g = hq.reshape(bq, hkv, hh // hkv, s, dd)
        logits = jnp.einsum("bngqd,bnkd->bngqk", hq_g, k_all).astype(
            jnp.float32) * scale                     # (1,Hkv,g,S,W+S)
        logits = jnp.where(mask[None, None, None, :, :], logits,
                           -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1).astype(v_all.dtype)
        o = jnp.einsum("bngqk,bnkd->bngqd", probs, v_all) \
            .reshape(bq, hh, s, dd)
        x = x + blk.attn.project_out(p["attn"], o)
        x = x + blk.mlp(p, x)

    x_last = jax.lax.dynamic_slice_in_dim(x, true_len - 1, 1, axis=1)
    x_last = model.ln_f.apply(params["ln_f"], x_last)
    logits = model.project_vocab(params, x_last)[:, 0]
    if kv_bits is None:
        return logits, new_kp, new_vp
    return logits, new_kp, new_vp, new_ks, new_vs, new_kt, new_vt


def spec_verify_slots(model: TransformerLM, params: Params, ks, vs,
                      lengths, tokens) -> Tuple[jnp.ndarray, list, list]:
    """Speculative-decoding VERIFY over a contiguous slot pool
    (``serve/spec/``): score all k+1 candidate positions of every row
    in ONE batched forward, without writing the pool.

    ``tokens`` (B, S) int32 is per row ``[cur, d_1 .. d_k]`` — the
    slot's current (last-emitted, not-yet-cached) token followed by its
    k draft proposals; S = k + 1. Row b's queries run at global
    positions ``lengths[b] + j`` and attend over [pool row masked to
    positions < lengths[b] | causal in-register candidate block] — the
    same [resident | inline] layout as :func:`prefill_partial`, so the
    position-j logits equal what j sequential :func:`decode_step_slots`
    calls would produce (to the usual ~1-ulp batching tolerance; greedy
    token streams are the asserted contract, per PR 3).

    READ-ONLY with respect to the pool: nothing is scattered, so a
    rejected suffix needs no rewind — acceptance is decided on the host
    and only the accepted prefix is ever written, by
    :func:`spec_commit_slots`, from the returned scratch K/V.

    Returns ``(logits (B, S, vocab), sk, sv)`` where sk/sv are
    per-layer (B, Hkv, S, Dh) f32 EXACT candidate K/V (position j holds
    the key of ``tokens[:, j]`` at ``lengths + j``)."""
    b, s = tokens.shape
    idx = lengths
    width = ks[0].shape[2]
    positions = idx[:, None] + jnp.arange(s)[None, :]          # (B, S)
    x = model.tok.apply(params["tok"], tokens)
    if getattr(model, "pos", None) is not None:
        # discarded over-length positions may clip into the learned
        # table's last row — harmless, their logits are never accepted
        x = x + model.pos.apply(params["pos"], positions)
    scale = 1.0 / math.sqrt(model.dim // model.n_heads)
    prefix_mask = jnp.broadcast_to(
        (jnp.arange(width)[None, :] < idx[:, None])[:, None, :],
        (b, s, width))
    causal = jnp.broadcast_to(
        jnp.tril(jnp.ones((s, s), dtype=bool))[None], (b, s, s))
    mask = jnp.concatenate([prefix_mask, causal], axis=2)  # (B,S,W+S)

    sk_out, sv_out = [], []
    for i, blk in enumerate(model.blocks):
        p = params["blocks"][i]
        hq, hk, hv = blk.attn.project_qkv(p["attn"],
                                          blk.ln1.apply(p["ln1"], x))
        hq, hk = blk.attn.maybe_rope(hq, hk, positions[:, None, :])
        sk_out.append(hk.astype(jnp.float32))
        sv_out.append(hv.astype(jnp.float32))
        k_all = jnp.concatenate([ks[i].astype(hk.dtype), hk], axis=2)
        v_all = jnp.concatenate([vs[i].astype(hv.dtype), hv], axis=2)
        bq, hh, _, dd = hq.shape
        hkv = k_all.shape[1]
        hq_g = hq.reshape(bq, hkv, hh // hkv, s, dd)
        att = jnp.einsum("bngqd,bnkd->bngqk", hq_g, k_all).astype(
            jnp.float32) * scale
        att = jnp.where(mask[:, None, None, :, :], att, -jnp.inf)
        probs = jax.nn.softmax(att, axis=-1).astype(v_all.dtype)
        o = jnp.einsum("bngqk,bnkd->bngqd", probs, v_all) \
            .reshape(bq, hh, s, dd)
        x = x + blk.attn.project_out(p["attn"], o)
        x = x + blk.mlp(p, x)

    x = model.ln_f.apply(params["ln_f"], x)
    return model.project_vocab(params, x), sk_out, sv_out


def spec_commit_slots(ks, vs, lengths, sk, sv,
                      commit) -> Tuple[list, list, jnp.ndarray]:
    """Scatter the ACCEPTED prefix of a verify's scratch K/V into a
    contiguous slot pool (``serve/spec/`` — the write half
    :func:`spec_verify_slots` deliberately does not do).

    ``commit`` (B,) int32 is the per-row accepted position count e
    (0 = the row took no part in this spec iteration): scratch
    positions ``0 .. e-1`` land at pool positions ``lengths + 0 ..
    lengths + e - 1`` and the rejected suffix is simply never written —
    rollback by construction, no rewind. Returns ``(new_ks, new_vs,
    lengths + commit)``."""
    s = sk[0].shape[2]
    width = ks[0].shape[2]
    new_k, new_v = list(ks), list(vs)
    for j in range(s):
        committed = j < commit                              # (B,)
        wm = ((jnp.arange(width)[None, :] == (lengths + j)[:, None])
              & committed[:, None])[:, None, :, None]       # (B,1,W,1)
        for i in range(len(new_k)):
            kj = sk[i][:, :, j:j + 1, :].astype(new_k[i].dtype)
            vj = sv[i][:, :, j:j + 1, :].astype(new_v[i].dtype)
            new_k[i] = jnp.where(wm, kj, new_k[i])
            new_v[i] = jnp.where(wm, vj, new_v[i])
    return new_k, new_v, lengths + commit


def spec_verify_slots_paged(model: TransformerLM, params: Params,
                            k_pages, v_pages, tables, lengths, tokens,
                            *, page_len: int, kv_bits=None,
                            k_scales=None, v_scales=None,
                            k_tail=None, v_tail=None
                            ) -> Tuple[jnp.ndarray, list, list]:
    """Paged twin of :func:`spec_verify_slots`: batched k+1-position
    verify over a PAGED slot pool, read-only.

    Resident keys come from a dense page gather over each row's table
    (the verify runs once per engine iteration over a short candidate
    block, so the gather is amortized over k+1 scored positions; a
    blockwise verify kernel is future work — docs/serving.md). In a
    quantized pool (``kv_bits`` = 8 | 4) the gathered pages are
    dequantized and each row's PARTIAL current page is overlaid from
    its exact f32 tail buffer — the pool row for an incomplete page was
    never written, exactly as in ``paged_decode_attention``.

    Returns ``(logits (B, S, vocab), sk, sv)`` — the same exact-f32
    scratch contract as the contiguous verify; committing (and, on page
    completion, quantizing) accepted positions belongs to
    :func:`spec_commit_slots_paged`."""
    b, s = tokens.shape
    idx = lengths
    width = tables.shape[1] * page_len
    positions = idx[:, None] + jnp.arange(s)[None, :]          # (B, S)
    x = model.tok.apply(params["tok"], tokens)
    if getattr(model, "pos", None) is not None:
        x = x + model.pos.apply(params["pos"], positions)
    scale = 1.0 / math.sqrt(model.dim // model.n_heads)
    prefix_mask = jnp.broadcast_to(
        (jnp.arange(width)[None, :] < idx[:, None])[:, None, :],
        (b, s, width))
    causal = jnp.broadcast_to(
        jnp.tril(jnp.ones((s, s), dtype=bool))[None], (b, s, s))
    mask = jnp.concatenate([prefix_mask, causal], axis=2)  # (B,S,W+S)
    if kv_bits is not None:
        from ..ops.quant import (dequantize_page_blocks, page_block_map,
                                 unpack_page_nibbles)
        h_kv = getattr(model, "n_kv_heads", model.n_heads)
        dh = model.dim // model.n_heads
        bmap = page_block_map(h_kv, page_len, dh)
        # positions on a row's CURRENT (partial) page read the slot's
        # exact f32 tail buffer; the mask hides everything >= lengths,
        # so a just-completed page never exposes stale tail values
        jcol = jnp.arange(width)
        tail_sel = ((jcol[None, :] // page_len)
                    == (idx[:, None] // page_len))[:, None, :, None]
        toff = jcol % page_len                      # static (W,) index

    sk_out, sv_out = [], []
    for i, blk in enumerate(model.blocks):
        p = params["blocks"][i]
        hq, hk, hv = blk.attn.project_qkv(p["attn"],
                                          blk.ln1.apply(p["ln1"], x))
        hq, hk = blk.attn.maybe_rope(hq, hk, positions[:, None, :])
        sk_out.append(hk.astype(jnp.float32))
        sv_out.append(hv.astype(jnp.float32))
        if kv_bits is None:
            gk = _gather_pages(k_pages[i], tables).astype(hk.dtype)
            gv = _gather_pages(v_pages[i], tables).astype(hv.dtype)
        else:
            qk, qv = k_pages[i][tables], v_pages[i][tables]
            if kv_bits == 4:
                qk, qv = unpack_page_nibbles(qk), unpack_page_nibbles(qv)
            dk = dequantize_page_blocks(qk, k_scales[i][tables], bmap)
            dv = dequantize_page_blocks(qv, v_scales[i][tables], bmap)
            bb, pp, hh_kv, ll, dd_h = dk.shape
            gk = dk.transpose(0, 2, 1, 3, 4).reshape(bb, hh_kv,
                                                     pp * ll, dd_h)
            gv = dv.transpose(0, 2, 1, 3, 4).reshape(bb, hh_kv,
                                                     pp * ll, dd_h)
            gk = jnp.where(tail_sel, k_tail[i][:, :, toff, :], gk) \
                .astype(hk.dtype)
            gv = jnp.where(tail_sel, v_tail[i][:, :, toff, :], gv) \
                .astype(hv.dtype)
        k_all = jnp.concatenate([gk, hk], axis=2)
        v_all = jnp.concatenate([gv, hv], axis=2)
        bq, hh, _, dd = hq.shape
        hkv = k_all.shape[1]
        hq_g = hq.reshape(bq, hkv, hh // hkv, s, dd)
        att = jnp.einsum("bngqd,bnkd->bngqk", hq_g, k_all).astype(
            jnp.float32) * scale
        att = jnp.where(mask[:, None, None, :, :], att, -jnp.inf)
        probs = jax.nn.softmax(att, axis=-1).astype(v_all.dtype)
        o = jnp.einsum("bngqk,bnkd->bngqd", probs, v_all) \
            .reshape(bq, hh, s, dd)
        x = x + blk.attn.project_out(p["attn"], o)
        x = x + blk.mlp(p, x)

    x = model.ln_f.apply(params["ln_f"], x)
    return model.project_vocab(params, x), sk_out, sv_out


def spec_commit_slots_paged(k_pages, v_pages, tables, lengths, sk, sv,
                            commit, *, page_len: int, kv_bits=None,
                            k_scales=None, v_scales=None,
                            k_tail=None, v_tail=None):
    """Paged twin of :func:`spec_commit_slots`: scatter each row's
    accepted scratch prefix into its pages.

    Position ``lengths[b] + j`` lands in page ``tables[b, (lengths[b] +
    j) // page_len]`` at offset ``(lengths[b] + j) % page_len``;
    rejected positions (``j >= commit[b]``) route out of bounds and
    drop, so a page can only ever COMPLETE from accepted tokens. In a
    quantized pool each accepted position is first written to the
    slot's exact f32 tail buffer, and whenever a write fills offset
    ``page_len - 1`` the whole tail is quantized ONCE — from exact
    values, on the wire block grid — and scattered with its scales,
    preserving the PR 16 quantize-once discipline token-for-token with
    the non-speculative decode path. Returns ``(new_k_pages,
    new_v_pages)`` (+ scales and tails in quant mode); advancing the
    host ``lengths`` by ``commit`` is the caller's business."""
    s = sk[0].shape[2]
    n_pages = k_pages[0].shape[0]
    n_tables = tables.shape[1]
    bsz = lengths.shape[0]
    kp, vp = list(k_pages), list(v_pages)
    if kv_bits is not None:
        from ..ops.quant import pack_page_nibbles, quantize_page_blocks
        ksc, vsc = list(k_scales), list(v_scales)
        kt, vt = list(k_tail), list(v_tail)
        n_tail = k_tail[0].shape[0]
    for j in range(s):
        committed = j < commit                              # (B,)
        pos = lengths + j
        wp = jnp.take_along_axis(
            tables, jnp.clip(pos // page_len, 0, n_tables - 1)[:, None],
            axis=1)[:, 0]
        wo = pos % page_len
        if kv_bits is None:
            dest = jnp.where(committed, wp, n_pages)
            for i in range(len(kp)):
                kp[i] = kp[i].at[dest, :, wo].set(
                    sk[i][:, :, j, :].astype(kp[i].dtype), mode="drop")
                vp[i] = vp[i].at[dest, :, wo].set(
                    sv[i][:, :, j, :].astype(vp[i].dtype), mode="drop")
        else:
            dest_t = jnp.where(committed, jnp.arange(bsz), n_tail)
            completed = jnp.logical_and(committed, wo == page_len - 1)
            dest_q = jnp.where(completed, wp, n_pages)
            for i in range(len(kp)):
                kt[i] = kt[i].at[dest_t, :, wo].set(
                    sk[i][:, :, j, :].astype(jnp.float32), mode="drop")
                vt[i] = vt[i].at[dest_t, :, wo].set(
                    sv[i][:, :, j, :].astype(jnp.float32), mode="drop")
                qk, sc_k = quantize_page_blocks(kt[i], kv_bits)
                qv, sc_v = quantize_page_blocks(vt[i], kv_bits)
                if kv_bits == 4:
                    qk, qv = pack_page_nibbles(qk), pack_page_nibbles(qv)
                kp[i] = kp[i].at[dest_q].set(qk, mode="drop")
                vp[i] = vp[i].at[dest_q].set(qv, mode="drop")
                ksc[i] = ksc[i].at[dest_q].set(sc_k, mode="drop")
                vsc[i] = vsc[i].at[dest_q].set(sc_v, mode="drop")
    if kv_bits is None:
        return kp, vp
    return kp, vp, ksc, vsc, kt, vt


def _sample(logits, rng, temperature: float, top_k: Optional[int],
            top_p: Optional[float] = None):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        vals, _ = jax.lax.top_k(logits, top_k)
        cutoff = vals[..., -1:]
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    if top_p is not None:
        # nucleus sampling: keep the smallest prefix of the
        # probability-sorted vocab whose mass reaches top_p (the token
        # that CROSSES the threshold stays — cum - p < top_p — so at
        # least one survives even for tiny top_p)
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < top_p
        # clamp: top_p == 0.0 would keep zero tokens and the -1 index
        # would WRAP to the smallest logit, silently disabling filtering
        kept = jnp.maximum(jnp.sum(keep_sorted, axis=-1, keepdims=True), 1)
        cutoff = jnp.take_along_axis(sorted_logits, kept - 1, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def generate(model: TransformerLM, params: Params, prompt, max_new: int,
             *, temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             rng=None, max_len: Optional[int] = None) -> jnp.ndarray:
    """Generate ``max_new`` tokens after ``prompt`` ((B, S) int32).

    temperature=0 is greedy; otherwise softmax sampling with optional
    top-k. Returns (B, max_new) int32. The decode loop is one
    ``lax.scan`` — jit :func:`make_generate_fn`'s product to cache the
    whole pipeline as two XLA programs."""
    return make_generate_fn(model, max_new, temperature=temperature,
                            top_k=top_k, top_p=top_p, max_len=max_len)(
        params, prompt, rng if rng is not None else jax.random.PRNGKey(0))


def _model_window(model: TransformerLM) -> Optional[int]:
    """The model's uniform sliding-window width, or None.

    A model built with ``make_flash_attn_fn(window=W)`` advertises W on
    every block's attn_fn; a uniform W switches decode to the rolling
    O(W)-memory cache that reproduces the window exactly. Mixed widths
    are not a cache layout this path can serve."""
    widths = {getattr(blk.attn.attn_fn, "window", None)
              for blk in model.blocks}
    if widths == {None} or not model.blocks:
        return None
    if len(widths) == 1:
        return next(iter(widths))
    raise ValueError(f"blocks disagree on attention window ({sorted(map(str, widths))}); "
                     "cached decode needs a uniform width")


def _check_attn_compatible(model: TransformerLM,
                           allow_custom_attn: bool) -> None:
    """Decode attends over the cache with an inline softmax(qk)v — exact
    for the dense core, for dense-equivalent kernels (flash attention
    marks itself ``dense_equivalent``), and for uniform sliding-window
    kernels (served by the rolling cache). Refuse behavior-changing
    custom cores (biased, ring islands) unless the caller explicitly
    opts in."""
    if allow_custom_attn:
        return
    for blk in model.blocks:
        f = blk.attn.attn_fn
        if (f is dense_attention or getattr(f, "dense_equivalent", False)
                or getattr(f, "window", None) is not None):
            continue
        raise ValueError(
            "model was built with a custom attn_fn whose semantics the "
            "cached decode path cannot reproduce; pass "
            "allow_custom_attn=True only if the core computes standard "
            "softmax(q k^T * scale) v")


def make_generate_fn(model: TransformerLM, max_new: int, *,
                     temperature: float = 0.0, top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     max_len: Optional[int] = None,
                     allow_custom_attn: bool = False,
                     pin_weight_stream: bool = False,
                     param_shardings=None):
    """Build ``fn(params, prompt, rng) -> (B, max_new) tokens`` suitable
    for ``jax.jit`` (all shape-determining arguments are closed over).

    ``param_shardings``: the producer's params out-shardings (a train
    step's ``out_shardings["params"]`` — docs/front_door.md). When set,
    the returned fn asserts the params it receives already carry them
    (``parallel.front_door.verify_handoff``): the eval/prefill entry of
    the reshard-free pjit-to-pjit chain — a mismatch raises a typed
    ``HandoffMismatch`` instead of pjit silently copying the weights.
    The check runs on CONCRETE params — i.e. on eager calls of the
    returned fn (tracers carry no sharding on this jax). If you wrap
    fn in ``jax.jit`` yourself, run ``verify_handoff(params,
    param_shardings)`` once before the first call — that is exactly
    what ``serve.EngineConfig(param_shardings=)`` does at engine
    construction, the production admit path.

    ``pin_weight_stream``: ties the params consumed by each decode step
    to the loop-varying cache counter through an optimization barrier,
    so weight-DERIVED tensors cannot be hoisted out of the scan by
    loop-invariant code motion. Matters for int8 weights
    (``ops/quant.py``): if XLA hoists the dequantized bf16 copy, every
    step streams bf16 and the bandwidth win of storing int8 evaporates;
    pinned, each step re-derives from the int8 bytes (dequant fuses into
    the consuming matmul). Costs nothing when weights are un-quantized
    except disabling that same hoisting — benchmark both
    (benchmarks/decode_tpu.py measures the pinned arm against the plain
    int8 arm to show which way XLA went).

    A model built with a uniform sliding window decodes through the
    ROLLING cache automatically: W slots, position p at slot p % W —
    exact window semantics in O(window) memory however long generation
    runs."""
    _check_attn_compatible(model, allow_custom_attn)
    window = _model_window(model)

    def fn(params, prompt, rng):
        if param_shardings is not None and not isinstance(
                jax.tree_util.tree_leaves(params)[0], jax.core.Tracer):
            from ..parallel.front_door import verify_handoff
            verify_handoff(params, param_shardings,
                           what="generate params")
        s = prompt.shape[1]
        limit = max_len or (s + max_new)
        if limit > model.max_seq:
            raise ValueError(
                f"cache length {limit} (prompt {s} + max_new {max_new} "
                f"or explicit max_len) exceeds the model's max_seq "
                f"({model.max_seq})")
        if window is None and s + max_new > limit:
            raise ValueError(
                f"max_len {limit} cannot hold prompt ({s}) + max_new "
                f"({max_new}) tokens — the cache would wrap and corrupt")
        if (window is not None and getattr(model, "pos", None) is not None
                and s + max_new > model.max_seq):
            # the rolling cache is unbounded but LEARNED position
            # embeddings are not: past max_seq the table gather would
            # clip and silently reuse the last row. rope/none have no
            # such ceiling.
            raise ValueError(
                f"prompt ({s}) + max_new ({max_new}) exceeds max_seq "
                f"({model.max_seq}): learned position embeddings cannot "
                "extrapolate past their table even under a sliding "
                "window (use pos='rope' for unbounded generation)")
        # never allocate more slots than positions can exist: a window
        # wider than the whole run degenerates to the plain layout size
        # with identical semantics (nothing is ever evicted). s+max_new
        # (not max_len) is the bound — an explicit small max_len must
        # not silently shrink the semantic window.
        w_eff = None if window is None else min(window, s + max_new)
        rng_first, *step_rngs = jax.random.split(rng, max_new)
        logits, cache = prefill(model, params, prompt, limit,
                                window=w_eff)
        first = _sample(logits, rng_first, temperature, top_k, top_p)

        def body(carry, step_rng):
            cache, token = carry
            p = params
            if pin_weight_stream:
                p, _ = jax.lax.optimization_barrier((params, cache.length))
            logits, cache = decode_step(model, p, cache, token,
                                        window=w_eff)
            nxt = _sample(logits, step_rng, temperature, top_k, top_p)
            return (cache, nxt), nxt

        if max_new == 1:
            return first[:, None]
        (_, _), rest = jax.lax.scan(body, (cache, first),
                                    jnp.stack(step_rngs))
        return jnp.concatenate([first[:, None], jnp.moveaxis(rest, 0, 1)],
                               axis=1)                        # (B, max_new)

    return fn
