"""The reference workload's model: a 2-layer no-activation MLP
(``min_DDP.py:41-49``: Linear(in→hidden) → Linear(hidden→classes))."""

from __future__ import annotations

import jax

from ..nn.core import Linear, Module, Params, Sequential


class DummyModel(Module):
    """Linear → Linear, no activation between — exactly the reference's
    ``DummyModel`` shape (``min_DDP.py:44-48``), in_dim defaulting to the
    scalar-feature dataset's 1."""

    def __init__(self, in_dim: int = 1, hidden_dim: int = 32,
                 n_classes: int = 4):
        self.net = Sequential([
            ("lin1", Linear(in_dim, hidden_dim)),
            ("lin2", Linear(hidden_dim, n_classes)),
        ])

    def init(self, key) -> Params:
        return self.net.init(key)

    def apply(self, params: Params, x, **kwargs):
        return self.net.apply(params, x, **kwargs)
