"""Mixture-of-experts Transformer LM — the expert-parallel flagship
variant: TransformerLM blocks with the dense MLP swapped for a Switch
MoE layer (parallel/moe.py), experts sharded over the ``ep`` mesh axis."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..nn.attention import MultiHeadAttention
from ..nn.core import Embedding, LayerNorm, Linear, Module, Params
from ..parallel.moe import MoELayer, moe_param_specs
from jax.sharding import PartitionSpec as P


class MoEBlock(Module):
    """Pre-norm block with MoE MLP: x + MHA(LN(x)); x + MoE(LN(x))."""

    def __init__(self, dim: int, n_heads: int, n_experts: int,
                 mlp_ratio: int = 4, *, causal: bool = True,
                 capacity_factor: float = 2.0, top_k: int = 1,
                 router_z_coef: float = 0.1, router: str = "tokens",
                 n_shared_experts: int = 0,
                 n_kv_heads: Optional[int] = None, rope: bool = False,
                 attn_fn: Optional[Callable] = None, dtype=jnp.float32):
        self.ln1 = LayerNorm(dim, dtype=dtype)
        self.attn = MultiHeadAttention(dim, n_heads, causal=causal,
                                       n_kv_heads=n_kv_heads, rope=rope,
                                       attn_fn=attn_fn, dtype=dtype)
        self.ln2 = LayerNorm(dim, dtype=dtype)
        self.router_z_coef = router_z_coef
        self.moe = MoELayer(dim, n_experts, mlp_ratio,
                            capacity_factor=capacity_factor, top_k=top_k,
                            router=router,
                            n_shared_experts=n_shared_experts,
                            dtype=dtype)

    def init(self, key) -> Params:
        ks = jax.random.split(key, 3)
        return {"ln1": self.ln1.init(ks[0]), "attn": self.attn.init(ks[1]),
                "ln2": self.ln2.init(ks[2]), "moe": self.moe.init(ks[2])}

    def apply_with_metrics(self, params: Params, x, *, positions=None, **_):
        """(y, router metrics dict incl. the combined trainable ``aux``)."""
        x = x + self.attn.apply(params["attn"],
                                self.ln1.apply(params["ln1"], x),
                                positions=positions)
        h, m = self.moe.apply_with_metrics(params["moe"],
                                           self.ln2.apply(params["ln2"], x))
        # trainable aux = load-balancing loss + router z-loss, with
        # router_z_coef weighting z RELATIVE to the load loss (callers
        # scale the combined aux into their loss — e.g. loss + 0.01*aux
        # with the 0.1 default lands on ST-MoE's 0.01*load + 0.001*z)
        m = dict(m, aux=m["aux_loss"] + self.router_z_coef * m["z_loss"])
        return x + h, m

    def apply(self, params: Params, x, **kw):
        y, m = self.apply_with_metrics(params, x, **kw)
        return y, m["aux"]


class MoETransformerLM(Module):
    """Decoder-only LM with MoE MLPs; apply returns (logits, aux_loss)."""

    def __init__(self, vocab: int = 256, dim: int = 128, n_layers: int = 2,
                 n_heads: int = 4, n_experts: int = 4, max_seq: int = 512,
                 mlp_ratio: int = 4, capacity_factor: float = 2.0,
                 top_k: int = 1, router_z_coef: float = 0.1,
                 router: str = "tokens", n_shared_experts: int = 0,
                 n_kv_heads: Optional[int] = None, pos: str = "learned",
                 attn_fn: Optional[Callable] = None, dtype=jnp.float32):
        if pos not in ("learned", "rope", "none"):
            raise ValueError(f"pos must be learned|rope|none, got {pos!r}")
        self.vocab = vocab
        self.dim = dim
        self.n_layers = n_layers
        self.n_experts = n_experts
        self.n_shared_experts = n_shared_experts
        self.pos_kind = pos
        # dimension-aware table init (std 1/sqrt(dim)), matching
        # TransformerLM's tables — an intentional init change from the
        # earlier unit-std draws (better-conditioned; no tying here)
        self.tok = Embedding(vocab, dim, std=dim ** -0.5, dtype=dtype)
        self.pos = Embedding(max_seq, dim, std=dim ** -0.5, dtype=dtype) \
            if pos == "learned" else None
        self.blocks = [
            MoEBlock(dim, n_heads, n_experts, mlp_ratio,
                     capacity_factor=capacity_factor, top_k=top_k,
                     router_z_coef=router_z_coef, router=router,
                     n_shared_experts=n_shared_experts,
                     n_kv_heads=n_kv_heads,
                     rope=(pos == "rope"), attn_fn=attn_fn,
                     dtype=dtype)
            for _ in range(n_layers)
        ]
        self.ln_f = LayerNorm(dim, dtype=dtype)
        self.head = Linear(dim, vocab, bias=False, dtype=dtype)

    def init(self, key) -> Params:
        ks = jax.random.split(key, self.n_layers + 3)
        p = {
            "tok": self.tok.init(ks[0]),
            "blocks": [b.init(k) for b, k in zip(self.blocks, ks[2:-1])],
            "ln_f": self.ln_f.init(ks[-1]),
            "head": self.head.init(ks[-1]),
        }
        if self.pos is not None:
            p["pos"] = self.pos.init(ks[1])
        return p

    def apply_with_metrics(self, params: Params, tokens, *, pos_offset=0,
                           positions=None, **_):
        """(logits, aux_loss, metrics): metrics averages the per-layer
        router diagnostics (``drop_rate``, ``z_loss``, ``aux_loss``,
        ``expert_load``) so capacity_factor/top_k can be tuned from the
        training loop without bypassing the model API. ``positions``
        overrides the position ids — the permuted-layout contract shared
        with :class:`..models.transformer.TransformerLM` (striped
        sequence parallelism, ``parallel.sequence.stripe_tokens``)."""
        b, s = tokens.shape
        x = self.tok.apply(params["tok"], tokens)
        if positions is None:
            positions = pos_offset + jnp.arange(s)
        if self.pos is not None:
            x = x + self.pos.apply(params["pos"], positions)
        per_layer = []
        for i, blk in enumerate(self.blocks):
            x, m = blk.apply_with_metrics(params["blocks"][i], x,
                                          positions=positions)
            per_layer.append(m)
        x = self.ln_f.apply(params["ln_f"], x)
        metrics = {k: sum(m[k] for m in per_layer) / self.n_layers
                   for k in per_layer[0]}
        return (self.head.apply(params["head"], x), metrics.pop("aux"),
                metrics)

    def apply(self, params: Params, tokens, **kw):
        logits, aux, _ = self.apply_with_metrics(params, tokens, **kw)
        return logits, aux

    def param_specs(self, ep_axis: str = "ep", tp_axis: str = "tp"):
        """PartitionSpec tree: attention tensor-parallel over ``tp``,
        experts over ``ep``."""
        t = tp_axis

        def block_specs():
            return {
                "ln1": {"scale": P(), "bias": P()},
                "attn": {"qkv": {"w": P(None, t), "b": P(t)},
                         "out": {"w": P(t, None), "b": P()}},
                "ln2": {"scale": P(), "bias": P()},
                "moe": moe_param_specs(
                    ep_axis=ep_axis, tp_axis=t,
                    n_shared_experts=self.n_shared_experts),
            }

        specs = {
            "tok": {"emb": P()},
            "blocks": [block_specs() for _ in range(self.n_layers)],
            "ln_f": {"scale": P(), "bias": P()},
            "head": {"w": P(None, t)},
        }
        if self.pos is not None:
            specs["pos"] = {"emb": P()}
        return specs
