"""ResNet-18 — the vision rung of the ladder (BASELINE.md: ResNet-18 on
CIFAR-10), NHWC/TPU-native (see nn/conv.py for the layout rationale).

Structure matches torchvision resnet18: 7x7/2 stem + maxpool, four stages
of two BasicBlocks (64/128/256/512, stride 2 from stage 2), global average
pool, fc. ``small_input=True`` swaps the stem for the common CIFAR variant
(3x3/1, no maxpool). BatchNorm running stats thread through an explicit
state pytree: ``init(key) -> (params, state)``,
``apply(params, x, state=state, train=...) -> (logits, new_state)`` —
per-device batch statistics under DP by default, matching torch DDP's
default (unsynced) BatchNorm; ``sync_bn=True`` computes batch statistics
over the global batch across the ``dp`` axis (torch ``nn.SyncBatchNorm``),
which matters at small per-device batches.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..nn.conv import BatchNorm2d, Conv2d, global_avg_pool, max_pool
from ..nn.core import Linear, Module, Params, relu


class BasicBlock(Module):
    def __init__(self, in_ch: int, out_ch: int, stride: int = 1,
                 bn_axis: str = None):
        self.conv1 = Conv2d(in_ch, out_ch, 3, stride=stride, padding=1)
        self.bn1 = BatchNorm2d(out_ch, axis_name=bn_axis)
        self.conv2 = Conv2d(out_ch, out_ch, 3, stride=1, padding=1)
        self.bn2 = BatchNorm2d(out_ch, axis_name=bn_axis)
        self.downsample = None
        if stride != 1 or in_ch != out_ch:
            self.downsample = (Conv2d(in_ch, out_ch, 1, stride=stride),
                               BatchNorm2d(out_ch, axis_name=bn_axis))

    def init(self, key) -> Params:
        ks = jax.random.split(key, 3)
        p = {"conv1": self.conv1.init(ks[0]), "bn1": self.bn1.init(ks[0]),
             "conv2": self.conv2.init(ks[1]), "bn2": self.bn2.init(ks[1])}
        if self.downsample is not None:
            p["ds_conv"] = self.downsample[0].init(ks[2])
            p["ds_bn"] = self.downsample[1].init(ks[2])
        return p

    def init_state(self):
        s = {"bn1": self.bn1.init_state(), "bn2": self.bn2.init_state()}
        if self.downsample is not None:
            s["ds_bn"] = self.downsample[1].init_state()
        return s

    def apply(self, params: Params, x, *, state=None, train: bool = False, **_):
        s = state or {}
        ns = {}
        h = self.conv1.apply(params["conv1"], x)
        h, ns["bn1"] = self.bn1.apply(params["bn1"], h,
                                      state=s.get("bn1"), train=train)
        h = relu(h)
        h = self.conv2.apply(params["conv2"], h)
        h, ns["bn2"] = self.bn2.apply(params["bn2"], h,
                                      state=s.get("bn2"), train=train)
        idn = x
        if self.downsample is not None:
            idn = self.downsample[0].apply(params["ds_conv"], x)
            idn, ns["ds_bn"] = self.downsample[1].apply(
                params["ds_bn"], idn, state=s.get("ds_bn"), train=train)
        return relu(h + idn), ns


class ResNet18(Module):
    def __init__(self, n_classes: int = 10, in_ch: int = 3,
                 small_input: bool = False, sync_bn: bool = False,
                 bn_axis: str = "dp"):
        self.small_input = small_input
        axis = bn_axis if sync_bn else None
        if small_input:
            self.stem = Conv2d(in_ch, 64, 3, stride=1, padding=1)
        else:
            self.stem = Conv2d(in_ch, 64, 7, stride=2, padding=3)
        self.bn_stem = BatchNorm2d(64, axis_name=axis)
        cfg = [(64, 64, 1), (64, 128, 2), (128, 256, 2), (256, 512, 2)]
        self.stages = []
        for (cin, cout, stride) in cfg:
            self.stages.append([BasicBlock(cin, cout, stride, bn_axis=axis),
                                BasicBlock(cout, cout, 1, bn_axis=axis)])
        self.fc = Linear(512, n_classes)

    def init(self, key) -> Tuple[Params, dict]:
        ks = jax.random.split(key, 10)
        params = {"stem": self.stem.init(ks[0]),
                  "bn_stem": self.bn_stem.init(ks[0]),
                  "fc": self.fc.init(ks[1])}
        state = {"bn_stem": self.bn_stem.init_state()}
        i = 2
        for si, stage in enumerate(self.stages):
            for bi, blk in enumerate(stage):
                name = f"s{si}b{bi}"
                params[name] = blk.init(ks[i])
                state[name] = blk.init_state()
                i += 1
        return params, state

    def apply(self, params: Params, x, *, state=None, train: bool = False, **_):
        """x: (N, H, W, C) → (logits (N, classes), new_state)."""
        s = state or {}
        ns = {}
        h = self.stem.apply(params["stem"], x)
        h, ns["bn_stem"] = self.bn_stem.apply(params["bn_stem"], h,
                                              state=s.get("bn_stem"),
                                              train=train)
        h = relu(h)
        if not self.small_input:
            h = max_pool(h, 3, 2, padding=1)
        for si, stage in enumerate(self.stages):
            for bi, blk in enumerate(stage):
                name = f"s{si}b{bi}"
                h, ns[name] = blk.apply(params[name], h,
                                        state=s.get(name), train=train)
        h = global_avg_pool(h)
        return self.fc.apply(params["fc"], h), ns
