"""Transformer language model — the framework's flagship model.

Covers the reference ladder's 'nn.TransformerEncoder LM on WikiText-2' rung
(BASELINE.md) as a decoder-only causal LM (the modern equivalent of the
masked-encoder LM setup). Designed mesh-first: every parameter has a
tensor-parallel PartitionSpec (``parallel/tensor.py``), attention takes a
pluggable core so sequence parallelism (ring attention) drops in, and the
forward is pure static-shape jnp — one XLA program per step at any mesh
shape.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from ..nn.attention import TransformerBlock
from ..nn.core import Embedding, LayerNorm, Linear, Module, Params

#: Named per-layer rematerialization policies (docs/compute.md).
#: ``none``  — save every activation (fastest step, most HBM);
#: ``full``  — ``jax.checkpoint`` the whole block: save only the block
#:             boundary, recompute the block in backward (~1/3 more
#:             forward FLOPs for O(n_layers) less activation HBM);
#: ``dots_saveable`` — ``jax.checkpoint_policies.dots_saveable``: save
#:             matmul outputs, recompute only the cheap elementwise
#:             chain (LN/GELU/softmax) — most of ``full``'s memory win
#:             at a fraction of its recompute.
REMAT_POLICIES = ("none", "full", "dots_saveable")


def resolve_remat(remat: Union[bool, str, None]) -> str:
    """Canonical policy name for a ``remat=`` argument: bools keep
    their historical meaning (False -> ``none``, True -> ``full``),
    ``None`` defers to the typed ``DPX_REMAT`` env knob, strings must
    name a member of :data:`REMAT_POLICIES`."""
    if remat is None:
        from ..runtime import env as _env
        remat = _env.get("DPX_REMAT")
    if remat is False:
        return "none"
    if remat is True:
        return "full"
    if remat not in REMAT_POLICIES:
        raise ValueError(
            f"remat must be a bool or one of {'|'.join(REMAT_POLICIES)}, "
            f"got {remat!r}")
    return remat


def apply_remat_policy(fn: Callable, policy: str) -> Callable:
    """Wrap a per-layer forward with the named checkpoint policy — the
    ONE place a policy name becomes a ``jax.checkpoint`` call, shared
    by :class:`TransformerLM` and any custom trainer that wants the
    same vocabulary. Unknown names raise (a typo'd policy silently
    becoming a different memory/recompute tradeoff is exactly what the
    typed vocabulary exists to stop); callers with bools/None resolve
    through :func:`resolve_remat` first."""
    if policy not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {policy!r}; choose from "
            f"{'|'.join(REMAT_POLICIES)}")
    if policy == "none":
        return fn
    if policy == "full":
        return jax.checkpoint(fn)
    return jax.checkpoint(fn, policy=jax.checkpoint_policies.dots_saveable)


class TransformerLM(Module):
    """Decoder-only causal LM: tok+pos embed → N pre-norm blocks → LN →
    vocab projection."""

    def __init__(self, vocab: int = 256, dim: int = 128, n_layers: int = 2,
                 n_heads: int = 4, max_seq: int = 512, mlp_ratio: int = 4,
                 dropout: float = 0.0, n_kv_heads: Optional[int] = None,
                 pos: str = "learned", rope_base: float = 10000.0,
                 tie_embeddings: bool = False,
                 attn_fn: Optional[Callable] = None,
                 remat: Union[bool, str, None] = False,
                 dtype=jnp.float32):
        if pos not in ("learned", "rope", "none"):
            raise ValueError(f"pos must be learned|rope|none, got {pos!r}")
        self.vocab = vocab
        self.dim = dim
        self.n_layers = n_layers
        self.n_heads = n_heads
        # GQA: n_kv_heads < n_heads shrinks k/v projections and the
        # decode KV cache by the group factor (nn/attention.py)
        self.n_kv_heads = n_kv_heads if n_kv_heads is not None else n_heads
        self.max_seq = max_seq
        # named per-layer remat policy (REMAT_POLICIES); bools keep
        # their historical meaning, None defers to DPX_REMAT.
        # self.remat stays the truthy back-compat view of the policy.
        self.remat_policy = resolve_remat(remat)
        self.remat = self.remat_policy != "none"
        self.dtype = dtype
        # positional scheme: "learned" absolute table (the classic GPT-2
        # setup), "rope" rotary phases inside attention (no positional
        # parameters; extrapolates — nn/rotary.py), or "none"
        self.pos_kind = pos
        # dimension-aware table init (std 1/sqrt(dim)): behind the first
        # LayerNorm either scale trains, but with tied embeddings the
        # table IS the output projection and unit-std rows diverge
        self.tok = Embedding(vocab, dim, std=dim ** -0.5, dtype=dtype)
        self.pos = Embedding(max_seq, dim, std=dim ** -0.5, dtype=dtype) \
            if pos == "learned" else None
        self.blocks = [
            TransformerBlock(dim, n_heads, mlp_ratio, causal=True,
                             dropout=dropout, n_kv_heads=n_kv_heads,
                             rope=(pos == "rope"), rope_base=rope_base,
                             attn_fn=attn_fn, dtype=dtype)
            for _ in range(n_layers)
        ]
        self.ln_f = LayerNorm(dim, dtype=dtype)
        # tied embeddings (the GPT-2 recipe): the vocab projection reuses
        # the token table transposed — no head parameter exists
        self.tie_embeddings = tie_embeddings
        self.head = None if tie_embeddings \
            else Linear(dim, vocab, bias=False, dtype=dtype)

    def init(self, key) -> Params:
        ks = jax.random.split(key, self.n_layers + 3)
        p = {
            "tok": self.tok.init(ks[0]),
            "blocks": [b.init(k) for b, k in zip(self.blocks, ks[2:-1])],
            "ln_f": self.ln_f.init(ks[-1]),
        }
        if self.head is not None:
            p["head"] = self.head.init(ks[-1])
        if self.pos is not None:
            p["pos"] = self.pos.init(ks[1])
        return p

    def head_weight(self, params):
        """The (dim, vocab) vocab-projection matrix — the head's weight,
        or the transposed token table when ``tie_embeddings``; either may
        be int8-quantized (ops/quant.py). The input contract of
        ``ops.losses.fused_linear_cross_entropy``."""
        from ..ops.quant import resolve_weight
        if self.tie_embeddings:
            return resolve_weight(params["tok"], "emb", self.dtype).T
        return resolve_weight(params["head"], "w", self.dtype)

    def project_vocab(self, params, x):
        """Hidden states (..., dim) → logits (..., vocab). Single source
        of truth for the output projection (training apply and the cached
        decode path both route through it)."""
        return jnp.matmul(x, self.head_weight(params))

    def apply(self, params: Params, tokens, *, rng=None, train: bool = False,
              pos_offset=0, positions=None, return_hidden: bool = False,
              **_):
        """tokens: (B, S) int32 → logits (B, S, vocab).

        ``pos_offset`` shifts position ids — under sequence parallelism each
        device holds a local block whose global positions start at
        ``axis_index(sp) * S_local``. ``positions`` (S,) int overrides the
        ids entirely — the contract for PERMUTED token layouts
        (``parallel.sequence.stripe_tokens``: pass the striped ids so
        RoPE/learned embeddings see each token's true position).

        ``return_hidden=True`` returns the post-final-norm hidden states
        (B, S, dim) *instead of* logits, skipping the vocab projection — the
        input contract of ``ops.losses.fused_linear_cross_entropy`` (pass
        ``model.head_weight(params)`` as its weight), which streams the projection
        chunkwise so the full (B, S, vocab) logits never materialize."""
        b, s = tokens.shape
        x = self.tok.apply(params["tok"], tokens)
        if positions is None:
            positions = pos_offset + jnp.arange(s)
        if self.pos is not None:
            x = x + self.pos.apply(params["pos"], positions)
        for i, blk in enumerate(self.blocks):
            r = jax.random.fold_in(rng, i) if rng is not None else None

            def run_block(p, x, blk=blk, r=r):
                return blk.apply(p, x, rng=r, train=train,
                                 positions=positions)

            # per-layer remat policy: "full" recomputes the block in
            # backward instead of saving its activations (~1/3 more
            # FLOPs for O(n_layers) less activation HBM, buying batch
            # size on memory-bound configs); "dots_saveable" keeps the
            # matmul outputs and recomputes only the elementwise chain
            run_block = apply_remat_policy(run_block, self.remat_policy)
            x = run_block(params["blocks"][i], x)
        x = self.ln_f.apply(params["ln_f"], x)
        if return_hidden:
            return x
        return self.project_vocab(params, x)
