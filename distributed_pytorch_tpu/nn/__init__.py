"""Functional nn modules (pytree params, pure apply)."""
from . import attention, conv, core
from .attention import (MultiHeadAttention, TransformerBlock, dense_attention)
from .conv import BatchNorm2d, Conv2d, global_avg_pool, max_pool
from .core import (Dropout, Embedding, LayerNorm, Linear, Module, Params,
                   Sequential, gelu, relu)
