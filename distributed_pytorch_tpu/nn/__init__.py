"""Functional nn modules (pytree params, pure apply)."""
from . import core
from .core import (Dropout, Embedding, LayerNorm, Linear, Module, Params,
                   Sequential, gelu, relu)
