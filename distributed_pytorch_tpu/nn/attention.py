"""Attention and transformer blocks — the LM rung of the ladder
(BASELINE.md: TransformerEncoder LM) and the substrate for long-context
sequence parallelism (ring attention lives in ``parallel/sequence.py`` and
plugs in here via the ``attn_fn`` hook).

Compute shapes are chosen for the MXU: projections are single fused
matmuls over (B*S, D); attention is batched (B, H, S, S) einsums XLA tiles
onto the systolic array. bfloat16-friendly: pass ``dtype=jnp.bfloat16`` for
activations/params while softmax runs in float32 for stability.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .core import Dropout, LayerNorm, Linear, Module, Params, gelu


def dense_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    window: Optional[int] = None):
    """Reference attention: softmax(q k^T / sqrt(d)) v.

    q,k,v: (B, H, S, Dh). Softmax in float32 regardless of input dtype.
    ``window`` (requires ``causal``): sliding-window attention — row i
    sees keys (i+off-window, i+off] only (off aligns cross-length
    diagonals). This is the single-device path;
    ``parallel.sequence.ring_attention`` computes the same function with
    K/V sharded around the mesh ring, and ``ops.flash_attention`` is the
    O(S)-memory kernel equivalent.
    """
    *_, s_q, dh = q.shape
    s_k = k.shape[-2]
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    scale = scale if scale is not None else 1.0 / math.sqrt(dh)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        if window is not None:
            mask &= ~jnp.tril(jnp.ones((s_q, s_k), dtype=bool),
                              k=s_k - s_q - window)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


class MultiHeadAttention(Module):
    """Multi-head self-attention with a pluggable core.

    ``attn_fn(q, k, v, causal=...)`` defaults to :func:`dense_attention`;
    the sequence-parallel engine substitutes ring attention without
    touching this module's parameters or callers.
    """

    def __init__(self, dim: int, n_heads: int, *, causal: bool = False,
                 attn_fn: Optional[Callable] = None, dtype=jnp.float32):
        if dim % n_heads:
            raise ValueError(f"dim {dim} not divisible by n_heads {n_heads}")
        self.dim = dim
        self.n_heads = n_heads
        self.head_dim = dim // n_heads
        self.causal = causal
        self.attn_fn = attn_fn or dense_attention
        self.qkv = Linear(dim, 3 * dim, dtype=dtype)
        self.out = Linear(dim, dim, dtype=dtype)

    def init(self, key) -> Params:
        k1, k2 = jax.random.split(key)
        return {"qkv": self.qkv.init(k1), "out": self.out.init(k2)}

    def project_qkv(self, params: Params, x):
        """x (B, S, D) → q, k, v each (B, H, S, Dh), via the fused qkv
        matmul. The single source of truth for the qkv memory layout —
        the cached decode path (models/generate.py) builds its KV cache
        through this method."""
        b, s, _ = x.shape
        qkv = self.qkv.apply(params["qkv"], x)           # (B, S, 3D) one matmul
        qkv = qkv.reshape(b, s, 3, self.n_heads, self.head_dim)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        return q, k, v

    def project_out(self, params: Params, o):
        """o (B, H, S, Dh) → output projection (B, S, D)."""
        b, h, s, dh = o.shape
        return self.out.apply(params["out"],
                              o.transpose(0, 2, 1, 3).reshape(b, s, h * dh))

    def apply(self, params: Params, x, **kwargs):
        q, k, v = self.project_qkv(params, x)
        o = self.attn_fn(q, k, v, causal=self.causal)
        return self.project_out(params, o)


class TransformerBlock(Module):
    """Pre-norm block: x + MHA(LN(x)); x + MLP(LN(x)), GELU MLP."""

    def __init__(self, dim: int, n_heads: int, mlp_ratio: int = 4, *,
                 causal: bool = False, dropout: float = 0.0,
                 attn_fn: Optional[Callable] = None, dtype=jnp.float32):
        self.ln1 = LayerNorm(dim, dtype=dtype)
        self.attn = MultiHeadAttention(dim, n_heads, causal=causal,
                                       attn_fn=attn_fn, dtype=dtype)
        self.ln2 = LayerNorm(dim, dtype=dtype)
        self.fc1 = Linear(dim, mlp_ratio * dim, dtype=dtype)
        self.fc2 = Linear(mlp_ratio * dim, dim, dtype=dtype)
        self.drop = Dropout(dropout)

    def init(self, key) -> Params:
        ks = jax.random.split(key, 4)
        return {"ln1": self.ln1.init(ks[0]), "attn": self.attn.init(ks[1]),
                "ln2": self.ln2.init(ks[2]),
                "fc1": self.fc1.init(ks[3]),
                "fc2": self.fc2.init(jax.random.fold_in(ks[3], 1))}

    def mlp(self, params: Params, x):
        """LN → fc1 → GELU → fc2 (no residual/dropout). Shared by apply
        and the cached decode path (models/generate.py)."""
        return self.fc2.apply(params["fc2"],
                              gelu(self.fc1.apply(params["fc1"],
                                                  self.ln2.apply(params["ln2"], x))))

    def apply(self, params: Params, x, *, rng=None, train: bool = False, **_):
        r1, r2 = (jax.random.split(rng) if rng is not None else (None, None))
        h = self.attn.apply(params["attn"], self.ln1.apply(params["ln1"], x))
        x = x + self.drop.apply({}, h, rng=r1, train=train)
        return x + self.drop.apply({}, self.mlp(params, x), rng=r2,
                                   train=train)
