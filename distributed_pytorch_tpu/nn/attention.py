"""Attention and transformer blocks — the LM rung of the ladder
(BASELINE.md: TransformerEncoder LM) and the substrate for long-context
sequence parallelism (ring attention lives in ``parallel/sequence.py`` and
plugs in here via the ``attn_fn`` hook).

Compute shapes are chosen for the MXU: projections are single fused
matmuls over (B*S, D); attention is batched (B, H, S, S) einsums XLA tiles
onto the systolic array. bfloat16-friendly: pass ``dtype=jnp.bfloat16`` for
activations/params while softmax runs in float32 for stability.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .core import Dropout, LayerNorm, Linear, Module, Params, gelu
from .rotary import apply_rope


def dense_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    window: Optional[int] = None):
    """Reference attention: softmax(q k^T / sqrt(d)) v.

    q: (B, H, S, Dh); k, v: (B, Hkv, S, Dh) where Hkv divides H —
    Hkv < H is grouped-query attention (each kv head serves H/Hkv query
    heads), computed via a grouped einsum so the kv tensors are never
    repeated in memory.

    The **f32-stats contract** (docs/compute.md, guarded by
    tests/test_compute_path.py): the softmax — max, exp, and the
    normalizing SUM — runs in float32 regardless of input dtype; only
    the resulting probabilities are cast back to ``v.dtype`` for the
    p@v matmul. Under bf16 mixed precision this is what keeps the
    normalizer from accumulating in 8 mantissa bits (at S=512 a pure
    bf16 sum of uniform probabilities drifts by several percent). The
    flash kernel and ``ops.decode_attention`` follow the same rule.
    A fully-masked ROW (causal with s_q > s_k puts whole rows above
    the diagonal) yields NaN here by definition of softmax over an
    all--inf row; the flash kernel deliberately matches that, while
    the blockwise decode path — where fully-masked BLOCKS are routine
    for short rows — masks with a finite sentinel and exact-zero
    probabilities so the merge never manufactures NaN.

    ``window`` (requires ``causal``): sliding-window attention — row i
    sees keys (i+off-window, i+off] only (off aligns cross-length
    diagonals). This is the single-device path;
    ``parallel.sequence.ring_attention`` computes the same function with
    K/V sharded around the mesh ring, and ``ops.flash_attention`` is the
    O(S)-memory kernel equivalent.
    """
    b, h, s_q, dh = q.shape
    h_kv, s_k = k.shape[-3], k.shape[-2]
    if h % h_kv:
        raise ValueError(f"n_heads {h} not divisible by kv heads {h_kv}")
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    scale = scale if scale is not None else 1.0 / math.sqrt(dh)
    qg = q.reshape(b, h_kv, h // h_kv, s_q, dh)
    logits = jnp.einsum("bngqd,bnkd->bngqk", qg, k).astype(jnp.float32) \
        * scale
    if causal:
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        if window is not None:
            mask &= ~jnp.tril(jnp.ones((s_q, s_k), dtype=bool),
                              k=s_k - s_q - window)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bngqk,bnkd->bngqd", probs, v) \
        .reshape(b, h, s_q, dh)


class MultiHeadAttention(Module):
    """Multi-head self-attention with a pluggable core.

    ``attn_fn(q, k, v, causal=...)`` defaults to :func:`dense_attention`;
    the sequence-parallel engine substitutes ring attention without
    touching this module's parameters or callers.
    """

    def __init__(self, dim: int, n_heads: int, *, causal: bool = False,
                 n_kv_heads: Optional[int] = None, rope: bool = False,
                 rope_base: float = 10000.0,
                 attn_fn: Optional[Callable] = None, dtype=jnp.float32):
        if dim % n_heads:
            raise ValueError(f"dim {dim} not divisible by n_heads {n_heads}")
        self.dim = dim
        self.n_heads = n_heads
        self.n_kv_heads = n_kv_heads if n_kv_heads is not None else n_heads
        if n_heads % self.n_kv_heads:
            raise ValueError(f"n_heads {n_heads} not divisible by "
                             f"n_kv_heads {self.n_kv_heads}")
        self.head_dim = dim // n_heads
        self.causal = causal
        self.rope = rope
        self.rope_base = rope_base
        self.attn_fn = attn_fn or dense_attention
        # GQA (n_kv_heads < n_heads) shrinks the k/v projections and the
        # decode KV cache by n_heads/n_kv_heads; with the default the
        # parameter tree is identical to classic MHA.
        kv_dim = self.n_kv_heads * self.head_dim
        self.qkv = Linear(dim, dim + 2 * kv_dim, dtype=dtype)
        self.out = Linear(dim, dim, dtype=dtype)

    def init(self, key) -> Params:
        k1, k2 = jax.random.split(key)
        return {"qkv": self.qkv.init(k1), "out": self.out.init(k2)}

    def project_qkv(self, params: Params, x):
        """x (B, S, D) → q (B, H, S, Dh), k, v (B, Hkv, S, Dh), via the
        fused qkv matmul. The single source of truth for the qkv memory
        layout — the cached decode path (models/generate.py) builds its
        KV cache through this method."""
        b, s, _ = x.shape
        dh, h, hkv = self.head_dim, self.n_heads, self.n_kv_heads
        qkv = self.qkv.apply(params["qkv"], x)      # (B, S, (H+2Hkv)*Dh)
        q, k, v = jnp.split(qkv, [h * dh, (h + hkv) * dh], axis=-1)

        def heads(t, n):
            return t.reshape(b, s, n, dh).transpose(0, 2, 1, 3)
        return heads(q, h), heads(k, hkv), heads(v, hkv)

    def project_out(self, params: Params, o):
        """o (B, H, S, Dh) → output projection (B, S, D)."""
        b, h, s, dh = o.shape
        return self.out.apply(params["out"],
                              o.transpose(0, 2, 1, 3).reshape(b, s, h * dh))

    def maybe_rope(self, q, k, positions=None):
        """Rotate q/k when built with ``rope=True`` (no-op otherwise).
        ``positions`` (S,) default to arange — pass explicit ids for a
        sequence-parallel shard (global offset) or a cached decode step
        (the single slot being written). The decode path MUST rotate
        through this method before caching k: the cache stores
        post-rotation keys so decode-time q.k phases are correct."""
        if not self.rope:
            return q, k
        if positions is None:
            positions = jnp.arange(q.shape[2])
        return (apply_rope(q, positions, self.rope_base),
                apply_rope(k, positions, self.rope_base))

    def apply(self, params: Params, x, *, positions=None, **kwargs):
        q, k, v = self.project_qkv(params, x)
        q, k = self.maybe_rope(q, k, positions)
        o = self.attn_fn(q, k, v, causal=self.causal)
        return self.project_out(params, o)


class TransformerBlock(Module):
    """Pre-norm block: x + MHA(LN(x)); x + MLP(LN(x)), GELU MLP."""

    def __init__(self, dim: int, n_heads: int, mlp_ratio: int = 4, *,
                 causal: bool = False, dropout: float = 0.0,
                 n_kv_heads: Optional[int] = None, rope: bool = False,
                 rope_base: float = 10000.0,
                 attn_fn: Optional[Callable] = None, dtype=jnp.float32):
        self.ln1 = LayerNorm(dim, dtype=dtype)
        self.attn = MultiHeadAttention(dim, n_heads, causal=causal,
                                       n_kv_heads=n_kv_heads, rope=rope,
                                       rope_base=rope_base,
                                       attn_fn=attn_fn, dtype=dtype)
        self.ln2 = LayerNorm(dim, dtype=dtype)
        self.fc1 = Linear(dim, mlp_ratio * dim, dtype=dtype)
        self.fc2 = Linear(mlp_ratio * dim, dim, dtype=dtype)
        self.drop = Dropout(dropout)

    def init(self, key) -> Params:
        ks = jax.random.split(key, 4)
        return {"ln1": self.ln1.init(ks[0]), "attn": self.attn.init(ks[1]),
                "ln2": self.ln2.init(ks[2]),
                "fc1": self.fc1.init(ks[3]),
                "fc2": self.fc2.init(jax.random.fold_in(ks[3], 1))}

    def mlp(self, params: Params, x):
        """LN → fc1 → GELU → fc2 (no residual/dropout). Shared by apply
        and the cached decode path (models/generate.py)."""
        return self.fc2.apply(params["fc2"],
                              gelu(self.fc1.apply(params["fc1"],
                                                  self.ln2.apply(params["ln2"], x))))

    def apply(self, params: Params, x, *, rng=None, train: bool = False,
              positions=None, **_):
        r1, r2 = (jax.random.split(rng) if rng is not None else (None, None))
        h = self.attn.apply(params["attn"], self.ln1.apply(params["ln1"], x),
                            positions=positions)
        x = x + self.drop.apply({}, h, rng=r1, train=train)
        return x + self.drop.apply({}, self.mlp(params, x), rng=r2,
                                   train=train)
