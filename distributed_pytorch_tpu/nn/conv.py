"""Convolution / norm / pooling modules for the vision rung of the ladder
(BASELINE.md: ResNet-18 on CIFAR-10).

Layout is NHWC — the TPU-native image layout (channels-last feeds the MXU's
128-lane minor dimension directly; NCHW is the CUDA idiom and forces
transposes on TPU). BatchNorm is stateful: ``init`` returns params,
``init_state`` returns running stats, ``apply`` takes/returns state. Under
the DP engine each device normalizes with its *local* batch statistics —
the same semantics as torch DDP's default (non-synced) BatchNorm.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .core import Module, Params


class Conv2d(Module):
    """2-D convolution, NHWC, HWIO kernel, stride/padding like torch's
    Conv2d(padding=p). Kaiming-normal (fan_out, relu) init — the torchvision
    ResNet initialization."""

    def __init__(self, in_ch: int, out_ch: int, kernel: int, stride: int = 1,
                 padding: int = 0, bias: bool = False, groups: int = 1,
                 dtype=jnp.float32):
        self.in_ch = in_ch
        self.out_ch = out_ch
        self.kernel = kernel
        self.stride = stride
        self.padding = padding
        self.use_bias = bias
        self.groups = groups
        self.dtype = dtype

    def init(self, key) -> Params:
        kw, kb = jax.random.split(key)
        fan_out = self.kernel * self.kernel * self.out_ch
        std = math.sqrt(2.0 / fan_out)
        p = {"w": std * jax.random.normal(
            kw, (self.kernel, self.kernel, self.in_ch // self.groups,
                 self.out_ch), self.dtype)}
        if self.use_bias:
            p["b"] = jnp.zeros((self.out_ch,), self.dtype)
        return p

    def apply(self, params: Params, x, **_):
        from ..ops.quant import resolve_weight
        y = lax.conv_general_dilated(
            x, resolve_weight(params, "w", self.dtype),
            window_strides=(self.stride, self.stride),
            padding=[(self.padding, self.padding)] * 2,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.groups,
        )
        if self.use_bias:
            y = y + params["b"]
        return y


class BatchNorm2d(Module):
    """BatchNorm over N,H,W with running-stat state (torch semantics:
    train mode uses batch stats and updates running stats with momentum
    0.1; eval mode uses running stats).

    ``axis_name`` turns it into **SyncBatchNorm** (torch
    ``nn.SyncBatchNorm`` under DDP): inside a ``shard_map`` over that
    mesh axis, batch statistics are computed over the GLOBAL batch (one
    psum of the per-shard sum/sum-of-squares), and every replica updates
    identical running stats. Outside any binding of the axis (world-1
    runs, plain jit) it degrades to local statistics — the framework's
    0/1/N contract. Note the pure-GSPMD path needs no flag: there the
    model sees global shapes, so plain ``jnp.mean`` already reduces over
    the whole batch."""

    def __init__(self, ch: int, eps: float = 1e-5, momentum: float = 0.1,
                 axis_name: Optional[str] = None, dtype=jnp.float32):
        self.ch = ch
        self.eps = eps
        self.momentum = momentum
        self.axis_name = axis_name
        self.dtype = dtype

    def init(self, key) -> Params:
        del key
        return {"scale": jnp.ones((self.ch,), self.dtype),
                "bias": jnp.zeros((self.ch,), self.dtype)}

    def init_state(self):
        return {"mean": jnp.zeros((self.ch,), self.dtype),
                "var": jnp.ones((self.ch,), self.dtype),
                "count": jnp.zeros((), jnp.int32)}

    def _batch_stats(self, x):
        """(mean, var, n) over N,H,W — cross-replica when ``axis_name``
        is bound (sum/sum-of-squares psum: one collective, the standard
        sync-BN form), local otherwise."""
        n = x.shape[0] * x.shape[1] * x.shape[2]
        if self.axis_name is None:
            return jnp.mean(x, axis=(0, 1, 2)), jnp.var(x, axis=(0, 1, 2)), n
        s = jnp.sum(x, axis=(0, 1, 2))
        ss = jnp.sum(jnp.square(x), axis=(0, 1, 2))
        try:
            s = lax.psum(s, self.axis_name)
            ss = lax.psum(ss, self.axis_name)
            n = n * lax.psum(1, self.axis_name)
        except NameError:
            pass  # axis not bound here: local stats (0/1-device runs)
        mean = s / n
        # E[x^2]-E[x]^2 can go slightly negative from cancellation when
        # |mean| >> std; clamp like torch SyncBatchNorm or rsqrt NaNs
        return mean, jnp.maximum(ss / n - jnp.square(mean), 0.0), n

    def apply(self, params: Params, x, *, state=None, train: bool = False, **_):
        if train:
            mean, var, n = self._batch_stats(x)
            new_state = None
            if state is not None:
                m = self.momentum
                # torch tracks unbiased running var
                unbiased = var * n / max(n - 1, 1)
                new_state = {
                    "mean": (1 - m) * state["mean"] + m * mean,
                    "var": (1 - m) * state["var"] + m * unbiased,
                    "count": state["count"] + 1,
                }
        else:
            mean = state["mean"] if state is not None else jnp.zeros((self.ch,))
            var = state["var"] if state is not None else jnp.ones((self.ch,))
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        y = (x - mean) * inv * params["scale"] + params["bias"]
        return y, new_state


def max_pool(x, window: int, stride: int, padding: int = 0):
    """NHWC max pooling (torch MaxPool2d equivalent)."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding=((0, 0), (padding, padding), (padding, padding), (0, 0)),
    )


def global_avg_pool(x):
    """NHWC global average pool → (N, C)."""
    return jnp.mean(x, axis=(1, 2))
