"""Functional neural-net modules: params are pytrees, apply is pure.

The reference builds models from ``torch.nn`` (``min_DDP.py:41-49``). This
framework's module system is deliberately functional — ``init(key)`` returns
a params pytree, ``apply(params, x)`` is a pure function — because that is
what compiles cleanly under ``jit``/``pjit``: parameters are explicit inputs
the sharding machinery can annotate (replicated for DP, axis-sharded for TP),
and a whole training step closes over nothing.

Initialization follows the same fan-in uniform scheme torch's ``Linear``
uses (U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for both weight and bias), so
model-quality behavior matches the reference workload's.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


class Module:
    """Base: subclasses define ``init(key) -> params`` and
    ``apply(params, x, **kw) -> out``."""

    def init(self, key) -> Params:
        raise NotImplementedError

    def apply(self, params: Params, x, **kwargs):
        raise NotImplementedError

    def __call__(self, params: Params, x, **kwargs):
        return self.apply(params, x, **kwargs)


class Linear(Module):
    """Affine map ``x @ W + b`` (the reference model's only layer type,
    ``min_DDP.py:44-45``). Weight stored as (in, out) — the layout the MXU
    wants for ``x @ W`` without a transpose."""

    def __init__(self, in_dim: int, out_dim: int, bias: bool = True,
                 dtype=jnp.float32):
        self.in_dim = in_dim
        self.out_dim = out_dim
        self.bias = bias
        self.dtype = dtype

    def init(self, key) -> Params:
        kw, kb = jax.random.split(key)
        bound = 1.0 / math.sqrt(self.in_dim)
        p = {"w": jax.random.uniform(kw, (self.in_dim, self.out_dim),
                                     self.dtype, -bound, bound)}
        if self.bias:
            p["b"] = jax.random.uniform(kb, (self.out_dim,), self.dtype,
                                        -bound, bound)
        return p

    def apply(self, params: Params, x, **_):
        # params may hold the weight int8-quantized ({"w_q","w_scale"},
        # ops/quant.py); the dequant fuses into the matmul so HBM streams
        # the int8 bytes
        from ..ops.quant import resolve_weight
        y = jnp.matmul(x, resolve_weight(params, "w", self.dtype))
        if self.bias:
            y = y + params["b"]
        return y


class Embedding(Module):
    def __init__(self, vocab: int, dim: int, std: float = 1.0,
                 dtype=jnp.float32):
        self.vocab = vocab
        self.dim = dim
        # N(0, std). The default keeps historical behavior; models whose
        # table doubles as the output projection (tied embeddings) MUST
        # use a small std — at std=1 the tied logits come out with
        # ~sqrt(dim) scale and the loss diverges within a few steps
        # (TransformerLM passes dim**-0.5 for its tables).
        self.std = std
        self.dtype = dtype

    def init(self, key) -> Params:
        return {"emb": self.std * jax.random.normal(
            key, (self.vocab, self.dim)).astype(self.dtype)}

    def apply(self, params: Params, ids, **_):
        if "emb" in params:
            return jnp.take(params["emb"], ids, axis=0)
        # int8 table (ops/quant.py): gather the int8 rows, dequantize
        # only what was looked up
        rows = jnp.take(params["emb_q"], ids, axis=0).astype(self.dtype)
        return rows * params["emb_scale"].astype(self.dtype)


class LayerNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-5, dtype=jnp.float32):
        self.dim = dim
        self.eps = eps
        self.dtype = dtype

    def init(self, key) -> Params:
        del key
        return {"scale": jnp.ones((self.dim,), self.dtype),
                "bias": jnp.zeros((self.dim,), self.dtype)}

    def apply(self, params: Params, x, **_):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mu) * jax.lax.rsqrt(var + self.eps)
        return y * params["scale"] + params["bias"]


class Dropout(Module):
    """Stateless dropout: pass ``rng=`` and ``train=True`` to drop."""

    def __init__(self, rate: float):
        self.rate = rate

    def init(self, key) -> Params:
        del key
        return {}

    def apply(self, params: Params, x, *, rng=None, train: bool = False, **_):
        del params
        if not train or self.rate <= 0.0 or rng is None:
            return x
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


class Sequential(Module):
    """Named chain of modules; params nest under each layer's name."""

    def __init__(self, layers: Sequence[Tuple[str, Module]]):
        self.layers = list(layers)

    def init(self, key) -> Params:
        keys = jax.random.split(key, max(len(self.layers), 1))
        return {name: mod.init(k)
                for (name, mod), k in zip(self.layers, keys)}

    def apply(self, params: Params, x, **kwargs):
        for name, mod in self.layers:
            x = mod.apply(params[name], x, **kwargs)
        return x


def relu(x):
    return jnp.maximum(x, 0)


def gelu(x):
    return jax.nn.gelu(x)
