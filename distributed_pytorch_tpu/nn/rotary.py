"""Rotary position embeddings (RoPE).

Positions enter attention by rotating each (q, k) head vector in 2-D
planes — relative offsets then appear as phase differences inside the
q.k dot product, so no positional parameters exist and the scheme
extrapolates by construction. This is the modern replacement for the
learned absolute table (``TransformerLM(pos="rope")``); the reference
repo has no positional scheme at all (its model is an MLP over scalar
indices, reference ``min_DDP.py:44-48``).

TPU notes: the rotation is a pure elementwise map (two multiplies, one
shuffle) that XLA fuses into the surrounding qkv projection; it composes
with the flash/ring kernels untouched because it runs BEFORE attention.
The half-split ("rotate_half", NeoX/Llama) layout is used: dims [0, d/2)
pair with [d/2, d), which keeps the shuffle a single concat instead of a
stride-2 gather (strided lane moves are slow on the VPU).
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_angles(positions, head_dim: int, base: float = 10000.0,
                dtype=jnp.float32):
    """(cos, sin) tables for ``positions`` (any shape P), each
    (P..., head_dim/2): angle(p, i) = p * base^(-2i/d)."""
    if head_dim % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {head_dim}")
    half = head_dim // 2
    inv_freq = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def apply_rope(x, positions, base: float = 10000.0):
    """Rotate head vectors: x (..., H, S, Dh), positions (S,) int.

    Returns x with each head vector rotated by its position's angles in
    the half-split pairing; dtype preserved (angles computed in f32)."""
    dh = x.shape[-1]
    cos, sin = rope_angles(positions, dh, base, dtype=jnp.float32)
    # broadcast (S, Dh/2) over leading (..., H) axes
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)
