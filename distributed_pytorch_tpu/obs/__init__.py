"""obs — cross-rank observability: span tracing, flight recorder,
Chrome-trace export, straggler detection (docs/observability.md).

* :mod:`.trace`  — the span API + per-rank flight recorder every
  instrumented seam (comm ops, host train step, serve lifecycle, ckpt
  phases, fault injections) writes through; also the process wall
  anchor behind ``utils.logging``'s monotone timestamps.
* :mod:`.export` — merge per-rank line-JSON span logs into Chrome
  trace-event JSON (rank→pid, thread→tid, clock alignment at
  collective exits) + the metrics-log vocabulary/validator.
* :mod:`.detect` — per-op per-rank duration medians, k·IQR straggler
  flagging (the ``perfbench/stats`` policy).

CLI: ``python -m tools.dpxtrace`` (merge/export/summarize/stragglers/
check) — stdlib-only, loads without the heavy package ``__init__``.

Every module here is stdlib-only with lazy cross-package imports, the
``analysis/lint.py`` contract.
"""

from . import detect, export, trace  # noqa: F401
from .trace import (enabled, event, flight_dump, flight_snapshot,  # noqa: F401
                    new_trace_id, on_typed_failure, span, wall_now)

__all__ = [
    "trace", "export", "detect",
    "span", "event", "enabled", "new_trace_id", "wall_now",
    "flight_dump", "flight_snapshot", "on_typed_failure",
]
