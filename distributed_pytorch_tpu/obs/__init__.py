"""obs — cross-rank observability: span tracing, flight recorder,
Chrome-trace export, straggler detection, live metrics + streaming SLO
health (docs/observability.md).

* :mod:`.trace`  — the span API + per-rank flight recorder every
  instrumented seam (comm ops, host train step, serve lifecycle, ckpt
  phases, fault injections) writes through; also the process wall
  anchor behind ``utils.logging``'s monotone timestamps.
* :mod:`.export` — merge per-rank line-JSON span logs into Chrome
  trace-event JSON (rank→pid, thread→tid, clock alignment at
  collective exits) + the metrics-log vocabulary/validator.
* :mod:`.detect` — per-op per-rank duration medians, k·IQR straggler
  flagging (the ``perfbench/stats`` policy).
* :mod:`.metrics` — the dpxmon live registry: typed counter/gauge/
  histogram instruments, pull providers (CommStats, RSS, flight drops),
  rank-attributed ``metrics_snapshot`` events on a cadence.
* :mod:`.health` — streaming SLO evaluation over snapshot windows:
  declarative rules (ceilings, drift-vs-trailing-median, monotone
  growth), a typed ok→degraded→critical state machine with hysteresis,
  ``health_transition`` events naming the firing rule and metric.

CLIs: ``python -m tools.dpxtrace`` (merge/export/summarize/stragglers/
check) and ``python -m tools.dpxmon`` (replay/follow/check) —
stdlib-only, load without the heavy package ``__init__``.

Every module here is stdlib-only with lazy cross-package imports, the
``analysis/lint.py`` contract.
"""

from . import detect, export, health, metrics, trace  # noqa: F401
from .trace import (enabled, event, flight_dump, flight_snapshot,  # noqa: F401
                    new_trace_id, on_typed_failure, span, wall_now)

__all__ = [
    "trace", "export", "detect", "metrics", "health",
    "span", "event", "enabled", "new_trace_id", "wall_now",
    "flight_dump", "flight_snapshot", "on_typed_failure",
]
