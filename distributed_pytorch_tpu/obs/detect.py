"""Straggler / anomaly detection over merged per-rank spans.

The CUDA-aware-MPI characterization (PAPERS.md, arXiv 1810.11112) shows
the distributed pathologies that matter at scale — one slow rank
gating every collective, skewed per-rank compute, exposed comm on one
host — only appear when per-op durations are compared ACROSS ranks.
This module does exactly that join over the spans :mod:`.export`
collects:

* per (op name, rank): the rank's duration median/IQR through the SAME
  statistical policy every perf number already uses
  (``perfbench/stats.summarize`` — warmup semantics disabled here,
  spans are not benchmark trials);
* per op name: each rank is fenced against the *other* ranks' medians
  (leave-one-out): a rank whose median lies above
  ``median(peers) + k·IQR(peers)`` (AND above a 5% relative floor —
  µs-scale jitter on a quiet op must not page anyone) is flagged a
  straggler.  The fence is leave-one-out because the pooled form is
  degenerate at small n: in a 3-rank world one 90x outlier drags q75
  toward itself and lifts the pooled fence above its own median.

Stdlib-only; ``perfbench.stats`` is itself stdlib-only by contract, so
the dpxtrace CLI runs this in a bare venv.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

__all__ = ["IQR_K", "REL_FLOOR", "op_durations", "summarize_ops",
           "stragglers"]

#: Default k of the k·IQR straggler gate (the classic robust outlier
#: fence; perfbench's spread gate is the same IQR vocabulary).
IQR_K = 3.0

#: Relative floor: a flagged rank must also exceed the across-rank
#: median by this fraction — absolute-µs jitter is not a straggler.
REL_FLOOR = 0.05


def _stats():
    # lazy: resolves under the dpxtrace CLI's fabricated parents too
    from ..perfbench import stats
    return stats


def op_durations(spans: Sequence[Dict[str, Any]]
                 ) -> Dict[str, Dict[Any, List[float]]]:
    """``{op name: {rank: [duration_ms, ...]}}`` over span records
    (rank falls back to pid for unattributed single-process spans)."""
    out: Dict[str, Dict[Any, List[float]]] = {}
    for s in spans:
        name = s.get("name")
        dur = s.get("dur_ns")
        if not name or not isinstance(dur, (int, float)) or dur <= 0:
            continue
        r = s.get("rank")
        if r is None:
            r = s.get("pid")
        out.setdefault(name, {}).setdefault(r, []).append(dur / 1e6)
    return out


def summarize_ops(spans: Sequence[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """Per-op per-rank summary table rows: count, median/IQR ms — the
    ``dpxtrace summarize`` payload."""
    st = _stats()
    rows: List[Dict[str, Any]] = []
    for name, by_rank in sorted(op_durations(spans).items()):
        for rank in sorted(by_rank, key=lambda r: (r is None, r)):
            durs = by_rank[rank]
            agg = st.summarize(durs, warmup=0, max_spread=float("inf"))
            rows.append({
                "op": name, "rank": rank, "count": len(durs),
                "median_ms": round(agg.median, 4),
                "iqr_ms": round(agg.iqr, 4),
                "total_ms": round(sum(durs), 3),
            })
    return rows


def stragglers(spans: Sequence[Dict[str, Any]], *,
               k: Optional[float] = None,
               min_ranks: int = 3) -> List[Dict[str, Any]]:
    """Flag (op, rank) pairs whose per-rank median duration lies outside
    the leave-one-out fence ``median(peers) + k·IQR(peers)`` (peers =
    the other ranks' medians for the same op), with the 5% relative
    floor.  Ops seen on fewer than ``min_ranks`` ranks are skipped;
    values below 3 are clamped to 3 — with fewer than two peers there
    is no spread to fence against (a single-peer "IQR" is 0 and would
    flag ANY gap), so two-rank worlds never produce a verdict."""
    st = _stats()
    k = IQR_K if k is None else float(k)
    findings: List[Dict[str, Any]] = []
    for name, by_rank in sorted(op_durations(spans).items()):
        if len(by_rank) < max(min_ranks, 3):
            continue
        medians = {
            r: st.summarize(d, warmup=0,
                            max_spread=float("inf")).median
            for r, d in by_rank.items()}
        for rank in sorted(medians, key=lambda r: (r is None, r)):
            m = medians[rank]
            peers = sorted(v for r2, v in medians.items() if r2 != rank)
            med = st._quantile(peers, 0.5)
            iqr = (st._quantile(peers, 0.75)
                   - st._quantile(peers, 0.25))
            if med <= 0:
                continue
            threshold = med + k * iqr
            if m > threshold and (m - med) / med > REL_FLOOR:
                findings.append({
                    "op": name, "rank": rank,
                    "median_ms": round(m, 4),
                    "world_median_ms": round(med, 4),
                    "iqr_ms": round(iqr, 4),
                    "threshold_ms": round(threshold, 4),
                    "excess_x": round(m / med, 2),
                    "n_ranks": len(by_rank),
                })
    findings.sort(key=lambda f: -f["excess_x"])
    return findings
