"""Merge per-rank span logs into Chrome trace-event JSON.

The runtime half (:mod:`.trace`) writes ``trace_span`` events into the
shared line-JSON stream from every rank/process; this module joins them
into the one artifact the MLPerf-pod recipe starts from — per-rank
timelines laid side by side (``chrome://tracing`` / Perfetto):

* **rank → pid, thread/engine → tid** — each rank renders as one
  process row, its control thread / serve-engine thread / ckpt-io
  thread as lanes within it;
* **cross-rank clock alignment** — each process stamps spans against
  its OWN wall anchor (one ``time.time()`` read at import), so rank
  clocks are offset by anchor skew. Collective EXITS are
  synchronization points (every rank leaves a barrier — and completes
  a ring allreduce — within one hop of each other), so the estimator
  matches ``comm:*`` spans across ranks by (op name, per-rank
  occurrence index) and shifts each rank by the median end-time delta
  against the reference rank. Barrier spans are preferred when present
  (tightest bound); the applied offsets are reported in the trace
  metadata, not hidden.

Also home of the metrics-log VOCABULARY (:data:`KNOWN_EVENTS`) and the
strict validator behind ``tools/dpxtrace.py check`` — malformed lines
with line numbers, unknown event names, rank-unattributed failure
events.

Stdlib-only (the ``analysis/lint.py`` contract): the dpxtrace CLI loads
this in a bare venv without the package ``__init__``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "KNOWN_EVENTS", "FAILURE_EVENTS", "read_log", "collect_spans",
    "chrome_trace", "estimate_offsets", "check_log",
]

#: Every event name the framework writes into the line-JSON stream
#: (``utils.logging.append_event`` / ``MetricsLogger.event`` call
#: sites). The ``check`` validator flags names outside this vocabulary
#: — a typo'd event is invisible to every consumer that greps by name.
KNOWN_EVENTS = frozenset({
    # runtime / supervision
    "worker_failure", "comm_schedule", "schedule_divergence",
    "elastic_reconfigured", "elastic_recovered", "elastic_worker_exit",
    "elastic_giveup",
    # checkpointing
    "ckpt_save", "ckpt_restore",
    # serving
    "serve_request",
    # perfbench trajectory rows
    "bench_row",
    # observability (this subsystem)
    "trace_span", "flight_recorder", "fault_injected",
    # chaos campaigns (runtime/chaos.py + benchmarks/chaos_campaign.py):
    # one comm_retry per transient-fault retry (op/rank/attempt/backoff
    # attributed — a retry is never silent), one chaos_clause per
    # campaign clause verdict (fired / typed error / attribution /
    # recovery)
    "comm_retry", "chaos_clause",
    # dpxmon live monitoring (obs/metrics.py + obs/health.py): per-rank
    # registry snapshots and the SLO state machine's transitions
    "metrics_snapshot", "health_transition",
    # multi-replica fleet (serve/fleet/): one fleet_route per routed
    # request (home/replica/spilled attributed), one fleet_spill per
    # back-pressure spill (from/to replica), one fleet_scale per
    # scaling decision (add/drain/revive, rule attributed), and the
    # replica lifecycle edges — replica_failed is failure-shaped
    # (rank = replica id) and degrades that replica's health stream
    "fleet_route", "fleet_spill", "fleet_scale", "replica_drained",
    "replica_failed",
})

#: Failure-shaped events that MUST carry rank attribution — a failure
#: record that cannot say which rank it came from is ungreppable in a
#: multi-writer stream.
FAILURE_EVENTS = frozenset({"worker_failure", "comm_schedule",
                            "flight_recorder", "replica_failed"})


def read_log(path: str) -> Tuple[List[Dict[str, Any]],
                                 List[Tuple[int, str]]]:
    """Parse one line-JSON log. Returns ``(records, malformed)`` where
    each record gains ``_line`` (1-based) and malformed is
    ``[(line_no, reason)]`` — the log is a shared multi-writer file, so
    damage is surfaced with line numbers, never silently skipped."""
    records: List[Dict[str, Any]] = []
    malformed: List[Tuple[int, str]] = []
    with open(path, encoding="utf-8", errors="replace") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                malformed.append((i, f"invalid JSON: {e.msg}"))
                continue
            if not isinstance(rec, dict):
                malformed.append((i, "not a JSON object"))
                continue
            rec["_line"] = i
            records.append(rec)
    return records, malformed


def collect_spans(records: Iterable[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """Span records from a merged event stream: ``trace_span`` events
    plus the spans embedded in ``flight_recorder`` dumps (a killed rank
    may have shipped spans ONLY through its dump), deduplicated by
    span_id — dump spans already live-logged must not render twice."""
    spans: List[Dict[str, Any]] = []
    seen: set = set()

    def add(rec: Dict[str, Any]) -> None:
        sid = rec.get("span_id")
        if sid is not None and sid in seen:
            return
        if sid is not None:
            seen.add(sid)
        if not isinstance(rec.get("t0_wall"), (int, float)):
            return
        if not isinstance(rec.get("dur_ns"), (int, float)):
            # damaged/foreign record in the shared stream: render as an
            # instant rather than crash the whole export on arithmetic
            rec = dict(rec)
            rec["dur_ns"] = 0
        spans.append(rec)

    for rec in records:
        ev = rec.get("event")
        if ev == "trace_span":
            add(rec)
        elif ev == "flight_recorder":
            for s in rec.get("spans") or []:
                if isinstance(s, dict):
                    add(s)
    spans.sort(key=lambda s: s.get("t0_wall", 0.0))
    return spans


def _span_rank(s: Dict[str, Any]):
    r = s.get("rank")
    return r if r is not None else s.get("pid")


def estimate_offsets(spans: Sequence[Dict[str, Any]]
                     ) -> Dict[Any, float]:
    """Per-rank clock offsets (seconds, relative to the lowest rank)
    estimated from matched collective exits.

    For each rank, ``comm:*`` span END times are collected per op name
    in occurrence order; against the reference rank, the k-th exit of
    the same op happened "at the same time" up to one network hop, so
    ``offset = median(end_r[k] - end_ref[k])``. Barrier spans alone are
    used when every rank has one (the tightest sync point); otherwise
    all comm ops contribute. Ranks with no matchable comm spans get 0.
    """
    by_rank: Dict[Any, Dict[str, List[float]]] = {}
    for s in spans:
        name = s.get("name") or ""
        if not name.startswith("comm:"):
            continue
        r = _span_rank(s)
        end = s.get("t0_wall", 0.0) + s.get("dur_ns", 0) / 1e9
        by_rank.setdefault(r, {}).setdefault(name, []).append(end)
    if len(by_rank) < 2:
        return {r: 0.0 for r in by_rank}
    ranks = sorted(by_rank, key=lambda r: (r is None, r))
    ref = ranks[0]
    use_barrier = all("comm:barrier" in ops for ops in by_rank.values())
    offsets: Dict[Any, float] = {ref: 0.0}
    for r in ranks[1:]:
        deltas: List[float] = []
        for op, ends in by_rank[r].items():
            if use_barrier and op != "comm:barrier":
                continue
            ref_ends = by_rank[ref].get(op, [])
            for k in range(min(len(ends), len(ref_ends))):
                deltas.append(ends[k] - ref_ends[k])
        if deltas:
            deltas.sort()
            offsets[r] = deltas[len(deltas) // 2]
        else:
            offsets[r] = 0.0
    return offsets


def chrome_trace(records: Iterable[Dict[str, Any]],
                 align: bool = True) -> Dict[str, Any]:
    """Build the Chrome trace-event JSON dict from a merged event
    stream: complete ("X") events per span, instant ("i") events for
    zero-duration records (fault injections), process-name metadata per
    rank, and the estimated clock offsets in ``otherData``."""
    spans = collect_spans(records)
    offsets = estimate_offsets(spans) if align else {}
    events: List[Dict[str, Any]] = []
    ranks_seen: Dict[Any, None] = {}
    for s in spans:
        r = _span_rank(s)
        ranks_seen.setdefault(r, None)
        ts_s = s.get("t0_wall", 0.0) - offsets.get(r, 0.0)
        dur_us = s.get("dur_ns", 0) / 1e3
        ev: Dict[str, Any] = {
            "name": s.get("name", "?"),
            "ph": "i" if s.get("ph") == "i" else "X",
            "pid": r if isinstance(r, int) else -1,
            "tid": str(s.get("tid", "main")),
            "ts": ts_s * 1e6,
            "args": {k: v for k, v in (s.get("attrs") or {}).items()},
        }
        if ev["ph"] == "X":
            ev["dur"] = dur_us
        else:
            ev["s"] = "p"      # process-scoped instant marker
        for key in ("trace_id", "span_id", "parent_id"):
            if s.get(key) is not None:
                ev["args"][key] = s[key]
        events.append(ev)
        for sub in s.get("events") or []:
            if not isinstance(sub, dict):
                continue
            events.append({
                "name": sub.get("name", "?"), "ph": "i", "s": "t",
                "pid": ev["pid"], "tid": ev["tid"],
                "ts": (sub.get("t_wall", 0.0)
                       - offsets.get(r, 0.0)) * 1e6,
                "args": {k: v for k, v in sub.items()
                         if k not in ("name", "t_wall")},
            })
    for r in ranks_seen:
        events.append({
            "name": "process_name", "ph": "M",
            "pid": r if isinstance(r, int) else -1, "tid": "",
            "args": {"name": (f"rank {r}" if isinstance(r, int)
                              else "unattributed")},
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock_offsets_s": {str(k): round(v, 6)
                                for k, v in offsets.items()},
            "n_spans": len(spans),
        },
    }


def check_log(records: Sequence[Dict[str, Any]],
              malformed: Sequence[Tuple[int, str]]
              ) -> List[Tuple[Optional[int], str]]:
    """The strict metrics-log validator behind ``dpxtrace check``.

    Issues (``(line_no, message)``): malformed JSON lines, records that
    are neither a named event nor a step record, event names outside
    :data:`KNOWN_EVENTS`, and failure-shaped events with no rank
    attribution. An empty return = the log is well-formed."""
    issues: List[Tuple[Optional[int], str]] = [
        (ln, f"malformed line: {why}") for ln, why in malformed]
    for rec in records:
        line = rec.get("_line")
        ev = rec.get("event")
        if ev is None:
            # MetricsLogger.log step records carry `step`, no `event`
            if "step" not in rec:
                issues.append(
                    (line, "record is neither a named event nor a "
                           "step record (no 'event'/'step' key)"))
            continue
        if ev not in KNOWN_EVENTS:
            issues.append(
                (line, f"unknown event name {ev!r} (not in the "
                       f"KNOWN_EVENTS vocabulary — obs/export.py)"))
        if ev in FAILURE_EVENTS and rec.get("rank") is None:
            issues.append(
                (line, f"failure event {ev!r} carries no rank "
                       f"attribution"))
    return issues
