"""Streaming SLO health evaluation over ``metrics_snapshot`` windows.

The live half of dpxmon: :mod:`.metrics` writes per-rank snapshots into
the line-JSON stream; this module turns them into a typed
ok → degraded → critical verdict with hysteresis, so "is this job
healthy right now" is a machine answer, not a dashboard squint. The
statistical vocabulary is ``perfbench/stats`` (median + IQR), the same
policy every perf number in the repo already flows through.

**Rule grammar** (``parse_rules``; the ``DPX_MON_RULES`` knob and
``dpxmon --rules`` both speak it)::

    rules  = rule (';' rule)*
    rule   = metric '<=' number opts?      # ceiling: breach when value > n
           | metric '>=' number opts?      # floor:   breach when value < n
           | 'drift(' metric ')' opts?     # value below the trailing
                                           # median beyond the IQR gate
           | 'growth(' metric ')' opts?    # monotone growth across the
                                           # whole window (leak suspicion)
    opts   = '@' key '=' val (',' key '=' val)*
    keys   = window | k | floor | grow | name

    serve.ttft_ms.p99<=500; drift(train.steps_per_sec)@k=3;
    growth(proc.rss_bytes)@window=6; serve.pool_occupancy<=0.95

Metric names resolve against the snapshot's ``metrics`` dict; a
``.p50``/``.p99``/``.max``... suffix reaches into a histogram summary.
A rule whose metric is ABSENT from a snapshot neither breaches nor
clears — snapshots from different sources (serve engine vs train step)
must not vote on each other's rules.

**State machine** (:class:`HealthMonitor`): per (rule, rank) breach
streaks with hysteresis — ``degrade_after`` consecutive breaches mark
the stream degraded, ``critical_after`` critical, ``recover_after``
consecutive clean evaluations recover it. The monitor's overall state
is the worst stream state; every overall transition is returned AND
(when a log path is given) written as a rank-attributed
``health_transition`` event naming the firing rule and metric — the
``critical`` verdict always says WHICH rule on WHICH rank fired.

Failure events feed the same machine: ``worker_failure`` /
``elastic_worker_exit`` / ``replica_failed`` degrade the named rank's
stream immediately (the built-in ``worker-failure`` pseudo-rule; any
later snapshot from that rank — or, for fleet replicas, any fleet
snapshot naming the replica live in its ``replicas`` field — counts as
a clean evaluation, so an elastic or replica recovery shows as
degraded → ok), and ``elastic_giveup`` is critical outright.

Stdlib-only with lazy imports (the ``analysis/lint.py`` contract) —
``tools/dpxmon.py`` loads this in a bare venv.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "OK", "DEGRADED", "CRITICAL", "STATES", "Rule", "parse_rules",
    "DEFAULT_RULES", "FAILURE_RULE", "resolve_metric", "HealthMonitor",
    "LogFollower", "scan_records",
]

OK, DEGRADED, CRITICAL = "ok", "degraded", "critical"
STATES = (OK, DEGRADED, CRITICAL)
_SEVERITY = {OK: 0, DEGRADED: 1, CRITICAL: 2}

#: Name of the built-in pseudo-rule failure events breach.
FAILURE_RULE = "worker-failure"

#: The default rule set the soak harness and dpxmon evaluate when no
#: spec is given: serve TTFT/TPOT p99 ceilings (generous — the smoke
#: runs on a contended CPU container), throughput drift vs the trailing
#: median beyond the IQR gate, monotone RSS growth, pool saturation.
DEFAULT_RULES = (
    "serve.ttft_ms.p99<=30000;"
    "serve.tpot_ms.p99<=10000;"
    "drift(train.steps_per_sec)@k=3,floor=0.25;"
    "growth(proc.rss_bytes)@window=8,grow=0.05;"
    "serve.pool_occupancy<=0.98"
)


def _stats():
    # lazy: resolves under the dpxmon CLI's fabricated parents too
    from ..perfbench import stats
    return stats


@dataclasses.dataclass(frozen=True)
class Rule:
    """One declarative SLO rule (see the module grammar)."""

    name: str
    kind: str                      # 'max' | 'min' | 'drift' | 'growth'
    metric: str
    threshold: Optional[float] = None
    window: int = 8                # trailing snapshots (drift/growth)
    k: float = 3.0                 # IQR multiplier (drift)
    rel_floor: float = 0.10        # minimum relative drop (drift)
    min_growth: float = 0.02       # net growth fraction (growth)


_RULE_FN_RE = re.compile(r"^(drift|growth)\(\s*([^)\s]+)\s*\)$")


def _parse_opts(text: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for tok in filter(None, (t.strip() for t in text.split(","))):
        key, eq, val = tok.partition("=")
        if not eq:
            raise ValueError(f"bad rule option {tok!r}")
        out[key.strip()] = val.strip()
    return out


def parse_rules(spec: str) -> List[Rule]:
    """Parse a rule spec (module grammar). Raises ``ValueError`` on
    malformed input — a typo'd SLO that silently monitors nothing would
    make a soak gate vacuously green (the DPX_FAULT parser's
    contract)."""
    rules: List[Rule] = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        body, _, opt_text = part.partition("@")
        body = body.strip()
        opts = _parse_opts(opt_text) if opt_text else {}
        kw: Dict[str, Any] = {}
        if "window" in opts:
            kw["window"] = int(opts["window"])
        if "k" in opts:
            kw["k"] = float(opts["k"])
        if "floor" in opts:
            kw["rel_floor"] = float(opts["floor"])
        if "grow" in opts:
            kw["min_growth"] = float(opts["grow"])
        m = _RULE_FN_RE.match(body)
        if m:
            kind, metric = m.group(1), m.group(2)
            if kw.get("window", 8) < 4:
                # drift needs >= 3 trailing values and growth >= 4
                # history entries, both trimmed to the window — a
                # smaller window can never evaluate, i.e. the silently-
                # vacuous SLO this parser exists to reject
                raise ValueError(
                    f"rule {part!r}: {kind} needs window >= 4 "
                    f"(got {kw['window']}) — a smaller window never "
                    f"accumulates enough history to evaluate")
            rules.append(Rule(name=opts.get("name", f"{kind}:{metric}"),
                              kind=kind, metric=metric, **kw))
            continue
        for op, kind in (("<=", "max"), (">=", "min")):
            if op in body:
                metric, _, num = body.partition(op)
                metric = metric.strip()
                try:
                    threshold = float(num)
                except ValueError:
                    raise ValueError(
                        f"bad threshold in rule {part!r}") from None
                rules.append(Rule(
                    name=opts.get("name", f"{metric}{op}{num.strip()}"),
                    kind=kind, metric=metric, threshold=threshold, **kw))
                break
        else:
            raise ValueError(
                f"unparseable rule {part!r} (expected metric<=n, "
                f"metric>=n, drift(metric) or growth(metric))")
    return rules


def resolve_metric(metrics: Dict[str, Any], name: str):
    """Look ``name`` up in a snapshot's metrics dict; a dotted suffix
    (``serve.ttft_ms.p99``) reaches into a histogram summary. Returns
    None when absent (absent = not evaluable, never zero)."""
    v = metrics.get(name)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v
    if v is None and "." in name:
        base, _, sub = name.rpartition(".")
        parent = metrics.get(base)
        if isinstance(parent, dict):
            vv = parent.get(sub)
            if isinstance(vv, (int, float)) and not isinstance(vv, bool):
                return vv
    if isinstance(v, dict):
        return None   # a bare histogram needs a .pXX suffix
    return None


class _Stream:
    """Per-(rule, rank) hysteresis state."""

    __slots__ = ("state", "breaches", "clears", "history", "last_value",
                 "total_breaches")

    def __init__(self):
        self.state = OK
        self.breaches = 0
        self.clears = 0
        self.history: List[float] = []
        self.last_value: Optional[float] = None
        self.total_breaches = 0   # never resets — the audit view


class HealthMonitor:
    """Feed line-JSON records in time order; read back transitions and
    the current verdict (see the module docstring for the semantics)."""

    def __init__(self, rules: Optional[Iterable[Rule]] = None, *,
                 degrade_after: int = 1, critical_after: int = 3,
                 recover_after: int = 2,
                 emit_path: Optional[str] = None):
        self.rules: List[Rule] = list(
            parse_rules(DEFAULT_RULES) if rules is None else rules)
        self.degrade_after = max(int(degrade_after), 1)
        self.critical_after = max(int(critical_after),
                                  self.degrade_after)
        self.recover_after = max(int(recover_after), 1)
        self.emit_path = emit_path
        self.state = OK
        self.transitions: List[Dict[str, Any]] = []
        self._streams: Dict[Tuple[str, Any], _Stream] = {}
        self._snapshots_seen = 0

    # -- stream updates -----------------------------------------------------

    def _stream(self, rule_name: str, rank) -> _Stream:
        return self._streams.setdefault((rule_name, rank), _Stream())

    def _breach(self, s: _Stream, critical: bool = False) -> None:
        s.breaches += 1
        s.total_breaches += 1
        s.clears = 0
        if critical or s.breaches >= self.critical_after:
            new = CRITICAL
        elif s.breaches >= self.degrade_after:
            new = DEGRADED
        else:
            new = s.state
        # escalate only: a breach can never DOWNGRADE a stream (a
        # critical stream re-breaching after one clean snapshot must
        # not fall back to degraded on streak arithmetic)
        if _SEVERITY[new] > _SEVERITY[s.state]:
            s.state = new

    def _clear(self, s: _Stream) -> None:
        s.clears += 1
        # one clean evaluation breaks the CONSECUTIVE-breach streak
        # (critical_after means consecutive: ok↔degraded flapping at
        # the boundary must never escalate to critical) ...
        s.breaches = 0
        # ... but recovery itself is hysteretic: the state clears only
        # after recover_after consecutive clean evaluations
        if s.state != OK and s.clears >= self.recover_after:
            s.state = OK

    def _evaluate_rule(self, rule: Rule, rank, metrics: Dict[str, Any]
                       ) -> None:
        value = resolve_metric(metrics, rule.metric)
        if value is None:
            return   # absent: neither breach nor clear
        s = self._stream(rule.name, rank)
        s.last_value = value
        if rule.kind in ("drift", "growth"):
            s.history.append(float(value))
            if len(s.history) > max(rule.window, 2):
                del s.history[:len(s.history) - rule.window]
        breached = False
        if rule.kind == "max":
            breached = value > rule.threshold
        elif rule.kind == "min":
            breached = value < rule.threshold
        elif rule.kind == "drift":
            trailing = s.history[:-1]
            if len(trailing) >= 3:   # single/small windows: not evaluable
                st = _stats()
                agg = st.summarize(trailing, warmup=0,
                                   max_spread=float("inf"))
                gate = max(rule.k * agg.iqr,
                           rule.rel_floor * abs(agg.median))
                breached = value < agg.median - gate
        elif rule.kind == "growth":
            h = s.history
            if len(h) >= max(rule.window, 4) and h[0] > 0:
                monotone = all(b >= a for a, b in zip(h, h[1:]))
                breached = (monotone
                            and (h[-1] - h[0]) / h[0] >= rule.min_growth)
        if breached:
            self._breach(s)
        else:
            self._clear(s)

    # -- feeding ------------------------------------------------------------

    def feed(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Consume one record; returns the overall-state transitions it
        caused (usually empty)."""
        ev = rec.get("event")
        if ev == "metrics_snapshot":
            metrics = rec.get("metrics")
            rank = rec.get("rank")
            if isinstance(metrics, dict):
                self._snapshots_seen += 1
                for rule in self.rules:
                    self._evaluate_rule(rule, rank, metrics)
                # a live, reporting rank is a clean observation for its
                # failure pseudo-rule (the elastic-recovery half); the
                # rank-None stream (attempt-level elastic_worker_exit
                # events carry no rank) is cleared by ANY snapshot —
                # a reporting world is the evidence the job came back
                for key in ((FAILURE_RULE, rank), (FAILURE_RULE, None)):
                    if key in self._streams:
                        self._clear(self._streams[key])
                # a fleet snapshot names its live replica set
                # (serve/fleet/router.py): each named replica is a
                # clean observation for ITS failure stream — the
                # replica_failed events key on rank = replica id, so a
                # revived replica shows as degraded → ok with replica
                # attribution
                reps = rec.get("replicas")
                if isinstance(reps, (list, tuple)):
                    for r in reps:
                        key = (FAILURE_RULE, r)
                        if key in self._streams:
                            self._clear(self._streams[key])
        elif ev in ("worker_failure", "elastic_worker_exit",
                    "replica_failed"):
            s = self._stream(FAILURE_RULE, rec.get("rank"))
            s.breaches = max(s.breaches, self.degrade_after)
            s.total_breaches += 1
            s.clears = 0
            if s.state == OK:
                s.state = DEGRADED
            s.last_value = rec.get("exitcode")
        elif ev == "elastic_giveup":
            self._breach(self._stream(FAILURE_RULE, rec.get("rank")),
                         critical=True)
        else:
            return []
        return self._update_overall(rec)

    def _worst(self) -> Tuple[str, Optional[Tuple[str, Any, _Stream]]]:
        worst_state, worst = OK, None
        for (rule_name, rank), s in self._streams.items():
            if _SEVERITY[s.state] > _SEVERITY[worst_state]:
                worst_state = s.state
                worst = (rule_name, rank, s)
        return worst_state, worst

    def _update_overall(self, rec: Dict[str, Any]
                        ) -> List[Dict[str, Any]]:
        new_state, worst = self._worst()
        if new_state == self.state:
            return []
        if worst is None and self.transitions:
            # a recovery to ok has no firing stream — attribute it to
            # the rule that last degraded the monitor, so the
            # degraded → ok transition still names what recovered
            prev = self.transitions[-1]
            rule_name, rank, stream = prev["rule"], prev["rank"], None
            metric = prev["metric"]
        else:
            rule_name, rank, stream = worst if worst else (None, None,
                                                           None)
            metric = next((r.metric for r in self.rules
                           if r.name == rule_name), rule_name)
        tr = {"from": self.state, "to": new_state,
              "rule": rule_name, "metric": metric, "rank": rank,
              "value": stream.last_value if stream else None,
              "time": rec.get("time")}
        self.state = new_state
        self.transitions.append(tr)
        if self.emit_path:
            try:
                from ..utils.logging import append_event
                append_event("health_transition", path=self.emit_path,
                             **{k: v for k, v in tr.items()
                                if k != "time"})
            except Exception:  # noqa: BLE001 — monitoring must never
                pass           # take down the monitored run
        return [tr]

    # -- verdicts -----------------------------------------------------------

    @property
    def snapshots_seen(self) -> int:
        return self._snapshots_seen

    def stream_states(self) -> List[Dict[str, Any]]:
        """EVERY (rule, rank) stream the monitor has ever tracked, with
        cumulative breach counts — the audit view a harness gates on
        (a recovered stream keeps its history here; :meth:`firing` is
        the live view)."""
        return [{"rule": rn, "rank": rank, "state": s.state,
                 "breaches": s.breaches,
                 "total_breaches": s.total_breaches,
                 "value": s.last_value}
                for (rn, rank), s in self._streams.items()]

    def firing(self) -> List[Dict[str, Any]]:
        """Streams currently not-ok, worst first — the attribution the
        ``critical`` verdict names."""
        rows = [{"rule": rn, "rank": rank, "state": s.state,
                 "breaches": s.breaches, "value": s.last_value}
                for (rn, rank), s in self._streams.items()
                if s.state != OK]
        rows.sort(key=lambda r: -_SEVERITY[r["state"]])
        return rows

    def verdict(self) -> Dict[str, Any]:
        return {"state": self.state,
                "snapshots": self._snapshots_seen,
                "transitions": list(self.transitions),
                "firing": self.firing()}


def scan_records(records: Iterable[Dict[str, Any]],
                 monitor: Optional[HealthMonitor] = None
                 ) -> HealthMonitor:
    """Replay records (time order as given) through a monitor."""
    mon = monitor if monitor is not None else HealthMonitor()
    for rec in records:
        mon.feed(rec)
    return mon


class LogFollower:
    """Incremental line-JSON reader for LIVE evaluation: each
    :meth:`poll` parses the complete lines appended since the last
    call, feeds them to the monitor, and returns the transitions. A
    torn final line (a writer mid-``os.write``) stays buffered until
    its newline arrives — the multi-writer stream is never
    half-parsed."""

    def __init__(self, path: str, monitor: HealthMonitor):
        self.path = path
        self.monitor = monitor
        self._offset = 0
        self._buf = b""

    def poll(self) -> List[Dict[str, Any]]:
        import json
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except OSError:
            return []
        if not chunk:
            return []
        self._offset += len(chunk)
        data = self._buf + chunk
        lines = data.split(b"\n")
        self._buf = lines.pop()   # b"" after a complete final line
        out: List[Dict[str, Any]] = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue   # damage is the validator's job, not ours
            if isinstance(rec, dict):
                out.extend(self.monitor.feed(rec))
        return out
