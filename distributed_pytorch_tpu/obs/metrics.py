"""dpxmon — the live runtime metrics registry (one per process).

dpxtrace (:mod:`.trace`) answers "what happened" after a failure; this
module answers "is this job healthy RIGHT NOW". The MLPerf-pod recipe
(PAPERS.md, arXiv 1909.09756) and the CUDA-aware-MPI characterization
(arXiv 1810.11112) both show that composition-scale pathologies —
throughput drift, straggler onset, memory creep — only appear over
SUSTAINED runs, so they must be detected from live telemetry, not from
a post-hoc trace merge. Three pieces:

* **Typed instruments** — :class:`Counter` (monotone), :class:`Gauge`
  (last value), :class:`Histogram` (cumulative count/sum/min/max plus a
  BOUNDED reservoir of the most recent values for window percentiles —
  a multi-week run must not fund percentile estimates with an unbounded
  list). Get-or-create by name via :func:`counter` / :func:`gauge` /
  :func:`histogram`, or the one-call forms :func:`inc` /
  :func:`set_gauge` / :func:`observe`.
* **Providers** — pull-model sources polled once per snapshot
  (:func:`register_provider`): CommStats per-op calls/bytes/exposed-vs-
  overlapped seconds (registered by ``HostComm.__init__``), process RSS
  and the dpxtrace flight-recorder drop counter (built in). Hot paths
  never pay for them.
* **Snapshots** — :func:`emit_snapshot` writes ONE rank-attributed
  ``metrics_snapshot`` line-JSON event through the locked ``O_APPEND``
  ``utils.logging.append_event`` path, so live metrics ride the same
  multi-writer stream as failure events and dpxtrace spans.
  :func:`on_train_step` is the train-loop hook: it counts steps,
  observes the inter-step cadence histogram, and auto-emits every
  ``DPX_MON_EVERY`` steps with a fresh ``train.steps_per_sec`` gauge.

Overhead contract (gated in ``bench.py --smoke``): with ``DPX_MON=0``
every instrument method is one module-global read + one ``if`` —
the same disabled-path shape as dpxtrace spans (<= 2 µs/increment
asserted); enabled increments are a per-instrument-locked field
update (counters/histograms are fed from arbitrary threads — the
serve engines' caller threads — and the lock is far inside the gated
15 µs budget). Snapshot emission
costs one provider poll + reservoir percentiles + one locked write,
amortized over the cadence (the smoke asserts the amortized fraction
of the measured dp8 step).

The streaming health evaluator over these snapshots is
:mod:`.health`; the operator CLI is ``tools/dpxmon.py``. Everything
here is stdlib-only with lazy cross-package imports (the
``analysis/lint.py`` contract), so the dpxmon CLI loads this module in
a bare venv.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "MON_ENV", "EVERY_ENV", "RESERVOIR_CAP",
    "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram", "inc", "set_gauge", "observe",
    "register_provider", "unregister_provider",
    "enabled", "configure", "refresh", "reset", "set_rank",
    "snapshot", "emit_snapshot", "on_train_step", "validate_snapshot",
]

#: Env var: master switch for metric recording (0 = every instrument is
#: a no-op costing one global read).
MON_ENV = "DPX_MON"
#: Env var: auto-emit a snapshot every N train steps (0 disables the
#: automatic train-loop cadence; explicit emit_snapshot always works).
EVERY_ENV = "DPX_MON_EVERY"

#: Bounded histogram reservoir: percentiles are over the most recent
#: this-many observations (cumulative count/sum/min/max never drop).
RESERVOIR_CAP = 256


def _envreg():
    # lazy: this module must import with NOTHING but stdlib available
    # (the dpxmon CLI loads it in a bare venv)
    from ..runtime import env
    return env


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------


class Counter:
    """Monotone event count. Snapshot value: the cumulative total.
    Incremented from arbitrary threads (the serve engines' caller
    threads), so the read-modify-write is locked."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        st = _state
        if st is None or not st.enabled:
            return
        with self._lock:
            self.value += n

    def snap(self):
        return self.value


class Gauge:
    """Last-set value (queue depth, occupancy, steps/s)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        st = _state
        if st is None or not st.enabled:
            return
        self.value = float(v)

    def snap(self):
        return self.value


class Histogram:
    """Cumulative count/sum/min/max + a bounded reservoir of the most
    recent :data:`RESERVOIR_CAP` observations for window percentiles
    (p50/p99 of the RECENT window — the SLO-rule view; overwrites of
    older observations are implicit and bounded by construction)."""

    __slots__ = ("name", "count", "sum", "min", "max", "recent",
                 "_lock")

    def __init__(self, name: str, cap: int = RESERVOIR_CAP):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.recent: collections.deque = collections.deque(maxlen=cap)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        st = _state
        if st is None or not st.enabled:
            return
        v = float(v)
        # locked: observed from arbitrary threads; the reservoir must
        # also never mutate under snap()'s sorted() iteration
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            self.recent.append(v)

    def snap(self) -> Optional[Dict[str, float]]:
        if not self.count:
            return None
        def q(xs, frac: float) -> float:
            return xs[min(len(xs) - 1, int(frac * (len(xs) - 1) + 0.5))]

        with self._lock:   # one consistent view of all five fields
            xs = sorted(self.recent)
            return {"count": self.count, "sum": round(self.sum, 6),
                    "min": self.min, "max": self.max,
                    "p50": q(xs, 0.50), "p99": q(xs, 0.99)}


# ---------------------------------------------------------------------------
# process-local registry state
# ---------------------------------------------------------------------------


class _State:
    __slots__ = ("enabled", "every", "instruments", "providers", "rank",
                 "lock", "steps", "last_emit_steps", "last_emit_ns",
                 "last_step_ns")

    def __init__(self, enabled: bool, every: int):
        self.enabled = enabled
        self.every = max(int(every), 0)
        self.instruments: Dict[str, Any] = {}
        self.providers: Dict[str, Callable[[], Dict[str, Any]]] = {}
        self.rank: Optional[int] = None
        self.lock = threading.Lock()
        self.steps = 0
        self.last_emit_steps = 0
        self.last_emit_ns: Optional[int] = None
        self.last_step_ns: Optional[int] = None


_state: Optional[_State] = None
_state_lock = threading.Lock()


def _init() -> _State:
    global _state
    with _state_lock:
        if _state is None:
            env = _envreg()
            _state = _State(enabled=bool(env.get(MON_ENV)),
                            every=int(env.get(EVERY_ENV)))
        return _state


def refresh() -> None:
    """Re-read the ``DPX_MON*`` knobs; keeps rank, drops instruments
    (tests and long-lived drivers that flip the env mid-process)."""
    global _state
    rank = None
    with _state_lock:
        if _state is not None:
            rank = _state.rank
        _state = None
    _init().rank = rank


def configure(enabled: Optional[bool] = None,
              every: Optional[int] = None,
              rank: Optional[int] = None) -> None:
    """Programmatic override of the env-derived config (benchmark arms,
    tests). Only the named fields change."""
    st = _init()
    if enabled is not None:
        st.enabled = bool(enabled)
    if every is not None:
        st.every = max(int(every), 0)
    if rank is not None:
        st.rank = int(rank)


def reset() -> None:
    """Drop all state (test isolation); next use re-reads the env."""
    global _state
    with _state_lock:
        _state = None


def enabled() -> bool:
    st = _state if _state is not None else _init()
    return st.enabled


def set_rank(rank: int) -> None:
    """Stamp this process's rank onto every subsequent snapshot (called
    by ``HostComm.__init__`` alongside ``trace.set_rank``)."""
    _init().rank = int(rank)


def _instrument(name: str, cls):
    st = _state if _state is not None else _init()
    inst = st.instruments.get(name)
    if inst is None:
        with st.lock:
            inst = st.instruments.get(name)
            if inst is None:
                inst = st.instruments[name] = cls(name)
    if not isinstance(inst, cls):
        raise TypeError(f"metric {name!r} is a {type(inst).__name__}, "
                        f"requested as {cls.__name__}")
    return inst


def counter(name: str) -> Counter:
    return _instrument(name, Counter)


def gauge(name: str) -> Gauge:
    return _instrument(name, Gauge)


def histogram(name: str) -> Histogram:
    return _instrument(name, Histogram)


def inc(name: str, n: int = 1) -> None:
    st = _state if _state is not None else _init()
    if not st.enabled:
        return
    counter(name).inc(n)


def set_gauge(name: str, v: float) -> None:
    st = _state if _state is not None else _init()
    if not st.enabled:
        return
    gauge(name).set(v)


def observe(name: str, v: float) -> None:
    st = _state if _state is not None else _init()
    if not st.enabled:
        return
    histogram(name).observe(v)


# ---------------------------------------------------------------------------
# providers (pull model, polled once per snapshot)
# ---------------------------------------------------------------------------


def register_provider(name: str,
                      fn: Callable[[], Dict[str, Any]]) -> None:
    """Register ``fn() -> {metric name: number}``, polled at snapshot
    time. Re-registering a name replaces the provider (elastic children
    and tests rebuild comms; the newest is the live one)."""
    _init().providers[name] = fn


def unregister_provider(name: str) -> None:
    _init().providers.pop(name, None)


def _rss_bytes() -> Optional[int]:
    """Current resident set, /proc (Linux); None where unavailable."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
    except (OSError, ValueError, IndexError):
        return None


def _builtin_metrics() -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    rss = _rss_bytes()
    if rss is not None:
        out["proc.rss_bytes"] = rss
    # dpxtrace flight-recorder accounting: recorded spans + counted
    # drops (0/0 when tracing is off — still reported, the health rule
    # vocabulary expects the key space to be stable)
    from . import trace as _trace
    tst = _trace._state
    if tst is not None:
        out["obs.spans_recorded"] = tst.recorded
        out["obs.flight_dropped"] = tst.dropped
    return out


# ---------------------------------------------------------------------------
# snapshots
# ---------------------------------------------------------------------------


def snapshot() -> Dict[str, Any]:
    """The registry's current view: every instrument (histograms as
    ``{count,sum,min,max,p50,p99}`` dicts) + one poll of every provider
    + the built-ins (RSS, flight-recorder drops). Unset gauges and
    empty histograms are omitted — absent means never-observed, and the
    health evaluator treats absent as not-evaluable, never as zero."""
    st = _state if _state is not None else _init()
    out: Dict[str, Any] = {}
    for name, inst in list(st.instruments.items()):
        v = inst.snap()
        if v is not None:
            out[name] = v
    for pname, fn in list(st.providers.items()):
        try:
            polled = fn() or {}
        except Exception:  # noqa: BLE001 — a provider must never take
            continue       # down the snapshot path
        for k, v in polled.items():
            if v is not None:
                out[k] = v
    out.update(_builtin_metrics())
    return out


def _resolve_rank(st: _State) -> Optional[int]:
    if st.rank is not None:
        return st.rank
    from . import trace as _trace
    if _trace._state is not None and _trace._state.rank is not None:
        return _trace._state.rank
    try:
        from ..runtime import context
        return int(context.get_rank())
    except Exception:  # noqa: BLE001 — bare-venv / pre-init use
        return None


def emit_snapshot(path: Optional[str] = None,
                  step: Optional[int] = None,
                  source: str = "process", **extra) -> bool:
    """Write ONE rank-attributed ``metrics_snapshot`` line-JSON event
    (``path`` defaults to ``$DPX_METRICS_LOG`` via ``append_event``).
    No-op (False) when recording is disabled or no sink is configured —
    observability must never take down the instrumented path."""
    st = _state if _state is not None else _init()
    if not st.enabled:
        return False
    try:
        snap = snapshot()
        from ..utils.logging import append_event
        return append_event("metrics_snapshot", path=path,
                            rank=_resolve_rank(st), step=step,
                            source=source, metrics=snap, **extra)
    except Exception:  # noqa: BLE001
        return False


def on_train_step(source: str = "train") -> None:
    """Train-loop hook (the host/front-door steps call it once per
    step): counts ``train.steps``, observes the inter-step cadence into
    ``train.step_ms``, and — every ``DPX_MON_EVERY`` steps — refreshes
    ``train.steps_per_sec`` from the wall delta since the last emission
    and writes a snapshot. One global read + one ``if`` when disabled
    (the bench-smoke hot-path contract)."""
    st = _state if _state is not None else _init()
    if not st.enabled:
        return
    now = time.perf_counter_ns()
    st.steps += 1
    counter("train.steps").inc()
    if st.last_step_ns is not None:
        histogram("train.step_ms").observe((now - st.last_step_ns) / 1e6)
    st.last_step_ns = now
    if st.every and st.steps % st.every == 0:
        if st.last_emit_ns is not None and now > st.last_emit_ns:
            sps = ((st.steps - st.last_emit_steps)
                   / ((now - st.last_emit_ns) / 1e9))
            gauge("train.steps_per_sec").set(round(sps, 3))
        st.last_emit_ns = now
        st.last_emit_steps = st.steps
        emit_snapshot(step=st.steps, source=source)


# ---------------------------------------------------------------------------
# strict snapshot validation (the dpxmon `check` contract)
# ---------------------------------------------------------------------------

_HIST_KEYS = ("count", "sum", "min", "max", "p50", "p99")


def validate_snapshot(rec: Dict[str, Any]) -> List[str]:
    """Strictly validate one ``metrics_snapshot`` record. Returns issue
    strings (empty = valid): rank attribution is REQUIRED (a per-rank
    metric stream that cannot say which rank it came from is
    ungreppable in a multi-writer log), ``metrics`` must be a dict of
    name -> number | histogram-summary, histogram summaries must carry
    every expected key as a number."""
    issues: List[str] = []
    if not isinstance(rec.get("rank"), int):
        issues.append("metrics_snapshot carries no integer rank "
                      "attribution")
    if not isinstance(rec.get("time"), (int, float)):
        issues.append("metrics_snapshot carries no numeric time")
    if not isinstance(rec.get("source"), str):
        issues.append("metrics_snapshot carries no source")
    metrics = rec.get("metrics")
    if not isinstance(metrics, dict):
        issues.append("metrics_snapshot carries no metrics dict")
        return issues
    for name, v in metrics.items():
        if isinstance(v, bool) or not isinstance(v, (int, float, dict)):
            issues.append(f"metric {name!r}: value {v!r} is neither a "
                          f"number nor a histogram summary")
        elif isinstance(v, dict):
            for k in _HIST_KEYS:
                if not isinstance(v.get(k), (int, float)) \
                        or isinstance(v.get(k), bool):
                    issues.append(f"metric {name!r}: histogram summary "
                                  f"missing numeric {k!r}")
                    break
    return issues
