"""dpxtrace — the one span-tracing spine shared by train and serve.

The repo's per-op time breakdowns were siloed: ``CommStats`` books comm
seconds, ``serve/metrics.py`` books TTFT/TPOT, ckpt has its own phase
trace — and nothing correlates them ACROSS ranks or across the
prefill→decode split. The MLPerf-pod recipe (PAPERS.md, arXiv
1909.09756) starts every scaling investigation from a per-op time
breakdown, and the CUDA-aware-MPI characterization (arXiv 1810.11112)
shows the interesting distributed pathologies (stragglers, exposed
comm, skewed ranks) only appear when per-rank timelines are laid side
by side. This module is the spine that makes that view exist:

* **Spans** — ``with span("comm:allreduce", bytes=n):`` records one
  timed region with ``trace_id``/``span_id``/``parent_id`` lineage.
  Timing is ``perf_counter_ns`` (monotone, ns resolution); every span
  additionally carries a wall-clock anchor mapping (ONE
  ``time.time()``/``perf_counter_ns()`` pair captured per process at
  import) so cross-process merges have a common time base without any
  per-span wall read. Ambient nesting is per-thread (the serve engine
  thread's spans parent under its own stack, never the submitter's).
* **Flight recorder** — every finished span also lands in a bounded
  per-process ring (``DPX_TRACE_RING`` spans, drop-counted). Typed
  failure paths (``CommError``, ``HandoffError``, ``PagePoolExhausted``,
  ``WorkerFailure``) call :func:`on_typed_failure`, which dumps the
  ring's last-N spans as ONE ``flight_recorder`` line-JSON event — so a
  chaos kill ships a postmortem timeline from every survivor with zero
  operator action.
* **Sink** — spans append to the ``DPX_TRACE_LOG`` line-JSON file
  (default: the ``DPX_METRICS_LOG`` stream failure events already ride)
  as ``trace_span`` events through the multi-writer-safe
  ``utils.logging.append_event`` path. ``tools/dpxtrace.py`` merges
  per-rank logs into Chrome trace-event JSON (:mod:`.export`) and runs
  the straggler detector (:mod:`.detect`).

Overhead contract (gated in ``bench.py --smoke``): with ``DPX_TRACE``
off, :func:`span` is one module-global read + one ``if`` returning a
shared no-op context manager — unmeasurable next to any op worth
tracing. With tracing on, a span costs one ``perf_counter_ns`` pair,
a dict build, a ring append and one locked O_APPEND write; the smoke
asserts the per-step total stays a small fraction of the dp8 step.

Wall-anchor discipline: :func:`wall_now` is the ONE wall-clock stamp
the framework's loggers use (``utils/logging.py``) — anchor wall time
plus elapsed ``perf_counter_ns``, so within-process event timestamps
are monotone non-decreasing even when the system clock steps (NTP).
The dpxlint rule DPX007 keeps ``time.time()`` out of duration math
package-wide.

Everything here is stdlib-only; the env registry is imported lazily so
``tools/dpxtrace.py`` can load this module in a bare venv without the
heavy package ``__init__`` (the ``analysis/lint.py`` contract).
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "TRACE_ENV", "RING_ENV", "LOG_ENV",
    "span", "event", "emit_span", "new_trace_id", "enabled", "refresh",
    "configure", "set_rank", "wall_now", "wall_from_ns", "wall_from_mono",
    "flight_snapshot", "flight_dump", "on_typed_failure", "reset",
]

#: Env var: master switch for span recording (off = near-zero overhead).
TRACE_ENV = "DPX_TRACE"
#: Env var: flight-recorder ring capacity in spans (0 disables the ring).
RING_ENV = "DPX_TRACE_RING"
#: Env var: span sink path (default: the DPX_METRICS_LOG stream).
LOG_ENV = "DPX_TRACE_LOG"

# ---------------------------------------------------------------------------
# wall anchor: ONE (wall, perf_counter_ns, monotonic) triple per process.
# Every duration is perf_counter_ns math; every wall stamp is anchor +
# elapsed — so stamps are monotone and cross-clock conversions exact.
# ---------------------------------------------------------------------------

_ANCHOR_WALL = time.time()
_ANCHOR_NS = time.perf_counter_ns()
_ANCHOR_MONO = time.monotonic()


def wall_now() -> float:
    """Monotone wall-clock stamp: anchor + elapsed ``perf_counter_ns``.
    The framework's loggers use this instead of ``time.time()`` so a
    stepping system clock can never make event timestamps go backwards
    within a process."""
    return _ANCHOR_WALL + (time.perf_counter_ns() - _ANCHOR_NS) / 1e9


def wall_from_ns(ns: int) -> float:
    """Wall seconds of a ``perf_counter_ns`` stamp from THIS process."""
    return _ANCHOR_WALL + (ns - _ANCHOR_NS) / 1e9


def wall_from_mono(t: float) -> float:
    """Wall seconds of a ``time.monotonic()`` stamp from THIS process
    (the serve request lifecycle records monotonic timestamps)."""
    return _ANCHOR_WALL + (t - _ANCHOR_MONO)


# ---------------------------------------------------------------------------
# process-local state
# ---------------------------------------------------------------------------


class _State:
    __slots__ = ("enabled", "ring", "ring_cap", "dropped", "recorded",
                 "rank", "log_path", "log_fd", "lock", "last_dump_n")

    def __init__(self, enabled: bool, ring_cap: int,
                 log_path: Optional[str], rank: Optional[int]):
        self.enabled = enabled
        self.ring_cap = max(int(ring_cap), 0)
        self.ring: collections.deque = collections.deque(
            maxlen=self.ring_cap or 1)
        self.dropped = 0
        self.recorded = 0
        self.rank = rank
        self.log_path = log_path
        self.log_fd: Optional[int] = None   # cached O_APPEND sink fd
        self.lock = threading.Lock()
        self.last_dump_n = -1

    def close_fd(self) -> None:
        fd, self.log_fd = self.log_fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass


_state: Optional[_State] = None
_state_lock = threading.Lock()
_ids = itertools.count(1)
_tls = threading.local()


def _envreg():
    # lazy: this module must import with NOTHING but stdlib available
    # (the dpxtrace CLI loads it in a bare venv)
    from ..runtime import env
    return env


def _init() -> _State:
    global _state
    with _state_lock:
        if _state is None:
            env = _envreg()
            _state = _State(
                enabled=bool(env.get(TRACE_ENV)),
                ring_cap=int(env.get(RING_ENV)),
                log_path=env.get(LOG_ENV) or env.get("DPX_METRICS_LOG"),
                rank=None)
        return _state


def refresh() -> None:
    """Re-read the ``DPX_TRACE*`` knobs (tests and long-lived drivers
    that flip the env mid-process; child processes re-read at import).
    Keeps the rank but drops the ring."""
    global _state
    rank = None
    with _state_lock:
        if _state is not None:
            rank = _state.rank
            _state.close_fd()
        _state = None
    st = _init()
    st.rank = rank


def configure(enabled: Optional[bool] = None,
              ring: Optional[int] = None,
              log_path: Optional[str] = "__unset__",
              rank: Optional[int] = None) -> None:
    """Programmatic override of the env-derived config (benchmark arms,
    tests). Only the named fields change."""
    st = _init()
    if enabled is not None:
        st.enabled = bool(enabled)
    if ring is not None:
        st.ring_cap = max(int(ring), 0)
        st.ring = collections.deque(maxlen=st.ring_cap or 1)
        st.dropped = 0
    if log_path != "__unset__":
        with st.lock:
            st.close_fd()
            st.log_path = log_path
    if rank is not None:
        st.rank = int(rank)


def reset() -> None:
    """Drop all state (test isolation); next use re-reads the env."""
    global _state
    with _state_lock:
        if _state is not None:
            _state.close_fd()
        _state = None
    _tls.__dict__.pop("stack", None)


def enabled() -> bool:
    st = _state if _state is not None else _init()
    return st.enabled


def set_rank(rank: int) -> None:
    """Stamp this process's rank onto every subsequent span (called by
    ``HostComm.__init__`` / the process-group front door)."""
    _init().rank = int(rank)


def new_trace_id() -> str:
    """A process-unique trace id (pid-scoped counter — deterministic,
    collision-free across the ranks of one host-group launch)."""
    return f"{os.getpid():x}-{next(_ids):x}"


def _new_span_id() -> str:
    return f"{os.getpid():x}.{next(_ids):x}"


def _stack() -> List["_Span"]:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------


def _record(st: _State, rec: Dict[str, Any]) -> None:
    """Ring append (drop-counted) + line-JSON sink. Never raises: a
    tracing failure must not take down the traced op.

    The sink is a CACHED ``O_APPEND`` fd with one ``os.write`` per span
    under the state lock — the same single-write-per-line multi-writer
    contract as ``utils.logging.append_event`` (which opens per event;
    spans are ~100x more frequent than failure events, so the sink
    amortizes the open — the bench smoke gates the resulting cost
    against the dp8 step). The record shape matches ``append_event``'s
    (``event``/``time`` first), so the merged stream stays uniform."""
    line = None
    try:
        with st.lock:
            if st.ring_cap and len(st.ring) == st.ring_cap:
                st.dropped += 1
            if st.ring_cap:
                st.ring.append(rec)
            st.recorded += 1
        if st.log_path:
            out = {"event": "trace_span", "time": rec.get("t0_wall"),
                   **rec}
            try:
                # compact, no default hook: span records are built from
                # JSON-native values; the fallback keeps odd attrs safe
                text = json.dumps(out, separators=(",", ":"))
            except (TypeError, ValueError):
                text = json.dumps(out, default=str)
            line = (text + "\n").encode()
            with st.lock:
                if st.log_fd is None:
                    st.log_fd = os.open(
                        st.log_path,
                        os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
                os.write(st.log_fd, line)
    except Exception:
        pass


class _NullSpan:
    """The disabled path: a shared, reusable no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def event(self, name: str, **attrs) -> None:
        pass

    @property
    def span_id(self) -> None:
        return None

    trace_id = None


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "tid",
                 "attrs", "events", "t0_ns", "t1_ns", "_st", "_ambient")

    def __init__(self, st: _State, name: str, trace_id: Optional[str],
                 parent_id: Optional[str], tid: Optional[str],
                 attrs: Dict[str, Any]):
        self._st = st
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.tid = tid
        self.attrs = attrs
        self.events: List[Tuple[str, int, Dict[str, Any]]] = []
        self.t0_ns = 0
        self.t1_ns = 0
        self._ambient = False

    def __enter__(self) -> "_Span":
        stack = _stack()
        if self.parent_id is None and stack:
            top = stack[-1]
            self.parent_id = top.span_id
            if self.trace_id is None:
                self.trace_id = top.trace_id
        stack.append(self)
        self._ambient = True
        self.t0_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.t1_ns = time.perf_counter_ns()
        if self._ambient:
            stack = _stack()
            if stack and stack[-1] is self:
                stack.pop()
            elif self in stack:           # unbalanced exit: repair
                stack.remove(self)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._finish()
        return False

    def event(self, name: str, **attrs) -> None:
        """Instant event attached to this span's timeline."""
        self.events.append((name, time.perf_counter_ns(), attrs))

    def _finish(self) -> None:
        st = self._st
        rec: Dict[str, Any] = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t0_wall": wall_from_ns(self.t0_ns),
            "dur_ns": self.t1_ns - self.t0_ns,
            "rank": st.rank,
            "pid": os.getpid(),
            "tid": self.tid or threading.current_thread().name,
        }
        if self.attrs:
            rec["attrs"] = self.attrs
        if self.events:
            rec["events"] = [
                {"name": n, "t_wall": wall_from_ns(ns), **a}
                for n, ns, a in self.events]
        _record(st, rec)


def span(name: str, *, trace_id: Optional[str] = None,
         parent_id: Optional[str] = None, tid: Optional[str] = None,
         **attrs):
    """Open a timed span as a context manager.

    Disabled tracing returns a shared no-op (one global read + one
    ``if`` — the near-zero-overhead contract the bench smoke gates).
    ``trace_id``/``parent_id`` default to the ambient per-thread span
    stack; pass them explicitly to stitch lineage across threads (the
    serve request lifecycle does)."""
    st = _state if _state is not None else _init()
    if not st.enabled:
        return _NULL
    return _Span(st, name, trace_id, parent_id, tid, attrs)


def event(name: str, **attrs) -> None:
    """Record one instant event: attached to the ambient span when one
    is open (fault injections inside a collective), standalone
    otherwise. No-op when tracing is off."""
    st = _state if _state is not None else _init()
    if not st.enabled:
        return
    stack = _stack()
    if stack:
        stack[-1].event(name, **attrs)
        return
    now = time.perf_counter_ns()
    rec = {"name": name, "ph": "i",
           "trace_id": attrs.pop("trace_id", None),
           "span_id": _new_span_id(), "parent_id": None,
           "t0_wall": wall_from_ns(now), "dur_ns": 0,
           "rank": st.rank, "pid": os.getpid(),
           "tid": threading.current_thread().name}
    if attrs:
        rec["attrs"] = attrs
    _record(st, rec)


def emit_span(name: str, t0_wall: float, t1_wall: float, *,
              trace_id: Optional[str] = None,
              parent_id: Optional[str] = None,
              span_id: Optional[str] = None,
              tid: Optional[str] = None, **attrs) -> Optional[str]:
    """Record an ALREADY-TIMED span from explicit wall stamps (the serve
    lifecycle synthesizes its span tree at retirement from the request's
    recorded timestamps — :func:`wall_from_mono` converts them).
    Returns the span id (for parenting children), or None when tracing
    is off."""
    st = _state if _state is not None else _init()
    if not st.enabled:
        return None
    sid = span_id or _new_span_id()
    rec: Dict[str, Any] = {
        "name": name, "trace_id": trace_id, "span_id": sid,
        "parent_id": parent_id, "t0_wall": t0_wall,
        "dur_ns": max(int(round((t1_wall - t0_wall) * 1e9)), 0),
        "rank": st.rank, "pid": os.getpid(),
        "tid": tid or threading.current_thread().name,
    }
    if attrs:
        rec["attrs"] = attrs
    _record(st, rec)
    return sid


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def flight_snapshot() -> Tuple[List[Dict[str, Any]], int]:
    """(last-N span records, dropped count) of this process's ring."""
    st = _state if _state is not None else _init()
    with st.lock:
        return list(st.ring), st.dropped


def flight_dump(reason: str, rank: Optional[int] = None,
                **fields) -> bool:
    """Dump the flight recorder's last-N spans as ONE ``flight_recorder``
    line-JSON event (the postmortem timeline a failed rank ships).

    Idempotent per recording point — a teardown cascade that fails
    several ops in a row dumps once, like the schedule recorder's flush
    — and silent when the ring is empty (a supervisor that never traced
    a span has no timeline to ship). ``rank`` is a fallback attribution
    when this process never learned its own; with neither, ``-1``
    ("this process is not a rank": a single-process serve engine, a
    campaign driver) — the dump must stay rank-attributed either way,
    that is the ``dpxtrace check`` contract. Never raises; returns
    whether a line was written."""
    st = _state if _state is not None else _init()
    if not st.enabled or not st.log_path:
        return False
    try:
        with st.lock:
            if st.recorded == st.last_dump_n or not st.ring:
                return False
            st.last_dump_n = st.recorded
            spans = list(st.ring)
            dropped = st.dropped
        from ..utils.logging import append_event
        return append_event(
            "flight_recorder", path=st.log_path, reason=reason,
            rank=st.rank if st.rank is not None
            else (rank if rank is not None else -1),
            pid=os.getpid(), n_spans=len(spans),
            dropped=dropped, spans=spans, **fields)
    except Exception:
        return False


#: Attribution attributes lifted off a typed error into the flight dump
#: (the PR 2/3 vocabulary: CommError op/rank/peer, ServeError
#: request/iteration, HandoffError engine, PagePoolExhausted
#: needed/free_pages, WorkerFailure exitcode/kind ...).
_ATTRIBUTION_ATTRS = ("op", "rank", "peer", "kind", "exitcode",
                      "request_id", "iteration", "engine", "needed",
                      "free_pages", "deadline_ms", "stage", "page",
                      "reason")


def on_typed_failure(exc: BaseException, **extra) -> bool:
    """Flight-dump on a typed failure path: reason = the exception class
    name, fields = its attribution attributes. The call sites are the
    raise points of the typed vocabularies (``HostComm._check``, the
    serve engines' fail paths, the multiprocess supervisor) — best
    effort by contract, it must never mask the error it annotates."""
    try:
        fields: Dict[str, Any] = {}
        for attr in _ATTRIBUTION_ATTRS:
            v = getattr(exc, attr, None)
            if v is not None and not callable(v):
                fields["err_" + attr] = v
        fields.update(extra)
        rank = fields.get("err_rank")
        return flight_dump(type(exc).__name__,
                           rank=rank if isinstance(rank, int) else None,
                           error=str(exc)[:300], **fields)
    except Exception:
        return False
