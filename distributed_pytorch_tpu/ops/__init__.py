"""Ops: losses and TPU (Pallas) kernels with portable fallbacks."""
from . import losses
from .losses import cross_entropy, cross_entropy_per_example
