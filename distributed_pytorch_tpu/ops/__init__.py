"""Ops: losses and TPU (Pallas) kernels with portable fallbacks."""
from . import losses
from .decode_attention import (blockwise_decode_attention,
                               paged_decode_attention)
from .flash_attention import (flash_attention, flash_attention_with_lse,
                              make_flash_attn_fn)
from .losses import (cross_entropy, cross_entropy_per_example,
                     fused_linear_cross_entropy,
                     make_vocab_parallel_ce_fn,
                     vocab_parallel_cross_entropy)
