"""Page-blockwise decode attention — the single-token attention kernel
shared by ``generate()`` and every serving engine.

The dense decode path pays O(cache width) per token regardless of how
many positions are actually resident: a slot pool sized for 4096-token
requests charges a 32-token request the full 4096-wide softmax every
step. This module replaces that with the online-softmax block merge the
flash kernel already uses (``ops/flash_attention.py`` —
``lse = logaddexp(lse1, lse2)``, partials rescaled by ``exp(m_old -
m_new)``), run as a ``lax.fori_loop`` over KV *blocks* whose trip count
is the TRACED number of resident blocks:

    n_blocks = max(lengths) // block_len + 1          (<= total blocks)

One compiled program serves every request mix (the loop bound is data,
not shape), and per-token attention cost scales with the blocks that
actually hold keys — dead pages past every slot's length are never
gathered, never multiplied, never even touched (the contract tests
poison them with NaN to prove it).

Numerics contract (the mixed-precision guard, docs/compute.md):

- softmax statistics (running max ``m``, normalizer ``l``) and the
  output accumulator are **float32** regardless of cache dtype — the
  same f32-stats rule the flash kernel and ``nn.attention
  .dense_attention`` follow, so bf16 caches cannot silently degrade
  softmax accumulation;
- masked logits use a large-negative finite sentinel (``_MASK``), not
  ``-inf``: a visited block that is fully masked for a short row would
  otherwise poison the merge with ``-inf - -inf = NaN`` (and the
  ``exp(0) = 1`` rescue of an all-`_MASK` block is closed by masking
  the probabilities to exact zeros);
- the p@v matmul runs with the probabilities cast to the cache dtype
  and ``preferred_element_type=float32`` (the FlashAttention-2 recipe:
  bf16 on the MXU's native path, f32 accumulation).

Every decode front door routes here (``models/generate.py``:
``decode_step``, ``decode_step_slots``, ``decode_step_slots_paged``),
so ``serve/cache.py``, ``serve/pages/``, and both the monolithic and
disaggregated engines share one kernel. The sliding-window rolling
cache keeps the dense path: its width IS the window, so every slot is
potentially resident and there is nothing to skip.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .flash_attention import _MASK

__all__ = ["DECODE_BLOCK", "blockwise_decode_attention",
           "dense_decode_attention", "paged_decode_attention",
           "resident_blocks"]

#: Default block length for CONTIGUOUS caches (``decode_step`` /
#: ``decode_step_slots``); paged pools use their ``page_len``. 128 =
#: one VPU lane width per gather on TPU, and small enough that a short
#: resident prefix in a long pool skips most of the width.
DECODE_BLOCK = 128


def resident_blocks(lengths, block_len: int, total_blocks: int):
    """Traced number of leading blocks holding any resident position.

    ``lengths`` are the CURRENT write positions (position ``lengths[b]``
    is being written this step, so ``lengths[b] + 1`` positions are
    live). The ONE definition of the loop bound — the kernels and the
    contract tests (`tests/test_compute_path.py`) both call it, so
    "the scan visits only ceil(len/block) blocks" is asserted against
    the same formula the kernel executes."""
    lengths = jnp.asarray(lengths)
    return jnp.minimum(jnp.max(lengths) // block_len + 1, total_blocks)


def dense_decode_attention(hq, k, v, pos_mask, *, scale):
    """The dense full-width decode softmax — the REFERENCE the
    blockwise kernel is contract-tested against, and the baseline the
    decode bench arm times. One definition for every ``blockwise=False``
    branch (decode_step / decode_step_slots / decode_step_slots_paged)
    and the sliding-window rolling cache, whose width IS the window.

    hq: (B, H, 1, Dh); k, v: (B, Hkv, W, Dh); pos_mask: (B, W) or
    (1, W) bool — True where the position is visible. The grouped
    einsum reads GQA kv zero-copy; softmax stats are f32 with probs
    cast back to ``v.dtype`` (the f32-stats contract); a row with NO
    visible position yields NaN, matching dense_attention/flash."""
    b, h, _, dh = hq.shape
    hkv = k.shape[1]
    hq_g = hq.reshape(b, hkv, h // hkv, 1, dh)
    logits = jnp.einsum("bngqd,bnkd->bngqk", hq_g, k).astype(
        jnp.float32) * scale                         # (B,Hkv,g,1,W)
    logits = jnp.where(pos_mask[:, None, None, None, :], logits,
                       -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bngqk,bnkd->bngqd", probs, v) \
        .reshape(b, h, 1, dh)


def _merge_block(carry, s, v_blk, valid):
    """One online-softmax merge step, f32 stats.

    carry = (m, l, acc): running max (B, Hkv, g, 1), normalizer
    (B, Hkv, g, 1), output accumulator (B, Hkv, g, 1, Dh) — all f32.
    s: (B, Hkv, g, 1, L) f32 logits with masked entries ALREADY at
    ``_MASK``; valid: (B, 1, 1, 1, L) bool; v_blk: (B, Hkv, L, Dh).
    """
    m, l, acc = carry
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp(_MASK - m_new) underflows to 0 once any real logit has been
    # seen, but while m_new is still the _MASK sentinel (every visited
    # position masked so far) it would be exp(0) = 1 — mask explicitly
    # so fully-masked blocks contribute exact zeros, never NaN.
    p = jnp.where(valid, jnp.exp(s - m_new[..., None]), 0.0)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1)
    # p@v in the cache dtype with f32 accumulation (flash recipe)
    pv = jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk,
        (((4,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32)       # (B, Hkv, g, 1, Dh)
    acc_new = alpha[..., None] * acc + pv
    return m_new, l_new, acc_new


def _finish(m, l, acc, out_dtype):
    # l == 0 cannot happen for a live decode row (position 0 is always
    # <= idx and block 0 is always visited), but a zero normalizer must
    # divide safely rather than emit inf — belt to the _MASK braces.
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe[..., None]).astype(out_dtype)


def blockwise_decode_attention(hq, k, v, idx, *, scale,
                               block_len: Optional[int] = None):
    """Single-token attention over a CONTIGUOUS cache, blockwise.

    hq: (B, H, 1, Dh) this step's queries; k, v: (B, Hkv, W, Dh) cache
    rows (Hkv divides H — GQA reads grouped); idx: (B,) int32 current
    positions (the mask exposes positions ``<= idx[b]``, matching the
    dense decode's ``pos_mask``). Returns o (B, H, 1, Dh) in v.dtype.

    Value-identical (up to f32 summation order) to

        softmax(where(pos <= idx, q k^T * scale, -inf)) @ v

    but only ``resident_blocks(idx, block_len, ...)`` leading blocks of
    the width are ever read — cost scales with occupancy, not capacity.
    """
    block_len = block_len or DECODE_BLOCK
    b, h, _, dh = hq.shape
    hkv, width = k.shape[1], k.shape[2]
    g = h // hkv
    hq_g = hq.reshape(b, hkv, g, 1, dh)
    total = -(-width // block_len)
    nb = resident_blocks(idx, block_len, total)

    def body(j, carry):
        # ragged tail: clip the gather indices into range; the position
        # mask kills the duplicated tail entries (pos >= width is never
        # <= idx because idx < width by the cache-capacity contract)
        pos = j * block_len + jnp.arange(block_len)
        span = jnp.clip(pos, 0, width - 1)
        k_blk = jnp.take(k, span, axis=2)
        v_blk = jnp.take(v, span, axis=2)
        valid = ((pos[None, :] <= idx[:, None])
                 & (pos[None, :] < width))            # (B, L)
        s = jax.lax.dot_general(
            hq_g.astype(k_blk.dtype), k_blk,
            (((4,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32) * scale
        valid5 = valid[:, None, None, None, :]
        s = jnp.where(valid5, s, _MASK)
        return _merge_block(carry, s, v_blk, valid5)

    carry = (jnp.full((b, hkv, g, 1), _MASK, jnp.float32),
             jnp.zeros((b, hkv, g, 1), jnp.float32),
             jnp.zeros((b, hkv, g, 1, dh), jnp.float32))
    m, l, acc = jax.lax.fori_loop(0, nb, body, carry)
    return _finish(m, l, acc, v.dtype).reshape(b, h, 1, dh)


def paged_decode_attention(hq, k_pages, v_pages, tables, idx, new_k,
                           new_v, *, scale, page_len: int,
                           k_scales=None, v_scales=None,
                           k_tail=None, v_tail=None):
    """Single-token attention over a PAGED pool, one page per step.

    hq: (B, H, 1, Dh); k_pages/v_pages: (n_pages[+1], Hkv, page_len,
    Dh) pool buffers (an out-of-range table id reads garbage a masked
    position never exposes); tables: (B, P) int32 page ids; idx: (B,)
    int32 positions; new_k/new_v: (B, Hkv, 1, Dh) — THIS step's K/V,
    re-selected at position ``idx[b]`` so rows whose pool scatter was
    dropped (inactive slots) still see their own key, value-identical
    to ``decode_step_slots``' write-mask semantics.

    Visits only ``resident_blocks(idx, page_len, P)`` pages: the page
    gather itself is inside the loop, so a long pool serving short
    requests neither reads nor multiplies its dead pages.

    **Quantized resident pool** (``serve/pages``, docs/serving.md):
    when ``k_scales``/``v_scales`` are given, the pool buffers hold
    block-quantized int pages (int8 at q8; nibble-packed uint8 with
    ``Dh/2`` last dim at q4) and ``k_scales``/``v_scales`` are their
    ``(n_pages[+1], nb)`` f32 per-page-per-block scales — dequant rides
    the page gather (one scale lookup + multiply per page, f32 math).
    ``k_tail``/``v_tail`` ``(B, Hkv, page_len, Dh)`` f32 are the
    per-slot EXACT tail pages (positions not yet quantized): the page
    holding position ``idx[b]`` is overlaid wholesale from the tail
    buffer, so un-finalized positions attend exactly and quantization
    error only ever comes from completed pages' single rounding. All
    four default to None = the exact path, traced jaxpr unchanged.
    """
    b, h, _, dh = hq.shape
    hkv = k_pages.shape[1]
    g = h // hkv
    hq_g = hq.reshape(b, hkv, g, 1, dh)
    total = tables.shape[1]
    nb = resident_blocks(idx, page_len, total)
    nk_g = new_k.reshape(b, hkv, 1, dh)
    nv_g = new_v.reshape(b, hkv, 1, dh)
    quant = k_scales is not None
    if quant:
        from .quant import (dequantize_page_blocks, page_block_map,
                            unpack_page_nibbles)
        packed = k_pages.dtype == jnp.uint8
        bmap = page_block_map(hkv, page_len, dh)
        tail_page = idx // page_len

    def body(j, carry):
        pids = jax.lax.dynamic_index_in_dim(tables, j, axis=1,
                                            keepdims=False)     # (B,)
        k_blk = jnp.take(k_pages, pids, axis=0)  # (B, Hkv, L, Dh)
        v_blk = jnp.take(v_pages, pids, axis=0)
        pos = j * page_len + jnp.arange(page_len)
        if quant:
            if packed:
                k_blk = unpack_page_nibbles(k_blk)
                v_blk = unpack_page_nibbles(v_blk)
            k_blk = dequantize_page_blocks(
                k_blk, jnp.take(k_scales, pids, axis=0), bmap)
            v_blk = dequantize_page_blocks(
                v_blk, jnp.take(v_scales, pids, axis=0), bmap)
            # the slot's CURRENT page is exact: overlay the f32 tail
            # buffer before the write-mask overlay (order matters — wm
            # must still win for inactive rows' value semantics)
            it = (j == tail_page)[:, None, None, None]
            k_blk = jnp.where(it, k_tail, k_blk)
            v_blk = jnp.where(it, v_tail, v_blk)
        wm = (pos[None, :] == idx[:, None])[:, None, :, None]
        k_blk = jnp.where(wm, nk_g.astype(k_blk.dtype), k_blk)
        v_blk = jnp.where(wm, nv_g.astype(v_blk.dtype), v_blk)
        valid = (pos[None, :] <= idx[:, None])
        s = jax.lax.dot_general(
            hq_g.astype(k_blk.dtype), k_blk,
            (((4,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32) * scale
        valid5 = valid[:, None, None, None, :]
        s = jnp.where(valid5, s, _MASK)
        return _merge_block(carry, s, v_blk, valid5)

    carry = (jnp.full((b, hkv, g, 1), _MASK, jnp.float32),
             jnp.zeros((b, hkv, g, 1), jnp.float32),
             jnp.zeros((b, hkv, g, 1, dh), jnp.float32))
    m, l, acc = jax.lax.fori_loop(0, nb, body, carry)
    out_dtype = new_v.dtype if quant else v_pages.dtype
    return _finish(m, l, acc, out_dtype).reshape(b, h, 1, dh)
