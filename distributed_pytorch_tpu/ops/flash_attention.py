"""Flash attention as a Pallas TPU kernel (forward + backward).

The hot op of the Transformer rung (BASELINE.md ladder). The reference
repo has no attention at all (its model is two Linear layers, reference
``min_DDP.py:44-48``) — this kernel exists because our framework carries
full transformer workloads; it is designed for the TPU memory hierarchy
rather than translated from any CUDA kernel:

- Blockwise online-softmax (FlashAttention-2 schedule): O(S) memory
  instead of the O(S^2) probability matrix of ``nn.attention.dense_attention``.
- Q/K/V tiles staged through VMEM by the pallas grid pipeline; the
  (block_q, block_k) logits tile lives only in registers/VMEM.
- All matmuls hit the MXU with ``preferred_element_type=float32``;
  softmax statistics are kept in float32 even for bfloat16 inputs.
- The TPU grid executes the last axis sequentially (annotated
  "arbitrary"), which is what makes the scratch-accumulator pattern
  (m/l/acc carried across k-blocks) correct without atomics; the
  batch*head and outer block axes are annotated "parallel" so Mosaic can
  megacore-partition them.

Backward follows FlashAttention-2: recompute p = exp(qk - lse) blockwise;
one kernel accumulates dK/dV over q-blocks, a second accumulates dQ over
k-blocks. Residuals are (q, k, v, o, lse) — no S^2 tensor is ever saved.

Numerics are validated against ``dense_attention`` (values and grads) in
``tests/test_flash_attention.py`` using interpret mode on CPU.
"""

from __future__ import annotations

import functools
import logging
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..runtime.jax_compat import tpu_compiler_params as _compiler_params

# Large-negative mask value instead of -inf: -inf - (-inf) = NaN would
# poison the online-softmax rescaling for fully-masked tiles.
_MASK = -0.7 * float(jnp.finfo(jnp.float32).max)
_LANES = 128   # VPU lane width; m/l scratch replicates across lanes.
_STATS = 8     # trailing dim of row-stat arrays (lse, delta): the smallest
# width Mosaic's tiling accepts as a full trailing dimension, so stats cost
# 8 floats/row in HBM instead of a lane-replicated 128.

_PARALLEL = ("parallel", "parallel", "arbitrary")  # grid = (bh, outer, inner)


def _interpret_default(interpret):
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _ceil128(s):
    return -(-s // 128) * 128


def _block_sizes(s_q, s_k, block_q, block_k, d=64, bwd=False, window=None):
    """Resolve tile sizes. Explicit ints behave as before (clamped to the
    sequence); ``None`` picks the measured-best default for the chip.

    The on-chip sweep (benchmarks/flash_block_sweep.py, v5e, d=64) showed
    the kernel is grid-step-bound at moderate seq: 1024-wide tiles beat
    the old 128x128 default by 3-7x in forward (seq 4096: 1.87ms vs
    14.2ms) and XLA's dense path by up to 8.5x. Backward caps at 512 —
    its three (bq, bk) f32 tiles (p, dp, ds) triple the VMEM bill, and
    (512,512) measured within 8% of the s=1024 optimum. Caps shrink with
    head_dim since every tile scales with d. With sliding-window
    attention the k cap clamps near the window width instead — a k tile
    much wider than the band would compute mostly-masked logits and
    degrade the O(S*window) cost toward O(S*block_k)."""
    cap = (512 if d <= 64 else 256) if bwd else \
        (1024 if d <= 64 else (512 if d <= 128 else 256))
    cap_k = min(cap, max(128, _ceil128(window))) if window is not None \
        else cap
    bq = min(cap, _ceil128(s_q)) if block_q is None \
        else max(min(block_q, s_q), 1)
    bk = min(cap_k, _ceil128(s_k)) if block_k is None \
        else max(min(block_k, s_k), 1)
    return bq, bk


def _pad_seq(x, block, axis):
    s = x.shape[axis]
    pad = (-s) % block
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _frontier_ok(iq, ik, *, block_q, block_k, q_len, k_len, window=None,
                 diag_offset=0):
    """Whether k-tile ``ik`` intersects the causal-visible region of q-tile
    ``iq``. The ``k_len - q_len`` offset aligns the causal diagonal when
    s_q != s_k (query block i attends through absolute key position
    i + k_len - q_len); ``diag_offset`` shifts that diagonal further —
    the windowed-ring-hop contract where this kv block sits
    ``diag_offset`` positions EARLIER in the global sequence than the
    local indices suggest. With a sliding ``window`` the band has a
    LOWER edge too (row r sees cols (r+off-window, r+off]), so tiles
    entirely below it are skipped — that skip is what makes windowed
    attention O(S*window) instead of O(S^2/2). Single source of truth
    for fwd and both bwd kernels — the masks must never desynchronize or
    gradients silently break."""
    off = k_len - q_len + diag_offset
    ok = ik * block_k <= (iq + 1) * block_q - 1 + off
    if window is not None:
        # tile's last col >= the tile's first row's lowest visible col
        ok = jnp.logical_and(
            ok, ik * block_k + block_k - 1 >= iq * block_q + off - window + 1)
    return ok


def _tile_mask(iq, ik, *, block_q, block_k, q_len, k_len, causal,
               mask_pad_rows, window=None, causal_offset=0,
               diag_offset=0):
    """Boolean (block_q, block_k) mask of logits to suppress: padded key
    columns, the causal future, positions below the sliding window's
    lower edge, and (in backward only, where padded q rows would
    otherwise leak into the dK/dV accumulators) padded query rows.
    In forward, padded-row outputs are sliced away on the host instead.

    ``causal_offset`` shifts the causal frontier down: offset 1 masks the
    diagonal too (strict lower-triangular). The striped sequence-parallel
    ring (parallel/sequence.py:striped_ring_flash_attention) alternates
    between offset 0 and 1 per hop — in striped token layout a rotated
    k/v block is visible either through the diagonal or strictly below
    it. ``diag_offset`` shifts the whole diagonal (causal AND window
    edges) the other way: key column j stands for global position
    j - diag_offset relative to the queries — the windowed-ring-hop
    contract (hop t's kv block sits t*S_local positions earlier, so
    ``diag_offset = t*S_local``). The tile FRONTIER (_frontier_ok)
    shares diag_offset but deliberately ignores causal_offset: it
    over-includes by at most the diagonal elements of diagonal tiles,
    which this mask then suppresses — fwd and bwd stay in lockstep."""
    rows = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    off = k_len - q_len + diag_offset
    masked = cols >= k_len
    if mask_pad_rows:
        masked = jnp.logical_or(masked, rows >= q_len)
    if causal:
        masked = jnp.logical_or(
            masked, cols > rows + off - causal_offset)
    if window is not None:
        masked = jnp.logical_or(
            masked, cols <= rows + off - window)
    return masked


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, window, block_q, block_k, n_k, q_len,
                k_len, causal_offset=0, diag_offset=0):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _MASK)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0]                                       # (bq, d)
        k = k_ref[0]                                       # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # (bq, bk)
        s = jnp.where(
            _tile_mask(iq, ik, block_q=block_q, block_k=block_k,
                       q_len=q_len, k_len=k_len, causal=causal,
                       mask_pad_rows=False, window=window,
                       causal_offset=causal_offset,
                       diag_offset=diag_offset),
            _MASK, s)

        m_old = m_scr[:, :1]                               # (bq, 1)
        m_new = jnp.maximum(m_old, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                             # (bq, bk) f32
        alpha = jnp.exp(m_old - m_new)                     # (bq, 1)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        # The p@v matmul runs in the INPUT dtype (softmax stats stay f32,
        # accumulation stays f32 via preferred_element_type): for bf16
        # inputs this keeps the MXU on its native bf16 path (~4x the f32
        # matmul throughput on v5e) — the FlashAttention-2 mixed-precision
        # recipe. For f32 inputs nothing changes.
        acc_scr[:] = alpha * acc_scr[:] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        @pl.when(_frontier_ok(iq, ik, block_q=block_q, block_k=block_k,
                              q_len=q_len, k_len=k_len, window=window,
                              diag_offset=diag_offset))
        def _():
            _body()
    else:
        _body()

    @pl.when(ik == n_k - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        # Rows whose running max never moved off the _MASK sentinel saw no
        # unmasked logit (causal with s_q > s_k puts whole rows above the
        # diagonal). Dense softmax over an all--inf row is NaN; match it —
        # otherwise such rows silently emit a mean of masked-out v rows.
        no_logit = m_scr[:, :1] == _MASK
        out = jnp.where(no_logit, jnp.float32(jnp.nan), acc_scr[:] / l_safe)
        o_ref[0] = out.astype(o_ref.dtype)
        # Row stats are written (bq, _STATS)-wide: TPU blocks need their
        # trailing dim to be 128-divisible or the full array dim, so the
        # stat arrays carry a narrow replicated trailing axis and column 0
        # is read back on the host side.
        lse_ref[0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l_safe),
                                      lse_ref.shape[1:])


def _kv_head_group(h: int, h_kv: int):
    """Validate grouped-query head counts; return the group size g.

    GQA (g q-heads share one kv-head) costs the kernels NOTHING extra:
    the kv BlockSpec index map (:func:`_kv_index`) sends the q-head-major
    grid index to its kv block — the shared kv tile is simply read by g
    programs, never replicated in HBM."""
    if h % h_kv:
        raise ValueError(f"n_heads {h} not divisible by kv heads {h_kv}")
    return h // h_kv


def _kv_index(bh, h, h_kv, g):
    """Grid index ``bh = bi*h + hi`` -> kv block ``bi*h_kv + hi//g``.
    The ONE definition of the GQA head mapping, shared by the forward and
    both backward kernels' BlockSpecs — if fwd and bwd ever addressed kv
    differently, gradients would silently be wrong."""
    return bh // h * h_kv + bh % h // g


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               window=None, causal_offset=0, diag_offset=0):
    b, h, s_q, d = q.shape
    h_kv, s_k = k.shape[1], k.shape[2]
    g = _kv_head_group(h, h_kv)
    bq, bk = _block_sizes(s_q, s_k, block_q, block_k, d=d, window=window)

    q3 = _pad_seq(q.reshape(b * h, s_q, d), bq, 1)
    k3 = _pad_seq(k.reshape(b * h_kv, s_k, d), bk, 1)
    v3 = _pad_seq(v.reshape(b * h_kv, s_k, d), bk, 1)
    sq_p, sk_p = q3.shape[1], k3.shape[1]
    n_q, n_k = sq_p // bq, sk_p // bk

    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, window=window,
        block_q=bq, block_k=bk, n_k=n_k, q_len=s_q, k_len=s_k,
        causal_offset=causal_offset, diag_offset=diag_offset)
    o3, lse3 = pl.pallas_call(
        kern,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda bh, iq, ik: (_kv_index(bh, h, h_kv, g),
                                             ik, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda bh, iq, ik: (_kv_index(bh, h, h_kv, g),
                                             ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, bq, _STATS), lambda bh, iq, ik: (bh, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq_p, _STATS), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=_compiler_params(dimension_semantics=_PARALLEL),
        interpret=_interpret_default(interpret),
    )(q3, k3, v3)
    o = o3[:, :s_q].reshape(b, h, s_q, d)
    lse = lse3[:, :s_q, 0].reshape(b, h, s_q)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _recompute_p(q_ref, k_ref, lse_ref, iq, ik, *, scale, causal, window,
                 block_q, block_k, q_len, k_len, causal_offset=0,
                 diag_offset=0):
    """p = exp(qk*scale - lse) for one tile, masked to exact zeros."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    masked = _tile_mask(iq, ik, block_q=block_q, block_k=block_k,
                        q_len=q_len, k_len=k_len, causal=causal,
                        mask_pad_rows=True, window=window,
                        causal_offset=causal_offset,
                        diag_offset=diag_offset)
    p = jnp.exp(jnp.where(masked, _MASK, s) - lse_ref[0][:, :1])
    return jnp.where(masked, 0.0, p)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, window, block_q, block_k, n_q, q_len,
                    k_len, causal_offset=0, diag_offset=0):
    ik, iq = pl.program_id(1), pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _body():
        # Matmul operands stay in the input dtype (bf16 on the MXU's
        # native path; f32 stats/accumulators) — see _fwd_kernel._body.
        p = _recompute_p(q_ref, k_ref, lse_ref, iq, ik, scale=scale,
                         causal=causal, window=window, block_q=block_q,
                         block_k=block_k, q_len=q_len, k_len=k_len,
                         causal_offset=causal_offset,
                         diag_offset=diag_offset)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # p^T @ dO
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                # dO @ v^T
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # ds^T @ q

    if causal:
        @pl.when(_frontier_ok(iq, ik, block_q=block_q, block_k=block_k,
                              q_len=q_len, k_len=k_len, window=window,
                              diag_offset=diag_offset))
        def _():
            _body()
    else:
        _body()

    @pl.when(iq == n_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr,
                   *, scale, causal, window, block_q, block_k, n_k, q_len,
                   k_len, causal_offset=0, diag_offset=0):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _body():
        p = _recompute_p(q_ref, k_ref, lse_ref, iq, ik, scale=scale,
                         causal=causal, window=window, block_q=block_q,
                         block_k=block_k, q_len=q_len, k_len=k_len,
                         causal_offset=causal_offset,
                         diag_offset=diag_offset)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # ds @ k

    if causal:
        @pl.when(_frontier_ok(iq, ik, block_q=block_q, block_k=block_k,
                              q_len=q_len, k_len=k_len, window=window,
                              diag_offset=diag_offset))
        def _():
            _body()
    else:
        _body()

    @pl.when(ik == n_k - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, causal, scale, block_q, block_k,
               interpret, g_lse=None, window=None, causal_offset=0,
               diag_offset=0):
    b, h, s_q, d = q.shape
    h_kv, s_k = k.shape[1], k.shape[2]
    grp = _kv_head_group(h, h_kv)
    bq, bk = _block_sizes(s_q, s_k, block_q, block_k, d=d, bwd=True,
                          window=window)
    interp = _interpret_default(interpret)

    # delta_i = sum_d dO_i * O_i — tiny elementwise+reduce; XLA fuses it.
    # Zero cotangent elements contribute exactly zero even where O is
    # non-finite: rows with NO visible key (causal s_q > s_k, or the
    # strict causal_offset=1 mask) emit NaN output by design, and their
    # callers weight them to zero — 0 * NaN = NaN would otherwise poison
    # delta and, through ds = p * (dp - delta), the dq/dk/dv of every
    # OTHER row sharing the tile.
    gf, of = g.astype(jnp.float32), o.astype(jnp.float32)
    delta = jnp.sum(jnp.where(gf == 0.0, 0.0, gf * of), axis=-1)
    if g_lse is not None:
        # An lse cotangent folds into the same kernels: per query row,
        # ds_j = p_j (dp_j - delta + g_lse)   [dlse/ds_j = p_j], i.e. the
        # kernels run unchanged with delta' = delta - g_lse.
        delta = delta - g_lse.astype(jnp.float32)

    q3 = _pad_seq(q.reshape(b * h, s_q, d), bq, 1)
    k3 = _pad_seq(k.reshape(b * h_kv, s_k, d), bk, 1)
    v3 = _pad_seq(v.reshape(b * h_kv, s_k, d), bk, 1)
    g3 = _pad_seq(g.reshape(b * h, s_q, d), bq, 1)
    # Row stats replicated to a narrow (BH, S, _STATS) trailing axis — see
    # the lse layout note in _fwd_kernel.
    lse2 = _pad_seq(lse.reshape(b * h, s_q), bq, 1)
    delta2 = _pad_seq(delta.reshape(b * h, s_q), bq, 1)
    lse3 = jnp.broadcast_to(lse2[..., None], lse2.shape + (_STATS,))
    delta3 = jnp.broadcast_to(delta2[..., None], lse3.shape)
    sq_p, sk_p = q3.shape[1], k3.shape[1]
    n_q, n_k = sq_p // bq, sk_p // bk

    q_spec = pl.BlockSpec((1, bq, d), lambda bh, ik, iq: (bh, iq, 0))
    kv_spec = pl.BlockSpec((1, bk, d),
                           lambda bh, ik, iq: (_kv_index(bh, h, h_kv, grp),
                                               ik, 0))
    dkv_spec = pl.BlockSpec((1, bk, d), lambda bh, ik, iq: (bh, ik, 0))
    row_spec = pl.BlockSpec((1, bq, _STATS), lambda bh, ik, iq: (bh, iq, 0))
    # dK/dV are written PER Q-HEAD (grid programs may not reduce into a
    # shared output block) and group-summed by XLA below — one extra
    # (B, H, Sk, D) temp, only when grp > 1.
    dkv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          window=window, block_q=bq, block_k=bk, n_q=n_q,
                          q_len=s_q, k_len=s_k,
                          causal_offset=causal_offset,
                          diag_offset=diag_offset),
        grid=(b * h, n_k, n_q),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[dkv_spec, dkv_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, sk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk_p, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_compiler_params(dimension_semantics=_PARALLEL),
        interpret=interp,
    )(q3, k3, v3, g3, lse3, delta3)
    dk3, dv3 = dkv

    q_spec2 = pl.BlockSpec((1, bq, d), lambda bh, iq, ik: (bh, iq, 0))
    kv_spec2 = pl.BlockSpec((1, bk, d),
                            lambda bh, iq, ik: (_kv_index(bh, h, h_kv, grp),
                                                ik, 0))
    row_spec2 = pl.BlockSpec((1, bq, _STATS), lambda bh, iq, ik: (bh, iq, 0))
    dq3 = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          window=window, block_q=bq, block_k=bk, n_k=n_k,
                          q_len=s_q, k_len=s_k,
                          causal_offset=causal_offset,
                          diag_offset=diag_offset),
        grid=(b * h, n_q, n_k),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=q_spec2,
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_compiler_params(dimension_semantics=_PARALLEL),
        interpret=interp,
    )(q3, k3, v3, g3, lse3, delta3)

    dq = dq3[:, :s_q].reshape(b, h, s_q, d)
    dk = dk3[:, :s_k].reshape(b, h, s_k, d)
    dv = dv3[:, :s_k].reshape(b, h, s_k, d)
    if grp > 1:
        # sum the g per-q-head partials of each kv group (f32 to avoid
        # bf16 accumulation error across the group)
        dk = dk.reshape(b, h_kv, grp, s_k, d).astype(jnp.float32) \
               .sum(axis=2).astype(k.dtype)
        dv = dv.reshape(b, h_kv, grp, s_k, d).astype(jnp.float32) \
               .sum(axis=2).astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret,
               window, causal_offset, diag_offset):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                      window=window, causal_offset=causal_offset,
                      diag_offset=diag_offset)


def _flash_lse_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                       window, causal_offset, diag_offset):
    o, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                        window=window, causal_offset=causal_offset,
                        diag_offset=diag_offset)
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_vjp_bwd(causal, scale, block_q, block_k, interpret, window,
                       causal_offset, diag_offset, res, gs):
    q, k, v, o, lse = res
    g_o, g_lse = gs
    return _flash_bwd(q, k, v, o, lse, g_o, causal, scale, block_q,
                      block_k, interpret, g_lse=g_lse, window=window,
                      causal_offset=causal_offset,
                      diag_offset=diag_offset)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_with_lse(q, k, v, *, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             interpret: Optional[bool] = None,
                             window: Optional[int] = None,
                             causal_offset: int = 0,
                             diag_offset: int = 0):
    """Like :func:`flash_attention` but also returns the per-row
    log-sum-exp ``lse`` (B, H, Sq) — DIFFERENTIABLY (the lse cotangent is
    folded into the backward kernels' delta term). This is the building
    block for cross-block softmax merging: two attention partials
    ``(o1, lse1), (o2, lse2)`` over disjoint key sets combine exactly via

        lse = logaddexp(lse1, lse2)
        o   = o1 * exp(lse1 - lse) + o2 * exp(lse2 - lse)

    which is how ring flash attention (parallel/sequence.py) accumulates
    a device's queries over the rotating k/v blocks."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True (sliding-window "
                         "attention is a causal-decoder pattern)")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if causal_offset and not causal:
        raise ValueError("causal_offset shifts the causal frontier and "
                         "requires causal=True")
    if causal_offset and window is not None:
        raise ValueError("causal_offset cannot combine with window: the "
                         "window lower edge is anchored to the inclusive "
                         "diagonal, so the combination would silently "
                         "shrink the band to window-1 keys")
    if causal_offset not in (0, 1):
        raise ValueError(f"causal_offset must be 0 (include diagonal) or "
                         f"1 (strict), got {causal_offset}")
    if diag_offset and not causal:
        raise ValueError("diag_offset shifts the causal/window diagonal "
                         "and requires causal=True")
    *_, dh = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(dh)
    return _flash_lse(q, k, v, causal, float(scale),
                      int(block_q) if block_q is not None else None,
                      int(block_k) if block_k is not None else None,
                      interpret,
                      int(window) if window is not None else None,
                      int(causal_offset), int(diag_offset))


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    window: Optional[int] = None):
    """Memory-efficient attention: softmax(q k^T * scale) v, blockwise.

    Drop-in for :func:`nn.attention.dense_attention` (same signature,
    same result up to float tolerance) with O(S) memory and MXU-tiled
    pallas kernels. q: (B, H, Sq, Dh); k, v: (B, Hkv, Sk, Dh) with Hkv
    dividing H — Hkv < H is grouped-query attention, served zero-copy by
    the kv BlockSpec index maps (do NOT repeat kv heads to H yourself;
    that materializes exactly the memory GQA removes). Sequence lengths
    need not divide the block sizes (tiles are padded+masked).
    ``block_q``/``block_k`` default to the measured-best tiling for the
    chip (large tiles — see ``_block_sizes``); pass explicit ints only to
    pin a tiling (tests, VMEM-constrained fusions).

    ``interpret=None`` auto-selects interpreter mode off-TPU so the same
    code path runs in CPU tests (conftest's 8-device CPU mesh) and
    compiled on real chips.
    """
    o, _ = flash_attention_with_lse(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret, window=window)
    # single vjp path: the unused lse output gets a zero cotangent, which
    # the backward folds away for free (delta - 0)
    return o


# Measured flash/dense crossover (v5e, d=64, causal, honest amortized
# timing — BASELINE.md round-3 table): seq 512 flash runs 0.87x dense
# (grid too short to amortize kernel overhead); seq 1024 flash wins
# 1.51x and the gap widens with seq (8.5x at 4096). Below this many
# KEYS, the dense einsum is the faster O(S^2) and still cheap in
# memory, so make_flash_attn_fn dispatches to it. The threshold lives
# in the typed env registry (DPX_FLASH_MIN_SEQ, default = the measured
# crossover); this module attribute is its import-time read, kept for
# the consumers that report it (benchmarks/mfu_transformer.py).
# make_flash_attn_fn re-reads the registry at build time, so a test or
# deployment that sets the variable after import still takes effect.
from ..runtime import env as _env  # noqa: E402 — placed at its consumer

FLASH_MIN_SEQ = int(_env.get("DPX_FLASH_MIN_SEQ"))

#: Sentinel default for ``make_flash_attn_fn(min_seq_flash=...)``: "use
#: the registry value at build time" (None/0 keep meaning "always run
#: the kernel").
_MIN_SEQ_ENV = object()

# one-time flag for the dense-dispatch info log (list, so the closure in
# make_flash_attn_fn can mutate it without a global statement)
_dense_dispatch_logged = []


def make_flash_attn_fn(block_q: Optional[int] = None,
                       block_k: Optional[int] = None,
                       interpret: Optional[bool] = None,
                       window: Optional[int] = None,
                       min_seq_flash=_MIN_SEQ_ENV):
    """An ``attn_fn`` for :class:`nn.attention.MultiHeadAttention` /
    model constructors: models built with this compute attention through
    the pallas kernel instead of the dense einsum path. ``window`` bakes
    sliding-window (local) attention into the model — O(S*window)
    compute and the long-context default for causal decoders.

    Below ``min_seq_flash`` keys (default: the typed registry knob
    ``DPX_FLASH_MIN_SEQ``, whose default is the measured v5e crossover)
    the call dispatches to the dense einsum instead — same function,
    faster at short seq — so enabling flash is safe at every sequence
    length. Shapes are static under jit, so the dispatch costs nothing
    at runtime. Pass ``min_seq_flash=None`` (or 0) to always run the
    kernel (tests, kernel benchmarking)."""
    if min_seq_flash is _MIN_SEQ_ENV:
        min_seq_flash = int(_env.get("DPX_FLASH_MIN_SEQ"))

    def attn_fn(q, k, v, *, causal=False, scale=None):
        if min_seq_flash and k.shape[-2] < min_seq_flash:
            if not _dense_dispatch_logged:
                _dense_dispatch_logged.append(True)
                logging.getLogger(__name__).info(
                    "flash attn_fn: %d keys < min_seq_flash=%d, "
                    "dispatching to dense einsum (measured v5e "
                    "crossover; numerics identical — logged once)",
                    k.shape[-2], min_seq_flash)
            from ..nn.attention import dense_attention
            return dense_attention(q, k, v, causal=causal, scale=scale,
                                   window=window)
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret, window=window)

    # full-window flash computes exactly softmax(qk)v, so cached decode
    # (models/generate.py) may substitute its inline core; a sliding
    # window changes the function and must not be silently swapped —
    # decode reads .window instead and switches to the rolling
    # (O(window)-memory) cache that reproduces it exactly
    attn_fn.dense_equivalent = window is None
    attn_fn.window = window
    return attn_fn
