"""Loss functions (the reference uses ``nn.CrossEntropyLoss()``,
``min_DDP.py:75``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from ..runtime.jax_compat import shard_map


def cross_entropy_per_example(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-example softmax cross-entropy, labels as int class ids.

    ``logits``: (..., C); ``labels``: (...). Matches torch
    ``CrossEntropyLoss(reduction='none')`` numerics (log-softmax gather)."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    return logz - true_logit


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean cross-entropy — torch ``CrossEntropyLoss()`` default reduction."""
    return jnp.mean(cross_entropy_per_example(logits, labels))


def fused_linear_cross_entropy(hidden: jnp.ndarray, w: jnp.ndarray,
                               labels: jnp.ndarray, *,
                               chunk_rows: int = 512) -> jnp.ndarray:
    """Mean CE of ``softmax(hidden @ w)`` vs ``labels`` without ever
    materializing the full ``(N, vocab)`` logits.

    For a language model the vocab projection dominates activation memory:
    at batch 8 x seq 1024 x vocab 32k the logits are 1 GiB in f32, and the
    standard loss keeps them (plus their cotangent) live across the whole
    backward. This streams row chunks through a ``lax.scan`` whose body is
    ``jax.checkpoint``-ed, so the forward saves only the scan inputs and the
    backward recomputes one ``(chunk, vocab)`` logits tile at a time —
    activation memory drops from O(N*V) to O(chunk*V), buying batch size
    (and therefore MFU) on memory-bound configs.

    Each chunk is still a ``(chunk, d) @ (d, vocab)`` matmul — large enough
    to keep the MXU saturated (use ``chunk_rows`` >= 256). The matmul
    accumulates in f32 (``preferred_element_type``), which for bf16 inputs
    is *more* precise than the unfused bf16-logits path at identical MXU
    cost.

    ``hidden``: (..., d); ``w``: (d, vocab) — the (in, out) layout of
    ``nn.core.Linear``; ``labels``: integer ids, shape ``hidden.shape[:-1]``.
    """
    d = hidden.shape[-1]
    h = hidden.reshape(-1, d)
    y = labels.reshape(-1).astype(jnp.int32)
    n = h.shape[0]
    c = min(int(chunk_rows), n)
    n_chunks = -(-n // c)
    pad = n_chunks * c - n
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        y = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
    valid = (jnp.arange(n_chunks * c) < n).astype(jnp.float32)

    def body(total, inp):
        h_i, y_i, m_i = inp
        logits = jnp.matmul(h_i, w, preferred_element_type=jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        true_logit = jnp.take_along_axis(logits, y_i[:, None], axis=-1)[:, 0]
        return total + jnp.sum((logz - true_logit) * m_i), None

    total, _ = lax.scan(
        jax.checkpoint(body),
        jnp.zeros((), jnp.float32),
        (h.reshape(n_chunks, c, d), y.reshape(n_chunks, c),
         valid.reshape(n_chunks, c)))
    return total / n


def vocab_parallel_cross_entropy(logits_local: jnp.ndarray, labels,
                                 *, axis_name: str = "tp") -> jnp.ndarray:
    """Per-example CE from VOCAB-SHARDED logits — call inside
    ``shard_map`` with each device holding its contiguous
    ``(..., V/n)`` vocab slice (shard r owns ids ``[r*V/n, (r+1)*V/n)``,
    the layout ``P(..., tp)`` produces). ``labels`` are GLOBAL ids.

    The Megatron-LM vocab-parallel loss: the full (..., V) logits are
    never gathered — two scalar-per-row collectives (a pmax for the
    stabilizer, ONE fused psum of local sum-exp, masked target logit,
    and label-ownership count) replace the O(V) all-gather XLA would
    otherwise insert between a tp-sharded head and an unsharded loss.
    The max is detached (mathematically the logsumexp shift cancels in
    the gradient), so gradients flow only through differentiable psums
    — exactness vs the gathered loss is pinned by tests/test_models.py.
    A label no shard owns (out-of-range ids such as -100 padding)
    yields NaN, matching the gathered path — silent finite garbage
    would corrupt training instead of surfacing the masking bug."""
    from ..comm import primitives as prim

    v_loc = logits_local.shape[-1]
    my = prim.axis_index(axis_name)
    offset = my * v_loc
    lf = logits_local.astype(jnp.float32)
    # stop_gradient BEFORE the pmax: the stabilizer shift cancels in the
    # gradient mathematically, and pmax has no differentiation rule —
    # a zero-tangent operand keeps it out of the linearized graph
    gmax = prim.pmax(
        jax.lax.stop_gradient(jnp.max(lf, axis=-1)), axis_name)
    loc = labels.astype(jnp.int32) - offset
    in_shard = (loc >= 0) & (loc < v_loc)
    loc_c = jnp.clip(loc, 0, v_loc - 1)
    tgt_local = jnp.take_along_axis(lf, loc_c[..., None], axis=-1)[..., 0]
    # one all-reduce for all three per-row scalars (psum takes a pytree)
    denom, tgt, owned = prim.psum(
        (jnp.sum(jnp.exp(lf - gmax[..., None]), axis=-1),
         jnp.where(in_shard, tgt_local, 0.0),
         in_shard.astype(jnp.float32)), axis_name)
    loss = jnp.log(denom) + gmax - tgt
    return jnp.where(owned > 0, loss, jnp.float32(jnp.nan))


def make_vocab_parallel_ce_fn(mesh, *, dp: str = "dp", tp: str = "tp"):
    """``fn(hidden, head_w, labels) -> per-example CE`` fusing the vocab
    projection INTO the tp island: hidden (B, S, D) replicated over tp,
    ``head_w`` (D, V) sharded ``P(None, tp)`` (the TransformerLM head
    layout), labels (B, S) global ids. Each device computes only its
    (B, S, V/n) logits slice and the loss reduces with scalar-per-token
    collectives — the (B, S, V) logits never exist on any device, in
    forward or backward. The GSPMD alternative (plain
    ``cross_entropy_per_example`` on a sharded head) all-gathers the
    full logits; at B8 x S1024 x V32k that is 1 GiB per step."""
    from jax.sharding import PartitionSpec as P

    def island(hidden, w_local, labels):
        logits_local = jnp.matmul(hidden, w_local,
                                  preferred_element_type=jnp.float32)
        return vocab_parallel_cross_entropy(logits_local, labels,
                                            axis_name=tp)

    def fn(hidden, head_w, labels):
        return shard_map(
            island, mesh=mesh,
            in_specs=(P(dp, None, None), P(None, tp), P(dp, None)),
            out_specs=P(dp, None), check_vma=False)(hidden, head_w,
                                                    labels)
    return fn
