"""Loss functions (the reference uses ``nn.CrossEntropyLoss()``,
``min_DDP.py:75``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def cross_entropy_per_example(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-example softmax cross-entropy, labels as int class ids.

    ``logits``: (..., C); ``labels``: (...). Matches torch
    ``CrossEntropyLoss(reduction='none')`` numerics (log-softmax gather)."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    return logz - true_logit


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean cross-entropy — torch ``CrossEntropyLoss()`` default reduction."""
    return jnp.mean(cross_entropy_per_example(logits, labels))


def fused_linear_cross_entropy(hidden: jnp.ndarray, w: jnp.ndarray,
                               labels: jnp.ndarray, *,
                               chunk_rows: int = 512) -> jnp.ndarray:
    """Mean CE of ``softmax(hidden @ w)`` vs ``labels`` without ever
    materializing the full ``(N, vocab)`` logits.

    For a language model the vocab projection dominates activation memory:
    at batch 8 x seq 1024 x vocab 32k the logits are 1 GiB in f32, and the
    standard loss keeps them (plus their cotangent) live across the whole
    backward. This streams row chunks through a ``lax.scan`` whose body is
    ``jax.checkpoint``-ed, so the forward saves only the scan inputs and the
    backward recomputes one ``(chunk, vocab)`` logits tile at a time —
    activation memory drops from O(N*V) to O(chunk*V), buying batch size
    (and therefore MFU) on memory-bound configs.

    Each chunk is still a ``(chunk, d) @ (d, vocab)`` matmul — large enough
    to keep the MXU saturated (use ``chunk_rows`` >= 256). The matmul
    accumulates in f32 (``preferred_element_type``), which for bf16 inputs
    is *more* precise than the unfused bf16-logits path at identical MXU
    cost.

    ``hidden``: (..., d); ``w``: (d, vocab) — the (in, out) layout of
    ``nn.core.Linear``; ``labels``: integer ids, shape ``hidden.shape[:-1]``.
    """
    d = hidden.shape[-1]
    h = hidden.reshape(-1, d)
    y = labels.reshape(-1).astype(jnp.int32)
    n = h.shape[0]
    c = min(int(chunk_rows), n)
    n_chunks = -(-n // c)
    pad = n_chunks * c - n
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        y = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
    valid = (jnp.arange(n_chunks * c) < n).astype(jnp.float32)

    def body(total, inp):
        h_i, y_i, m_i = inp
        logits = jnp.matmul(h_i, w, preferred_element_type=jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        true_logit = jnp.take_along_axis(logits, y_i[:, None], axis=-1)[:, 0]
        return total + jnp.sum((logz - true_logit) * m_i), None

    total, _ = lax.scan(
        jax.checkpoint(body),
        jnp.zeros((), jnp.float32),
        (h.reshape(n_chunks, c, d), y.reshape(n_chunks, c),
         valid.reshape(n_chunks, c)))
    return total / n
