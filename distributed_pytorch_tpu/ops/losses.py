"""Loss functions (the reference uses ``nn.CrossEntropyLoss()``,
``min_DDP.py:75``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_per_example(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-example softmax cross-entropy, labels as int class ids.

    ``logits``: (..., C); ``labels``: (...). Matches torch
    ``CrossEntropyLoss(reduction='none')`` numerics (log-softmax gather)."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    return logz - true_logit


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean cross-entropy — torch ``CrossEntropyLoss()`` default reduction."""
    return jnp.mean(cross_entropy_per_example(logits, labels))
