"""int8 quantization: weight-only inference tables AND gradient wire codec.

Single-token decode streams every parameter once per token — it is
bandwidth-bound, not FLOP-bound (benchmarks/decode_tpu.py) — so halving
weight bytes (bf16 -> int8) is a direct decode-throughput lever on TPU,
orthogonal to the GQA cache shrink. This module implements the standard
weight-only recipe: symmetric per-channel int8 (scales over the
contraction axis, one scale per output channel), dequantized on the fly
into the matmul dtype. Activations stay in bf16/f32 — no calibration
data needed, and quality loss is the weight rounding error only
(~0.4% relative per channel at int8).

The quantized representation is a DROP-IN param-tree transform
(:func:`quantize_tree`): a Linear/Embedding leaf dict ``{"w": ...}``
becomes ``{"w_q": int8, "w_scale": f32}`` and ``nn.core`` consumes
either form — every model/call-site works unchanged on a quantized
tree. The reference has no inference path at all, let alone a quantized
one (SURVEY.md §5).

The GRADIENT side (:func:`quantize_grad_blocks` /
:func:`dequantize_grad_blocks` / :class:`ErrorFeedback`) is the jnp
face of the collective wire codec defined in
:mod:`..comm.wire` — symmetric per-block int8 with the integer-exact
snap — used by :func:`..comm.primitives.quantized_pmean` inside the
compiled step and by the host-backend quantized ring's error-feedback
pre-compensation. Same block rule everywhere, so the two comm front
doors quantize identically.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

# weight matrices smaller than this stay unquantized (LN scales, biases,
# tiny projections — no bandwidth to win, precision to lose)
DEFAULT_MIN_SIZE = 4096


def quantize_int8(w: jnp.ndarray):
    """Symmetric per-output-channel int8.

    ``w``: (..., in, out) — scales are max(|w|)/127 over the contraction
    axis (-2), shape ``w.shape[:-2] + (out,)``. For an Embedding table
    (vocab, dim) pass it as-is: scales per dim column, i.e. the table is
    treated as the (vocab -> dim) projection it is; its transposed use as
    a tied output head dequantizes with the same scales.
    Returns ``(w_q int8, scale f32)`` with ``w ~= w_q * scale``.
    """
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[..., None, :]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize(w_q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    """w_q * scale in ``dtype``.

    Intended behavior: XLA fuses the dequant into the consumer matmul so
    the int8 bytes (not the dequantized values) are what HBM streams.
    CAVEAT: inside a scan whose iterations all consume the same weights
    (the cached decode loop), loop-invariant code motion may hoist the
    dequantized bf16 tensor out of the loop — then each step streams
    bf16 again and the bandwidth win evaporates. The decode benchmark
    measures the int8 arm AGAINST the bf16 arm (decode_tpu.py
    run_gqa_compare) precisely so this shows up empirically; if the
    speedups ever match, the next step is a pallas matmul that takes the
    int8 weights directly."""
    return w_q.astype(dtype) * scale[..., None, :].astype(dtype)


def resolve_weight(leaf: Any, key: str, dtype):
    """Read weight ``key`` from a param dict that may hold it quantized
    (``{key}_q`` + ``{key}_scale``). The one accessor every consumer
    (nn.core.Linear/Embedding, TransformerLM.head_weight) goes through."""
    if key in leaf:
        return leaf[key]
    if f"{key}_q" not in leaf or f"{key}_scale" not in leaf:
        raise ValueError(
            f"param dict holds neither '{key}' nor the quantized pair "
            f"'{key}_q'/'{key}_scale' (keys present: {sorted(leaf)})")
    return dequantize(leaf[f"{key}_q"], leaf[f"{key}_scale"], dtype)


def quantize_tree(params: Any, *, min_size: int = DEFAULT_MIN_SIZE) -> Any:
    """Quantize every eligible weight in a param pytree.

    Eligible: a dict entry named ``w`` or ``emb`` whose array has ndim
    >= 2 and >= ``min_size`` elements. Biases, LayerNorm scales and
    small matrices pass through. Returns a new tree; use for INFERENCE
    only (training on int8 weights would quantize the gradient signal
    away).
    """
    def rec(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if (k in ("w", "emb") and hasattr(v, "ndim")
                        and v.ndim >= 2 and v.size >= min_size):
                    q, s = quantize_int8(v)
                    out[f"{k}_q"] = q
                    out[f"{k}_scale"] = s
                else:
                    out[k] = rec(v)
            return out
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v) for v in node)
        return node

    return rec(params)


def quantized_bytes(params: Any) -> int:
    """Total parameter bytes of a (possibly quantized) tree — the number
    decode streams per token."""
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params))


# ---------------------------------------------------------------------------
# gradient wire codec (jnp face of comm/wire.py's block format)
# ---------------------------------------------------------------------------


def quantize_grad_blocks(v: jnp.ndarray, bits: int = 8):
    """Symmetric per-block quantizer at a selectable wire width.

    ``v``: f32 ``(..., block)`` — the trailing axis is one quantization
    block. Per block, with ``levels`` = 127 (q8) or 7 (q4):
    ``scale = amax/levels`` with two snaps matching ``comm/wire.py``:
    all-zero blocks get scale 1 (exact zeros), and blocks of INTEGERS
    with ``amax <= levels`` get scale 1 (small-magnitude integer
    payloads — counters, token tallies — transfer exactly).
    Returns ``(q int8, scale f32 (..., 1))`` — ``q`` stays one int8 per
    element even at q4 (|q| <= 7): nibble PACKING is a host/wire-framing
    concern (``comm/wire.py:pack_nibbles``); inside a compiled step the
    int8 tensor is what the collective moves either way, so the q4 win
    on the SPMD front door is the coarser grid's role as the adaptive
    policy's compiled-program twin, not ICI bytes.
    """
    from ..comm.wire import quant_levels
    levels = jnp.float32(quant_levels(bits))
    v = v.astype(jnp.float32)
    amax = jnp.max(jnp.abs(v), axis=-1, keepdims=True)
    int_exact = jnp.logical_and(
        amax <= levels,
        jnp.all(v == jnp.round(v), axis=-1, keepdims=True))
    unit = jnp.logical_or(amax == 0.0, int_exact)
    scale = jnp.where(unit, jnp.float32(1.0), amax / levels)
    # quantize by the f32 INVERSE (multiply) — same grid as the native
    # codec and comm/wire.py, which vectorize the multiply
    inv = jnp.where(unit, jnp.float32(1.0), levels / amax)
    q = jnp.clip(jnp.round(v * inv), -levels, levels).astype(jnp.int8)
    return q, scale


def block_outlier_frac_jnp(flat: jnp.ndarray, block: int,
                           thresh: float) -> jnp.ndarray:
    """jnp twin of ``comm/wire.py:block_outlier_frac`` — the adaptive
    width chooser's dynamic-range statistic, computed INSIDE the
    compiled step on the reduced bucket so only one scalar crosses to
    the host. All-zero blocks are neither counted nor hostile; the
    ragged tail's rms divides by its REAL element count (the zero
    padding added here must not read as dynamic range)."""
    flat = flat.astype(jnp.float32).ravel()
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    v = flat.reshape(-1, block)
    nb = v.shape[0]
    amax = jnp.max(jnp.abs(v), axis=-1)
    counts = jnp.full((nb,), block, jnp.float32)
    if pad:
        counts = counts.at[-1].set(block - pad)
    rms = jnp.sqrt(jnp.square(v).sum(axis=-1) / counts)
    valid = rms > 0.0
    hostile = jnp.logical_and(valid, amax > jnp.float32(thresh) * rms)
    return hostile.sum() / jnp.maximum(valid.sum(), 1)


def dequantize_grad_blocks(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`quantize_grad_blocks` (f32 output)."""
    return q.astype(jnp.float32) * scale


# ---------------------------------------------------------------------------
# paged-KV page codec (jnp face of the quantized resident pool,
# serve/pages/ — docs/serving.md "Quantized resident pool")
# ---------------------------------------------------------------------------


def page_block_map(h_kv: int, page_len: int, dh: int) -> jnp.ndarray:
    """``(Hkv, page_len, Dh)`` int32 constant mapping each page element
    to its wire scale block (flat C-order ``QUANT_BLOCK`` blocking —
    the SAME grid ``comm/wire.py`` frames a handoff page on, which is
    what keeps pool bytes and wire bytes bit-identical at matched
    widths). Constant-folded by XLA; the in-kernel dequant is one
    gather + one multiply per page."""
    from ..comm.wire import QUANT_BLOCK
    e = h_kv * page_len * dh
    return (jnp.arange(e, dtype=jnp.int32) // QUANT_BLOCK) \
        .reshape(h_kv, page_len, dh)


def quantize_page_blocks(pages: jnp.ndarray, bits: int):
    """Quantize whole pages onto the wire block grid, inside a compiled
    program.

    ``pages``: f32 ``(..., Hkv, page_len, Dh)`` (any leading batch
    dims). Returns ``(q int8 UNPACKED same shape, scales (..., nb)
    f32)`` where ``nb = wire.num_blocks(Hkv*page_len*Dh)``. The flat
    page is zero-padded up to ``nb * QUANT_BLOCK`` before blocking —
    padding changes neither a block's amax nor its all-integer snap, so
    the result is bit-identical to the numpy wire codec on the unpadded
    page (``serve/pages/quant.py`` asserts this agreement in tests)."""
    from ..comm.wire import QUANT_BLOCK, num_blocks
    shape = pages.shape
    e = shape[-3] * shape[-2] * shape[-1]
    nb = num_blocks(e)
    lead = shape[:-3]
    flat = pages.astype(jnp.float32).reshape(lead + (e,))
    pad = nb * QUANT_BLOCK - e
    if pad:
        flat = jnp.pad(flat, [(0, 0)] * len(lead) + [(0, pad)])
    q, scale = quantize_grad_blocks(
        flat.reshape(lead + (nb, QUANT_BLOCK)), bits=bits)
    q = q.reshape(lead + (nb * QUANT_BLOCK,))[..., :e].reshape(shape)
    return q, scale[..., 0]


def dequantize_page_blocks(q: jnp.ndarray, scales: jnp.ndarray,
                           block_map: jnp.ndarray) -> jnp.ndarray:
    """``q`` (..., Hkv, L, Dh) int8, ``scales`` (..., nb),
    ``block_map`` from :func:`page_block_map` → f32 pages. The scale
    gather rides the page gather nearly free (one (..., nb) lookup
    broadcast over the page)."""
    return q.astype(jnp.float32) * scales[..., block_map]


def pack_page_nibbles(q: jnp.ndarray) -> jnp.ndarray:
    """jnp twin of ``comm/wire.py:pack_nibbles`` over page layouts:
    ``(..., Dh)`` int8 (|q| <= 7) → ``(..., Dh // 2)`` uint8, pairs of
    flat-adjacent elements with the LOW nibble first — byte-identical
    to the wire/native packing, so packed pool pages ship into a q4
    handoff frame without re-encoding."""
    lo = q[..., 0::2]
    hi = q[..., 1::2]
    byte = jnp.bitwise_or(jnp.bitwise_and(lo, 0x0F),
                          jnp.left_shift(jnp.bitwise_and(hi, 0x0F), 4))
    return jax.lax.bitcast_convert_type(byte.astype(jnp.int8), jnp.uint8)


def unpack_page_nibbles(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_page_nibbles`: ``(..., Dh // 2)`` uint8 →
    ``(..., Dh)`` sign-extended int8 (arithmetic shifts recover the
    two's-complement nibbles)."""
    b = jax.lax.bitcast_convert_type(packed, jnp.int8)
    lo = jnp.right_shift(jnp.left_shift(b, 4), 4)
    hi = jnp.right_shift(b, 4)
    return jnp.stack([lo, hi], axis=-1) \
        .reshape(packed.shape[:-1] + (packed.shape[-1] * 2,))


class ErrorFeedback:
    """Error-feedback residual for repeated lossy gradient reduction.

    The classic compressed-SGD correction (1-bit SGD / EF-SGD): the
    quantization error of step t is carried into step t+1's input, so
    the TIME-AVERAGE of what crosses the wire converges to the true
    gradient instead of accumulating bias — systematic rounding (e.g. a
    tiny gradient always rounding to zero under a big block-mate's
    scale) is recovered on later steps.

        ef = ErrorFeedback()
        compensated = ef.compensate(flat_grads)   # quantization-aware
        ... lossy all-reduce of `compensated` ...

    ``compensate`` adds the carried residual, rounds the result onto the
    wire grid it will be transmitted on (so the FIRST wire hop is
    exact), and stores the new residual. Host-resident (numpy) state —
    this wraps the eager per-rank-process reduce path, not the compiled
    SPMD step. Width-aware: pass ``bits=4`` to round onto the q4 grid —
    the residual then carries the (larger) q4 rounding error into the
    next step, so the coarser adaptive wire stays non-compounding
    exactly like q8; the residual survives width flips unchanged (it is
    just the un-transmitted remainder, grid-agnostic by construction).
    """

    def __init__(self, block: int = None, bits: int = 8):
        from ..comm import wire
        self._wire = wire
        self.block = block or wire.QUANT_BLOCK
        self.bits = bits
        self.residual = None

    def compensate(self, flat, bits: int = None):
        import numpy as np

        bits = self.bits if bits is None else bits
        flat = np.ascontiguousarray(flat, dtype=np.float32).ravel()
        if self.residual is None or self.residual.size != flat.size:
            self.residual = np.zeros(flat.size, np.float32)
        e = flat + self.residual
        q, s = self._wire.quantize_blocks(e, self.block, bits)
        grid = self._wire.dequantize_blocks(q, s, self.block)
        self.residual = e - grid
        return grid
