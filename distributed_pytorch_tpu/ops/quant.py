"""Weight-only int8 quantization for inference.

Single-token decode streams every parameter once per token — it is
bandwidth-bound, not FLOP-bound (benchmarks/decode_tpu.py) — so halving
weight bytes (bf16 -> int8) is a direct decode-throughput lever on TPU,
orthogonal to the GQA cache shrink. This module implements the standard
weight-only recipe: symmetric per-channel int8 (scales over the
contraction axis, one scale per output channel), dequantized on the fly
into the matmul dtype. Activations stay in bf16/f32 — no calibration
data needed, and quality loss is the weight rounding error only
(~0.4% relative per channel at int8).

The quantized representation is a DROP-IN param-tree transform
(:func:`quantize_tree`): a Linear/Embedding leaf dict ``{"w": ...}``
becomes ``{"w_q": int8, "w_scale": f32}`` and ``nn.core`` consumes
either form — every model/call-site works unchanged on a quantized
tree. The reference has no inference path at all, let alone a quantized
one (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

# weight matrices smaller than this stay unquantized (LN scales, biases,
# tiny projections — no bandwidth to win, precision to lose)
DEFAULT_MIN_SIZE = 4096


def quantize_int8(w: jnp.ndarray):
    """Symmetric per-output-channel int8.

    ``w``: (..., in, out) — scales are max(|w|)/127 over the contraction
    axis (-2), shape ``w.shape[:-2] + (out,)``. For an Embedding table
    (vocab, dim) pass it as-is: scales per dim column, i.e. the table is
    treated as the (vocab -> dim) projection it is; its transposed use as
    a tied output head dequantizes with the same scales.
    Returns ``(w_q int8, scale f32)`` with ``w ~= w_q * scale``.
    """
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[..., None, :]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize(w_q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    """w_q * scale in ``dtype``.

    Intended behavior: XLA fuses the dequant into the consumer matmul so
    the int8 bytes (not the dequantized values) are what HBM streams.
    CAVEAT: inside a scan whose iterations all consume the same weights
    (the cached decode loop), loop-invariant code motion may hoist the
    dequantized bf16 tensor out of the loop — then each step streams
    bf16 again and the bandwidth win evaporates. The decode benchmark
    measures the int8 arm AGAINST the bf16 arm (decode_tpu.py
    run_gqa_compare) precisely so this shows up empirically; if the
    speedups ever match, the next step is a pallas matmul that takes the
    int8 weights directly."""
    return w_q.astype(dtype) * scale[..., None, :].astype(dtype)


def resolve_weight(leaf: Any, key: str, dtype):
    """Read weight ``key`` from a param dict that may hold it quantized
    (``{key}_q`` + ``{key}_scale``). The one accessor every consumer
    (nn.core.Linear/Embedding, TransformerLM.head_weight) goes through."""
    if key in leaf:
        return leaf[key]
    if f"{key}_q" not in leaf or f"{key}_scale" not in leaf:
        raise ValueError(
            f"param dict holds neither '{key}' nor the quantized pair "
            f"'{key}_q'/'{key}_scale' (keys present: {sorted(leaf)})")
    return dequantize(leaf[f"{key}_q"], leaf[f"{key}_scale"], dtype)


def quantize_tree(params: Any, *, min_size: int = DEFAULT_MIN_SIZE) -> Any:
    """Quantize every eligible weight in a param pytree.

    Eligible: a dict entry named ``w`` or ``emb`` whose array has ndim
    >= 2 and >= ``min_size`` elements. Biases, LayerNorm scales and
    small matrices pass through. Returns a new tree; use for INFERENCE
    only (training on int8 weights would quantize the gradient signal
    away).
    """
    def rec(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if (k in ("w", "emb") and hasattr(v, "ndim")
                        and v.ndim >= 2 and v.size >= min_size):
                    q, s = quantize_int8(v)
                    out[f"{k}_q"] = q
                    out[f"{k}_scale"] = s
                else:
                    out[k] = rec(v)
            return out
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v) for v in node)
        return node

    return rec(params)


def quantized_bytes(params: Any) -> int:
    """Total parameter bytes of a (possibly quantized) tree — the number
    decode streams per token."""
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params))
