"""Optimizers as pure pytree transforms.

The reference uses ``torch.optim.AdamW(params, 1e-4)`` (``min_DDP.py:74``).
Here an optimizer is an ``(init, update)`` pair over pytrees so the whole
update fuses into the compiled train step — the TPU-idiomatic shape, where
"optimizer.step()" is just more HLO after the gradient all-reduce.

Numerics match torch's AdamW: bias-corrected first/second moments, decoupled
weight decay applied as ``p -= lr * wd * p`` before the Adam step.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Tuple[Any, Any]]
    """update(grads, state, params) -> (new_params, new_state)"""


def sgd(lr: float, momentum: float = 0.0) -> Optimizer:
    def init(params):
        if momentum == 0.0:
            return ()
        return jax.tree_util.tree_map(jnp.zeros_like, params)

    def update(grads, state, params):
        if momentum == 0.0:
            new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
            return new, state
        vel = jax.tree_util.tree_map(lambda v, g: momentum * v + g, state, grads)
        new = jax.tree_util.tree_map(lambda p, v: p - lr * v, params, vel)
        return new, vel

    return Optimizer(init, update)


class AdamWState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any


def adamw(lr: float, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
          weight_decay: float = 0.01) -> Optimizer:
    """AdamW with torch-default hyperparameters (``min_DDP.py:74`` passes
    only the learning rate, inheriting betas/eps/wd defaults).

    Moments are kept in float32 and the update computed in float32
    regardless of parameter dtype — for float32 params this is exactly
    torch's arithmetic; for bfloat16 params it is the standard
    mixed-precision recipe (bf16 moments destroy Adam's second-moment
    scale), with the delta cast back to the parameter dtype."""

    def init(params):
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
        return AdamWState(step=jnp.zeros((), jnp.int32), mu=zeros(), nu=zeros())

    def update(grads, state, params):
        step = state.step + 1
        t = step.astype(jnp.float32)
        c1 = 1.0 - b1 ** t
        c2 = 1.0 - b2 ** t

        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
            state.mu, grads)
        nu = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1 - b2) * jnp.square(
                g.astype(jnp.float32)),
            state.nu, grads)

        def step_fn(p, m, v):
            pf = p.astype(jnp.float32) * (1.0 - lr * weight_decay)
            mhat = m / c1
            vhat = v / c2
            return (pf - lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype)

        new_params = jax.tree_util.tree_map(step_fn, params, mu, nu)
        return new_params, AdamWState(step=step, mu=mu, nu=nu)

    return Optimizer(init, update)


# Schedules/transforms import Optimizer from this module, so they load
# after it is defined.
from . import schedules  # noqa: E402
from .schedules import (accumulate, clip_by_global_norm, constant,  # noqa: E402
                        cosine_decay, linear_warmup, warmup_cosine,
                        with_clipping, with_master_f32, with_schedule)
