"""Optimizers as pure pytree transforms.

The reference uses ``torch.optim.AdamW(params, 1e-4)`` (``min_DDP.py:74``).
Here an optimizer is an ``(init, update)`` pair over pytrees so the whole
update fuses into the compiled train step — the TPU-idiomatic shape, where
"optimizer.step()" is just more HLO after the gradient all-reduce.

Numerics match torch's AdamW: bias-corrected first/second moments, decoupled
weight decay applied as ``p -= lr * wd * p`` before the Adam step.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Tuple[Any, Any]]
    """update(grads, state, params) -> (new_params, new_state)"""


def sgd(lr: float, momentum: float = 0.0) -> Optimizer:
    def init(params):
        if momentum == 0.0:
            return ()
        return jax.tree_util.tree_map(jnp.zeros_like, params)

    def update(grads, state, params):
        if momentum == 0.0:
            new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
            return new, state
        vel = jax.tree_util.tree_map(lambda v, g: momentum * v + g, state, grads)
        new = jax.tree_util.tree_map(lambda p, v: p - lr * v, params, vel)
        return new, vel

    return Optimizer(init, update)


class AdamWState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any


def adamw(lr: float, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
          weight_decay: float = 0.01) -> Optimizer:
    """AdamW with torch-default hyperparameters (``min_DDP.py:74`` passes
    only the learning rate, inheriting betas/eps/wd defaults).

    Moments are kept in float32 and the update computed in float32
    regardless of parameter dtype — for float32 params this is exactly
    torch's arithmetic; for bfloat16 params it is the standard
    mixed-precision recipe (bf16 moments destroy Adam's second-moment
    scale), with the delta cast back to the parameter dtype."""

    def init(params):
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
        return AdamWState(step=jnp.zeros((), jnp.int32), mu=zeros(), nu=zeros())

    def update(grads, state, params):
        step = state.step + 1
        t = step.astype(jnp.float32)
        c1 = 1.0 - b1 ** t
        c2 = 1.0 - b2 ** t

        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
            state.mu, grads)
        nu = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1 - b2) * jnp.square(
                g.astype(jnp.float32)),
            state.nu, grads)

        def step_fn(p, m, v):
            pf = p.astype(jnp.float32) * (1.0 - lr * weight_decay)
            mhat = m / c1
            vhat = v / c2
            return (pf - lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype)

        new_params = jax.tree_util.tree_map(step_fn, params, mu, nu)
        return new_params, AdamWState(step=step, mu=mu, nu=nu)

    return Optimizer(init, update)


class AdafactorState(NamedTuple):
    step: jnp.ndarray
    vr: Any    # factored row second moments (matrices; () placeholder else)
    vc: Any    # factored col second moments
    v: Any     # full second moment (vectors/scalars; () placeholder else)


def adafactor(lr: float = None, *, decay_pow: float = 0.8,
              clip_threshold: float = 1.0, eps1: float = 1e-30,
              eps2: float = 1e-3, weight_decay: float = 0.0,
              scale_by_param: bool = None) -> Optimizer:
    """Adafactor (Shazeer & Stern): Adam-class adaptivity at O(rows+cols)
    optimizer memory — the TPU-classic choice for big embedding/vocab
    matrices, where Adam's two full f32 moments triple the parameter HBM.

    For ndim>=2 leaves the second moment is stored FACTORED (a row vector
    and a column vector over the trailing two axes; their outer product,
    normalized by the row mean, is the rank-1 maximum-likelihood fit to
    the full moment); smaller leaves keep a full moment. No first moment.
    beta2 follows the 1 - t^-decay_pow schedule, updates are RMS-clipped
    to ``clip_threshold``, and with ``lr=None`` the canonical relative
    step min(1e-2, 1/sqrt(t)) * max(eps2, RMS(param)) is used
    (``scale_by_param`` defaults to True exactly when lr is None).
    Decoupled weight decay as in :func:`adamw`. State is f32; under FSDP
    the factored vectors replicate (``parallel/fsdp.py:opt_state_specs``)
    — they are O(rows+cols), which is the whole point.
    """
    if scale_by_param is None:
        scale_by_param = lr is None

    def _flat(params):
        return jax.tree_util.tree_flatten(params)

    def init(params):
        leaves, _ = _flat(params)
        # placeholders must be DISTINCT arrays: donated train steps reject
        # the same buffer appearing twice in one argument list
        empty = lambda: jnp.zeros((0,), jnp.float32)
        vr, vc, v = [], [], []
        for p in leaves:
            if p.ndim >= 2:
                vr.append(jnp.zeros(p.shape[:-1], jnp.float32))
                vc.append(jnp.zeros(p.shape[:-2] + p.shape[-1:],
                                    jnp.float32))
                v.append(empty())
            else:
                vr.append(empty())
                vc.append(empty())
                v.append(jnp.zeros(jnp.shape(p), jnp.float32))
        return AdafactorState(step=jnp.zeros((), jnp.int32),
                              vr=tuple(vr), vc=tuple(vc), v=tuple(v))

    def _rms(x):
        return jnp.sqrt(jnp.mean(jnp.square(x)) + 1e-30)

    def update(grads, state, params):
        g_leaves, treedef = _flat(grads)
        p_leaves = treedef.flatten_up_to(params)
        step = state.step + 1
        t = step.astype(jnp.float32)
        beta2 = 1.0 - t ** (-decay_pow)
        base_step = lr if lr is not None else jnp.minimum(
            1e-2, 1.0 / jnp.sqrt(t))

        new_p, new_vr, new_vc, new_v = [], [], [], []
        for p, g, vr, vc, v in zip(p_leaves, g_leaves, state.vr, state.vc,
                                   state.v):
            gf = g.astype(jnp.float32)
            g2 = jnp.square(gf) + eps1
            if p.ndim >= 2:
                vr = beta2 * vr + (1 - beta2) * jnp.mean(g2, axis=-1)
                vc = beta2 * vc + (1 - beta2) * jnp.mean(g2, axis=-2)
                r = vr / jnp.mean(vr, axis=-1, keepdims=True)
                u = gf * jax.lax.rsqrt(r)[..., None] \
                    * jax.lax.rsqrt(vc)[..., None, :]
            else:
                v = beta2 * v + (1 - beta2) * g2
                u = gf * jax.lax.rsqrt(v)
            u = u / jnp.maximum(1.0, _rms(u) / clip_threshold)
            alpha = base_step * (jnp.maximum(eps2, _rms(p.astype(
                jnp.float32))) if scale_by_param else 1.0)
            pf = p.astype(jnp.float32) * (1.0 - alpha * weight_decay)
            new_p.append((pf - alpha * u).astype(p.dtype))
            new_vr.append(vr)
            new_vc.append(vc)
            new_v.append(v)

        return (jax.tree_util.tree_unflatten(treedef, new_p),
                AdafactorState(step=step, vr=tuple(new_vr),
                               vc=tuple(new_vc), v=tuple(new_v)))

    return Optimizer(init, update)


# Schedules/transforms (and the sharded-update subsystem) import
# Optimizer from this module, so they load after it is defined.
from . import schedules  # noqa: E402
from .schedules import (accumulate, clip_by_global_norm, constant,  # noqa: E402
                        cosine_decay, ema_params, linear_warmup,
                        warmup_cosine, with_clipping, with_ema,
                        with_master_f32, with_schedule)


class Q8Moment(NamedTuple):
    q: Any        # param-shaped int8 codes per leaf
    scale: Any    # per-block f32 scales, (ceil(size/block),) per leaf


class AdamW8bitState(NamedTuple):
    step: jnp.ndarray
    mu: Any       # Q8Moment tree
    nu: Any


_Q8_BLOCK = 256  # bitsandbytes-style blockwise scaling granularity


class _LeafOut(NamedTuple):
    p: Any
    m: Any
    v: Any


def _q8_quant(x, block=_Q8_BLOCK):
    """Blockwise symmetric int8 quantization of a f32 leaf (flattened
    view; per-block amax scales)."""
    shape = x.shape
    flat = x.ravel()
    pad = (-flat.shape[0]) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, block)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.round(blocks / scale[:, None]).astype(jnp.int8)
    return Q8Moment(q=q.ravel()[:x.size].reshape(shape), scale=scale)


def _q8_dequant(qm: Q8Moment, shape, block=_Q8_BLOCK):
    flat = qm.q.ravel().astype(jnp.float32)
    pad = (-flat.shape[0]) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    out = (flat.reshape(-1, block) * qm.scale[:, None]).ravel()
    n = 1
    for s in shape:
        n *= s
    return out[:n].reshape(shape)


class Q8LogMoment(NamedTuple):
    q: Any        # param-shaped int8 codes (affine, log domain)
    scale: Any    # per-block f32 code width
    mid: Any      # per-block f32 affine midpoint


_Q8_VFLOOR = 1e-12  # log-domain floor for the second moment


def _q8_quant_log(v, block=_Q8_BLOCK):
    """Blockwise AFFINE int8 quantization of a NON-NEGATIVE leaf in the
    log domain. Linear codes cannot hold the second moment: a block's
    small entries round to exactly zero and the Adam denominator
    sqrt(0)+eps explodes the step. In log space the code error is a
    RELATIVE error on v (and halves through the sqrt), with the floor
    pinned at _Q8_VFLOOR instead of zero."""
    shape = v.shape
    flat = jnp.log(v.ravel() + _Q8_VFLOOR)
    pad = (-flat.shape[0]) % block
    if pad:
        # edge padding: a 0.0 pad value (log v = 0 -> v = 1) would
        # contaminate the last block's lo/hi range and inflate its code
        # step for every REAL element in it
        flat = jnp.pad(flat, (0, pad), mode="edge")
    blocks = flat.reshape(-1, block)
    lo = jnp.min(blocks, axis=1)
    hi = jnp.max(blocks, axis=1)
    scale = jnp.where(hi > lo, (hi - lo) / 254.0, 1.0)
    mid = (hi + lo) / 2.0
    q = jnp.round((blocks - mid[:, None]) / scale[:, None]) \
        .astype(jnp.int8)
    return Q8LogMoment(q=q.ravel()[:v.size].reshape(shape),
                       scale=scale, mid=mid)


def _q8_dequant_log(qm: Q8LogMoment, shape, block=_Q8_BLOCK):
    flat = qm.q.ravel().astype(jnp.float32)
    pad = (-flat.shape[0]) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    y = flat.reshape(-1, block) * qm.scale[:, None] + qm.mid[:, None]
    out = jnp.exp(y).ravel()
    n = 1
    for s in shape:
        n *= s
    return (out[:n] - _Q8_VFLOOR).clip(min=0.0).reshape(shape)


def adamw_8bit(lr: float, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-8, weight_decay: float = 0.01) -> Optimizer:
    """AdamW whose moments are STORED as blockwise int8 (256-element
    blocks, one f32 scale each) — the bitsandbytes-style 8-bit optimizer.

    AdamW's state is 2x the params in f32; this stores it at ~1/4 the
    bytes (int8 codes + 1 scale per 256 elements), the memory rung
    BELOW ZeRO when the optimizer state itself is the constraint (or on
    top of it: `parallel.fsdp.opt_state_specs` shards the param-shaped
    int8 code tree like any moment). Each step dequantizes, applies the
    exact f32 AdamW arithmetic, and requantizes — the quantization error
    enters only through the stored moments (linear blockwise codes; the
    second moment additionally passes through sqrt, softening its
    effective error). Loss trajectories track f32 AdamW closely but not
    bit-exactly — use plain :func:`adamw` when exact torch parity
    matters (tests/test_optim_generate_prefetch.py pins the tracking
    tolerance).
    """

    def init(params):
        zm = lambda p: _q8_quant(jnp.zeros(jnp.shape(p), jnp.float32))
        zv = lambda p: _q8_quant_log(jnp.zeros(jnp.shape(p), jnp.float32))
        return AdamW8bitState(
            step=jnp.zeros((), jnp.int32),
            mu=jax.tree_util.tree_map(zm, params),
            nu=jax.tree_util.tree_map(zv, params))

    def update(grads, state, params):
        step = state.step + 1
        t = step.astype(jnp.float32)
        c1 = 1.0 - b1 ** t
        c2 = 1.0 - b2 ** t

        def leaf_update(p, g, qm, qv):
            gf = g.astype(jnp.float32)
            m = b1 * _q8_dequant(qm, p.shape) + (1 - b1) * gf
            v = (b2 * _q8_dequant_log(qv, p.shape)
                 + (1 - b2) * jnp.square(gf))
            pf = p.astype(jnp.float32) * (1.0 - lr * weight_decay)
            new_p = (pf - lr * (m / c1)
                     / (jnp.sqrt(v / c2) + eps)).astype(p.dtype)
            return _LeafOut(new_p, _q8_quant(m), _q8_quant_log(v))

        out = jax.tree_util.tree_map(leaf_update, params, grads,
                                     state.mu, state.nu)
        # tree_map over params drives the structure; unzip the _LeafOut
        # nodes field-wise (isinstance match, no positional fragility)
        is_out = lambda x: isinstance(x, _LeafOut)
        pick = lambda f: jax.tree_util.tree_map(
            lambda o: getattr(o, f), out, is_leaf=is_out)
        return pick("p"), AdamW8bitState(step=step, mu=pick("m"),
                                         nu=pick("v"))

    return Optimizer(init, update)


# Cross-replica sharded weight update (ZeRO-1) — loads last: it wraps
# Optimizer and builds on the quantized-ring comm layer.
from . import sharded  # noqa: E402
from .sharded import (FlatLayout, ShardedOptimizer,  # noqa: E402,F401
                      ShardedOptState, build_layout, shard_optimizer)
