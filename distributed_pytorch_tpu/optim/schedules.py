"""Learning-rate schedules and gradient transforms.

The reference fixes ``AdamW(params, 1e-4)`` with no schedule, no clipping,
no accumulation (``min_DDP.py:74``). Real training runs need all three;
they are provided as pure functions/transforms so they compile into the
same single XLA step program as the optimizer itself.

A schedule is ``f(step) -> lr`` on traced int steps (usable inside jit);
``with_schedule`` rebuilds any lr-taking optimizer factory into a
scheduled optimizer. ``clip_by_global_norm`` is a grad transform;
``accumulate`` wraps an optimizer so updates apply every k-th step with
averaged gradients — the standard big-batch recipe when the per-step
batch doesn't fit.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from . import Optimizer

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def constant(lr: float) -> Schedule:
    return lambda step: jnp.asarray(lr, jnp.float32)


def linear_warmup(base: Schedule, warmup_steps: int) -> Schedule:
    def f(step):
        s = jnp.asarray(step, jnp.float32)
        w = jnp.minimum(1.0, (s + 1.0) / float(max(warmup_steps, 1)))
        return base(step) * w
    return f


def cosine_decay(lr: float, decay_steps: int, alpha: float = 0.0) -> Schedule:
    """lr * (alpha + (1-alpha) * 0.5 * (1 + cos(pi * t)))  for t in [0,1]."""
    if decay_steps < 1:
        raise ValueError(f"decay_steps must be >= 1, got {decay_steps} "
                         "(0 would make the lr 0/0 = NaN)")
    def f(step):
        t = jnp.clip(jnp.asarray(step, jnp.float32) / float(decay_steps),
                     0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * t))
        return lr * (alpha + (1.0 - alpha) * cos)
    return f


def warmup_cosine(lr: float, warmup_steps: int, total_steps: int,
                  alpha: float = 0.0) -> Schedule:
    """The standard LM schedule: linear warmup into cosine decay."""
    decay = cosine_decay(lr, max(total_steps - warmup_steps, 1), alpha)
    def f(step):
        s = jnp.asarray(step, jnp.float32)
        warm = lr * (s + 1.0) / float(max(warmup_steps, 1))
        return jnp.where(s < warmup_steps,
                         warm, decay(s - warmup_steps))
    return f


class ScheduledState(NamedTuple):
    step: jnp.ndarray
    inner: Any


def with_schedule(opt_factory: Callable[[float], Optimizer],
                  schedule: Schedule) -> Optimizer:
    """Optimizer whose lr follows ``schedule``: ``opt_factory(lr)`` must
    build the underlying optimizer for a given lr in a way that uses lr
    only as a scalar multiplier (true of :func:`optim.sgd` /
    :func:`optim.adamw`) — the factory is traced once with lr=1 and the
    scheduled lr scales the parameter delta.

    Stateful-parameter wrappers break that assumption: a
    :func:`with_master_f32` INSIDE the factory would store the full lr=1
    update in its master copy, silently ignoring the schedule. That
    composition is rejected at init; wrap the other way around —
    ``with_master_f32(with_schedule(adamw, sched))``."""
    unit = opt_factory(1.0)

    def _has_master(state) -> bool:
        leaves = jax.tree_util.tree_leaves(
            state, is_leaf=lambda x: isinstance(x, MasterState))
        return any(isinstance(l, MasterState) for l in leaves)

    def init(params):
        inner = unit.init(params)
        if _has_master(inner):
            raise ValueError(
                "with_schedule(factory) cannot wrap with_master_f32: the "
                "master copy would absorb the unscaled lr=1 update and "
                "the schedule would be ignored. Compose as "
                "with_master_f32(with_schedule(adamw, schedule)) instead.")
        return ScheduledState(step=jnp.zeros((), jnp.int32), inner=inner)

    def update(grads, state, params):
        lr = schedule(state.step)
        new_params_unit, inner = unit.update(grads, state.inner, params)
        # delta computed at lr=1, scaled by the scheduled lr
        new_params = jax.tree_util.tree_map(
            lambda p, pu: p + lr * (pu - p), params, new_params_unit)
        return new_params, ScheduledState(step=state.step + 1, inner=inner)

    return Optimizer(init, update)


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


def clip_by_global_norm(grads, max_norm: float):
    """Scale ``grads`` so their global L2 norm is at most ``max_norm``."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


def with_clipping(opt: Optimizer, max_norm: float) -> Optimizer:
    """Clip gradients by global norm before the inner update."""
    def update(grads, state, params):
        return opt.update(clip_by_global_norm(grads, max_norm), state,
                          params)
    return Optimizer(opt.init, update)


class AccumState(NamedTuple):
    count: jnp.ndarray   # micro-steps since last apply
    acc: Any             # running gradient sum
    inner: Any


def accumulate(opt: Optimizer, every: int) -> Optimizer:
    """Apply the inner optimizer every ``every`` micro-steps with the
    mean of the accumulated gradients; in between, params pass through
    unchanged. Effective batch = every x per-step batch, numerics equal
    to one big batch (mean of means over equal micro-batches)."""
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")

    def init(params):
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return AccumState(count=jnp.zeros((), jnp.int32), acc=zeros,
                          inner=opt.init(params))

    def update(grads, state, params):
        acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), state.acc, grads)
        count = state.count + 1

        def apply(_):
            mean = jax.tree_util.tree_map(lambda a: a / every, acc)
            mean = jax.tree_util.tree_map(
                lambda m, g: m.astype(g.dtype), mean, grads)
            new_params, inner = opt.update(mean, state.inner, params)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return new_params, AccumState(jnp.zeros((), jnp.int32), zeros,
                                          inner)

        def skip(_):
            return params, AccumState(count, acc, state.inner)

        return jax.lax.cond(count >= every, apply, skip, None)

    return Optimizer(init, update)


class EmaState(NamedTuple):
    ema: Any             # exponential moving average of params
    inner: Any


def with_ema(opt: Optimizer, decay: float = 0.999) -> Optimizer:
    """Track an exponential moving average of the parameters.

    The averaged weights (Polyak averaging) evaluate better than the
    raw last iterate for most vision models and many LMs — a standard
    capability torch users get from ``swa_utils``/``AveragedModel``. As
    a pure optimizer wrapper the EMA tree lives in the optimizer state,
    so it checkpoints with it (utils/checkpoint.py), shards with it
    under FSDP (the param-shaped-subtree rule in
    ``parallel.fsdp.opt_state_specs``), and updates inside the one
    compiled train step — no host-side weight copies.

    The average initializes AT the initial params (a convex combination
    thereafter), so it is unbiased by construction and needs no
    Adam-style zero-init correction — ``ema_params(state, like=params)``
    extracts it as-is for evaluation. Caveat shared with torch's
    ``swa_utils``: for BatchNorm models, running statistics accumulated
    under the raw trajectory don't match the averaged weights
    (torch addresses this with ``update_bn``); expect the reported EMA
    accuracy to understate until stats are re-estimated.

    Wrap order with gradient accumulation: compose as
    ``accumulate(with_ema(opt), every=k)`` — accumulate then only calls
    this wrapper on real apply steps. The other order,
    ``with_ema(accumulate(opt))``, blends on every micro-step including
    the k-1 skip steps where params come back unchanged, which shrinks
    the effective averaging horizon by ~k and biases the average toward
    stale params.
    """
    if not 0.0 <= decay < 1.0:
        raise ValueError(f"decay must be in [0, 1), got {decay} "
                         "(1.0 would freeze the average at init forever)")

    def init(params):
        # jnp.array (copy semantics), NOT astype: astype of an
        # already-f32 leaf returns the same buffer, and a donating train
        # step would then donate params and state.ema as one buffer
        # ("donate the same buffer twice")
        ema = jax.tree_util.tree_map(
            lambda p: jnp.array(p, dtype=jnp.float32), params)
        return EmaState(ema=ema, inner=opt.init(params))

    def update(grads, state, params):
        new_params, inner = opt.update(grads, state.inner, params)
        ema = jax.tree_util.tree_map(
            lambda e, p: decay * e + (1.0 - decay) * p.astype(jnp.float32),
            state.ema, new_params)
        return new_params, EmaState(ema=ema, inner=inner)

    return Optimizer(init, update)


def ema_params(state, like=None):
    """The EMA weight tree from a ``with_ema`` state (searches nested
    wrapper states). ``like``: cast each leaf to the matching param's
    dtype (hand the result straight to ``model.apply``)."""
    found = _find_ema(state)
    if found is None:
        raise ValueError("no EmaState found in this optimizer state — "
                         "was the optimizer built with with_ema()?")
    ema = found.ema
    if like is not None:
        ema = jax.tree_util.tree_map(
            lambda e, p: e.astype(p.dtype), ema, like)
    return ema


def _find_ema(state):
    if isinstance(state, EmaState):
        return state
    if isinstance(state, tuple) and hasattr(state, "_fields"):
        for f in state._fields:
            found = _find_ema(getattr(state, f))
            if found is not None:
                return found
    return None


class MasterState(NamedTuple):
    master: Any          # float32 master copy of low-precision params
    inner: Any


def with_master_f32(opt: Optimizer) -> Optimizer:
    """Float32 master weights for low-precision training.

    bfloat16 parameters lose every update smaller than ~2^-8 of the
    weight's magnitude to rounding (8 mantissa bits), which stalls late
    training. The standard mixed-precision recipe keeps the authoritative
    copy in float32: the inner optimizer updates the MASTER, and the
    working (bf16) params handed back to the model are its cast. Leaves
    that are already float32 pass through untouched (no double storage).

    The working params keep their dtype, so the compiled train step's
    matmuls stay low-precision — only the update math changes.
    """
    def _to_master(p):
        # copy (jnp.array) even when already f32: an aliased leaf would
        # make a donating train step donate the same buffer twice on its
        # first call. The intended use is a bf16 model whose f32 leaves
        # are small (LayerNorm scales, biases), so the copy is cheap.
        return (p.astype(jnp.float32) if p.dtype == jnp.bfloat16
                else jnp.array(p))

    def init(params):
        master = jax.tree_util.tree_map(_to_master, params)
        return MasterState(master=master, inner=opt.init(master))

    def update(grads, state, params):
        grads32 = jax.tree_util.tree_map(
            lambda g, m: g.astype(m.dtype), grads, state.master)
        new_master, inner = opt.update(grads32, state.inner, state.master)
        new_params = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype), new_master, params)
        return new_params, MasterState(master=new_master, inner=inner)

    return Optimizer(init, update)
