"""optim/sharded — cross-replica sharded weight update (ZeRO-1) on the
quantized ring.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv 2004.13336) applied to this repo's two comm front
doors: the data-parallel update stops being ``allreduce(grads) ->
replicated optimizer step`` (every rank burning the same update FLOPs
and holding the full optimizer state) and becomes::

    reduce-scatter(grads)  ->  local step on the owned 1/world slice
                           ->  all-gather(updated params)

Same total wire bytes as the allreduce it replaces (the allreduce IS
those two legs), ~1/world the optimizer-state memory and update compute
per replica. On the host TCP ring both legs ride the PR 1 block-int8
wire (``dpx_reduce_scatter_q8`` / ``dpx_allgather_q8``, CRC32C-framed,
chunk-pipelined, deadline-guarded, error-feedback on both legs); under
the mesh they are ``psum_scatter`` / ``all_gather`` (optionally
quantized via the same block codec). See ``docs/optimizer_sharding.md``.

Public surface:

* :func:`build_layout` / :class:`FlatLayout` — the shared flat-bucket
  coordinate system (block-aligned, equal segments, ckpt-portable via
  ``pad_multiple``);
* :func:`shard_optimizer` / :class:`ShardedOptimizer` /
  :class:`ShardedOptState` — wrap any elementwise ``Optimizer``
  unchanged;
* :func:`make_sharded_train_step` — front-door dispatch; reached from
  ``parallel.make_train_step(..., weight_update="sharded")``.
"""

from __future__ import annotations

from typing import Callable, Optional

from .. import Optimizer
from .layout import FlatLayout, build_layout, lcm_pad_multiple
from .optimizer import ShardedOptimizer, ShardedOptState, shard_optimizer

__all__ = [
    "FlatLayout", "build_layout", "lcm_pad_multiple",
    "ShardedOptimizer", "ShardedOptState", "shard_optimizer",
    "make_sharded_train_step",
]


def make_sharded_train_step(loss_fn: Callable, optimizer: Optimizer,
                            donate: bool = True,
                            grad_reduce: str = "mean",
                            pad_multiple: Optional[int] = None
                            ) -> Callable:
    """The ``weight_update="sharded"`` engine behind
    :func:`...parallel.make_train_step`: dispatches to the host-ring
    engine when a native process group is live, else to the compiled
    SPMD engine (which also covers world == 1 with the same state
    structure). The returned step carries ``init_opt_state(params)``
    (build the sharded state) and, on the SPMD engine,
    ``state_specs(opt_state)`` (the checkpoint-facing PartitionSpecs).
    """
    from ...runtime import context

    if context.get_host_comm() is not None:
        from .host import make_host_sharded_train_step
        if pad_multiple is not None:
            raise ValueError(
                "pad_multiple applies to the SPMD/global-state engine; "
                "the host engine derives its layout from the live world")
        return make_host_sharded_train_step(loss_fn, optimizer,
                                            grad_reduce=grad_reduce)
    from .spmd import make_spmd_sharded_train_step
    return make_spmd_sharded_train_step(loss_fn, optimizer, donate=donate,
                                        grad_reduce=grad_reduce,
                                        pad_multiple=pad_multiple)
