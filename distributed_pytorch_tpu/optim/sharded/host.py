"""Host-front-door sharded train step: ZeRO-1 on the quantized TCP ring.

Replaces the per-rank-process DDP update's ``allreduce(grads) ->
replicated step`` with::

    reduce_scatter_q8(grad bucket)          # half the allreduce bytes
    local optimizer step on the owned 1/W   # 1/W update compute+memory
    allgather_q8(updated params)            # the other half

Total wire bytes per step equal one quantized allreduce (~4x under the
f32 ring), but the optimizer state shrinks to 1/world per rank and the
update FLOPs drop by the same factor — the arXiv 2004.13336 recipe on
the PR 1 wire format. Every collective below runs through
:class:`...runtime.native.HostComm`, so per-op deadlines, CRC32C
framing, typed :class:`...runtime.native.CommError` attribution, the
always-on schedule recorder and CommStats bytes/time all apply
unchanged — a rank that diverges mid-update is attributed by the
collective-schedule verifier like any other op.

Error feedback, both legs:

* **scatter leg** (gradients): an :class:`...ops.quant.ErrorFeedback`
  residual carries each step's bucket quantization error into the next
  step's bucket (the PR 1 mechanism, verbatim).
* **gather leg** (params): the rank's exact f32 ``master`` lives in the
  sharded state; working params are the int8-grid value every rank
  decoded (bit-identical across ranks by the byte-forwarding ring), and
  the master—working gap stays bounded by half a quantization step per
  block instead of compounding.

``grad_reduce="mean"`` keeps both legs exact: the grad bucket rides the
exact f32 ring and the updated slices ride the exact hub all-gather —
the resulting trajectory is BIT-IDENTICAL to the replicated host DDP
step (the ring allreduce *is* reduce-scatter + all-gather, and the
wrapped update is elementwise), which the acceptance test pins.
"""

from __future__ import annotations

from typing import Callable

from .. import Optimizer
from .layout import build_layout
from .optimizer import shard_optimizer


def make_host_sharded_train_step(loss_fn: Callable, optimizer: Optimizer,
                                 grad_reduce: str = "mean") -> Callable:
    """Per-rank-process sharded DP step. Same
    ``step(params, opt_state, batch) -> StepOutput`` signature as the
    replicated host step, but ``opt_state`` is this rank's
    :class:`.optimizer.ShardedOptState` — build it with the returned
    step's ``init_opt_state(params)``."""
    import jax
    import numpy as np

    from ...ops.quant import ErrorFeedback
    from ...runtime import context

    comm = context.get_host_comm()
    world = comm.world
    rank = comm.rank
    quant = grad_reduce in ("quant", "int8")
    ef = ErrorFeedback() if quant else None

    # dpxlint: disable=DPX006 grads-only jit; params re-read every step
    vg = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))
    holder = {}

    def _ensure(params):
        if "layout" not in holder:
            holder["layout"] = build_layout(params, world)
            holder["sharded"] = shard_optimizer(optimizer,
                                                holder["layout"])
            holder["upd"] = jax.jit(holder["sharded"].update_flat)
        return holder["layout"], holder["sharded"], holder["upd"]

    def init_opt_state(params):
        _, sharded, _ = _ensure(params)
        return sharded.init_slice(params, rank)

    def step(params, opt_state, batch):
        import jax.numpy as jnp

        from ...parallel.data_parallel import StepOutput

        layout, sharded, upd = _ensure(params)
        (loss, metrics), grads = vg(params, batch)
        flat = layout.flatten_np(grads)
        lo, hi = layout.span(layout.ring_segment(rank))
        if world > 1:
            if quant:
                flat = ef.compensate(flat)
                comm.reduce_scatter_q8(flat)
            else:
                # exact rung: the full ring allreduce IS reduce-scatter +
                # all-gather, so slicing the owned span afterwards gives
                # bit-identical reduced values at full-allreduce wire cost
                # — the exactness-over-bytes trade, documented
                comm.allreduce(flat)
        g_slice = jnp.asarray(flat[lo:hi] / world)
        new_master, new_state = upd(g_slice, opt_state)
        buf = flat  # reuse the bucket as the param gather buffer
        buf[lo:hi] = np.asarray(new_master)
        if world > 1:
            if quant:
                comm.allgather_q8(buf)
            else:
                stacked = comm.all_gather(buf[lo:hi])
                for r in range(world):
                    rlo, rhi = layout.span(layout.ring_segment(r))
                    buf[rlo:rhi] = stacked[r]
        new_params = layout.unflatten_jnp(jnp.asarray(buf))
        # dpxmon step hook (obs/metrics.py; one global read when off)
        from ...obs import metrics as _dpxmon
        _dpxmon.on_train_step("host_step_sharded")
        return StepOutput(new_params, new_state,
                          jnp.asarray(loss)[None], metrics)

    step.init_opt_state = init_opt_state
    step.holder = holder
    return step
