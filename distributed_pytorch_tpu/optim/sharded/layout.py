"""Flat cross-replica shard layout — the coordinate system of the
sharded weight update (arXiv 2004.13336).

The sharded optimizer does not shard per-leaf (that is the GSPMD/FSDP
road, :mod:`...parallel.fsdp`): it flattens the whole param/grad tree
into ONE f32 bucket and shards the bucket's index space evenly across
the ``world`` replicas — the same single-bucket shape the quantized
ring collectives already move (``parallel/data_parallel._reduce_grads``
buckets exactly like this). The layout is the contract both front doors
share:

* every leaf is zero-padded to a :data:`~...comm.wire.QUANT_BLOCK`
  multiple, so no quantization-scale block ever spans two leaves (a
  tiny layernorm grad must never share a scale with an embedding
  grad's tail);
* the bucket tail is zero-padded to a multiple of ``pad_multiple``
  (default ``world * block``), which makes every replica's segment the
  same length AND block-aligned — so the equal-segment grid the SPMD
  ``psum_scatter`` needs and the block grid the native ring
  (``comm/wire.py:segment_blocks``) computes are the SAME grid;
* padding is zeros and stays zeros: gradients of padding are zero, and
  every supported (elementwise) optimizer maps zero-grad/zero-param to
  zero-param, so the pad region never contaminates real elements.

``pad_multiple`` is the cross-topology knob: a layout built with
``pad_multiple = lcm(world_a, world_b) * block`` produces the same
global flat length at both worlds, so a sharded-optimizer checkpoint
written at dp=world_a restores onto dp=world_b through the ordinary
resharding restore (:mod:`...ckpt`) with no conversion step — the flat
state leaves are 1-D arrays sharded ``P(axis)`` and the reader just
re-slices them.
"""

from __future__ import annotations

import math
from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np

from ...comm import wire as _wire


class FlatLayout(NamedTuple):
    """Frozen description of how a pytree maps onto the flat bucket."""

    treedef: Any                 # jax treedef of the source pytree
    shapes: Tuple[Tuple[int, ...], ...]   # per-leaf shapes
    dtypes: Tuple[Any, ...]      # per-leaf dtypes (restored on unflatten)
    offsets: Tuple[int, ...]     # per-leaf start offset in the bucket
    sizes: Tuple[int, ...]       # per-leaf true element counts
    n_padded: int                # total bucket length (all padding in)
    world: int
    block: int

    @property
    def seg(self) -> int:
        """Elements per replica segment (equal by construction)."""
        return self.n_padded // self.world

    def span(self, seg_index: int) -> Tuple[int, int]:
        """(lo, hi) element range of segment ``seg_index``."""
        lo = seg_index * self.seg
        return lo, lo + self.seg

    def ring_segment(self, rank: int) -> int:
        """The segment ``rank`` OWNS under the native ring's schedule
        (segment ``(rank+1) % world`` — ``dpx_reduce_scatter_q8``'s
        ownership convention, which the equal grid makes identical to
        ``comm/wire.py:ring_owned_span``)."""
        return (rank + 1) % self.world

    # -- flatten / unflatten -----------------------------------------------

    def flatten_np(self, tree) -> np.ndarray:
        """Tree -> flat f32 numpy bucket (host front door)."""
        leaves = self.treedef.flatten_up_to(tree)
        out = np.zeros(self.n_padded, np.float32)
        for leaf, off, size in zip(leaves, self.offsets, self.sizes):
            out[off:off + size] = np.asarray(
                leaf, dtype=np.float32).ravel()
        return out

    def flatten_jnp(self, tree):
        """Tree -> flat f32 jnp bucket (traceable; SPMD front door)."""
        import jax.numpy as jnp
        leaves = self.treedef.flatten_up_to(tree)
        parts = []
        cursor = 0
        for leaf, off, size in zip(leaves, self.offsets, self.sizes):
            if off > cursor:  # inter-leaf pad
                parts.append(jnp.zeros(off - cursor, jnp.float32))
            parts.append(jnp.ravel(leaf).astype(jnp.float32))
            cursor = off + size
        if cursor < self.n_padded:
            parts.append(jnp.zeros(self.n_padded - cursor, jnp.float32))
        return jnp.concatenate(parts)

    def unflatten_jnp(self, flat):
        """Flat bucket -> tree (leaf dtypes restored)."""
        import jax
        leaves = []
        for shape, dtype, off, size in zip(self.shapes, self.dtypes,
                                           self.offsets, self.sizes):
            leaves.append(flat[off:off + size].reshape(shape)
                          .astype(dtype))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    # -- sharding specs -----------------------------------------------------

    def state_specs(self, state, axis: str = "dp"):
        """PartitionSpec tree for a flat-bucket optimizer state: 1-D
        leaves whose length divides evenly across ``world`` (the flat
        moments, masters, int8 code vectors, per-block scale vectors)
        shard along ``axis``; everything else (step counters)
        replicates. This is the ``opt_specs`` the sharded checkpoint
        writer (:class:`...ckpt.CheckpointManager`) consumes — the
        resharding restore then absorbs the sharded moments for free."""
        import jax
        from jax.sharding import PartitionSpec as P

        def pick(x):
            shape = tuple(getattr(x, "shape", ()) or ())
            if (len(shape) == 1 and shape[0] > 0
                    and shape[0] % self.world == 0):
                return P(axis)
            return P()

        return jax.tree_util.tree_map(pick, state)


def build_layout(params, world: int, *, block: int = _wire.QUANT_BLOCK,
                 pad_multiple: Optional[int] = None) -> FlatLayout:
    """Build the :class:`FlatLayout` of ``params`` for ``world``
    replicas. ``pad_multiple`` (elements) overrides the default
    ``world * block`` tail padding — pass ``lcm(worlds) * block`` when a
    checkpoint must restore across topology changes (see module doc)."""
    import jax
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if pad_multiple is None:
        pad_multiple = world * block
    if pad_multiple % (world * block):
        raise ValueError(
            f"pad_multiple ({pad_multiple}) must be a multiple of "
            f"world*block ({world * block}) so segments stay equal and "
            f"block-aligned")
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        raise ValueError("cannot build a shard layout for an empty tree")
    shapes, dtypes, offsets, sizes = [], [], [], []
    off = 0
    for leaf in leaves:
        shape = tuple(np.shape(leaf))
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        shapes.append(shape)
        # bare Python scalar leaves have no .dtype; np.asarray only for
        # those (device arrays must not take a host round-trip here),
        # canonicalized so a Python float restores as f32 under jax's
        # default x64-disabled config instead of warning every step
        dtypes.append(leaf.dtype if hasattr(leaf, "dtype")
                      else jax.dtypes.canonicalize_dtype(
                          np.asarray(leaf).dtype))
        offsets.append(off)
        sizes.append(size)
        off += size + ((-size) % block)   # per-leaf pad to a block edge
    n_padded = off + ((-off) % pad_multiple)
    return FlatLayout(treedef=treedef, shapes=tuple(shapes),
                      dtypes=tuple(dtypes), offsets=tuple(offsets),
                      sizes=tuple(sizes), n_padded=n_padded,
                      world=world, block=block)


def lcm_pad_multiple(worlds: List[int],
                     block: int = _wire.QUANT_BLOCK) -> int:
    """The ``pad_multiple`` under which every world in ``worlds`` builds
    the same global flat length (checkpoint-portable layouts)."""
    return math.lcm(*[int(w) for w in worlds]) * block
