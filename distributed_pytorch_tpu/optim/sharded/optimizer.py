"""``shard_optimizer`` — wrap any elementwise :class:`...optim.Optimizer`
so its state and update live on 1/world of the flat param bucket.

The wrapper changes NOTHING about the optimizer's arithmetic: the inner
``(init, update)`` pair runs verbatim on a flat f32 vector instead of
the param tree. Because every supported optimizer is **elementwise**
(each element's update depends only on that element's grad, param and
moments — ``sgd``, ``adamw``, ``adamw_8bit``; NOT ``adafactor``, whose
factored moments couple rows/columns, and NOT global-norm clipping
wrappers), updating a slice of the bucket is bit-identical to updating
the whole bucket and slicing — which is what the numerical-equivalence
acceptance test pins.

State shape (:class:`ShardedOptState`):

* ``inner`` — the wrapped optimizer's state over the flat bucket (or a
  slice of it): param-shaped moments become 1-D f32 vectors, step
  counters stay scalars.
* ``master`` — the exact f32 value of the owned params. This is the
  error-feedback residual of the quantized all-gather leg in disguise:
  the replicated working params hold the int8-grid value every rank
  decoded, the master keeps the exact value, and the next step updates
  the master — so the one-quantization-step gap between them
  (``|master - working| <= scale/2`` per block) never compounds across
  steps, exactly like the PR 1 grad-ring residual.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

from .. import Optimizer
from . import layout as _layout


class ShardedOptState(NamedTuple):
    inner: Any      # wrapped optimizer's state over the flat bucket
    master: Any     # exact f32 owned params (flat)


def _reject_non_elementwise(inner_state) -> None:
    """Turn the detectable non-elementwise case into a typed error
    instead of silent numerical corruption: adafactor's factored
    moments couple rows/columns, so a flat-slice update computes
    DIFFERENT (wrong) statistics while appearing to train. Detected by
    its state type at init. (Global-norm clipping wrappers are equally
    unsupported — the norm is a cross-shard reduction — but they reuse
    the inner state type and cannot be detected structurally; that
    restriction stays documentation, docs/optimizer_sharding.md.)"""
    import jax

    from .. import AdafactorState
    is_af = lambda x: isinstance(x, AdafactorState)
    if any(is_af(n) for n in
           jax.tree_util.tree_leaves(inner_state, is_leaf=is_af)):
        raise TypeError(
            "shard_optimizer requires an ELEMENTWISE optimizer "
            "(sgd/adamw/adamw_8bit): adafactor's factored second "
            "moments couple rows and columns and cannot be updated on "
            "a flat 1/world slice — keep weight_update='replicated', "
            "or use parallel.make_zero1_train_step, whose per-leaf "
            "specs keep the factored vectors intact")


class ShardedOptimizer(NamedTuple):
    """The sharded face of an :class:`...optim.Optimizer`: same
    ``(init, update)`` contract, but over flat f32 slices. Engines
    (:mod:`.host`, :mod:`.spmd`) move the bytes; this only does math."""

    inner: Optimizer
    layout: _layout.FlatLayout

    def init_flat(self, flat_params) -> ShardedOptState:
        """State over a flat f32 vector — the FULL bucket for the
        single-controller/SPMD global state (leaves then shard along the
        mesh axis via :meth:`FlatLayout.state_specs`), or one rank's
        slice for the host front door."""
        inner_state = self.inner.init(flat_params)
        _reject_non_elementwise(inner_state)
        return ShardedOptState(inner=inner_state, master=flat_params)

    def init_global(self, params) -> ShardedOptState:
        """State over the whole flat bucket of ``params``."""
        import jax.numpy as jnp
        flat = jnp.asarray(self.layout.flatten_np(params))
        return self.init_flat(flat)

    def init_slice(self, params, rank: int) -> ShardedOptState:
        """State over the segment ``rank`` owns on the native ring."""
        import jax.numpy as jnp
        flat = self.layout.flatten_np(params)
        lo, hi = self.layout.span(self.layout.ring_segment(rank))
        return self.init_flat(jnp.asarray(flat[lo:hi]))

    def update_flat(self, g_flat, state: ShardedOptState
                    ) -> Tuple[Any, ShardedOptState]:
        """One optimizer step on a flat slice: ``g_flat`` is the MEAN
        gradient of the owned elements; returns ``(new_master,
        new_state)``. Pure and traceable — engines jit it."""
        new_master, new_inner = self.inner.update(
            g_flat, state.inner, state.master)
        return new_master, ShardedOptState(inner=new_inner,
                                           master=new_master)

    def state_specs(self, state: ShardedOptState, axis: str = "dp"):
        """PartitionSpec tree of a global flat state (ckpt-facing)."""
        return self.layout.state_specs(state, axis=axis)


def shard_optimizer(opt: Optimizer,
                    layout: _layout.FlatLayout) -> ShardedOptimizer:
    """Wrap ``opt`` (an elementwise ``Optimizer`` NamedTuple, unchanged)
    for the cross-replica sharded weight update over ``layout``."""
    if not isinstance(opt, Optimizer):
        raise TypeError(
            f"shard_optimizer wraps an optim.Optimizer NamedTuple, got "
            f"{type(opt).__name__}")
    return ShardedOptimizer(inner=opt, layout=layout)
